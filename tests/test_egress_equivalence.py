"""Scalar vs columnar EGRESS equivalence (ISSUE 13).

The egress columnarization moved outbound work to wave granularity:
one coalescer flush hands its whole wave of folded bundles to ONE
``Authenticator.sign_wire_wave`` pass (payload bodies encode once per
distinct object through the shared-prefix ``FrameEncodeMemo``, MACs
batch over the PR-7 precomputed key schedules), single-receiver sends
ride the same signer, and the protocol plane's pending coin-share
issues pool in the CryptoHub's coin column — one native
multi-exponentiation dispatch per staged pool per wave instead of one
``issue_shares_batch`` per node per drain.  That reshapes WHEN frames
encode, sign, and coin shares issue — but it must never reshape WHAT
crosses the wire or what the roster commits.
``Config.egress_columnar=False`` keeps the per-post scalar egress
path as a live comparison arm; these tests run the same seeded
schedule under both arms and require byte-identical committed ledgers
AND byte-identical wire-frame streams (under deterministically pinned
entropy/time) on the channel transport, byte-identical signer output
and committed batches on real gRPC, that the deterministic sign/coin
counters actually DROP, that the PR-4 semantic coalitions still lie
per-receiver through the columnar egress arm, and that the whole
egress path is PYTHONHASHSEED-independent.
"""

from __future__ import annotations

import hashlib
import os
import pathlib
import subprocess
import sys
import threading

import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from cleisthenes_tpu.config import Config  # noqa: E402
from cleisthenes_tpu.core.ledger import encode_batch_body  # noqa: E402
from cleisthenes_tpu.protocol.cluster import SimulatedCluster  # noqa: E402


def _channel_run(egress: bool) -> tuple:
    """(ledger digest, depth, delivery counters, hub counters) for one
    seeded 4-node channel-transport run under the given egress arm."""
    cluster = SimulatedCluster(
        config=Config(
            n=4, batch_size=8, seed=3031, egress_columnar=egress
        ),
        seed=3031,
        key_seed=17,
    )
    for i in range(24):
        cluster.submit(b"egr-tx-%04d" % i)
    cluster.run_epochs()
    depth = cluster.assert_agreement()
    h = hashlib.sha256()
    for nid in cluster.ids:
        for epoch, batch in enumerate(
            cluster.nodes[nid].committed_batches
        ):
            h.update(encode_batch_body(epoch, batch))
    hub = cluster.nodes[cluster.ids[0]].hub.stats()
    return h.hexdigest(), depth, cluster.net.delivery_stats(), hub


def test_scalar_vs_columnar_identical_ledgers_channel():
    col = _channel_run(egress=True)
    sca = _channel_run(egress=False)
    assert col[1] >= 2 and sca[1] >= 2  # both actually committed
    assert col[0] == sca[0], (
        "columnar egress committed different ledger bytes than the "
        f"scalar arm:\n  columnar: {col}\n  scalar:   {sca}"
    )
    # the refactor's entire point: the columnar arm makes FEWER
    # Authenticator sign passes (one wave call per flush instead of
    # one per post) and FEWER native coin-issue dispatches (one
    # pooled share_batch per wave instead of one per node per drain)
    # for the identical schedule — never more
    assert col[2]["mac_signs"] < sca[2]["mac_signs"], (col[2], sca[2])
    assert 2 * col[3]["coin_issue_batches"] <= sca[3]["coin_issue_batches"], (
        col[3], sca[3],
    )
    # both arms issue the identical shares through the same unit
    assert col[3]["coin_issue_items"] == sca[3]["coin_issue_items"]
    # payload bodies actually encoded never increase (the memo only
    # ever dedups; with no cross-receiver sharing the arms tie)
    assert col[2]["frames_encoded"] <= sca[2]["frames_encoded"]
    # scalar arm reports zeroed memo keys (schema stability)
    assert sca[2]["encode_memo_hits"] == 0
    assert sca[2]["encode_memo_misses"] == 0


def test_transport_metrics_surface_egress_counters():
    """Metrics.snapshot() carries the egress-plane counters on the
    channel transport (endpoint_stats provider) and the coin-issue
    tallies in the hub block."""
    cluster = SimulatedCluster(
        config=Config(n=4, batch_size=8, seed=6, egress_columnar=True),
        seed=6,
        key_seed=3,
    )
    for i in range(8):
        cluster.submit(b"megr-%04d" % i)
    cluster.run_epochs()
    snap = cluster.nodes[cluster.ids[0]].metrics.snapshot()
    transport = snap["transport"]
    for key in (
        "frames_encoded",
        "encode_memo_hits",
        "encode_memo_misses",
        "mac_sign_batches",
    ):
        assert key in transport, transport
    assert transport["mac_sign_batches"] > 0
    assert transport["frames_encoded"] > 0
    assert snap["hub"]["coin_share_batches"] > 0
    assert snap["hub"]["coin_share_items"] > 0


# ---------------------------------------------------------------------------
# codec/signer-level parity: sign_wire_wave vs sign_wire_many
# ---------------------------------------------------------------------------


def test_sign_wire_wave_parity_and_memo_sharing():
    """The wave signer must produce byte-identical frames to looping
    sign_wire_many (the gRPC egress path's signer — this IS the
    wire-frame equivalence proof at the seam real sockets use), share
    payload-body encodes across a wave's bundles via the memo, and
    evict FIFO."""
    from cleisthenes_tpu.transport.base import (
        HmacAuthenticator,
        NullAuthenticator,
    )
    from cleisthenes_tpu.transport.message import (
        BbaPayload,
        BbaType,
        BundlePayload,
        FrameEncodeMemo,
        Message,
        RbcPayload,
        RbcType,
    )

    roster = ["node0", "node1", "node2", "node3"]
    auth = HmacAuthenticator.derive(b"egress-master", "node0", roster)
    shared = BbaPayload(BbaType.BVAL, "node0", 3, 1, True)
    vals = [
        RbcPayload(
            RbcType.VAL, "node0", 3, b"r" * 32, (b"b" * 32,),
            shard_index=i, shard=b"s%d" % i,
        )
        for i in range(3)
    ]
    # a mixed egress wave: per-receiver bundles sharing one broadcast
    # run object (`shared`) plus a distinct VAL each — the coalescer's
    # exact output shape
    msgs = [
        Message(
            sender_id="node0",
            timestamp=99.25,
            payload=BundlePayload((shared, vals[i])),
        )
        for i in range(3)
    ]
    items = [(m, [f"node{i + 1}"]) for i, m in enumerate(msgs)]
    memo = FrameEncodeMemo()
    waved = auth.sign_wire_wave(items, memo)
    for (m, rids), frames in zip(items, waved):
        want = auth.sign_wire_many(m, rids)
        assert frames == want, "wave signer drifted from scalar signer"
    # `shared` encoded once, hit twice; each VAL encoded once
    assert memo.hits == 2 and memo.misses == 4, (memo.hits, memo.misses)
    # Null backend parity (benchmarks isolating crypto cost)
    null = NullAuthenticator()
    nw = null.sign_wire_wave(items, FrameEncodeMemo())
    for (m, rids), frames in zip(items, nw):
        assert frames == null.sign_wire_many(m, rids)
    # FIFO eviction: at cap the OLDEST entry goes, never the table
    small = FrameEncodeMemo(cap=2)
    from cleisthenes_tpu.transport.message import encode_payload_shared

    for p in (shared, vals[0], vals[1]):
        encode_payload_shared(p, small)
    assert len(small.map) == 2
    encode_payload_shared(vals[1], small)  # newest still resident
    assert small.hits == 1


# ---------------------------------------------------------------------------
# ops-level parity: the wave-batched coin kernels vs their scalar maps
# ---------------------------------------------------------------------------


def test_coin_share_batch_matches_scalar_kernels():
    """`CommonCoin.share_batch` / `verify_shares_batch` are the
    coin-only batch entry points for callers without a hub (lockstep
    executor, tests) — the batch results must match mapping the
    scalar `share` / `verify_shares` kernels item for item, and a
    tampered share must fail exactly where the scalar check fails."""
    from cleisthenes_tpu.ops import tpke
    from cleisthenes_tpu.ops.coin import CommonCoin

    pub, secrets = tpke.deal(4, 2, seed=23)
    coin = CommonCoin(pub)
    coin_ids = [b"egr-coin-%d" % r for r in range(3)]
    sec = secrets[1]
    batch = coin.share_batch(sec, coin_ids)
    assert len(batch) == 3
    per_coin = []
    for cid, sh in zip(coin_ids, batch):
        # a batch-issued share verifies under the scalar verifier...
        assert coin.verify_shares(cid, [sh]) == [True]
        # ...and combines to the same deterministic VUF value as a
        # quorum of scalar-issued shares
        others = [coin.share(secrets[0], cid), coin.share(secrets[2], cid)]
        assert coin.toss(cid, others) == coin.toss(cid, [sh, others[0]])
        per_coin.append((cid, [sh] + others))
    # batched verify across every coin == mapping verify_shares
    verdicts = coin.verify_shares_batch(per_coin)
    assert verdicts == [
        coin.verify_shares(cid, shs) for cid, shs in per_coin
    ]
    assert all(all(v) for v in verdicts)
    # a forged share fails in the batch exactly like in the scalar map
    from cleisthenes_tpu.ops.tpke import DhShare

    good = per_coin[1][1][0]
    forged = DhShare(good.index, good.d + 1, good.e, good.z)
    tampered = [
        (per_coin[0][0], per_coin[0][1]),
        (per_coin[1][0], [forged] + per_coin[1][1][1:]),
    ]
    got = coin.verify_shares_batch(tampered)
    assert got[0] == [True, True, True]
    assert got[1][0] is False and got[1][1:] == [True, True]
    assert coin.share_batch(sec, []) == []
    assert coin.verify_shares_batch([]) == []


# ---------------------------------------------------------------------------
# wire-frame byte equivalence across arms (channel transport)
# ---------------------------------------------------------------------------

# Runs BOTH egress arms inside one subprocess with entropy and wall
# clock pinned (constant CP-nonce bytes keep every Chaum-Pedersen
# proof valid while making it batch-position-independent; a fixed
# time.time pins the envelope timestamp field), captures every frame
# at enqueue time via ChannelNetwork.frame_tap, and requires the two
# frame STREAMS — sender, receiver, and wire bytes, in order — to be
# byte-identical.  Prints one digest line carrying the deterministic
# egress counters; two PYTHONHASHSEED values must produce identical
# lines (hash-order iteration in the wave-signer / coin-pool path
# would show up as different counters, frame order, or ledger bytes).
_EGRESS_DRIVER = r"""
import hashlib
import secrets
import time

secrets.token_bytes = lambda n: b"\x07" * n  # constant CP nonces
time.time = lambda: 1_700_000_000.0  # pinned envelope timestamps

from cleisthenes_tpu.config import Config
from cleisthenes_tpu.core.ledger import encode_batch_body
from cleisthenes_tpu.protocol.cluster import SimulatedCluster


def run(egress):
    cluster = SimulatedCluster(
        config=Config(
            n=4, batch_size=8, seed=4042, egress_columnar=egress
        ),
        seed=4042,
        key_seed=19,
    )
    frames = []
    cluster.net.frame_tap = lambda s, r, w: frames.append((s, r, w))
    for i in range(24):
        cluster.submit(b"egr-hs-%04d" % i)
    cluster.run_epochs()
    depth = cluster.assert_agreement()
    assert depth >= 2, f"want >=2 committed epochs, got {depth}"
    h = hashlib.sha256()
    for nid in cluster.ids:
        for epoch, batch in enumerate(
            cluster.nodes[nid].committed_batches
        ):
            h.update(encode_batch_body(epoch, batch))
    return frames, h.hexdigest(), cluster.net.delivery_stats(), (
        cluster.nodes[cluster.ids[0]].hub.stats()
    )


col_frames, col_digest, col_d, col_hub = run(True)
sca_frames, sca_digest, sca_d, sca_hub = run(False)
assert col_digest == sca_digest, "ledger bytes diverged across arms"
assert len(col_frames) == len(sca_frames), (
    len(col_frames), len(sca_frames),
)
for i, (a, b) in enumerate(zip(col_frames, sca_frames)):
    assert a == b, (
        f"frame {i} diverged across egress arms: "
        f"{a[0]}->{a[1]} vs {b[0]}->{b[1]}"
    )
fh = hashlib.sha256()
for s, r, w in col_frames:
    fh.update(s.encode() + b"|" + r.encode() + b"|" + w)
print(
    "EGRESS_DIGEST=%s frames=%d stream=%s signs=%d encoded=%d "
    "coin_batches=%d coin_items=%d"
    % (
        col_digest,
        len(col_frames),
        fh.hexdigest(),
        col_d["mac_signs"],
        col_d["frames_encoded"],
        col_hub["coin_issue_batches"],
        col_hub["coin_issue_items"],
    )
)
"""


def _run_egress_driver(hashseed: str) -> str:
    env = dict(os.environ)
    env["PYTHONHASHSEED"] = hashseed
    env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.run(
        [sys.executable, "-c", _EGRESS_DRIVER],
        cwd=REPO,
        env=env,
        capture_output=True,
        text=True,
        timeout=300,
    )
    assert proc.returncode == 0, (
        f"PYTHONHASHSEED={hashseed} egress run failed:\n"
        f"{proc.stdout}\n{proc.stderr}"
    )
    for line in proc.stdout.splitlines():
        if line.startswith("EGRESS_DIGEST="):
            return line
    raise AssertionError(f"no egress digest line:\n{proc.stdout}")


def test_wire_frames_identical_across_arms_and_hash_seeds():
    a = _run_egress_driver("1")
    b = _run_egress_driver("2")
    assert a == b, (
        "columnar egress diverged across PYTHONHASHSEED values:\n"
        f"  {a}\n  {b}\n-> hash-order iteration is leaking into the "
        "wave-signer / coin-pool path (see staticcheck DET002)"
    )


# ---------------------------------------------------------------------------
# real gRPC: columnar vs scalar egress over sockets
# ---------------------------------------------------------------------------


def _grpc_epoch0_bodies(egress: bool) -> tuple:
    """(per-node epoch-0 bodies, one host's metrics snapshot) from a
    4-node run over real localhost gRPC under the given egress arm."""
    from cleisthenes_tpu.protocol.honeybadger import setup_keys
    from cleisthenes_tpu.transport.host import ValidatorHost

    n = 4
    cfg = Config(n=n, batch_size=8, seed=81, egress_columnar=egress)
    ids = [f"node{i}" for i in range(n)]
    keys = setup_keys(cfg, ids, seed=58)
    hosts = {i: ValidatorHost(cfg, i, ids, keys[i]) for i in ids}
    try:
        addrs = {i: h.listen() for i, h in hosts.items()}
        threads = [
            threading.Thread(target=h.connect, args=(addrs,))
            for h in hosts.values()
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=15)
        for i in range(8):
            hosts[ids[i % n]].submit(b"grpc-egr-%02d" % i)
        for h in hosts.values():
            h.propose()
        first = {i: h.wait_commit(timeout=60) for i, h in hosts.items()}
        assert {e for e, _ in first.values()} == {0}
        snap = hosts[ids[0]].node.metrics.snapshot()
        return [encode_batch_body(0, b) for _, b in first.values()], snap
    finally:
        for h in hosts.values():
            h.stop()


def test_scalar_vs_columnar_identical_ledgers_grpc():
    """Same roster, same submissions, real sockets: the columnar and
    scalar egress arms must commit byte-identical epoch-0 batches,
    and the columnar arm's wave signer must actually engage (sign
    batches > 0; frame-level byte equality for this path is proven at
    the signer seam by test_sign_wire_wave_parity_and_memo_sharing,
    since thread timing makes whole-run frame streams incomparable
    over real sockets)."""
    col, col_snap = _grpc_epoch0_bodies(egress=True)
    sca, _sca_snap = _grpc_epoch0_bodies(egress=False)
    # within-run agreement is byte-exact on both arms...
    assert all(b == col[0] for b in col)
    assert all(b == sca[0] for b in sca)
    # ...and across the egress-arm boundary too
    assert col[0] == sca[0], (
        "columnar vs scalar gRPC runs committed different epoch-0 bytes"
    )
    transport = col_snap["transport"]
    assert transport["mac_sign_batches"] > 0
    assert transport["frames_encoded"] > 0
    assert col_snap["hub"]["coin_share_batches"] > 0


# ---------------------------------------------------------------------------
# PR-4 semantic coalitions against the columnar egress arm
# ---------------------------------------------------------------------------


def _drive_coalition(behaviors: dict, n: int, seed: int):
    """Run a Byzantine coalition on the columnar egress arm; returns
    (agreed honest depth, the network) — assert_agreement = identical
    ledger prefixes."""
    bad = sorted(behaviors)
    cluster = SimulatedCluster(
        n=n,
        config=Config(n=n, batch_size=8, egress_columnar=True),
        seed=seed,
        key_seed=27,
        behaviors=behaviors,
    )
    honest = [i for i in cluster.ids if i not in bad]
    for i in range(12):
        cluster.submit(b"tx-%04d" % i, node_id=honest[i % len(honest)])
    cluster.run_until_drained(max_rounds=30, skip=bad)
    depth = cluster.assert_agreement(skip=bad)
    for nid in honest:
        for batch in cluster.nodes[nid].committed_batches:
            for tx in batch.tx_list():
                assert tx.startswith(b"tx-"), tx
    return depth, cluster.net


@pytest.mark.faults
def test_equivocator_coalition_columnar_egress():
    """An Equivocator's per-receiver lies enter BETWEEN the protocol
    plane and the coalescer, so the columnar flush must sign each
    receiver's distinct bundle separately (per-receiver signable)
    while the honest run's shared bodies still fold through the
    memo — conflating the two would either leak one receiver's lie to
    another or fail the MACs wholesale."""
    from cleisthenes_tpu.protocol.byzantine import make_behavior

    assert Config().egress_columnar is True  # the arm under test
    behaviors = {"node003": make_behavior("equivocator", seed=51)}
    depth, net = _drive_coalition(behaviors, n=4, seed=37)
    assert depth >= 1
    assert behaviors["node003"].rewrites > 0, "adversary never lied"
    # the liar's per-receiver fan-out makes mixed egress waves whose
    # unrewritten payload objects are shared across receivers — the
    # encode memo must actually dedup them
    stats = net.delivery_stats()
    assert stats["encode_memo_hits"] > 0, stats


@pytest.mark.faults
def test_selective_mute_coalition_columnar_egress():
    """SelectiveMute silences chosen links: the muted receivers'
    entries simply vanish from the egress wave, and the remaining
    per-receiver frames must still sign and deliver (honest quorums
    reach agreement without the starved links)."""
    from cleisthenes_tpu.protocol.byzantine import make_behavior

    behaviors = {"node003": make_behavior("selective_mute", seed=52)}
    depth, _net = _drive_coalition(behaviors, n=4, seed=41)
    assert depth >= 1
