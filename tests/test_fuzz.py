"""Schedule-fuzzer self-tests (tools/fuzz.py).

The fuzzer is only trustworthy if (a) sampled schedules are a pure
function of the seed, (b) the invariant checker actually fires, and
(c) shrinking converges to a MINIMAL failing schedule whose repro file
re-triggers the identical violation deterministically.  (b) and (c)
are proven with a PLANTED violation: a ``tx_injector`` behavior — a
Byzantine proposer slipping its own transactions into its proposals,
perfectly legal HBBFT — which the harness detects with certainty
because it knows every submitted tx.
"""

import copy
import json

import pytest

from tools.fuzz import (
    Violation,
    load_repro,
    run_schedule,
    sample_schedule,
    shrink,
    write_repro,
)

pytestmark = pytest.mark.faults


def planted_schedule():
    """A small failing schedule buried under irrelevant components the
    shrinker must strip away."""
    return {
        "version": 1,
        "seed": 3,
        "n": 4,
        "f": 1,
        "batch_size": 8,
        "key_seed": 33,
        "rounds": 4,
        "txs": 4,
        "bad": ["node003"],
        "behaviors": [
            {"kind": "split_voter", "node": "node003", "seed": 1},
            {"kind": "tx_injector", "node": "node003", "seed": 9},
        ],
        "wire": [{"stage": "drop", "args": {"fraction": 0.1}}],
        "timeline": [
            {
                "round": 1,
                "op": "partition",
                "node": "node003",
                "peer": "node000",
            },
            {
                "round": 2,
                "op": "heal",
                "node": "node003",
                "peer": "node000",
            },
        ],
        "check_liveness": True,
    }


def test_sampled_schedules_are_seed_pure():
    a = sample_schedule(5)
    b = sample_schedule(5)
    assert a == b
    assert a["seed"] == 5
    # sampled faults stay inside the f-budget coalition
    fault_nodes = {spec["node"] for spec in a["behaviors"]}
    fault_nodes |= {
        ev["node"] for ev in a["timeline"] if ev["op"] == "crash"
    }
    assert fault_nodes <= set(a["bad"])
    assert len(a["bad"]) == a["f"]


def test_sampler_never_mounts_the_tx_injector():
    for seed in range(40):
        s = sample_schedule(seed)
        assert all(
            b["kind"] != "tx_injector" for b in s["behaviors"]
        ), f"seed {seed} sampled the planted-violation behavior"


def test_smoke_seeds_hold_every_invariant():
    """A slice of the ci.sh smoke band: composite semantic+wire
    schedules over seeded 4-node clusters, all invariants green."""
    for seed in (0, 3):
        assert run_schedule(sample_schedule(seed)) is None


def test_planted_violation_is_detected_and_detail_named():
    v = run_schedule(planted_schedule())
    assert v is not None
    assert v["invariant"] == "no_foreign_tx"
    assert "injected|9|0" in v["detail"]


def test_shrink_converges_to_minimal_replayable_repro(tmp_path):
    """The acceptance scenario: shrink the planted schedule to the
    single guilty component, write the repro, and replay it twice —
    same violation, byte for byte."""
    schedule = planted_schedule()
    minimal, violation = shrink(schedule)
    # every irrelevant component stripped: only the injector remains
    assert minimal["behaviors"] == [
        {"kind": "tx_injector", "node": "node003", "seed": 9}
    ]
    assert minimal["wire"] == []
    assert minimal["timeline"] == []
    assert minimal["txs"] == 1
    assert minimal["rounds"] == 2
    # the minimal schedule violates the SAME invariant that started
    # the shrink (the invariant-pinning contract)
    assert violation is not None
    assert violation["invariant"] == "no_foreign_tx"
    assert run_schedule(minimal) == violation
    repro = tmp_path / "repro.json"
    write_repro(str(repro), minimal, violation)
    loaded = load_repro(str(repro))
    assert loaded["schedule"] == minimal
    # deterministic re-trigger: two fresh replays, identical reports
    r1 = run_schedule(loaded["schedule"])
    r2 = run_schedule(loaded["schedule"])
    assert r1 == r2 == violation
    # and the repro is honest JSON: round-trips unchanged
    assert json.loads(json.dumps(loaded["schedule"])) == minimal


def test_shrink_refuses_a_passing_schedule():
    with pytest.raises(ValueError, match="failing schedule"):
        shrink(sample_schedule(0))


def test_shrink_input_is_not_mutated():
    schedule = planted_schedule()
    frozen = copy.deepcopy(schedule)
    shrink(schedule)
    assert schedule == frozen


def test_shrink_skips_confirming_run_when_violation_supplied():
    """fuzz_seeds hands shrink the violation it already observed; the
    pinned invariant must match what an unprimed shrink finds."""
    schedule = planted_schedule()
    known = run_schedule(schedule)
    minimal, violation = shrink(schedule, known)
    assert violation["invariant"] == known["invariant"]
    assert minimal["behaviors"] == [
        {"kind": "tx_injector", "node": "node003", "seed": 9}
    ]


def test_violation_exception_report_shape():
    v = Violation("agreement", "fork at epoch 0", 3)
    assert v.report == {
        "invariant": "agreement",
        "detail": "fork at epoch 0",
        "round": 3,
    }


def test_fuzzer_records_flight_recorder_artifact(tmp_path):
    """run_schedule(trace_path=...) writes a merged Perfetto-loadable
    artifact (the PR-3 plane) for any schedule, failing or not."""
    path = tmp_path / "fuzz_trace.json"
    v = run_schedule(
        {**planted_schedule(), "wire": [], "timeline": []},
        trace_path=str(path),
    )
    assert v is not None
    doc = json.loads(path.read_text())
    assert doc["traceEvents"], "empty trace artifact"


@pytest.mark.slow
def test_fuzz_deep_sweep():
    """The deep band: 200 sampled composite schedules, every safety
    and liveness invariant must hold (ci.sh stage runs the 0:20 smoke
    band; this is the RUN-SLOW extension)."""
    for seed in range(20, 220):
        v = run_schedule(sample_schedule(seed))
        assert v is None, f"seed {seed}: {v}"


@pytest.mark.slow
def test_fuzz_pipeline_deep_sweep():
    """The K-deep pipelined-frontier deep band (ISSUE 15): 200
    sampled composite schedules pinned alternately to depth 2 and
    depth 4 — the cross-frontier invariants (settled prefix ⊆
    ordered log, byte-identical honest ordered logs, decrypt-lag
    bound) must hold over the widened in-flight window (ci.sh runs
    the 20-seed smoke band of this sampler)."""
    for seed in range(20, 220):
        depth = 2 if seed % 2 else 4
        v = run_schedule(
            sample_schedule(seed, pipeline_depth=depth)
        )
        assert v is None, f"seed {seed} depth {depth}: {v}"


@pytest.mark.slow
def test_fuzz_wan_deep_sweep():
    """The WAN emulation deep band (ISSUE 16): 200 sampled composite
    schedules over the seeded link-model plane — the profile (lan /
    wan_3region / wan_global / straggler_tail / lossy) is itself
    drawn from the seed, so latency, jitter, loss-retransmission,
    bandwidth serialization and heavy-tailed straggler episodes all
    reshape delivery order — and every safety and liveness invariant
    must hold (ci.sh runs the 0:20 smoke band of this sampler; this
    is the RUN-SLOW extension)."""
    for seed in range(20, 220):
        v = run_schedule(sample_schedule(seed, wan=True))
        assert v is None, f"seed {seed}: {v}"


@pytest.mark.slow
def test_fuzz_reconfig_deep_sweep():
    """The dynamic-membership deep band: 200 reconfig-bearing
    schedules — every sampled crash/partition/semantic composite runs
    ACROSS a join (sometimes composed with a coalition retirement)
    reshare ceremony, and the invariants (ledger agreement, roster/
    key agreement, no foreign tx, liveness for the final roster) must
    span the switch (ci.sh runs the 0:20 smoke band of this sampler;
    this is the RUN-SLOW extension)."""
    for seed in range(20, 220):
        v = run_schedule(
            sample_schedule(seed, rounds=16, reconfig=True)
        )
        assert v is None, f"seed {seed}: {v}"


@pytest.mark.slow
def test_fuzz_lanes_deep_sweep():
    """The lane shard-out deep band (ISSUE 20): 200 sampled composite
    schedules with Config.lanes drawn from {2,3,4} per seed (appended
    LAST, extending the historical stream) — S independent HBBFT
    lanes over one roster, hash-partitioned admission and the
    deterministic cross-lane merge — gating merge-determinism (every
    honest node's merged total order byte-identical), cross-lane
    settle-exactly-once, the per-lane two-frontier invariants and
    liveness over the merged ledger (ci.sh runs the 0:20 smoke band
    of this sampler; this is the RUN-SLOW extension)."""
    for seed in range(20, 220):
        v = run_schedule(sample_schedule(seed, lanes=True))
        assert v is None, f"seed {seed}: {v}"
