"""Known-good CONC002 corpus: handlers that enqueue instead of block,
and blocking calls confined to non-handler worker loops."""

import time


class Conn:
    def __init__(self):
        self.outbox = []

    def serve_request(self, msg):
        self.outbox.append(msg)  # enqueue; the writer thread ships it

    def handle_frame(self, frame):
        return len(frame)

    def writer_loop(self, sock):
        # not a handler: the dedicated writer thread may block
        while self.outbox:
            sock.sendall(self.outbox.pop(0))
            time.sleep(0.01)
