"""Known-good DET004 fixture: the wave-router seam discipline — the
transport buffers a delivery wave and hands it over in ONE serve_wave
call; the per-frame fallback for handlers without wave ingest carries
a justified pragma (the scalar comparison arm pattern)."""


def read_loop(inbound, handler, decode):
    batch = []
    for wire in inbound:
        batch.append(decode(wire))
    if not batch:
        return
    serve_wave = getattr(handler, "serve_wave", None)
    if serve_wave is not None:
        serve_wave(batch)
    else:
        for msg in batch:
            handler.serve_request(msg)  # staticcheck: allow[DET004] non-wave handler fallback
