"""Known-bad CONC004 corpus: blocking calls one or more hops BELOW a
dispatcher handler — invisible to CONC002's single-body scan, caught
by the pass-3 reachability walk."""

import os
import time


class Conn:
    def __init__(self, fd):
        self._fd = fd
        self.outbox = []

    def handle_frame(self, frame):
        self.outbox.append(frame)
        self._persist()

    def _persist(self):
        os.fsync(self._fd)  # BAD:CONC004

    def on_tick(self):
        self._drain_slowly()

    def _drain_slowly(self):
        while self.outbox:
            self.outbox.pop(0)
            time.sleep(0.01)  # BAD:CONC004

    def serve_batch(self, frames):
        for frame in frames:
            self._relay(frame)

    def _relay(self, frame):
        self._deep_relay(frame)

    def _deep_relay(self, frame):
        # two hops down still stalls the dispatch thread
        return self._sock.recv(1024)  # BAD:CONC004
