"""Known-bad DET006 fixture: a transport send loop encoding and
signing per frame — the exact per-post envelope encode + MAC pass the
wave signer (ISSUE 13) replaced.  Both the sign_wire_many form (one
scalar signer pass per post) and a direct encode_message call (a raw
per-frame envelope encode from a send path) must gate."""

from cleisthenes_tpu.transport.message import encode_message


def flush_outbound(auth, posts):
    frames = []
    for msg, receiver_id in posts:
        wire = auth.sign_wire_many(msg, [receiver_id])  # BAD:DET006
        frames.append(wire[receiver_id])
    return frames


def send_raw(conn, auth, msg, receiver_id):
    conn.send_wire(encode_message(auth.sign(msg, receiver_id)))  # BAD:DET006
