"""Known-good DET006 fixture: the wave-signer discipline — a flush
buffers its whole egress wave and signs it in ONE sign_wire_wave call
(payload bodies encode once per distinct object through the shared
FrameEncodeMemo, MACs batch over the precomputed key schedules); the
scalar comparison arm carries a justified pragma."""


def flush_outbound(auth, posts, memo, egress_columnar):
    if egress_columnar:
        items = [(msg, (receiver_id,)) for msg, receiver_id in posts]
        return [
            frames[rids[0]]
            for (_msg, rids), frames in zip(
                items, auth.sign_wire_wave(items, memo)
            )
        ]
    return [
        auth.sign_wire_many(msg, [rid])[rid]  # staticcheck: allow[DET006] scalar arm
        for msg, rid in posts
    ]
