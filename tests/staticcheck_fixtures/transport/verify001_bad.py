"""known-bad VERIFY001: a receive path that decodes network-origin
frames and hands them to the handler with NO verify_wire* between
decode and dispatch — Byzantine bytes reaching the protocol plane
unauthenticated, the exact hole the reference left open
(conn.go:134-137 TODO)."""

from cleisthenes_tpu.transport.message import decode_frame


class RawPath:
    def __init__(self, handler, auth):
        self._handler = handler
        self._auth = auth

    def pump(self, frames):
        wave = []
        for data in frames:
            msg, prefix = decode_frame(data)
            wave.append(msg)
        self._handler.serve_wave(wave)  # BAD:VERIFY001
