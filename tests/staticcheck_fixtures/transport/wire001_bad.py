"""known-bad WIRE001: a payload-kind registry with a reused number,
a kind no parser accepts, and a kind no encoder emits.  A miniature
transport/message.py — the registry index detects the ``_KIND_*``
module constants and cross-checks them against the encode returns and
the parse comparisons in the same module."""

_KIND_ALPHA = 3
_KIND_BETA = 3  # BAD:WIRE001
_KIND_GAMMA = 5  # BAD:WIRE001
_KIND_DELTA = 6  # BAD:WIRE001


def _encode_payload(p):
    if isinstance(p, tuple):
        return _KIND_ALPHA, b"a"
    if isinstance(p, list):
        return _KIND_BETA, b"b"
    if isinstance(p, dict):
        return _KIND_GAMMA, b"g"
    raise TypeError(type(p))


def _parse_payload(kind, data):
    if kind == _KIND_ALPHA:
        return ("alpha", data)
    if kind == _KIND_BETA:
        return ["beta", data]
    if kind == _KIND_DELTA:
        return {"delta": data}
    raise ValueError(kind)
