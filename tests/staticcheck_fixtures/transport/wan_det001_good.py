"""Known-good DET001 corpus for the WAN stem rule: link-model entropy
drawn through the audited utils.determinism doorway replays
byte-identically for a fixed seed."""

import random
from typing import Optional

from cleisthenes_tpu.utils.determinism import wan_rng


def link_rng(seed: Optional[int], sender: str, receiver: str) -> random.Random:
    return wan_rng(seed, "link", sender, receiver)


def jittered_owd(rng: random.Random, rtt_s: float) -> float:
    return rtt_s / 2 * (1.0 + 0.25 * rng.random())
