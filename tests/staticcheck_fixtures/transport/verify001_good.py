"""known-good VERIFY001: the same receive path with the MAC check in
place — frames failing verify_wire never reach the handler, and the
dispatched wave derives only from verified values."""

from cleisthenes_tpu.transport.message import decode_frame


class VerifiedPath:
    def __init__(self, handler, auth):
        self._handler = handler
        self._auth = auth

    def pump(self, frames):
        wave = []
        for data in frames:
            msg, prefix = decode_frame(data)
            if not self._auth.verify_wire(msg, prefix):
                continue
            wave.append(msg)
        self._handler.serve_wave(wave)
