"""Known-bad DET001 corpus for the WAN stem rule: a ``transport/``
file whose stem starts with ``wan_`` is part of the determinism plane
(tools/staticcheck/core.py FileContext) — raw entropy or wall-clock in
a link model would silently break byte-identical replay of a seeded
WAN schedule, so the same DET001 bans gate here as in protocol/."""

import random
import time


def jittered_owd(rtt_s: float) -> float:
    return rtt_s / 2 * (1.0 + 0.25 * random.random())  # BAD:DET001


def link_rng() -> random.Random:
    return random.Random()  # BAD:DET001


def deadline() -> float:
    return time.monotonic() + 0.5  # BAD:DET001
