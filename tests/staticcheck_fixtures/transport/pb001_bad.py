"""known-bad WIRE001 (pb side): an extension-tag registry with a
reused tag number, a tag landing on the reference envelope's reserved
numbers, and a declared-but-never-used tag."""

_PB_TAG_X = 15
_PB_TAG_Y = 15  # BAD:WIRE001
_PB_TAG_Z = 2  # BAD:WIRE001
_PB_TAG_W = 19  # BAD:WIRE001


def encode_tags():
    return (_PB_TAG_X, _PB_TAG_Y, _PB_TAG_Z)
