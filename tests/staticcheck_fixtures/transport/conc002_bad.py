"""Known-bad CONC002 corpus: blocking calls inside handler callbacks
(the ``transport/`` directory name puts this in the rule's scope)."""

import time


class Conn:
    def serve_request(self, msg):
        time.sleep(0.1)  # BAD:CONC002
        return msg

    def handle_frame(self, sock):
        return sock.recv(1024)  # BAD:CONC002

    def on_message(self, sock):
        sock.sendall(b"ack")  # BAD:CONC002

    def _handle_accept(self, listener):
        return listener.accept()  # BAD:CONC002
