"""known-good WIRE001: every kind carries a unique number, an encode
return and a parse comparison.  No pb adapter imports this module's
stem, so pb-slot coverage is not demanded here (the cross-module
fixture tree exercises that pairing)."""

_KIND_ALPHA = 3
_KIND_BETA = 4


def _encode_payload(p):
    if isinstance(p, tuple):
        return _KIND_ALPHA, b"a"
    return _KIND_BETA, b"b"


def _parse_payload(kind, data):
    if kind == _KIND_ALPHA:
        return ("alpha", data)
    if kind == _KIND_BETA:
        return ["beta", data]
    raise ValueError(kind)
