"""Known-good CONC004 corpus: handlers enqueue; the blocking helpers
are reachable only from dedicated worker loops (non-handler names),
which MAY block."""

import os
import time


class Conn:
    def __init__(self, fd):
        self._fd = fd
        self.outbox = []

    def handle_frame(self, frame):
        self.outbox.append(frame)

    def on_tick(self):
        return len(self.outbox)

    def writer_loop(self):
        # not a handler: the dedicated writer thread owns the fsync
        while self.outbox:
            self.outbox.pop(0)
            self._persist()
            time.sleep(0.01)

    def _persist(self):
        os.fsync(self._fd)
