"""Known-bad DET004 fixture: a transport reader loop dispatching
per-frame into the handler — the exact per-payload ingest chain the
wave router (ISSUE 10) replaced.  Both the serve_request form (a
Handler boundary) and a direct handle_message call (reaching into the
protocol plane from transport code) must gate."""


def read_loop(inbound, handler, decode):
    for wire in inbound:
        msg = decode(wire)
        handler.serve_request(msg)  # BAD:DET004


def deliver_decoded(msgs, node):
    for msg in msgs:
        node.handle_message(msg.sender_id, msg.payload)  # BAD:DET004
