"""known-good WIRE001 (pb side): unique extension tags off the
reserved envelope numbers, every declared tag used by the adapter."""

_PB_TAG_X = 15
_PB_TAG_Y = 16


def encode_tags():
    return (_PB_TAG_X, _PB_TAG_Y)
