"""xmodule-bad exposition: xb_stray_total is emitted but absent
from the golden; the golden's xb_ghost_total is never emitted."""


def render(exp, metrics, labels):
    exp.add(
        exp.family("xb_foo_total", "counter", "requests"),
        labels,
        metrics.xb_reqs_total.value,
    )
    exp.add(
        exp.family("xb_stray_total", "counter", "strays"),
        labels,
        0,
    )
