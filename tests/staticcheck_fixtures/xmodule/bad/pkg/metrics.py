"""xmodule-bad metrics: xb_lost_total is incremented by the engine
but never reaches the snapshot schema (silent dashboard drift)."""


class Counter:
    def __init__(self):
        self.value = 0

    def inc(self, by=1):
        self.value += by


class Metrics:
    def __init__(self):
        self.xb_reqs_total = Counter()
        self.xb_lost_total = Counter()

    def snapshot(self):
        return {"xb_reqs_total": self.xb_reqs_total.value}
