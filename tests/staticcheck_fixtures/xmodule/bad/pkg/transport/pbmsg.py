"""xmodule-bad pb adapter: pairs with wiremsg via the import stem
but only carries _KIND_ONE."""

from pkg.transport.wiremsg import _KIND_ONE

_PB_TAG_ONE = 15


def encode_pb(kind, body):
    if kind == _KIND_ONE:
        return (_PB_TAG_ONE, body)
    raise ValueError(kind)
