"""xmodule-bad engine: reads both arm flags (so neither is a dead
arm) and increments both counters (so the schema drift is about the
snapshot, not about dead metrics)."""


class Engine:
    def __init__(self, config, metrics):
        self._wave = bool(config.xb_turbo) and bool(config.xb_nitro)
        self._gears = int(config.xb_gears)
        self.metrics = metrics

    def step(self, ok):
        self.metrics.xb_reqs_total.inc()
        if not ok:
            self.metrics.xb_lost_total.inc()
