"""xmodule-bad config: xb_turbo is missing from the perfgate
fingerprint; xb_nitro is never pinned in the equivalence tests;
xb_gears (an int arm) is pinned at only ONE value."""

import dataclasses

ARM_FLAGS = ("xb_turbo", "xb_nitro", "xb_gears")


@dataclasses.dataclass
class Config:
    xb_turbo: bool = True
    xb_nitro: bool = True
    xb_gears: int = 1
    batch: int = 8
