"""xmodule-bad config: xb_turbo is missing from the perfgate
fingerprint; xb_nitro is never pinned in the equivalence tests."""

import dataclasses

ARM_FLAGS = ("xb_turbo", "xb_nitro")


@dataclasses.dataclass
class Config:
    xb_turbo: bool = True
    xb_nitro: bool = True
    batch: int = 8
