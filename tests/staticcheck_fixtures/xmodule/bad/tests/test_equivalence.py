"""xmodule-bad equivalence tests: xb_turbo is pinned on both arms;
xb_nitro never is."""

from pkg.config import Config


def test_turbo_arms():
    assert Config(xb_turbo=False).batch == Config(xb_turbo=True).batch
