"""xmodule-bad equivalence tests: xb_turbo is pinned on both arms;
xb_nitro never is; xb_gears pins only the baseline value."""

from pkg.config import Config


def test_turbo_arms():
    assert Config(xb_turbo=False).batch == Config(xb_turbo=True).batch


def test_gear_baseline_only():
    assert Config(xb_gears=1).batch == Config(xb_gears=1).batch
