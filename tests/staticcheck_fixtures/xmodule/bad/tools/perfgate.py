"""xmodule-bad perfgate: the fingerprint carries xb_nitro and
xb_gears but NOT xb_turbo."""


def sample(cfg):
    return {
        "kind": "mini",
        "fingerprint": {
            "kind": "mini",
            "xb_nitro": bool(cfg.xb_nitro),
            "xb_gears": int(cfg.xb_gears),
        },
    }
