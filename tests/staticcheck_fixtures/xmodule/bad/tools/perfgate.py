"""xmodule-bad perfgate: the fingerprint carries xb_nitro but NOT
xb_turbo."""


def sample(cfg):
    return {
        "kind": "mini",
        "fingerprint": {
            "kind": "mini",
            "xb_nitro": bool(cfg.xb_nitro),
        },
    }
