from pkg.transport import helpers


class Conn:
    def __init__(self, fd):
        self._fd = fd
        self.outbox = []

    def handle_frame(self, frame):
        self.outbox.append(frame)

    def writer_loop(self):
        # not a handler: the dedicated writer thread owns the fsync
        while self.outbox:
            self.outbox.pop(0)
            helpers.slow_write(self._fd)
