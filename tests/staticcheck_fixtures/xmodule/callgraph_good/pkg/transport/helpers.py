import os


def slow_write(fd):
    os.fsync(fd)
