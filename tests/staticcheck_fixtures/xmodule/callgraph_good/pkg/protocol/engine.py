from pkg.protocol import clock
from pkg.protocol.state import Table


class Engine:
    def lookup(self, k):
        t = Table()
        with t._lock:
            return t._get_locked(k)

    def mark(self, seed):
        self.t0 = clock.logical(seed)
