def logical(seed):
    return seed + 1
