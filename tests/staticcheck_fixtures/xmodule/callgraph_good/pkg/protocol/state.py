import threading

from cleisthenes_tpu.utils.determinism import guarded_by


@guarded_by("_lock", "_table")
class Table:
    def __init__(self):
        self._lock = threading.Lock()
        self._table = {}

    def _get_locked(self, k):
        return self._table.get(k)
