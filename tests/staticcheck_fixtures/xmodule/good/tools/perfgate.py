"""xmodule-good perfgate: the fingerprint keys on the arm flag."""


def sample(cfg):
    return {
        "kind": "mini",
        "fingerprint": {
            "kind": "mini",
            "xg_turbo": bool(cfg.xg_turbo),
        },
    }
