"""xmodule-good perfgate: the fingerprint keys on both arm flags."""


def sample(cfg):
    return {
        "kind": "mini",
        "fingerprint": {
            "kind": "mini",
            "xg_turbo": bool(cfg.xg_turbo),
            "xg_gears": int(cfg.xg_gears),
        },
    }
