"""xmodule-good equivalence tests: the scalar arm is pinned."""

from pkg.config import Config


def test_turbo_arms():
    assert Config(xg_turbo=False).batch == Config(xg_turbo=True).batch
