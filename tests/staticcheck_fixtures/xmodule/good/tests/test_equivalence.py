"""xmodule-good equivalence tests: the scalar arm is pinned, and
the int arm pins two distinct values."""

from pkg.config import Config


def test_turbo_arms():
    assert Config(xg_turbo=False).batch == Config(xg_turbo=True).batch


def test_gear_arms():
    assert Config(xg_gears=1).batch == Config(xg_gears=4).batch
