"""xmodule-good metrics: every counter incremented and exported."""


class Counter:
    def __init__(self):
        self.value = 0

    def inc(self, by=1):
        self.value += by


class Metrics:
    def __init__(self):
        self.xg_reqs_total = Counter()

    def snapshot(self):
        return {"xg_reqs_total": self.xg_reqs_total.value}
