"""xmodule-good config: the bool arm flag is fingerprinted and
pinned; the int arm flag is fingerprinted and pinned at two distinct
values (baseline + fast arm)."""

import dataclasses

ARM_FLAGS = ("xg_turbo", "xg_gears")


@dataclasses.dataclass
class Config:
    xg_turbo: bool = True
    xg_gears: int = 1
    batch: int = 8
