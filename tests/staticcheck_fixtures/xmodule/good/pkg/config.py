"""xmodule-good config: the arm flag is fingerprinted and pinned."""

import dataclasses

ARM_FLAGS = ("xg_turbo",)


@dataclasses.dataclass
class Config:
    xg_turbo: bool = True
    batch: int = 8
