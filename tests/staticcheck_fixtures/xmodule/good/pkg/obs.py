"""xmodule-good exposition: families match the golden exactly."""


def render(exp, metrics, labels):
    exp.add(
        exp.family("xg_foo_total", "counter", "requests"),
        labels,
        metrics.xg_reqs_total.value,
    )
