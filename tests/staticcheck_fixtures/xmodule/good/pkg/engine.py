"""xmodule-good engine: reads the arm flag, feeds the counter."""


class Engine:
    def __init__(self, config, metrics):
        self._wave = bool(config.xg_turbo)
        self._gears = int(config.xg_gears)
        self.metrics = metrics

    def step(self):
        self.metrics.xg_reqs_total.inc()
