"""xmodule-good engine: reads the arm flag, feeds the counter."""


class Engine:
    def __init__(self, config, metrics):
        self._wave = bool(config.xg_turbo)
        self.metrics = metrics

    def step(self):
        self.metrics.xg_reqs_total.inc()
