"""xmodule-good wire registry: both kinds fully covered."""

_KIND_ONE = 3
_KIND_TWO = 4


def _encode_payload(p):
    if isinstance(p, tuple):
        return _KIND_ONE, b"1"
    return _KIND_TWO, b"2"


def _parse_payload(kind, data):
    if kind == _KIND_ONE:
        return ("one", data)
    if kind == _KIND_TWO:
        return ["two", data]
    raise ValueError(kind)
