"""xmodule-good pb adapter: carries every kind of the paired wire
registry."""

from pkg.transport.wiremsg import _KIND_ONE, _KIND_TWO

_PB_TAG_ONE = 15
_PB_TAG_TWO = 16


def encode_pb(kind, body):
    if kind == _KIND_ONE:
        return (_PB_TAG_ONE, body)
    if kind == _KIND_TWO:
        return (_PB_TAG_TWO, body)
    raise ValueError(kind)
