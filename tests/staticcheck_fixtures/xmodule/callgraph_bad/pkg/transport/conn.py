from pkg.transport import helpers


class Conn:
    def __init__(self, fd):
        self._fd = fd

    def handle_frame(self, frame):
        # the blocking call lives in another module: CONC002's
        # single-body scan sees a clean handler
        helpers.slow_write(self._fd)
