from pkg.protocol import clock
from pkg.protocol.state import Table


class Engine:
    def lookup(self, k):
        t = Table()
        # the guarded class lives one module away: only the
        # cross-file graph can demand its lock here
        return t._get_locked(k)  # BAD:CONC003

    def mark(self):
        # the entropy source is two files away (clock.wall ->
        # time.time); the derived value still lands in plane state
        self.t0 = clock.wall()  # BAD:DET007
