import time


def wall():
    return time.time()  # BAD:DET001
