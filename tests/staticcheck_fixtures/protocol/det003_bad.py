"""DET003 known-bad: direct BatchCrypto verify/decode dispatch from
protocol/ code outside hub.py — every call here bypasses the hub's
columnar seam and regresses the wave back to scalar dispatch."""

from cleisthenes_tpu.ops.tpke import verify_share_groups


class LeakyClient:
    def __init__(self, crypto, pub):
        self.crypto = crypto
        self.pub = pub
        self._pending = []

    def handle_echo(self, root, leaf, branch, index):
        # scalar per-message Merkle check instead of staging the proof
        return self.crypto.merkle.verify_branch(root, leaf, branch, index)  # BAD:DET003

    def handle_echo_wavefront(self, items):
        # batched, but still a direct dispatch — the hub owns this call
        return self.crypto.merkle.verify_batch(items)  # BAD:DET003

    def try_decode(self, idxs, shards):
        data, roots, _n = self.crypto.decode_recheck_batch(idxs, shards)  # BAD:DET003
        return data, roots

    def check_shares(self, base, context, shares):
        # from-imported ops function resolves through the alias map
        return verify_share_groups(  # BAD:DET003
            [(self.pub, base, context, shares)]
        )
