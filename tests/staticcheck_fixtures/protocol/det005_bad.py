"""DET005 fixture: epoch-scoped code pinning the construction-time
roster instead of resolving through the roster-version accessor."""


class Node:
    def __init__(self, config, members, keys):
        self.config = config
        self.members = members
        self._member_set = frozenset(members)
        self.keys = keys

    def handle_share(self, sender, epoch):
        if sender not in self._member_set:  # BAD:DET005
            return None
        if self.config.n < 4:  # BAD:DET005
            return None
        if self.config.f == 0:  # BAD:DET005
            return None
        return self.keys  # BAD:DET005

    def serve_column(self, items, expected_epoch):
        # any epoch-ish parameter scopes the function to one epoch
        width = self.config.n  # BAD:DET005
        return [i for i in items][:width]
