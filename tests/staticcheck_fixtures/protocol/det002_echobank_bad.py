"""Known-bad DET002 corpus for the EchoBank surface (ISSUE 9): the
delivery-plane bank keeps receipt state in arrays and insertion-
ordered dicts precisely so no set order ever reaches protocol
decisions — a hand-rolled bank that iterates its sender/root SETS in
hash order must still gate.  Every tagged line is the exact shape the
real protocol.echobank avoids (its registry is a dict, its pending
slots are lists)."""


class BadEchoBank:
    """An EchoBank-alike that leaks PYTHONHASHSEED order."""

    def __init__(self):
        # receipt state as sets — the pre-bank dict-of-dicts shape
        self.echo_senders = set()
        self.ready_roots: set = set()
        self.pending = {}

    def drain_slots(self, wave):
        # hash-order drain: wave column order would differ across
        # PYTHONHASHSEED values (the regression DET002 exists for)
        for sender in self.echo_senders:  # BAD:DET002
            wave.add(sender)

    def quorum_roots(self):
        return [r for r in self.ready_roots]  # BAD:DET002

    def first_root(self):
        candidates = {b"r1", b"r2"}
        ordered = list(candidates)  # BAD:DET002
        return ordered[0]

    def relay_order(self):
        crossings = frozenset(("a", "b"))
        return max(crossings)  # BAD:DET002
