"""DET005 fixture (clean): epoch-scoped code resolving n/f/keys and
membership through the epoch's roster view, and active-roster reads
confined to epoch-UNSCOPED code."""


class Node:
    def __init__(self, config, members, keys):
        self.config = config
        self.members = members
        self._member_set = frozenset(members)
        self.keys = keys

    def roster_for(self, epoch):
        return self

    def handle_share(self, sender, epoch, es):
        view = es.view
        if sender not in view.member_set:
            return None
        if view.config.n < 4:
            return None
        if view.config.f == 0:
            return None
        return view.keys

    def resolve(self, epoch):
        # the sanctioned accessor: the view carries the roster
        view = self.roster_for(epoch)
        return view.config.n

    def roster_unscoped(self, sender):
        # no epoch parameter: the ACTIVE roster is exactly right here
        return sender in self._member_set and self.config.n
