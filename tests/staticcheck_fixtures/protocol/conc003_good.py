"""Known-good CONC003 corpus: every *_locked call site holds the
callee's declared lock, defers to its own *_locked caller, or runs in
single-threaded construction."""

import threading

from cleisthenes_tpu.utils.determinism import guarded_by


@guarded_by("_lock", "_items")
class Store:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = {}
        # constructors are exempt: nothing else can hold a reference
        self._warm_locked()

    def _size_locked(self):
        return len(self._items)

    def _warm_locked(self):
        # *_locked calling a sibling *_locked of the same class
        # defers the obligation to ITS callers (transitivity)
        return self._size_locked()

    def snapshot(self):
        with self._lock:
            return self._size_locked()


class Reader:
    def report(self):
        store = Store()
        with store._lock:
            return store._size_locked()
