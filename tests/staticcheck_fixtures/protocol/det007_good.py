"""Known-good DET007 corpus: plane state fed from seeded inputs, the
sanctioned utils.determinism doorway, or pragma-owned exceptions."""

from cleisthenes_tpu.utils.determinism import proposal_rng


class EpochState:
    def __init__(self, seed, node_id):
        # the sanctioned doorway: utils.determinism defs never count
        # as entropy sources (that module owns the seed->entropy fork)
        self._rng = proposal_rng(seed, node_id)

    def _derive(self, seed):
        return seed * 2654435761 % (1 << 32)

    def mark(self, seed):
        # a pure function of the seed is not entropy
        self.t_start = self._derive(seed)

    def pick(self, n):
        self.last = self._rng.randrange(n)


class Telemetry:
    def stamp(self):
        import time

        # a pragma-owned exception seeds no taint: the justified
        # allow already records why this wall-clock read is legal
        t = time.time()  # staticcheck: allow[DET001] obs-only stamp
        self.t_obs = t
