"""Known-good DET002 corpus: sorted() boundaries, membership tests,
len(), set algebra, and (insertion-ordered) dict iteration."""


class Proto:
    def __init__(self):
        self.roots = set()
        self.tally = {}

    def walk(self):
        for r in sorted(self.roots):
            del r
        out = list(sorted(self.roots))
        if b"x" in self.roots:
            out.append(b"x")
        for k, v in self.tally.items():  # dicts are insertion-ordered
            del k, v
        return out, len(self.roots)


def set_algebra(a, b):
    merged = set(a) | set(b)
    merged -= set(b)
    return sorted(merged)
