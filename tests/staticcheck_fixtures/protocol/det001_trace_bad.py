"""Known-bad: a raw ``perf_counter`` in protocol code still gates
even with the tracing subsystem landed.

The observability plane's contract (docs/ARCHITECTURE.md) is that
trace timestamps come from ``utils.trace.TraceRecorder`` — the ONE
file carrying the ``allow[DET001]`` pragma — and protocol code calls
``recorder.now()`` / ``recorder.instant()``.  Inlining the clock here
must keep firing DET001: the pragma is confined to utils/trace.py,
not granted to the plane.
"""

import time


def record_epoch_open(events, epoch):
    # hand-rolled instrumentation instead of the recorder seam
    events.append(("open", epoch, time.perf_counter()))  # BAD:DET001


def record_epoch_commit(events, epoch):
    events.append(("commit", epoch, time.perf_counter_ns()))  # BAD:DET001
