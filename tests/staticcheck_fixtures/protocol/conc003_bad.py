"""Known-bad CONC003 corpus: *_locked callees invoked without the
caller lexically holding the callee class's declared lock — the
interprocedural gap CONC001 (same-method discipline) cannot see."""

import threading

from cleisthenes_tpu.utils.determinism import guarded_by


@guarded_by("_lock", "_items")
class Store:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = {}

    def _size_locked(self):
        return len(self._items)

    def snapshot(self):
        # same-class caller, lock not held at the call site
        return self._size_locked()  # BAD:CONC003

    def drain(self):
        with self._lock:
            n = self._size_locked()
        # ...and held-then-released does not count: the with block
        # closed before this call
        return n + self._size_locked()  # BAD:CONC003


class Reader:
    def report(self):
        store = Store()
        # cross-class caller through a constructor-typed local,
        # holding NO lock at all
        return store._size_locked()  # BAD:CONC003
