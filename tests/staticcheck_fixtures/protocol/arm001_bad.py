"""known-bad ARM001: an arm registry declaring a flag that is not a
bool Config field, a flag nothing ever reads (a dead arm whose scalar
twin cannot be reachable), and a wave entry point no arm-flag-reading
module reaches (a wave seam with no Config-flag gate)."""

import dataclasses

ARM_FLAGS = ("ab_phantom_arm", "ab_dead_arm")  # BAD:ARM001


@dataclasses.dataclass
class Config:
    ab_dead_arm: bool = True  # BAD:ARM001
    batch: int = 8


def handle_ab_wave(items):  # BAD:ARM001
    return [i for i in items]
