"""Known-good DET001 corpus: the sanctioned shapes — seeded RNGs,
hash-derived streams, and the audited utils helper."""

import hashlib
import random

from cleisthenes_tpu.utils.determinism import proposal_rng


def seeded_rng(seed: int, node_id: str) -> random.Random:
    return random.Random(f"{seed}|{node_id}")


def hash_stream(seed: int, ctr: int) -> bytes:
    return hashlib.sha256(b"dealer|%d|%d" % (seed, ctr)).digest()


def audited(seed, node_id):
    return proposal_rng(seed, node_id)
