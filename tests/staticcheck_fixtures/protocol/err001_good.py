"""Known-good ERR001 corpus: narrow excepts, and blanket excepts that
actually handle (deterministic-exclusion idiom, re-raise, logging)."""


def handle_vote(x):
    try:
        return int(x)
    except ValueError:
        return None


def handle_junk(decode, blob, excluded):
    try:
        return decode(blob)
    except Exception:
        # every correct node sees the same bytes: exclusion is the
        # deterministic handling, not a swallow
        excluded.add(blob)
        return None


def handle_fatal(op):
    try:
        return op()
    except Exception:
        raise
