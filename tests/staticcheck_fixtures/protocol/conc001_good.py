"""Known-good CONC001 corpus: disciplined access, *_locked helpers,
and an unannotated class (out of the rule's scope by construction)."""

import threading

from cleisthenes_tpu.utils.determinism import guarded_by


@guarded_by("_lock", "_items")
class Store:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = {}

    def add(self, k, v):
        with self._lock:
            self._items[k] = v

    def snapshot(self):
        with self._lock:
            return dict(self._items)

    def _size_locked(self):
        return len(self._items)


class Unannotated:
    def __init__(self):
        self._items = {}

    def touch(self):
        return len(self._items)
