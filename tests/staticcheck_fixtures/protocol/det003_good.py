"""DET003 known-good: protocol clients stage crypto work and offer it
through the hub's drain protocol; justified inline checks carry
allow[DET003] pragmas; and names that merely LOOK like crypto calls
(a local helper, bytes.decode) must not trip the rule."""


class WaveClient:
    def __init__(self, hub):
        self.hub = hub
        self._pending_echo = []
        self._staged_decodes = []

    def handle_echo(self, root, leaf, branch, index, sender):
        # the columnar discipline: park the proof, mark dirty, let the
        # hub's wave drain and batch it
        self._pending_echo.append((root, leaf, branch, index, sender))
        self.hub.mark_dirty(self)

    def drain_pending(self, wave):
        for root, leaf, branch, index, sender in self._pending_echo:
            wave.add_branch(self, root, leaf, branch, index, sender)
        self._pending_echo = []
        for root, idxs, shards, cb in self._staged_decodes:
            wave.add_decode(root, idxs, shards, cb)
        self._staged_decodes = []

    def precheck_val(self, crypto, root, leaf, branch, index):
        return crypto.merkle.verify_branch(  # staticcheck: allow[DET003] inline VAL check
            root, leaf, branch, index
        )

    def parse_frame(self, raw: bytes) -> str:
        # bytes.decode is text decoding, not an RS dispatch
        return raw.decode("utf-8")

    def decode_batch_label(self, rows):
        # a local helper that happens to share a hazard name is fine
        # when it is plain data shaping, not a crypto object's method
        return [f"row-{r}" for r in rows]


class BankClient:
    """The EchoBank discipline (ISSUE 9): pending proofs park in a
    contiguous per-instance bank slot and pop WHOLESALE into the hub
    wave — no inline verify anywhere on the receive path."""

    def __init__(self, hub, bank, index):
        self.hub = hub
        self.bank = bank
        self.index = index

    def echo_item(self, root, sender, shard, shard_index, branch):
        self.bank.pending[self.index].append(
            (root, sender, shard, shard_index, branch)
        )
        self.hub.mark_dirty(self)

    def drain_pending(self, wave):
        pend = self.bank.pending[self.index]
        self.bank.pending[self.index] = []
        for root, sender, shard, sidx, branch in pend:
            wave.add_branch(
                self, root, shard, branch, sidx, (root, sender)
            )
