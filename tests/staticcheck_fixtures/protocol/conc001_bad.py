"""Known-bad CONC001 corpus: guarded attributes touched outside the
declared lock."""

import threading

from cleisthenes_tpu.utils.determinism import guarded_by


@guarded_by("_lock", "_items", "_count")
class Store:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = {}
        self._count = 0

    def ok_add(self, k, v):
        with self._lock:
            self._items[k] = v
            self._count += 1

    def bad_get(self, k):
        return self._items.get(k)  # BAD:CONC001

    def bad_after_release(self):
        with self._lock:
            n = self._count
        return n + self._count  # BAD:CONC001

    def _scan_locked(self):
        # *_locked naming contract: caller holds the lock — exempt
        return len(self._items)
