"""Known-bad DET007 corpus: entropy escaping through a returning
helper into determinism-plane state — DET001 convicts the source
line, DET007 convicts where the derived value LANDS."""

import time


class EpochState:
    def _stamp(self):
        return time.time()  # BAD:DET001

    def mark(self):
        # the store is one hop from the source: only the taint walk
        # connects them
        self.t_start = self._stamp()  # BAD:DET007

    def reseed(self):
        salt = self._stamp()
        # tainted argument into a plane function
        self._apply(salt)  # BAD:DET007

    def _apply(self, salt):
        self.salt = salt
