"""DET005 fixture (lane shard-out): lane-scoped code reading the
bare primary-lane frontier instead of resolving through the
lane-indexed accessor."""


class Node:
    def __init__(self, config, lanes):
        self.config = config
        self.lanes = lanes
        self.epoch = 0
        self.settled_epoch = 0
        self.committed_batches = []

    def lane_frontier(self, lane):
        return self.epoch  # BAD:DET005

    def settle_column(self, lane, items):
        depth = len(self.committed_batches)  # BAD:DET005
        if self.settled_epoch > 0:  # BAD:DET005
            return items[:depth]
        return items
