"""Pragma corpus: a justified allow suppresses; a bare allow is
itself a finding (PRAGMA001) and suppresses nothing."""

import time


def sanctioned():
    return time.monotonic()  # staticcheck: allow[DET001] fixture: justified waiver


def unsanctioned():
    return time.time()  # staticcheck: allow[DET001]
