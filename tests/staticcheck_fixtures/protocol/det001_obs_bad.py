"""Known-bad: a hand-rolled telemetry sampling loop in protocol code
still gates even with the live telemetry plane landed.

The telemetry plane's contract (docs/OBSERVABILITY.md) is that every
wall-clock read lives in utils/ behind an audited ``allow[DET001]``
pragma — ``utils/timeseries.py`` (the sampler tick) and
``utils/watchdog.py`` (the stall clock) — and protocol code only ever
*provides* state (pending counts, epoch frontiers) through callables.
Inlining a sampler or a stall budget here must keep firing DET001:
the pragmas are confined to those two files, not granted to the plane.
"""

import time


def sample_metrics(series, snapshot):
    # hand-rolled sampler tick instead of utils.timeseries
    series.append((time.monotonic(), snapshot()))  # BAD:DET001


def commit_stalled(last_commit_t, budget_s):
    # hand-rolled stall detector instead of utils.watchdog
    return time.monotonic() - last_commit_t > budget_s  # BAD:DET001
