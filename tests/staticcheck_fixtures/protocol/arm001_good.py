"""known-good ARM001: the declared arm flag is a bool Config field,
read as the gate that selects between the wave entry point and its
scalar twin — so the wave seam is reachable from an arm-flag reader
and the scalar arm stays live."""

import dataclasses

ARM_FLAGS = ("ag_live_arm",)


@dataclasses.dataclass
class Config:
    ag_live_arm: bool = True
    batch: int = 8


def handle_ag_wave(items):
    return [i for i in items]


class Plane:
    def __init__(self, config):
        self._wave = bool(config.ag_live_arm)

    def ingest(self, items):
        if self._wave:
            return handle_ag_wave(items)
        return [self.ingest_one(i) for i in items]

    def ingest_one(self, item):
        return item
