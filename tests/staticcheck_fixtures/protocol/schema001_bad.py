"""known-bad SCHEMA001: a metrics registry with a counter nothing
ever increments and a counter that is incremented but never reaches
the snapshot schema (silent dashboard drift — the exact hazard the
zeroed-key snapshot rule of PRs 9/10/13 exists for)."""


class Counter:
    def __init__(self):
        self.value = 0

    def inc(self, by=1):
        self.value += by


class BadMetrics:
    def __init__(self):
        self.sc_orphan_total = Counter()  # BAD:SCHEMA001
        self.sc_ghost_total = Counter()  # BAD:SCHEMA001
        self.sc_good_total = Counter()

    def bump(self):
        self.sc_ghost_total.inc()
        self.sc_good_total.inc()

    def snapshot(self):
        return {
            "sc_orphan_total": self.sc_orphan_total.value,
            "sc_good_total": self.sc_good_total.value,
        }
