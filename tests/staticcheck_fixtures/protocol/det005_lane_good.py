"""DET005 fixture (lane shard-out, clean): lane-scoped code resolving
frontiers through the lane-indexed accessor, and bare primary-lane
frontier reads confined to lane-UNSCOPED code."""


class Node:
    def __init__(self, config, lanes):
        self.config = config
        self.lanes = lanes
        self.epoch = 0
        self.committed_batches = []

    def lane_frontier(self, lane):
        # the sanctioned accessor: the sibling carries its frontier
        return self.lanes[lane].epoch

    def settle_column(self, lane, items):
        depth = len(self.lanes[lane].committed_batches)
        return items[:depth]

    def primary_frontier(self):
        # no lane parameter: the primary lane's own frontier is
        # exactly right here (== the merged frontier at lanes=1)
        return self.epoch
