"""known-good SCHEMA001: every declared counter is incremented
somewhere and read into the snapshot schema."""


class Counter:
    def __init__(self):
        self.value = 0

    def inc(self, by=1):
        self.value += by


class GoodMetrics:
    def __init__(self):
        self.sg_reqs_total = Counter()
        self.sg_errs_total = Counter()

    def bump(self, failed):
        self.sg_reqs_total.inc()
        if failed:
            self.sg_errs_total.inc()

    def snapshot(self):
        return {
            "sg_reqs_total": self.sg_reqs_total.value,
            "sg_errs_total": self.sg_errs_total.value,
        }
