"""Known-bad ERR001 corpus: bare excepts and silent swallows."""


def handle_vote(x):
    try:
        return int(x)
    except:  # BAD:ERR001
        return None


def handle_share(x):
    try:
        return float(x)
    except Exception:  # BAD:ERR001
        pass


def handle_rows(rows):
    out = []
    for r in rows:
        try:
            out.append(int(r))
        except BaseException:  # BAD:ERR001
            continue
    return out
