"""Known-bad DET001 corpus: every banned construct, one per marked
line.  Tagged lines (BAD markers) must each yield exactly one finding
(tests/test_staticcheck.py asserts the exact set).  The ``protocol/``
directory name puts this file in the determinism plane for the
analyzer — same path-derived scoping as the real package."""

import os
import random
import secrets
import time
import uuid
import secrets as _sec


def clocks():
    a = time.time()  # BAD:DET001
    b = time.monotonic()  # BAD:DET001
    c = time.perf_counter()  # BAD:DET001
    return a, b, c


def entropy():
    w = secrets.token_bytes(8)  # BAD:DET001
    x = os.urandom(8)  # BAD:DET001
    y = uuid.uuid4()  # BAD:DET001
    z = random.random()  # BAD:DET001
    r = random.SystemRandom()  # BAD:DET001
    s = _sec.token_bytes(4)  # BAD:DET001
    t = random.Random()  # BAD:DET001
    return w, x, y, z, r, s, t


def seeded_is_fine(seed):
    return random.Random(seed)
