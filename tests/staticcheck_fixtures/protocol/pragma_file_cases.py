"""File-pragma corpus: allow-file waives a rule for the whole file."""

# staticcheck: allow-file[DET001] fixture: stats-only module, whole-file waiver

import time


def t1():
    return time.time()


def t2():
    return time.monotonic()
