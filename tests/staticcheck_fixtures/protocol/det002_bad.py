"""Known-bad DET002 corpus: set iteration reaching order-sensitive
sinks without sorted()."""


class Proto:
    def __init__(self):
        self.roots = set()
        self.names: set = set()

    def walk(self):
        for r in self.roots:  # BAD:DET002
            del r
        return [x for x in self.names]  # BAD:DET002


def local_sets():
    s = {b"a", b"b"}
    out = list(s)  # BAD:DET002
    t = frozenset((1, 2))
    m = max(t)  # BAD:DET002
    for x in set((1, 2)):  # BAD:DET002
        del x
    return out, m
