"""Native C++ GF(2^8) kernel tests: property-tested against the numpy
reference backend, plus a full HBBFT epoch on crypto_backend='cpp'."""

import numpy as np
import pytest

from cleisthenes_tpu.native.build import native_available

pytestmark = pytest.mark.skipif(
    not native_available(), reason="no C++ toolchain"
)


def test_native_selftest_passes():
    from cleisthenes_tpu.native.build import load_gf256

    assert load_gf256().gf256_selftest() == 0


@pytest.mark.parametrize("n,k", [(4, 2), (7, 3), (16, 6), (64, 22)])
def test_cpp_encode_matches_numpy(n, k):
    from cleisthenes_tpu.ops.rs_cpp import CppErasureCoder
    from cleisthenes_tpu.ops.rs_cpu import CpuErasureCoder

    rng = np.random.default_rng(n * 100 + k)
    data = rng.integers(0, 256, size=(k, 384), dtype=np.uint8)
    assert np.array_equal(
        CppErasureCoder(n, k).encode(data), CpuErasureCoder(n, k).encode(data)
    )


@pytest.mark.parametrize("seed", range(4))
def test_cpp_decode_roundtrip_any_k_survivors(seed):
    from cleisthenes_tpu.ops.rs_cpp import CppErasureCoder

    rng = np.random.default_rng(seed)
    n, k = 10, 4
    coder = CppErasureCoder(n, k)
    data = rng.integers(0, 256, size=(k, 200), dtype=np.uint8)
    full = coder.encode(data)
    survivors = sorted(rng.choice(n, size=k, replace=False).tolist())
    out = coder.decode(survivors, full[survivors])
    assert np.array_equal(out, data)


def test_cpp_encode_batch_matches_single():
    from cleisthenes_tpu.ops.rs_cpp import CppErasureCoder

    rng = np.random.default_rng(3)
    n, k, b = 8, 4, 5
    coder = CppErasureCoder(n, k)
    data = rng.integers(0, 256, size=(b, k, 128), dtype=np.uint8)
    batched = coder.encode_batch(data)
    for i in range(b):
        assert np.array_equal(batched[i], coder.encode(data[i]))


def test_backend_registry_exposes_cpp():
    from cleisthenes_tpu.config import Config
    from cleisthenes_tpu.ops.backend import get_backend

    cfg = Config(n=4, crypto_backend="cpp")
    crypto = get_backend(cfg)
    assert crypto.engine_backend == "cpu"
    data = np.arange(2 * 128, dtype=np.uint8).reshape(2, 128)
    full = crypto.erasure.encode(data)
    assert np.array_equal(
        crypto.erasure.decode([2, 3], full[2:4]), data
    )


def test_hbbft_epoch_on_cpp_backend():
    from tests.test_honeybadger import (
        assert_identical_batches,
        make_hb_network,
        push_txs,
    )
    from cleisthenes_tpu.config import Config
    from cleisthenes_tpu.protocol.honeybadger import setup_keys
    from cleisthenes_tpu.transport.base import HmacAuthenticator
    from cleisthenes_tpu.transport.broadcast import ChannelBroadcaster
    from cleisthenes_tpu.transport.channel import ChannelNetwork
    from cleisthenes_tpu.protocol.honeybadger import HoneyBadger

    cfg = Config(n=4, batch_size=8, crypto_backend="cpp")
    ids = [f"node{i}" for i in range(4)]
    keys = setup_keys(cfg, ids, seed=11)
    net = ChannelNetwork()
    nodes = {}
    for node_id in ids:
        hb = HoneyBadger(
            config=cfg,
            node_id=node_id,
            member_ids=ids,
            keys=keys[node_id],
            out=ChannelBroadcaster(net, node_id, ids),
        )
        nodes[node_id] = hb
        net.join(node_id, hb, HmacAuthenticator(node_id, keys[node_id].mac_keys))
    push_txs(nodes, 8)
    for hb in nodes.values():
        hb.start_epoch()
    net.run()
    assert_identical_batches(nodes)



class TestSha256Rows:
    def test_matches_hashlib_fixed_and_var(self):
        import hashlib

        import numpy as np

        from cleisthenes_tpu.ops.hashrows import sha256_rows

        rng = np.random.default_rng(3)
        rows = rng.integers(0, 256, size=(97, 131), dtype=np.uint8)
        got = sha256_rows(rows)
        for i in (0, 50, 96):
            assert got[i].tobytes() == hashlib.sha256(rows[i].tobytes()).digest()
        lens = rng.integers(0, 132, size=97)
        got = sha256_rows(rows, lens)
        for i in (0, 13, 96):
            assert (
                got[i].tobytes()
                == hashlib.sha256(rows[i, : int(lens[i])].tobytes()).digest()
            )

    def test_rejects_out_of_range_lens(self):
        import numpy as np
        import pytest

        from cleisthenes_tpu.ops.hashrows import sha256_rows

        rows = np.zeros((2, 8), dtype=np.uint8)
        with pytest.raises(ValueError):
            sha256_rows(rows, np.array([1, 9]))
        with pytest.raises(ValueError):
            sha256_rows(rows, np.array([-1, 4]))

    def test_fallback_path_matches_native(self, monkeypatch):
        """With the native library unavailable the hashlib fallback
        must produce identical digests (it is the degraded path for
        toolchain-less deployments)."""
        import hashlib

        import numpy as np

        import pytest

        from cleisthenes_tpu.ops import hashrows
        from cleisthenes_tpu.native.build import load_sha256

        if load_sha256() is None:
            # without the toolchain "native" would BE the fallback and
            # the comparison below would check it against itself
            pytest.skip("native sha256 unavailable; nothing to compare")
        rng = np.random.default_rng(9)
        rows = rng.integers(0, 256, size=(13, 57), dtype=np.uint8)
        lens = rng.integers(0, 58, size=13)
        native = hashrows.sha256_rows(rows, lens)
        monkeypatch.setattr(hashrows, "load_sha256", lambda: None)
        degraded = hashrows.sha256_rows(rows, lens)
        assert (native == degraded).all()
        # independent hashlib checks for BOTH fallback branches
        for i in (0, 7):
            assert (
                degraded[i].tobytes()
                == hashlib.sha256(rows[i, : int(lens[i])].tobytes()).digest()
            )
        full = hashrows.sha256_rows(rows)
        assert full[3].tobytes() == hashlib.sha256(rows[3].tobytes()).digest()
