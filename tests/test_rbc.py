"""RBC protocol tests: the behavior matrix the reference's TDD
placeholders enumerate (reference rbc/rbc_test.go:5-19,
rbc/rbc_internal_test.go:5-31) plus Byzantine cases, run as full
multi-node instances over the deterministic in-proc transport
(SURVEY.md §4.3 pattern)."""

import hashlib

import pytest

from cleisthenes_tpu.config import Config
from cleisthenes_tpu.ops.backend import get_backend
from cleisthenes_tpu.protocol.rbc import RBC
from cleisthenes_tpu.transport.base import HmacAuthenticator
from cleisthenes_tpu.transport.broadcast import ChannelBroadcaster
from cleisthenes_tpu.transport.channel import ChannelNetwork
from cleisthenes_tpu.transport.message import RbcType


class RbcHandler:
    """Minimal node: every inbound message goes to one RBC instance."""

    def __init__(self, rbc: RBC):
        self.rbc = rbc

    def serve_request(self, msg):
        self.rbc.handle_message(msg.sender_id, msg.payload)


def make_rbc_network(n, proposer_idx=0, seed=None, auth=False, epoch=0):
    cfg = Config(n=n)
    crypto = get_backend(cfg)
    ids = [f"node{i}" for i in range(n)]
    proposer = ids[proposer_idx]
    net = ChannelNetwork(seed=seed)
    rbcs = {}
    master = b"test-master-secret"
    for node_id in ids:
        rbc = RBC(
            config=cfg,
            crypto=crypto,
            epoch=epoch,
            proposer=proposer,
            owner=node_id,
            member_ids=ids,
            out=ChannelBroadcaster(net, node_id, ids),
        )
        rbcs[node_id] = rbc
        net.join(
            node_id,
            RbcHandler(rbc),
            HmacAuthenticator.derive(master, node_id, ids) if auth else None,
        )
    return cfg, net, rbcs, proposer


PAYLOAD = b"tx-batch|" + bytes(range(256)) * 9 + b"|end"


def test_rbc_all_nodes_deliver_n4():
    cfg, net, rbcs, proposer = make_rbc_network(4)
    rbcs[proposer].propose(PAYLOAD)
    net.run()
    for node_id, rbc in rbcs.items():
        assert rbc.delivered, f"{node_id} did not deliver"
        assert rbc.value() == PAYLOAD


@pytest.mark.parametrize("seed", [1, 2, 3, 17])
def test_rbc_delivers_under_adversarial_scheduling(seed):
    cfg, net, rbcs, proposer = make_rbc_network(7, seed=seed, auth=True)
    rbcs[proposer].propose(PAYLOAD)
    net.run()
    for rbc in rbcs.values():
        assert rbc.value() == PAYLOAD


def test_rbc_tolerates_f_crashes():
    # n=7, f=2: crash two non-proposer nodes before the proposal
    cfg, net, rbcs, proposer = make_rbc_network(7, seed=5)
    net.crash("node5")
    net.crash("node6")
    rbcs[proposer].propose(PAYLOAD)
    net.run()
    for node_id, rbc in rbcs.items():
        if node_id in ("node5", "node6"):
            continue
        assert rbc.value() == PAYLOAD


def test_rbc_on_deliver_callback_fires_once():
    cfg, net, rbcs, proposer = make_rbc_network(4)
    got = []
    rbcs["node2"].on_deliver = lambda p, v: got.append((p, v))
    rbcs[proposer].propose(PAYLOAD)
    net.run()
    assert got == [(proposer, PAYLOAD)]


def test_rbc_rejects_non_proposer_val():
    """VAL from anyone but the proposer must be ignored
    (reference rbc/rbc.go:56-58 handleValueRequest is proposer-scoped)."""
    cfg, net, rbcs, proposer = make_rbc_network(4)
    impostor = "node3"
    # node3 crafts a full proposal as if it were the proposer
    fake = RBC(
        config=cfg,
        crypto=get_backend(cfg),
        epoch=0,
        proposer=impostor,  # its own instance id...
        owner=impostor,
        member_ids=list(rbcs),
        out=ChannelBroadcaster(net, impostor, list(rbcs)),
    )
    # ...but stamp the payloads with the real proposer's instance by
    # sending through the real network as node3: receivers route it to
    # proposer node0's instance, whose VAL check must reject node3.
    fake.proposer = proposer
    fake.owner = proposer  # bypass the local propose() ownership guard
    fake.propose(b"forged value")
    net.run()
    for rbc in rbcs.values():
        assert not rbc.delivered


def test_rbc_equivocating_proposer_never_splits_delivery():
    """A proposer sending two different values to two halves of the
    roster must not get two values delivered (agreement)."""
    n = 4
    cfg, net, rbcs, proposer = make_rbc_network(n)
    ids = sorted(rbcs)
    crypto = get_backend(cfg)

    # Byzantine proposer: two separate encodings, VALs interleaved
    def forged_vals(value):
        from cleisthenes_tpu.ops.payload import split_payload
        from cleisthenes_tpu.transport.message import RbcPayload

        data = split_payload(value, cfg.data_shards)
        shards = crypto.erasure.encode(data)
        tree = crypto.merkle.build(shards)
        return [
            RbcPayload(
                type=RbcType.VAL,
                proposer=proposer,
                epoch=0,
                root_hash=tree.root,
                branch=tuple(tree.branch(j)),
                shard=shards[j].tobytes(),
                shard_index=j,
            )
            for j in range(n)
        ]

    vals_a = forged_vals(b"value A" * 50)
    vals_b = forged_vals(b"value B" * 50)
    out = ChannelBroadcaster(net, proposer, ids)
    for j, node_id in enumerate(ids):
        out.send_to(node_id, vals_a[j] if j % 2 == 0 else vals_b[j])
    net.run()
    delivered = {r.value() for r in rbcs.values() if r.delivered}
    assert len(delivered) <= 1  # agreement: never two values


def test_rbc_tampered_echo_rejected_by_mac():
    """Bit-flipped wire bytes must be dropped by the authenticator
    (the implemented version of conn.go:134-137's TODO)."""
    cfg, net, rbcs, proposer = make_rbc_network(4, auth=True)

    from cleisthenes_tpu.transport.message import decode_message

    tampered = []

    def flip_echo(sender, receiver, wire):
        if (
            sender == "node1"
            and decode_message(wire).payload.type == RbcType.ECHO
        ):
            tampered.append(1)
            return wire[:-1] + bytes([wire[-1] ^ 0xFF])
        return wire

    net.fault_filter = flip_echo
    rbcs[proposer].propose(PAYLOAD)
    net.run()
    assert tampered  # the filter actually hit ECHO frames
    # node1's tampered ECHOs are MAC-rejected, everyone else suffices
    for rbc in rbcs.values():
        assert rbc.value() == PAYLOAD
    assert all(
        ep.rejected > 0 for nid, ep in net._endpoints.items() if nid != "node1"
    )


def test_rbc_corrupt_shard_fails_branch_check():
    """A corrupted shard with a stale branch must fail Merkle
    verification (docs/RBC-EN.md:35) and never block honest delivery."""
    cfg, net, rbcs, proposer = make_rbc_network(7, seed=9)

    from cleisthenes_tpu.transport.message import (
        decode_message,
        encode_message,
    )

    def corrupt_node1_echo(sender, receiver, wire):
        if sender != "node1":
            return wire
        msg = decode_message(wire)
        p = msg.payload
        if getattr(p, "type", None) == RbcType.ECHO:
            import dataclasses

            bad = p._replace(
                shard=bytes(len(p.shard))  # zeroed shard, same proof
            )
            return encode_message(dataclasses.replace(msg, payload=bad))
        return wire

    net.fault_filter = corrupt_node1_echo
    rbcs[proposer].propose(PAYLOAD)
    net.run()
    for rbc in rbcs.values():
        assert rbc.value() == PAYLOAD


def test_rbc_large_payload_roundtrip():
    payload = hashlib.sha256(b"seed").digest() * 4096  # 128 KiB
    cfg, net, rbcs, proposer = make_rbc_network(4)
    rbcs[proposer].propose(payload)
    net.run()
    for rbc in rbcs.values():
        assert rbc.value() == payload


def test_rbc_unverified_echo_cannot_poison_shard_length():
    """ADVICE.md round-2 high finding: a Byzantine member racing one
    junk ECHO (honest root, wrong-length shard, garbage branch) ahead
    of the honest traffic must not poison the expected shard length —
    pre-fix this wedged the victim forever (every honest ECHO and even
    the VAL failed the length precheck)."""
    from cleisthenes_tpu.ops.payload import split_payload
    from cleisthenes_tpu.transport.message import RbcPayload

    cfg, net, rbcs, proposer = make_rbc_network(4)
    crypto = rbcs[proposer].crypto

    # compute the honest root the proposer will use
    data = split_payload(PAYLOAD, cfg.data_shards)
    shards = crypto.erasure.encode(data)
    tree = crypto.merkle.build(shards)
    honest_len = shards.shape[1]
    depth = tree.depth

    junk = RbcPayload(
        type=RbcType.ECHO,
        proposer=proposer,
        epoch=0,
        root_hash=tree.root,
        branch=tuple(bytes(32) for _ in range(depth)),
        shard=b"\x5a" * (honest_len + 7),  # wrong length
        shard_index=0,
    )
    # attacker's ECHO lands at every honest node FIRST
    for victim in rbcs.values():
        victim.handle_message("node1", junk)

    rbcs[proposer].propose(PAYLOAD)
    net.run()
    for node_id, rbc in rbcs.items():
        assert rbc.value() == PAYLOAD, f"{node_id} wedged by poisoned len"
