"""Two-frontier commit split tests (ISSUE 8, Config.order_then_settle).

Covers the acceptance matrix:

- equivalence: the split arm's SETTLED plaintext log is byte-identical
  to the coupled arm's committed log for the same seed, on the channel
  transport and over real gRPC;
- crash/restart over the ordered-ahead window: a WAL torn between
  ``COrd`` and ``CLOG`` restarts into the settler and recovers with no
  loss, no duplicate and NO re-ordering — via the re-issued dec-share
  exchange when the whole roster tore, via CLOG catch-up when peers
  settled first;
- backpressure: the ordered frontier never runs more than
  ``decrypt_lag_max`` epochs past settlement, and progress still
  completes at the tightest bound;
- ordered CATCHUP: ``COrd`` bodies serve/adopt on f+1 byte-identical
  quorums, advancing a laggard's ordered frontier into a settle-only
  state;
- the settle-stall SLO watchdog and the wire codec for the new
  CatchupOrd payload (TLV + reference-pb framing).
"""

from __future__ import annotations

import hashlib
import os
import struct
import threading
import time

import pytest

from cleisthenes_tpu.config import Config
from cleisthenes_tpu.core.ledger import (
    BatchLog,
    decode_ordered_body,
    encode_batch_body,
    encode_ordered_body,
)
from cleisthenes_tpu.protocol.cluster import SimulatedCluster
from cleisthenes_tpu.protocol.honeybadger import HoneyBadger, setup_keys
from cleisthenes_tpu.transport.broadcast import ChannelBroadcaster
from cleisthenes_tpu.transport.channel import ChannelNetwork


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------


def _ledger_digest(cluster: SimulatedCluster) -> str:
    h = hashlib.sha256()
    for nid in cluster.ids:
        for epoch, batch in enumerate(
            cluster.nodes[nid].committed_batches
        ):
            h.update(encode_batch_body(epoch, batch))
    return h.hexdigest()


def _run_cluster(order_then_settle: bool, txs: int = 48) -> tuple:
    cluster = SimulatedCluster(
        config=Config(
            n=4,
            batch_size=16,
            seed=5,
            order_then_settle=order_then_settle,
        ),
        seed=5,
        key_seed=3,
    )
    for i in range(txs):
        cluster.submit(b"os-tx-%04d" % i)
    cluster.run_epochs()
    depth = cluster.assert_agreement()
    return _ledger_digest(cluster), depth, cluster


def _tear_last_clog(path: str) -> None:
    """Drop the newest CLOG record from a WAL, leaving its epoch's
    COrd in place — the crash-between-order-and-settle window."""
    data = open(path, "rb").read()
    recs = []
    off = 0
    while off + 8 <= len(data):
        (ln,) = struct.unpack_from(">I", data, off + 4)
        end = off + 8 + ln + 4
        recs.append((data[off : off + 4], data[off:end]))
        off = end
    for i in range(len(recs) - 1, -1, -1):
        if recs[i][0] == b"CLOG":
            del recs[i]
            break
    else:
        raise AssertionError(f"no CLOG record in {path}")
    with open(path, "wb") as fh:
        fh.write(b"".join(rec for _, rec in recs))


def _build_wal_cluster(cfg, ids, keys, logdir, net):
    nodes = {}
    for nid in ids:
        nodes[nid] = HoneyBadger(
            config=cfg,
            node_id=nid,
            member_ids=ids,
            keys=keys[nid],
            out=ChannelBroadcaster(net, nid, ids),
            batch_log=BatchLog(os.path.join(logdir, nid + ".log")),
        )
        net.join(nid, nodes[nid], None)
    return nodes


# ---------------------------------------------------------------------------
# equivalence: split vs coupled commit identical plaintext
# ---------------------------------------------------------------------------


def test_split_vs_coupled_identical_settled_ledgers_channel():
    split, split_depth, c1 = _run_cluster(order_then_settle=True)
    coupled, coupled_depth, c2 = _run_cluster(order_then_settle=False)
    assert split_depth >= 2 and split_depth == coupled_depth
    assert split == coupled, (
        "two-frontier settled log diverged from the coupled arm"
    )
    n0 = c1.nodes[c1.ids[0]]
    # the split actually ran: every settled epoch was ordered first,
    # with a durable canonical COrd body
    assert n0.metrics.epochs_ordered.value == len(n0.committed_batches)
    for e in range(split_depth):
        body = n0.ordered_record(e)
        assert body is not None
        oe, output = decode_ordered_body(body)
        assert oe == e
        assert set(n0.committed_batches[e].contributions) <= set(output)
    # the coupled arm never ordered
    m2 = c2.nodes[c2.ids[0]].metrics
    assert m2.epochs_ordered.value == 0


def test_ordered_logs_byte_identical_across_nodes():
    _, depth, cluster = _run_cluster(order_then_settle=True)
    for e in range(depth):
        bodies = {
            cluster.nodes[nid].ordered_record(e) for nid in cluster.ids
        }
        assert len(bodies) == 1 and None not in bodies, (
            f"ordered logs fork at epoch {e}"
        )


def test_split_vs_coupled_identical_epoch0_grpc():
    """Same roster, same submissions, real sockets: the split and
    coupled arms commit byte-identical epoch-0 batches."""
    from cleisthenes_tpu.transport.host import ValidatorHost

    def epoch0(order_then_settle: bool) -> list:
        n = 4
        cfg = Config(
            n=n,
            batch_size=8,
            seed=77,
            order_then_settle=order_then_settle,
        )
        ids = [f"node{i}" for i in range(n)]
        keys = setup_keys(cfg, ids, seed=55)
        hosts = {i: ValidatorHost(cfg, i, ids, keys[i]) for i in ids}
        try:
            addrs = {i: h.listen() for i, h in hosts.items()}
            threads = [
                threading.Thread(target=h.connect, args=(addrs,))
                for h in hosts.values()
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=15)
            for i in range(8):
                hosts[ids[i % n]].submit(b"grpc-os-%02d" % i)
            for h in hosts.values():
                h.propose()
            first = {
                i: h.wait_commit(timeout=60) for i, h in hosts.items()
            }
            assert {e for e, _ in first.values()} == {0}
            return [encode_batch_body(0, b) for _, b in first.values()]
        finally:
            for h in hosts.values():
                h.stop()

    split = epoch0(True)
    coupled = epoch0(False)
    assert all(b == split[0] for b in split)
    assert all(b == coupled[0] for b in coupled)
    assert split[0] == coupled[0]


# ---------------------------------------------------------------------------
# crash/restart across the ordered-ahead window (channel transport)
# ---------------------------------------------------------------------------


def test_whole_roster_crash_between_order_and_settle(tmp_path):
    """Every WAL torn between COrd and CLOG: the restarted roster
    re-enters the epoch into its settlers, re-issues its own dec
    shares at the first idle boundary, and settles the SAME batch —
    no loss, no duplicate, no consensus re-run."""
    logdir = str(tmp_path / "wals")
    os.makedirs(logdir)
    cfg = Config(n=4, batch_size=8, seed=11)
    ids = [f"node{i}" for i in range(4)]
    keys = setup_keys(cfg, ids, seed=66)

    net = ChannelNetwork(seed=11)
    nodes = _build_wal_cluster(cfg, ids, keys, logdir, net)
    for i in range(16):
        nodes[ids[i % 4]].add_transaction(b"tear-%03d" % i)
    for _ in range(6):
        for hb in nodes.values():
            hb.start_epoch()
        net.run()
        if all(hb.pending_tx_count() == 0 for hb in nodes.values()):
            break
    committed = [
        b.tx_list() for b in nodes[ids[0]].committed_batches
    ]
    assert len(committed) >= 2
    for hb in nodes.values():
        hb.batch_log.close()
    for nid in ids:
        _tear_last_clog(os.path.join(logdir, nid + ".log"))

    net2 = ChannelNetwork(seed=12)
    nodes2 = _build_wal_cluster(cfg, ids, keys, logdir, net2)
    for hb in nodes2.values():
        # ordered-ahead: the torn epoch re-entered as a settle-only
        # state, the ordered frontier is PAST it, settlement is not
        assert hb.epoch == len(committed)
        assert hb.settled_epoch == len(committed) - 1
        es = hb._epochs[len(committed) - 1]
        assert es.ordered and es.acs is None and not es.shares_issued
    net2.run()  # idle phase drives the settlers: shares re-issue
    for hb in nodes2.values():
        assert hb.settled_epoch == len(committed)
        got = [b.tx_list() for b in hb.committed_batches]
        assert got == committed  # same batch, once, in order
        hb.batch_log.close()


def test_single_node_torn_window_recovers_via_clog_catchup(tmp_path):
    """Only one node tore between COrd and CLOG; its peers settled and
    GC'd the epoch, so its own re-issued share can never reach the
    threshold — the plaintext must arrive via CLOG catch-up, settling
    the ordered-ahead epoch without re-ordering."""
    logdir = str(tmp_path / "wals")
    os.makedirs(logdir)
    cfg = Config(n=4, batch_size=8, seed=11)
    ids = [f"node{i}" for i in range(4)]
    keys = setup_keys(cfg, ids, seed=66)

    net = ChannelNetwork(seed=11)
    nodes = _build_wal_cluster(cfg, ids, keys, logdir, net)
    for i in range(16):
        nodes[ids[i % 4]].add_transaction(b"solo-%03d" % i)
    for _ in range(6):
        for hb in nodes.values():
            hb.start_epoch()
        net.run()
        if all(hb.pending_tx_count() == 0 for hb in nodes.values()):
            break
    committed = [b.tx_list() for b in nodes[ids[0]].committed_batches]
    for hb in nodes.values():
        hb.batch_log.close()
    _tear_last_clog(os.path.join(logdir, "node0.log"))

    net2 = ChannelNetwork(seed=12)
    nodes2 = _build_wal_cluster(cfg, ids, keys, logdir, net2)
    n0 = nodes2["node0"]
    assert n0.settled_epoch == len(committed) - 1
    assert n0.epoch == len(committed)
    n0.request_catchup()
    net2.run()
    assert n0.settled_epoch == len(committed)
    assert [b.tx_list() for b in n0.committed_batches] == committed
    for hb in nodes2.values():
        hb.batch_log.close()


@pytest.mark.faults
def test_grpc_torn_window_restart_settles_from_wal(tmp_path):
    """The ordered-ahead crash window over real sockets: every host
    keeps a WAL, epoch 0 commits, the roster stops, ONE WAL is torn
    between COrd and CLOG.  The restarted victim comes back ordered-
    ahead (epoch 1, settled 0), ``connect`` fires catch-up from its
    SETTLED frontier, and the epoch settles from the peers' CLOG
    bodies — the same batch, once, with no consensus re-run."""
    from cleisthenes_tpu.transport.host import ValidatorHost

    n = 4
    cfg = Config(n=n, batch_size=8, seed=21)
    ids = [f"node{i}" for i in range(n)]
    keys = setup_keys(cfg, ids, seed=42)
    wals = {i: str(tmp_path / (i + ".log")) for i in ids}

    def boot():
        hosts = {
            i: ValidatorHost(
                cfg, i, ids, keys[i], batch_log_path=wals[i]
            )
            for i in ids
        }
        addrs = {i: h.listen() for i, h in hosts.items()}
        threads = [
            threading.Thread(target=h.connect, args=(addrs,))
            for h in hosts.values()
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=15)
        return hosts

    hosts = boot()
    try:
        for i in range(8):
            hosts[ids[i % n]].submit(b"grpc-tear-%02d" % i)
        for h in hosts.values():
            h.propose()
        commits = {i: h.wait_commit(timeout=60) for i, h in hosts.items()}
        assert {e for e, _ in commits.values()} == {0}
        want = commits[ids[0]][1].tx_list()
    finally:
        for h in hosts.values():
            h.stop()
    _tear_last_clog(wals["node0"])

    # ordered-ahead out of WAL replay: the COrd survived the tear.
    # Asserted on a standalone construction BEFORE any connect —
    # catch-up fires inside connect() and can settle the epoch within
    # milliseconds of the dial completing, so asserting after boot()
    # races the very recovery this test exists to prove.
    probe = ValidatorHost(cfg, "node0", ids, keys["node0"],
                          batch_log_path=wals["node0"])
    assert probe.node.epoch == 1
    assert probe.node.settled_epoch == 0
    probe.stop()

    hosts2 = boot()
    try:
        victim = hosts2["node0"]
        assert victim.node.epoch == 1
        deadline = time.monotonic() + 30
        got = None
        while time.monotonic() < deadline:
            got = victim.committed_batches()
            if len(got) >= 1:
                break
            time.sleep(0.25)
        assert got is not None and len(got) == 1
        assert got[0].tx_list() == want
        assert victim.node.settled_epoch == 1
    finally:
        for h in hosts2.values():
            h.stop()


# ---------------------------------------------------------------------------
# backpressure
# ---------------------------------------------------------------------------


def test_backpressure_bounds_ordered_frontier():
    """decrypt_lag_max=1 — the tightest legal bound: ordering may run
    at most ONE epoch past settlement at every quiescence point, and
    the run still drains completely."""
    cfg = Config(n=4, batch_size=16, seed=9, decrypt_lag_max=1)
    cluster = SimulatedCluster(config=cfg, seed=9, key_seed=3)
    for i in range(64):
        cluster.submit(b"bp-tx-%04d" % i)

    def check_bound(_r: int) -> None:
        for hb in cluster.nodes.values():
            lag = hb.epoch - hb.settled_epoch
            assert 0 <= lag <= 1, (hb.node_id, hb.epoch, hb.settled_epoch)

    cluster.run_epochs(on_quiescence=check_bound)
    depth = cluster.assert_agreement()
    assert depth >= 3
    n0 = cluster.nodes[cluster.ids[0]]
    assert n0.epoch == n0.settled_epoch  # fully settled at the end


def test_decrypt_lag_max_validation():
    with pytest.raises(ValueError):
        Config(n=4, decrypt_lag_max=0)


# ---------------------------------------------------------------------------
# ordered CATCHUP (COrd serve/adopt)
# ---------------------------------------------------------------------------


def test_ordered_catchup_adopts_on_quorum(tmp_path):
    """f+1 byte-identical COrd bodies advance a laggard's ordered
    frontier into a settle-only state with a durable COrd record; a
    sub-quorum (or a forged body) adopts nothing."""
    from cleisthenes_tpu.transport.message import CatchupOrdPayload

    cfg = Config(n=4, batch_size=8, seed=21)
    ids = [f"node{i}" for i in range(4)]
    keys = setup_keys(cfg, ids, seed=44)
    # a real agreed output -> canonical COrd body for epoch 0
    output = {ids[0]: b"ct-a", ids[1]: b"ct-b"}
    body = encode_ordered_body(0, output)

    net = ChannelNetwork()
    hb = HoneyBadger(
        config=cfg,
        node_id=ids[0],
        member_ids=ids,
        keys=keys[ids[0]],
        out=ChannelBroadcaster(net, ids[0], ids),
        batch_log=BatchLog(str(tmp_path / "lag.log")),
    )
    net.join(ids[0], hb, None)
    # one vote: below the f+1=2 quorum — nothing adopts
    hb._handle_catchup_ord(ids[1], CatchupOrdPayload(epoch=0, body=body))
    assert hb.epoch == 0 and hb.ordered_record(0) is None
    # a second, FORGED body from another peer must not help the quorum
    forged = encode_ordered_body(0, {ids[0]: b"ct-x"})
    hb._handle_catchup_ord(
        ids[2], CatchupOrdPayload(epoch=0, body=forged)
    )
    assert hb.epoch == 0
    # the honest second vote completes the quorum
    hb._handle_catchup_ord(ids[2], CatchupOrdPayload(epoch=0, body=body))
    assert hb.epoch == 1  # ordered frontier advanced
    assert hb.settled_epoch == 0  # nothing settled yet
    assert hb.ordered_record(0) == body
    es = hb._epochs[0]
    assert es.ordered and es.acs is None and es.output == output
    # durable: a restart replays the adopted ordering into the settler
    hb.batch_log.close()
    log2 = BatchLog(str(tmp_path / "lag.log"))
    assert log2.last_ordered_epoch == 0
    replayed = list(log2.replay_ordered())
    assert replayed == [(0, body)]
    log2.close()


def test_settlement_release_redrives_parked_ordered_catchup(tmp_path):
    """A laggard parked at decrypt_lag_max with a full f+1 COrd tally
    buffered must resume adopting the moment settlement advances (here
    via CLOG catch-up) — backpressure release re-drives BOTH ordering
    paths, the local buffered-ACS one and the catch-up tally one, or
    the node wedges behind the roster in a quiescent cluster."""
    from cleisthenes_tpu.core.batch import Batch
    from cleisthenes_tpu.core.ledger import encode_batch_body
    from cleisthenes_tpu.transport.message import (
        CatchupOrdPayload,
        CatchupRespPayload,
    )

    cfg = Config(n=4, batch_size=8, seed=23, decrypt_lag_max=1)
    ids = [f"node{i}" for i in range(4)]
    keys = setup_keys(cfg, ids, seed=55)
    net = ChannelNetwork()
    hb = HoneyBadger(
        config=cfg,
        node_id=ids[0],
        member_ids=ids,
        keys=keys[ids[0]],
        out=ChannelBroadcaster(net, ids[0], ids),
        batch_log=BatchLog(str(tmp_path / "lag.log")),
    )
    net.join(ids[0], hb, None)

    body0 = encode_ordered_body(0, {ids[1]: b"ct-0"})
    body1 = encode_ordered_body(1, {ids[1]: b"ct-1"})
    # f+1 votes adopt epoch 0's ordering; the ordered frontier now
    # leads settlement by decrypt_lag_max=1
    for s in (ids[1], ids[2]):
        hb._handle_catchup_ord(
            s, CatchupOrdPayload(epoch=0, body=body0)
        )
    assert hb.epoch == 1
    # epoch 1's full quorum arrives but parks at the bound
    for s in (ids[1], ids[2]):
        hb._handle_catchup_ord(
            s, CatchupOrdPayload(epoch=1, body=body1)
        )
    assert hb.epoch == 1, "ordering must park at decrypt_lag_max"

    # peers settle epoch 0 for us: f+1 identical CLOG bodies
    clog0 = encode_batch_body(0, Batch({ids[1]: [b"tx-a"]}))
    for s in (ids[1], ids[2]):
        hb._handle_catchup_resp(
            s, CatchupRespPayload(epoch=0, body=clog0)
        )
    assert hb.settled_epoch == 1  # the settled frontier: epoch 0 done
    # ...and the parked tally must adopt without any further traffic
    assert hb.epoch == 2, "parked COrd tally wedged after settlement"
    assert hb.ordered_record(1) == body1
    hb.batch_log.close()


def test_catchup_serves_cord_for_unsettled_epochs(tmp_path):
    """A server that ordered-but-not-settled an epoch answers a
    CatchupReq with the COrd body for it (it has no plaintext yet)."""
    from cleisthenes_tpu.transport.message import (
        CatchupOrdPayload,
        CatchupReqPayload,
    )

    logdir = str(tmp_path / "wals")
    os.makedirs(logdir)
    cfg = Config(n=4, batch_size=8, seed=11)
    ids = [f"node{i}" for i in range(4)]
    keys = setup_keys(cfg, ids, seed=66)
    net = ChannelNetwork(seed=11)
    nodes = _build_wal_cluster(cfg, ids, keys, logdir, net)
    for i in range(16):
        nodes[ids[i % 4]].add_transaction(b"serve-%03d" % i)
    for _ in range(6):
        for hb in nodes.values():
            hb.start_epoch()
        net.run()
        if all(hb.pending_tx_count() == 0 for hb in nodes.values()):
            break
    depth = len(nodes[ids[0]].committed_batches)
    for hb in nodes.values():
        hb.batch_log.close()
    _tear_last_clog(os.path.join(logdir, "node0.log"))

    # restart node0 alone: ordered-ahead of its settled frontier
    net2 = ChannelNetwork(seed=13)
    nodes2 = _build_wal_cluster(cfg, ids, keys, logdir, net2)
    n0 = nodes2["node0"]
    assert n0.settled_epoch == depth - 1 and n0.epoch == depth

    served = []
    orig = n0.out.send_to

    def spy(member_id, payload):
        served.append(payload)
        orig(member_id, payload)

    n0.out.send_to = spy
    n0._handle_catchup_req(
        "node1", CatchupReqPayload(from_epoch=depth - 1)
    )
    ords = [p for p in served if isinstance(p, CatchupOrdPayload)]
    assert [p.epoch for p in ords] == [depth - 1]
    assert ords[0].body == n0.ordered_record(depth - 1)
    for hb in nodes2.values():
        hb.batch_log.close()


def test_settled_plaintext_pushed_after_cord_only_serve(tmp_path):
    """A server that answered a catch-up window with COrd bodies only
    (epochs ordered but unsettled) owes the requester those epochs'
    plaintext: the CLOG bodies push as the server settles.  Without
    the push the requester's repeat budget is spent, budgets re-arm
    only on ordering advances, and a quiescent cluster wedges."""
    from cleisthenes_tpu.core.batch import Batch
    from cleisthenes_tpu.core.ledger import encode_batch_body
    from cleisthenes_tpu.transport.message import (
        CatchupOrdPayload,
        CatchupReqPayload,
        CatchupRespPayload,
    )

    cfg = Config(n=4, batch_size=8, seed=31)
    ids = [f"node{i}" for i in range(4)]
    keys = setup_keys(cfg, ids, seed=77)
    net = ChannelNetwork()
    hb = HoneyBadger(
        config=cfg,
        node_id=ids[0],
        member_ids=ids,
        keys=keys[ids[0]],
        out=ChannelBroadcaster(net, ids[0], ids),
        batch_log=BatchLog(str(tmp_path / "srv.log")),
    )
    net.join(ids[0], hb, None)
    # ordered-ahead server state: adopt orderings for epochs 0 and 1
    for e in (0, 1):
        body = encode_ordered_body(e, {ids[1]: b"ct-%d" % e})
        for s in (ids[1], ids[2]):
            hb._handle_catchup_ord(
                s, CatchupOrdPayload(epoch=e, body=body)
            )
    assert hb.epoch == 2 and hb.settled_epoch == 0

    sent = []
    orig = hb.out.send_to
    hb.out.send_to = lambda m, p: (sent.append((m, p)), orig(m, p))

    # node3 asks; only COrd bodies are servable (no plaintext yet)...
    hb._handle_catchup_req(ids[3], CatchupReqPayload(from_epoch=0))
    assert [
        p.epoch for _m, p in sent if isinstance(p, CatchupOrdPayload)
    ] == [0, 1]
    assert not [
        p for _m, p in sent if isinstance(p, CatchupRespPayload)
    ]
    # ...and the requester burns its repeat budget on retries
    for _ in range(3):
        hb._handle_catchup_req(ids[3], CatchupReqPayload(from_epoch=0))
    del sent[:]

    # peers settle epoch 0 for us (f+1 CLOG bodies): the owed epoch-0
    # plaintext must push to node3 with NO further request from it
    clog0 = encode_batch_body(0, Batch({ids[1]: [b"tx-0"]}))
    for s in (ids[1], ids[2]):
        hb._handle_catchup_resp(
            s, CatchupRespPayload(epoch=0, body=clog0)
        )
    got = [
        p
        for m, p in sent
        if m == ids[3] and isinstance(p, CatchupRespPayload)
    ]
    assert [p.epoch for p in got] == [0]
    del sent[:]
    clog1 = encode_batch_body(1, Batch({ids[1]: [b"tx-1"]}))
    for s in (ids[1], ids[2]):
        hb._handle_catchup_resp(
            s, CatchupRespPayload(epoch=1, body=clog1)
        )
    got = [
        p
        for m, p in sent
        if m == ids[3] and isinstance(p, CatchupRespPayload)
    ]
    assert [p.epoch for p in got] == [1]
    # the debt is limit-bounded: fully repaid, no standing stream
    assert not hb._catchup_plain_owed
    hb.batch_log.close()


# ---------------------------------------------------------------------------
# settle-stall SLO watchdog
# ---------------------------------------------------------------------------


def test_settle_stall_watchdog_flips_degraded():
    from cleisthenes_tpu.utils.metrics import Metrics
    from cleisthenes_tpu.utils.watchdog import (
        DEGRADED,
        SETTLE_STALL,
        UP,
        SloWatchdog,
    )

    m = Metrics()
    frontiers = {"ordered": 0, "settled": 0}
    m.set_frontiers(lambda: (frontiers["ordered"], frontiers["settled"]))
    wd = SloWatchdog(
        metrics=m, pending_fn=lambda: 0, decrypt_lag_budget=4
    )
    assert wd.check(now=m._t0 + 1.0) == UP
    frontiers["ordered"] = 4  # lag == budget: ordering parked...
    # ...but settlement is still streaming (a settle just landed):
    # steady-state backpressure of a decrypt-bound node must NOT page
    # — the alert means settlement STOPPED trailing, not "busy"
    m.epoch_committed(0, 1)
    last = m._last_commit_t
    assert wd.check(now=last + 1.0) == UP
    # parked at the bound with no settle for > the stall budget
    assert wd.check(now=last + 1000.0) == DEGRADED
    block = wd.alerts_block()[SETTLE_STALL]
    assert block["active"] and block["count"] == 1
    assert "backpressure" in block["reason"]
    frontiers["settled"] = 2  # settler caught up below the budget
    assert wd.check(now=last + 1001.0) == UP
    assert not wd.alerts_block()[SETTLE_STALL]["active"]
    assert wd.alerts_block()[SETTLE_STALL]["count"] == 1  # edge-counted


# ---------------------------------------------------------------------------
# wire codec: CatchupOrdPayload (TLV + reference-pb extension slot)
# ---------------------------------------------------------------------------


def test_catchup_ord_payload_roundtrips():
    from cleisthenes_tpu.transport.message import (
        CatchupOrdPayload,
        Message,
        decode_frame,
        encode_message,
    )
    from cleisthenes_tpu.transport.pb_adapter import (
        decode_pb_message,
        encode_pb_message,
    )

    body = encode_ordered_body(7, {"a": b"ct-1", "b": b"ct-2"})
    msg = Message(
        sender_id="node1",
        timestamp=55.25,
        payload=CatchupOrdPayload(epoch=7, body=body),
    )
    decoded, _prefix = decode_frame(encode_message(msg))
    assert decoded.payload == msg.payload
    pb = encode_pb_message(msg)
    back = decode_pb_message(pb)
    assert back.payload == msg.payload


def test_ordered_wal_record_roundtrip_and_torn_tail(tmp_path):
    path = str(tmp_path / "cord.log")
    log = BatchLog(path)
    out0 = {"a": b"ct-1"}
    out1 = {"a": b"ct-2", "b": b"ct-3"}
    body0 = log.append_ordered(0, out0)
    body1 = log.append_ordered(1, out1)
    assert decode_ordered_body(body0) == (0, out0)
    log.close()

    log2 = BatchLog(path)
    assert log2.last_ordered_epoch == 1
    assert log2.last_epoch is None  # no plaintext records at all
    assert list(log2.replay_ordered()) == [(0, body0), (1, body1)]
    log2.close()

    # torn mid-append COrd record: truncated away on open, like CLOG
    with open(path, "ab") as fh:
        from cleisthenes_tpu.core.ledger import (
            _frame_record,
            _MAGIC_ORD,
        )

        rec = _frame_record(_MAGIC_ORD, encode_ordered_body(2, out0))
        fh.write(rec[: len(rec) // 2])
    log3 = BatchLog(path)
    assert log3.last_ordered_epoch == 1
    log3.close()
