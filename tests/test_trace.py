"""The observability plane: flight recorder + tracetool (ISSUE 3).

Covers the recorder's contract (bounded ring keeps newest + counts
drops; the DISABLED path allocates nothing), the Chrome-trace
rendering and tracetool's schema gate, the per-epoch critical-path
attribution (>= 95% of each epoch's wall time lands on named stages —
the PR's acceptance criterion), and — extending
test_hashseed_determinism's pattern — that two subprocess runs of the
same seeded cluster under different PYTHONHASHSEED values record the
IDENTICAL event sequence (timestamps differ; sequence must not)."""

from __future__ import annotations

import copy
import os
import pathlib
import subprocess
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from cleisthenes_tpu.config import Config  # noqa: E402
from cleisthenes_tpu.utils.trace import (  # noqa: E402
    CATEGORIES,
    TraceRecorder,
    maybe_recorder,
    to_chrome,
)
from tools import tracetool  # noqa: E402


# ---------------------------------------------------------------------------
# recorder unit behavior
# ---------------------------------------------------------------------------


def test_ring_overflow_keeps_newest_and_counts_drops():
    tr = TraceRecorder("n0", cap=8)
    for i in range(20):
        tr.instant("rbc", f"ev{i:02d}")
    events = tr.events()
    assert len(events) == 8
    # newest events won; oldest were evicted
    assert [e[4] for e in events] == [f"ev{i:02d}" for i in range(12, 20)]
    # sequence numbers survive eviction (ordering ground truth)
    assert [e[0] for e in events] == list(range(13, 21))
    stats = tr.stats()
    assert stats == {
        "events_recorded": 20,
        "events_dropped": 12,
        "high_water": 8,
    }


def test_span_nesting_and_chrome_rendering():
    tr = TraceRecorder("n0")
    tr.instant("epoch", "open", epoch=0)
    with tr.span("rbc", "propose", epoch=0):
        with tr.span("hub", "flush"):
            pass
    tr.instant("epoch", "commit", epoch=0, txs=3)
    events = tr.events()
    assert len(events) == 4
    # spans record at END: the inner flush carries the smaller seq,
    # and both have non-None durations
    names = [(e[3], e[4], e[2] is None) for e in events]
    assert names == [
        ("epoch", "open", True),
        ("hub", "flush", False),
        ("rbc", "propose", False),
        ("epoch", "commit", True),
    ]
    doc = to_chrome({"n0": events})
    evs = doc["traceEvents"]
    assert evs[0]["ph"] == "M" and evs[0]["args"]["name"] == "n0"
    phases = [e["ph"] for e in evs[1:]]
    assert phases == ["i", "X", "X", "i"]
    # timestamps normalized to the earliest event, in microseconds
    assert min(e["ts"] for e in evs[1:]) == 0.0
    assert tracetool.validate(doc) == []


def test_unknown_category_rejected_by_validator():
    tr = TraceRecorder("n0")
    tr.instant("epoch", "open", epoch=0)
    doc = to_chrome({"n0": tr.events()})
    bad = copy.deepcopy(doc)
    for ev in bad["traceEvents"]:
        if ev["ph"] != "M":
            ev["cat"] = "bogus"
    errors = tracetool.validate(bad)
    assert errors and "bogus" in errors[0]


def test_validator_catches_non_monotone_seq():
    tr = TraceRecorder("n0")
    tr.instant("epoch", "open", epoch=0)
    tr.instant("epoch", "commit", epoch=0, txs=0)
    doc = to_chrome({"n0": tr.events()})
    assert tracetool.validate(doc) == []
    bad = copy.deepcopy(doc)
    analysis = [e for e in bad["traceEvents"] if e["ph"] != "M"]
    analysis[1]["args"]["seq"] = analysis[0]["args"]["seq"]  # replay
    errors = tracetool.validate(bad)
    assert errors and "strictly increasing" in errors[0]


def test_disabled_path_allocates_nothing():
    """Config.trace=False constructs NO recorder; the instrumentation
    guard (one load + identity check) must not allocate."""
    import tracemalloc

    assert maybe_recorder(Config(n=4), "n0") is None  # off by default
    assert maybe_recorder(Config(n=4, trace=True), "n0") is not None

    tr = None
    tracemalloc.start()
    try:
        tracemalloc.reset_peak()
        base = tracemalloc.get_traced_memory()[1]
        for _ in range(10_000):
            if tr is not None:  # the site pattern, disabled
                tr.instant("rbc", "x")
        peak = tracemalloc.get_traced_memory()[1]
    finally:
        tracemalloc.stop()
    # the loop machinery itself is the only allowance; the guard must
    # add nothing per iteration (10k iterations, < 512B total)
    assert peak - base < 512


def test_disabled_cluster_has_no_recorders():
    from cleisthenes_tpu.protocol.cluster import SimulatedCluster

    cluster = SimulatedCluster(
        config=Config(n=4, batch_size=8, seed=5), seed=5, key_seed=1
    )
    assert all(hb.trace is None for hb in cluster.nodes.values())
    assert cluster.hub_trace is None
    assert cluster.trace_events() == {}
    nid = cluster.ids[0]
    assert "trace" not in cluster.nodes[nid].metrics.snapshot()


# ---------------------------------------------------------------------------
# traced cluster end to end: artifact, attribution, metrics block
# ---------------------------------------------------------------------------


def _traced_cluster_doc(tmp_path):
    from cleisthenes_tpu.protocol.cluster import SimulatedCluster

    cluster = SimulatedCluster(
        config=Config(n=4, batch_size=8, seed=7, trace=True),
        seed=7,
        key_seed=1,
    )
    for i in range(24):
        cluster.submit(b"tx-%04d" % i)
    cluster.run_epochs()
    cluster.assert_agreement()
    path = tmp_path / "trace.json"
    cluster.write_trace(str(path))
    return cluster, tracetool.load(str(path))


def test_traced_cluster_validates_and_attributes(tmp_path):
    cluster, doc = _traced_cluster_doc(tmp_path)
    assert tracetool.validate(doc) == []
    # per-node tracks: all four nodes plus the shared hub
    names = set(tracetool.track_names(doc).values())
    assert names == set(cluster.ids) | {"hub"}
    windows = tracetool.epoch_windows(doc)
    assert len(windows) >= 2
    for t_open, t_commit in windows.values():
        shares, chain = tracetool.attribute_epoch(doc, t_open, t_commit)
        wall = t_commit - t_open
        covered = sum(shares.values())
        # the acceptance criterion: >= 95% of each epoch's wall time
        # attributed to named stages
        assert covered >= 0.95 * wall
        assert set(shares) <= CATEGORIES
        assert chain and max(c[0] for c in chain) <= wall
    fractions = tracetool.stage_shares(doc)
    assert fractions and abs(sum(fractions.values()) - 1.0) < 0.01
    # the epoch anatomy is visible: the crypto and delivery planes
    # both show up as named stages
    assert "rbc" in fractions and "tpke" in fractions
    # metrics snapshot carries the recorder stats block
    snap = cluster.nodes[cluster.ids[0]].metrics.snapshot()
    assert snap["trace"]["events_recorded"] > 0
    assert snap["trace"]["events_dropped"] == 0
    assert 0 < snap["trace"]["high_water"] <= Config(n=4).trace_buffer
    # the report renders without error and names every epoch (windows
    # key by (lane, epoch); single-lane artifacts are all lane 0)
    text = tracetool.report(doc)
    for lane, epoch in windows:
        assert lane == 0
        assert f"epoch {epoch}:" in text
    summary = tracetool.summarize(doc)
    assert summary["hub"]["flushes"] > 0
    assert summary["events_by_category"].get("transport", 0) > 0


def test_wal_appends_record_ledger_spans(tmp_path):
    from cleisthenes_tpu.core.batch import Batch
    from cleisthenes_tpu.core.ledger import BatchLog

    log = BatchLog(str(tmp_path / "wal.log"))
    log.trace = TraceRecorder("n0")
    log.append(0, Batch(contributions={"a": [b"tx"]}))
    log.append_checkpoint(0, [{b"tx"}])
    log.close()
    events = log.trace.events()
    assert [(e[3], e[4]) for e in events] == [
        ("ledger", "wal_append"),
        ("ledger", "wal_checkpoint"),
    ]
    assert all(e[2] is not None and e[2] >= 0 for e in events)
    assert all(e[5]["epoch"] == 0 and e[5]["bytes"] > 0 for e in events)


# ---------------------------------------------------------------------------
# cross-PYTHONHASHSEED sequence determinism (test_hashseed_determinism
# pattern: the hash seed is fixed at interpreter start, so subprocesses
# are the only honest test)
# ---------------------------------------------------------------------------

_DRIVER = r"""
import hashlib
from cleisthenes_tpu.config import Config
from cleisthenes_tpu.protocol.cluster import SimulatedCluster

cluster = SimulatedCluster(
    config=Config(n=4, batch_size=8, seed=1234, trace=True),
    seed=1234,
    key_seed=1,
)
for i in range(24):
    cluster.submit(b"tx-%04d" % i)
cluster.run_epochs()
depth = cluster.assert_agreement()
h = hashlib.sha256()
n_events = 0
events_by_node = cluster.trace_events()
for node in sorted(events_by_node):
    for seq, ts, dur, cat, name, args in events_by_node[node]:
        # digest everything EXCEPT the observability clock: seq, the
        # instant/span kind, category, name, and the sorted args
        n_events += 1
        h.update(
            repr(
                (node, seq, dur is None, cat, name, sorted(args.items()))
            ).encode()
        )
print("TRACE_DIGEST=%s n=%d depth=%d" % (h.hexdigest(), n_events, depth))
"""


def _run_with_hashseed(hashseed: str) -> str:
    env = dict(os.environ)
    env["PYTHONHASHSEED"] = hashseed
    env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.run(
        [sys.executable, "-c", _DRIVER],
        cwd=REPO,
        env=env,
        capture_output=True,
        text=True,
        timeout=300,
    )
    assert proc.returncode == 0, (
        f"PYTHONHASHSEED={hashseed} traced run failed:\n"
        f"{proc.stdout}\n{proc.stderr}"
    )
    for line in proc.stdout.splitlines():
        if line.startswith("TRACE_DIGEST="):
            return line
    raise AssertionError(f"no digest line in output:\n{proc.stdout}")


def test_trace_sequence_identical_across_hash_seeds():
    a = _run_with_hashseed("1")
    b = _run_with_hashseed("2")
    assert a == b, (
        "seeded traced runs under different PYTHONHASHSEED values "
        f"recorded different event sequences:\n  {a}\n  {b}\n"
        "-> nondeterministic ordering (or args) is leaking into the "
        "flight recorder; only timestamps may differ between replays"
    )
