"""Fee-priority mempool (core/mempool.py), unit level (ISSUE 18).

The admission contract under test: every admit() returns an explicit
verdict (OK / DUPLICATE / REJECTED / RETRY_AFTER — never a silent
drop); under pressure the pool evicts by priority, visibly, and only
when the newcomer strictly outbids the lowest pending entry; dedup
spans the entry's whole lifetime (pending, in flight, settled,
evicted) via the bounded seen-ring; equal-fee ordering is a seeded
pure function of the tx digest, identical across interpreters.
"""

from __future__ import annotations

import pytest

from cleisthenes_tpu.core.mempool import (
    DUPLICATE,
    MAX_TX_BYTES,
    OK,
    REJECTED,
    RETRY_AFTER,
    Mempool,
    tx_digest,
)


class _Queue:
    """Minimal TxQueue stand-in recording drain order."""

    def __init__(self):
        self.items = []

    def push(self, tx):
        self.items.append(tx)


def _fill(pool, fees, client="c0"):
    txs = []
    for i, fee in enumerate(fees):
        tx = b"tx-%04d" % i
        assert pool.admit(tx, client, fee).status == OK
        txs.append(tx)
    return txs


def test_priority_eviction_order():
    """A full pool evicts its LOWEST-priority pending entry — and only
    for a newcomer that strictly outbids it; losers ack RETRY_AFTER."""
    evicted = []
    pool = Mempool(
        capacity=3, seed=7, on_evict=lambda d, c: evicted.append(d)
    )
    txs = _fill(pool, [10, 20, 30])
    # fee 40 outbids the fee-10 floor: admitted, floor evicted
    assert pool.admit(b"rich", "c1", 40).status == OK
    assert evicted == [tx_digest(txs[0])]
    assert pool.stats()["evicted"] == 1
    # fee 5 does NOT outbid the new fee-20 floor: visible RETRY_AFTER
    v = pool.admit(b"poor", "c1", 5)
    assert v.status == RETRY_AFTER
    assert v.retry_after_ms > 0
    assert pool.depth() == 3
    # an evicted tx stays in the seen-ring: resubmit acks DUPLICATE,
    # never a second OK for bytes the client already got an OK for
    assert pool.admit(txs[0], "c0", 99).status == DUPLICATE
    # drain order is fee-descending: 40, 30, 20
    q = _Queue()
    assert pool.drain_into(q, 10) == 3
    assert q.items == [b"rich", txs[2], txs[1]]


def test_equal_fee_order_is_seeded_and_digest_pure():
    """Equal-fee ordering is a pure function of (seed, digest): two
    pools with the same seed drain identically whatever the admission
    order; a different seed reorders the same txs."""
    txs = [b"tie-%04d" % i for i in range(8)]

    def drain_order(seed, order):
        pool = Mempool(capacity=16, seed=seed)
        for tx in order:
            assert pool.admit(tx, f"c{tx[-1]}", 5).status == OK
        q = _Queue()
        pool.drain_into(q, 16)
        return q.items

    a = drain_order(3, txs)
    b = drain_order(3, list(reversed(txs)))
    assert a == b
    assert drain_order(4, txs) != a


def test_backpressure_rejected_and_retry_after():
    """Malformed txs ack REJECTED; per-client and global pressure ack
    RETRY_AFTER carrying the configured backoff hint."""
    pool = Mempool(capacity=8, client_cap=2, retry_after_ms=250, seed=1)
    assert pool.admit(b"", "c0", 1).status == REJECTED
    assert pool.admit(b"x" * (MAX_TX_BYTES + 1), "c0", 1).status == REJECTED
    assert pool.admit(b"neg", "c0", -1).status == REJECTED
    assert pool.stats()["rejected"] == 3
    # per-client cap: the 3rd live tx from one client backs off
    assert pool.admit(b"a", "c0", 1).status == OK
    assert pool.admit(b"b", "c0", 1).status == OK
    v = pool.admit(b"c", "c0", 1)
    assert (v.status, v.retry_after_ms) == (RETRY_AFTER, 250)
    # other clients are unaffected by c0's cap
    assert pool.admit(b"c", "c1", 1).status == OK
    # settling frees the cap slot: c0 can submit fresh bytes again
    q = _Queue()
    pool.drain_into(q, 8)
    pool.mark_settled([b"a"])
    assert pool.admit(b"d", "c0", 1).status == OK


def test_dedup_spans_pending_inflight_and_settled():
    """DUPLICATE acks cover the full lifetime: pending, drained (in
    flight), and settled — the settle-time seen-ring keeps late
    resubmits idempotent after the entry's memory is freed."""
    pool = Mempool(capacity=8, seed=2)
    assert pool.admit(b"tx", "c0", 3).status == OK
    assert pool.admit(b"tx", "c9", 9).status == DUPLICATE  # pending
    q = _Queue()
    assert pool.drain_into(q, 8) == 1
    assert pool.admit(b"tx", "c0", 3).status == DUPLICATE  # in flight
    assert (pool.pending_count(), pool.inflight_count()) == (0, 1)
    pool.mark_settled([b"tx"])
    assert pool.depth() == 0
    assert pool.admit(b"tx", "c0", 3).status == DUPLICATE  # settled
    assert pool.stats()["deduped"] == 3


def test_seen_ring_is_bounded():
    """The dedup ring forgets oldest-first at seen_cap — bounded
    memory is the contract; a forgotten digest re-admits."""
    pool = Mempool(capacity=4, seen_cap=4, seed=0)
    assert pool.admit(b"old", "c0", 1).status == OK
    q = _Queue()
    pool.drain_into(q, 4)
    pool.mark_settled([b"old"])
    for i in range(4):  # push b"old" out of the 4-slot ring
        tx = b"new-%d" % i
        assert pool.admit(tx, "c1", 1).status == OK
        pool.drain_into(q, 4)
        pool.mark_settled([tx])
    assert pool.admit(b"old", "c0", 1).status == OK


def test_capacity_validation():
    with pytest.raises(ValueError):
        Mempool(capacity=0)
    with pytest.raises(ValueError):
        Mempool(capacity=1, client_cap=0)
