"""LockstepCluster (protocol.spmd): the synchronous batched executor.

Cross-validates the lockstep path against the full message-passing
cluster (protocol.cluster.SimulatedCluster): same roster, same dealer
keys, same submitted transactions — the committed transaction sets
must be identical, because both run the same protocol with the same
threshold crypto (the combined KEM/coin values are subset-independent,
ops/tpke.py combine docstring)."""

import numpy as np
import pytest

from cleisthenes_tpu.protocol.cluster import SimulatedCluster
from cleisthenes_tpu.protocol.spmd import LockstepCluster


def _tx(i: int) -> bytes:
    return b"spmd-tx-%06d" % i


def _committed_txs(batches) -> set:
    out = set()
    for b in batches:
        out.update(b.tx_list())
    return out


def test_lockstep_commits_all_txs():
    c = LockstepCluster(n=4, batch_size=64, key_seed=3)
    for i in range(128):
        c.submit(_tx(i))
    epochs = c.run_epochs()
    got = _committed_txs(c.committed())
    assert got == {_tx(i) for i in range(128)}
    assert epochs == len(c.committed())
    assert c.pending_tx_count() == 0


def test_lockstep_matches_message_passing_cluster():
    """The flagship equivalence check: lockstep vs full async path."""
    n, batch, total = 4, 64, 256
    lock = LockstepCluster(n=n, batch_size=batch, key_seed=11)
    sim = SimulatedCluster(n=n, batch_size=batch, key_seed=11, seed=5)
    for i in range(total):
        lock.submit(_tx(i))
        sim.submit(_tx(i))
    lock.run_epochs()
    sim.run_epochs()
    lock_txs = _committed_txs(lock.committed())
    sim_txs = _committed_txs(sim.committed("node000"))
    assert lock_txs == sim_txs == {_tx(i) for i in range(total)}


def test_lockstep_epoch_stats_report_real_work():
    c = LockstepCluster(n=4, batch_size=16, key_seed=1)
    for i in range(16):
        c.submit(_tx(i))
    s = c.run_epoch()
    n = 4
    # N^2 decryption-share issues, >= N^2 coin issues (>=1 round)
    assert s["dec_issues"] == n * n
    assert s["coin_issues"] >= n * n
    assert s["bba_rounds"] >= 1
    assert s["epoch_s"] > 0


def test_lockstep_multi_epoch_dedup_and_order():
    """Committed batches dedupe across proposers like the live commit
    rule; epochs drain queues in order."""
    c = LockstepCluster(n=4, batch_size=16, key_seed=2)
    # same tx submitted to two nodes: must commit exactly once
    c.submit(b"dup-tx", node_id=c.ids[0])
    c.submit(b"dup-tx", node_id=c.ids[1])
    c.run_epoch()
    batch = c.committed()[0]
    assert list(batch.tx_list()).count(b"dup-tx") == 1


def test_lockstep_n16_scale():
    c = LockstepCluster(n=16, batch_size=256, key_seed=9)
    for i in range(512):
        c.submit(_tx(i))
    c.run_epochs()
    assert _committed_txs(c.committed()) == {_tx(i) for i in range(512)}


def test_lockstep_conflicting_config_rejected():
    from cleisthenes_tpu.config import Config

    with pytest.raises(ValueError):
        LockstepCluster(n=7, config=Config(n=4, batch_size=16))


def test_lockstep_roster_past_gf256_ceiling():
    """n > 256 forces the GF(2^16) codec inside the full protocol —
    a roster the reference's codec dependency cannot express (256
    total shards).  Kept small-batch; the epoch still runs every
    phase (RS-16 encode/decode, 2^9-leaf Merkle forest, threshold
    coin at f=85, optimistic decryption) for all 257 validators."""
    c = LockstepCluster(n=257, batch_size=257, key_seed=13)
    for i in range(257):
        c.submit(_tx(i))
    c.run_epoch()
    got = _committed_txs(c.committed())
    assert got == {_tx(i) for i in range(257)}
    assert c.crypto.erasure.MAX_N == 1 << 16


def test_lockstep_serial_coin_blocks_match_doubling():
    """The coin_block_doubling knob (the on-chip A/B comparator,
    AB_COIN_BLOCKS_r05) changes dispatch batching only: committed
    transactions, coin values, and round counts are identical because
    the shares are deterministic VUFs of (epoch, proposer, round)."""
    a = LockstepCluster(n=5, batch_size=40, key_seed=9)
    b = LockstepCluster(
        n=5, batch_size=40, key_seed=9, coin_block_doubling=False
    )
    for i in range(80):
        a.submit(_tx(i))
        b.submit(_tx(i))
    a.run_epochs()
    b.run_epochs()
    assert _committed_txs(a.committed()) == _committed_txs(b.committed())
    assert a.last_stats["bba_rounds"] == b.last_stats["bba_rounds"]
    # serial runs one wave per round; doubling compresses the tail
    assert b.last_stats["coin_waves"] == b.last_stats["bba_rounds"]


def test_lockstep_aggressive_initial_block_matches():
    """coin_block_initial=4 (the RTT-aggressive first block) changes
    dispatch batching only — committed transactions and round counts
    are identical to the default schedule."""
    a = LockstepCluster(n=5, batch_size=40, key_seed=9)
    b = LockstepCluster(
        n=5, batch_size=40, key_seed=9, coin_block_initial=4
    )
    for i in range(80):
        a.submit(_tx(i))
        b.submit(_tx(i))
    a.run_epochs()
    b.run_epochs()
    assert _committed_txs(a.committed()) == _committed_txs(b.committed())
    assert a.last_stats["bba_rounds"] == b.last_stats["bba_rounds"]
    assert b.last_stats["coin_waves"] <= a.last_stats["coin_waves"]


def test_lockstep_reconfig_boundary():
    """Reconfig under the lockstep plane: the activation-boundary swap
    (join + retire + fresh key material) between epochs — committed
    history continuous, every tx exactly once, retiring node's pending
    txs failed over to survivors."""
    c = LockstepCluster(n=4, batch_size=16, key_seed=21)
    for i in range(32):
        c.submit(_tx(i))
    pre_epochs = c.run_epochs()
    pub0 = c.tpke.pub.master
    # strand a tx at the retiring member: it must fail over
    c.submit(_tx(900), node_id="node000")
    c.reconfigure(join=["node100"], retire=["node000"])
    assert c.ids == ["node001", "node002", "node003", "node100"]
    assert c.config.n == 4 and c.config.f == 1
    assert c.tpke.pub.master != pub0  # key material actually rotated
    for i in range(32, 48):
        c.submit(_tx(i))
    c.run_epochs()
    got = _committed_txs(c.committed())
    assert got == {_tx(i) for i in range(48)} | {_tx(900)}
    assert len(c.committed()) > pre_epochs  # epoch counter continuous


def test_lockstep_reduced_quorum_roster():
    """The 2f+1 trust model on the lockstep plane: n=5 carries f=2
    (data shards = n-2f = 1) and still commits everything — the
    quorum-mode seam reaches the batched executor through the same
    Config arithmetic the async plane reads."""
    from cleisthenes_tpu.config import Config

    c = LockstepCluster(
        n=5,
        config=Config(
            n=5, batch_size=16, attested_log=True, reduced_quorum=True
        ),
        key_seed=23,
    )
    assert c.config.f == 2 and c.config.data_shards == 1
    for i in range(20):
        c.submit(_tx(i))
    c.run_epochs()
    assert _committed_txs(c.committed()) == {_tx(i) for i in range(20)}
