"""Metrics/tracing subsystem tests (SURVEY.md §5.1/§5.5), including
integration with the HoneyBadger epoch loop."""

from cleisthenes_tpu.utils.metrics import Counter, Histogram, Metrics


def test_counter():
    c = Counter()
    c.inc()
    c.inc(5)
    assert c.value == 6


def test_histogram_percentiles():
    h = Histogram()
    assert h.p50 is None
    for v in range(1, 101):
        h.observe(float(v))
    assert h.count == 100
    assert 49 <= h.p50 <= 52
    assert 94 <= h.p95 <= 97
    assert h.percentile(0) == 1.0
    assert h.percentile(100) == 100.0


def test_histogram_bounded_reservoir():
    h = Histogram(cap=10)
    for v in range(100):
        h.observe(float(v))
    assert h.count == 10
    assert h.percentile(0) == 90.0  # only the newest 10 remain


def test_epoch_trace_phases():
    m = Metrics()
    m.epoch_proposed(0)
    m.epoch_acs_output(0)
    m.epoch_committed(0, n_txs=12)
    tr = m.trace(0)
    assert tr.total_s is not None and tr.total_s >= 0
    assert tr.acs_s is not None and tr.decrypt_s is not None
    assert m.epochs_committed.value == 1
    assert m.txs_committed.value == 12
    snap = m.snapshot()
    assert snap["epochs_committed"] == 1
    assert snap["epoch_p50_s"] is not None
    assert snap["tx_per_sec"] >= 0


def test_trace_map_bounded():
    m = Metrics(trace_cap=4)
    for e in range(10):
        m.epoch_proposed(e)
    assert len(m._traces) <= 4


def test_transport_health_state_machine_and_snapshot():
    """transport.health: UP/DEGRADED/DOWN transitions, reconnect
    counters, the recorded backoff schedule, and the Metrics.snapshot
    integration (the dial layer's observability block)."""
    from cleisthenes_tpu.transport.health import (
        DOWN_AFTER,
        Backoff,
        PeerHealthTracker,
        backoff_rng,
    )

    t = PeerHealthTracker(["peer-a", "peer-b"])
    assert t.state("peer-a") == "degraded"  # not connected yet
    t.dial_started("peer-a")
    t.connected("peer-a")
    assert t.state("peer-a") == "up"
    snap = t.snapshot()["peer-a"]
    assert snap["reconnects"] == 0  # boot connect is not a reconnect
    # stream loss -> DEGRADED; enough consecutive failures -> DOWN
    t.stream_lost("peer-a")
    assert t.state("peer-a") == "degraded"
    for _ in range(DOWN_AFTER):
        t.dial_started("peer-a")
        t.dial_failed("peer-a")
    assert t.state("peer-a") == "down"
    t.dial_scheduled("peer-a", 0.1)
    t.dial_scheduled("peer-a", 0.2)
    t.dial_started("peer-a")
    t.connected("peer-a")
    snap = t.snapshot()["peer-a"]
    assert snap["state"] == "up"
    assert snap["reconnects"] == 1  # the re-establishment counted
    assert snap["consecutive_failures"] == 0
    assert snap["recent_delays_s"] == [0.1, 0.2]
    # Metrics folds the block in once a provider registers
    m = Metrics()
    assert "transport_health" not in m.snapshot()
    m.set_transport_health(t.snapshot)
    assert m.snapshot()["transport_health"]["peer-b"]["state"] == "degraded"
    # Backoff: exponential growth to the cap, jitter within +/-25%,
    # deterministic for a seeded rng
    bo = Backoff(0.1, 1.0, rng=backoff_rng(5, "n0", "n1"))
    a = [bo.next_delay() for _ in range(6)]
    bo2 = Backoff(0.1, 1.0, rng=backoff_rng(5, "n0", "n1"))
    assert a == [bo2.next_delay() for _ in range(6)]
    raws = [0.1, 0.2, 0.4, 0.8, 1.0, 1.0]
    for got, raw in zip(a, raws):
        # max_s is a HARD cap: jitter never overshoots it
        assert raw * 0.75 <= got <= min(raw * 1.25, 1.0)
    assert a[1] > a[0] and a[2] > a[1]  # growth dominates the jitter
    bo.reset()
    assert bo.next_delay() <= 0.1 * 1.25


def test_honeybadger_records_epoch_metrics():
    from tests.test_honeybadger import make_hb_network, push_txs

    cfg, net, nodes = make_hb_network(4, batch_size=8)
    push_txs(nodes, 8)
    for hb in nodes.values():
        hb.start_epoch()
    net.run()
    for hb in nodes.values():
        snap = hb.metrics.snapshot()
        assert snap["epochs_committed"] >= 1
        assert snap["epoch_p50_s"] is not None
        assert snap["msgs_in"] > 0
        # phase split adds up
        tr = hb.metrics.trace(0)
        assert abs((tr.acs_s + tr.decrypt_s) - tr.total_s) < 1e-6
