"""Dynamic membership (ISSUE 12): RECONFIG transactions, in-band key
resharing, joiner bootstrap via CATCHUP, retirement teardown, and WAL
replay across the roster switch — on both transports.
"""

from __future__ import annotations

import threading
import time

import pytest

from cleisthenes_tpu.config import Config
from cleisthenes_tpu.core.ledger import encode_batch_body
from cleisthenes_tpu.core.member import Member, RosterSchedule, RosterVersion
from cleisthenes_tpu.protocol import reconfig as rcfg
from cleisthenes_tpu.protocol.cluster import SimulatedCluster
from cleisthenes_tpu.protocol.honeybadger import setup_keys


# ---------------------------------------------------------------------------
# unit: versioned rosters + codecs
# ---------------------------------------------------------------------------


def _rv(version, activation, ids):
    return RosterVersion(
        version=version,
        activation_epoch=activation,
        members=tuple(Member(id=m) for m in ids),
    )


def test_roster_schedule_resolution():
    sched = RosterSchedule(_rv(0, 0, ["a", "b", "c", "d"]))
    sched.install(_rv(1, 10, ["b", "c", "d", "e"]))
    assert sched.version_for(0).version == 0
    assert sched.version_for(9).version == 0
    assert sched.version_for(10).version == 1
    assert sched.version_for(999).version == 1
    assert sched.known_member_ids() == frozenset("abcde")
    with pytest.raises(ValueError):
        sched.install(_rv(3, 20, ["b"]))  # skips version 2
    with pytest.raises(ValueError):
        sched.install(_rv(2, 10, ["b"]))  # activation does not advance


def test_roster_version_sorts_members():
    rv = _rv(0, 0, ["d", "a", "c", "b"])
    assert rv.member_ids == ("a", "b", "c", "d")
    assert rv.n == 4 and rv.f == 1


def test_reconfig_tx_roundtrip_and_validation():
    secret, pub = rcfg.enrollment_keypair(seed=5)
    tx = rcfg.encode_reconfig_tx(
        3,
        [("b", "", 0), ("a", "10.0.0.1", 4711), ("j", "", 0)],
        {"j": pub},
    )
    assert rcfg.is_protocol_tx(tx)
    spec = rcfg.decode_reconfig_tx(tx)
    assert spec.version == 3
    assert spec.member_ids == ("a", "b", "j")
    assert spec.members[0] == ("a", "10.0.0.1", 4711)
    assert spec.enroll_pubs == {"j": pub}
    assert spec.n == 3 and spec.f == 0 and spec.threshold == 1
    # malformations reject deterministically
    with pytest.raises(ValueError):
        rcfg.decode_reconfig_tx(tx + b"\x00")  # trailing bytes
    with pytest.raises(ValueError):
        rcfg.decode_reconfig_tx(b"\x00RCFG1|garbage")
    with pytest.raises(ValueError):  # enrollment key for a non-member
        rcfg.decode_reconfig_tx(
            rcfg.encode_reconfig_tx(1, [("a", "", 0)], {"z": pub})
        )
    with pytest.raises(ValueError):  # pub outside the group
        rcfg.decode_reconfig_tx(
            rcfg.encode_reconfig_tx(1, [("j", "", 0)], {"j": 0})
        )


def test_dealing_tx_roundtrip():
    tx = rcfg.encode_dealing_tx(
        2, "dealer-a", [3, 5], [7, 11], {"x": b"A" * 96, "y": b"B" * 96}
    )
    assert rcfg.is_protocol_tx(tx)
    d = rcfg.decode_dealing_tx(tx)
    assert d.version == 2 and d.dealer == "dealer-a"
    assert d.tpke_commits == (3, 5) and d.coin_commits == (7, 11)
    assert sorted(d.blobs) == ["x", "y"]
    with pytest.raises(ValueError):
        rcfg.decode_dealing_tx(tx[:-1])


def _pvss_fixture(tamper=None):
    """A full dealing (tpke + coin sharings over 4 receivers) with
    optional tampering applied to one receiver's blob bytes."""
    import hashlib

    from cleisthenes_tpu.ops.dkg import DkgDealing
    from cleisthenes_tpu.ops.tpke import DEFAULT_GROUP as G

    n, t = 4, 2
    ids = [f"n{i}" for i in range(n)]
    xs = {
        rid: int.from_bytes(
            hashlib.sha256(b"pvss-x|" + rid.encode()).digest(), "big"
        )
        % G.q
        for rid in ids
    }
    pubs = {rid: pow(G.g, x, G.p) for rid, x in xs.items()}
    deal_t = DkgDealing(1, n, t, G, seed=42)
    deal_c = DkgDealing(1, n, t, G, seed=43)
    ct = tuple(deal_t.commitments(backend="cpu"))
    cc = tuple(deal_c.commitments(backend="cpu"))
    blobs = {}
    for j, rid in enumerate(ids, start=1):
        parts = []
        for kind, (deal, commits) in enumerate(
            ((deal_t, ct), (deal_c, cc))
        ):
            parts.append(
                rcfg.pvss_encrypt_share(
                    deal.share_for(j),
                    pubs[rid],
                    hashlib.sha256(
                        b"rho|%d|" % kind + rid.encode()
                    ).digest(),
                    rcfg._pvss_ctx(7, "d0", rid, kind, commits, G),
                    G,
                )
            )
        blobs[rid] = b"".join(parts)
    if tamper is not None:
        blobs = dict(blobs)
        blobs[tamper[0]] = tamper[1](blobs[tamper[0]])
    dealing = rcfg.Dealing(
        version=7, dealer="d0", tpke_commits=ct, coin_commits=cc,
        blobs=blobs,
    )
    return G, ids, xs, pubs, (deal_t, deal_c), dealing


def test_pvss_blob_roundtrip_and_public_verification():
    """The PVSS satellite's unit contract: blobs decrypt to the dealt
    shares, verification is PUBLIC (needs no receiver secret), and a
    blob tampered toward ONE receiver fails verification for every
    observer — the dealer is excluded deterministically rather than
    detected by the victim alone."""
    G, ids, xs, pubs, deals, dealing = _pvss_fixture()
    assert all(
        len(b) == rcfg.pvss_blob_len(G) for b in dealing.blobs.values()
    )
    assert rcfg.pvss_verify_dealing(dealing, pubs, G)
    for j, rid in enumerate(ids, start=1):
        for kind, deal in enumerate(deals):
            s = rcfg.pvss_decrypt_share(
                dealing.blobs[rid], kind, xs[rid], G
            )
            assert s == deal.share_for(j) % G.q
    # flip one ciphertext byte of one receiver's blob
    def _flip(b):
        ba = bytearray(b)
        ba[10] ^= 0x01
        return bytes(ba)

    _, _, _, pubs2, _, bad = _pvss_fixture(tamper=("n2", _flip))
    assert not rcfg.pvss_verify_dealing(bad, pubs2, G)


def test_pvss_rejects_wrong_share_ciphertext():
    """A dealer that encrypts a VALID-LOOKING ciphertext of the WRONG
    share to a targeted receiver (the docs/FAULTS.md limitation this
    PR closes) fails the DLEQ against its own commitments — publicly,
    on every node."""
    import hashlib

    from cleisthenes_tpu.ops.dkg import DkgDealing
    from cleisthenes_tpu.ops.tpke import DEFAULT_GROUP as G

    G2, ids, xs, pubs, (deal_t, deal_c), dealing = _pvss_fixture()

    def _reencrypt_wrong(blob):
        parts = []
        for kind, (deal, commits) in enumerate(
            (
                (deal_t, dealing.tpke_commits),
                (deal_c, dealing.coin_commits),
            )
        ):
            wrong = (deal.share_for(3) + 12345) % G.q
            parts.append(
                rcfg.pvss_encrypt_share(
                    wrong,
                    pubs["n2"],
                    hashlib.sha256(b"evil|%d" % kind).digest(),
                    rcfg._pvss_ctx(7, "d0", "n2", kind, commits, G),
                    G,
                )
            )
        return b"".join(parts)

    _, _, _, _, _, evil = _pvss_fixture(
        tamper=("n2", _reencrypt_wrong)
    )
    assert not rcfg.pvss_verify_dealing(evil, pubs, G)


def test_pair_mac_key_symmetry():
    """Both ends of every new pair derive the same key from opposite
    DH halves (old member: coin share vs enrollment pub; joiner:
    enrollment secret vs coin verification key)."""
    cfg = Config(n=4, batch_size=8)
    ids = [f"n{i}" for i in range(4)]
    keys = setup_keys(cfg, ids, seed=9)
    es, ep = rcfg.enrollment_keypair(seed=17)
    g = keys["n0"].coin_pub.group
    old = keys["n1"]
    vk1 = old.coin_pub.verification_keys[old.coin_share.index - 1]
    k_old_side = rcfg.pair_mac_key(
        1, rcfg.dh_point(old.coin_share.value, ep, g), "n1", "j", g
    )
    k_joiner_side = rcfg.pair_mac_key(
        1, rcfg.dh_point(es, vk1, g), "j", "n1", g
    )
    assert k_old_side == k_joiner_side
    boot = rcfg.joiner_bootstrap_keys(es, 1, old.coin_pub, ids, "j")
    assert boot["n1"] == k_joiner_side


def test_config_validates_reconfig_lead():
    with pytest.raises(ValueError):
        Config(n=4, decrypt_lag_max=4, reconfig_lead=4)
    # ISSUE 15: the bound now clears the K-deep in-flight window too
    # (reconfig_lead > pipeline_depth + decrypt_lag_max)
    with pytest.raises(ValueError):
        Config(
            n=4, decrypt_lag_max=4, pipeline_depth=2, reconfig_lead=6
        )
    Config(n=4, decrypt_lag_max=4, pipeline_depth=1, reconfig_lead=6)  # ok
    Config(n=4, decrypt_lag_max=4, pipeline_depth=2, reconfig_lead=7)  # ok


# ---------------------------------------------------------------------------
# channel transport: the full lifecycle
# ---------------------------------------------------------------------------


def _drained_cluster(n=4, seed=7, **kw):
    c = SimulatedCluster(n=n, batch_size=8, seed=seed, key_seed=33, **kw)
    for i in range(3 * n):
        c.submit(b"pre-%03d" % i)
    c.run_until_drained(max_rounds=30)
    return c


def _assert_identical_ledgers(cluster, nids):
    depth = min(
        len(cluster.nodes[nid].committed_batches) for nid in nids
    )
    assert depth > 0
    for e in range(depth):
        bodies = {
            encode_batch_body(
                e, cluster.nodes[nid].committed_batches[e]
            )
            for nid in nids
        }
        assert len(bodies) == 1, f"fork at epoch {e}"
    return depth


def test_joiner_bootstraps_and_participates():
    """Acceptance: a joiner added mid-run adopts the committed log via
    CATCHUP, receives its shares from the in-band ceremony, and
    participates from the activation epoch — all honest nodes (old
    and new) hold byte-identical ledgers and identical key digests."""
    c = _drained_cluster()
    try:
        pre_depth = c.assert_agreement()
        v = c.begin_reconfig(join=["node100"])
        assert v == 1
        c.run_until_drained(max_rounds=60)
        assert set(c.roster_versions().values()) == {1}
        # the reconfig machinery's own txs are protocol-internal
        seen = [
            tx
            for b in c.committed()
            for tx in b.tx_list()
            if rcfg.is_protocol_tx(tx)
        ]
        assert any(tx.startswith(rcfg.RECONFIG_TX_PREFIX) for tx in seen)
        assert any(tx.startswith(rcfg.DEAL_TX_PREFIX) for tx in seen)
        # post-activation traffic: the joiner proposes under v1
        for i in range(20):
            c.submit(b"post-%03d" % i)
        c.run_until_drained(max_rounds=40)
        depth = _assert_identical_ledgers(c, list(c.nodes))
        assert depth > pre_depth
        jn = c.nodes["node100"]
        assert jn.roster_version == 1
        assert len(jn.committed_batches) == len(
            c.nodes["node000"].committed_batches
        )
        assert any(
            "node100" in b.contributions and b.contributions["node100"]
            for b in jn.committed_batches
        ), "joiner never contributed a committed proposal"
        # key agreement: every node derived the identical material
        digests = {
            hb.rosters.latest().key_material_digest
            for hb in c.nodes.values()
        }
        assert len(digests) == 1 and b"" not in digests
        # observability: the roster switch is visible per node
        snap = jn.metrics.snapshot()["reconfig"]
        assert snap == {"roster_version": 1, "reconfigs_total": 1}
    finally:
        c.stop()


def test_retirement_teardown():
    """A retired validator orders its last epoch at the boundary and
    parks; once the survivors settle past it, its pair keys drop and
    the broadcast set narrows — and the ledgers stay byte-identical
    up to the retiree's final epoch."""
    c = _drained_cluster(seed=11)
    try:
        v = c.begin_reconfig(join=["node100"], retire=["node003"])
        assert v == 1
        c.run_until_drained(max_rounds=60)
        for i in range(12):
            c.submit(b"post-%03d" % i, node_id="node100")
        c.run_until_drained(max_rounds=40, skip=("node003",))
        retiree = c.nodes["node003"]
        assert retiree._retired_self
        activation = retiree.rosters.latest().activation_epoch
        assert retiree.epoch == activation
        assert len(retiree.committed_batches) == activation
        # survivors moved past the boundary under the new roster
        for nid in ("node000", "node001", "node002", "node100"):
            hb = c.nodes[nid]
            assert hb.roster_version == 1
            assert len(hb.committed_batches) > activation
            assert "node003" not in hb.members
        # the retiree's prefix matches everyone's
        _assert_identical_ledgers(c, list(c.nodes))
        # MAC teardown: continuing nodes no longer hold its pair key
        assert "node003" not in c.auths["node000"]._peer_keys
        assert "node003" not in c.auths["node100"]._peer_keys
        # ...so post-teardown frames from the retiree are rejected
        rejected0 = c.net.endpoint_stats("node000")["rejected"]
        retiree.request_catchup()
        c.net.run()
        assert c.net.endpoint_stats("node000")["rejected"] > rejected0
    finally:
        c.stop()


def test_rekey_only_reconfig_rotates_material():
    """Same members, new version: the threshold key material rotates
    (proactive re-key) and the ledger keeps extending seamlessly."""
    c = _drained_cluster(seed=13)
    try:
        digest0 = c.nodes["node000"].rosters.latest().key_material_digest
        pub0 = c.nodes["node000"].active_view.keys.tpke_pub.master
        v = c.begin_reconfig()  # no joins, no retirements
        c.run_until_drained(max_rounds=60)
        assert set(c.roster_versions().values()) == {v}
        for i in range(12):
            c.submit(b"rekey-%03d" % i)
        c.run_until_drained(max_rounds=40)
        c.assert_agreement()
        rv1 = c.nodes["node000"].rosters.latest()
        assert rv1.member_ids == ("node000", "node001", "node002",
                                  "node003")
        assert rv1.key_material_digest != digest0
        pub1 = c.nodes["node000"].active_view.keys.tpke_pub.master
        assert pub1 != pub0
        digests = {
            hb.rosters.latest().key_material_digest
            for hb in c.nodes.values()
        }
        assert len(digests) == 1
    finally:
        c.stop()


@pytest.mark.slow
def test_reconfig_lifecycle_n64():
    """Reconfig at scale (BASELINE config 3 roster): a 64-validator
    cluster runs the full in-band ceremony — 22 qualifying PVSS
    dealings publicly verified by every node, a join+retire roster
    swap, MAC rotation for all ~2k surviving pairs — and the ledgers
    stay byte-identical across the boundary."""
    c = SimulatedCluster(n=64, batch_size=64, seed=29, key_seed=41)
    try:
        # one epoch at n=64 costs ~15s wall (64^2 frames, RS-64
        # coding, 64-wide BBA banks): keep the tx load minimal and let
        # the CEREMONY be the thing this test spends its budget on
        for i in range(8):
            c.submit(b"pre-%03d" % i)
        c.run_until_drained(max_rounds=4)
        v = c.begin_reconfig(join=["node100"], retire=["node000"])
        assert v == 1
        c.run_until_drained(max_rounds=20)
        for i in range(8):
            c.submit(b"post-%03d" % i, node_id="node100")
        c.run_until_drained(max_rounds=8, skip=("node000",))
        survivors = [nid for nid in c.nodes if nid != "node000"]
        for nid in survivors:
            hb = c.nodes[nid]
            assert hb.roster_version == 1, nid
            assert hb.active_view.config.n == 64
            assert "node000" not in hb.members
        _assert_identical_ledgers(c, list(c.nodes))
        # every survivor committed the post-boundary traffic
        committed = set()
        for b in c.nodes["node100"].committed_batches:
            committed.update(b.tx_list())
        assert {b"post-%03d" % i for i in range(8)} <= committed
    finally:
        c.stop()


@pytest.mark.faults
def test_stale_mac_frames_rejected_after_rotation_channel():
    """MAC rotation satellite (channel transport): a rekey-only
    reconfig rotates EVERY surviving pair's MAC key; once the settled
    frontier crosses the boundary the pre-rotation keys are gone from
    both ends — frames MAC'd under a stale key are rejected."""
    c = _drained_cluster(seed=19)
    try:
        old_key = c.auths["node001"]._peer_keys["node000"]
        c.begin_reconfig()  # rekey-only: same members, new version
        c.run_until_drained(max_rounds=60)
        for i in range(8):
            c.submit(b"post-%03d" % i)
        c.run_until_drained(max_rounds=40)  # settle past the boundary
        # step 2+3 of the rotation lifecycle completed: fresh key on
        # both ends, verify-either alternates dropped
        new_key = c.auths["node001"]._peer_keys["node000"]
        assert new_key != old_key
        assert c.auths["node000"]._peer_keys["node001"] == new_key
        assert "node000" not in c.auths["node001"]._alt_keys
        assert "node001" not in c.auths["node000"]._alt_keys
        # a sender still MAC'ing under the pre-rotation key (a stale
        # process, or an attacker holding compromised v0 material) is
        # rejected at the receiving endpoint
        rejected0 = c.net.endpoint_stats("node000")["rejected"]
        c.auths["node001"].set_peer_key("node000", old_key)
        c.submit(b"stale-probe", node_id="node001")
        c.run_until_drained(max_rounds=10)
        assert c.net.endpoint_stats("node000")["rejected"] > rejected0
        # the rest of the roster (fresh keys) was unaffected
        c.assert_agreement()
    finally:
        c.stop()


@pytest.mark.faults
def test_stale_mac_frames_rejected_after_rotation_grpc():
    """MAC rotation satellite (gRPC transport): the rekey-only
    ceremony runs over real sockets; post-activation, a host signing
    under the stale v0 pair key is rejected at the receiving server."""
    from cleisthenes_tpu.transport.host import ValidatorHost

    n = 4
    cfg = Config(
        n=n,
        batch_size=8,
        seed=7,
        dial_timeout_s=0.25,
        dial_retry_base_s=0.05,
        dial_retry_max_s=1.0,
        decrypt_lag_max=2,
        reconfig_lead=4,
        pipeline_depth=1,
    )
    ids = [f"node{i}" for i in range(n)]
    keys = setup_keys(cfg, ids, seed=77)
    old_key = keys["node1"].mac_keys["node0"]
    hosts = {i: ValidatorHost(cfg, i, ids, keys[i]) for i in ids}
    try:
        addrs = {i: h.listen() for i, h in hosts.items()}
        threads = [
            threading.Thread(target=h.connect, args=(addrs,))
            for h in hosts.values()
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=15)
        for i in range(8):
            hosts[ids[i % n]].submit(b"pre-%02d" % i)
        for h in hosts.values():
            h.propose()
        for h in hosts.values():
            h.wait_commit(timeout=60)
        # rekey-only RECONFIG: same members, fresh key material
        members = [(m, *a.rsplit(":", 1)) for m, a in addrs.items()]
        members = [(m, ip, int(p)) for m, ip, p in members]
        hosts[ids[0]].submit(rcfg.encode_reconfig_tx(1, members, {}))
        for h in hosts.values():
            h.propose()
        deadline = time.monotonic() + 90
        while time.monotonic() < deadline:
            if all(h.node.roster_version == 1 for h in hosts.values()):
                break
            time.sleep(0.25)
        assert all(h.node.roster_version == 1 for h in hosts.values())
        # drive settlement past the boundary so teardown pins the
        # fresh keys and drops the verify-either alternates
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if all(
                "node0" not in h._auth._alt_keys
                and h._auth._peer_keys.get("node0", old_key) != old_key
                for h in hosts.values()
                if h.node_id != "node0"
            ):
                break
            for i in range(4):
                hosts[ids[i % n]].submit(b"post-%02d" % i)
            for h in hosts.values():
                h.propose()
            time.sleep(0.5)
        assert hosts["node1"]._auth._peer_keys["node0"] != old_key
        assert "node1" not in hosts["node0"]._auth._alt_keys
        # stale sender: node1 signs to node0 under the v0 key
        rejected0 = hosts["node0"]._transport_stats()["rejected"]
        hosts["node1"]._auth.set_peer_key("node0", old_key)
        hosts["node1"].submit(b"stale-probe")
        hosts["node1"].propose()
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if hosts["node0"]._transport_stats()["rejected"] > rejected0:
                break
            time.sleep(0.1)
        assert hosts["node0"]._transport_stats()["rejected"] > rejected0
    finally:
        for h in hosts.values():
            h.stop()


@pytest.mark.faults
def test_wal_replay_across_reconfig_boundary_channel(tmp_path):
    """Satellite: a node crashes AFTER the RCFG record is durable but
    BEFORE the first post-activation commit, restarts from its WAL,
    re-derives the roster switch from the replayed log (cross-checked
    against the RCFG record), and rejoins under the NEW roster."""
    c = SimulatedCluster(
        n=4, batch_size=8, seed=7, key_seed=33,
        wal_dir=str(tmp_path),
    )
    try:
        for i in range(12):
            c.submit(b"pre-%03d" % i)
        c.run_until_drained(max_rounds=30)
        c.begin_reconfig(join=["node100"])
        # quiesce WITHOUT post-activation traffic: every node crosses
        # the boundary (RCFG durable, settled == activation) but no
        # epoch >= activation has committed yet
        c.run_until_drained(max_rounds=60)
        victim = "node001"
        hb = c.nodes[victim]
        activation = hb.rosters.latest().activation_epoch
        assert hb.roster_version == 1
        assert len(hb.committed_batches) == activation
        # the RCFG record is on disk
        logged = list(hb.batch_log.replay_reconfigs())
        assert len(logged) == 1
        assert logged[0][0] == 1 and logged[0][1] == activation
        # fail-stop + process restart from the WAL
        c.crash(victim)
        hb2 = c.restart_node(victim)
        assert hb2.roster_version == 1
        assert hb2.epoch == activation
        assert "node100" in hb2.members
        assert hb2.active_view.keys.tpke_pub.master == (
            c.nodes["node000"].active_view.keys.tpke_pub.master
        )
        # the restarted node participates in post-activation epochs
        for i in range(16):
            c.submit(b"post-%03d" % i)
        c.run_until_drained(max_rounds=40)
        depth = _assert_identical_ledgers(c, list(c.nodes))
        assert depth > activation
        assert any(
            victim in b.contributions and b.contributions[victim]
            for b in hb2.committed_batches[activation:]
        ), "restarted node never proposed under the new roster"
    finally:
        c.stop()


def test_routing_arms_stay_byte_equivalent_across_reconfig():
    """The PR-9/10 equivalence-arm contract survives the roster
    change: the same seeded schedule, run under the wave-routed and
    the scalar routing disciplines, commits byte-identical ledgers
    through a join+retire reconfig (the ResharePayload barrier and
    the roster-version demux behave identically on both arms)."""
    ledgers = {}
    for wave in (True, False):
        cfg = Config(
            n=4, batch_size=8, seed=5,
            wave_routing=wave, delivery_columnar=wave,
        )
        c = SimulatedCluster(config=cfg, seed=5, key_seed=33)
        try:
            for i in range(12):
                c.submit(b"eq-%03d" % i)
            c.run_until_drained(max_rounds=30)
            c.begin_reconfig(join=["node100"], retire=["node003"])
            c.run_until_drained(max_rounds=60)
            for i in range(12, 24):
                c.submit(b"eq-%03d" % i, node_id="node100")
            c.run_until_drained(max_rounds=40, skip=("node003",))
            assert c.roster_versions()["node100"] == 1
            c.assert_agreement()
            ledgers[wave] = [
                encode_batch_body(e, b)
                for e, b in enumerate(
                    c.nodes["node000"].committed_batches
                )
            ]
        finally:
            c.stop()
    assert ledgers[True] == ledgers[False]


def test_fuzz_reconfig_schedules_hold_invariants():
    """The reconfig fuzz band's machinery end to end: sampled
    schedules carry a reconfig event, and the safety/liveness
    invariants hold across the roster change (two fixed seeds of the
    CI band; the band itself runs in ci.sh)."""
    from tools.fuzz import run_schedule, sample_schedule

    for seed in (0, 3):
        schedule = sample_schedule(seed, n=4, rounds=16, reconfig=True)
        assert any(
            ev["op"] == "reconfig" for ev in schedule["timeline"]
        )
        assert run_schedule(schedule) is None


# ---------------------------------------------------------------------------
# transport/health: retirement (satellite)
# ---------------------------------------------------------------------------


def test_health_tracker_retirement():
    from cleisthenes_tpu.transport.health import PeerHealthTracker

    t = PeerHealthTracker(["a", "b"])
    t.dial_failed("a")
    assert "a" in t.snapshot()
    t.retire("a")
    assert t.is_retired("a")
    assert "a" not in t.snapshot()
    # racing dial events for a retired peer must not resurrect it
    t.dial_started("a")
    t.dial_failed("a")
    t.dial_scheduled("a", 0.5)
    t.connected("a")
    t.stream_lost("a")
    assert "a" not in t.snapshot()
    assert t.state("a") == "down"
    # the live peer is untouched
    t.connected("b")
    assert t.snapshot()["b"]["state"] == "up"


@pytest.mark.faults
def test_grpc_retired_peer_stops_redial_storm():
    """Satellite: a host redialing an unreachable peer backs off; the
    moment the peer retires, the loop cancels — dial attempts stop
    growing and the peer vanishes from transport_health."""
    from cleisthenes_tpu.transport.host import ValidatorHost

    cfg = Config(
        n=4,
        batch_size=8,
        seed=7,
        dial_timeout_s=0.1,
        dial_retry_base_s=0.02,
        dial_retry_max_s=0.1,
    )
    ids = [f"node{i}" for i in range(4)]
    keys = setup_keys(cfg, ids, seed=77)
    host = ValidatorHost(cfg, "node0", ids, keys["node0"])
    try:
        host.listen()
        # a peer that will never answer: the redial loop spins up
        host._addrs["node1"] = "127.0.0.1:1"  # reserved port: refused
        t = threading.Thread(
            target=host._redial_loop, args=("node1",), daemon=True
        )
        t.start()
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            snap = host.health.snapshot().get("node1")
            if snap is not None and snap["dial_attempts"] >= 2:
                break
            time.sleep(0.02)
        assert snap is not None and snap["dial_attempts"] >= 2
        # retire: the loop must cancel and the health row drop
        host.retire_peer("node1")
        t.join(timeout=5)
        assert not t.is_alive(), "redial loop survived retirement"
        assert "node1" not in host.health.snapshot()
        assert "node1" not in host.members
    finally:
        host.stop()


# ---------------------------------------------------------------------------
# gRPC transport: join + WAL replay across the boundary (satellite)
# ---------------------------------------------------------------------------


@pytest.mark.faults
def test_grpc_join_and_wal_replay_across_reconfig(tmp_path):
    """The acceptance scenario over real sockets: a joiner host dials
    in mid-run, bootstraps via CATCHUP, and participates from its
    activation epoch; a crash-restarted member replays the roster
    switch from its WAL and rejoins under the NEW roster — ledgers
    byte-identical across old, new, and restarted nodes."""
    from cleisthenes_tpu.protocol.honeybadger import NodeKeys
    from cleisthenes_tpu.transport.host import ValidatorHost

    n = 4
    cfg = Config(
        n=n,
        batch_size=8,
        seed=7,
        dial_timeout_s=0.25,
        dial_retry_base_s=0.05,
        dial_retry_max_s=1.0,
        decrypt_lag_max=2,
        reconfig_lead=4,
        # lockstep window keeps this scenario's tight reconfig_lead
        # legal (ISSUE 15 validates lead > depth + lag); the K-deep
        # reconfig-boundary case lives in tests/test_pipeline_depth.py
        pipeline_depth=1,
    )
    ids = [f"node{i}" for i in range(n)]
    keys = setup_keys(cfg, ids, seed=77)
    victim = "node2"
    wal = str(tmp_path / "node2.log")
    hosts = {
        i: ValidatorHost(
            cfg, i, ids, keys[i],
            batch_log_path=wal if i == victim else None,
        )
        for i in ids
    }
    joiner = None
    restarted = None
    try:
        addrs = {i: h.listen() for i, h in hosts.items()}
        threads = [
            threading.Thread(target=h.connect, args=(addrs,))
            for h in hosts.values()
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=15)
        for i, tx in enumerate([b"pre-%02d" % i for i in range(8)]):
            hosts[ids[i % n]].submit(tx)
        for h in hosts.values():
            h.propose()
        for h in hosts.values():
            h.wait_commit(timeout=60)

        # -- the joiner host boots and the operator submits RECONFIG --
        jid = "nodeJ"
        enroll_secret, enroll_pub = rcfg.enrollment_keypair(seed=99)
        jkeys = NodeKeys(
            tpke_pub=keys[ids[0]].tpke_pub,
            tpke_share=None,
            coin_pub=keys[ids[0]].coin_pub,
            coin_share=None,
            mac_keys=rcfg.joiner_bootstrap_keys(
                enroll_secret, 1, keys[ids[0]].coin_pub, ids, jid
            ),
            enroll_secret=enroll_secret,
        )
        import dataclasses as _dc

        joiner = ValidatorHost(
            _dc.replace(cfg, n=n, f=None),
            jid,
            ids,
            jkeys,
            joining=True,
        )
        jaddr = joiner.listen()
        jt = threading.Thread(target=joiner.connect, args=(addrs,))
        jt.start()
        jt.join(timeout=15)
        jip, jport = jaddr.rsplit(":", 1)
        members = [(m, *a.rsplit(":", 1)) for m, a in addrs.items()]
        members = [(m, ip, int(p)) for m, ip, p in members]
        members.append((jid, jip, int(jport)))
        tx = rcfg.encode_reconfig_tx(1, members, {jid: enroll_pub})
        hosts[ids[0]].submit(tx)
        for h in hosts.values():
            h.propose()

        # the ceremony + boundary drive themselves; wait for every
        # host (joiner included) to activate v1
        deadline = time.monotonic() + 90
        everyone = list(hosts.values()) + [joiner]
        while time.monotonic() < deadline:
            if all(
                h.node.roster_version == 1 for h in everyone
            ):
                break
            time.sleep(0.25)
        assert all(h.node.roster_version == 1 for h in everyone), {
            h.node_id: h.node.roster_version for h in everyone
        }

        # -- post-activation traffic: the joiner participates ---------
        for i, tx2 in enumerate([b"post-%02d" % i for i in range(8)]):
            joiner.submit(tx2) if i % 2 else hosts[ids[0]].submit(tx2)
        for h in everyone:
            h.propose()
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            depths = [len(h.committed_batches()) for h in everyone]
            if min(depths) >= cfg.reconfig_lead and all(
                h.pending_tx_count() == 0 for h in everyone
            ):
                break
            time.sleep(0.25)

        # -- crash the WAL-bearing member and restart under v1 --------
        hosts[victim].stop()
        restarted = ValidatorHost(
            cfg,
            victim,
            ids,
            keys[victim],
            listen_addr=addrs[victim],
            batch_log_path=wal,
        )
        assert restarted.node.roster_version == 1
        assert jid in restarted.node.members
        restarted.listen()
        raddrs = dict(addrs)
        raddrs[jid] = jaddr
        restarted.connect(raddrs)
        want = hosts[ids[0]].committed_batches()
        deadline = time.monotonic() + 60
        got = []
        while time.monotonic() < deadline:
            got = restarted.committed_batches()
            if len(got) >= len(want):
                break
            time.sleep(0.25)
        assert len(got) >= len(want), (len(got), len(want))
        # byte-identical ledgers across old, new and restarted nodes
        ref = [
            encode_batch_body(e, b) for e, b in enumerate(want)
        ]
        for h in [hosts[ids[0]], hosts[ids[1]], joiner, restarted]:
            batches = h.committed_batches()
            for e, body in enumerate(ref):
                assert encode_batch_body(e, batches[e]) == body
    finally:
        for h in hosts.values():
            h.stop()
        if joiner is not None:
            joiner.stop()
        if restarted is not None:
            restarted.stop()
