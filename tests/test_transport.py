"""Transport layer tests: wire codec, authentication, channel network.

Models the reference's conn/comm tests (conn_test.go:32-202,
comm_test.go:27-96, SURVEY.md §4): full send -> wire -> verify ->
dispatch round trips over the in-proc transport, plus the adversarial
cases the reference's TODO ``verify`` (conn.go:134-137) could not test.
"""

import pytest

from cleisthenes_tpu.transport import (
    BbaPayload,
    BbaType,
    ChannelNetwork,
    CoinPayload,
    ConnectionPool,
    DecSharePayload,
    HmacAuthenticator,
    Message,
    RbcPayload,
    RbcType,
    decode_message,
    encode_message,
)


def _payloads():
    return [
        RbcPayload(
            type=RbcType.VAL,
            proposer="node-2",
            epoch=7,
            root_hash=b"\x01" * 32,
            branch=(b"\x02" * 32, b"\x03" * 32),
            shard=bytes(range(200)),
            shard_index=3,
        ),
        RbcPayload(type=RbcType.READY, proposer="n0", epoch=0, root_hash=b"r" * 32),
        BbaPayload(type=BbaType.BVAL, proposer="n1", epoch=2, round=5, value=True),
        BbaPayload(type=BbaType.AUX, proposer="n1", epoch=2, round=0, value=False),
        CoinPayload(
            proposer="n3", epoch=1, round=2, index=4, d=2**255 - 19, e=12345, z=0
        ),
        DecSharePayload(proposer="n0", epoch=9, index=1, d=1, e=2**200, z=7),
    ]


class TestCodec:
    @pytest.mark.parametrize("payload", _payloads(), ids=lambda p: type(p).__name__)
    def test_round_trip(self, payload):
        msg = Message(
            sender_id="node-9", timestamp=123.5, payload=payload, signature=b"sig"
        )
        out = decode_message(encode_message(msg))
        assert out == msg

    def test_bad_magic_rejected(self):
        with pytest.raises(ValueError):
            decode_message(b"XXXX\x01\x03" + b"\x00" * 32)

    def test_truncated_rejected(self):
        wire = encode_message(
            Message("a", 0.0, RbcPayload(RbcType.READY, "p", 0, b"h"))
        )
        with pytest.raises(ValueError):
            decode_message(wire[:-3])

    def test_trailing_bytes_rejected(self):
        wire = encode_message(
            Message("a", 0.0, RbcPayload(RbcType.READY, "p", 0, b"h"))
        )
        with pytest.raises(ValueError):
            decode_message(wire + b"x")

    def test_oversized_length_field_rejected(self):
        """A Byzantine length prefix must not drive allocation."""
        wire = bytearray(
            encode_message(Message("a", 0.0, RbcPayload(RbcType.READY, "p", 0, b"h")))
        )
        wire[6:10] = (2**31).to_bytes(4, "big")  # sender_id length field
        with pytest.raises(ValueError):
            decode_message(bytes(wire))


_ROSTER = ["n0", "n1", "n2", "nX"]


def _auth(self_id, master=b"master", roster=_ROSTER):
    return HmacAuthenticator.derive(master, self_id, roster)


class TestAuthenticator:
    def test_cached_schedule_matches_hmac_new(self):
        """The precomputed inner/outer key schedule must be
        byte-identical to stdlib HMAC-SHA256 — for short keys, the
        64-byte block boundary, and over-long keys (hashed first)."""
        import hashlib
        import hmac as hmac_mod

        from cleisthenes_tpu.transport.base import _hmac_sha256_fn

        for key in (b"k", b"x" * 32, b"y" * 64, b"z" * 200):
            fn = _hmac_sha256_fn(key)
            for msg in (b"", b"m", b"payload" * 100):
                assert fn(msg) == hmac_mod.new(
                    key, msg, hashlib.sha256
                ).digest()

    def test_sign_verify(self):
        n0, n1 = _auth("n0"), _auth("n1")
        msg = n0.sign(
            Message("n0", 1.0, RbcPayload(RbcType.READY, "p", 0, b"h")), "n1"
        )
        assert msg.signature != b""
        assert n1.verify(msg)

    def test_tamper_detected(self):
        n0, n1 = _auth("n0"), _auth("n1")
        msg = n0.sign(
            Message("n0", 1.0, RbcPayload(RbcType.READY, "p", 0, b"h")), "n1"
        )
        forged = Message("n0", 1.0, RbcPayload(RbcType.READY, "p", 1, b"h"), msg.signature)
        assert not n1.verify(forged)

    def test_third_member_cannot_forge_between_pair(self):
        """The ADVICE.md round-1 finding: with per-SENDER keys any
        roster member could compute every other member's key.  With
        per-PAIR keys, Byzantine nX (holding all of ITS pair keys)
        still cannot MAC a message n1->n0, because k_{n0,n1} is not
        among them."""
        import hmac as hmac_mod
        import hashlib

        from cleisthenes_tpu.transport.message import signing_bytes

        nX, n0 = _auth("nX"), _auth("n0")
        msg = Message("n1", 1.0, RbcPayload(RbcType.READY, "p", 0, b"h"))
        # nX tries every key it holds
        for key in nX._peer_keys.values():
            forged = Message(
                msg.sender_id,
                msg.timestamp,
                msg.payload,
                hmac_mod.new(key, signing_bytes(msg), hashlib.sha256).digest(),
            )
            assert not n0.verify(forged)

    def test_wrong_pair_key_rejected(self):
        """A frame n0 signed for n1 must not verify at n2 (receiver
        binding)."""
        n0, n2 = _auth("n0"), _auth("n2")
        msg = n0.sign(
            Message("n0", 1.0, RbcPayload(RbcType.READY, "p", 0, b"h")), "n1"
        )
        assert not n2.verify(msg)

    def test_unknown_sender_rejected(self):
        n0 = _auth("n0")
        stranger = Message(
            "not-in-roster", 1.0, RbcPayload(RbcType.READY, "p", 0, b"h")
        )
        assert not n0.verify(stranger)

    def test_sign_refuses_wrong_sender(self):
        """sign() raises rather than emit a message every receiver
        would silently reject."""
        auth = _auth("n0")
        with pytest.raises(ValueError):
            auth.sign(
                Message("n1", 1.0, RbcPayload(RbcType.READY, "p", 0, b"h")),
                "n2",
            )

    def test_sign_requires_receiver(self):
        auth = _auth("n0")
        with pytest.raises(ValueError):
            auth.sign(Message("n0", 1.0, RbcPayload(RbcType.READY, "p", 0, b"h")))

    def test_payload_trailing_bytes_rejected(self):
        """Non-canonical payload bodies (trailing junk inside the
        length-prefixed body) must not decode — frame malleability."""
        from cleisthenes_tpu.transport.message import (
            _KIND_BBA,
            _decode_payload,
            _encode_payload,
        )

        kind, body = _encode_payload(
            BbaPayload(BbaType.BVAL, "p", 0, 0, True)
        )
        assert kind == _KIND_BBA
        _decode_payload(kind, body)  # canonical: fine
        with pytest.raises(ValueError):
            _decode_payload(kind, body + b"\x00")


class _Collector:
    def __init__(self):
        self.got = []

    def serve_request(self, msg):
        self.got.append(msg)


def _mk_net(n=3, seed=None, master=b"k"):
    net = ChannelNetwork(seed=seed)
    collectors = {}
    roster = [f"n{i}" for i in range(n)]
    for nid in roster:
        collectors[nid] = _Collector()
        net.join(
            nid, collectors[nid], HmacAuthenticator.derive(master, nid, roster)
        )
    return net, collectors


def _msg(sender, epoch=0):
    return Message(sender, 0.0, RbcPayload(RbcType.READY, "p", epoch, b"h" * 32))


class TestChannelNetwork:
    def test_point_to_point_delivery(self):
        net, col = _mk_net()
        conn = net.connect("n0", "n1")
        conn.send(_msg("n0"))
        assert net.run() == 1
        assert len(col["n1"].got) == 1
        assert col["n1"].got[0].sender_id == "n0"

    def test_pool_broadcast(self):
        """Reference conn_test.go:138-202 (broadcast to the pool)."""
        net, col = _mk_net(4)
        pool = ConnectionPool()
        for peer in ("n1", "n2", "n3"):
            pool.add(net.connect("n0", peer))
        pool.broadcast(_msg("n0"))
        assert net.run() == 3
        for peer in ("n1", "n2", "n3"):
            assert len(col[peer].got) == 1
        assert len(col["n0"].got) == 0

    def test_tampered_wire_rejected(self):
        net, col = _mk_net()

        def flip(sender, receiver, wire):
            w = bytearray(wire)
            w[-1] ^= 0xFF  # corrupt MAC byte
            return bytes(w)

        net.fault_filter = flip
        net.connect("n0", "n1").send(_msg("n0"))
        net.run()
        assert col["n1"].got == []
        # rejection is visible for observability
        assert net._endpoints["n1"].rejected == 1

    def test_crash_drops_traffic(self):
        net, col = _mk_net()
        net.crash("n1")
        net.connect("n0", "n1").send(_msg("n0"))
        net.connect("n0", "n2").send(_msg("n0"))
        net.run()
        assert col["n1"].got == []
        assert len(col["n2"].got) == 1

    @pytest.mark.faults
    def test_crash_purges_inflight_and_restart_gets_fresh_inbox(self):
        """Fail-stop semantics: frames in flight to/from the node die
        with it, so a restart() cannot see pre-crash ghosts — it
        rejoins with a NEW handler and an empty inbox."""
        net, col = _mk_net(3)
        net.connect("n0", "n1").send(_msg("n0", epoch=1))
        net.connect("n1", "n2").send(_msg("n1", epoch=2))
        net.crash("n1")  # both in-flight frames involve n1: purged
        assert net.pending_count() == 0
        net.run()
        assert col["n1"].got == [] and col["n2"].got == []
        fresh = _Collector()
        net.restart("n1", fresh)
        net.connect("n0", "n1").send(_msg("n0", epoch=3))
        net.connect("n1", "n2").send(_msg("n1", epoch=4))
        net.run()
        # the restarted handler (not the old one) receives new traffic
        assert [m.payload.epoch for m in fresh.got] == [3]
        assert col["n1"].got == []
        assert [m.payload.epoch for m in col["n2"].got] == [4]

    def test_partition_and_heal(self):
        net, col = _mk_net()
        net.partition("n0", "n1")
        net.connect("n0", "n1").send(_msg("n0"))
        net.run()
        assert col["n1"].got == []
        net.heal("n0", "n1")
        net.connect("n0", "n1").send(_msg("n0"))
        net.run()
        assert len(col["n1"].got) == 1

    def test_seeded_scheduler_is_replayable(self):
        """Same seed -> identical adversarial interleaving (SURVEY §5.2)."""

        def run_once(seed):
            net, col = _mk_net(3, seed=seed)
            for e in range(20):
                net.connect("n0", "n2").send(_msg("n0", epoch=e))
                net.connect("n1", "n2").send(_msg("n1", epoch=e))
            net.run()
            return [(m.sender_id, m.payload.epoch) for m in col["n2"].got]

        a, b = run_once(42), run_once(42)
        assert a == b
        c = run_once(7)
        assert sorted(a) == sorted(c)
        assert a != c  # different seed, different order (40 msgs: collision ~0)

    def test_handler_cascade_drains(self):
        """Handlers that send more messages keep the scheduler busy
        (the pattern every protocol round uses)."""
        net = ChannelNetwork()

        class Relay:
            def __init__(self, nid, limit=5):
                self.nid = nid
                self.limit = limit
                self.seen = 0

            def serve_request(self, msg):
                self.seen += 1
                if msg.payload.epoch < self.limit:
                    net.connect(self.nid, "n0" if self.nid == "n1" else "n1").send(
                        Message(
                            self.nid,
                            0.0,
                            RbcPayload(
                                RbcType.READY, "p", msg.payload.epoch + 1, b"h"
                            ),
                        )
                    )

        r0, r1 = Relay("n0"), Relay("n1")
        net.join("n0", r0)
        net.join("n1", r1)
        net.connect("n0", "n1").send(_msg("n0", epoch=0))
        delivered = net.run()
        assert delivered == 6  # epochs 0..5 ping-pong
        assert r0.seen + r1.seen == 6


def test_codec_fuzz_never_crashes():
    """Decoder robustness: random and mutated frames must decode or
    raise ValueError — never any other exception (the channel layer
    catches exactly ValueError; anything else would kill a node on a
    Byzantine frame)."""
    import random

    from cleisthenes_tpu.transport.message import (
        BbaBatchPayload,
        BbaPayload,
        BbaType,
        BundlePayload,
        CatchupReqPayload,
        CatchupRespPayload,
        CoinBatchPayload,
        CoinPayload,
        DecShareBatchPayload,
        DecSharePayload,
        EchoBatchPayload,
        Message,
        RbcPayload,
        RbcType,
        ReadyBatchPayload,
        decode_frame,
        encode_message,
    )

    rng = random.Random(1234)
    seeds = [
        Message(
            "node-a",
            1.5,
            BundlePayload(
                items=(
                    RbcPayload(RbcType.ECHO, "p", 1, b"r" * 32,
                               (b"x" * 32,), b"s" * 8, 1),
                    BbaPayload(BbaType.BVAL, "p", 1, 0, True),
                    CoinPayload("p", 1, 0, 1, 7, 8, 9),
                    DecSharePayload("p", 1, 1, 7, 8, 9),
                    CatchupReqPayload(1),
                    CatchupRespPayload(1, b"body"),
                    BbaBatchPayload(BbaType.BVAL, 1, 0, True, ("a", "b")),
                    CoinBatchPayload(1, 0, 2, ("a", "b"), (1, 2), (3, 4),
                                     (5, 6)),
                    DecShareBatchPayload(1, 2, ("a", "b"), (1, 2), (3, 4),
                                         (5, 6)),
                    ReadyBatchPayload(1, ("a", "b"), (b"q" * 32, b"w" * 32)),
                    EchoBatchPayload(
                        1, 3, ("a", "b"), (b"q" * 32, b"w" * 32),
                        ((b"x" * 32,), (b"y" * 32,)), (b"s1", b"s2"),
                    ),
                )
            ),
            b"m" * 32,
        ),
        Message("node-b", 2.0,
                RbcPayload(RbcType.READY, "p", 3, b"q" * 32), b"m" * 32),
    ]
    wires = [encode_message(m) for m in seeds]
    for m, w in zip(seeds, wires):
        assert decode_frame(w)[0] == m  # sanity
    for _ in range(3000):
        w = bytearray(rng.choice(wires))
        for _ in range(rng.randrange(1, 6)):
            op = rng.randrange(3)
            if op == 0 and w:  # mutate
                w[rng.randrange(len(w))] = rng.randrange(256)
            elif op == 1 and len(w) > 2:  # truncate
                del w[rng.randrange(1, len(w)) :]
            else:  # extend
                w += bytes(rng.randrange(256) for _ in range(rng.randrange(1, 9)))
        try:
            decode_frame(bytes(w))
        except ValueError:
            pass  # the one allowed failure mode
    # pure-random frames too
    for _ in range(2000):
        blob = bytes(rng.randrange(256) for _ in range(rng.randrange(0, 120)))
        try:
            decode_frame(blob)
        except ValueError:
            pass


def test_echo_batch_columnarizes_and_roundtrips():
    """A turn's ECHO fan-out (one per instance, all at the sender's
    shard slot) merges into ONE EchoBatchPayload — the last
    O(N^2)-per-epoch class to go columnar — and survives the codec."""
    from cleisthenes_tpu.transport.broadcast import _columnarize
    from cleisthenes_tpu.transport.message import (
        EchoBatchPayload,
        Message,
        RbcPayload,
        RbcType,
        decode_frame,
        encode_message,
    )

    echoes = [
        RbcPayload(
            RbcType.ECHO, f"p{i}", 7, bytes([i]) * 32,
            (bytes([i]) * 32, bytes([64 + i]) * 32), bytes([i]) * 16, 3,
        )
        for i in range(4)
    ]
    items = _columnarize(list(echoes))
    assert len(items) == 1 and isinstance(items[0], EchoBatchPayload)
    batch = items[0]
    assert batch.epoch == 7 and batch.shard_index == 3
    assert batch.proposers == tuple(f"p{i}" for i in range(4))
    wire = encode_message(Message("s", 1.0, batch, b"m" * 32))
    got, _prefix = decode_frame(wire)
    assert got.payload == batch
