"""Core-layer tests, mirroring the reference's white-box unit tests
(queue_internal_test.go:9-146, member_map_internal_test.go:24-74,
member_map_test.go:9-21)."""

import pytest

from cleisthenes_tpu import (
    Address,
    Batch,
    Config,
    EmptyQueueError,
    IndexBoundaryError,
    Member,
    MemberMap,
    TxQueue,
)
from cleisthenes_tpu.core.request import (
    DuplicateRequestError,
    IncomingRequestRepository,
    RequestRepository,
)


class TestTxQueue:
    def test_fifo_order(self):
        q = TxQueue()
        for i in range(5):
            q.push(f"tx{i}")
        assert [q.poll() for _ in range(5)] == [f"tx{i}" for i in range(5)]

    def test_poll_empty_raises(self):
        with pytest.raises(EmptyQueueError):
            TxQueue().poll()

    def test_peek_does_not_remove(self):
        q = TxQueue()
        q.push("a")
        assert q.peek() == "a"
        assert len(q) == 1

    def test_peek_empty_raises(self):
        with pytest.raises(EmptyQueueError):
            TxQueue().peek()

    def test_at(self):
        q = TxQueue()
        for i in range(3):
            q.push(i)
        assert q.at(2) == 2
        assert len(q) == 3

    def test_at_out_of_bounds(self):
        q = TxQueue()
        q.push("x")
        with pytest.raises(IndexBoundaryError):
            q.at(1)
        with pytest.raises(IndexBoundaryError):
            q.at(-1)

    def test_len(self):
        q = TxQueue()
        assert q.len() == 0
        q.push(1)
        assert q.len() == 1


class TestMemberMap:
    def test_add_and_lookup(self):
        mm = MemberMap()
        m = Member("v0", Address("127.0.0.1", 5000))
        mm.add(m)
        assert mm.member("v0") == m
        assert "v0" in mm

    def test_delete(self):
        mm = MemberMap()
        mm.add(Member("v0"))
        mm.delete("v0")
        assert mm.member("v0") is None
        assert len(mm) == 0

    def test_members_sorted(self):
        mm = MemberMap()
        for name in ("v2", "v0", "v1"):
            mm.add(Member(name))
        assert [m.id for m in mm.members()] == ["v0", "v1", "v2"]

    def test_overwrite(self):
        mm = MemberMap()
        mm.add(Member("v0", Address("a", 1)))
        mm.add(Member("v0", Address("b", 2)))
        assert mm.member("v0").addr == Address("b", 2)


class TestConfig:
    def test_defaults(self):
        c = Config(n=4)
        assert c.f == 1
        assert c.data_shards == 2
        assert c.parity_shards == 2
        assert c.decryption_threshold == 2

    def test_n128(self):
        c = Config(n=128, f=42, batch_size=10_000)
        assert c.data_shards == 44
        assert c.parity_shards == 84

    def test_invalid_f(self):
        with pytest.raises(ValueError):
            Config(n=4, f=2)

    def test_invalid_backend(self):
        with pytest.raises(ValueError):
            Config(n=4, crypto_backend="gpu")


class TestBatch:
    def test_tx_list_deterministic_order(self):
        b = Batch({"v1": ["b", "c"], "v0": ["a"]})
        assert b.tx_list() == ["a", "b", "c"]
        assert len(b) == 3


class TestRequestRepository:
    def test_first_write_wins(self):
        r = RequestRepository()
        r.save("c1", "req1")
        with pytest.raises(DuplicateRequestError):
            r.save("c1", "req2")
        assert r.find("c1") == "req1"
        assert len(r) == 1

    def test_find_all(self):
        r = RequestRepository()
        r.save("c1", 1)
        r.save("c2", 2)
        assert sorted(r.find_all()) == [("c1", 1), ("c2", 2)]


class TestIncomingRequestRepository:
    def test_epoch_buffer_replay(self):
        """Future-epoch messages are parked and replayed
        (reference bba/request.go:28-32)."""
        r = IncomingRequestRepository()
        r.save(epoch=2, conn_id="c1", req="late1", current_epoch=1)
        r.save(epoch=2, conn_id="c1", req="late2", current_epoch=1)
        r.save(epoch=3, conn_id="c2", req="later", current_epoch=1)
        assert r.find_all(2) == [("c1", "late1"), ("c1", "late2")]
        drained = r.pop_epoch(2)
        assert drained == [("c1", "late1"), ("c1", "late2")]
        assert r.find_all(2) == []
        assert r.find_all(3) == [("c2", "later")]
