"""End-to-end HoneyBadgerBFT: N in-proc validators over the channel
transport committing identical batches (BASELINE config 1), plus the
batch-policy unit tests mirroring the reference's
honeybadger_internal_test.go:8-180."""

import os

import pytest

from cleisthenes_tpu.config import Config
from cleisthenes_tpu.protocol.honeybadger import (
    HoneyBadger,
    deserialize_ciphertext,
    deserialize_txs,
    serialize_ciphertext,
    serialize_txs,
    setup_keys,
)
from cleisthenes_tpu.transport.base import HmacAuthenticator
from cleisthenes_tpu.transport.broadcast import ChannelBroadcaster
from cleisthenes_tpu.transport.channel import ChannelNetwork


def make_hb_network(
    n,
    batch_size=16,
    seed=None,
    auth=True,
    auto_propose=True,
    key_seed=33,
    crypto_backend="cpu",
    mesh_shape=None,
):
    cfg = Config(
        n=n,
        batch_size=batch_size,
        crypto_backend=crypto_backend,
        mesh_shape=mesh_shape,
    )
    ids = [f"node{i}" for i in range(n)]
    keys = setup_keys(cfg, ids, seed=key_seed)
    net = ChannelNetwork(seed=seed)
    nodes = {}
    for node_id in ids:
        hb = HoneyBadger(
            config=cfg,
            node_id=node_id,
            member_ids=ids,
            keys=keys[node_id],
            out=ChannelBroadcaster(net, node_id, ids),
            auto_propose=auto_propose,
        )
        nodes[node_id] = hb
        net.join(
            node_id,
            hb,
            HmacAuthenticator(node_id, keys[node_id].mac_keys)
            if auth
            else None,
        )
    return cfg, net, nodes


def push_txs(nodes, count, prefix=b"tx"):
    txs = []
    for i in range(count):
        tx = b"%s-%06d" % (prefix, i)
        txs.append(tx)
        # spray txs round-robin across nodes (each node's queue differs)
        node = list(nodes.values())[i % len(nodes)]
        node.add_transaction(tx)
    return txs


def assert_identical_batches(nodes, skip=()):
    live = {nid: hb for nid, hb in nodes.items() if nid not in skip}
    counts = {nid: len(hb.committed_batches) for nid, hb in live.items()}
    depth = min(counts.values())
    assert depth > 0, f"no common committed epoch: {counts}"
    for e in range(depth):
        lists = {
            nid: hb.committed_batches[e].tx_list() for nid, hb in live.items()
        }
        first = next(iter(lists.values()))
        for nid, txl in lists.items():
            assert txl == first, f"epoch {e}: {nid} batch differs"
    return depth


# ---------------------------------------------------------------------------
# serialization round-trips
# ---------------------------------------------------------------------------


def test_tx_list_roundtrip():
    txs = [b"", b"a", b"hello" * 100, bytes(range(256))]
    assert deserialize_txs(serialize_txs(txs)) == txs
    assert deserialize_txs(serialize_txs([])) == []


def test_tx_list_rejects_garbage():
    with pytest.raises(ValueError):
        deserialize_txs(b"\x00")
    with pytest.raises(ValueError):
        deserialize_txs(b"\xff\xff\xff\xff" + b"x" * 10)
    with pytest.raises(ValueError):
        deserialize_txs(serialize_txs([b"a"]) + b"junk")


def test_ciphertext_roundtrip():
    from cleisthenes_tpu.ops.tpke import Tpke, deal

    pub, _ = deal(4, 2, seed=1)
    ct = Tpke(pub).encrypt(b"secret batch")
    ct2 = deserialize_ciphertext(serialize_ciphertext(ct))
    assert ct2 == ct
    with pytest.raises(ValueError):
        deserialize_ciphertext(b"short")


# ---------------------------------------------------------------------------
# batch policy (reference honeybadger_internal_test.go)
# ---------------------------------------------------------------------------


def test_batch_policy_b_is_max_of_batchsize_and_n():
    cfg, net, nodes = make_hb_network(4, batch_size=2)
    assert next(iter(nodes.values())).b == 4  # max(2, 4)
    cfg2, net2, nodes2 = make_hb_network(4, batch_size=100)
    assert next(iter(nodes2.values())).b == 100


def test_create_batch_samples_b_over_n_and_restores_rest():
    cfg, net, nodes = make_hb_network(4, batch_size=8, auto_propose=False)
    hb = nodes["node0"]
    for i in range(20):
        hb.add_transaction(b"tx-%02d" % i)
    picked = hb._create_batch()
    # b/n = 8/4 = 2 picked; the other 6 candidates restored
    assert len(picked) == 2
    assert len(hb.que) == 18
    assert len(set(picked)) == len(picked)


def test_create_batch_with_few_txs_takes_what_exists():
    cfg, net, nodes = make_hb_network(4, batch_size=8, auto_propose=False)
    hb = nodes["node0"]
    hb.add_transaction(b"only-one")
    assert hb._create_batch() == [b"only-one"]
    assert len(hb.que) == 0


# ---------------------------------------------------------------------------
# end-to-end epochs (BASELINE config 1)
# ---------------------------------------------------------------------------


def test_hbbft_single_epoch_identical_batches_n4():
    cfg, net, nodes = make_hb_network(4, batch_size=16)
    txs = push_txs(nodes, 16)
    for hb in nodes.values():
        hb.start_epoch()
    net.run()
    depth = assert_identical_batches(nodes)
    assert depth >= 1
    committed = set(nodes["node0"].committed_batches[0].tx_list())
    assert committed <= set(txs)
    assert len(committed) > 0


def test_hbbft_runs_multiple_epochs_until_queues_drain():
    cfg, net, nodes = make_hb_network(4, batch_size=8)
    txs = push_txs(nodes, 24)
    for hb in nodes.values():
        hb.start_epoch()
    net.run()
    depth = assert_identical_batches(nodes)
    assert depth >= 2  # 24 txs / (b=8 per epoch best case) needs >= 3
    all_committed = [
        tx for b in nodes["node0"].committed_batches for tx in b.tx_list()
    ]
    assert len(all_committed) == len(set(all_committed))  # no replays
    assert set(all_committed) <= set(txs)


def test_hbbft_commits_all_txs_eventually():
    cfg, net, nodes = make_hb_network(4, batch_size=16)
    txs = push_txs(nodes, 30)
    for _ in range(40):  # keep kicking epochs until all queues drain
        for hb in nodes.values():
            hb.start_epoch()
        net.run()
        if all(hb.pending_tx_count() == 0 for hb in nodes.values()):
            break
    assert all(hb.pending_tx_count() == 0 for hb in nodes.values())
    assert_identical_batches(nodes)
    all_committed = {
        tx for b in nodes["node0"].committed_batches for tx in b.tx_list()
    }
    assert all_committed == set(txs)


@pytest.mark.parametrize("seed", [3, 12, 77])
def test_hbbft_adversarial_scheduling(seed):
    cfg, net, nodes = make_hb_network(4, batch_size=8, seed=seed)
    push_txs(nodes, 16)
    for hb in nodes.values():
        hb.start_epoch()
    net.run()
    assert_identical_batches(nodes)


def test_hbbft_tolerates_f_crashed_nodes():
    cfg, net, nodes = make_hb_network(4, batch_size=8, seed=5)
    crashed = "node3"
    net.crash(crashed)
    txs = push_txs(nodes, 12)
    for nid, hb in nodes.items():
        if nid != crashed:
            hb.start_epoch()
    net.run()
    depth = assert_identical_batches(nodes, skip=(crashed,))
    assert depth >= 1


def test_hbbft_epoch_progression_and_queue_decrease():
    cfg, net, nodes = make_hb_network(4, batch_size=8)
    push_txs(nodes, 8)
    before = sum(hb.pending_tx_count() for hb in nodes.values())
    for hb in nodes.values():
        hb.start_epoch()
    net.run()
    after = sum(hb.pending_tx_count() for hb in nodes.values())
    assert after < before
    assert all(hb.epoch >= 1 for hb in nodes.values())


def test_hbbft_epoch_on_tpu_backend():
    """Full consensus with the XLA crypto plane (runs on the CPU
    backend's XLA in tests; same code path as real TPU)."""
    cfg, net, nodes = make_hb_network(
        4, batch_size=8, key_seed=44, crypto_backend="tpu"
    )
    push_txs(nodes, 8, prefix=b"xla")
    for hb in nodes.values():
        hb.start_epoch()
    net.run()
    assert_identical_batches(nodes)


def test_hbbft_scale_n16():
    """BASELINE config 2 shape (N=16, f=5) in-proc: one full epoch,
    64 txs, identical batches on all 16 validators."""
    cfg, net, nodes = make_hb_network(16, batch_size=64, seed=4)
    assert cfg.f == 5 and cfg.data_shards == 6
    txs = push_txs(nodes, 64)
    for hb in nodes.values():
        hb.start_epoch()
    net.run()
    depth = assert_identical_batches(nodes)
    committed = {
        tx
        for b in nodes["node0"].committed_batches[:depth]
        for tx in b.tx_list()
    }
    assert committed == set(txs)


class TestEpochPipelining:
    """BASELINE config 5: epoch e+1's proposal overlaps epoch e's
    decryption-share phase (Config.epoch_pipelining, default on)."""

    def test_overlap_happens_and_commits_stay_correct(self):
        cfg, net, nodes = make_hb_network(4, batch_size=8)
        assert cfg.epoch_pipelining
        push_txs(nodes, 32)  # several epochs of work
        for hb in nodes.values():
            hb.start_epoch()
        net.run()
        depth = assert_identical_batches(nodes)
        assert depth >= 3
        hb = nodes["node0"]
        overlaps = 0
        for e in range(depth - 1):
            t_next_prop = hb.metrics.trace(e + 1).t_propose
            t_commit = hb.metrics.trace(e).t_commit
            if (
                t_next_prop is not None
                and t_commit is not None
                and t_next_prop < t_commit
            ):
                overlaps += 1
        assert overlaps >= 1, "no epoch proposed ahead of the previous commit"

    def test_pipelining_off_still_commits(self):
        from cleisthenes_tpu.config import Config

        cfg, net, nodes = make_hb_network(4, batch_size=8)
        for hb in nodes.values():
            hb.config.epoch_pipelining = False
        push_txs(nodes, 16)
        for hb in nodes.values():
            hb.start_epoch()
        net.run()
        depth = assert_identical_batches(nodes)
        assert depth >= 2
        # strict sequencing: no epoch proposed before the previous commit
        hb = nodes["node0"]
        for e in range(depth - 1):
            t_next_prop = hb.metrics.trace(e + 1).t_propose
            t_commit = hb.metrics.trace(e).t_commit
            if t_next_prop is not None and t_commit is not None:
                assert t_next_prop >= t_commit

    def test_pipelining_under_adversarial_scheduler(self):
        cfg, net, nodes = make_hb_network(4, batch_size=8, seed=29)
        push_txs(nodes, 24)
        for _ in range(30):
            for hb in nodes.values():
                hb.start_epoch()
            net.run()
            if all(hb.pending_tx_count() == 0 for hb in nodes.values()):
                break
        assert_identical_batches(nodes)


@pytest.mark.slow
def test_full_epoch_n64_agreement_and_validity():
    """BASELINE config 3 scale, end to end: N=64, f=21 — north-star
    quorum math (threshold-22 coin/TPKE, 43-ECHO quorums, depth-6
    branches) executing as a full protocol epoch, not a crypto unit
    test (VERDICT round-2 item 4).  CPU backend for CI portability."""
    n = 64
    cfg, net, nodes = make_hb_network(
        n, batch_size=64, auth=True, key_seed=41
    )
    cfg_f = (n - 1) // 3
    assert cfg_f == 21 and cfg.n - 2 * cfg.f == 22
    txs = push_txs(nodes, 64, prefix=b"n64")
    for _ in range(4):
        for hb in nodes.values():
            hb.start_epoch()
        net.run()
        if all(hb.pending_tx_count() == 0 for hb in nodes.values()):
            break
    depth = assert_identical_batches(nodes)  # agreement, every node
    committed = [
        tx
        for b in nodes["node0"].committed_batches[:depth]
        for tx in b.tx_list()
    ]
    # validity: everything committed was submitted, nothing duplicated,
    # and the union of epochs committed every submitted tx
    assert set(committed) <= set(txs)
    assert len(committed) == len(set(committed))
    assert set(committed) == set(txs)


@pytest.mark.skipif(
    os.environ.get("RUN_SLOW") != "1",
    reason="~4 min: full-protocol N=128 epoch (RUN_SLOW=1 to enable)",
)
def test_n128_full_protocol_epoch():
    """BASELINE config 4 on the REAL message-passing path: one
    N=128/f=42 epoch over the in-proc transport — every frame through
    the codec and MACs — commits with agreement on all 128 nodes.
    (Measured ~130 s/epoch on one CPU core; the lockstep executor
    covers this scale in the default bench, protocol_spmd_n128.)"""
    from cleisthenes_tpu.protocol.cluster import SimulatedCluster

    cluster = SimulatedCluster(n=128, batch_size=1024, seed=7, key_seed=5)
    for i in range(1024):
        cluster.submit(b"n128-tx-%06d" % i)
    cluster.run_epochs(max_rounds=3)
    hist = {
        tuple(tuple(sorted(b.tx_list())) for b in cluster.committed(nid))
        for nid in cluster.ids
    }
    assert len(hist) == 1
    assert sum(len(b) for b in cluster.committed()) == 1024


def test_tx_parse_memo_hit_and_cap():
    """The content-keyed deserialize_txs memo (cluster simulations
    only; instance-scoped, never global): the hit path must return a
    fresh, equal LIST, and the cap overflow must clear without
    corrupting results."""
    from cleisthenes_tpu.protocol import honeybadger as hb

    memo = hb.make_tx_parse_memo()
    txs = [b"x" * 40 for _ in range(12)]  # blob >= 256 B
    blob = hb.serialize_txs(txs)
    first = hb.deserialize_txs(blob, memo)
    second = hb.deserialize_txs(bytes(blob), memo)  # distinct object
    assert first == second == txs
    assert isinstance(second, list)
    assert second is not first  # callers may mutate their copy
    second.append(b"mutant")
    assert hb.deserialize_txs(blob, memo) == txs  # cache unpoisoned
    # cap overflow clears wholesale and keeps parsing correctly
    memo.cap = 4
    for i in range(10):
        extra = hb.serialize_txs([b"y%02d" % i] + txs)
        assert hb.deserialize_txs(extra, memo)[0] == b"y%02d" % i
    assert hb.deserialize_txs(blob, memo) == txs
    # no memo passed (real per-node deployments): nothing cached
    before = len(memo.map)
    assert hb.deserialize_txs(blob) == txs
    assert len(memo.map) == before
