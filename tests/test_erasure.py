"""Property tests for GF(2^8) math and both Reed-Solomon backends.

Mirrors the reference's TDD matrix for RBC internals
(rbc/rbc_internal_test.go:5-31: shard, interpolate, validateMessage)
plus field-axiom checks, at N sizes up to the BASELINE north-star
(N=128, f=42).
"""

import numpy as np
import pytest

from cleisthenes_tpu.ops import gf256
from cleisthenes_tpu.ops.backend import make_erasure_coder
from cleisthenes_tpu.ops.payload import join_payload, split_payload

rng = np.random.default_rng(42)


class TestGF256:
    def test_field_axioms_sampled(self):
        for _ in range(200):
            a, b, c = (int(x) for x in rng.integers(0, 256, 3))
            assert gf256.gf_mul(a, b) == gf256.gf_mul(b, a)
            assert gf256.gf_mul(a, gf256.gf_mul(b, c)) == gf256.gf_mul(
                gf256.gf_mul(a, b), c
            )
            # distributivity over XOR (field addition)
            assert gf256.gf_mul(a, b ^ c) == gf256.gf_mul(a, b) ^ gf256.gf_mul(a, c)

    def test_inverse(self):
        for a in range(1, 256):
            assert gf256.gf_mul(a, gf256.gf_inv(a)) == 1
        with pytest.raises(ZeroDivisionError):
            gf256.gf_inv(0)

    def test_mul_table_matches_scalar(self):
        a = rng.integers(0, 256, 64)
        b = rng.integers(0, 256, 64)
        for x, y in zip(a, b):
            assert gf256.GF_MUL_TABLE[x, y] == gf256.gf_mul(int(x), int(y))

    def test_mat_inv_roundtrip(self):
        for k in (1, 2, 5, 16):
            m = gf256.systematic_rs_matrix(min(256, 3 * k), k)[k : 2 * k]
            # rows k..2k-1 of a systematic RS matrix are invertible
            inv = gf256.gf_mat_inv(m)
            assert np.array_equal(
                gf256.gf_matmul(m, inv), np.eye(k, dtype=np.uint8)
            )

    def test_mat_inv_singular(self):
        m = np.zeros((3, 3), dtype=np.uint8)
        with pytest.raises(np.linalg.LinAlgError):
            gf256.gf_mat_inv(m)

    def test_bit_lifting_equals_gf_matmul(self):
        a = rng.integers(0, 256, (6, 4)).astype(np.uint8)
        x = rng.integers(0, 256, (4, 33)).astype(np.uint8)
        want = gf256.gf_matmul(a, x)
        g = gf256.lift_to_bits(a)
        got_bits = (g.astype(np.int64) @ gf256.bytes_to_bits(x).astype(np.int64)) & 1
        assert np.array_equal(gf256.bits_to_bytes(got_bits.astype(np.uint8)), want)

    def test_bytes_bits_roundtrip(self):
        x = rng.integers(0, 256, (7, 19)).astype(np.uint8)
        assert np.array_equal(gf256.bits_to_bytes(gf256.bytes_to_bits(x)), x)


@pytest.mark.parametrize("backend", ["cpu", "tpu"])
@pytest.mark.parametrize(
    "n,f",
    [(4, 1), (7, 2), (16, 5), (128, 42)],
)
class TestErasureCoder:
    def test_roundtrip_random_erasures(self, backend, n, f):
        k = n - 2 * f
        coder = make_erasure_coder(backend, n, k)
        data = rng.integers(0, 256, (k, 128)).astype(np.uint8)
        shards = coder.encode(data)
        assert shards.shape == (n, 128)
        assert np.array_equal(shards[:k], data)  # systematic
        for _ in range(3):
            survivors = np.sort(rng.choice(n, size=k, replace=False))
            rec = coder.decode([int(i) for i in survivors], shards[survivors])
            assert np.array_equal(rec, data)

    def test_worst_case_erasure(self, backend, n, f):
        """Lose ALL data shards; reconstruct from parity alone where
        possible (2f parity rows can replace up to 2f data rows)."""
        k = n - 2 * f
        coder = make_erasure_coder(backend, n, k)
        data = rng.integers(0, 256, (k, 64)).astype(np.uint8)
        shards = coder.encode(data)
        lost = min(2 * f, k)
        survivors = list(range(lost, k)) + list(range(k, k + lost))
        rec = coder.decode(survivors, shards[survivors])
        assert np.array_equal(rec, data)


@pytest.mark.parametrize("n,f", [(4, 1), (16, 5)])
def test_backends_agree(n, f):
    k = n - 2 * f
    cpu = make_erasure_coder("cpu", n, k)
    tpu = make_erasure_coder("tpu", n, k)
    data = rng.integers(0, 256, (k, 256)).astype(np.uint8)
    assert np.array_equal(cpu.encode(data), tpu.encode(data))
    shards = cpu.encode(data)
    survivors = list(range(n - k, n))
    assert np.array_equal(
        cpu.decode(survivors, shards[survivors]),
        tpu.decode(survivors, shards[survivors]),
    )


@pytest.mark.parametrize("backend", ["cpu", "tpu"])
def test_batched_matches_single(backend):
    n, f = 7, 2
    k = n - 2 * f
    coder = make_erasure_coder(backend, n, k)
    data = rng.integers(0, 256, (5, k, 128)).astype(np.uint8)
    enc = coder.encode_batch(data)
    for b in range(5):
        assert np.array_equal(enc[b], coder.encode(data[b]))
    idx = np.stack([np.sort(rng.choice(n, k, replace=False)) for _ in range(5)])
    shards = np.stack([enc[b][idx[b]] for b in range(5)])
    dec = coder.decode_batch(idx, shards)
    for b in range(5):
        assert np.array_equal(dec[b], data[b])


def test_decode_rejects_bad_indices():
    coder = make_erasure_coder("cpu", 4, 2)
    with pytest.raises(ValueError):
        coder.decode([0], np.zeros((1, 8), dtype=np.uint8))
    with pytest.raises(ValueError):
        coder.decode([1, 1], np.zeros((2, 8), dtype=np.uint8))


class TestPayload:
    def test_roundtrip(self):
        payload = bytes(rng.integers(0, 256, 1000, dtype=np.uint8))
        m = split_payload(payload, k=5)
        assert m.shape[0] == 5 and m.shape[1] % 128 == 0
        assert join_payload(m) == payload

    def test_empty_payload(self):
        m = split_payload(b"", k=3)
        assert join_payload(m) == b""

    def test_corrupt_length_rejected(self):
        m = split_payload(b"hello", k=2)
        m[0, :4] = 255
        with pytest.raises(ValueError):
            join_payload(m)

    def test_full_rbc_flow(self):
        """split -> encode -> erase -> decode -> join, both backends."""
        n, f = 7, 2
        k = n - 2 * f
        payload = bytes(rng.integers(0, 256, 4096, dtype=np.uint8))
        data = split_payload(payload, k)
        for backend in ("cpu", "tpu"):
            coder = make_erasure_coder(backend, n, k)
            shards = coder.encode(data)
            survivors = [1, 3, 6]  # any k of n
            rec = coder.decode(survivors, shards[survivors])
            assert join_payload(rec) == payload
