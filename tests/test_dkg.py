"""DKG (ops/dkg.py): threshold keys without the trusted dealer.

The output must be a drop-in for ops.tpke.deal's (pub, shares): TPKE
encrypt/decrypt, the common coin, and a full SimulatedCluster epoch
all run on DKG-generated keys."""

import pytest

from cleisthenes_tpu.ops import dkg, tpke
from cleisthenes_tpu.ops.coin import CommonCoin


def test_dkg_keys_reconstruct_and_decrypt():
    pub, shares, qualified = dkg.run_dkg(n=5, threshold=3, seed=7)
    assert qualified == [1, 2, 3, 4, 5]
    # verification keys really are g^{x_j}
    gp = pub.group
    for sh in shares:
        assert pow(gp.g, sh.value, gp.p) == pub.verification_keys[sh.index - 1]
    # TPKE end to end on the DKG key set
    svc = tpke.Tpke(pub)
    ct = svc.encrypt(b"no dealer was harmed in the making of this key")
    dec = [svc.dec_share(sh, ct) for sh in shares[:3]]
    assert all(svc.verify_dec_shares(ct, dec))
    assert (
        svc.combine(ct, dec)
        == b"no dealer was harmed in the making of this key"
    )
    # subset independence: any t shares combine to the same plaintext
    dec2 = [svc.dec_share(sh, ct) for sh in shares[2:]]
    assert svc.combine(ct, dec2) == svc.combine(ct, dec)


def test_dkg_coin_tosses_agree():
    pub, shares, _ = dkg.run_dkg(n=4, threshold=2, seed=9)
    coin = CommonCoin(pub)
    cid = b"dkg-coin|0"
    sh = [coin.share(s, cid) for s in shares]
    assert all(coin.verify_shares(cid, sh))
    t1 = coin.toss(cid, sh[:2])
    t2 = coin.toss(cid, sh[2:])
    assert t1 == t2  # any threshold subset yields the network bit


def test_dkg_disqualifies_corrupt_dealer():
    pub, shares, qualified = dkg.run_dkg(
        n=5, threshold=3, seed=11, corrupt_dealers=[4]
    )
    assert qualified == [1, 2, 3, 5]
    svc = tpke.Tpke(pub)
    ct = svc.encrypt(b"qualified-set key still works")
    dec = [svc.dec_share(sh, ct) for sh in shares[:3]]
    assert svc.combine(ct, dec) == b"qualified-set key still works"


def test_dkg_too_many_corrupt_dealers_fails_loudly():
    with pytest.raises(RuntimeError):
        dkg.run_dkg(n=3, threshold=3, seed=2, corrupt_dealers=[1])


def test_dkg_share_verification_rejects_tampering():
    d = dkg.DkgDealing(1, 4, 2, seed=5)
    commits = d.commitments()
    good = d.share_for(2)
    ok = dkg.verify_dealer_shares(
        [(commits, 2, good), (commits, 2, good + 1), (commits, 3, good)]
    )
    assert ok == [True, False, False]  # wrong value / wrong receiver


def test_cluster_runs_on_dkg_keys():
    """Full HBBFT epoch over the in-proc transport with every
    threshold key DKG-generated (no dealer anywhere): setup_keys'
    output shape rebuilt from run_dkg results."""
    from cleisthenes_tpu.config import Config
    from cleisthenes_tpu.protocol.cluster import SimulatedCluster
    from cleisthenes_tpu.protocol.honeybadger import NodeKeys, setup_keys

    n = 4
    cfg = Config(n=n, batch_size=16)
    tpke_pub, tpke_shares, _ = dkg.run_dkg(
        n=n, threshold=cfg.decryption_threshold, seed=21
    )
    coin_pub, coin_shares, _ = dkg.run_dkg(
        n=n, threshold=cfg.f + 1, seed=22
    )
    cluster = SimulatedCluster(n=n, batch_size=16, seed=3, key_seed=33)
    ids = cluster.ids
    dealer = setup_keys(cfg, ids, seed=33)  # only for the MAC keys
    # swap the dealer keys for the DKG keys before any traffic
    for i, nid in enumerate(ids):
        hb = cluster.nodes[nid]
        hb.keys = NodeKeys(
            tpke_pub=tpke_pub,
            tpke_share=tpke_shares[i],
            coin_pub=coin_pub,
            coin_share=coin_shares[i],
            mac_keys=dealer[nid].mac_keys,
        )
        hb.tpke = hb.crypto.tpke(tpke_pub)
        hb.coin = hb.crypto.coin(coin_pub)
    for i in range(32):
        cluster.submit(b"dkg-tx-%02d" % i)
    cluster.run_epochs()
    hist = {
        tuple(tuple(sorted(b.tx_list())) for b in cluster.committed(nid))
        for nid in ids
    }
    assert len(hist) == 1
    assert sum(len(b) for b in cluster.committed()) == 32


def test_non_subgroup_commitment_disqualifies_dealer():
    """A commitment with an order-2 component must disqualify its
    dealer deterministically BEFORE exponent arithmetic — otherwise
    the mod-q-reduced verification equation evaluates inconsistently
    across receivers and honest nodes' qualified sets diverge."""
    from cleisthenes_tpu.ops.modmath import DEFAULT_GROUP

    gp = DEFAULT_GROUP
    d = dkg.DkgDealing(1, 4, 2, seed=5)
    good = d.commitments()
    # p-1 has order 2: not in the QR subgroup
    assert dkg.validate_commitments([good, [good[0], gp.p - 1]]) == [
        True,
        False,
    ]
    # 0 and 1 are rejected too (identity/degenerate)
    assert dkg.validate_commitments([[1, good[1]], [0, good[1]]]) == [
        False,
        False,
    ]


# -- GJKR two-phase properties (round 4) --------------------------------


def test_gjkr_pedersen_generator_in_subgroup():
    from cleisthenes_tpu.ops.modmath import DEFAULT_GROUP

    gp = DEFAULT_GROUP
    h = dkg.pedersen_generator(gp)
    assert 1 < h < gp.p and h != gp.g
    assert pow(h, gp.q, gp.p) == 1  # order-q element


def test_gjkr_phase1_broadcast_hides_the_secret():
    """Pedersen commitments are not the Feldman ones: the phase-1
    broadcast must not expose g^{a_k} (that exposure is exactly the
    Joint-Feldman rushing-bias channel)."""
    d = dkg.PedersenDealing(1, 4, 3, seed=5)
    ped = d.pedersen_commitments()
    feld = d.commitments()
    assert all(e != a for e, a in zip(ped, feld))
    # and the pair verification really binds both polynomials
    s, s2 = d.share_pair_for(2)
    ok = dkg.verify_pedersen_shares(
        [(ped, 2, s, s2), (ped, 2, s + 1, s2), (ped, 2, s, s2 + 1)]
    )
    assert ok == [True, False, False]


def test_gjkr_rushing_adversary_cannot_move_the_key():
    """THE regression the two-phase structure exists for: once phase
    one fixes Q, nothing the adversary does with its remaining moves
    (its phase-2 opening — the only move made after seeing anything
    secret-dependent) changes the key.  A phase-2 cheater is
    reconstructed, stays in Q, and the final public state is
    IDENTICAL to the all-honest run."""
    honest_pub, honest_shares, honest_q = dkg.run_dkg(
        n=5, threshold=3, seed=13
    )
    pub, shares, qualified = dkg.run_dkg(
        n=5, threshold=3, seed=13, phase2_cheaters=[5]
    )
    assert qualified == honest_q == [1, 2, 3, 4, 5]  # NOT disqualified
    assert pub == honest_pub  # master key and all vks unmoved
    assert [s.value for s in shares] == [s.value for s in honest_shares]
    # and the reconstructed-key system still decrypts end to end
    svc = tpke.Tpke(pub)
    ct = svc.encrypt(b"phase-2 abort moves nothing")
    dec = [svc.dec_share(sh, ct) for sh in shares[1:4]]
    assert svc.combine(ct, dec) == b"phase-2 abort moves nothing"


def test_gjkr_false_accuser_cannot_split_q():
    """A Byzantine receiver complains against every dealer; each
    honest dealer reveals the disputed pair, every node checks the
    reveal against the broadcast commitments, and the qualified set is
    unchanged — slander cannot desynchronize Q (the agreement break
    ADVICE.md round 3 flagged for unjustified complaint handling)."""
    honest_pub, _, _ = dkg.run_dkg(n=5, threshold=3, seed=17)
    pub, shares, qualified = dkg.run_dkg(
        n=5, threshold=3, seed=17, false_accusers=[2]
    )
    assert qualified == [1, 2, 3, 4, 5]
    assert pub == honest_pub


def test_gjkr_corrupt_dealer_plus_slander_plus_phase2_abort():
    """All three adversaries at once: dealer 4 cheats in phase 1 (and
    doubles down on reveal -> disqualified), receiver 2 slanders
    everyone (ignored), dealer 5 aborts phase 2 (reconstructed)."""
    pub, shares, qualified = dkg.run_dkg(
        n=6,
        threshold=3,
        seed=19,
        corrupt_dealers=[4],
        false_accusers=[2],
        phase2_cheaters=[5],
    )
    assert qualified == [1, 2, 3, 5, 6]
    gp = pub.group
    for sh in shares:
        assert pow(gp.g, sh.value, gp.p) == pub.verification_keys[sh.index - 1]
    svc = tpke.Tpke(pub)
    ct = svc.encrypt(b"three adversaries, one key")
    dec = [svc.dec_share(sh, ct) for sh in shares[:3]]
    assert all(svc.verify_dec_shares(ct, dec))
    assert svc.combine(ct, dec) == b"three adversaries, one key"


def test_gjkr_wrong_length_opening_reconstructed():
    """A phase-2 opening with t-1 entries must hit the length guard
    and be reconstructed like any bad opening — NOT desynchronize the
    flattened exponent batches (advisor r4: the deployment template
    must be safe to copy).  Outcome is byte-identical to honest."""
    honest_pub, honest_shares, honest_q = dkg.run_dkg(
        n=5, threshold=3, seed=23
    )
    pub, shares, qualified = dkg.run_dkg(
        n=5, threshold=3, seed=23, phase2_short_openers=[2]
    )
    assert qualified == honest_q == [1, 2, 3, 4, 5]
    assert pub == honest_pub
    assert [s.value for s in shares] == [s.value for s in honest_shares]


def test_gjkr_group384_xla_matches_cpu(jax_cpu_devices, monkeypatch):
    """The whole two-phase DKG under the production-width GROUP384 on
    the XLA engine, byte-identical to the cpu backend (round-4 verdict
    item 5: the protocol actually RUNS on the wide path).  Host
    delegation is pinned off: the tiny n=4 batches sit below
    WIDE_FLOORS[(12,32)]=256 and would otherwise route to the host,
    making the 'tpu' side python pow vs python pow."""
    from cleisthenes_tpu.ops.modmath import GROUP384, ModEngine

    monkeypatch.setattr(ModEngine, "host_delegation", False)

    pub_c, shares_c, q_c = dkg.run_dkg(
        n=4, threshold=2, seed=29, group=GROUP384, backend="cpu"
    )
    pub_t, shares_t, q_t = dkg.run_dkg(
        n=4, threshold=2, seed=29, group=GROUP384, backend="tpu"
    )
    assert q_c == q_t and pub_c == pub_t
    assert [s.value for s in shares_c] == [s.value for s in shares_t]
    svc = tpke.Tpke(pub_t)
    ct = svc.encrypt(b"wide-group dkg end to end")
    dec = [svc.dec_share(sh, ct) for sh in shares_t[:2]]
    assert svc.combine(ct, dec) == b"wide-group dkg end to end"
