"""DKG (ops/dkg.py): threshold keys without the trusted dealer.

The output must be a drop-in for ops.tpke.deal's (pub, shares): TPKE
encrypt/decrypt, the common coin, and a full SimulatedCluster epoch
all run on DKG-generated keys."""

import pytest

from cleisthenes_tpu.ops import dkg, tpke
from cleisthenes_tpu.ops.coin import CommonCoin


def test_dkg_keys_reconstruct_and_decrypt():
    pub, shares, qualified = dkg.run_dkg(n=5, threshold=3, seed=7)
    assert qualified == [1, 2, 3, 4, 5]
    # verification keys really are g^{x_j}
    gp = pub.group
    for sh in shares:
        assert pow(gp.g, sh.value, gp.p) == pub.verification_keys[sh.index - 1]
    # TPKE end to end on the DKG key set
    svc = tpke.Tpke(pub)
    ct = svc.encrypt(b"no dealer was harmed in the making of this key")
    dec = [svc.dec_share(sh, ct) for sh in shares[:3]]
    assert all(svc.verify_dec_shares(ct, dec))
    assert (
        svc.combine(ct, dec)
        == b"no dealer was harmed in the making of this key"
    )
    # subset independence: any t shares combine to the same plaintext
    dec2 = [svc.dec_share(sh, ct) for sh in shares[2:]]
    assert svc.combine(ct, dec2) == svc.combine(ct, dec)


def test_dkg_coin_tosses_agree():
    pub, shares, _ = dkg.run_dkg(n=4, threshold=2, seed=9)
    coin = CommonCoin(pub)
    cid = b"dkg-coin|0"
    sh = [coin.share(s, cid) for s in shares]
    assert all(coin.verify_shares(cid, sh))
    t1 = coin.toss(cid, sh[:2])
    t2 = coin.toss(cid, sh[2:])
    assert t1 == t2  # any threshold subset yields the network bit


def test_dkg_disqualifies_corrupt_dealer():
    pub, shares, qualified = dkg.run_dkg(
        n=5, threshold=3, seed=11, corrupt_dealers=[4]
    )
    assert qualified == [1, 2, 3, 5]
    svc = tpke.Tpke(pub)
    ct = svc.encrypt(b"qualified-set key still works")
    dec = [svc.dec_share(sh, ct) for sh in shares[:3]]
    assert svc.combine(ct, dec) == b"qualified-set key still works"


def test_dkg_too_many_corrupt_dealers_fails_loudly():
    with pytest.raises(RuntimeError):
        dkg.run_dkg(n=3, threshold=3, seed=2, corrupt_dealers=[1])


def test_dkg_share_verification_rejects_tampering():
    d = dkg.DkgDealing(1, 4, 2, seed=5)
    commits = d.commitments()
    good = d.share_for(2)
    ok = dkg.verify_dealer_shares(
        [(commits, 2, good), (commits, 2, good + 1), (commits, 3, good)]
    )
    assert ok == [True, False, False]  # wrong value / wrong receiver


def test_cluster_runs_on_dkg_keys():
    """Full HBBFT epoch over the in-proc transport with every
    threshold key DKG-generated (no dealer anywhere): setup_keys'
    output shape rebuilt from run_dkg results."""
    from cleisthenes_tpu.config import Config
    from cleisthenes_tpu.protocol.cluster import SimulatedCluster
    from cleisthenes_tpu.protocol.honeybadger import NodeKeys, setup_keys

    n = 4
    cfg = Config(n=n, batch_size=16)
    tpke_pub, tpke_shares, _ = dkg.run_dkg(
        n=n, threshold=cfg.decryption_threshold, seed=21
    )
    coin_pub, coin_shares, _ = dkg.run_dkg(
        n=n, threshold=cfg.f + 1, seed=22
    )
    cluster = SimulatedCluster(n=n, batch_size=16, seed=3, key_seed=33)
    ids = cluster.ids
    dealer = setup_keys(cfg, ids, seed=33)  # only for the MAC keys
    # swap the dealer keys for the DKG keys before any traffic
    for i, nid in enumerate(ids):
        hb = cluster.nodes[nid]
        hb.keys = NodeKeys(
            tpke_pub=tpke_pub,
            tpke_share=tpke_shares[i],
            coin_pub=coin_pub,
            coin_share=coin_shares[i],
            mac_keys=dealer[nid].mac_keys,
        )
        hb.tpke = hb.crypto.tpke(tpke_pub)
        hb.coin = hb.crypto.coin(coin_pub)
    for i in range(32):
        cluster.submit(b"dkg-tx-%02d" % i)
    cluster.run_epochs()
    hist = {
        tuple(tuple(sorted(b.tx_list())) for b in cluster.committed(nid))
        for nid in ids
    }
    assert len(hist) == 1
    assert sum(len(b) for b in cluster.committed()) == 32


def test_non_subgroup_commitment_disqualifies_dealer():
    """A commitment with an order-2 component must disqualify its
    dealer deterministically BEFORE exponent arithmetic — otherwise
    the mod-q-reduced verification equation evaluates inconsistently
    across receivers and honest nodes' qualified sets diverge."""
    from cleisthenes_tpu.ops.modmath import DEFAULT_GROUP

    gp = DEFAULT_GROUP
    d = dkg.DkgDealing(1, 4, 2, seed=5)
    good = d.commitments()
    # p-1 has order 2: not in the QR subgroup
    assert dkg.validate_commitments([good, [good[0], gp.p - 1]]) == [
        True,
        False,
    ]
    # 0 and 1 are rejected too (identity/degenerate)
    assert dkg.validate_commitments([[1, good[1]], [0, good[1]]]) == [
        False,
        False,
    ]
