"""GF(2^16) Reed-Solomon (ops/gf65536.py, ops/rs16.py): rosters past
the GF(2^8) 256-shard ceiling (the reference's own dependency limit)."""

import numpy as np
import pytest

from cleisthenes_tpu.ops import gf65536 as gf
from cleisthenes_tpu.ops.rs16 import Cpu16ErasureCoder, Xla16ErasureCoder


def test_field_axioms_sampled():
    rng = np.random.default_rng(3)
    for _ in range(200):
        a, b, c = (int(x) for x in rng.integers(1, gf.ORDER, 3))
        assert gf.gf_mul(a, gf.gf_inv(a)) == 1
        assert gf.gf_mul(a, b) == gf.gf_mul(b, a)
        assert gf.gf_mul(a, gf.gf_mul(b, c)) == gf.gf_mul(gf.gf_mul(a, b), c)
        # distributivity over xor (field addition)
        assert gf.gf_mul(a, b ^ c) == gf.gf_mul(a, b) ^ gf.gf_mul(a, c)


def test_mul_vec_matches_scalar():
    rng = np.random.default_rng(4)
    a = rng.integers(0, gf.ORDER, 64).astype(np.uint16)
    b = rng.integers(0, gf.ORDER, 64).astype(np.uint16)
    got = gf.gf_mul_vec(a, b)
    for i in range(64):
        assert int(got[i]) == gf.gf_mul(int(a[i]), int(b[i]))


def test_cpu16_roundtrip_any_k_subset():
    rng = np.random.default_rng(5)
    n, k, L = 24, 9, 96
    coder = Cpu16ErasureCoder(n, k)
    data = rng.integers(0, 256, size=(k, L), dtype=np.uint8)
    full = coder.encode(data)
    assert np.array_equal(full[:k], data)  # systematic
    for _ in range(5):
        pick = sorted(rng.choice(n, size=k, replace=False).tolist())
        assert np.array_equal(coder.decode(pick, full[pick]), data)


def test_xla16_matches_cpu16():
    rng = np.random.default_rng(6)
    n, k, L = 20, 7, 64
    cpu = Cpu16ErasureCoder(n, k)
    xla = Xla16ErasureCoder(n, k)
    batch = rng.integers(0, 256, size=(6, k, L), dtype=np.uint8)
    full = xla.encode_batch(batch)
    assert np.array_equal(full, np.stack([cpu.encode(b) for b in batch]))
    pick = [19, 17, 11, 7, 5, 3, 0]
    idx = np.tile(np.array(pick), (6, 1))
    assert np.array_equal(
        xla.decode_batch(idx, full[:, pick, :]), batch
    )


def test_n512_roster_roundtrip():
    """512 distinct shard indices — impossible in GF(2^8)."""
    rng = np.random.default_rng(7)
    n, k = 512, 172
    coder = Cpu16ErasureCoder(n, k)
    data = rng.integers(0, 256, size=(k, 8), dtype=np.uint8)
    full = coder.encode(data)
    surv = list(range(n - k, n))  # parity-heavy survivor set
    assert np.array_equal(coder.decode(surv, full[surv]), data)


def test_factory_selects_field_by_n():
    from cleisthenes_tpu.ops.backend import make_erasure_coder

    assert make_erasure_coder("cpu", 512, 172).MAX_N == gf.ORDER
    assert make_erasure_coder("tpu", 300, 100).MAX_N == gf.ORDER
    assert make_erasure_coder("cpu", 64, 22).MAX_N == 256


def test_odd_shard_length_rejected():
    coder = Cpu16ErasureCoder(8, 3)
    with pytest.raises(ValueError):
        coder.encode(np.zeros((3, 7), dtype=np.uint8))
