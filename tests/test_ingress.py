"""Client ingress plane (transport/ingress.py), end to end (ISSUE 18).

The surface under test is the production admission/subscription path:
encoded client frames -> IngressPlane -> fee-priority mempool ->
TxQueue -> settled batches -> subscription feeds.  The in-proc twin
(SimulatedCluster.ingress) and the real gRPC mount on ValidatorHost
run the IDENTICAL plane code, so the channel-transport tests here and
the socket round-trip exercise one code path.

Contract: explicit acks (OK/DUPLICATE/REJECTED/RETRY_AFTER) carrying
the admitting node's two commit frontiers; dedup coordinated across
ingress admission AND settle time; subscribe(from_epoch) replays
committed history then follows the live settled tail with no gap and
no duplicate at the seam; the whole plane is a pure function of the
seeds (cross-PYTHONHASHSEED subprocess replay).
"""

from __future__ import annotations

import pathlib
import subprocess
import sys
import threading

from cleisthenes_tpu.config import Config
from cleisthenes_tpu.core.ledger import encode_batch_body
from cleisthenes_tpu.core.mempool import MAX_TX_BYTES
from cleisthenes_tpu.protocol.cluster import SimulatedCluster
from cleisthenes_tpu.protocol.honeybadger import setup_keys
from cleisthenes_tpu.transport.host import ValidatorHost
from cleisthenes_tpu.transport.ingress import IngressGrpcClient
from cleisthenes_tpu.transport.message import IngressStatus

REPO = pathlib.Path(__file__).resolve().parent.parent


def _ingress_cluster(*, n: int = 4, seed: int = 7, capacity: int = 64,
                     client_cap: int = 64) -> SimulatedCluster:
    return SimulatedCluster(
        config=Config(
            n=n,
            batch_size=8,
            seed=seed,
            mempool_capacity=capacity,
            mempool_client_cap=client_cap,
        ),
        seed=seed,
        key_seed=11,
        auto_propose=False,
    )


def test_submit_ack_carries_frontiers_and_settles_once():
    """An OK ack carries the admitting node's ordered/settled
    frontiers; the tx settles exactly once on every node."""
    cluster = _ingress_cluster()
    gate = cluster.ingress()
    ack = gate.submit("alice", 0, 5, b"tx-hello")
    assert IngressStatus(ack.status) is IngressStatus.OK
    assert (ack.client_id, ack.nonce) == ("alice", 0)
    assert ack.ordered_epoch == 0 and ack.settled_epoch == 0
    cluster.run_until_drained()
    assert cluster.assert_agreement() >= 1
    for nid in cluster.ids:
        settled = [
            tx
            for b in cluster.nodes[nid].committed_batches
            for tx in b.tx_list()
        ]
        assert settled.count(b"tx-hello") == 1
    # the frontiers in a fresh ack moved with the commit
    ack2 = gate.submit("alice", 1, 5, b"tx-second")
    assert ack2.settled_epoch >= 1


def test_dedup_across_ingress_and_settle():
    """One tx, three resubmit points — while pending, from another
    client, and AFTER settlement — all ack DUPLICATE; the ledger
    carries the bytes exactly once."""
    cluster = _ingress_cluster()
    gate = cluster.ingress()
    assert IngressStatus(
        gate.submit("c0", 0, 5, b"tx-once").status
    ) is IngressStatus.OK
    # pending: same bytes, same client / different client
    for client, nonce in (("c0", 1), ("c1", 0)):
        dup = gate.submit(client, nonce, 9, b"tx-once")
        assert IngressStatus(dup.status) is IngressStatus.DUPLICATE
    cluster.run_until_drained()
    # settled: the settle-time seen-ring still answers
    late = gate.submit("c2", 0, 99, b"tx-once")
    assert IngressStatus(late.status) is IngressStatus.DUPLICATE
    settled = [
        tx
        for b in cluster.nodes[cluster.ids[0]].committed_batches
        for tx in b.tx_list()
    ]
    assert settled.count(b"tx-once") == 1
    assert cluster.assert_agreement() >= 1


def test_backpressure_rejected_and_retry_after_acks():
    """Admission failures are explicit acks, never silent drops:
    malformed -> REJECTED; per-client cap and a full pool the bid
    does not outrank -> RETRY_AFTER with a backoff hint."""
    cluster = _ingress_cluster(capacity=2, client_cap=2)
    gate = cluster.ingress()
    bad = gate.submit("c0", 0, 1, b"x" * (MAX_TX_BYTES + 1))
    assert IngressStatus(bad.status) is IngressStatus.REJECTED
    assert IngressStatus(
        gate.submit("c0", 1, 10, b"tx-a").status
    ) is IngressStatus.OK
    assert IngressStatus(
        gate.submit("c0", 2, 10, b"tx-b").status
    ) is IngressStatus.OK
    # per-client cap (2 live) trips first for c0
    v = gate.submit("c0", 3, 10, b"tx-c")
    assert IngressStatus(v.status) is IngressStatus.RETRY_AFTER
    assert v.retry_after_ms > 0
    # global capacity (2) with a NON-outranking fee trips for c1
    v2 = gate.submit("c1", 0, 1, b"tx-d")
    assert IngressStatus(v2.status) is IngressStatus.RETRY_AFTER
    # ...and an outranking fee evicts instead of backing off
    v3 = gate.submit("c1", 1, 99, b"tx-e")
    assert IngressStatus(v3.status) is IngressStatus.OK


def test_subscribe_replays_then_follows_live_tail():
    """subscribe(from_epoch) replays committed history from the WAL
    state and then streams fresh settles, gap- and duplicate-free
    across the replay/live seam."""
    cluster = _ingress_cluster()
    gate = cluster.ingress()
    for i in range(3):
        gate.submit("c0", i, 5, b"warm-%02d" % i)
        cluster.run_until_drained()
    node = cluster.nodes[cluster.ids[0]]
    depth = len(node.committed_batches)
    assert depth >= 3
    feed = gate.subscribe(1)  # skip epoch 0: replay honors from_epoch
    replayed = []
    while True:
        b = gate.next_batch(feed, timeout=0.05)
        if b is None:
            break
        replayed.append(b)
    assert [b.epoch for b in replayed] == list(range(1, depth))
    for b in replayed:
        assert b.body == encode_batch_body(
            b.epoch, node.committed_batches[b.epoch]
        )
    # live tail: a new settle lands on the SAME feed, next epoch, once
    gate.submit("c0", 99, 5, b"tail-tx")
    cluster.run_until_drained()
    tail = gate.next_batch(feed, timeout=1.0)
    assert tail is not None and tail.epoch == depth
    assert b"tail-tx" in encode_batch_body(
        tail.epoch, node.committed_batches[tail.epoch]
    )
    assert gate.next_batch(feed, timeout=0.05) is None
    feed.close()


def test_grpc_roundtrip_on_real_validator_host():
    """The full socket path: 4 ValidatorHosts with ingress mounted,
    submits pipelined over real gRPC streams (acks in order), commits
    driven by the admission kick, and a gRPC subscriber streaming the
    settled batch."""
    n = 4
    cfg = Config(
        n=n,
        batch_size=8,
        ingress_port=0,  # ephemeral: each host reports its bound port
        mempool_capacity=64,
    )
    ids = [f"node{i}" for i in range(n)]
    keys = setup_keys(cfg, ids, seed=55)
    hosts = {i: ValidatorHost(cfg, i, ids, keys[i]) for i in ids}
    clients = []
    try:
        addrs = {i: h.listen() for i, h in hosts.items()}
        threads = [
            threading.Thread(target=h.connect, args=(addrs,))
            for h in hosts.values()
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=15)
        txs = [b"ingress-tx-%02d" % i for i in range(2 * n)]
        # every node admits its share over its own ingress socket, so
        # every node proposes (the on_admitted kick starts its epoch)
        for rank, nid in enumerate(ids):
            c = IngressGrpcClient(
                f"127.0.0.1:{hosts[nid].ingress_server.port}"
            )
            clients.append(c)
            batch = [
                (f"cli-{i % 3}", i, 1 + i % 5, tx)
                for i, tx in enumerate(txs)
                if i % n == rank
            ]
            acks = c.submit_many(batch)
            assert len(acks) == len(batch)
            assert all(
                IngressStatus(a.status) is IngressStatus.OK for a in acks
            )
            # acks come back in submit order (pipelined one stream)
            assert [a.nonce for a in acks] == [s[1] for s in batch]
        first = {i: h.wait_commit(timeout=60) for i, h in hosts.items()}
        bodies = {
            encode_batch_body(e, b) for e, b in first.values()
        }
        assert len(bodies) == 1
        committed = first[ids[0]][1].tx_list()
        assert set(committed) <= set(txs) and len(committed) > 0
        # subscription over the same socket streams that batch
        sub = clients[0].subscribe(0, timeout=30)
        streamed = next(sub)
        assert streamed.epoch == first[ids[0]][0]
        assert streamed.body == bodies.pop()
    finally:
        for c in clients:
            c.close()
        for h in hosts.values():
            h.stop()


# Runs the seeded loadgen (tiny band) through the in-proc ingress
# plane and prints the settled-ledger digest — the exact order-
# independent digest the acceptance harness compares across arms.
_DRIVER = r"""
from tools.loadgen import build_schedule, run_arm
sched = build_schedule(clients=300, txs=300, ticks=6, seed=9)
arm = run_arm(sched, depth=2, n=4, batch=64, seed=9)
print("LEDGER_DIGEST=%s settled=%d" % (arm["ledger_digest"],
                                       arm["settled"]))
"""


def test_ingress_plane_identical_across_hash_seeds():
    """Cross-PYTHONHASHSEED replay: the mempool's seeded tiebreak and
    the plane's admission path must leak no hash()-order, so two
    interpreters with different hash seeds settle byte-identical
    ledgers for the same client schedule."""
    digests = set()
    for hash_seed in ("0", "1"):
        proc = subprocess.run(
            [sys.executable, "-c", _DRIVER],
            capture_output=True,
            text=True,
            cwd=str(REPO),
            env={
                "PYTHONHASHSEED": hash_seed,
                "JAX_PLATFORMS": "cpu",
                "PATH": "/usr/bin:/bin",
            },
            timeout=600,
        )
        assert proc.returncode == 0, proc.stderr
        line = [
            ln for ln in proc.stdout.splitlines()
            if ln.startswith("LEDGER_DIGEST=")
        ][0]
        digests.add(line)
    assert len(digests) == 1, f"hash-seed-dependent ledger: {digests}"
