"""gRPC transport tests: real-network loopback round-trips (the
reference's comm_test.go:27-96 pattern) and full HBBFT over localhost
gRPC with MAC-authenticated envelopes."""

import queue
import threading
import time

import pytest

from cleisthenes_tpu.config import Config
from cleisthenes_tpu.protocol.honeybadger import setup_keys
from cleisthenes_tpu.transport.base import HmacAuthenticator
from cleisthenes_tpu.transport.grpc_net import (
    DialOpts,
    GrpcClient,
    GrpcServer,
)
from cleisthenes_tpu.transport.host import ValidatorHost
from cleisthenes_tpu.transport.message import (
    Message,
    RbcPayload,
    RbcType,
)


class CollectingHandler:
    def __init__(self):
        self.inbox = queue.Queue()

    def serve_request(self, msg):
        self.inbox.put(msg)


def _val_msg(sender, note=b"shard"):
    return Message(
        sender_id=sender,
        timestamp=time.time(),
        payload=RbcPayload(
            type=RbcType.VAL,
            proposer=sender,
            epoch=0,
            root_hash=b"\x07" * 32,
            branch=(b"\x01" * 32,),
            shard=note,
            shard_index=0,
        ),
    )


def test_grpc_loopback_roundtrip():
    """Server accepts, client sends VAL, handler receives it intact
    (comm_test.go:27-96 without the 1s bootstrap sleep)."""
    handler = CollectingHandler()
    server = GrpcServer("127.0.0.1:0")
    server.on_conn(lambda conn: (conn.handle(handler), conn.start()))
    server.listen()
    try:
        client = GrpcClient()
        conn = client.dial(DialOpts(f"127.0.0.1:{server.port}"))
        conn.start()
        sent = _val_msg("alice")
        acks = []
        conn.send(sent, on_success=lambda m: acks.append(m))
        got = handler.inbox.get(timeout=5)
        assert got.sender_id == "alice"
        assert got.payload == sent.payload
        assert acks == [sent]
        conn.close()
        client.close()
    finally:
        server.stop()


def test_grpc_bidirectional_stream():
    """The server can push frames back down the same stream."""
    handler = CollectingHandler()
    server_conns = []
    server = GrpcServer("127.0.0.1:0")

    def on_conn(conn):
        conn.handle(handler)
        server_conns.append(conn)  # before start(): the reader thread
        conn.start()               # may dispatch immediately

    server.on_conn(on_conn)
    server.listen()
    try:
        client_handler = CollectingHandler()
        client = GrpcClient()
        conn = client.dial(DialOpts(f"127.0.0.1:{server.port}"))
        conn.handle(client_handler)
        conn.start()
        conn.send(_val_msg("alice", b"ping"))
        handler.inbox.get(timeout=5)
        server_conns[0].send(_val_msg("server", b"pong"))
        got = client_handler.inbox.get(timeout=5)
        assert got.payload.shard == b"pong"
        conn.close()
        client.close()
    finally:
        server.stop()


def test_grpc_mac_rejects_forged_sender():
    """A frame MAC'd with the wrong key must be dropped (the
    implemented conn.go:134-137)."""
    master = b"grpc-test-master"
    roster = ["server", "bob", "eve"]
    handler = CollectingHandler()
    server = GrpcServer(
        "127.0.0.1:0", HmacAuthenticator.derive(master, "server", roster)
    )
    conns = []
    server.on_conn(lambda c: (c.handle(handler), c.start(), conns.append(c)))
    server.listen()
    try:
        # eve signs with a key derived from a DIFFERENT master secret
        eve = GrpcClient(HmacAuthenticator.derive(b"wrong-master", "eve", roster))
        conn = eve.dial(DialOpts(f"127.0.0.1:{server.port}", conn_id="server"))
        conn.start()
        conn.send(_val_msg("eve"))
        # honest bob gets through on the same server
        bob = GrpcClient(HmacAuthenticator.derive(master, "bob", roster))
        bconn = bob.dial(DialOpts(f"127.0.0.1:{server.port}", conn_id="server"))
        bconn.start()
        bconn.send(_val_msg("bob"))
        got = handler.inbox.get(timeout=5)
        assert got.sender_id == "bob"
        assert handler.inbox.empty()
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            if sum(c.rejected for c in conns) >= 1:
                break
            time.sleep(0.02)
        assert sum(c.rejected for c in conns) >= 1
        conn.close()
        bconn.close()
        eve.close()
        bob.close()
    finally:
        server.stop()


def test_grpc_dial_timeout():
    client = GrpcClient()
    with pytest.raises(Exception):
        # RFC 5737 TEST-NET address: unroutable
        client.dial(DialOpts("192.0.2.1:1", timeout_s=0.3))


@pytest.mark.parametrize("n_epochs_min", [1])
def test_hbbft_over_real_grpc_network(n_epochs_min):
    """BASELINE config 1 over real sockets: 4 validators on localhost
    gRPC commit identical batches."""
    n = 4
    cfg = Config(n=n, batch_size=8)
    ids = [f"node{i}" for i in range(n)]
    keys = setup_keys(cfg, ids, seed=55)
    hosts = {i: ValidatorHost(cfg, i, ids, keys[i]) for i in ids}
    try:
        addrs = {i: h.listen() for i, h in hosts.items()}
        threads = [
            threading.Thread(target=h.connect, args=(addrs,))
            for h in hosts.values()
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=15)
        txs = [b"grpc-tx-%02d" % i for i in range(8)]
        for i, tx in enumerate(txs):
            hosts[ids[i % n]].submit(tx)
        for h in hosts.values():
            h.propose()
        # wait for every node's first commit
        first = {i: h.wait_commit(timeout=60) for i, h in hosts.items()}
        epochs = {e for e, _ in first.values()}
        assert epochs == {0}
        lists = [b.tx_list() for _, b in first.values()]
        assert all(l == lists[0] for l in lists)
        assert set(lists[0]) <= set(txs)
        assert len(lists[0]) > 0
    finally:
        for h in hosts.values():
            h.stop()


def test_broadcaster_buffers_until_ready():
    """Outbound traffic before connect() completes must be parked and
    flushed, not dropped (peers boot concurrently)."""
    from cleisthenes_tpu.transport.base import (
        ConnectionPool,
        NullAuthenticator,
    )
    from cleisthenes_tpu.transport.host import (
        GrpcPayloadBroadcaster,
        SerialDispatcher,
    )

    sent = []

    class FakeConn:
        def __init__(self, cid):
            self._cid = cid

        def id(self):
            return self._cid

        def send_wire(self, wire):
            sent.append(("wire", self._cid))
            return True

        def send(self, msg, on_success=None, on_err=None):
            sent.append(("msg", self._cid))

    disp = SerialDispatcher()
    pool = ConnectionPool()
    out = GrpcPayloadBroadcaster("a", pool, disp, NullAuthenticator())

    msg_payload = _val_msg("a").payload
    out.broadcast(msg_payload)  # pool still empty, not ready
    out.send_to("b", msg_payload)
    assert sent == []  # nothing dropped into the void

    pool.add(FakeConn("b"))
    pool.add(FakeConn("c"))
    out.mark_ready()
    kinds = sorted(sent)
    assert ("msg", "b") in kinds  # the queued send_to flushed
    assert kinds.count(("wire", "b")) == 1 and kinds.count(("wire", "c")) == 1
    sent.clear()
    out.broadcast(msg_payload)  # post-ready goes straight through
    assert len(sent) == 2
    disp.stop()


@pytest.mark.faults
def test_host_crash_restart_catchup_with_backoff(tmp_path):
    """Crash recovery over real sockets: a host with a durable batch
    log stops mid-roster, the survivors commit an epoch without it,
    and a FRESH host restarted from the WAL on the same address
    rejoins, catches up via CATCHUP, and converges to the survivors'
    batches.  Meanwhile the survivors' redial loops must back off
    exponentially — growing delays in the health tracker's reconnect
    counters, not fixed-interval spinning."""
    n = 4
    cfg = Config(
        n=n,
        batch_size=8,
        seed=7,  # seeds the dial-jitter rng: replayable schedule
        dial_timeout_s=0.25,
        dial_retry_base_s=0.05,
        dial_retry_max_s=1.0,
    )
    ids = [f"node{i}" for i in range(n)]
    keys = setup_keys(cfg, ids, seed=77)
    victim = "node3"
    wal = str(tmp_path / "node3.log")
    hosts = {
        i: ValidatorHost(
            cfg, i, ids, keys[i],
            batch_log_path=wal if i == victim else None,
        )
        for i in ids
    }
    restarted = None
    try:
        addrs = {i: h.listen() for i, h in hosts.items()}
        threads = [
            threading.Thread(target=h.connect, args=(addrs,))
            for h in hosts.values()
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=15)
        # epoch 0 commits everywhere (the victim logs it durably)
        for i, tx in enumerate([b"pre-%02d" % i for i in range(8)]):
            hosts[ids[i % n]].submit(tx)
        for h in hosts.values():
            h.propose()
        for h in hosts.values():
            h.wait_commit(timeout=60)
        # fail-stop the victim; survivors' redial loops start backing off
        hosts[victim].stop()
        survivors = {i: h for i, h in hosts.items() if i != victim}
        # n=4 tolerates the single crash: epoch 1 commits without it
        for i, tx in enumerate([b"down-%02d" % i for i in range(9)]):
            survivors[ids[i % 3]].submit(tx)
        for h in survivors.values():
            h.propose()
        commits = {i: h.wait_commit(timeout=60) for i, h in survivors.items()}
        lists = [b.tx_list() for _, b in commits.values()]
        assert all(l == lists[0] for l in lists) and lists[0]
        time.sleep(1.0)  # let several redial attempts record their delays
        # restart from the WAL: same identity, same address, new process
        restarted = ValidatorHost(
            cfg, victim, ids, keys[victim],
            listen_addr=addrs[victim],
            batch_log_path=wal,
        )
        assert restarted.node.epoch == 1  # epoch 0 replayed from the WAL
        got = restarted.listen()
        assert got == addrs[victim]
        restarted.connect(addrs)  # fires the CATCHUP request
        # NO manual re-kicking: if a survivor's redial to us had not
        # healed when our CatchupReq arrived, its responses went into
        # the void — the heal event (peer_reconnected) must re-serve
        # our window on its own
        want = survivors[ids[0]].committed_batches()
        deadline = time.monotonic() + 30
        caught_up = None
        while time.monotonic() < deadline:
            caught_up = restarted.committed_batches()
            if len(caught_up) >= len(want):
                break
            time.sleep(0.25)
        assert caught_up is not None and len(caught_up) >= len(want)
        for e, batch in enumerate(want):
            assert caught_up[e].tx_list() == batch.tx_list()
        # backoff evidence: a survivor reconnected to the victim, and
        # its scheduled redial delays GREW (factor 2, jitter +/-25%:
        # each pre-cap delay strictly exceeds the previous one)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            snap = survivors[ids[0]].health.snapshot()[victim]
            if snap["state"] == "up" and snap["reconnects"] >= 1:
                break
            time.sleep(0.05)
        assert snap["reconnects"] >= 1, snap
        delays = snap["recent_delays_s"]
        assert len(delays) >= 2, snap
        pre_cap = [d for d in delays if d < cfg.dial_retry_max_s * 0.75]
        assert all(b > a for a, b in zip(pre_cap, pre_cap[1:])), delays
        assert max(delays) > cfg.dial_retry_base_s * 1.25, delays
    finally:
        for h in hosts.values():
            h.stop()  # double-stop of the victim is a no-op
        if restarted is not None:
            restarted.stop()


@pytest.mark.faults
def test_host_redials_lost_peer_stream():
    """A severed peer stream re-establishes via the host's backoff
    redial loop, and the protocol commits a later epoch through the
    healed connection (VERDICT round-2 weak item 8: the reference
    leaves a dropped stream dropped until process restart)."""
    n = 4
    cfg = Config(n=n, batch_size=8)
    ids = [f"node{i}" for i in range(n)]
    keys = setup_keys(cfg, ids, seed=66)
    hosts = {i: ValidatorHost(cfg, i, ids, keys[i]) for i in ids}
    try:
        addrs = {i: h.listen() for i, h in hosts.items()}
        threads = [
            threading.Thread(target=h.connect, args=(addrs,))
            for h in hosts.values()
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=15)
        # epoch 0 commits everywhere
        for i, tx in enumerate([b"pre-%02d" % i for i in range(8)]):
            hosts[ids[i % n]].submit(tx)
        for h in hosts.values():
            h.propose()
        for h in hosts.values():
            h.wait_commit(timeout=60)
        # sever node0 -> node1 and wait for the redial loop to heal it
        victim = hosts[ids[0]]
        conn = victim.pool.get(ids[1])
        assert conn is not None
        conn.close()  # fires _on_conn_lost -> background redial
        deadline = time.monotonic() + 10
        healed = None
        while time.monotonic() < deadline:
            healed = victim.pool.get(ids[1])
            if healed is not None and healed is not conn:
                break
            time.sleep(0.05)
        assert healed is not None and healed is not conn, "no redial"
        # the healed pool carries a later epoch to commitment
        for i, tx in enumerate([b"post-%02d" % i for i in range(8)]):
            hosts[ids[i % n]].submit(tx)
        for h in hosts.values():
            h.propose()
        commits = {i: h.wait_commit(timeout=60) for i, h in hosts.items()}
        lists = [b.tx_list() for _, b in commits.values()]
        assert all(l == lists[0] for l in lists) and len(lists[0]) > 0
    finally:
        for h in hosts.values():
            h.stop()
