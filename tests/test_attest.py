"""Attested sender log tests (protocol/attest.py, Config.attested_log
/ Config.reduced_quorum).

Unit layer: slot extraction semantics, vault refusal + restart
monotonicity, the authenticator's counter policy (replay, regression,
missing/forged trailers, fork evidence -> exclusion).

Cluster layer: the PR-4 Equivocator behavior mounted under
``attested_log=True`` — its per-receiver RBC lies hit the vault at the
``sign_wire_wave`` egress, ship self-incriminating ``refused=1``
stamps, and every honest receiver records the counter-fork evidence
and excludes the sender while the honest ledgers stay identical.  The
reduced-quorum (2f+1) arm rides the same plane: n=5/f=2 commits with
``quorum_large = n - f``.

Module carries the ``faults`` marker (ci.sh fault-regression stage).
"""

import pytest

from cleisthenes_tpu.config import Config
from cleisthenes_tpu.protocol.attest import (
    ATTEST_LEN,
    AttestationDirectory,
    AttestingAuthenticator,
    payload_slots,
)
from cleisthenes_tpu.protocol.byzantine import Equivocator
from cleisthenes_tpu.protocol.cluster import SimulatedCluster
from cleisthenes_tpu.transport.message import (
    BbaPayload,
    BbaType,
    BundlePayload,
    EchoBatchPayload,
    Message,
    RbcPayload,
    RbcType,
)

pytestmark = pytest.mark.faults


# ---------------------------------------------------------------------------
# unit: slots and vault
# ---------------------------------------------------------------------------


def _slots(payload):
    out = []
    payload_slots(payload, out)
    return out


def test_payload_slot_semantics():
    """Slots bind exactly the statements a correct node makes once:
    RBC roots per (epoch, proposer, type), BBA AUX/TERM values per
    round — and BVAL (legally two-valued) is NOT slotted."""
    val = RbcPayload(RbcType.VAL, "p", 3, root_hash=b"R" * 32)
    assert _slots(val) == [(("rbc", 3, "p", int(RbcType.VAL)), b"R" * 32)]
    # per-receiver branch/shard differences do NOT change the slot digest
    val2 = RbcPayload(
        RbcType.VAL, "p", 3, root_hash=b"R" * 32, shard=b"x", shard_index=1
    )
    assert _slots(val2) == _slots(val)
    aux = BbaPayload(BbaType.AUX, "p", 3, 0, True)
    assert _slots(aux) == [(("bba", 3, "p", 0, int(BbaType.AUX)), b"\x01")]
    bval = BbaPayload(BbaType.BVAL, "p", 3, 0, True)
    assert _slots(bval) == []
    batch = EchoBatchPayload(
        epoch=3, shard_index=0, proposers=("a", "b"),
        roots=(b"A" * 32, b"B" * 32), branches=((), ()), shards=(b"", b""),
    )
    assert len(_slots(batch)) == 2
    bundle = BundlePayload(items=(val, aux, bval))
    assert len(_slots(bundle)) == 2


def test_vault_refuses_forks_and_survives_restart():
    """First digest per slot wins; a different digest is refused (and
    counted) but the same digest re-attests freely.  Re-attaching —
    the process-restart path — bumps the incarnation while KEEPING the
    slot registry, so a crash cannot launder a second dealing."""
    d = AttestationDirectory()
    vault = d.attach("n0")
    a = RbcPayload(RbcType.ECHO, "p", 0, root_hash=b"A" * 32)
    b = RbcPayload(RbcType.ECHO, "p", 0, root_hash=b"B" * 32)
    assert vault.observe(a) is False
    assert vault.observe(a) is False  # same statement: fine
    assert vault.observe(b) is True  # fork: refused
    assert vault.refusals == 1
    inc1 = vault.incarnation
    vault2 = d.attach("n0")  # "restart"
    assert vault2.incarnation == inc1 + 1
    assert vault2.observe(b) is True  # registry survived the restart
    assert vault2.observe(a) is False


def _pair(directory=None):
    """Two attesting authenticators sharing one pair key."""
    d = directory or AttestationDirectory()
    key = b"k" * 32
    a = AttestingAuthenticator("a", {"b": key}, d.attach("a"))
    b = AttestingAuthenticator("b", {"a": key}, d.attach("b"))
    return a, b, d


def _msg(root=b"R" * 32, epoch=0):
    return Message(
        sender_id="a",
        timestamp=1.0,
        payload=RbcPayload(RbcType.VAL, "a", epoch, root_hash=root),
    )


def test_authenticator_counter_policy():
    """Replays, stripped trailers and forged trailer MACs are rejected
    loudly; fresh frames verify."""
    a, b, _ = _pair()
    m1 = a.sign(_msg(), "b")
    assert len(m1.attestation) == ATTEST_LEN
    assert b.verify(m1) is True
    # exact replay: the (incarnation, seq) pair was already seen
    assert b.verify(m1) is False
    assert b.attest_stats["regressions"] == 1
    # stripped trailer
    m2 = a.sign(_msg(epoch=1), "b")
    stripped = Message(m2.sender_id, m2.timestamp, m2.payload, m2.signature)
    assert b.verify(stripped) is False
    assert b.attest_stats["missing"] == 1
    # forged trailer MAC (flip one byte)
    att = bytearray(m2.attestation)
    att[-1] ^= 0x01
    forged = Message(
        m2.sender_id, m2.timestamp, m2.payload, m2.signature, bytes(att)
    )
    assert b.verify(forged) is False
    assert b.attest_stats["bad_mac"] == 1
    # the untampered original still verifies after all that
    assert b.verify(m2) is True


def test_refused_stamp_is_fork_evidence_not_a_sender_ban():
    """A refused=1 stamp — the only thing an equivocator can ship for
    a forked slot — makes the receiver record fork evidence in the
    directory and reject THAT frame.  The sender's refused=0 traffic
    must keep verifying: at n = 2f+1 the accused node's honest votes
    are load-bearing, so detection is per-statement omission plus an
    accusation, never a wholesale frame ban."""
    a, b, d = _pair()
    assert b.verify(a.sign(_msg(root=b"A" * 32), "b"))
    m_forked = a.sign(_msg(root=b"B" * 32), "b")  # vault refuses
    assert b.verify(m_forked) is False
    assert b.attest_stats["forks"] == 1
    assert b.accused_senders() == {"a"}
    assert d.accused == {"a"}
    assert d.fork_reports["a"][0][0] == "b"  # (reporter, inc, seq)
    # an honest (refused=0) frame from the accused sender still flows
    assert b.verify(a.sign(_msg(root=b"A" * 32, epoch=2), "b")) is True
    # but a second lie is rejected and tallied just like the first
    assert b.verify(a.sign(_msg(root=b"C" * 32, epoch=2), "b")) is False
    assert b.attest_stats["forks"] == 2


def test_incarnation_regression_rejected():
    """Pre-restart frames (old incarnation) replayed after a restart
    are counter regressions, not valid traffic."""
    d = AttestationDirectory()
    key = b"k" * 32
    a1 = AttestingAuthenticator("a", {"b": key}, d.attach("a"))
    old = a1.sign(_msg(), "b")
    a2 = AttestingAuthenticator("a", {"b": key}, d.attach("a"))  # restart
    b = AttestingAuthenticator("b", {"a": key}, d.attach("b"))
    assert b.verify(a2.sign(_msg(), "b")) is True  # incarnation 2
    assert b.verify(old) is False  # incarnation 1: regression
    assert b.attest_stats["regressions"] == 1


# ---------------------------------------------------------------------------
# cluster: equivocation under the attested log
# ---------------------------------------------------------------------------


def _drive(cluster, bad=(), txs=12, max_rounds=30):
    honest = [i for i in cluster.ids if i not in bad]
    for i in range(txs):
        cluster.submit(b"tx-%04d" % i, node_id=honest[i % len(honest)])
    cluster.run_until_drained(max_rounds=max_rounds, skip=bad)
    return cluster.assert_agreement(skip=bad)


def test_equivocator_detected_and_excluded_under_attested_log():
    """The tentpole contract: an Equivocator under attested_log=True
    ships self-incriminating refused=1 stamps — honest receivers
    record counter-fork evidence (the exclusion surface the reconfig
    plane evicts on), reject the lied frames, and commit identical
    ledgers (equivocation degraded to omission of the lies)."""
    bad = "node000"
    c = SimulatedCluster(
        n=4,
        batch_size=8,
        seed=13,
        config=Config(n=4, batch_size=8, attested_log=True),
        behaviors={bad: Equivocator(seed=21)},
    )
    depth = _drive(c, (bad,))
    assert depth >= 1
    assert c.behaviors[bad].rewrites > 0, "the adversary never lied"
    # the equivocator's vault refused at least one forked slot
    assert c.auths[bad].vault.refusals > 0
    # fork evidence reached the directory, against the equivocator ONLY
    assert c.attest_dir.accused == {bad}
    reporters = {rep for rep, _, _ in c.attest_dir.fork_reports[bad]}
    assert reporters and bad not in reporters
    # every reporter holds the accusation at its authenticator
    for nid in reporters:
        assert c.auths[nid].accused_senders() == {bad}
        assert c.auths[nid].attest_stats["forks"] > 0
    # and no honest node was ever accused of anything
    for nid in c.ids:
        if nid != bad:
            assert c.auths[nid].vault.refusals == 0


def test_attested_log_clean_run_has_no_evidence():
    """Baseline attested run (no adversary): trailers verify, no
    forks, no exclusions, no refusals — the plane is inert overhead."""
    c = SimulatedCluster(
        n=4,
        batch_size=8,
        seed=5,
        config=Config(n=4, batch_size=8, attested_log=True),
    )
    assert _drive(c) >= 1
    assert c.attest_dir.accused == set()
    for nid in c.ids:
        st = c.auths[nid].attest_stats
        assert st["forks"] == 0
        assert st["missing"] == 0
        assert st["bad_mac"] == 0
        assert c.auths[nid].vault.refusals == 0


def test_attested_arm_matches_plain_ledgers():
    """ARM pin: the attested_log=True arm commits the same ledger
    bytes as the attested_log=False baseline for an identical seeded
    run — the trailer is additive, never schedule-changing."""
    ledgers = {}
    for flag in (False, True):
        cfg = (
            Config(n=4, batch_size=8, attested_log=True)
            if flag
            else Config(n=4, batch_size=8, attested_log=False)
        )
        c = SimulatedCluster(n=4, batch_size=8, seed=7, config=cfg)
        assert _drive(c, txs=8) >= 1
        ledgers[flag] = [
            tuple(b.tx_list())
            for b in c.nodes[c.ids[0]].committed_batches
        ]
    assert ledgers[False] == ledgers[True]


# ---------------------------------------------------------------------------
# reduced-quorum arm
# ---------------------------------------------------------------------------


def test_reduced_quorum_requires_attested_log():
    with pytest.raises(ValueError, match="requires attested_log"):
        Config(n=5, reduced_quorum=True, attested_log=False)


def test_reduced_quorum_arithmetic():
    """n=5 carries f=2 in reduced mode (3f+1 would need n=7): the
    large quorum is n-f=3 and the erasure split is n-2f=1 data shard;
    at the baseline shape n=3f+1 the two arms agree exactly."""
    cfg = Config(n=5, attested_log=True, reduced_quorum=True)
    assert (cfg.f, cfg.quorum_large) == (2, 3)
    base = Config(n=7, reduced_quorum=False)
    red = Config(n=7, f=2, attested_log=True, reduced_quorum=True)
    assert base.quorum_large == red.quorum_large == 5  # n=3f+1: identical


def test_reduced_quorum_cluster_commits_n5():
    """An n=5 roster tolerating f=2 — impossible under 3f+1 — commits
    and agrees under the attested 2f+1 trust model."""
    c = SimulatedCluster(
        n=5,
        config=Config(
            n=5, batch_size=8, attested_log=True, reduced_quorum=True
        ),
        seed=11,
    )
    assert _drive(c, txs=10) >= 1
    committed = sum(
        len(b) for b in c.nodes[c.ids[0]].committed_batches
    )
    assert committed == 10


def test_reduced_quorum_survives_equivocator_at_full_budget():
    """n=5, f=2 reduced quorum with an equivocating member: the
    attested log converts the equivocation to omission and the
    remaining 4 >= n-f honest nodes stay live and consistent."""
    bad = "node004"
    c = SimulatedCluster(
        n=5,
        config=Config(
            n=5, batch_size=8, attested_log=True, reduced_quorum=True
        ),
        seed=17,
        behaviors={bad: Equivocator(seed=23)},
    )
    depth = _drive(c, (bad,), txs=10)
    assert depth >= 1
    assert c.behaviors[bad].rewrites > 0
    # detection fired iff the equivocator actually forked a slot that
    # reached a receiver; with per-receiver VAL/ECHO lies it must have
    assert c.attest_dir.accused == {bad}
