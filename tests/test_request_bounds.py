"""DoS bounds on the epoch catch-up buffer and config validation."""

import pytest

from cleisthenes_tpu import Config
from cleisthenes_tpu.core.request import IncomingRequestRepository


def test_far_future_epoch_dropped():
    r = IncomingRequestRepository(max_epoch_horizon=4)
    assert r.save(epoch=100, conn_id="byz", req="x", current_epoch=1) is False
    assert r.save(epoch=5, conn_id="byz", req="x", current_epoch=1) is True
    assert r.dropped == 1


def test_per_sender_cap():
    r = IncomingRequestRepository(max_per_sender=3)
    for i in range(5):
        r.save(epoch=2, conn_id="byz", req=i, current_epoch=1)
    assert len(r.find_all(2)) == 3
    assert r.dropped == 2


def test_config_rejects_nonpositive_n_and_negative_f():
    with pytest.raises(ValueError):
        Config(n=0)
    with pytest.raises(ValueError):
        Config(n=4, f=-1)


def test_past_and_current_epoch_dropped():
    r = IncomingRequestRepository()
    assert r.save(epoch=1, conn_id="c", req="x", current_epoch=1) is False
    assert r.save(epoch=0, conn_id="c", req="x", current_epoch=1) is False
    assert r.dropped == 2


def test_pop_epoch_gcs_stale():
    r = IncomingRequestRepository()
    r.save(epoch=2, conn_id="c", req="a", current_epoch=1)
    r.save(epoch=3, conn_id="c", req="b", current_epoch=1)
    r.pop_epoch(3)  # skipped past epoch 2
    assert r.find_all(2) == []
