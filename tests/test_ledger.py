"""Committed-batch log tests: durability, torn-write recovery, and
validator restart/rejoin (SURVEY.md §5.4 checkpoint/resume)."""

import os

from cleisthenes_tpu.core.batch import Batch
from cleisthenes_tpu.core.ledger import BatchLog, _encode_record
from tests.test_honeybadger import (
    assert_identical_batches,
    make_hb_network,
    push_txs,
)


def _batch(*pairs):
    return Batch(contributions={p: list(txs) for p, txs in pairs})


def test_log_roundtrip(tmp_path):
    path = str(tmp_path / "batches.log")
    log = BatchLog(path)
    b0 = _batch(("a", [b"t1", b"t2"]), ("b", [b"t3"]))
    b1 = _batch(("c", [b""]))  # empty tx allowed
    log.append(0, b0)
    log.append(1, b1)
    log.close()

    log2 = BatchLog(path)
    got = list(log2.replay())
    assert [e for e, _ in got] == [0, 1]
    assert got[0][1].contributions == b0.contributions
    assert got[1][1].contributions == b1.contributions
    assert log2.last_epoch == 1
    log2.close()


def test_log_truncates_torn_tail(tmp_path):
    path = str(tmp_path / "batches.log")
    log = BatchLog(path)
    log.append(0, _batch(("a", [b"x"])))
    log.close()
    # simulate a crash mid-append: write half a record
    rec = _encode_record(1, _batch(("a", [b"y"])))
    with open(path, "ab") as fh:
        fh.write(rec[: len(rec) // 2])
    log2 = BatchLog(path)
    assert log2.last_epoch == 0
    assert len(list(log2.replay())) == 1
    # and the log accepts new appends cleanly after truncation
    log2.append(1, _batch(("a", [b"z"])))
    log2.close()
    log3 = BatchLog(path)
    assert log3.last_epoch == 1
    assert len(list(log3.replay())) == 2
    log3.close()


def test_log_rejects_corrupt_crc(tmp_path):
    path = str(tmp_path / "batches.log")
    log = BatchLog(path)
    log.append(0, _batch(("a", [b"x"])))
    log.append(1, _batch(("a", [b"y"])))
    log.close()
    data = bytearray(open(path, "rb").read())
    data[-6] ^= 0xFF  # corrupt inside the second record
    open(path, "wb").write(bytes(data))
    log2 = BatchLog(path)
    assert log2.last_epoch == 0  # second record dropped
    log2.close()


def test_checkpoint_roundtrip_and_replay_skips_it(tmp_path):
    path = str(tmp_path / "batches.log")
    log = BatchLog(path)
    log.append(0, _batch(("a", [b"t1"])))
    log.append(1, _batch(("b", [b"t2", b"t3"])))
    log.append_checkpoint(1, [{b"t1"}, {b"t2", b"t3"}])
    log.append(2, _batch(("a", [b"t4"])))
    log.close()

    log2 = BatchLog(path)
    # batch replay is unchanged by the interleaved checkpoint
    assert [e for e, _ in log2.replay()] == [0, 1, 2]
    assert log2.last_epoch == 2
    epoch, history = log2.last_checkpoint
    assert epoch == 1
    assert history == [{b"t1"}, {b"t2", b"t3"}]
    log2.close()


def test_torn_checkpoint_truncated_like_torn_batch(tmp_path):
    from cleisthenes_tpu.core.ledger import (
        _encode_checkpoint_body,
        _frame_record,
        _MAGIC_CKPT,
    )

    path = str(tmp_path / "batches.log")
    log = BatchLog(path)
    log.append(0, _batch(("a", [b"x"])))
    log.append_checkpoint(0, [{b"x"}])
    log.close()
    rec = _frame_record(_MAGIC_CKPT, _encode_checkpoint_body(1, [{b"y"}]))
    with open(path, "ab") as fh:
        fh.write(rec[: len(rec) // 2])  # crash mid-checkpoint
    log2 = BatchLog(path)
    assert log2.last_epoch == 0
    assert log2.last_checkpoint == (0, [{b"x"}])
    log2.close()


def test_restart_seeds_filter_from_checkpoint(tmp_path):
    """A restarted node whose log carries a checkpoint must restore
    the SAME duplicate filter the pre-crash node held — without
    re-deriving tx sets from the batches the checkpoint covers."""
    from cleisthenes_tpu.config import Config
    from cleisthenes_tpu.protocol.honeybadger import HoneyBadger, setup_keys
    from cleisthenes_tpu.transport.broadcast import ChannelBroadcaster
    from cleisthenes_tpu.transport.channel import ChannelNetwork

    cfg = Config(n=4, batch_size=8, ledger_checkpoint_every=2)
    ids = [f"node{i}" for i in range(4)]
    keys = setup_keys(cfg, ids, seed=77)
    logdir = tmp_path / "ckpt-logs"
    os.makedirs(logdir)

    def build(net):
        nodes = {}
        for node_id in ids:
            nodes[node_id] = HoneyBadger(
                config=cfg,
                node_id=node_id,
                member_ids=ids,
                keys=keys[node_id],
                out=ChannelBroadcaster(net, node_id, ids),
                batch_log=BatchLog(str(logdir / f"{node_id}.log")),
            )
            net.join(node_id, nodes[node_id], None)
        return nodes

    net = ChannelNetwork()
    nodes = build(net)
    push_txs(nodes, 24, prefix=b"ck")
    for _ in range(10):
        for hb in nodes.values():
            hb.start_epoch()
        net.run()
        if all(hb.pending_tx_count() == 0 for hb in nodes.values()):
            break
    depth = assert_identical_batches(nodes)
    assert depth >= 2
    # every-2-commits policy actually wrote checkpoints
    assert nodes["node0"].batch_log.last_checkpoint is not None
    filters = {nid: set(hb._committed_filter) for nid, hb in nodes.items()}
    for hb in nodes.values():
        hb.batch_log.close()

    net2 = ChannelNetwork()
    nodes2 = build(net2)
    for nid, hb in nodes2.items():
        assert hb.epoch == len(hb.committed_batches)
        assert set(hb._committed_filter) == filters[nid]


def test_node_restart_resumes_epoch_and_filter(tmp_path):
    """A validator restarted from its log continues at last_epoch+1
    with its committed history and duplicate filter restored."""
    from cleisthenes_tpu.protocol.honeybadger import HoneyBadger, setup_keys
    from cleisthenes_tpu.config import Config
    from cleisthenes_tpu.transport.broadcast import ChannelBroadcaster
    from cleisthenes_tpu.transport.channel import ChannelNetwork

    logdir = tmp_path / "logs"
    os.makedirs(logdir)

    cfg = Config(n=4, batch_size=8)
    ids = [f"node{i}" for i in range(4)]
    keys = setup_keys(cfg, ids, seed=66)

    def build(net):
        nodes = {}
        for node_id in ids:
            nodes[node_id] = HoneyBadger(
                config=cfg,
                node_id=node_id,
                member_ids=ids,
                keys=keys[node_id],
                out=ChannelBroadcaster(net, node_id, ids),
                batch_log=BatchLog(str(logdir / f"{node_id}.log")),
            )
            net.join(node_id, nodes[node_id], None)
        return nodes

    net = ChannelNetwork()
    nodes = build(net)
    txs1 = push_txs(nodes, 8, prefix=b"run1")
    for hb in nodes.values():
        hb.start_epoch()
    net.run()
    depth1 = assert_identical_batches(nodes)
    committed1 = [
        b.tx_list() for b in nodes["node0"].committed_batches[:depth1]
    ]
    for hb in nodes.values():
        hb.batch_log.close()

    # "restart" the whole cluster from logs on a fresh network
    net2 = ChannelNetwork()
    nodes2 = build(net2)
    for hb in nodes2.values():
        assert hb.epoch == depth1  # resumed after the last commit
        assert len(hb.committed_batches) >= depth1
    # replaying an already-committed tx is filtered as a duplicate
    nodes2["node0"].add_transaction(txs1[0])
    assert nodes2["node0"]._create_batch() == []

    txs2 = push_txs(nodes2, 8, prefix=b"run2")
    for hb in nodes2.values():
        hb.start_epoch()
    net2.run()
    depth2 = assert_identical_batches(nodes2)
    assert depth2 > depth1
    # history preserved across the restart
    for e in range(depth1):
        assert nodes2["node0"].committed_batches[e].tx_list() == committed1[e]
    new_txs = {
        tx
        for b in nodes2["node0"].committed_batches[depth1:depth2]
        for tx in b.tx_list()
    }
    assert new_txs <= set(txs2)
    assert new_txs  # run2 actually committed something


def test_lagging_restart_catches_up_via_catchup(tmp_path):
    """A node restarted with a stale log (missing epochs the cluster
    already committed) must adopt the missing batches via f+1 matching
    CATCHUP responses, not stall or fork — and recover the whole
    outage window from ONE request round (range serving), not one
    request per epoch."""
    from cleisthenes_tpu.protocol.honeybadger import HoneyBadger, setup_keys
    from cleisthenes_tpu.config import Config
    from cleisthenes_tpu.transport.broadcast import ChannelBroadcaster
    from cleisthenes_tpu.transport.channel import ChannelNetwork

    cfg = Config(n=4, batch_size=8)
    ids = [f"node{i}" for i in range(4)]
    keys = setup_keys(cfg, ids, seed=91)

    def build(net, node_id, log=None):
        hb = HoneyBadger(
            config=cfg,
            node_id=node_id,
            member_ids=ids,
            keys=keys[node_id],
            out=ChannelBroadcaster(net, node_id, ids),
            batch_log=log,
        )
        net.join(node_id, hb, None)
        return hb

    # phase 1: run the full cluster a few epochs (no logs needed for
    # the up-to-date nodes; the laggard's state is simulated below)
    net = ChannelNetwork()
    nodes = {i: build(net, i) for i in ids}
    push_txs(nodes, 24, prefix=b"sync")
    for _ in range(10):
        for hb in nodes.values():
            hb.start_epoch()
        net.run()
        if all(hb.pending_tx_count() == 0 for hb in nodes.values()):
            break
    depth = assert_identical_batches(nodes)
    assert depth >= 2

    # phase 2: node3 "restarts" empty (lost everything) on a fresh
    # network with the same up-to-date peers
    net2 = ChannelNetwork()
    for i in ids[:3]:
        net2.join(i, nodes[i], None)
        # re-point the node's transport-level broadcaster (walk the
        # counting + coalescing wrappers down to the ChannelBroadcaster)
        inner = nodes[i].out
        while not hasattr(inner, "_network"):
            inner = inner._inner
        inner._network = net2
    fresh = build(net2, "node3")
    assert fresh.epoch == 0
    fresh.request_catchup()
    net2.run()
    assert fresh.epoch >= depth  # caught up past the common depth
    for e in range(depth):
        assert (
            fresh.committed_batches[e].tx_list()
            == nodes["node0"].committed_batches[e].tx_list()
        )


def test_catchup_rejects_forged_minority(tmp_path):
    """f forged catch-up responses must not fool a syncing node:
    adoption needs f+1 identical bodies."""
    from cleisthenes_tpu.core.ledger import encode_batch_body
    from cleisthenes_tpu.core.batch import Batch
    from cleisthenes_tpu.protocol.honeybadger import HoneyBadger, setup_keys
    from cleisthenes_tpu.config import Config
    from cleisthenes_tpu.transport.broadcast import ChannelBroadcaster
    from cleisthenes_tpu.transport.channel import ChannelNetwork
    from cleisthenes_tpu.transport.message import CatchupRespPayload

    cfg = Config(n=4, batch_size=8)
    ids = [f"node{i}" for i in range(4)]
    keys = setup_keys(cfg, ids, seed=92)
    net = ChannelNetwork()
    hb = HoneyBadger(
        config=cfg,
        node_id="node3",
        member_ids=ids,
        keys=keys["node3"],
        out=ChannelBroadcaster(net, "node3", ids),
    )
    net.join("node3", hb, None)

    forged = encode_batch_body(
        0, Batch(contributions={"node0": [b"EVIL-TX"]})
    )
    # one Byzantine response (f=1): must NOT be adopted
    hb._handle_catchup_resp("node0", CatchupRespPayload(0, forged))
    assert hb.epoch == 0 and not hb.committed_batches
    # a second matching response crosses f+1 and is adopted (by design:
    # two senders => at least one honest in the threat model)
    hb._handle_catchup_resp("node1", CatchupRespPayload(0, forged))
    assert hb.epoch == 1
    # duplicate/overwrite from the same sender never double-counts
    hb2 = HoneyBadger(
        config=cfg,
        node_id="node2",
        member_ids=ids,
        keys=keys["node2"],
        out=ChannelBroadcaster(net, "node2", ids),
    )
    net.join("node2", hb2, None)
    hb2._handle_catchup_resp("node0", CatchupRespPayload(0, forged))
    hb2._handle_catchup_resp("node0", CatchupRespPayload(0, forged))
    assert hb2.epoch == 0 and not hb2.committed_batches


def _bare_hb(node_id="node3", seed=93):
    from cleisthenes_tpu.config import Config
    from cleisthenes_tpu.protocol.honeybadger import HoneyBadger, setup_keys
    from cleisthenes_tpu.transport.broadcast import ChannelBroadcaster
    from cleisthenes_tpu.transport.channel import ChannelNetwork

    cfg = Config(n=4, batch_size=8)
    ids = [f"node{i}" for i in range(4)]
    keys = setup_keys(cfg, ids, seed=seed)
    net = ChannelNetwork()
    hb = HoneyBadger(
        config=cfg,
        node_id=node_id,
        member_ids=ids,
        keys=keys[node_id],
        out=ChannelBroadcaster(net, node_id, ids),
    )
    net.join(node_id, hb, None)
    return hb


def test_catchup_chase_not_suppressed_by_subquorum_tally():
    """Liveness regression: after adopting a window, a SUB-quorum (or
    Byzantine) tally already sitting at the new frontier must not
    suppress the follow-up CatchupReq — one dropped response would
    otherwise wedge the catch-up forever in a quiescent cluster."""
    from cleisthenes_tpu.core.ledger import encode_batch_body
    from cleisthenes_tpu.core.batch import Batch
    from cleisthenes_tpu.transport.message import CatchupRespPayload

    hb = _bare_hb()
    body0 = encode_batch_body(0, Batch(contributions={"node0": [b"a"]}))
    body1 = encode_batch_body(1, Batch(contributions={"node0": [b"b"]}))
    # a lone epoch-1 response arrives first (sub-quorum at the future
    # frontier), then epoch 0 reaches its f+1 quorum
    hb._handle_catchup_resp("node0", CatchupRespPayload(1, body1))
    hb._handle_catchup_resp("node0", CatchupRespPayload(0, body0))
    hb._handle_catchup_resp("node1", CatchupRespPayload(0, body0))
    assert hb.epoch == 1  # epoch 0 adopted
    # the chase fired at the new frontier despite the epoch-1 tally
    assert hb._last_catchup_request == 1


def test_catchup_serving_rate_limited_and_reserved_on_heal():
    """Amplification guard: a request whose from_epoch does not
    advance past the window already served draws from a small repeat
    budget (counted, never clocked — seeded runs replay exactly), so
    an 8-byte CatchupReq cannot buy unlimited 32-batch response
    windows; a link-heal event (peer_reconnected) re-arms the budget
    and re-serves the sender's last window."""
    from cleisthenes_tpu.core.batch import Batch
    from cleisthenes_tpu.protocol.honeybadger import CATCHUP_REPEAT_BUDGET
    from cleisthenes_tpu.transport.message import CatchupReqPayload

    hb = _bare_hb()
    hb.committed_batches.extend(
        [Batch(contributions={"node0": [b"e%d" % e]}) for e in range(2)]
    )
    out0 = hb.metrics.msgs_out.value
    hb._handle_catchup_req("node0", CatchupReqPayload(0))
    served = hb.metrics.msgs_out.value - out0
    assert served == 2  # both epochs served in one window
    # non-advancing replays drain the repeat budget, then are refused
    for i in range(CATCHUP_REPEAT_BUDGET):
        hb._handle_catchup_req("node0", CatchupReqPayload(0))
        assert hb.metrics.msgs_out.value - out0 == (i + 2) * served
    hb._handle_catchup_req("node0", CatchupReqPayload(0))
    hb._handle_catchup_req("node0", CatchupReqPayload(0))
    assert (
        hb.metrics.msgs_out.value - out0
        == (CATCHUP_REPEAT_BUDGET + 1) * served
    )
    # other senders have their own budget
    out1 = hb.metrics.msgs_out.value
    hb._handle_catchup_req("node1", CatchupReqPayload(0))
    assert hb.metrics.msgs_out.value - out1 == served
    # the transport's link-heal event re-arms node0 and re-serves its
    # last requested window (responses sent into a dead link are gone)
    out2 = hb.metrics.msgs_out.value
    hb.peer_reconnected("node0")
    assert hb.metrics.msgs_out.value - out2 == served
    # a non-member heal event is ignored
    out3 = hb.metrics.msgs_out.value
    hb.peer_reconnected("intruder")
    assert hb.metrics.msgs_out.value == out3
