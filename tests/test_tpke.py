"""Threshold encryption, common coin, and the Montgomery mod-engine.

Covers the TPKE.SetUp/Encrypt/DecShare/Decrypt API matrix
(reference docs/THRESHOLD_ENCRYPTION-EN.md:33-36), Byzantine-share
rejection, and coin agreement/unpredictability properties
(docs/BBA-EN.md:163-181), on both backends.
"""

import random

import pytest

from cleisthenes_tpu.ops import coin as coin_mod
from cleisthenes_tpu.ops import modmath as mm
from cleisthenes_tpu.ops import tpke

rng = random.Random(99)


class TestModEngine:
    def test_pow_batch_tpu_matches_pow(self):
        eng = mm.ModEngine("tpu")
        bases = [rng.randrange(2, mm.P) for _ in range(9)]
        exps = [rng.randrange(mm.Q) for _ in range(9)]
        assert eng.pow_batch(bases, exps) == [
            pow(b, e, mm.P) for b, e in zip(bases, exps)
        ]

    def test_dual_pow_batch_tpu(self):
        eng = mm.ModEngine("tpu")
        u1 = [rng.randrange(2, mm.P) for _ in range(5)]
        u2 = [rng.randrange(2, mm.P) for _ in range(5)]
        e1 = [rng.randrange(mm.Q) for _ in range(5)]
        e2 = [rng.randrange(mm.Q) for _ in range(5)]
        assert eng.dual_pow_batch(u1, e1, u2, e2) == [
            pow(a, x, mm.P) * pow(b, y, mm.P) % mm.P
            for a, x, b, y in zip(u1, e1, u2, e2)
        ]

    def test_edge_exponents(self):
        eng = mm.ModEngine("tpu")
        assert eng.pow_batch([7, 7, 0, 1, mm.P - 1], [0, 1, 5, 9, 2]) == [
            1, 7, 0, 1, pow(mm.P - 1, 2, mm.P)
        ]

    def test_empty_batch(self):
        assert mm.ModEngine("tpu").pow_batch([], []) == []

    def test_limb_roundtrip(self):
        for _ in range(20):
            x = rng.randrange(mm.P)
            assert mm.limbs_to_int(mm.int_to_limbs(x)) == x


class TestShamir:
    def test_lagrange_recovers_secret(self):
        secret = rng.randrange(mm.Q)
        shares = tpke._shamir_shares(
            secret, 7, 3, lambda k: rng.randbytes(k)
        )
        xs = [2, 5, 7]
        lams = tpke.lagrange_coeff_at_zero(xs)
        got = sum(l * shares[x - 1] for l, x in zip(lams, xs)) % mm.Q
        assert got == secret

    def test_fewer_than_threshold_insufficient(self):
        # t-1 shares give a different (wrong) interpolation
        secret = rng.randrange(mm.Q)
        shares = tpke._shamir_shares(secret, 7, 3, lambda k: rng.randbytes(k))
        xs = [1, 4]
        lams = tpke.lagrange_coeff_at_zero(xs)
        got = sum(l * shares[x - 1] for l, x in zip(lams, xs)) % mm.Q
        assert got != secret


@pytest.mark.parametrize("backend", ["cpu", "tpu"])
class TestTpke:
    def _setup(self, backend, n=4, f=1, seed=5):
        pub, shares = tpke.deal(n, f + 1, seed=seed)
        return tpke.Tpke(pub, backend=backend), shares

    def test_encrypt_decrypt_roundtrip(self, backend):
        svc, shares = self._setup(backend)
        msg = b"proposal for epoch 9: " + bytes(range(100))
        ct = svc.encrypt(msg)
        dec = [svc.dec_share(s, ct) for s in shares]
        ok = svc.verify_dec_shares(ct, dec)
        assert ok == [True] * 4
        # any f+1 = 2 shares decrypt
        assert svc.combine(ct, [dec[1], dec[3]]) == msg
        assert svc.combine(ct, [dec[0], dec[2]]) == msg

    def test_bad_share_rejected(self, backend):
        svc, shares = self._setup(backend)
        ct = svc.encrypt(b"secret")
        good = svc.dec_share(shares[0], ct)
        forged = tpke.DhShare(index=2, d=good.d, e=good.e, z=good.z)
        wrong_d = tpke.DhShare(
            index=good.index, d=pow(good.d, 2, mm.P), e=good.e, z=good.z
        )
        oob = tpke.DhShare(index=99, d=good.d, e=good.e, z=good.z)
        ok = svc.verify_dec_shares(ct, [good, forged, wrong_d, oob])
        assert ok == [True, False, False, False]

    def test_share_for_other_ciphertext_rejected(self, backend):
        svc, shares = self._setup(backend)
        ct1 = svc.encrypt(b"one")
        ct2 = svc.encrypt(b"two")
        d1 = svc.dec_share(shares[0], ct1)
        assert svc.verify_dec_shares(ct2, [d1]) == [False]

    def test_tampered_ciphertext_fails_integrity(self, backend):
        svc, shares = self._setup(backend)
        ct = svc.encrypt(b"payload")
        bad = tpke.Ciphertext(
            c1=ct.c1, c2=bytes([ct.c2[0] ^ 1]) + ct.c2[1:], tag=ct.tag
        )
        dec = [svc.dec_share(s, bad) for s in shares[:2]]
        with pytest.raises(ValueError, match="integrity"):
            svc.combine(bad, dec)

    def test_too_few_shares_raises(self, backend):
        svc, shares = self._setup(backend)
        ct = svc.encrypt(b"x")
        with pytest.raises(ValueError, match="need >="):
            svc.combine(ct, [svc.dec_share(shares[0], ct)])


@pytest.mark.parametrize("backend", ["cpu", "tpu"])
class TestCommonCoin:
    def test_agreement_across_share_subsets(self, backend):
        n, f = 7, 2
        pub, shares = tpke.deal(n, f + 1, seed=11)
        c = coin_mod.CommonCoin(pub, backend=backend)
        cid = b"epoch3|proposer5|round0"
        all_shares = [c.share(s, cid) for s in shares]
        assert c.verify_shares(cid, all_shares) == [True] * n
        v1 = c.combine(cid, all_shares[:3])
        v2 = c.combine(cid, all_shares[4:7])
        v3 = c.combine(cid, [all_shares[0], all_shares[3], all_shares[6]])
        assert v1 == v2 == v3

    def test_different_ids_differ(self, backend):
        pub, shares = tpke.deal(4, 2, seed=12)
        c = coin_mod.CommonCoin(pub, backend=backend)
        vals = set()
        for r in range(8):
            cid = b"round|%d" % r
            sh = [c.share(s, cid) for s in shares[:2]]
            vals.add(c.toss(cid, sh))
        assert vals == {True, False}  # 8 tosses, both outcomes seen

    def test_bad_coin_share_rejected(self, backend):
        pub, shares = tpke.deal(4, 2, seed=13)
        c = coin_mod.CommonCoin(pub, backend=backend)
        cid = b"cid"
        good = c.share(shares[0], cid)
        evil = tpke.DhShare(index=1, d=good.d, e=good.e, z=(good.z + 1) % mm.Q)
        assert c.verify_shares(cid, [good, evil]) == [True, False]


def test_keys_distinct_between_tpke_and_coin_seeds():
    pub_a, _ = tpke.deal(4, 2, seed=1)
    pub_b, _ = tpke.deal(4, 2, seed=2)
    assert pub_a.master != pub_b.master


class TestGroupMembership:
    """ADVICE.md round-1 high finding: ciphertext c1 values outside the
    prime-order subgroup must be rejected before share issuance."""

    def test_rejects_non_members(self):
        for bad in (0, 1, mm.P - 1, mm.P, mm.P + 5):
            assert not tpke.is_group_element(bad)

    def test_rejects_non_residue(self):
        # a generator of the full group Z_p* is not a QR; find one by
        # scanning small values (p = 2q+1 safe prime: non-residues have
        # order 2q, i.e. x^q == -1)
        x = next(
            x for x in range(2, 100) if pow(x, mm.Q, mm.P) == mm.P - 1
        )
        assert not tpke.is_group_element(x)

    def test_accepts_honest_values(self):
        assert tpke.is_group_element(mm.G)
        pub, _ = tpke.deal(4, 2, seed=3)
        assert tpke.is_group_element(pub.master)
        ct = tpke.Tpke(pub).encrypt(b"m")
        assert tpke.is_group_element(ct.c1)

    def test_deserialize_rejects_poisoned_c1(self):
        import struct

        import pytest

        from cleisthenes_tpu.protocol.honeybadger import (
            deserialize_ciphertext,
            serialize_ciphertext,
        )

        c2 = b"\x00" * 8
        for bad_c1 in (0, 1, mm.P - 1):
            blob = (
                bad_c1.to_bytes(32, "big")
                + struct.pack(">I", len(c2))
                + c2
                + b"\x11" * 32
            )
            with pytest.raises(ValueError):
                deserialize_ciphertext(blob)
        # round-trip of an honest ciphertext still works
        pub, _ = tpke.deal(4, 2, seed=5)
        ct = tpke.Tpke(pub).encrypt(b"honest")
        assert deserialize_ciphertext(serialize_ciphertext(ct)) == ct


class TestBatchedChallenge:
    """The batched CP-challenge path (ops/hashrows + _cp_challenge_batch)
    must stay byte-identical to the scalar _hash_to_int transcript —
    this equivalence is what lets shares issued by the batched path
    verify under the scalar path and vice versa."""

    def test_cp_challenge_batch_matches_scalar(self):
        import secrets as _s

        gp = mm.DEFAULT_GROUP
        nb = gp.nbytes
        ctxs, bases, his, ds, a1s, a2s = [], [], [], [], [], []
        # m=100 is ABOVE the m<64 scalar cutoff: this must exercise
        # the numpy/native matrix path, not compare the scalar path
        # with itself (a round-4 review caught exactly that vacuity)
        m = 100
        assert m >= 64
        for i in range(m):
            # mixed context lengths exercise the group-by-length path
            ctxs.append(b"ctx|%d" % (10 ** (i % 4)))
            for lst in (bases, his, ds, a1s, a2s):
                lst.append(int.from_bytes(_s.token_bytes(nb), "big") % gp.p)
        got = tpke._cp_challenge_batch(ctxs, bases, his, ds, a1s, a2s, gp)
        # and the sub-cutoff scalar path agrees on a prefix slice
        got_small = tpke._cp_challenge_batch(
            ctxs[:8], bases[:8], his[:8], ds[:8], a1s[:8], a2s[:8], gp
        )
        assert got_small == got[:8]
        for k in range(m):
            want = (
                tpke._hash_to_int(
                    b"cp", ctxs[k],
                    tpke._ibytes(bases[k], nb), tpke._ibytes(his[k], nb),
                    tpke._ibytes(ds[k], nb), tpke._ibytes(a1s[k], nb),
                    tpke._ibytes(a2s[k], nb),
                )
                % gp.q
            )
            assert got[k] == want

    def test_batched_issue_verifies_under_scalar_path(self):
        pub, shares = tpke.deal(n=5, threshold=2, seed=77)
        base = tpke.hash_to_group(b"cross-check")
        ctx = b"cross|ctx"
        out = tpke.issue_shares_batch(
            [(s, base, ctx, pub.verification_keys[s.index - 1]) for s in shares]
        )
        # scalar verifier accepts every batched-issued share
        assert all(tpke.verify_shares(pub, base, out, ctx))
        # and the scalar-issued share verifies under the batched path
        one = tpke.issue_share(shares[0], base, ctx)
        v, _, _ = tpke.verify_and_combine_share_groups(
            [(pub, base, [one] + out[1:], ctx)], 2
        )
        assert all(v[0])


class TestFusedVerifyCombine:
    def test_fused_matches_separate_ops(self):
        pub, shares = tpke.deal(n=7, threshold=3, seed=42)
        groups = []
        for i in range(4):
            ctx = b"g|%d" % i
            base = tpke.hash_to_group(b"b|%d" % i)
            out = tpke.issue_shares_batch(
                [(s, base, ctx, pub.verification_keys[s.index - 1])
                 for s in shares]
            )
            groups.append((pub, base, out, ctx))
        v1 = tpke.verify_share_groups(groups)
        c1 = tpke.combine_shares_batch([g[2][:3] for g in groups], 3)
        tpke._COMBINE_MEMO.clear()
        v2, c2, _ = tpke.verify_and_combine_share_groups(groups, 3)
        assert v1 == v2 and c1 == c2
        # memo is seeded: a follow-up scalar combine is a pure hit
        assert tpke.combine_shares(groups[0][2][:3], 3) == c2[0]

    def test_fused_combine_only_sets(self):
        pub, shares = tpke.deal(n=6, threshold=3, seed=43)
        base = tpke.hash_to_group(b"co")
        ctx = b"co|ctx"
        out = tpke.issue_shares_batch(
            [(s, base, ctx, pub.verification_keys[s.index - 1])
             for s in shares]
        )
        want = tpke.combine_shares_batch([out[:3], out[2:5]], 3)
        tpke._COMBINE_MEMO.clear()
        # equal-but-distinct group object must still combine (keyed by
        # value, not identity)
        gp2 = mm.GroupParams(p=mm.P, q=mm.Q, g=mm.G)
        v, gvals, co = tpke.verify_and_combine_share_groups(
            [(pub, base, out, ctx)],
            3,
            combine_only_sets=[out[:3], out[2:5]],
            combine_only_group=gp2,
        )
        assert all(v[0])
        assert co == want

    def test_fused_flags_tampered_share(self):
        pub, shares = tpke.deal(n=5, threshold=2, seed=44)
        base = tpke.hash_to_group(b"tamper")
        ctx = b"t|ctx"
        out = tpke.issue_shares_batch(
            [(s, base, ctx, pub.verification_keys[s.index - 1])
             for s in shares]
        )
        bad = list(out)
        bad[2] = tpke.DhShare(
            index=bad[2].index, d=bad[2].d, e=bad[2].e, z=bad[2].z + 1
        )
        v, _, _ = tpke.verify_and_combine_share_groups(
            [(pub, base, bad, ctx)], 2
        )
        assert v[0] == [True, True, False, True, True]
