"""The crypto-group seam, demonstrated (VERDICT round-2 item 6).

ops/tpke.py's security notes promise the modulus is a seam: "a
production deployment would swap the group seam for a pairing curve or
a larger prime — the API and the batched-verify data flow are
unchanged".  These tests run the full threshold stack — TPKE.SetUp /
Encrypt / DecShare / batched CP verify / Decrypt
(reference docs/THRESHOLD_ENCRYPTION-EN.md:33-36) plus the common coin
(docs/BBA-EN.md:163-181) — under NON-default groups:

- a second 256-bit safe prime, through BOTH engines (the native C++
  Montgomery kernel and the XLA limb kernel: one compiled program
  serves every <=256-bit group, constants ride in as traced arrays);
- the 2048-bit RFC 3526 MODP-14 safe prime, CPU-only, proving the
  limb-free python path and every byte-width in the CP transcripts
  generalize past the 256-bit layout.
"""

import pytest

from cleisthenes_tpu.ops import tpke
from cleisthenes_tpu.ops.coin import CommonCoin
from cleisthenes_tpu.ops.modmath import DEFAULT_GROUP, GroupParams, get_engine

# Second 256-bit safe prime (deterministic search, seed 20260730,
# 64-round Miller-Rabin), g = 4 generates the order-q QR subgroup.
P2 = 0x93A40B764F1F5026ADA7C38AA3EF4EE81E01E89F9FE80837B1E370913DA99F13
GROUP2 = GroupParams(p=P2, q=(P2 - 1) // 2, g=4)

# RFC 3526 group 14: 2048-bit MODP safe prime (well-known constant).
MODP14 = int(
    "FFFFFFFFFFFFFFFFC90FDAA22168C234C4C6628B80DC1CD129024E088A67CC74"
    "020BBEA63B139B22514A08798E3404DDEF9519B3CD3A431B302B0A6DF25F1437"
    "4FE1356D6D51C245E485B576625E7EC6F44C42E9A637ED6B0BFF5CB6F406B7ED"
    "EE386BFB5A899FA5AE9F24117C4B1FE649286651ECE45B3DC2007CB8A163BF05"
    "98DA48361C55D39A69163FA8FD24CF5F83655D23DCA3AD961C62F356208552BB"
    "9ED529077096966D670C354E4ABC9804F1746C08CA18217C32905E462E36CE3B"
    "E39E772C180E86039B2783A2EC07A28FB5C55DF06F4C52C9DE2BCBF695581718"
    "3995497CEA956AE515D2261898FA051015728E5A8AACAA68FFFFFFFFFFFFFFFF",
    16,
)
GROUP14 = GroupParams(p=MODP14, q=(MODP14 - 1) // 2, g=4)

N, F = 7, 2


def _roundtrip(group: GroupParams, engine_backend: str) -> None:
    """Full threshold-decryption + coin lifecycle under ``group``."""
    pub, shares = tpke.deal(N, F + 1, seed=9, group=group)
    assert pub.group is group

    # subgroup membership sanity in this group
    assert tpke.is_group_element(pub.master, group)
    assert not tpke.is_group_element(group.p - 1, group)  # order-2 elt

    svc = tpke.Tpke(pub, backend=engine_backend)
    msg = b"the woods are lovely, dark and deep" * 3
    ct = svc.encrypt(msg)
    assert tpke.is_group_element(ct.c1, group)

    dec = [svc.dec_share(shares[i], ct) for i in range(N)]
    ok = tpke.verify_shares(
        pub, ct.c1, dec, svc.context(ct), backend=engine_backend
    )
    assert all(ok)
    # a corrupted share must fail CP verification in this group too
    bad = tpke.DhShare(index=dec[0].index, d=dec[0].d, e=dec[0].e,
                       z=(dec[0].z + 1) % group.q)
    assert tpke.verify_shares(
        pub, ct.c1, [bad], svc.context(ct), backend=engine_backend
    ) == [False]

    # any f+1 subset decrypts identically
    assert svc.combine(ct, dec[: F + 1]) == msg
    assert svc.combine(ct, dec[F + 1 :]) == msg

    # the common coin over the same group: identical bit from any
    # threshold subset, shares verifiable
    cpub, cshares = tpke.deal(N, F + 1, seed=10, group=group)
    coin = CommonCoin(cpub, backend=engine_backend)
    cid = b"epoch|instance|round0"
    cs = [coin.share(cshares[i], cid) for i in range(N)]
    assert all(coin.verify_shares(cid, cs))
    bits = {coin.toss(cid, subset) for subset in (cs[: F + 1], cs[F + 1 :])}
    assert len(bits) == 1


def test_second_256bit_prime_cpu_engine():
    _roundtrip(GROUP2, "cpu")


def test_second_256bit_prime_xla_engine(jax_cpu_devices):
    _roundtrip(GROUP2, "tpu")


def test_2048bit_modp14_cpu_only():
    _roundtrip(GROUP14, "cpu")


# The packaged 384-bit safe-prime group (BLS12-381 base-field width
# class, (12, 32) XLA limb family) — see ops/modmath.GROUP384.
from cleisthenes_tpu.ops.modmath import GROUP384, P384  # noqa: E402

# The measured per-family floors (ModEngine.WIDE_FLOORS) delegate
# small wide-group batches to the host — so device-path correctness
# tests pin the device kernels with host_delegation=False (the
# class-level test escape; round-4 review found the earlier version
# comparing python pow against python pow).
WIDE_BATCH = 24


@pytest.fixture
def device_pinned(monkeypatch):
    from cleisthenes_tpu.ops.modmath import ModEngine

    monkeypatch.setattr(ModEngine, "host_delegation", False)


def test_384bit_group_xla_engine_matches_pow(
    jax_cpu_devices, device_pinned
):
    """The wide XLA limb family (SURVEY §7 hard part 1: a group sized
    for BLS12-381's base field on the device path, replacing round-3's
    256-bit rejection)."""
    import random

    rng = random.Random(7)
    eng = get_engine("tpu", group=GROUP384)
    assert eng._host_floor(WIDE_BATCH) is None  # really the device path
    bases = [rng.randrange(2, P384) for _ in range(2 * WIDE_BATCH)]
    exps = [rng.randrange(1, GROUP384.q) for _ in range(2 * WIDE_BATCH)]
    assert eng.pow_batch(bases, exps) == [
        pow(b, e, P384) for b, e in zip(bases, exps)
    ]
    h = 2 * WIDE_BATCH // 2
    got = eng.dual_pow_batch(bases[:h], exps[:h], bases[h:], exps[h:])
    assert got == [
        pow(a, x, P384) * pow(b, y, P384) % P384
        for a, x, b, y in zip(bases[:h], exps[:h], bases[h:], exps[h:])
    ]


def test_384bit_group_full_protocol_xla(jax_cpu_devices, device_pinned):
    """The whole TPKE + coin round-trip under the 384-bit group on the
    XLA engine — the seam swap the module docstrings promise."""
    _roundtrip(GROUP384, "tpu")


def test_2048bit_modp14_xla_engine_matches_pow(
    jax_cpu_devices, device_pinned
):
    """Round-3 verdict item: the 2048-bit MODP-14 group runs on the
    TPU path (11x192-limb family), property-matched against python
    pow.  Replaces test_xla_engine_rejects_oversized_group."""
    import random

    rng = random.Random(5)
    eng = get_engine("tpu", group=GROUP14)
    assert eng.backend == "tpu"
    assert eng._host_floor(WIDE_BATCH) is None  # really the device path
    bases = [rng.randrange(2, GROUP14.p) for _ in range(WIDE_BATCH)]
    exps = [rng.randrange(1, GROUP14.q) for _ in range(WIDE_BATCH)]
    assert eng.pow_batch(bases, exps) == [
        pow(b, e, GROUP14.p) for b, e in zip(bases, exps)
    ]
    h = WIDE_BATCH // 2
    got = eng.dual_pow_batch(bases[:h], exps[:h], bases[h:], exps[h:])
    assert got == [
        pow(a, x, GROUP14.p) * pow(b, y, GROUP14.p) % GROUP14.p
        for a, x, b, y in zip(bases[:h], exps[:h], bases[h:], exps[h:])
    ]


def test_wide_floors_route_by_measured_crossover(jax_cpu_devices):
    """Round-4 verdict weak #4: engine defaults must follow measured
    device-vs-host crossovers per limb family (TPU_QUICK_r05
    modexp_wide).  384-bit wins on device above ~160 exps (floor 256);
    2048-bit measured 0.97x host — it must ALWAYS delegate."""
    eng384 = get_engine("tpu", group=GROUP384)
    assert eng384._host_floor(255) is not None  # below floor -> host
    assert eng384._host_floor(256) is None  # above -> device
    eng2048 = get_engine("tpu", group=GROUP14)
    for b in (8, 256, 1 << 16):
        host = eng2048._host_floor(b)
        assert host is not None and host.backend == "cpu"


def test_xla_engine_still_rejects_beyond_every_family():
    """layout_for_group must return None past the widest family (a
    matching-anyway bug would silently TRUNCATE limbs instead of
    raising)."""
    from cleisthenes_tpu.ops.modmath import layout_for_group

    p_huge = (1 << 3000) + 117  # odd, 3001 bits > 2112-bit family
    g_huge = GroupParams(p=p_huge, q=(p_huge - 1) // 2, g=4)
    assert layout_for_group(g_huge) is None
    with pytest.raises(ValueError, match="limb family"):
        get_engine("tpu", group=g_huge)


def test_groups_are_isolated():
    """Shares dealt in one group must not verify under a key from
    another (the transcript binds the group via element widths and
    reductions)."""
    pub_a, shares_a = tpke.deal(N, F + 1, seed=9, group=GROUP2)
    pub_b, _ = tpke.deal(N, F + 1, seed=9)  # default group
    svc_a = tpke.Tpke(pub_a)
    ct = svc_a.encrypt(b"x" * 32)
    share = svc_a.dec_share(shares_a[0], ct)
    assert tpke.verify_shares(
        pub_b, ct.c1 % pub_b.group.p, [share], svc_a.context(ct)
    ) == [False]


def test_full_protocol_under_second_group():
    """The seam reaches the protocol plane: a 4-node HBBFT network
    whose dealer issued keys in GROUP2 (ciphertext wire width, subgroup
    validation, share issuance/verification and coin all in the
    non-default group) commits identical batches."""
    from tests.test_honeybadger import (
        assert_identical_batches,
        push_txs,
    )
    from cleisthenes_tpu.config import Config
    from cleisthenes_tpu.protocol.honeybadger import HoneyBadger, setup_keys
    from cleisthenes_tpu.transport.base import HmacAuthenticator
    from cleisthenes_tpu.transport.broadcast import ChannelBroadcaster
    from cleisthenes_tpu.transport.channel import ChannelNetwork

    cfg = Config(n=4, batch_size=8)
    ids = [f"node{i}" for i in range(4)]
    keys = setup_keys(cfg, ids, seed=33, group=GROUP2)
    assert keys[ids[0]].tpke_pub.group is GROUP2
    net = ChannelNetwork()
    nodes = {}
    for nid in ids:
        hb = HoneyBadger(
            config=cfg,
            node_id=nid,
            member_ids=ids,
            keys=keys[nid],
            out=ChannelBroadcaster(net, nid, ids),
        )
        nodes[nid] = hb
        net.join(nid, hb, HmacAuthenticator(nid, keys[nid].mac_keys))
    txs = push_txs(nodes, 12, prefix=b"g2")
    for _ in range(6):
        for hb in nodes.values():
            hb.start_epoch()
        net.run()
        if all(hb.pending_tx_count() == 0 for hb in nodes.values()):
            break
    depth = assert_identical_batches(nodes)
    committed = {
        tx
        for b in nodes["node0"].committed_batches[:depth]
        for tx in b.tx_list()
    }
    assert committed == set(txs)
