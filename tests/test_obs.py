"""Live telemetry plane: sampler rings, SLO watchdogs, scrape
endpoints, and the perf-regression observatory (ISSUE 6).

Three layers under test:

1. runtime export — utils/timeseries.py bounded rings,
   Histogram.cumulative_buckets, the Prometheus text exposition
   (golden-file scrape), and the /metrics | /healthz | /vars endpoints
   on both the in-proc cluster and real gRPC hosts;
2. watchdogs — the epoch-stall detector under a PR-4 SelectiveMute
   coalition, backpressure + peer-lag detectors, and /healthz flipping
   to DEGRADED under PR-1 crash/partition faults;
3. the observatory — tools/perfgate.py trend seeding, noise-band
   pass on a repeated seeded run, and hard failure on an inflated
   record.
"""

from __future__ import annotations

import json
import pathlib
import sys
import time
import urllib.error
import urllib.request

import pytest

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from cleisthenes_tpu.config import Config
from cleisthenes_tpu.protocol.byzantine import SelectiveMute
from cleisthenes_tpu.protocol.cluster import SimulatedCluster
from cleisthenes_tpu.transport.obs_http import (
    ObsServer,
    ObsTarget,
    escape_label_value,
    render_prometheus,
)
from cleisthenes_tpu.utils.metrics import Histogram, Metrics
from cleisthenes_tpu.utils.timeseries import (
    TimeSeriesSampler,
    flatten_snapshot,
)
from cleisthenes_tpu.utils.watchdog import (
    EPOCH_STALL,
    PEER_LAG,
    QUEUE_BACKPRESSURE,
    SloWatchdog,
    worst_health,
)

GOLDEN = pathlib.Path(__file__).resolve().parent / "golden"


def _get(url: str):
    try:
        with urllib.request.urlopen(url, timeout=10) as resp:
            return resp.status, resp.read().decode("utf-8")
    except urllib.error.HTTPError as e:  # 404/503 are assertable answers
        return e.code, e.read().decode("utf-8")


# ---------------------------------------------------------------------------
# layer 1: histogram buckets, flattening, sampler rings
# ---------------------------------------------------------------------------


def test_histogram_cumulative_buckets_and_sum():
    h = Histogram(buckets=(0.1, 1.0, 10.0))
    for v in (0.05, 0.5, 0.5, 5.0, 50.0):
        h.observe(v)
    buckets = h.cumulative_buckets()
    assert buckets == [
        (0.1, 1),  # 0.05
        (1.0, 3),  # + the two 0.5s (cumulative)
        (10.0, 4),  # + 5.0
        (float("inf"), 5),  # everything
    ]
    assert h.total_sum == pytest.approx(56.05)
    assert h.total_count == 5
    # a boundary value counts into its own bucket (le is inclusive)
    hb = Histogram(buckets=(1.0,))
    hb.observe(1.0)
    assert hb.cumulative_buckets() == [(1.0, 1), (float("inf"), 1)]
    # reservoir eviction must NOT move the exposition tallies: the
    # Prometheus histogram contract wants monotonic counters, while
    # the percentile window stays bounded
    h2 = Histogram(cap=2, buckets=(10.0,))
    for v in (1.0, 2.0, 3.0):
        h2.observe(v)
    assert h2.count == 2  # percentile reservoir: bounded
    assert h2.total_count == 3  # exposition: lifetime, monotonic
    assert h2.total_sum == pytest.approx(6.0)
    assert h2.cumulative_buckets() == [(10.0, 3), (float("inf"), 3)]


def test_transport_block_uniform_on_bare_metrics():
    """Satellite: every transport key — including the delivery-plane
    columnarization counters — is ALWAYS present (zeroed) even before
    any transport registers its provider; a scraper must never see
    keys appear mid-run."""
    snap = Metrics().snapshot()
    assert snap["transport"] == {
        "delivered": 0,
        "rejected": 0,
        "dedup_absorbed": 0,
        "frames_decoded": 0,
        "decode_memo_hits": 0,
        "decode_memo_misses": 0,
        "mac_verify_batches": 0,
        "frames_encoded": 0,
        "encode_memo_hits": 0,
        "encode_memo_misses": 0,
        "mac_sign_batches": 0,
    }
    # egress-columnarization twin block (ISSUE 13): same zeroed-key
    # schema rule for the coin-issue dispatch tallies
    assert snap["hub"] == {
        "coin_share_batches": 0,
        "coin_share_items": 0,
    }
    # K-deep pipeline block (ISSUE 15): same zeroed-key schema rule
    # — present on bare metrics, at depth 1, and on every transport
    assert snap["pipeline"] == {
        "epochs_in_flight": 0,
        "eager_share_waves": 0,
    }


def test_flatten_snapshot_numeric_leaves_only():
    flat = flatten_snapshot(
        {
            "a": 1,
            "b": {"c": 2.5, "state": "up", "d": {"e": True}},
            "skip": None,
            "lst": [1, 2],
        }
    )
    assert flat == {"a": 1.0, "b.c": 2.5, "b.d.e": 1.0}


def test_sampler_rings_bounded_and_rates():
    state = {"v": 0}
    sampler = TimeSeriesSampler(
        lambda: {"ctr": state["v"], "nest": {"x": 1}}, cap=4
    )
    for i in range(8):
        state["v"] = i * 10
        sampler.sample(now=float(i))
    series = sampler.series()
    assert len(series["ctr"]) == 4  # ring keeps the newest cap points
    assert series["ctr"][0] == (4.0, 40.0)
    assert series["ctr"][-1] == (7.0, 70.0)
    assert sampler.latest() == {"ctr": 70.0, "nest.x": 1.0}
    assert sampler.rate("ctr") == pytest.approx(10.0)  # 30 over 3s
    assert sampler.rate("missing") is None
    assert sampler.stats() == {"samples": 8, "series": 2}


def test_sampler_tick_receives_synthetic_clock():
    """on_tick callbacks get the sample instant, so a synthetic
    ``sample(now=...)`` drives the riding watchdog's clock too —
    rings and verdicts tell one consistent story."""
    seen = []
    sampler = TimeSeriesSampler(lambda: {"v": 1})
    sampler.on_tick(seen.append)
    sampler.sample(now=123.5)
    assert seen == [123.5]
    m = Metrics()
    wd = SloWatchdog(
        metrics=m, pending_fn=lambda: 3, stall_grace_s=5.0
    )
    s2 = TimeSeriesSampler(m.snapshot)
    s2.on_tick(wd.check)
    m.set_alerts(wd.alerts_block)
    s2.sample(now=m._t0 + 1000.0)  # synthetic stall, no sleeping
    assert wd.alerts_block()[EPOCH_STALL]["active"] is True
    # ...and the ring recorded the post-check alert state
    assert s2.latest()["alerts.epoch_stall.active"] == 1.0


def test_sampler_thread_ticks_and_stops():
    ticks = []
    sampler = TimeSeriesSampler(lambda: {"v": 1})
    sampler.on_tick(lambda now: ticks.append(now))
    sampler.start(period_s=0.02)
    deadline = time.monotonic() + 5.0
    while not ticks and time.monotonic() < deadline:
        time.sleep(0.01)
    sampler.stop()
    assert ticks, "sampler thread never ticked"
    assert sampler.latest()["v"] == 1.0


# ---------------------------------------------------------------------------
# layer 1: the Prometheus exposition (golden-file scrape)
# ---------------------------------------------------------------------------


def test_label_escaping_per_text_format():
    assert escape_label_value('a"b\\c\nd') == 'a\\"b\\\\c\\nd'
    # escaping the escapes first: a literal backslash-n stays distinct
    # from a newline
    assert escape_label_value("x\\n") == "x\\\\n"


def _golden_target() -> ObsTarget:
    """A fully deterministic scrape target: every counter, histogram,
    provider block and alert pinned to fixed values."""
    m = Metrics()
    m.msgs_in.inc(10)
    m.msgs_out.inc(20)
    m.epochs_committed.inc(2)
    m.txs_committed.inc(30)
    m.dedup_absorbed.inc(3)
    for v in (0.05, 0.2):
        m.epoch_latency.observe(v)
    m.acs_latency.observe(0.04)
    m.decrypt_latency.observe(0.01)
    # two-frontier commit split (ISSUE 8): ordered-frontier latency,
    # the trailing settle lag, and a 1-epoch decrypt lag in flight
    m.ordered_latency.observe(0.03)
    m.settle_lag_latency.observe(0.02)
    m.epochs_ordered.inc(3)
    m.set_frontiers(lambda: (3, 2))
    # wave-routed ingest counters (ISSUE 10): zeroed keys on every
    # path; pinned nonzero so the golden scrape covers the families
    m.handler_dispatches.inc(12)
    m.waves_routed.inc(4)
    # K-deep pipeline counters (ISSUE 15): pinned nonzero so the
    # golden scrape covers the new families
    m.eager_share_waves.inc(2)
    m.set_pipeline(lambda: 3)
    m.tx_per_sec = lambda: 1.5  # pin the one wall-clock-derived gauge
    m.set_transport_stats(
        lambda: {
            "delivered": 7,
            "rejected": 1,
            # delivery-plane columnarization counters (zeroed keys on
            # every path; pinned nonzero here so the golden scrape
            # covers the new families)
            "frames_decoded": 6,
            "decode_memo_hits": 4,
            "decode_memo_misses": 2,
            "mac_verify_batches": 3,
            # egress-columnarization counters (ISSUE 13): same rule
            "frames_encoded": 5,
            "encode_memo_hits": 3,
            "encode_memo_misses": 2,
            "mac_sign_batches": 4,
        }
    )
    m.set_hub_stats(
        lambda: {"coin_share_batches": 2, "coin_share_items": 9}
    )
    # WAN emulation-plane counters (ISSUE 16): zeroed keys on every
    # path; pinned nonzero so the golden scrape covers the families
    m.set_wan_stats(
        lambda: {
            "enabled": 1,
            "profile": "wan_3region",
            "frames_delayed": 11,
            "retransmits": 2,
            "straggler_episodes": 1,
            "virtual_time_ms": 1500,
        }
    )
    # client ingress-plane counters (ISSUE 18): zeroed keys on every
    # path; pinned nonzero so the golden scrape covers the families
    m.set_ingress(
        lambda: {
            "submitted": 9,
            "admitted": 6,
            "rejected": 1,
            "retried": 1,
            "deduped": 1,
            "evicted": 1,
            "subscribers": 2,
            "mempool_depth": 4,
        }
    )
    # lane shard-out gauges (ISSUE 20): zeroed keys on every path;
    # pinned to a two-lane shape so the golden scrape covers the
    # per-lane labeled families
    m.set_lanes(
        lambda: {
            "lanes": 2,
            "merge_frontier": 5,
            "ordered_epochs": [3, 2],
            "settled_epochs": [3, 2],
            "lane_fill": [8, 6],
            "partition_skew": 2,
        }
    )
    m.set_transport_health(
        lambda: {
            'peer"q\\s': {
                "state": "down",
                "reconnects": 2,
                "dial_attempts": 9,
                "dial_failures": 4,
                "consecutive_failures": 4,
                "recent_delays_s": [],
                "state_age_s": 0.0,
            }
        }
    )
    m.set_trace_stats(
        lambda: {"events_recorded": 5, "events_dropped": 0, "high_water": 5}
    )
    wd = SloWatchdog(
        metrics=m,
        pending_fn=lambda: 0,
        peer_states_fn=lambda: {'peer"q\\s': "down"},
    )
    m.set_alerts(wd.alerts_block)
    return ObsTarget("node-a", m, wd)


def test_prometheus_exposition_matches_golden():
    """The scrape is a FORMAT contract (Prometheus text exposition
    0.0.4): byte-compare against the committed golden file so any
    accidental change to names, labels, escaping or bucket layout
    shows up as a diff, not a silent scrape break."""
    server = ObsServer([_golden_target()])
    got = server.metrics_text()
    golden_path = GOLDEN / "metrics_exposition.txt"
    assert got == golden_path.read_text(encoding="utf-8"), (
        "exposition drifted from tests/golden/metrics_exposition.txt — "
        "if intentional, regenerate: write "
        "ObsServer([_golden_target()]).metrics_text() to the golden path"
    )


def test_exposition_self_consistency():
    text = render_prometheus([_golden_target()])
    lines = text.splitlines()
    # every non-comment sample parses as `name{labels} value`
    samples = [l for l in lines if l and not l.startswith("#")]
    assert samples
    for line in samples:
        name_part, value = line.rsplit(" ", 1)
        assert "{" in name_part and name_part.endswith("}")
        float(value.replace("+Inf", "inf"))
    # cumulative buckets end in the +Inf catch-all == _count
    inf = [l for l in samples if 'le="+Inf"' in l and "epoch_latency" in l]
    count = [l for l in samples if l.startswith(
        "cleisthenes_epoch_latency_seconds_count")]
    assert inf[0].rsplit(" ", 1)[1] == count[0].rsplit(" ", 1)[1] == "2"
    # each family header appears exactly once
    helps = [l for l in lines if l.startswith("# HELP")]
    assert len(helps) == len(set(helps))


# ---------------------------------------------------------------------------
# layer 2: SLO watchdogs
# ---------------------------------------------------------------------------


def test_watchdog_stall_budget_self_calibrates():
    m = Metrics()
    wd = SloWatchdog(metrics=m, pending_fn=lambda: 1, stall_grace_s=2.0)
    assert wd.stall_budget_s() == 2.0  # no p50 yet: the grace floor
    for _ in range(4):
        m.epoch_latency.observe(10.0)
    assert wd.stall_budget_s() == pytest.approx(80.0)  # factor * p50


def test_watchdog_detectors_and_health_transitions():
    m = Metrics()
    pending = {"n": 0}
    peers = {"p1": "up"}
    wd = SloWatchdog(
        metrics=m,
        pending_fn=lambda: pending["n"],
        stall_grace_s=5.0,
        queue_depth_limit=100,
        peer_states_fn=lambda: dict(peers),
    )
    t0 = m._t0
    assert wd.check(now=t0 + 1.0) == "up"
    # pending work + no commit past the budget -> stall -> DOWN
    pending["n"] = 7
    assert wd.check(now=t0 + 60.0) == "down"
    block = wd.alerts_block()
    assert block[EPOCH_STALL] == {
        "count": 1,
        "active": True,
        "reason": block[EPOCH_STALL]["reason"],
    }
    assert "7 txs pending" in block[EPOCH_STALL]["reason"]
    # a commit clears the stall; an over-limit queue degrades
    m.epoch_committed(0, n_txs=1)
    pending["n"] = 101
    verdict = wd.check(now=m._last_commit_t + 1.0)
    assert verdict == "degraded"
    block = wd.alerts_block()
    assert block[EPOCH_STALL]["active"] is False
    assert block[EPOCH_STALL]["count"] == 1  # edge-counted, not re-fired
    assert block[QUEUE_BACKPRESSURE]["active"] is True
    # a DOWN peer keeps health degraded even with an empty queue
    pending["n"] = 0
    peers["p1"] = "down"
    assert wd.check(now=m._last_commit_t + 1.0) == "degraded"
    assert wd.alerts_block()[PEER_LAG]["active"] is True
    peers["p1"] = "up"
    assert wd.check(now=m._last_commit_t + 1.0) == "up"
    assert worst_health(["up", "degraded", "down"]) == "down"


@pytest.mark.faults
def test_epoch_stall_watchdog_fires_under_selective_mute():
    """A SelectiveMute coalition past the fault budget (2 of 4 nodes
    silent toward everyone) starves every quorum: no epoch commits,
    and the stall detector must flip the node to DOWN, count the
    firing, and land an ``alert`` instant on the PR-3 timeline."""
    cfg = Config(n=4, batch_size=8, seed=11, trace=True,
                 slo_stall_grace_s=5.0)
    cluster = SimulatedCluster(
        config=cfg,
        seed=11,
        behaviors={
            "node001": SelectiveMute(seed=1, fraction=1.0),
            "node002": SelectiveMute(seed=2, fraction=1.0),
        },
    )
    for i in range(16):
        cluster.submit(b"stall-%03d" % i)
    cluster.run_until_drained(max_rounds=2)
    honest = cluster.nodes["node000"]
    assert honest.metrics.epochs_committed.value == 0  # truly stalled
    # the K-deep pipeline window may have absorbed the whole queue
    # into in-flight proposals; the watchdog reads the OUTSTANDING
    # count (queue + in-flight) so a stalled node still shows work
    assert honest.outstanding_tx_count() > 0
    wd = cluster.watchdogs["node000"]
    # synthetic clock: drive past the budget without sleeping
    assert wd.check(now=honest.metrics._t0 + 1000.0) == "down"
    block = honest.metrics.snapshot()["alerts"]
    assert block[EPOCH_STALL]["active"] is True
    assert block[EPOCH_STALL]["count"] == 1
    # the firing is on the flight-recorder timeline next to the
    # protocol events that explain it
    alerts = [
        ev for ev in honest.trace.events() if ev[3] == "alert"
    ]
    assert alerts and alerts[0][4] == EPOCH_STALL


@pytest.mark.faults
def test_cluster_health_degrades_under_partition():
    """PR-1 fault + telemetry: an injected partition flips the
    channel-transport /healthz verdict to DEGRADED via the peer-state
    detector (ChannelNetwork.link_states)."""
    cluster = SimulatedCluster(
        config=Config(n=4, batch_size=8, seed=5), seed=5
    )
    for i in range(8):
        cluster.submit(b"part-%03d" % i)
    cluster.run_epochs()
    assert cluster.health()["status"] == "up"
    cluster.partition("node000", "node001")
    doc = cluster.health()
    assert doc["status"] == "degraded"
    assert doc["nodes"]["node000"] == "degraded"
    assert doc["nodes"]["node002"] == "up"  # unaffected pair stays UP
    cluster.net.heal("node000", "node001")
    assert cluster.health()["status"] == "up"


# ---------------------------------------------------------------------------
# layer 1+2: live endpoints on both transports
# ---------------------------------------------------------------------------


def test_cluster_obs_endpoints_scrape():
    cluster = SimulatedCluster(
        config=Config(n=4, batch_size=8, seed=7, trace=True, obs_port=0),
        seed=7,
    )
    try:
        for i in range(16):
            cluster.submit(b"obs-%03d" % i)
        cluster.run_epochs()
        assert cluster.obs.port is not None
        base = f"http://127.0.0.1:{cluster.obs.port}"
        status, text = _get(base + "/metrics")
        assert status == 200
        # the acceptance surface: epoch-latency buckets, transport
        # frames, alert counters — for every roster member
        for nid in cluster.ids:
            assert (
                f'cleisthenes_epoch_latency_seconds_bucket{{node="{nid}"'
                in text
            )
            assert (
                f'cleisthenes_transport_frames_total{{node="{nid}",'
                f'result="delivered"}}' in text
            )
            assert (
                f'cleisthenes_alerts_total{{node="{nid}",'
                f'alert="epoch_stall"}} 0' in text
            )
        assert 'cleisthenes_health{node="node000"} 2' in text
        status, body = _get(base + "/healthz")
        assert status == 200 and json.loads(body)["status"] == "up"
        status, body = _get(base + "/vars")
        vars_doc = json.loads(body)
        assert set(vars_doc) == set(cluster.ids)
        assert "timeseries" in vars_doc["node000"]  # sampler rings ride /vars
        node0 = vars_doc["node000"]["metrics"]
        assert node0["epochs_committed"] >= 1
        assert set(node0["transport"]) == {
            "delivered", "rejected", "dedup_absorbed",
            "frames_decoded", "decode_memo_hits",
            "decode_memo_misses", "mac_verify_batches",
            "frames_encoded", "encode_memo_hits",
            "encode_memo_misses", "mac_sign_batches",
        }
        assert node0["alerts"][EPOCH_STALL]["active"] is False
        status, _ = _get(base + "/nope")
        assert status == 404
    finally:
        cluster.stop()


@pytest.mark.faults
def test_host_obs_endpoints_and_healthz_degrades_on_peer_crash():
    """The gRPC acceptance path: scrape a running ValidatorHost's
    /metrics (buckets + transport health + alerts present), then kill
    a peer — the survivor's /healthz must leave UP once its dial layer
    notices the lost stream."""
    from cleisthenes_tpu.protocol.honeybadger import setup_keys
    from cleisthenes_tpu.transport.host import ValidatorHost
    import threading

    cfg = Config(
        n=4, batch_size=8, seed=5, obs_port=0,
        dial_retry_base_s=0.05, dial_retry_max_s=0.2,
    )
    ids = [f"n{i}" for i in range(4)]
    keys = setup_keys(cfg, ids, seed=3)
    hosts = {i: ValidatorHost(cfg, i, ids, keys[i]) for i in ids}
    try:
        addrs = {i: h.listen() for i, h in hosts.items()}
        threads = [
            threading.Thread(target=h.connect, args=(addrs,))
            for h in hosts.values()
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for i in range(8):
            hosts[ids[i % 4]].submit(b"tx-%d" % i)
        for h in hosts.values():
            h.propose()
        hosts[ids[0]].wait_commit(timeout=60)
        base = f"http://127.0.0.1:{hosts[ids[0]].obs.port}"
        status, text = _get(base + "/metrics")
        assert status == 200
        assert 'cleisthenes_epoch_latency_seconds_bucket{node="n0"' in text
        assert 'cleisthenes_peer_health{node="n0",peer="n3",state=' in text
        assert 'cleisthenes_alert_active{node="n0",' in text
        status, body = _get(base + "/healthz")
        assert status == 200 and json.loads(body)["status"] == "up"
        # crash n3: the survivor's dial layer degrades the peer and
        # /healthz must follow
        hosts["n3"].stop()
        deadline = time.monotonic() + 30.0
        verdict = "up"
        while time.monotonic() < deadline:
            _, body = _get(base + "/healthz")
            verdict = json.loads(body)["status"]
            if verdict != "up":
                break
            time.sleep(0.2)
        assert verdict == "degraded"
        snap = hosts[ids[0]].node.metrics.snapshot()
        assert snap["transport_health"]["n3"]["state"] != "up"
    finally:
        for h in hosts.values():
            h.stop()
        # let the dying streams' close callbacks finish logging while
        # this test's capture streams are still open (the "peer stream
        # lost" warnings ride gRPC reader threads)
        time.sleep(0.3)


def test_demo_obs_port_flag(capsys):
    from cleisthenes_tpu import demo

    rc = demo.main(
        ["--n", "4", "--txs", "8", "--batch-size", "8",
         "--obs-port", "0"]
    )
    # same grace as the host test above: demo.main's stopped hosts
    # flush their stream-lost warnings on gRPC reader threads
    time.sleep(0.3)
    assert rc == 0
    out = capsys.readouterr().out
    assert "telemetry (/metrics /healthz /vars)" in out


# ---------------------------------------------------------------------------
# layer 3: the perf-regression observatory
# ---------------------------------------------------------------------------


def test_perfgate_seed_then_pass_then_inflated_fail(tmp_path):
    """The acceptance criterion end to end: run 1 seeds the trend,
    run 2 on the same seed passes within the noise band, and a record
    with an artificially inflated epoch p50 fails the gate."""
    from tools import perfgate

    trend = str(tmp_path / "trend.jsonl")
    args = ["--trend", trend, "--n", "4", "--batch", "16",
            "--epochs", "2", "--seed", "1999"]
    assert perfgate.main(args) == 0  # seeds
    records = perfgate.load_trend(trend)
    assert len(records) == 1
    assert perfgate.main(args) == 0  # same seed: within noise band
    records = perfgate.load_trend(trend)
    assert len(records) == 2
    # identical seeded runs dispatch identically (the deterministic
    # regression signal the gate leans on)
    assert records[0]["hub_dispatches"] == records[1]["hub_dispatches"]
    assert records[0]["stage_shares"], "traced run carries stage shares"
    inflated = dict(records[-1])
    # the gate keys on the ORDERED-frontier p50 when both sides carry
    # it (two-frontier commit split) and falls back to epoch_p50_ms
    # otherwise — inflate both so either key path trips
    for key in ("epoch_p50_ms", "ordered_epoch_p50_ms"):
        if isinstance(inflated.get(key), (int, float)):
            inflated[key] = inflated[key] * 100 + 10_000
    bad = tmp_path / "inflated.json"
    bad.write_text(json.dumps(inflated), encoding="utf-8")
    assert perfgate.main(args + ["--record", str(bad)]) == 1
    # --record never pollutes the trend
    assert len(perfgate.load_trend(trend)) == 2


def test_perfgate_share_stall_retries_but_real_leak_fails(
    tmp_path, monkeypatch
):
    """A one-sample scheduler stall (one stage's share inflated on the
    first measurement, clean on the re-measure) passes; a leak that
    reproduces on every sample still fails the share gate."""
    from tools import perfgate

    base = {
        "kind": "perfgate_mini",
        "fingerprint": {"kind": "perfgate_mini", "n": 4},
        "epoch_p50_ms": 50.0,
        "hub_dispatches": 30,
        "stage_shares": {"transport": 0.3, "rbc": 0.2},
    }
    trend = str(tmp_path / "trend.jsonl")
    perfgate.append_record(trend, base)
    stalled = dict(base, stage_shares={"transport": 0.7, "rbc": 0.1})
    clean = dict(base)

    def make_sampler(samples):
        it = iter(samples)

        def sample(**kwargs):
            return dict(next(it))

        return sample

    # stall on sample 1, clean on the retry: the min-share re-measure
    # absorbs it
    monkeypatch.setattr(
        perfgate, "run_sample", make_sampler([stalled, clean, clean])
    )
    assert perfgate.main(["--trend", trend, "--no-append"]) == 0
    # the same inflated share on EVERY sample is a real leak
    monkeypatch.setattr(
        perfgate,
        "run_sample",
        make_sampler([stalled, stalled, stalled]),
    )
    assert perfgate.main(["--trend", trend, "--no-append"]) == 1


def test_perfgate_inflated_total_is_not_share_gated():
    """A fresh run whose own epoch p50 blew past the trend median is
    host noise: its shares are meaningless and must not trip the
    share gate (the p50 band still guards real regressions)."""
    from tools import perfgate

    base = {
        "fingerprint": {"kind": "t"},
        "epoch_p50_ms": 50.0,
        "hub_dispatches": 30,
        "stage_shares": {"transport": 0.3, "rbc": 0.2},
    }
    trend = [dict(base) for _ in range(3)]
    # total within the p50 noise band but >1.25x the median, shares
    # skewed by the stall: share gate skipped, run passes
    noisy = dict(
        base,
        epoch_p50_ms=80.0,
        stage_shares={"transport": 0.7, "rbc": 0.1},
    )
    ok, reasons = perfgate.compare(noisy, trend)
    assert ok, reasons
    # same skew at an un-inflated total IS a leak hiding inside an
    # unchanged total — exactly what the share gate is for
    leak = dict(base, stage_shares={"transport": 0.7, "rbc": 0.1})
    ok, reasons = perfgate.compare(leak, trend)
    assert not ok and any("stage-share" in r for r in reasons)
    # two-frontier records: the gate keys on the ordered p50.  A
    # settle-track leak keeps the ordered p50 flat while the loop
    # total grows — the skip must NOT treat that as host noise
    base2 = dict(base, ordered_epoch_p50_ms=30.0)
    trend2 = [dict(base2) for _ in range(3)]
    settle_leak = dict(
        base2,
        epoch_p50_ms=80.0,
        stage_shares={"transport": 0.7, "rbc": 0.1},
    )
    ok, reasons = perfgate.compare(settle_leak, trend2)
    assert not ok and any("stage-share" in r for r in reasons)
    # whereas a stall that inflates the ordered p50 itself (but stays
    # inside the 2x band) is host noise: shares skipped
    stalled = dict(
        base2,
        epoch_p50_ms=80.0,
        ordered_epoch_p50_ms=55.0,
        stage_shares={"transport": 0.7, "rbc": 0.1},
    )
    ok, reasons = perfgate.compare(stalled, trend2)
    assert ok, reasons


def test_perfgate_dispatch_regression_is_noise_free(tmp_path):
    from tools import perfgate

    base = {
        "fingerprint": {"kind": "t"},
        "epoch_p50_ms": 50.0,
        "hub_dispatches": 30,
        "stage_shares": {"hub": 0.5, "rbc": 0.3},
    }
    trend = [dict(base) for _ in range(3)]
    ok, _ = perfgate.compare(dict(base), trend)
    assert ok
    worse = dict(base, hub_dispatches=60)
    ok, reasons = perfgate.compare(worse, trend)
    assert not ok and any("dispatch" in r for r in reasons)
    shifted = dict(base, stage_shares={"hub": 0.2, "rbc": 0.8})
    ok, reasons = perfgate.compare(shifted, trend)
    assert not ok and any("stage-share" in r for r in reasons)
    # a large IMPROVEMENT passes (the gate is one-sided)
    better = dict(base, epoch_p50_ms=1.0, hub_dispatches=10)
    ok, _ = perfgate.compare(better, trend)
    assert ok


def test_perfgate_trend_file_tolerates_torn_lines(tmp_path):
    from tools import perfgate

    trend = tmp_path / "trend.jsonl"
    good = {"fingerprint": {"k": 1}, "epoch_p50_ms": 5.0}
    trend.write_text(
        json.dumps(good) + "\n{torn json...\n" + json.dumps(good) + "\n",
        encoding="utf-8",
    )
    assert len(perfgate.load_trend(str(trend))) == 2


def test_bench_trend_append_extracts_sections(tmp_path):
    from tools import perfgate

    result = {
        "metric": "epoch_crypto_p50_n128_f42_b10k",
        "platform": "cpu",
        "protocol_n16": {
            "n": 16,
            "batch": 1024,
            "tpu": None,
            "cpu": {
                "epoch_p50_ms": 1234.5,
                "epoch_times_ms": [1200.0, 1234.5, 1300.0],
                "tx_per_sec": 800.0,
                "hub_dispatches_cluster": 99,
            },
            "vs_cpu": None,
        },
    }
    path = str(tmp_path / "trend.jsonl")
    assert perfgate.append_bench_trend(result, path) == 1
    rec = perfgate.load_trend(path)[0]
    assert rec["fingerprint"]["section"] == "protocol_n16"
    assert rec["fingerprint"]["backend"] == "cpu"
    assert rec["epoch_p50_ms"] == 1234.5
    assert rec["hub_dispatches"] == 99
