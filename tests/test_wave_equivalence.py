"""Scalar-flush vs columnar-wave-flush equivalence (ISSUE 7).

The wave refactor moved batched crypto to the transport's quiescence
points: one columnar flush per message wave instead of one scalar
flush per quorum event.  That reshuffles WHEN verdicts apply and what
each outbound bundle carries — but it must never reshuffle WHAT the
roster commits.  ``Config.hub_wave_flush=False`` keeps the pre-wave
scalar discipline as a live comparison arm; these tests run the same
seeded schedule under both disciplines and require byte-identical
committed ledgers on both transports, plus a cross-PYTHONHASHSEED
subprocess check that the new wave ordering itself (drain order, wave
widths, dispatch counts) is hash-seed independent.
"""

from __future__ import annotations

import hashlib
import os
import pathlib
import subprocess
import sys
import threading

import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from cleisthenes_tpu.config import Config  # noqa: E402
from cleisthenes_tpu.core.ledger import encode_batch_body  # noqa: E402
from cleisthenes_tpu.protocol.cluster import SimulatedCluster  # noqa: E402


def _channel_ledger_digest(wave_flush: bool) -> tuple:
    """(ledger digest, committed depth, hub dispatch count) for one
    seeded 4-node channel-transport run under the given discipline."""
    cluster = SimulatedCluster(
        config=Config(
            n=4, batch_size=8, seed=4321, hub_wave_flush=wave_flush
        ),
        seed=4321,
        key_seed=9,
    )
    for i in range(24):
        cluster.submit(b"wave-tx-%04d" % i)
    cluster.run_epochs()
    depth = cluster.assert_agreement()
    h = hashlib.sha256()
    for nid in cluster.ids:
        for epoch, batch in enumerate(
            cluster.nodes[nid].committed_batches
        ):
            h.update(encode_batch_body(epoch, batch))
    hub = cluster.nodes[cluster.ids[0]].hub
    return h.hexdigest(), depth, hub.stats()["dispatches"]


def test_scalar_vs_wave_identical_ledgers_channel():
    wave = _channel_ledger_digest(wave_flush=True)
    scalar = _channel_ledger_digest(wave_flush=False)
    assert wave[1] >= 2 and scalar[1] >= 2  # both actually committed
    assert wave[0] == scalar[0], (
        "columnar wave flush committed different ledger bytes than "
        f"the scalar discipline:\n  wave:   {wave}\n  scalar: {scalar}"
    )
    # and the refactor's entire point: the wave discipline needs FEWER
    # dispatches for the same schedule, never more
    assert wave[2] <= scalar[2], (wave[2], scalar[2])


def _grpc_epoch0_bodies(wave_flush: bool) -> list:
    """Every node's encoded epoch-0 batch body from one 4-node run
    over real localhost gRPC under the given flush discipline."""
    from cleisthenes_tpu.protocol.honeybadger import setup_keys
    from cleisthenes_tpu.transport.host import ValidatorHost

    n = 4
    cfg = Config(n=n, batch_size=8, seed=77, hub_wave_flush=wave_flush)
    ids = [f"node{i}" for i in range(n)]
    keys = setup_keys(cfg, ids, seed=55)
    hosts = {i: ValidatorHost(cfg, i, ids, keys[i]) for i in ids}
    try:
        addrs = {i: h.listen() for i, h in hosts.items()}
        threads = [
            threading.Thread(target=h.connect, args=(addrs,))
            for h in hosts.values()
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=15)
        for i in range(8):
            hosts[ids[i % n]].submit(b"grpc-wave-%02d" % i)
        for h in hosts.values():
            h.propose()
        first = {i: h.wait_commit(timeout=60) for i, h in hosts.items()}
        assert {e for e, _ in first.values()} == {0}
        return [encode_batch_body(0, b) for _, b in first.values()]
    finally:
        for h in hosts.values():
            h.stop()


def test_scalar_vs_wave_identical_ledgers_grpc():
    """Same roster, same submissions, real sockets: the wave and
    scalar disciplines must commit byte-identical epoch-0 batches
    (deterministic proposal sampling + full proposal inclusion on a
    quiet loopback make the committed bytes a pure function of the
    inputs, not of the flush discipline)."""
    wave = _grpc_epoch0_bodies(wave_flush=True)
    scalar = _grpc_epoch0_bodies(wave_flush=False)
    # within-run agreement is byte-exact on both arms...
    assert all(b == wave[0] for b in wave)
    assert all(b == scalar[0] for b in scalar)
    # ...and across the discipline boundary too
    assert wave[0] == scalar[0], (
        "wave vs scalar gRPC runs committed different epoch-0 bytes"
    )


# Prints one line digesting the ledger bytes AND the wave structure
# itself: per-run hub wave widths, dispatch count, and column item
# totals.  Two PYTHONHASHSEED values must produce identical lines —
# hash-order iteration anywhere in the drain/dispatch path would show
# up as different wave compositions even when ledgers converge.
_WAVE_DRIVER = r"""
import hashlib
from cleisthenes_tpu.config import Config
from cleisthenes_tpu.core.ledger import encode_batch_body
from cleisthenes_tpu.protocol.cluster import SimulatedCluster

cluster = SimulatedCluster(
    config=Config(n=4, batch_size=8, seed=2026, hub_wave_flush=True),
    seed=2026,
    key_seed=3,
)
for i in range(24):
    cluster.submit(b"wave-hs-%04d" % i)
cluster.run_epochs()
depth = cluster.assert_agreement()
assert depth >= 2, f"want >=2 committed epochs, got {depth}"
h = hashlib.sha256()
for nid in cluster.ids:
    for epoch, batch in enumerate(cluster.nodes[nid].committed_batches):
        h.update(encode_batch_body(epoch, batch))
hub = cluster.nodes[cluster.ids[0]].hub
st = hub.stats()
print(
    "WAVE_DIGEST=%s widths=%s dispatches=%d items=%d/%d/%d"
    % (
        h.hexdigest(),
        ",".join(str(w) for w in hub.wave_widths),
        st["dispatches"],
        st["branch_items"],
        st["decode_items"],
        st["share_items"],
    )
)
"""


def _run_wave_driver(hashseed: str) -> str:
    env = dict(os.environ)
    env["PYTHONHASHSEED"] = hashseed
    env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.run(
        [sys.executable, "-c", _WAVE_DRIVER],
        cwd=REPO,
        env=env,
        capture_output=True,
        text=True,
        timeout=300,
    )
    assert proc.returncode == 0, (
        f"PYTHONHASHSEED={hashseed} wave run failed:\n"
        f"{proc.stdout}\n{proc.stderr}"
    )
    for line in proc.stdout.splitlines():
        if line.startswith("WAVE_DIGEST="):
            return line
    raise AssertionError(f"no wave digest line:\n{proc.stdout}")


def test_wave_ordering_identical_across_hash_seeds():
    a = _run_wave_driver("1")
    b = _run_wave_driver("2")
    assert a == b, (
        "wave composition diverged across PYTHONHASHSEED values:\n"
        f"  {a}\n  {b}\n-> hash-order iteration is leaking into the "
        "hub's drain/dispatch path (see staticcheck DET002/DET003)"
    )
