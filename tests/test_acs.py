"""ACS tests: validity, agreement, totality (docs/HONEYBADGER-EN.md:34-37)
over the deterministic in-proc transport."""

import pytest

from cleisthenes_tpu.config import Config
from cleisthenes_tpu.ops import tpke
from cleisthenes_tpu.ops.backend import get_backend
from cleisthenes_tpu.ops.coin import CommonCoin
from cleisthenes_tpu.protocol.acs import ACS
from cleisthenes_tpu.transport.base import HmacAuthenticator
from cleisthenes_tpu.transport.broadcast import ChannelBroadcaster
from cleisthenes_tpu.transport.channel import ChannelNetwork


class AcsHandler:
    def __init__(self, acs: ACS):
        self.acs = acs

    def serve_request(self, msg):
        self.acs.handle_message(msg.sender_id, msg.payload)


def make_acs_network(n, seed=None, auth=False):
    cfg = Config(n=n)
    crypto = get_backend(cfg)
    ids = [f"node{i}" for i in range(n)]
    pub, secrets = tpke.deal(n, cfg.f + 1, seed=21)
    coin = CommonCoin(pub)
    net = ChannelNetwork(seed=seed)
    acss = {}
    for i, node_id in enumerate(ids):
        acs = ACS(
            config=cfg,
            crypto=crypto,
            epoch=0,
            owner=node_id,
            member_ids=ids,
            coin=coin,
            coin_secret=secrets[i],
            out=ChannelBroadcaster(net, node_id, ids),
        )
        acss[node_id] = acs
        net.join(
            node_id,
            AcsHandler(acs),
            HmacAuthenticator.derive(b"acs-master", node_id, ids)
            if auth
            else None,
        )
    return cfg, net, acss


def proposals(acss):
    return {nid: f"proposal-from-{nid}".encode() * 8 for nid in acss}


def assert_common_output(acss, skip=()):
    outs = {nid: a.output() for nid, a in acss.items() if nid not in skip}
    assert all(o is not None for o in outs.values()), {
        k: (v if v is None else len(v)) for k, v in outs.items()
    }
    first = next(iter(outs.values()))
    for nid, o in outs.items():
        assert o == first, f"{nid} disagrees"
    return first


def test_acs_all_inputs_all_output_same_set():
    cfg, net, acss = make_acs_network(4)
    props = proposals(acss)
    for nid, acs in acss.items():
        acs.input(props[nid])
    net.run()
    out = assert_common_output(acss)
    # validity: at least n-f proposals make it
    assert len(out) >= cfg.n - cfg.f
    for proposer, value in out.items():
        assert value == props[proposer]


@pytest.mark.parametrize("seed", [1, 4, 9, 23])
def test_acs_agreement_under_adversarial_scheduling(seed):
    cfg, net, acss = make_acs_network(4, seed=seed, auth=True)
    props = proposals(acss)
    for nid, acs in acss.items():
        acs.input(props[nid])
    net.run()
    out = assert_common_output(acss)
    assert len(out) >= cfg.n - cfg.f


@pytest.mark.parametrize("seed", [2, 7])
def test_acs_n7_with_f_crashed_nodes(seed):
    cfg, net, acss = make_acs_network(7, seed=seed)
    crashed = ("node5", "node6")
    for c in crashed:
        net.crash(c)
    props = proposals(acss)
    for nid, acs in acss.items():
        if nid not in crashed:
            acs.input(props[nid])
    net.run()
    out = assert_common_output(acss, skip=crashed)
    assert len(out) >= cfg.n - cfg.f
    # crashed nodes' proposals were never made, so can't be in the set
    for c in crashed:
        assert c not in out


def test_acs_silent_proposer_excluded_but_others_commit():
    """One correct-but-silent node (no input) must not block ACS."""
    cfg, net, acss = make_acs_network(4, seed=3)
    props = proposals(acss)
    for nid, acs in acss.items():
        if nid != "node2":
            acs.input(props[nid])
    net.run()
    out = assert_common_output(acss)
    assert len(out) >= cfg.n - cfg.f
    for proposer, value in out.items():
        assert value == props[proposer]


def test_acs_output_fires_exactly_once():
    cfg, net, acss = make_acs_network(4)
    fired = []
    acss["node1"].on_output = lambda epoch, out: fired.append((epoch, out))
    props = proposals(acss)
    for nid, acs in acss.items():
        acs.input(props[nid])
    net.run()
    assert len(fired) == 1
    assert fired[0][0] == 0
    assert fired[0][1] == acss["node1"].output()


def test_coin_index_replay_does_not_stall():
    """A Byzantine member re-issuing an HONEST node's coin shares
    (same Shamir index, valid CP proof — the textbook share replay)
    must not stall any instance's coin: a threshold-SIZE pool can be
    index-under-covered, and the row store's watch re-notification
    must pull genuinely distinct indices as they arrive (the coin
    analog of the round-4 dec-share crossing-stall regression)."""
    for seed in (None, 3, 11):
        cfg, net, acss = make_acs_network(4, seed=seed)
        # node3 clones node0's coin secret: every share it issues is a
        # byte-perfect replay of node0's (valid, index-colliding)
        donor = acss["node0"].bbas["node0"].coin_secret
        for bba in acss["node3"].bbas.values():
            bba.coin_secret = donor
        props = proposals(acss)
        for nid, acs in acss.items():
            acs.input(props[nid])
        net.run()
        out = assert_common_output(acss)
        assert set(out) == set(props) or len(out) >= len(acss) - cfg.f
