"""VoteBank (protocol/votebank.py): columnar BVAL/AUX receipt state.

The bank is the single source of truth for the current round's vote
bookkeeping across all of an epoch's BBA instances; these tests pin
the columnar/scalar equivalence and the Byzantine-shaped edges the
batch path must absorb (duplicate proposers in one frame, stale
rounds, unknown senders/instances, halted rows)."""

import numpy as np

from cleisthenes_tpu.protocol.votebank import VoteBank
from cleisthenes_tpu.transport.message import BbaType

MEMBERS = [f"n{i}" for i in range(4)]


class _StubBBA:
    """Records crossing callbacks; stands in for protocol.bba.BBA."""

    def __init__(self):
        self.halted = False
        self.relays = []
        self.bins = []
        self.aux_quorums = 0
        self.parked = []

    def on_bval_relay(self, value):
        self.relays.append(value)

    def on_bval_bin(self, value):
        self.bins.append(value)

    def on_aux_quorum(self):
        self.aux_quorums += 1

    def handle_vote(self, sender, t, rnd, value):
        self.parked.append((sender, t, rnd, value))


def _bank(f=1):
    bank = VoteBank(MEMBERS, f)
    bbas = []
    for i, m in enumerate(MEMBERS):
        b = _StubBBA()
        bank.attach(i, b)
        bbas.append(b)
    return bank, bbas


def test_scalar_and_columnar_counts_agree():
    bank, bbas = _bank()
    # columnar: n0 votes BVAL(True) across all instances
    bank.batch_vote("n0", True, 0, True, tuple(MEMBERS))
    # scalar write-through for one instance from n1
    assert bank.bval_add(2, bank.sidx["n1"], True) == 2
    assert int(bank.bval_cnt[1, 2]) == 2
    assert int(bank.bval_cnt[1, 0]) == 1
    # duplicate scalar add is rejected
    assert bank.bval_add(2, bank.sidx["n1"], True) is None


def test_crossings_fire_exactly_once_per_threshold():
    bank, bbas = _bank(f=1)
    # f+1 = 2 distinct senders -> relay; 2f+1 = 3 -> bin growth
    for s in ("n0", "n1", "n2"):
        bank.batch_vote(s, True, 0, True, tuple(MEMBERS))
    for b in bbas:
        assert b.relays == [True]
        assert b.bins == [True]
    # a 4th sender crosses no new threshold
    bank.batch_vote("n3", True, 0, True, tuple(MEMBERS))
    for b in bbas:
        assert b.relays == [True] and b.bins == [True]


def test_duplicate_proposers_in_one_frame_count_once():
    bank, bbas = _bank(f=1)
    dup = (MEMBERS[0],) * 5 + tuple(MEMBERS)
    bank.batch_vote("n0", True, 0, True, dup)
    assert int(bank.bval_cnt[1, 0]) == 1  # one sender, one count


def test_duplicate_frames_from_same_sender_count_once():
    bank, bbas = _bank(f=1)
    bank.batch_vote("n0", True, 0, True, tuple(MEMBERS))
    bank.batch_vote("n0", True, 0, True, tuple(MEMBERS))
    assert int(bank.bval_cnt[1, 0]) == 1


def test_stale_votes_drop_without_scalar_fallback():
    bank, bbas = _bank()
    bank.reset_row(0, 3)  # instance 0 is at round 3
    bank.batch_vote("n0", True, 1, True, (MEMBERS[0],))
    assert bbas[0].parked == []  # stale: vectorized drop
    assert int(bank.bval_cnt[1, 0]) == 0


def test_future_votes_park_via_scalar_fallback():
    bank, bbas = _bank()
    bank.batch_vote("n0", True, 2, True, (MEMBERS[1],))
    assert bbas[1].parked == [("n0", BbaType.BVAL, 2, True)]


def test_unknown_sender_and_instance_ignored():
    bank, bbas = _bank()
    bank.batch_vote("stranger", True, 0, True, tuple(MEMBERS))
    bank.batch_vote("n0", True, 0, True, ("ghost",))
    assert not bank.bval_seen.any()


def test_halted_rows_drop_vectorized():
    bank, bbas = _bank()
    bank.deactivate(1)
    bank.batch_vote("n0", True, 0, True, tuple(MEMBERS))
    assert int(bank.bval_cnt[1, 1]) == 0
    assert int(bank.bval_cnt[1, 0]) == 1


def test_aux_quorum_trigger_needs_bin_flags():
    bank, bbas = _bank(f=1)  # n-f = 3
    for s in ("n0", "n1", "n2"):
        bank.batch_vote(s, False, 0, True, tuple(MEMBERS))  # AUX
    # no bin flags yet: no quorum callbacks
    assert all(b.aux_quorums == 0 for b in bbas)
    bank.set_bin(0, True)
    # quorum computed on the NEXT aux arrival for instance 0
    bank.batch_vote("n3", False, 0, True, (MEMBERS[0],))
    assert bbas[0].aux_quorums == 1
    assert bank.aux_good(0) == 4
    assert bank.aux_vals(0) == {True}


def test_reset_row_clears_everything():
    bank, bbas = _bank()
    bank.batch_vote("n0", True, 0, True, tuple(MEMBERS))
    bank.batch_vote("n0", False, 0, False, tuple(MEMBERS))
    bank.set_bin(0, True)
    bank.reset_row(0, 1)
    assert not bank.bval_seen[:, :, 0].any()
    assert not bank.aux_seen[:, 0].any()
    assert not bank.bin_flags[0].any()
    assert bank.round_state[0] == 1
    # other rows untouched
    assert bank.bval_seen[:, :, 1].any()
