"""The whole-program analyzer (ISSUE 14): cross-module registry
index, audit mode, SARIF output, and the wall-budget regression.

The per-rule fixture corpus rides tests/test_staticcheck.py; this
module covers what only the TWO-PASS analysis can see — the
cross-module fixture trees under tests/staticcheck_fixtures/xmodule/
stand up miniature wire/pb, metrics/exposition/golden, and
config/perfgate/tests registries and assert the exact cross-file
findings (bad) and a clean bill (good)."""

from __future__ import annotations

import json
import pathlib
import subprocess
import sys
import time

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from tools.staticcheck.core import (  # noqa: E402
    check_paths,
    load_pragma_budget,
)

XMODULE = REPO / "tests" / "staticcheck_fixtures" / "xmodule"


def _findings(root):
    found, _n = check_paths([root], root)
    return {(f.rule, f.path, f.line) for f in found}


def test_xmodule_bad_tree_exact_cross_module_findings():
    """Each defect lives in a DIFFERENT file from the registry that
    convicts it: flag vs fingerprint/tests, counter vs snapshot,
    family vs golden, kind vs pb adapter."""
    assert _findings(XMODULE / "bad") == {
        # xb_turbo is read+pinned but missing from tools/perfgate.py's
        # fingerprint dict
        ("ARM001", "pkg/config.py", 12),
        # xb_nitro is read+fingerprinted but never pinned in tests/
        ("ARM001", "pkg/config.py", 13),
        # xb_gears (int arm) is read+fingerprinted but pins only ONE
        # distinct value in tests/ (the baseline; no fast-arm pin)
        ("ARM001", "pkg/config.py", 14),
        # xb_lost_total is incremented in pkg/engine.py but never
        # reaches pkg/metrics.py's snapshot()
        ("SCHEMA001", "pkg/metrics.py", 16),
        # the golden's xb_ghost_total is emitted by no exposition
        ("SCHEMA001", "pkg/obs.py", 1),
        # xb_stray_total is emitted but absent from the golden
        ("SCHEMA001", "pkg/obs.py", 12),
        # _KIND_TWO has no slot in the import-stem-paired pb adapter
        ("WIRE001", "pkg/transport/wiremsg.py", 5),
    }


def test_xmodule_good_tree_is_clean():
    assert _findings(XMODULE / "good") == set()


def test_callgraph_bad_tree_exact_cross_module_findings():
    """Pass 3 (ISSUE 17): each conviction needs a call edge into
    ANOTHER file — the guarded class, the blocking helper and the
    entropy source all live one module away from the code that
    misuses them."""
    assert _findings(XMODULE / "callgraph_bad") == {
        # clock.wall's direct wall-clock read (per-file DET001)...
        ("DET001", "pkg/protocol/clock.py", 5),
        # ...and where its return value LANDS two files away
        ("DET007", "pkg/protocol/engine.py", 15),
        # engine calls state.Table._get_locked() holding no lock
        ("CONC003", "pkg/protocol/engine.py", 10),
        # conn.handle_frame reaches helpers.slow_write's fsync;
        # the finding sits at the BLOCKING line, not the handler
        ("CONC004", "pkg/transport/helpers.py", 5),
    }


def test_callgraph_good_tree_is_clean():
    assert _findings(XMODULE / "callgraph_good") == set()


def test_callgraph_findings_carry_their_evidence_chain():
    """CONC004's related tuple is the hop-by-hop call path from the
    handler entry down to the blocking call — the debuggability
    contract the SARIF relatedLocations ride on."""
    root = XMODULE / "callgraph_bad"
    found, _n = check_paths([root], root)
    by_rule = {f.rule: f for f in found}
    chain = by_rule["CONC004"].related
    assert [(p, ln) for p, ln, _note in chain] == [
        ("pkg/transport/conn.py", 11),
        ("pkg/transport/helpers.py", 4),
    ]
    assert "handle_frame" in chain[0][2]
    # CONC003/DET007 point back at the defining/origin site
    assert by_rule["CONC003"].related[0][:2] == (
        "pkg/protocol/state.py",
        12,
    )
    assert by_rule["DET007"].related[0][:2] == (
        "pkg/protocol/clock.py",
        4,
    )


def test_xmodule_good_breaks_when_fingerprint_key_removed(tmp_path):
    """The index really reads the OTHER file: deleting the good
    tree's fingerprint key manufactures the ARM001 finding."""
    import shutil

    root = tmp_path / "tree"
    shutil.copytree(XMODULE / "good", root)
    pg = root / "tools" / "perfgate.py"
    pg.write_text(
        pg.read_text(encoding="utf-8").replace(
            '"xg_turbo": bool(cfg.xg_turbo),', ""
        ),
        encoding="utf-8",
    )
    rules = {f[0] for f in _findings(root)}
    assert rules == {"ARM001"}


# ---------------------------------------------------------------------------
# audit mode
# ---------------------------------------------------------------------------


def _write_plane_file(tmp_path, body):
    mod = tmp_path / "protocol" / "mod.py"
    mod.parent.mkdir(exist_ok=True)
    mod.write_text(body, encoding="utf-8")
    return mod


# assembled from pieces so the tree-wide audit of THIS file's source
# never sees a pragma-shaped line of its own
_P = "# staticcheck" + ": "
AUDIT_SRC = (
    "import time\n"
    "\n"
    "\n"
    "def f():\n"
    "    return time.time()  " + _P + "allow[DET001] sanctioned\n"
    "x = 1  " + _P + "allow[DET002] nothing ever fired here\n"
)


def test_audit_reports_stale_pragma_and_keeps_live_one(tmp_path):
    _write_plane_file(tmp_path, AUDIT_SRC)
    findings, _n = check_paths(
        [tmp_path], tmp_path, audit=True, pragma_budget=None
    )
    by_rule = {}
    for f in findings:
        by_rule.setdefault(f.rule, []).append(f)
    # the DET001 pragma suppresses a real finding: not stale; the
    # DET002 pragma suppresses nothing: PRAGMA002 at its exact line
    assert "DET001" not in by_rule
    stale = by_rule.pop("PRAGMA002")
    assert [(f.line) for f in stale] == [6]
    assert "allow-file" not in stale[0].message
    assert not by_rule  # nothing else


def test_audit_budget_gates_pragma_growth(tmp_path):
    _write_plane_file(tmp_path, AUDIT_SRC)
    over, _n = check_paths(
        [tmp_path], tmp_path, audit=True, pragma_budget=1
    )
    assert any(f.rule == "PRAGMA003" for f in over)
    under, _n = check_paths(
        [tmp_path], tmp_path, audit=True, pragma_budget=2
    )
    assert not any(f.rule == "PRAGMA003" for f in under)


def test_tree_pragma_budget_matches_population():
    """The committed budget is EXACT: adding a pragma anywhere in the
    gated tree must force a deliberate budget bump in review."""
    budget = load_pragma_budget()
    assert budget is not None
    targets = [REPO / p for p in ("cleisthenes_tpu", "tools", "tests")]
    findings, _n = check_paths(
        targets, REPO, audit=True, pragma_budget=budget
    )
    assert [f.render() for f in findings] == []
    over, _n = check_paths(
        targets, REPO, audit=True, pragma_budget=budget - 1
    )
    assert any(f.rule == "PRAGMA003" for f in over)


# ---------------------------------------------------------------------------
# CLI: SARIF output + the wall-budget regression
# ---------------------------------------------------------------------------


def _run_cli(*args, timeout=120):
    return subprocess.run(
        [sys.executable, "-m", "tools.staticcheck", *args],
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=timeout,
    )


def test_lone_real_file_scan_has_no_standing_to_convict_absence():
    """Single-file runs of the real registry modules must stay clean:
    'never incremented' / 'never read' / wave-unreachable are claims
    about consumers the scan cannot see (self-contained fixtures keep
    the full rule set — tests/test_staticcheck.py proves they still
    gate)."""
    for rel in (
        "cleisthenes_tpu/utils/metrics.py",
        "cleisthenes_tpu/config.py",
        "cleisthenes_tpu/protocol/acs.py",
    ):
        findings, _n = check_paths([REPO / rel], REPO)
        assert [f.render() for f in findings] == [], rel


def test_rules_subset_does_not_fake_stale_pragmas():
    """--rules narrows the REPORT, not the audit's evidence: pragma
    staleness is judged against every rule's raw findings, so a
    DET001-only run must not declare the WIRE001/DET004 pragmas
    stale."""
    proc = _run_cli(
        "cleisthenes_tpu",
        "tools",
        "tests",
        "--rules",
        "DET001",
        "--audit-pragmas",
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_fingerprint_registry_prefers_real_perfgate():
    """Fingerprint-shaped dict literals in tests must not mask a key
    dropped from the real perfgate fingerprint: with a perfgate.py in
    the scan, only its keys count."""
    from tools.staticcheck.core import _load_contexts
    from tools.staticcheck.program import build_index

    ctxs, _pf, _n = _load_contexts(
        [REPO / p for p in ("cleisthenes_tpu", "tools", "tests")], REPO
    )
    index = build_index(ctxs, REPO)
    # every declared arm flag keys the real fingerprint...
    from cleisthenes_tpu.config import ARM_FLAGS

    assert set(ARM_FLAGS) <= index.fingerprint_keys
    # ...and test_obs's mini record dicts were not unioned in
    assert "k" not in index.fingerprint_keys


def test_sarif_output_is_annotatable():
    proc = _run_cli(
        "tests/staticcheck_fixtures/transport/wire001_bad.py",
        "--format",
        "sarif",
        "--no-baseline",
    )
    assert proc.returncode == 1
    doc = json.loads(proc.stdout)
    assert doc["version"] == "2.1.0"
    run = doc["runs"][0]
    assert run["tool"]["driver"]["name"] == "cleisthenes-staticcheck"
    rule_ids = {r["id"] for r in run["tool"]["driver"]["rules"]}
    assert {"WIRE001", "SCHEMA001", "ARM001", "VERIFY001"} <= rule_ids
    results = run["results"]
    locs = {
        (
            r["ruleId"],
            r["locations"][0]["physicalLocation"]["artifactLocation"][
                "uri"
            ],
            r["locations"][0]["physicalLocation"]["region"]["startLine"],
        )
        for r in results
    }
    rel = "tests/staticcheck_fixtures/transport/wire001_bad.py"
    assert locs == {
        ("WIRE001", rel, 8),
        ("WIRE001", rel, 9),
        ("WIRE001", rel, 10),
    }


def test_sarif_carries_related_locations_for_call_chains():
    """A pass-3 finding's SARIF result embeds the full call chain as
    relatedLocations, so the report alone shows WHY the sink is
    reachable."""
    proc = _run_cli(
        "tests/staticcheck_fixtures/transport/conc004_bad.py",
        "--format",
        "sarif",
        "--no-baseline",
    )
    assert proc.returncode == 1
    doc = json.loads(proc.stdout)
    results = doc["runs"][0]["results"]
    conc004 = [r for r in results if r["ruleId"] == "CONC004"]
    assert conc004
    for r in conc004:
        rels = r["relatedLocations"]
        assert len(rels) >= 2  # >=1 hop + the containing function
        for rel_loc in rels:
            phys = rel_loc["physicalLocation"]
            assert phys["artifactLocation"]["uriBaseId"] == "SRCROOT"
            assert phys["region"]["startLine"] > 0
            assert rel_loc["message"]["text"]
    # the deepest chain walks serve_batch -> _relay -> _deep_relay
    deepest = max(conc004, key=lambda r: len(r["relatedLocations"]))
    notes = [x["message"]["text"] for x in deepest["relatedLocations"]]
    assert "serve_batch" in notes[0] and "_relay" in notes[0]
    assert "blocking call" in notes[-1]


def test_whole_program_pass_under_wall_budget():
    """The two-pass tree-wide run (the exact ci.sh stage-2 command)
    must stay far from being the slow CI stage: zero findings, and
    well under a minute on the tier-1 box (typically a few seconds)."""
    t0 = time.monotonic()
    proc = _run_cli(
        "cleisthenes_tpu", "tools", "tests", "--audit-pragmas"
    )
    elapsed = time.monotonic() - t0
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert elapsed < 60.0, f"staticcheck took {elapsed:.1f}s"
