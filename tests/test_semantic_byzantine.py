"""Semantic Byzantine adversary tests: key-holding nodes that follow
the rules of the wire but not the protocol (protocol.byzantine).

Every frame these adversaries emit carries a valid pairwise MAC — the
transport delivers all of it — and the lies differ PER RECEIVER:
conflicting RBC proposals (Equivocator), split BVAL/AUX votes
(SplitVoter), structurally-valid wrong shards (BadDealer), well-formed
wrong threshold shares (ShareForger), per-link silence (SelectiveMute)
and epoch-window spam (EpochSprayer).  The assertion is always HBBFT's
own contract: the honest majority commits identical ledger prefixes,
on the in-proc channel transport AND over real gRPC.

Module carries the ``faults`` marker (ci.sh fault-regression stage).
"""

import threading

import pytest

from cleisthenes_tpu.protocol.byzantine import (
    BEHAVIOR_KINDS,
    CompositeBehavior,
    EpochSprayer,
    Equivocator,
    SelectiveMute,
    ShareForger,
    make_behavior,
)
from cleisthenes_tpu.protocol.cluster import SimulatedCluster
from cleisthenes_tpu.utils.adversary import Coalition

pytestmark = pytest.mark.faults

SEMANTIC_KINDS = ("equivocator", "split_voter", "bad_dealer")


def drive(cluster, bad, txs=12, max_rounds=30):
    """Submit txs to honest nodes, drain, and return the agreed depth
    (assert_agreement == identical ledger prefixes among the honest)."""
    honest = [i for i in cluster.ids if i not in bad]
    for i in range(txs):
        cluster.submit(b"tx-%04d" % i, node_id=honest[i % len(honest)])
    cluster.run_until_drained(max_rounds=max_rounds, skip=bad)
    return cluster.assert_agreement(skip=bad)


def assert_only_submitted(cluster, bad):
    """No behavior here injects well-formed ciphertexts, so every
    committed tx must be one the test submitted."""
    for nid in cluster.ids:
        if nid in bad:
            continue
        for batch in cluster.nodes[nid].committed_batches:
            for tx in batch.tx_list():
                assert tx.startswith(b"tx-"), tx


@pytest.mark.parametrize("kind", SEMANTIC_KINDS)
@pytest.mark.parametrize(
    "n,bad",
    [(4, ("node003",)), (7, ("node005", "node006"))],
    ids=["n4f1", "n7f2"],
)
def test_semantic_coalition_channel_transport(kind, n, bad):
    """Equivocator / split-voter / bad-dealer coalitions at full fault
    budget: honest nodes commit identical ledger prefixes and every
    behavior actually told lies (rewrites > 0)."""
    behaviors = {
        b: make_behavior(kind, seed=11 + i) for i, b in enumerate(bad)
    }
    c = SimulatedCluster(n=n, batch_size=8, seed=3, behaviors=behaviors)
    depth = drive(c, bad)
    assert depth >= 1
    assert_only_submitted(c, bad)
    for b in c.behaviors.values():
        assert b.rewrites > 0, "the adversary never actually lied"


def test_share_forger_burns_and_still_commits():
    """Forged (well-formed, wrong) coin + TPKE shares: the batched CP
    verification burns them, replacements flow, every honest node
    commits identically AND completely — the liveness-critical share
    attack (arxiv 2407.12172's withholding/forgery class)."""
    bad = ("node000",)  # sorts first: forged shares land early in pools
    c = SimulatedCluster(
        n=4,
        batch_size=8,
        seed=9,
        behaviors={"node000": ShareForger(seed=5)},
    )
    depth = drive(c, bad)
    assert depth >= 1
    assert c.behaviors["node000"].rewrites > 0
    committed = sum(
        len(b) for b in c.nodes["node001"].committed_batches
    )
    assert committed == 12  # liveness: every submitted tx committed


def test_selective_mute_and_sprayer_composed_with_wire_faults():
    """CompositeBehavior(SelectiveMute + EpochSprayer) on one node,
    stacked with a wire-level drop/reorder coalition on the SAME node:
    the semantic and wire planes compose without breaking agreement."""
    bad = ("node003",)
    behavior = CompositeBehavior(
        [SelectiveMute(seed=3), EpochSprayer(seed=4, every=8)]
    )
    c = SimulatedCluster(
        n=4, batch_size=8, seed=7, behaviors={"node003": behavior}
    )
    c.fault_filter = (
        Coalition(["node003"], seed=7).drop(0.2).reorder(0.3).filter
    )
    depth = drive(c, bad)
    assert depth >= 1
    assert_only_submitted(c, bad)
    assert behavior.rewrites > 0


def test_equivocator_splits_roster_but_never_forks():
    """The canonical equivocation check, explicitly: the equivocating
    proposer's two proposals never BOTH commit — honest nodes agree on
    one value for its instance or exclude it entirely."""
    bad = "node000"  # the lowest-sorting proposer equivocates
    c = SimulatedCluster(
        n=4,
        batch_size=8,
        seed=13,
        behaviors={bad: Equivocator(seed=21)},
    )
    depth = drive(c, (bad,))
    assert depth >= 1
    # per-epoch: the bad proposer's contribution (if any) is identical
    # across every honest node — assert_agreement checked bytes; here
    # we check the instance-level view for the equivocator's slot
    for e in range(depth):
        views = {
            tuple(
                c.nodes[nid].committed_batches[e].contributions.get(
                    bad, ()
                )
            )
            for nid in c.ids[1:]
        }
        assert len(views) == 1, f"equivocator forked epoch {e}"


def test_behavior_registry_round_trip():
    """Every registered kind constructs from its JSON-schedule name
    (the tools/fuzz.py repro path) and rejects unknown kinds."""
    for kind in sorted(BEHAVIOR_KINDS):
        b = make_behavior(kind, seed=3)
        assert b.seed == 3
    with pytest.raises(ValueError, match="unknown behavior"):
        make_behavior("nonsense", seed=0)


# ---------------------------------------------------------------------------
# the same adversaries over real gRPC sockets
# ---------------------------------------------------------------------------


def _run_grpc_cluster(n, behaviors, txs=8, key_seed=55):
    """n validators over localhost gRPC, semantic behaviors mounted via
    the ValidatorHost seam; returns {node_id: first committed batch}
    for the honest members."""
    from cleisthenes_tpu.config import Config
    from cleisthenes_tpu.protocol.honeybadger import setup_keys
    from cleisthenes_tpu.transport.host import ValidatorHost

    cfg = Config(n=n, batch_size=8)
    ids = [f"node{i}" for i in range(n)]
    keys = setup_keys(cfg, ids, seed=key_seed)
    hosts = {
        i: ValidatorHost(cfg, i, ids, keys[i], behavior=behaviors.get(i))
        for i in ids
    }
    try:
        addrs = {i: h.listen() for i, h in hosts.items()}
        threads = [
            threading.Thread(target=h.connect, args=(addrs,))
            for h in hosts.values()
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=15)
        honest = [i for i in ids if i not in behaviors]
        for i in range(txs):
            hosts[honest[i % len(honest)]].submit(b"tx-%04d" % i)
        for h in hosts.values():
            h.propose()
        first = {i: hosts[i].wait_commit(timeout=60) for i in honest}
        # the transport counters are reachable through public metrics
        # on this transport too (GrpcServer.stats + pool connections)
        transport = hosts[honest[0]].node.metrics.snapshot()["transport"]
        return first, transport
    finally:
        for h in hosts.values():
            h.stop()


@pytest.mark.parametrize("kind", SEMANTIC_KINDS)
def test_semantic_coalition_over_grpc_n4(kind):
    """(n=4, f=1) semantic coalitions over REAL gRPC streams: honest
    hosts commit the identical first batch — the transport-independence
    half of the 'both transports' contract."""
    first, transport = _run_grpc_cluster(
        4, {"node3": make_behavior(kind, seed=17)}
    )
    epochs = {e for e, _ in first.values()}
    assert epochs == {0}
    lists = [b.tx_list() for _, b in first.values()]
    assert all(l == lists[0] for l in lists)
    assert len(lists[0]) > 0
    assert all(tx.startswith(b"tx-") for tx in lists[0])
    assert transport["delivered"] > 0
    assert transport["rejected"] == 0  # lies were valid frames


@pytest.mark.slow
@pytest.mark.parametrize("kind", SEMANTIC_KINDS)
def test_semantic_coalition_over_grpc_n7(kind):
    """(n=7, f=2) over real gRPC — the full-budget variant, in the
    slow tier (7 hosts x threads x sockets)."""
    behaviors = {
        "node5": make_behavior(kind, seed=17),
        "node6": make_behavior(kind, seed=18),
    }
    first, _transport = _run_grpc_cluster(7, behaviors, txs=10)
    epochs = {e for e, _ in first.values()}
    assert epochs == {0}
    lists = [b.tx_list() for _, b in first.values()]
    assert all(l == lists[0] for l in lists)
    assert len(lists[0]) > 0
