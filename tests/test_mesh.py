"""Multi-device crypto-plane sharding tests (SURVEY.md §2.2, §5.7-5.8).

Run on the 8-virtual-CPU-device mesh conftest.py forces — the same
sharding programs a v5e slice would execute, minus the ICI.  Every
test asserts the sharded path agrees bit-for-bit with the single-
device path.
"""

import numpy as np
import pytest

from cleisthenes_tpu.parallel.mesh import CryptoMesh, make_crypto_mesh


@pytest.fixture(scope="module")
def mesh24(jax_cpu_devices):
    return CryptoMesh((2, 4), devices=jax_cpu_devices)


class TestCryptoMesh:
    def test_needs_enough_devices(self, jax_cpu_devices):
        with pytest.raises(ValueError):
            CryptoMesh((4, 4), devices=jax_cpu_devices)

    def test_bad_shape_rejected(self):
        with pytest.raises(ValueError):
            CryptoMesh((0, 2))
        with pytest.raises(ValueError):
            CryptoMesh((2,))

    def test_none_passthrough(self):
        assert make_crypto_mesh(None) is None

    def test_axis_names_and_shape(self, mesh24):
        assert mesh24.mesh.axis_names == ("v", "l")
        assert dict(zip(("v", "l"), mesh24.mesh.devices.shape)) == {
            "v": 2,
            "l": 4,
        }

    def test_pad_rows_and_cols(self, mesh24):
        a = np.arange(3 * 5, dtype=np.uint8).reshape(3, 5)
        padded, b = mesh24.pad_rows(a, 4)
        assert padded.shape == (4, 5) and b == 3
        assert (padded[3] == a[0]).all()
        padded, l = mesh24.pad_cols(a, 4)
        assert padded.shape == (3, 8) and l == 5
        assert (padded[:, 5:] == 0).all()


class TestShardedErasure:
    """RS codec sharded P('v', None, 'l') vs single-device."""

    @pytest.mark.parametrize("n,f,batch,length", [(8, 2, 8, 256), (7, 2, 5, 130)])
    def test_encode_batch_agrees(self, mesh24, n, f, batch, length):
        from cleisthenes_tpu.ops.rs_xla import XlaErasureCoder

        k = n - 2 * f
        rng = np.random.default_rng(5)
        data = rng.integers(0, 256, size=(batch, k, length), dtype=np.uint8)
        plain = XlaErasureCoder(n, k)
        sharded = XlaErasureCoder(n, k, mesh=mesh24)
        np.testing.assert_array_equal(
            plain.encode_batch(data), sharded.encode_batch(data)
        )

    def test_decode_batch_agrees_shared_pattern(self, mesh24):
        from cleisthenes_tpu.ops.rs_xla import XlaErasureCoder

        n, k, batch, length = 8, 4, 8, 192
        rng = np.random.default_rng(6)
        data = rng.integers(0, 256, size=(batch, k, length), dtype=np.uint8)
        plain = XlaErasureCoder(n, k)
        sharded = XlaErasureCoder(n, k, mesh=mesh24)
        enc = plain.encode_batch(data)
        survivors = np.array([n - k + i for i in range(k)])  # parity-heavy
        idx = np.tile(survivors, (batch, 1))
        got = sharded.decode_batch(idx, enc[:, survivors, :])
        np.testing.assert_array_equal(got, data)

    def test_decode_batch_agrees_mixed_patterns(self, mesh24):
        from cleisthenes_tpu.ops.rs_xla import XlaErasureCoder

        n, k, batch, length = 8, 4, 6, 128
        rng = np.random.default_rng(7)
        data = rng.integers(0, 256, size=(batch, k, length), dtype=np.uint8)
        sharded = XlaErasureCoder(n, k, mesh=mesh24)
        enc = XlaErasureCoder(n, k).encode_batch(data)
        idx = np.stack(
            [
                np.sort(rng.choice(n, size=k, replace=False))
                for _ in range(batch)
            ]
        )
        shards = np.stack([enc[i, idx[i], :] for i in range(batch)])
        got = sharded.decode_batch(idx, shards)
        np.testing.assert_array_equal(got, data)


class TestShardedMerkle:
    """Merkle forest + branch verify sharded P(('v','l')) flat."""

    def test_build_batch_agrees(self, mesh24):
        from cleisthenes_tpu.ops.merkle import XlaMerkle

        rng = np.random.default_rng(8)
        shards = rng.integers(0, 256, size=(5, 8, 200), dtype=np.uint8)
        plain = XlaMerkle().build_batch(shards)
        sharded = XlaMerkle(mesh=mesh24).build_batch(shards)
        for t0, t1 in zip(plain, sharded):
            assert t0.root == t1.root
            for j in range(8):
                assert t0.branch(j) == t1.branch(j)

    def test_verify_batch_agrees(self, mesh24):
        from cleisthenes_tpu.ops.merkle import XlaMerkle

        rng = np.random.default_rng(9)
        shards = rng.integers(0, 256, size=(4, 8, 96), dtype=np.uint8)
        m = XlaMerkle(mesh=mesh24)
        trees = m.build_batch(shards)
        b = 4 * 8
        roots = np.stack(
            [np.frombuffer(t.root, dtype=np.uint8) for t in trees]
        ).repeat(8, axis=0)
        leaves = shards.reshape(b, -1).copy()
        branches = np.stack(
            [
                np.stack([np.frombuffer(s, np.uint8) for s in t.branch(j)])
                for t in trees
                for j in range(8)
            ]
        )
        indices = np.tile(np.arange(8), 4)
        ok = m.verify_batch(roots, leaves, branches, indices)
        assert ok.all()
        leaves[0, 0] ^= 1  # corrupt one shard byte
        ok = m.verify_batch(roots, leaves, branches, indices)
        assert not ok[0] and ok[1:].all()


class TestShardedModexp:
    def test_dual_pow_agrees_with_cpu(self, mesh24):
        from cleisthenes_tpu.ops.modmath import P, ModEngine

        rng = np.random.default_rng(10)
        b = 13  # deliberately not divisible by 8: exercises padding
        u1 = [int(x) % P for x in rng.integers(2, 1 << 62, size=b)]
        u2 = [int(x) % P for x in rng.integers(2, 1 << 62, size=b)]
        e1 = [int(x) for x in rng.integers(1, 1 << 62, size=b)]
        e2 = [int(x) for x in rng.integers(1, 1 << 62, size=b)]
        cpu = ModEngine("cpu").dual_pow_batch(u1, e1, u2, e2)
        tpu = ModEngine("tpu", mesh=mesh24).dual_pow_batch(u1, e1, u2, e2)
        assert cpu == tpu

    def test_pow_agrees_with_cpu(self, mesh24):
        from cleisthenes_tpu.ops.modmath import G, P, Q, ModEngine

        bases = [G, 9, P - 2, 12345678901234567890 % P]
        exps = [3, Q - 1, 2, 65537]
        cpu = ModEngine("cpu").pow_batch(bases, exps)
        tpu = ModEngine("tpu", mesh=mesh24).pow_batch(bases, exps)
        assert cpu == tpu


class TestShardedProtocolE2E:
    def test_hbbft_epoch_with_mesh(self, jax_cpu_devices):
        """Full HBBFT over the channel transport with the crypto plane
        sharded over the (2, 4) CPU mesh — Config.mesh_shape is a live
        knob end to end (the round-1 'dead knob' finding)."""
        from tests.test_honeybadger import (
            assert_identical_batches,
            make_hb_network,
            push_txs,
        )

        cfg, net, nodes = make_hb_network(
            4, batch_size=8, crypto_backend="tpu", mesh_shape=(2, 4)
        )
        assert nodes["node0"].crypto.mesh is not None
        assert nodes["node0"].crypto.mesh.shape == (2, 4)
        push_txs(nodes, 8)
        for hb in nodes.values():
            hb.start_epoch()
        net.run()
        assert_identical_batches(nodes)


class TestNonPow2Mesh:
    def test_merkle_bucket_handles_six_devices(self, jax_cpu_devices):
        """Regression: a (3, 2) mesh (6 devices) used to infinite-loop
        the Merkle bucket computation (2^k is never divisible by 6)."""
        from cleisthenes_tpu.ops.merkle import XlaMerkle

        mesh = CryptoMesh((3, 2), devices=jax_cpu_devices)
        m = XlaMerkle(mesh=mesh)
        assert m._bucket(5) % 6 == 0
        rng = np.random.default_rng(11)
        shards = rng.integers(0, 256, size=(5, 4, 64), dtype=np.uint8)
        plain = XlaMerkle().build_batch(shards)
        sharded = m.build_batch(shards)
        for t0, t1 in zip(plain, sharded):
            assert t0.root == t1.root
