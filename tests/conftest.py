"""Test harness configuration.

Tests never require real TPU hardware: JAX is pinned to the CPU
platform with 8 virtual devices so multi-device sharding (shard_map
over a Mesh) is exercised exactly as it would be on a v5e slice.  This
must run before the first ``import jax`` anywhere in the test session.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def jax_cpu_devices():
    import jax

    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual CPU devices, got {len(devs)}"
    return devs
