"""Test harness configuration.

Tests never require real TPU hardware: JAX is pinned to the CPU
platform with 8 virtual devices so multi-device sharding — the
('v','l') CryptoMesh with GSPMD-partitioned crypto kernels, see
parallel/mesh.py and tests/test_mesh.py — compiles and executes the
same partitioned programs a v5e slice would run (minus the ICI).

The env-var route (JAX_PLATFORMS=cpu) is NOT enough here: the host
image's sitecustomize registers the axon TPU PJRT plugin at
interpreter boot and that registration takes precedence over the env
var, so the platform is forced through jax.config before any test
imports jax.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402

# The staticcheck fixture corpus is analyzer test DATA, not a test
# suite: the cross-module registry trees under staticcheck_fixtures/
# carry miniature test_*.py files (flag-pin registries) that must
# never be collected as tests — they import modules that exist only
# relative to their own mini tree roots.
collect_ignore_glob = ["staticcheck_fixtures/*"]


@pytest.fixture(scope="session")
def jax_cpu_devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual CPU devices, got {len(devs)}"
    return devs


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: multi-minute scale tests (full-protocol N>=64 epochs); "
        "deselect with -m 'not slow'",
    )
    config.addinivalue_line(
        "markers",
        "faults: crash/partition/Byzantine-adversary suite — the ci.sh "
        "fault-regression gate runs it over a fixed seed matrix "
        "(FAULT_SEED env selects the scheduler/coalition seed)",
    )
