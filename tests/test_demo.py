"""The demo CLI (cleisthenes_tpu/demo.py) driven in-process.

The demo is the framework's app-facing entry; until round 4 it was
exercised only by out-of-process smoke runs, leaving its whole body
outside the coverage gate."""

from cleisthenes_tpu import demo


def test_demo_grpc_mode_commits_all(tmp_path):
    rc = demo.main(
        [
            "--n", "4", "--txs", "16", "--batch-size", "8",
            "--log-dir", str(tmp_path),
        ]
    )
    assert rc == 0
    # durable logs were written for every node
    assert sum(1 for _ in tmp_path.iterdir()) >= 4


def test_demo_lockstep_mode_commits_all():
    assert demo.main(["--n", "4", "--txs", "12", "--mode", "lockstep"]) == 0


def test_demo_lockstep_with_dkg_keys(capsys):
    assert (
        demo.main(
            ["--n", "4", "--txs", "8", "--mode", "lockstep", "--dkg"]
        )
        == 0
    )
    # the DKG really ran (the flag was silently ignored in lockstep
    # mode until the round-4 review): its banner is printed and the
    # epoch decrypted under the DKG key set
    out = capsys.readouterr().out
    assert "DKG complete" in out and "SUCCESS" in out


def test_demo_trace_writes_valid_artifact(tmp_path):
    """--trace runs the grpc cluster under the flight recorder and
    writes a tracetool-valid Chrome trace on exit (ISSUE 3)."""
    import pathlib
    import sys

    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
    from tools import tracetool

    out = tmp_path / "demo_trace.json"
    rc = demo.main(
        [
            "--n", "4", "--txs", "8", "--batch-size", "8",
            "--log-dir", str(tmp_path / "wal"),
            "--trace", str(out),
        ]
    )
    assert rc == 0
    doc = tracetool.load(str(out))
    assert tracetool.validate(doc) == []
    summary = tracetool.summarize(doc)
    # the gRPC path's own planes showed up: dispatcher queue-depth
    # waves and WAL appends ride the node timelines
    assert summary["events_by_category"].get("transport", 0) > 0
    assert summary["events_by_category"].get("ledger", 0) > 0
    assert summary["events_by_category"].get("epoch", 0) > 0


def test_demo_restart_resumes_from_logs(tmp_path):
    """Second run against the same --log-dir must replay the durable
    batches and keep committing (the restart/recovery surface)."""
    args = [
        "--n", "4", "--txs", "8", "--batch-size", "8",
        "--log-dir", str(tmp_path),
    ]
    assert demo.main(args) == 0
    assert demo.main(args) == 0
