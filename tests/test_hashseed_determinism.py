"""Cross-PYTHONHASHSEED ledger determinism (the DET002 invariant,
end to end).

CPython randomizes str/bytes hashing per process unless PYTHONHASHSEED
pins it, so any set-iteration order that leaks into message bodies,
batch contents or ledger bytes shows up as two processes committing
DIFFERENT bytes for the SAME seeded schedule.  The hash seed is fixed
at interpreter start, so the only honest test is subprocesses: run the
identical seeded 4-node cluster under two different PYTHONHASHSEED
values and require byte-identical ledgers (the full CLOG record bodies
of every node, hashed).
"""

from __future__ import annotations

import os
import pathlib
import subprocess
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent

# Runs a seeded 4-node simulated cluster to quiescence and prints one
# digest over every node's full committed-ledger record bytes — the
# exact bytes a BatchLog would persist and CATCHUP would serve.
_DRIVER = r"""
import hashlib
from cleisthenes_tpu.config import Config
from cleisthenes_tpu.core.ledger import encode_batch_body
from cleisthenes_tpu.protocol.cluster import SimulatedCluster

# Config.seed seeds batch sampling (proposal_rng); the cluster seed
# seeds the network scheduler — both must be pinned for a replay
cluster = SimulatedCluster(
    config=Config(n=4, batch_size=8, seed=1234),
    seed=1234,
    key_seed=1,
)
for i in range(24):
    cluster.submit(b"tx-%04d" % i)
cluster.run_epochs()
depth = cluster.assert_agreement()
assert depth >= 2, f"want >=2 committed epochs, got {depth}"
h = hashlib.sha256()
for nid in cluster.ids:
    for epoch, batch in enumerate(cluster.nodes[nid].committed_batches):
        h.update(encode_batch_body(epoch, batch))
print("LEDGER_DIGEST=%s depth=%d" % (h.hexdigest(), depth))
"""


def _run_with_hashseed(hashseed: str) -> str:
    env = dict(os.environ)
    env["PYTHONHASHSEED"] = hashseed
    env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.run(
        [sys.executable, "-c", _DRIVER],
        cwd=REPO,
        env=env,
        capture_output=True,
        text=True,
        timeout=300,
    )
    assert proc.returncode == 0, (
        f"PYTHONHASHSEED={hashseed} run failed:\n"
        f"{proc.stdout}\n{proc.stderr}"
    )
    for line in proc.stdout.splitlines():
        if line.startswith("LEDGER_DIGEST="):
            return line
    raise AssertionError(f"no digest line in output:\n{proc.stdout}")


def test_ledgers_identical_across_hash_seeds():
    a = _run_with_hashseed("1")
    b = _run_with_hashseed("2")
    assert a == b, (
        "seeded 4-node runs under different PYTHONHASHSEED values "
        f"committed different ledger bytes:\n  {a}\n  {b}\n"
        "-> set-iteration order is leaking into wire/ledger bytes "
        "(see staticcheck DET002)"
    )
