"""Seeded WAN emulation plane (ISSUE 16), end to end.

The link model (transport/wan.py) prices every frame into a
virtual-clock delivery deadline — per-region RTT, seeded jitter,
loss-as-retransmission, bandwidth serialization, heavy-tailed
straggler episodes — and the ChannelNetwork scheduler releases frames
only once the seeded virtual clock passes the deadline.  The contract
under test:

- every named profile commits with full honest agreement;
- a fixed (seed, profile) pair replays byte-identically across
  processes (cross-PYTHONHASHSEED subprocess runs);
- the hardening rides along: the epoch-stall budget floor keeps a
  LAN-calibrated p50 from flipping DOWN under WAN pricing, a
  straggling-but-alive peer degrades (never DOWN) on both transport
  provider shapes, a wan_3region regional partition heals back to
  quiescence with zero false watchdog DOWN transitions, and the gRPC
  dial backoff keeps its capped schedule across a flapping link.
"""

from __future__ import annotations

import os
import pathlib
import random
import subprocess
import sys
import threading

import pytest

from cleisthenes_tpu.config import Config
from cleisthenes_tpu.protocol.cluster import SimulatedCluster
from cleisthenes_tpu.transport.health import Backoff
from cleisthenes_tpu.transport.wan import (
    PROFILES,
    WanEmulator,
    wan_profile_names,
)
from cleisthenes_tpu.utils.determinism import wan_rng
from cleisthenes_tpu.utils.metrics import Metrics
from cleisthenes_tpu.utils.watchdog import (
    DEGRADED,
    DOWN,
    EPOCH_STALL,
    PEER_LAG,
    UP,
    SloWatchdog,
)

REPO = pathlib.Path(__file__).resolve().parent.parent


def _wan_cluster(profile: str, *, seed: int = 7, n: int = 4,
                 batch: int = 8) -> SimulatedCluster:
    return SimulatedCluster(
        config=Config(n=n, batch_size=batch, seed=seed),
        seed=seed,
        key_seed=11,
        wan_profile=profile,
    )


# ---------------------------------------------------------------------------
# the profile matrix: every named geography commits with agreement
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("profile", wan_profile_names())
def test_profile_commits_with_agreement(profile):
    cluster = _wan_cluster(profile)
    for i in range(16):
        cluster.submit(b"wan-%03d" % i)
    cluster.run_until_drained(max_rounds=60)
    depth = cluster.assert_agreement()
    assert depth >= 1
    # the link model left its evidence in the snapshot block
    snap = cluster.nodes[cluster.ids[0]].metrics.snapshot()
    assert snap["wan"]["enabled"] == 1
    assert snap["wan"]["profile"] == profile
    assert snap["wan"]["frames_delayed"] > 0
    assert snap["wan"]["virtual_time_ms"] > 0


def test_snapshot_wan_block_zeroed_without_profile():
    """PR-9 schema rule: the block is always present, all keys
    zeroed, when no WAN profile is mounted."""
    cluster = SimulatedCluster(config=Config(n=4, seed=1), key_seed=2)
    snap = cluster.nodes[cluster.ids[0]].metrics.snapshot()
    assert snap["wan"] == {
        "enabled": 0,
        "profile": "",
        "frames_delayed": 0,
        "retransmits": 0,
        "straggler_episodes": 0,
        "virtual_time_ms": 0,
    }


def test_link_states_carry_wan_fields():
    cluster = _wan_cluster("wan_3region")
    states = cluster.net.link_states(cluster.ids[0])
    assert states, "no links registered"
    for link, info in states.items():
        assert info["state"] in ("up", "down", "straggling")
        assert info["rtt_ms"] > 0.0  # priced by the region matrix
        assert info["loss"] == PROFILES["wan_3region"].loss_p
        assert info["straggling"] in (False, True)
    # without a profile the same keys exist, zeroed (schema rule)
    plain = SimulatedCluster(config=Config(n=4, seed=1), key_seed=2)
    for info in plain.net.link_states(plain.ids[0]).values():
        assert info["rtt_ms"] == 0.0
        assert info["loss"] == 0.0
        assert info["straggling"] is False


# ---------------------------------------------------------------------------
# determinism: the seeded virtual clock is a pure function of the seed
# ---------------------------------------------------------------------------


def test_wan_rng_streams_are_keyed_and_replayable():
    a = wan_rng(5, "link", "node000", "node001")
    b = wan_rng(5, "link", "node000", "node001")
    assert [a.random() for _ in range(4)] == [
        b.random() for _ in range(4)
    ]
    # distinct lanes draw from distinct streams (lazy construction
    # order cannot alias them)
    c = wan_rng(5, "link", "node001", "node000")
    assert a.random() != c.random()


def test_emulator_admission_replays_for_a_fixed_seed():
    def drive(order):
        wan = WanEmulator("wan_global", seed=42)
        for nid in ("node000", "node001", "node002"):
            wan.register(nid)
        out = []
        for s, r, nb in order:
            out.append(wan.admit(s, r, nb))
        return out

    order = [
        ("node000", "node001", 512),
        ("node000", "node002", 100_000),
        ("node001", "node000", 512),
        ("node002", "node001", 2048),
    ]
    assert drive(order) == drive(order)


# The acceptance bar: a fixed fuzz seed with the WAN band on (the
# profile itself drawn from the seed) commits byte-identical honest
# settled ledgers across processes with different hash seeds.
_FUZZ_DRIVER = r"""
import hashlib
from tools.fuzz import sample_schedule, _build_cluster, _apply_event
from cleisthenes_tpu.core.ledger import encode_batch_body
from cleisthenes_tpu.protocol.cluster import run_until_drained

schedule = sample_schedule(13, wan=True)
assert schedule["wan_profile"], "wan band did not draw a profile"
cluster = _build_cluster(schedule, trace=False)
bad = set(schedule["bad"])
honest = [nid for nid in cluster.ids if nid not in bad]
for i in range(schedule["txs"]):
    cluster.nodes[honest[i % len(honest)]].add_transaction(
        b"fuzz-%06d" % i
    )
by_round = {}
for ev in schedule["timeline"]:
    by_round.setdefault(ev["round"], []).append(ev)

def before_round(r):
    for ev in by_round.get(r, ()):
        _apply_event(cluster, ev)

run_until_drained(
    cluster.net,
    cluster.nodes,
    skip=bad,
    max_rounds=schedule["rounds"],
    before_round=before_round,
)
h = hashlib.sha256()
depth = None
for nid in honest:
    batches = cluster.nodes[nid].committed_batches
    depth = len(batches) if depth is None else min(depth, len(batches))
    for epoch, batch in enumerate(batches):
        h.update(nid.encode() + encode_batch_body(epoch, batch))
assert depth and depth >= 1, f"no settled epochs (depth={depth})"
print("WAN_LEDGER_DIGEST=%s profile=%s depth=%d"
      % (h.hexdigest(), schedule["wan_profile"], depth))
"""


def _run_wan_driver(hashseed: str) -> str:
    env = dict(os.environ)
    env["PYTHONHASHSEED"] = hashseed
    env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.run(
        [sys.executable, "-c", _FUZZ_DRIVER],
        cwd=REPO,
        env=env,
        capture_output=True,
        text=True,
        timeout=300,
    )
    assert proc.returncode == 0, (
        f"PYTHONHASHSEED={hashseed} WAN run failed:\n"
        f"{proc.stdout}\n{proc.stderr}"
    )
    for line in proc.stdout.splitlines():
        if line.startswith("WAN_LEDGER_DIGEST="):
            return line
    raise AssertionError(f"no digest line in output:\n{proc.stdout}")


def test_wan_ledgers_identical_across_hash_seeds():
    a = _run_wan_driver("1")
    b = _run_wan_driver("2")
    assert a == b, (
        "seeded WAN fuzz runs under different PYTHONHASHSEED values "
        f"committed different ledger bytes:\n  {a}\n  {b}\n"
        "-> non-seeded entropy or iteration order is leaking into "
        "the link model's delivery schedule (see staticcheck DET001 "
        "on transport/wan*.py)"
    )


# ---------------------------------------------------------------------------
# degradation hardening: the watchdog survives WAN pricing
# ---------------------------------------------------------------------------


def test_stall_budget_floor_survives_straggler_tail():
    """An epoch p50 self-calibrated on fast epochs must not flip DOWN
    the moment the link model's heavy tail lands: the profile's
    stall floor raises the leash to what the geography can cost."""
    floor = PROFILES["straggler_tail"].stall_floor_s
    m = Metrics()
    for v in (0.4, 0.5, 0.6):  # LAN-fast history
        m.epoch_latency.observe(v)
    naked = SloWatchdog(metrics=m, pending_fn=lambda: 5)
    floored = SloWatchdog(
        metrics=m, pending_fn=lambda: 5, budget_floor_fn=lambda: floor
    )
    assert floored.stall_budget_s() == floor
    # 25s of silence with txs pending: inside the straggler budget,
    # far past the naked one — the un-floored leash is the regression
    now = m._t0 + 25.0
    assert naked.check(now=now) == DOWN
    assert floored.check(now=now) == UP
    assert floored.alerts_block()[EPOCH_STALL]["count"] == 0
    # a genuine wedge still flips: the floor is a floor, not a blind
    assert floored.check(now=m._t0 + floor + 1.0) == DOWN


def test_straggling_peer_degrades_never_down_on_both_transports():
    """A straggling-but-alive peer must read DEGRADED, not DOWN, and
    must not fire the PEER_LAG alert — on both provider shapes: the
    channel transport's enriched link_states dicts and the gRPC
    tracker's plain state strings."""
    providers = {
        "channel": lambda: {
            "node001": {
                "state": "straggling",
                "rtt_ms": 80.0,
                "loss": 0.0,
                "straggling": 1,
            }
        },
        "grpc": lambda: {"node001": DEGRADED},
    }
    for name, provider in providers.items():
        m = Metrics()
        wd = SloWatchdog(
            metrics=m, pending_fn=lambda: 0, peer_states_fn=provider
        )
        verdict = wd.check(now=m._t0 + 1.0)
        assert verdict == DEGRADED, f"{name}: {verdict}"
        alerts = wd.alerts_block()
        assert alerts[PEER_LAG]["active"] is False, name
        assert alerts[EPOCH_STALL]["active"] is False, name


def test_straggler_tail_run_never_reads_down():
    """Cluster-level: an honest roster under the heavy-tail profile
    keeps committing, and no node's watchdog ever flips DOWN — the
    straggling minority degrades the verdict at most."""
    cluster = _wan_cluster("straggler_tail", seed=3)
    for i in range(24):
        cluster.submit(b"tail-%03d" % i)
    for _ in range(3):
        cluster.run_until_drained(max_rounds=40)
        health = cluster.health()
        assert health["status"] != DOWN, health
    cluster.assert_agreement()
    for nid in cluster.ids:
        alerts = cluster.watchdogs[nid].alerts_block()
        assert alerts[EPOCH_STALL]["count"] == 0, (nid, alerts)


def test_wan_3region_partition_heals_to_quiescence():
    """The acceptance scenario: a regional split under wan_3region
    (2/2 on n=4 — neither side holds a quorum) halts commits while
    open, then heals; the cluster recovers to quiescence and full
    agreement with ZERO false watchdog DOWN transitions."""
    cluster = _wan_cluster("wan_3region", seed=5)
    ids = cluster.ids
    # region assignment is round-robin by join order: ids[0]/ids[3]
    # share region 0 — cut every cross-group link for a 2/2 split
    west, east = [ids[0], ids[3]], [ids[1], ids[2]]

    def no_down() -> None:
        health = cluster.health()
        assert health["status"] != DOWN, health

    for i in range(8):
        cluster.submit(b"pre-%03d" % i)
    cluster.run_until_drained(max_rounds=40)
    depth_before = cluster.assert_agreement()
    no_down()

    for a in west:
        for b in east:
            cluster.net.partition(a, b)
    for i in range(8):
        cluster.submit(b"mid-%03d" % i)
    # neither side can assemble n-f=3: the network drains without
    # commits; the watchdog must degrade at most, never flip DOWN
    cluster.net.run()
    no_down()

    for a in west:
        for b in east:
            cluster.net.heal(a, b)
    cluster.run_until_drained(max_rounds=60)
    depth_after = cluster.assert_agreement()
    assert depth_after > depth_before, "healed roster did not commit"
    no_down()
    for nid in ids:
        alerts = cluster.watchdogs[nid].alerts_block()
        assert alerts[EPOCH_STALL]["count"] == 0, (nid, alerts)


# ---------------------------------------------------------------------------
# the dial-backoff flap fix (transport/health.py)
# ---------------------------------------------------------------------------


def test_backoff_flap_keeps_capped_schedule():
    """The regression: a flapping link (dial lands, stream dies
    before stability_s) must CONTINUE the capped schedule — the old
    reset-on-every-success re-probed from base forever."""
    b = Backoff(0.1, 3.0, rng=random.Random(1))
    for _ in range(10):
        b.next_delay()  # drive the schedule to the cap
    # flap: up for 0.5s < stability_s (defaults to max_s = 3.0)
    b.note_connected(now=100.0)
    b.note_lost(now=100.5)
    assert b.next_delay() >= 3.0 * 0.75, (
        "flap reset the schedule to base"
    )
    # a connection that SURVIVES the stability window re-arms
    b.note_connected(now=200.0)
    b.note_lost(now=204.0)
    assert b.next_delay() <= 0.1 * 1.25


def test_backoff_flap_sequence_stays_capped():
    """A sustained flap storm never decays below the cap, and every
    delay honors the hard max_s bound."""
    b = Backoff(0.05, 2.0, rng=random.Random(7))
    now = 0.0
    delays = []
    for _ in range(20):
        delays.append(b.next_delay())
        now += delays[-1]
        b.note_connected(now=now)
        now += 0.2  # each success lives 0.2s << stability_s
        b.note_lost(now=now)
    assert max(delays) <= 2.0  # the hard bound holds throughout
    # the tail sits at the cap (jitter floor 0.75 * max_s), instead
    # of sawtoothing back to base on every transient success
    assert all(d >= 2.0 * 0.75 for d in delays[8:]), delays


def test_host_backoff_persists_per_dial_lane():
    """ValidatorHost keeps ONE Backoff per member across connect()
    and every _redial_loop invocation (the flap fix's other half),
    and drops it when the peer retires."""
    from cleisthenes_tpu.transport.host import ValidatorHost

    class _Stub:
        config = Config(n=4, seed=9)
        node_id = "node000"
        _backoffs: dict = {}
        _backoffs_lock = threading.Lock()

    stub = _Stub()
    b1 = ValidatorHost._backoff_for(stub, "node001")
    b1.next_delay()
    b2 = ValidatorHost._backoff_for(stub, "node001")
    assert b1 is b2, "redial loop got a fresh backoff (flap reset)"
    assert ValidatorHost._backoff_for(stub, "node002") is not b1
    # seeded jitter is per dial lane: schedules replay per peer
    assert stub._backoffs["node001"].stability_s == b1.max_s
