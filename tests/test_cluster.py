"""SimulatedCluster API + shared-hub regression tests."""

import os

import numpy as np
import pytest

from cleisthenes_tpu.protocol.cluster import SimulatedCluster
from cleisthenes_tpu.utils.adversary import Coalition


def test_cluster_basic_commit():
    c = SimulatedCluster(4, batch_size=8)
    txs = [b"ct-%02d" % i for i in range(12)]
    for tx in txs:
        c.submit(tx)
    rounds = c.run_epochs()
    assert rounds >= 1
    depth = c.assert_agreement()
    committed = [tx for b in c.committed()[:depth] for tx in b.tx_list()]
    assert sorted(committed) == sorted(txs)
    # the shared hub really is shared and dispatch counts are cluster-wide
    hubs = {id(hb.hub) for hb in c.nodes.values()}
    assert len(hubs) == 1


def test_cluster_per_node_hubs_equivalent():
    a = SimulatedCluster(4, batch_size=8, shared_hub=True, seed=3)
    b = SimulatedCluster(4, batch_size=8, shared_hub=False, seed=3)
    for c in (a, b):
        for i in range(8):
            c.submit(b"eq-%02d" % i)
        c.run_epochs()
        c.assert_agreement()
    # identical committed tx sets regardless of hub topology
    sa = {tx for bt in a.committed() for tx in bt.tx_list()}
    sb = {tx for bt in b.committed() for tx in bt.tx_list()}
    assert sa == sb


def test_cluster_byzantine_and_crash():
    c = SimulatedCluster(7, batch_size=8, seed=11)
    c.fault_filter = Coalition(["node005"], seed=11).drop(0.4).tamper(0.4).filter
    c.crash("node006")
    for i in range(14):
        c.submit(b"bz-%02d" % i, node_id=c.ids[i % 5])  # only live nodes
    c.run_epochs(skip=("node006",))
    c.assert_agreement(skip=("node005", "node006"))


def test_shared_hub_epoch_gc_is_node_scoped():
    """Regression for the node-qualified hub scopes: one node advancing
    epochs (and GC'ing its old epoch scope) must not unregister a
    slower peer's hub clients for the same epoch number."""
    c = SimulatedCluster(4, batch_size=4)
    for i in range(16):
        c.submit(b"gc-%02d" % i)
    c.run_epochs()
    depth = c.assert_agreement()
    assert depth >= 2  # multiple epochs actually ran and GC'd
    hub = c.nodes[c.ids[0]].hub
    # after quiescence: only live-window scopes remain; every remaining
    # scope is node-qualified (node_id, epoch-or-tag)
    for scope in hub._clients:
        assert isinstance(scope, tuple) and scope[0] in c.nodes


@pytest.mark.skipif(
    os.environ.get("RUN_SLOW") != "1",
    reason="~3 min memory soak (RUN_SLOW=1 to enable)",
)
def test_cluster_memory_soak():
    """30 epochs over one cluster: RSS must plateau after warm-up —
    the dedup memos, payload memo, parked-message buffers, and epoch
    GC are all bounded (caps + drop_scope eviction)."""
    import gc
    import resource

    c = SimulatedCluster(n=8, batch_size=64, seed=4)

    def rss_mb():
        return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss // 1024

    base = None
    for burst in range(6):
        for i in range(64 * 5):
            c.submit(b"soak-%d-%05d" % (burst, i))
        c.run_epochs()
        gc.collect()
        if burst == 1:
            base = rss_mb()
    assert sum(len(b) for b in c.committed()) == 64 * 5 * 6
    assert rss_mb() - base < 120, "unbounded growth across epochs"
