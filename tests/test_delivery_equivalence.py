"""Scalar vs columnar DELIVERY equivalence (ISSUE 9).

The delivery-plane columnarization moved inbound work to wave
granularity: frame decode memoizes on the signing-prefix digest
(transport.message.FrameDecodeMemo), MAC verification batches through
one ``Authenticator.verify_wire_many`` call per wave, and RBC receipt
state lives in the roster-wide EchoBank.  That reshapes WHEN frames
decode and verify — but it must never reshape WHAT the roster
commits.  ``Config.delivery_columnar=False`` keeps the per-frame
scalar receive path as a live comparison arm; these tests run the
same seeded schedule under both arms and require byte-identical
committed ledgers on both transports, that the columnar arm's
deterministic frame/MAC counters actually DROP, that the PR-4
semantic coalitions (equivocating per-receiver roots included) run
green against the EchoBank, and that the whole columnar receive path
is PYTHONHASHSEED-independent.
"""

from __future__ import annotations

import hashlib
import os
import pathlib
import subprocess
import sys
import threading

import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from cleisthenes_tpu.config import Config  # noqa: E402
from cleisthenes_tpu.core.ledger import encode_batch_body  # noqa: E402
from cleisthenes_tpu.protocol.cluster import SimulatedCluster  # noqa: E402


def _channel_run(columnar: bool) -> tuple:
    """(ledger digest, depth, delivery counters) for one seeded
    4-node channel-transport run under the given delivery arm."""
    cluster = SimulatedCluster(
        config=Config(
            n=4, batch_size=8, seed=2027, delivery_columnar=columnar
        ),
        seed=2027,
        key_seed=15,
    )
    for i in range(24):
        cluster.submit(b"dlv-tx-%04d" % i)
    cluster.run_epochs()
    depth = cluster.assert_agreement()
    h = hashlib.sha256()
    for nid in cluster.ids:
        for epoch, batch in enumerate(
            cluster.nodes[nid].committed_batches
        ):
            h.update(encode_batch_body(epoch, batch))
    return h.hexdigest(), depth, cluster.net.delivery_stats()


def test_scalar_vs_columnar_identical_ledgers_channel():
    col = _channel_run(columnar=True)
    sca = _channel_run(columnar=False)
    assert col[1] >= 2 and sca[1] >= 2  # both actually committed
    assert col[0] == sca[0], (
        "columnar delivery committed different ledger bytes than the "
        f"scalar arm:\n  columnar: {col}\n  scalar:   {sca}"
    )
    # the refactor's entire point: the columnar arm decodes FEWER
    # frames (shared-prefix memo) and makes FEWER verify calls (wave
    # batches) for the identical schedule — never more
    assert col[2]["frames_decoded"] < sca[2]["frames_decoded"], (
        col[2], sca[2],
    )
    assert col[2]["mac_verifies"] < sca[2]["mac_verifies"], (
        col[2], sca[2],
    )
    # and the memo genuinely hit (a broadcast's N receiver frames
    # share one decode)
    probes = col[2]["decode_memo_hits"] + col[2]["decode_memo_misses"]
    assert probes > 0 and col[2]["decode_memo_hits"] > 0
    # scalar arm reports zeroed memo keys (schema stability)
    assert sca[2]["decode_memo_hits"] == 0
    assert sca[2]["decode_memo_misses"] == 0


def test_transport_metrics_surface_delivery_counters():
    """Metrics.snapshot()["transport"] carries the delivery-plane
    counters on the channel transport (endpoint_stats provider)."""
    cluster = SimulatedCluster(
        config=Config(n=4, batch_size=8, seed=5, delivery_columnar=True),
        seed=5,
        key_seed=2,
    )
    for i in range(8):
        cluster.submit(b"mtx-%04d" % i)
    cluster.run_epochs()
    snap = cluster.nodes[cluster.ids[0]].metrics.snapshot()["transport"]
    for key in (
        "frames_decoded",
        "decode_memo_hits",
        "decode_memo_misses",
        "mac_verify_batches",
    ):
        assert key in snap, snap
    assert snap["mac_verify_batches"] > 0
    assert snap["delivered"] > 0


def _grpc_epoch0_bodies(
    columnar: bool, wave_routing: bool = True
) -> tuple:
    """(per-node epoch-0 bodies, one host's metrics snapshot) from a
    4-node run over real localhost gRPC under the given arms."""
    from cleisthenes_tpu.protocol.honeybadger import setup_keys
    from cleisthenes_tpu.transport.host import ValidatorHost

    n = 4
    cfg = Config(
        n=n,
        batch_size=8,
        seed=78,
        delivery_columnar=columnar,
        wave_routing=wave_routing,
    )
    ids = [f"node{i}" for i in range(n)]
    keys = setup_keys(cfg, ids, seed=56)
    hosts = {i: ValidatorHost(cfg, i, ids, keys[i]) for i in ids}
    try:
        addrs = {i: h.listen() for i, h in hosts.items()}
        threads = [
            threading.Thread(target=h.connect, args=(addrs,))
            for h in hosts.values()
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=15)
        for i in range(8):
            hosts[ids[i % n]].submit(b"grpc-dlv-%02d" % i)
        for h in hosts.values():
            h.propose()
        first = {i: h.wait_commit(timeout=60) for i, h in hosts.items()}
        assert {e for e, _ in first.values()} == {0}
        snap = hosts[ids[0]].node.metrics.snapshot()
        return [encode_batch_body(0, b) for _, b in first.values()], snap
    finally:
        for h in hosts.values():
            h.stop()


def test_scalar_vs_columnar_identical_ledgers_grpc():
    """Same roster, same submissions, real sockets: the columnar and
    scalar delivery arms must commit byte-identical epoch-0 batches,
    and the columnar arm's wave verify must actually engage (batch
    count > 0, batches <= frames)."""
    col, col_snap = _grpc_epoch0_bodies(columnar=True)
    sca, _sca_snap = _grpc_epoch0_bodies(columnar=False)
    # within-run agreement is byte-exact on both arms...
    assert all(b == col[0] for b in col)
    assert all(b == sca[0] for b in sca)
    # ...and across the delivery-arm boundary too
    assert col[0] == sca[0], (
        "columnar vs scalar gRPC runs committed different epoch-0 bytes"
    )
    transport = col_snap["transport"]
    assert transport["mac_verify_batches"] > 0
    assert transport["mac_verify_batches"] <= transport["frames_decoded"]


# Prints one line digesting the ledger bytes AND the columnar delivery
# structure itself: deterministic frame-decode/MAC-verify counters and
# memo tallies.  Two PYTHONHASHSEED values must produce identical
# lines — hash-order iteration anywhere in the wave-prepare / bank
# path would show up as different counters or ledger bytes.
_DELIVERY_DRIVER = r"""
import hashlib
from cleisthenes_tpu.config import Config
from cleisthenes_tpu.core.ledger import encode_batch_body
from cleisthenes_tpu.protocol.cluster import SimulatedCluster

cluster = SimulatedCluster(
    config=Config(n=4, batch_size=8, seed=909, delivery_columnar=True),
    seed=909,
    key_seed=4,
)
for i in range(24):
    cluster.submit(b"dlv-hs-%04d" % i)
cluster.run_epochs()
depth = cluster.assert_agreement()
assert depth >= 2, f"want >=2 committed epochs, got {depth}"
h = hashlib.sha256()
for nid in cluster.ids:
    for epoch, batch in enumerate(cluster.nodes[nid].committed_batches):
        h.update(encode_batch_body(epoch, batch))
d = cluster.net.delivery_stats()
assert Config().wave_routing is True  # the router is the default arm
dispatches = sum(
    cluster.nodes[nid].metrics.handler_dispatches.value
    for nid in cluster.ids
)
waves = sum(
    cluster.nodes[nid].metrics.waves_routed.value for nid in cluster.ids
)
print(
    "DELIVERY_DIGEST=%s decoded=%d verifies=%d hits=%d misses=%d "
    "dispatches=%d waves=%d"
    % (
        h.hexdigest(),
        d["frames_decoded"],
        d["mac_verifies"],
        d["decode_memo_hits"],
        d["decode_memo_misses"],
        dispatches,
        waves,
    )
)
"""


def _run_delivery_driver(hashseed: str) -> str:
    env = dict(os.environ)
    env["PYTHONHASHSEED"] = hashseed
    env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.run(
        [sys.executable, "-c", _DELIVERY_DRIVER],
        cwd=REPO,
        env=env,
        capture_output=True,
        text=True,
        timeout=300,
    )
    assert proc.returncode == 0, (
        f"PYTHONHASHSEED={hashseed} delivery run failed:\n"
        f"{proc.stdout}\n{proc.stderr}"
    )
    for line in proc.stdout.splitlines():
        if line.startswith("DELIVERY_DIGEST="):
            return line
    raise AssertionError(f"no delivery digest line:\n{proc.stdout}")


def test_delivery_ordering_identical_across_hash_seeds():
    a = _run_delivery_driver("1")
    b = _run_delivery_driver("2")
    assert a == b, (
        "columnar delivery diverged across PYTHONHASHSEED values:\n"
        f"  {a}\n  {b}\n-> hash-order iteration is leaking into the "
        "wave-prepare / EchoBank path (see staticcheck DET002)"
    )


# ---------------------------------------------------------------------------
# wave routing (ISSUE 10): scalar vs wave-routed ingest
# ---------------------------------------------------------------------------


def _routing_run(wave_routing: bool) -> tuple:
    """(ledger digest, depth, cluster-wide handler dispatches, waves
    routed) for one seeded 4-node channel run under the given ROUTING
    arm (delivery_columnar stays on for both — the router rides it)."""
    cluster = SimulatedCluster(
        config=Config(
            n=4,
            batch_size=8,
            seed=4041,
            delivery_columnar=True,
            wave_routing=wave_routing,
        ),
        seed=4041,
        key_seed=23,
    )
    for i in range(24):
        cluster.submit(b"rtr-tx-%04d" % i)
    cluster.run_epochs()
    depth = cluster.assert_agreement()
    h = hashlib.sha256()
    for nid in cluster.ids:
        for epoch, batch in enumerate(
            cluster.nodes[nid].committed_batches
        ):
            h.update(encode_batch_body(epoch, batch))
    dispatches = sum(
        cluster.nodes[nid].metrics.handler_dispatches.value
        for nid in cluster.ids
    )
    waves = sum(
        cluster.nodes[nid].metrics.waves_routed.value
        for nid in cluster.ids
    )
    return h.hexdigest(), depth, dispatches, waves


def test_scalar_vs_wave_routing_identical_ledgers_channel():
    wav = _routing_run(wave_routing=True)
    sca = _routing_run(wave_routing=False)
    assert wav[1] >= 2 and sca[1] >= 2  # both actually committed
    assert wav[0] == sca[0], (
        "wave-routed ingest committed different ledger bytes than the "
        f"scalar routing arm:\n  wave:   {wav}\n  scalar: {sca}"
    )
    # the refactor's entire point: one batch handler invocation per
    # (kind, wave) instead of one Python call chain per payload —
    # the deterministic counter must drop by a real factor, and the
    # router must actually have demuxed waves
    assert sca[2] >= 3 * wav[2], (wav, sca)
    assert wav[3] > 0
    assert sca[3] == 0  # scalar arm never routes a wave


def test_router_metrics_schema_zeroed_on_scalar_arm():
    """snapshot()["router"] keys are present on BOTH arms (the PR-9
    schema rule) and zeroed on the scalar one."""
    for wave in (True, False):
        cluster = SimulatedCluster(
            config=Config(
                n=4, batch_size=8, seed=7, wave_routing=wave
            ),
            seed=7,
            key_seed=2,
        )
        for i in range(8):
            cluster.submit(b"rs-%04d" % i)
        cluster.run_epochs()
        snap = cluster.nodes[cluster.ids[0]].metrics.snapshot()["router"]
        assert set(snap) == {"handler_dispatches", "waves_routed"}
        assert snap["handler_dispatches"] > 0  # both arms dispatch
        assert (snap["waves_routed"] > 0) == wave


def test_scalar_vs_wave_routing_identical_ledgers_grpc():
    """Same roster, same submissions, real sockets + the dispatcher's
    wave mailbox: the wave-routed and scalar routing arms must commit
    byte-identical epoch-0 batches, and the wave arm must actually
    route waves."""
    wav, wav_snap = _grpc_epoch0_bodies(columnar=True, wave_routing=True)
    sca, _ = _grpc_epoch0_bodies(columnar=True, wave_routing=False)
    assert all(b == wav[0] for b in wav)
    assert all(b == sca[0] for b in sca)
    assert wav[0] == sca[0], (
        "wave vs scalar routing gRPC runs committed different "
        "epoch-0 bytes"
    )
    assert wav_snap["router"]["waves_routed"] > 0
    assert wav_snap["router"]["handler_dispatches"] > 0


# ---------------------------------------------------------------------------
# codec-level parity: decode_frame_shared vs decode_frame
# ---------------------------------------------------------------------------


def test_decode_frame_shared_parity_and_rejections():
    """The shared-prefix decoder must accept exactly what the scalar
    decoder accepts (same Message, byte-equal signing prefix), share
    the payload object across a broadcast's frames via the memo, and
    reject the same malformed inputs."""
    from cleisthenes_tpu.transport.message import (
        BbaPayload,
        BbaType,
        FrameDecodeMemo,
        Message,
        decode_frame,
        decode_frame_shared,
        encode_message,
    )

    payload = BbaPayload(BbaType.BVAL, "node0", 3, 1, True)
    msg = Message(
        sender_id="node0", timestamp=12.5, payload=payload,
        signature=b"m" * 32,
    )
    wire = encode_message(msg)
    memo = FrameDecodeMemo()
    got, prefix = decode_frame_shared(wire, memo)
    want, want_prefix = decode_frame(wire)
    assert got == want
    assert bytes(prefix) == want_prefix
    assert (memo.hits, memo.misses) == (0, 1)
    # a sibling frame of the same broadcast (same prefix, different
    # MAC) hits the memo and shares the SAME payload object — the id
    # identity the hub's dedup and the column memos downstream rely on
    sibling = encode_message(
        Message(
            sender_id="node0", timestamp=12.5, payload=payload,
            signature=b"x" * 32,
        )
    )
    got2, _ = decode_frame_shared(sibling, memo)
    assert (memo.hits, memo.misses) == (1, 1)
    assert got2.payload is got.payload
    assert got2.signature == b"x" * 32
    # rejection parity: truncations, trailing junk, bad magic
    for mutant in (
        wire[:10],
        wire[:-1],
        wire + b"\x00",
        b"XXXX" + wire[4:],
    ):
        with pytest.raises(ValueError):
            decode_frame(mutant)
        with pytest.raises(ValueError):
            decode_frame_shared(mutant, FrameDecodeMemo())
    # FIFO eviction: at cap the OLDEST entry goes, never the table
    small = FrameDecodeMemo(cap=2)
    frames = []
    for i in range(3):
        p = BbaPayload(BbaType.BVAL, "node0", i, 0, False)
        frames.append(
            encode_message(
                Message(
                    sender_id="node0", timestamp=1.0, payload=p,
                    signature=b"s" * 32,
                )
            )
        )
        decode_frame_shared(frames[-1], small)
    assert len(small.map) == 2
    decode_frame_shared(frames[2], small)  # newest still resident
    assert small.hits == 1


# ---------------------------------------------------------------------------
# PR-4 semantic coalitions against the EchoBank arm
# ---------------------------------------------------------------------------


def _drive_coalition(behaviors: dict, n: int, seed: int) -> int:
    """Run a Byzantine coalition on the columnar arm; returns the
    agreed honest depth (assert_agreement = identical ledger
    prefixes)."""
    bad = sorted(behaviors)
    cluster = SimulatedCluster(
        n=n,
        config=Config(n=n, batch_size=8, delivery_columnar=True),
        seed=seed,
        key_seed=21,
        behaviors=behaviors,
    )
    honest = [i for i in cluster.ids if i not in bad]
    for i in range(12):
        cluster.submit(b"tx-%04d" % i, node_id=honest[i % len(honest)])
    cluster.run_until_drained(max_rounds=30, skip=bad)
    depth = cluster.assert_agreement(skip=bad)
    for nid in honest:
        for batch in cluster.nodes[nid].committed_batches:
            for tx in batch.tx_list():
                assert tx.startswith(b"tx-"), tx
    return depth


@pytest.mark.faults
def test_equivocator_coalition_columnar_bank():
    """An Equivocator sends CONFLICTING per-receiver RBC roots: the
    EchoBank's per-(root, instance) counting must keep the quorums
    separate — conflating them would fork or stall the honest
    majority."""
    from cleisthenes_tpu.protocol.byzantine import make_behavior

    behaviors = {"node003": make_behavior("equivocator", seed=31)}
    depth = _drive_coalition(behaviors, n=4, seed=13)
    assert depth >= 1
    assert behaviors["node003"].rewrites > 0, "adversary never lied"


@pytest.mark.faults
def test_bad_dealer_coalition_columnar_bank():
    """BadDealer's structurally-valid wrong shards must burn their
    one-vote bank slots without wedging honest quorums."""
    from cleisthenes_tpu.protocol.byzantine import make_behavior

    behaviors = {"node003": make_behavior("bad_dealer", seed=32)}
    depth = _drive_coalition(behaviors, n=4, seed=17)
    assert depth >= 1
    assert behaviors["node003"].rewrites > 0


@pytest.mark.faults
def test_epoch_sprayer_coalition_columnar_bank():
    """EpochSprayer's far-future spam exercises the demux window in
    front of the bank (no bank rows may be minted for epochs outside
    the window)."""
    from cleisthenes_tpu.protocol.byzantine import (
        CompositeBehavior,
        make_behavior,
    )

    behaviors = {
        "node003": CompositeBehavior(
            [
                make_behavior("epoch_sprayer", seed=33),
                make_behavior("split_voter", seed=34),
            ]
        )
    }
    depth = _drive_coalition(behaviors, n=4, seed=19)
    assert depth >= 1


# ---------------------------------------------------------------------------
# fuzz bands on the columnar arm
# ---------------------------------------------------------------------------


# ---------------------------------------------------------------------------
# PR-4 semantic coalitions against the wave router (ISSUE 10)
# ---------------------------------------------------------------------------


@pytest.mark.faults
def test_equivocator_coalition_wave_router():
    """Equivocating per-receiver roots through the ROUTER's echo/ready
    columns: the per-(root, instance) EchoBank counting must keep the
    quorums separate when whole waves land in one dispatch."""
    from cleisthenes_tpu.protocol.byzantine import make_behavior

    assert Config().wave_routing is True  # the arm under test
    behaviors = {"node003": make_behavior("equivocator", seed=41)}
    depth = _drive_coalition(behaviors, n=4, seed=23)
    assert depth >= 1
    assert behaviors["node003"].rewrites > 0, "adversary never lied"


@pytest.mark.faults
def test_epoch_sprayer_coalition_wave_router():
    """EpochSprayer's far-future spam exercises the router's
    column-granular demux window (no state minted outside it) and the
    per-payload CATCHUP renudge cadence."""
    from cleisthenes_tpu.protocol.byzantine import (
        CompositeBehavior,
        make_behavior,
    )

    behaviors = {
        "node003": CompositeBehavior(
            [
                make_behavior("epoch_sprayer", seed=42),
                make_behavior("split_voter", seed=43),
            ]
        )
    }
    depth = _drive_coalition(behaviors, n=4, seed=29)
    assert depth >= 1


@pytest.mark.faults
def test_selective_mute_coalition_wave_router():
    """SelectiveMute starves chosen links: waves arrive asymmetric
    per receiver, so the router's per-receiver bundles must still
    drive the honest quorums to agreement."""
    from cleisthenes_tpu.protocol.byzantine import make_behavior

    behaviors = {"node003": make_behavior("selective_mute", seed=44)}
    depth = _drive_coalition(behaviors, n=4, seed=31)
    assert depth >= 1


@pytest.mark.faults
def test_fuzz_band_columnar_delivery():
    """20 sampled composite schedules (semantic behaviors x wire
    faults x crash/partition timelines) with delivery_columnar=True —
    a seed band disjoint from ci.sh's 0:20 smoke band, so the
    delivery plane adds coverage instead of re-running it."""
    from tools.fuzz import run_schedule, sample_schedule

    assert Config().delivery_columnar is True  # the fuzzer's arm
    for seed in range(300, 320):
        v = run_schedule(sample_schedule(seed))
        assert v is None, f"seed {seed}: {v}"


@pytest.mark.slow
@pytest.mark.faults
def test_fuzz_deep_sweep_columnar_delivery():
    """The 200-seed slow band on the columnar delivery arm."""
    from tools.fuzz import run_schedule, sample_schedule

    assert Config().delivery_columnar is True
    for seed in range(320, 520):
        v = run_schedule(sample_schedule(seed))
        assert v is None, f"seed {seed}: {v}"


@pytest.mark.faults
def test_fuzz_band_wave_routing():
    """20 sampled composite schedules against the WAVE ROUTER (the
    fuzzer's default arm since wave_routing defaults True) — a seed
    band disjoint from the ci.sh smoke band and the PR-9 delivery
    band, so the router seam adds coverage instead of re-running it.
    Wire-fault schedules mount a fault_filter, which on the channel
    transport keeps per-frame decode/verify but still routes the
    verified wave — the seam is exercised under tampering too."""
    from tools.fuzz import run_schedule, sample_schedule

    assert Config().wave_routing is True  # the fuzzer's arm
    for seed in range(520, 540):
        v = run_schedule(sample_schedule(seed))
        assert v is None, f"seed {seed}: {v}"


@pytest.mark.slow
@pytest.mark.faults
def test_fuzz_deep_sweep_wave_routing():
    """The 200-seed slow band on the wave-routing arm."""
    from tools.fuzz import run_schedule, sample_schedule

    assert Config().wave_routing is True
    for seed in range(540, 740):
        v = run_schedule(sample_schedule(seed))
        assert v is None, f"seed {seed}: {v}"


@pytest.mark.faults
def test_fuzz_band_scalar_routing_pinned():
    """Wave routing drains a whole wave before any handler runs, so
    the scalar arm's finer per-message interleavings (a new message
    overtaking older pending ones mid-wave) are a schedule space the
    default arm can no longer reach — this band stays PINNED to
    wave_routing=False so the adversarial scheduler keeps exploring
    it (the schedule key round-trips through repro files)."""
    from tools.fuzz import run_schedule, sample_schedule

    for seed in range(740, 760):
        s = sample_schedule(seed)
        s["wave_routing"] = False
        v = run_schedule(s)
        assert v is None, f"seed {seed}: {v}"
