"""tools/staticcheck: the analyzer's own test coverage.

The fixture corpus under tests/staticcheck_fixtures/ carries
known-bad and known-good snippets per rule; bad lines are tagged
``# BAD:<RULE>`` and the tests assert the EXACT (rule, line) set the
analyzer reports — a finding on an untagged line or a missed tag both
fail.  Fixture paths reuse the analyzer's path-derived scoping
(protocol/ = determinism plane, transport/ = transport scope), so
scope resolution itself is under test too.
"""

from __future__ import annotations

import json
import pathlib
import re
import subprocess
import sys

import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from tools.staticcheck import (  # noqa: E402
    BASELINE_PATH,
    check_paths,
    load_baseline,
    registered_rules,
    split_baselined,
    write_baseline,
)
from tools.staticcheck.core import check_file  # noqa: E402

FIXTURES = REPO / "tests" / "staticcheck_fixtures"
_BAD_RE = re.compile(r"#\s*BAD:([A-Z0-9]+)")


def expected_findings(path: pathlib.Path):
    """{(rule, line)} from the fixture's # BAD:<RULE> tags."""
    out = set()
    for i, line in enumerate(
        path.read_text(encoding="utf-8").splitlines(), 1
    ):
        m = _BAD_RE.search(line)
        if m:
            out.add((m.group(1), i))
    return out


def reported_findings(path: pathlib.Path):
    return {(f.rule, f.line) for f in check_file(path, REPO)}


BAD_FIXTURES = [
    "protocol/det001_bad.py",
    # the observability plane does not relax DET001: raw perf_counter
    # in protocol code gates even with utils/trace.py landed (its
    # allow[DET001] pragma is confined to that one file)
    "protocol/det001_trace_bad.py",
    # ...and neither does the live telemetry plane: the sampler /
    # watchdog clocks are pragma'd in utils/timeseries.py and
    # utils/watchdog.py only — a hand-rolled sampler loop or stall
    # clock in protocol/ still gates
    "protocol/det001_obs_bad.py",
    "protocol/det002_bad.py",
    # the EchoBank surface (ISSUE 9): a hand-rolled receipt bank that
    # iterates sender/root sets in hash order still gates — the bank
    # exists precisely so no set order reaches the delivery plane
    "protocol/det002_echobank_bad.py",
    # the columnar seam (ISSUE 7): direct BatchCrypto verify/decode
    # from protocol/ outside hub.py gates, so the wave refactor can't
    # silently erode back to scalar dispatch
    "protocol/det003_bad.py",
    # the wave-router seam (ISSUE 10): per-frame serve_request /
    # handle_message dispatch from transport code still gates — the
    # router's one-dispatch-per-kind-per-wave discipline can't
    # silently erode back to one Python call chain per payload
    "transport/det004_bad.py",
    # the roster-version seam (ISSUE 12): epoch-scoped protocol code
    # reading the construction-time n/f/keys/membership still gates —
    # a fixed-roster read is correct right up until the first
    # RECONFIG crosses, then a silent fork
    "protocol/det005_bad.py",
    # the lane-frontier seam (ISSUE 20): lane-scoped protocol code
    # reading the bare primary-lane epoch/settled/committed frontier
    # still gates — a bare read silently pins lane 0's frontier the
    # moment a second lane exists
    "protocol/det005_lane_bad.py",
    # the egress wave-signer seam (ISSUE 13): per-frame envelope
    # encode+sign from a transport send path still gates — the
    # one-sign-pass-per-wave discipline can't silently erode back to
    # one encode + MAC per post
    "transport/det006_bad.py",
    # the wire registry (ISSUE 14): duplicate kind numbers, kinds no
    # parser accepts and kinds no encoder emits gate at the registry
    # declaration — the two-pass index works on a single file too
    "transport/wire001_bad.py",
    # ...and the pb-adapter side: duplicate extension tags, reserved
    # envelope numbers, orphaned tags
    "transport/pb001_bad.py",
    # the snapshot-schema registry (ISSUE 14): counters nothing
    # increments and counters that never reach snapshot() gate at the
    # declaration line
    "protocol/schema001_bad.py",
    # the arm registry (ISSUE 14): stale ARM_FLAGS entries, dead arm
    # flags and wave entry points with no arm-flag gate
    "protocol/arm001_bad.py",
    # the verify-before-dispatch taint walk (ISSUE 14): decoded frames
    # reaching a handler sink with no verify_wire* in between
    "transport/verify001_bad.py",
    "protocol/conc001_bad.py",
    "transport/conc002_bad.py",
    # the caller-holds-lock contract (ISSUE 17): *_locked callees
    # invoked without the callee class's declared lock — the
    # interprocedural gap CONC001's same-method scan cannot see
    "protocol/conc003_bad.py",
    # blocking calls one or more hops BELOW a handler (ISSUE 17):
    # CONC002 sees a clean handler body; the pass-3 reachability
    # walk convicts the helper's fsync/sleep/recv
    "transport/conc004_bad.py",
    # interprocedural entropy taint (ISSUE 17): DET001 convicts the
    # source line, DET007 convicts where the derived value LANDS in
    # plane state — one hop apart within a file here, cross-module
    # in the xmodule/callgraph_bad tree
    "protocol/det007_bad.py",
    "protocol/err001_bad.py",
    # the WAN stem rule (ISSUE 16): transport files named wan/wan_*
    # join the determinism plane, so raw random/wall-clock in a link
    # model gates — seeded WAN schedules must replay byte-identically
    "transport/wan_det001_bad.py",
]
GOOD_FIXTURES = [
    "protocol/det001_good.py",
    "protocol/det002_good.py",
    "protocol/det003_good.py",
    "transport/det004_good.py",
    "protocol/det005_good.py",
    "protocol/det005_lane_good.py",
    "transport/det006_good.py",
    "transport/wire001_good.py",
    "transport/pb001_good.py",
    "protocol/schema001_good.py",
    "protocol/arm001_good.py",
    "transport/verify001_good.py",
    "protocol/conc001_good.py",
    "transport/conc002_good.py",
    "protocol/conc003_good.py",
    "transport/conc004_good.py",
    "protocol/det007_good.py",
    "protocol/err001_good.py",
    "transport/wan_det001_good.py",
    "protocol/pragma_file_cases.py",
]


@pytest.mark.parametrize("rel", BAD_FIXTURES)
def test_known_bad_exact_locations(rel):
    path = FIXTURES / rel
    expected = expected_findings(path)
    assert expected, f"fixture {rel} has no # BAD tags"
    assert reported_findings(path) == expected


@pytest.mark.parametrize("rel", GOOD_FIXTURES)
def test_known_good_is_clean(rel):
    path = FIXTURES / rel
    assert reported_findings(path) == set()


def test_out_of_plane_paths_skip_plane_rules(tmp_path):
    # identical source, no protocol/core/ops in the path: DET rules
    # must not fire (the plane is path-defined)
    src = (FIXTURES / "protocol" / "det001_bad.py").read_text(
        encoding="utf-8"
    )
    out = tmp_path / "toolscratch" / "det001_elsewhere.py"
    out.parent.mkdir()
    out.write_text(src, encoding="utf-8")
    rules = {f.rule for f in check_file(out, tmp_path)}
    assert "DET001" not in rules


def test_pragma_suppression_and_missing_justification():
    path = FIXTURES / "protocol" / "pragma_cases.py"
    findings = check_file(path, REPO)
    by_rule = {}
    for f in findings:
        by_rule.setdefault(f.rule, []).append(f)
    # the justified pragma suppressed its DET001; the bare pragma
    # suppressed nothing AND is itself reported
    assert len(by_rule.get("DET001", [])) == 1
    assert len(by_rule.get("PRAGMA001", [])) == 1
    det = by_rule["DET001"][0]
    bare = by_rule["PRAGMA001"][0]
    assert det.line == bare.line  # both point at the bare-pragma line
    assert "time.time" in det.message


def test_baseline_round_trip(tmp_path):
    path = FIXTURES / "protocol" / "det001_bad.py"
    findings = check_file(path, REPO)
    assert findings
    bl_path = tmp_path / "baseline.json"
    write_baseline(findings, bl_path)
    baseline = load_baseline(bl_path)
    # every current finding is grandfathered...
    fresh, old = split_baselined(findings, baseline)
    assert fresh == [] and len(old) == len(findings)
    # ...but a NEW copy of a baselined finding still gates (counts
    # are budgets, not wildcards)
    doubled = findings + [findings[0]]
    fresh2, _old2 = split_baselined(doubled, baseline)
    assert len(fresh2) == 1
    # and the file round-trips through JSON intact
    assert json.loads(bl_path.read_text())["findings"] == {
        k: v for k, v in sorted(baseline.items())
    }


def test_fixture_corpus_walk():
    # the per-rule corpus lives under protocol/ + transport/ (the
    # cross-module registry tree under xmodule/ has its own walk test
    # in tests/test_staticcheck_program.py)
    findings, n_files = check_paths(
        [FIXTURES / "protocol", FIXTURES / "transport"], REPO
    )
    assert n_files == len(BAD_FIXTURES) + len(GOOD_FIXTURES) + 1
    tagged = sum(
        len(expected_findings(FIXTURES / rel)) for rel in BAD_FIXTURES
    )
    # corpus-wide: every tagged line + the two pragma_cases findings
    assert len(findings) == tagged + 2


def test_tree_walks_skip_the_fixture_corpus():
    # scanning tests/ must NOT drown in the corpus's deliberate
    # findings: the walker treats staticcheck_fixtures as test data
    # unless a target points inside it
    findings, n_files = check_paths([REPO / "tests"], REPO)
    assert n_files > 0
    assert not any("staticcheck_fixtures" in f.path for f in findings)


def test_rule_catalog_registered():
    assert set(registered_rules()) == {
        "DET001",
        "DET002",
        "DET003",
        "DET004",
        "DET005",
        "DET006",
        "DET007",
        "CONC001",
        "CONC002",
        "CONC003",
        "CONC004",
        "ERR001",
        "WIRE001",
        "SCHEMA001",
        "ARM001",
        "VERIFY001",
    }


def test_guarded_by_metadata_merges():
    from cleisthenes_tpu.utils.determinism import guarded_by

    @guarded_by("_lock", "_a")
    @guarded_by("_other", "_b", "_c")
    class X:
        pass

    assert X.__guarded_by__ == {
        "_a": "_lock",
        "_b": "_other",
        "_c": "_other",
    }
    with pytest.raises(ValueError):
        guarded_by("_lock")


def test_gate_is_clean_on_the_package():
    """The merged tree ships at zero unbaselined findings with an
    EMPTY baseline (the PR's acceptance criterion), via the same CLI
    entry ci.sh runs."""
    proc = subprocess.run(
        [sys.executable, "-m", "tools.staticcheck", "cleisthenes_tpu"],
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=60,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert json.loads(BASELINE_PATH.read_text())["findings"] == {}
