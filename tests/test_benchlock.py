"""Measurement mutual exclusion (tools/benchlock.py).

Round-4 weak #2: concurrent watcher probes silently inflated the
driver's CPU capture ~2x on this one-core box.  These tests pin the
three behaviors that prevent a recurrence: exclusivity, reentrancy
for spawned children, and pause/resume of registered background jobs.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import time

import pytest

from tools import benchlock


@pytest.fixture(autouse=True)
def _isolated_lock(tmp_path, monkeypatch):
    monkeypatch.setattr(benchlock, "LOCK_PATH", str(tmp_path / "lock"))
    monkeypatch.setattr(benchlock, "PAUSE_DIR", str(tmp_path / "pause"))
    monkeypatch.delenv(benchlock._ENV_KEY, raising=False)


def test_exclusive_second_holder_sees_busy():
    with benchlock.hold("a") as held_a:
        assert held_a
        # a second would-be holder in THIS process is reentrant by
        # design; exclusivity is cross-process, via a child
        env = dict(os.environ)
        env.pop(benchlock._ENV_KEY, None)
        env["PYTHONPATH"] = os.path.dirname(os.path.dirname(__file__))
        code = (
            "from tools import benchlock\n"
            f"benchlock.LOCK_PATH = {benchlock.LOCK_PATH!r}\n"
            f"benchlock.PAUSE_DIR = {benchlock.PAUSE_DIR!r}\n"
            "with benchlock.hold('b', block=False) as held:\n"
            "    print('HELD' if held else 'BUSY')\n"
        )
        r = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True, text=True, env=env, timeout=60,
        )
        assert "BUSY" in r.stdout, r.stdout + r.stderr
    # released: the same child code now acquires
    r = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True, text=True, env=env, timeout=60,
    )
    assert "HELD" in r.stdout, r.stdout + r.stderr


def test_reentrant_for_children_via_env():
    with benchlock.hold("outer") as a:
        assert a
        # simulates bench.py --child spawned by a lock-holding parent:
        # the env marker is inherited, so the nested hold no-ops
        assert os.environ.get(benchlock._ENV_KEY) == str(os.getpid())
        with benchlock.hold("inner") as b:
            assert b
    assert benchlock._ENV_KEY not in os.environ


def test_pausable_job_is_stopped_and_resumed():
    child = subprocess.Popen(
        [sys.executable, "-c", "import time\nwhile True: time.sleep(0.2)"],
    )
    try:
        os.makedirs(benchlock.PAUSE_DIR, exist_ok=True)
        with open(os.path.join(benchlock.PAUSE_DIR, str(child.pid)), "w"):
            pass

        def state() -> str:
            with open(f"/proc/{child.pid}/stat") as f:
                return f.read().split(")")[-1].split()[0]

        with benchlock.hold("capture"):
            deadline = time.time() + 10
            while state() != "T" and time.time() < deadline:
                time.sleep(0.05)
            assert state() == "T"  # SIGSTOPped while the lock is held
        deadline = time.time() + 10
        while state() == "T" and time.time() < deadline:
            time.sleep(0.05)
        assert state() != "T"  # SIGCONTed on release
    finally:
        child.kill()
        child.wait()


def test_late_registration_self_stops_and_release_resumes():
    """A job that registers while a capture is in flight must stop
    itself immediately (the holder's pause snapshot cannot see it) and
    wake at release via the holder's registry re-scan."""
    code = (
        "import sys\n"
        "from tools import benchlock\n"
        f"benchlock.LOCK_PATH = {benchlock.LOCK_PATH!r}\n"
        f"benchlock.PAUSE_DIR = {benchlock.PAUSE_DIR!r}\n"
        "benchlock.register_pausable()\n"
        "print('RESUMED', flush=True)\n"
    )
    env = dict(os.environ)
    env.pop(benchlock._ENV_KEY, None)
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(__file__))
    with benchlock.hold("capture"):
        child = subprocess.Popen(
            [sys.executable, "-c", code],
            stdout=subprocess.PIPE, text=True, env=env,
        )
        # the child must reach its self-SIGSTOP, not print RESUMED
        deadline = time.time() + 20
        state = ""
        while time.time() < deadline:
            try:
                with open(f"/proc/{child.pid}/stat") as f:
                    state = f.read().split(")")[-1].split()[0]
            except OSError:
                break
            if state == "T":
                break
            time.sleep(0.05)
        assert state == "T", f"child never self-stopped (state={state})"
    out, _ = child.communicate(timeout=20)
    assert "RESUMED" in out  # release re-scan CONTed it


def test_load_snapshot_shape():
    snap = benchlock.load_snapshot()
    assert len(snap["loadavg"]) == 3
    assert isinstance(snap["competing_python_procs"], int)
    assert isinstance(snap["paused_jobs"], int)


def test_nonblocking_busy_probe_exits_cleanly(tmp_path, monkeypatch):
    """A busy block=False probe must yield False and EXIT without
    error: the double-close (EBADF in the outer finally) killed the
    armed relay watcher the first time a capture held the lock."""
    from tools import benchlock

    monkeypatch.setattr(
        benchlock, "LOCK_PATH", str(tmp_path / "lk"), raising=False
    )
    monkeypatch.delenv(benchlock._ENV_KEY, raising=False)
    with benchlock.hold("holder"):
        # the reentrancy env var is set by the outer hold; a sibling
        # process would not see it — simulate that sibling
        monkeypatch.delenv(benchlock._ENV_KEY, raising=False)
        with benchlock.hold("prober", block=False) as held:
            assert held is False
        # reaching here without OSError IS the regression assertion
    with benchlock.hold("after", block=False) as held:
        assert held is True
