"""BBA protocol tests: agreement, validity, probabilistic termination,
crash/Byzantine tolerance — full multi-node instances over the
deterministic in-proc transport (the behavior matrix of reference
docs/BBA-EN.md, which the skeleton bba/bba.go:63-107 never filled in)."""

import dataclasses

import pytest

from cleisthenes_tpu.config import Config
from cleisthenes_tpu.ops import tpke
from cleisthenes_tpu.ops.coin import CommonCoin
from cleisthenes_tpu.protocol.bba import BBA
from cleisthenes_tpu.transport.base import HmacAuthenticator
from cleisthenes_tpu.transport.broadcast import ChannelBroadcaster
from cleisthenes_tpu.transport.channel import ChannelNetwork
from cleisthenes_tpu.transport.message import BbaType, CoinPayload


class BbaHandler:
    def __init__(self, bba: BBA):
        self.bba = bba

    def serve_request(self, msg):
        self.bba.handle_message(msg.sender_id, msg.payload)


def make_bba_network(n, seed=None, auth=False, proposer_idx=0):
    cfg = Config(n=n)
    ids = [f"node{i}" for i in range(n)]
    proposer = ids[proposer_idx]
    pub, secrets = tpke.deal(n, cfg.f + 1, seed=7)
    coin = CommonCoin(pub)
    net = ChannelNetwork(seed=seed)
    bbas = {}
    for i, node_id in enumerate(ids):
        bba = BBA(
            config=cfg,
            epoch=0,
            proposer=proposer,
            owner=node_id,
            member_ids=ids,
            coin=coin,
            coin_secret=secrets[i],
            out=ChannelBroadcaster(net, node_id, ids),
        )
        bbas[node_id] = bba
        net.join(
            node_id,
            BbaHandler(bba),
            HmacAuthenticator.derive(b"master", node_id, ids) if auth else None,
        )
    return cfg, net, bbas


def assert_agreement(bbas, skip=()):
    decisions = {
        nid: b.result() for nid, b in bbas.items() if nid not in skip
    }
    assert all(d is not None for d in decisions.values()), decisions
    assert len(set(decisions.values())) == 1, decisions
    return next(iter(decisions.values()))


@pytest.mark.parametrize("value", [True, False])
def test_bba_unanimous_input_decides_that_value(value):
    """Validity: if every correct node inputs v, the decision is v."""
    cfg, net, bbas = make_bba_network(4)
    for bba in bbas.values():
        bba.input(value)
    net.run()
    assert assert_agreement(bbas) == value


@pytest.mark.parametrize("seed", [1, 2, 3, 11, 42])
def test_bba_mixed_inputs_agree_under_adversarial_scheduling(seed):
    cfg, net, bbas = make_bba_network(4, seed=seed, auth=True)
    for i, bba in enumerate(bbas.values()):
        bba.input(i % 2 == 0)
    net.run()
    assert_agreement(bbas)


@pytest.mark.parametrize("seed", [5, 9])
def test_bba_n7_mixed_inputs(seed):
    cfg, net, bbas = make_bba_network(7, seed=seed)
    for i, bba in enumerate(bbas.values()):
        bba.input(i < 3)
    net.run()
    assert_agreement(bbas)


def test_bba_tolerates_f_crashes():
    cfg, net, bbas = make_bba_network(7, seed=3)
    net.crash("node5")
    net.crash("node6")
    for nid, bba in bbas.items():
        if nid not in ("node5", "node6"):
            bba.input(True)
    net.run()
    assert assert_agreement(bbas, skip=("node5", "node6")) is True


def test_bba_unanimous_with_crashes_keeps_validity():
    cfg, net, bbas = make_bba_network(4, seed=8)
    net.crash("node3")
    for nid, bba in bbas.items():
        if nid != "node3":
            bba.input(False)
    net.run()
    assert assert_agreement(bbas, skip=("node3",)) is False


def test_bba_all_instances_halt_after_decision():
    """The TERM gadget must fully drain: 2f+1 TERMs halt every node."""
    cfg, net, bbas = make_bba_network(4, seed=2)
    for bba in bbas.values():
        bba.input(True)
    net.run()
    for bba in bbas.values():
        assert bba.done
        assert bba.halted  # saw 2f+1 TERM


def test_bba_late_input_still_decides():
    """A node whose ACS input arrives late must catch up (the
    passive-participation path; ACS inputs 0 only after n-f ones)."""
    cfg, net, bbas = make_bba_network(4)
    for nid, bba in bbas.items():
        if nid != "node3":
            bba.input(True)
    net.run()
    bbas["node3"].input(True)
    net.run()
    assert_agreement(bbas)


def test_bba_garbage_coin_shares_are_rejected():
    """Byzantine coin shares must fail CP verification and never skew
    or block the coin (docs/BBA-EN.md:174-177 cooperation property)."""
    cfg, net, bbas = make_bba_network(4, seed=6)

    from cleisthenes_tpu.transport.message import (
        decode_message,
        encode_message,
    )

    def corrupt_node2_coins(sender, receiver, wire):
        if sender != "node2":
            return wire
        msg = decode_message(wire)
        if isinstance(msg.payload, CoinPayload):
            bad = msg.payload._replace(d=12345, z=99999)
            return encode_message(dataclasses.replace(msg, payload=bad))
        return wire

    net.fault_filter = corrupt_node2_coins
    for bba in bbas.values():
        bba.input(True)
    net.run()
    assert assert_agreement(bbas) is True


def test_bba_byzantine_equivocating_bvals_no_split():
    """One node sending BVAL(0) to half and BVAL(1) to the other half
    must not break agreement."""
    cfg, net, bbas = make_bba_network(4, seed=13)

    from cleisthenes_tpu.transport.message import (
        BbaPayload,
        decode_message,
        encode_message,
    )

    def equivocate(sender, receiver, wire):
        if sender != "node0":
            return wire
        msg = decode_message(wire)
        p = msg.payload
        if isinstance(p, BbaPayload) and p.type == BbaType.BVAL:
            flip = receiver in ("node1", "node3")
            bad = p._replace(value=p.value ^ flip)
            return encode_message(dataclasses.replace(msg, payload=bad))
        return wire

    net.fault_filter = equivocate
    for nid, bba in bbas.items():
        bba.input(nid in ("node0", "node1"))
    net.run()
    assert_agreement(bbas, skip=("node0",))


def test_bba_result_none_before_decision():
    cfg, net, bbas = make_bba_network(4)
    assert all(b.result() is None for b in bbas.values())
    assert all(not b.done for b in bbas.values())
