"""The reference-protobuf wire adapter (VERDICT round-2 missing #6).

Round-trips our RBC/BBA envelopes through byte-level proto3 frames
matching reference pb/message.proto:11-46, and — where the image ships
a protobuf runtime — cross-checks against an independently built stock
decoder so "same capabilities on the wire" is verified by a second
implementation, not by our own inverse."""

import math

import pytest

from cleisthenes_tpu.transport.message import (
    BbaPayload,
    BbaType,
    CatchupReqPayload,
    CatchupRespPayload,
    CoinPayload,
    Message,
    RbcPayload,
    RbcType,
    ResharePayload,
)
from cleisthenes_tpu.transport.pb_adapter import (
    decode_pb_message,
    encode_pb_message,
)

RBC_P = RbcPayload(
    type=RbcType.ECHO,
    proposer="node1",
    epoch=7,
    root_hash=b"r" * 32,
    branch=(b"a" * 32, b"b" * 32),
    shard=b"shard-bytes",
    shard_index=3,
)
BBA_P = BbaPayload(
    type=BbaType.AUX, proposer="node2", epoch=7, round=1, value=True
)


@pytest.mark.parametrize("payload", [RBC_P, BBA_P])
def test_roundtrip(payload):
    msg = Message(
        sender_id="node9",
        timestamp=1234.5,
        payload=payload,
        signature=b"\x01" * 32,
    )
    wire = encode_pb_message(msg)
    back = decode_pb_message(wire, sender_id="node9")
    assert back.payload == payload
    assert back.signature == msg.signature
    assert math.isclose(back.timestamp, msg.timestamp, abs_tol=1e-6)


def test_non_reference_payloads_have_no_slot():
    msg = Message(
        sender_id="x",
        timestamp=0.0,
        payload=CoinPayload("p", 1, 0, 1, 7, 8, 9),
    )
    with pytest.raises(ValueError, match="no slot"):
        encode_pb_message(msg)


@pytest.mark.parametrize(
    "payload",
    [
        CatchupReqPayload(from_epoch=9),
        CatchupRespPayload(epoch=4, body=b"ledger-body-bytes"),
        ResharePayload(version=2, dealer="node001", body=b"dealing"),
    ],
)
def test_catchup_extension_slots_roundtrip(payload):
    """The crash-recovery CATCHUP pair rides extension tags beyond the
    reference's oneof and round-trips byte-exactly; a stock decoder of
    the unextended schema skips them as unknown fields."""
    msg = Message(
        sender_id="node9",
        timestamp=55.25,
        payload=payload,
        signature=b"\x02" * 32,
    )
    back = decode_pb_message(encode_pb_message(msg), sender_id="node9")
    assert back.payload == payload
    assert back.signature == msg.signature


def test_attestation_trailer_roundtrips_and_stays_optional():
    """The attested-log trailer (protocol/attest.py) rides its own
    extension tag beside signature/timestamp: it round-trips
    byte-exactly when armed and adds zero bytes on the baseline arm,
    where the frame must stay identical to the pre-attestation
    format."""
    att = b"\x00\x00\x00\x01" + b"\x07" * 41
    msg = Message(
        sender_id="node9",
        timestamp=9.5,
        payload=RBC_P,
        signature=b"\x03" * 32,
        attestation=att,
    )
    back = decode_pb_message(encode_pb_message(msg), sender_id="node9")
    assert back.attestation == att
    assert back.payload == RBC_P
    bare = Message(
        sender_id="node9", timestamp=9.5, payload=RBC_P,
        signature=b"\x03" * 32,
    )
    assert decode_pb_message(
        encode_pb_message(bare), sender_id="node9"
    ).attestation == b""
    assert len(encode_pb_message(msg)) > len(encode_pb_message(bare))


def test_malformed_frames_rejected():
    wire = encode_pb_message(
        Message(sender_id="x", timestamp=1.0, payload=BBA_P)
    )
    for bad in (wire[:-2], b"\xff" * 8, wire + b"\x05"):
        with pytest.raises(ValueError):
            decode_pb_message(bad)


def test_cross_check_with_stock_protobuf_decoder():
    """Decode our frames with an INDEPENDENT proto3 implementation
    built from the reference schema at runtime (skipped if the image
    lacks a protobuf runtime)."""
    try:
        from google.protobuf import descriptor_pb2, descriptor_pool
        from google.protobuf.message_factory import GetMessageClass
    except ImportError:
        pytest.skip("no protobuf runtime in image")

    fdp = descriptor_pb2.FileDescriptorProto()
    fdp.name = "ref_message.proto"
    fdp.package = "refpb"
    fdp.syntax = "proto3"
    ts = fdp.message_type.add()
    ts.name = "Timestamp"
    f = ts.field.add(); f.name = "seconds"; f.number = 1; f.type = 3; f.label = 1
    f = ts.field.add(); f.name = "nanos"; f.number = 2; f.type = 5; f.label = 1
    for sub in ("RBC", "BBA"):
        m = fdp.message_type.add()
        m.name = sub
        f = m.field.add(); f.name = "payload"; f.number = 1; f.type = 12; f.label = 1
        f = m.field.add(); f.name = "type"; f.number = 2; f.type = 5; f.label = 1
    msg = fdp.message_type.add()
    msg.name = "Message"
    f = msg.field.add(); f.name = "signature"; f.number = 1; f.type = 12; f.label = 1
    f = msg.field.add(); f.name = "timestamp"; f.number = 2; f.type = 11; f.label = 1
    f.type_name = ".refpb.Timestamp"
    f = msg.field.add(); f.name = "rbc"; f.number = 3; f.type = 11; f.label = 1
    f.type_name = ".refpb.RBC"; f.oneof_index = 0
    f = msg.field.add(); f.name = "bba"; f.number = 4; f.type = 11; f.label = 1
    f.type_name = ".refpb.BBA"; f.oneof_index = 0
    msg.oneof_decl.add().name = "payload"

    pool = descriptor_pool.DescriptorPool()
    pool.Add(fdp)
    MsgCls = GetMessageClass(pool.FindMessageTypeByName("refpb.Message"))

    ours = Message(
        sender_id="node9", timestamp=55.25, payload=BBA_P,
        signature=b"\x07" * 16,
    )
    parsed = MsgCls()
    parsed.ParseFromString(encode_pb_message(ours))
    assert parsed.signature == ours.signature
    assert parsed.timestamp.seconds == 55
    assert parsed.WhichOneof("payload") == "bba"
    assert parsed.bba.type == int(BbaType.AUX)
    assert parsed.bba.payload  # the opaque inner request bytes

    # and the reverse: a stock-encoded frame decodes through ours
    reencoded = parsed.SerializeToString()
    back = decode_pb_message(reencoded, sender_id="node9")
    assert back.payload == BBA_P
    assert back.signature == ours.signature


def test_unknown_scalar_fields_skip_per_proto3():
    """Forward compatibility: unknown varint/fixed fields from a newer
    schema revision must skip, not reject the frame."""
    from cleisthenes_tpu.transport.pb_adapter import _varint

    wire = encode_pb_message(
        Message(sender_id="x", timestamp=2.0, payload=BBA_P)
    )
    # append field 5 varint, field 6 fixed64, field 7 fixed32
    extra = (
        _varint((5 << 3) | 0) + _varint(777)
        + _varint((6 << 3) | 1) + b"\x01" * 8
        + _varint((7 << 3) | 5) + b"\x02" * 4
    )
    back = decode_pb_message(wire + extra, sender_id="x")
    assert back.payload == BBA_P


def test_interop_with_protoc_generated_stubs(tmp_path):
    """The strongest form of the byte-compatibility claim
    (pb_adapter.py:14-18): stubs generated by protoc from the
    REFERENCE'S OWN message.proto accept our frames, and frames the
    generated encoder produces decode through our adapter.  Skipped
    where the toolchain or the reference tree is absent."""
    import shutil
    import subprocess
    import sys

    ref_proto = "/root/reference/pb/message.proto"
    import os

    if shutil.which("protoc") is None or not os.path.exists(ref_proto):
        pytest.skip("protoc or the reference proto unavailable")
    pytest.importorskip("google.protobuf")
    shutil.copy(ref_proto, tmp_path / "message.proto")
    try:
        subprocess.run(
            [
                "protoc",
                "--python_out=.",
                "-I.",
                "-I/usr/include",
                "message.proto",
            ],
            cwd=tmp_path,
            check=True,
            capture_output=True,
            timeout=60,
        )
    except subprocess.CalledProcessError as e:
        pytest.skip(f"protoc failed: {e.stderr[:200]}")
    sys.path.insert(0, str(tmp_path))
    try:
        import message_pb2
    finally:
        sys.path.remove(str(tmp_path))

    # our adapter frame -> the reference's generated decoder
    ours = Message(
        sender_id="node4",
        timestamp=99.5,
        payload=RBC_P,
        signature=b"\x21" * 16,
    )
    parsed = message_pb2.Message()
    parsed.ParseFromString(encode_pb_message(ours))
    assert parsed.signature == ours.signature
    assert parsed.timestamp.seconds == 99
    assert parsed.WhichOneof("payload") == "rbc"
    assert parsed.rbc.payload  # opaque inner request bytes

    # the generated ENCODER's frame -> our adapter (round-trip the
    # parsed message; unknown fields — our type tag — are preserved
    # by proto3 semantics)
    back = decode_pb_message(parsed.SerializeToString(), sender_id="node4")
    assert back.payload == RBC_P
    assert back.signature == ours.signature
    assert math.isclose(back.timestamp, ours.timestamp)

    # and a frame built FROM SCRATCH by the generated encoder (no
    # unknown-field crutch) still decodes through ours
    from cleisthenes_tpu.transport import pb_adapter

    fresh = message_pb2.Message()
    fresh.signature = b"\x09" * 8
    fresh.timestamp.seconds = 12
    fresh.timestamp.nanos = 250_000_000
    _kind, tlv = pb_adapter._encode_payload(BBA_P)
    fresh.bba.payload = tlv
    back2 = decode_pb_message(fresh.SerializeToString(), sender_id="node2")
    assert back2.payload == BBA_P
    assert back2.signature == b"\x09" * 8
