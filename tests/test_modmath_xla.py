"""The XLA modexp kernels and batched TPKE helpers — pure JAX, no
native toolchain required (deliberately NOT in test_native.py, whose
module-level skip would hide kernel regressions on toolchain-less
hosts)."""

import random

from cleisthenes_tpu.ops import modmath as mm
from cleisthenes_tpu.ops import tpke as T


def test_xla_pow_path_above_host_floor():
    """The transposed-layout (NLIMBS, B) kernel itself: ModEngine
    delegates sub-floor batches to the host, so pin the batch AT the
    floor (strict `<` comparison) to hold the device path covered
    while landing exactly on the 8192 compile bucket."""
    eng = mm.ModEngine("tpu", group=mm.DEFAULT_GROUP)
    B = eng.HOST_FLOOR
    rnd = random.Random(7)
    p = mm.DEFAULT_GROUP.p
    bases = [rnd.randrange(1, p) for _ in range(B)]
    exps = [rnd.randrange(0, p) for _ in range(B)]
    got = eng.pow_batch(bases, exps)
    # spot-check a deterministic sample (full python-pow comparison at
    # 8k items costs more than the kernel run)
    for i in range(0, B, 997):
        assert got[i] == pow(bases[i], exps[i], p)
    u2 = list(reversed(bases))
    e2 = list(reversed(exps))
    dual = eng.dual_pow_batch(bases, exps, u2, e2)
    for i in range(0, B, 997):
        assert dual[i] == pow(bases[i], exps[i], p) * pow(u2[i], e2[i], p) % p


def test_mont_mul_batch_layout_roundtrip():
    """mont_mul_batch keeps its (B, NLIMBS) public surface over the
    transposed kernel."""
    import numpy as np

    rnd = random.Random(3)
    p = mm.DEFAULT_GROUP.p
    xs = [rnd.randrange(1, p) for _ in range(8)]
    ys = [rnd.randrange(1, p) for _ in range(8)]
    a = np.stack([mm.int_to_limbs(x) for x in xs])
    b = np.stack([mm.int_to_limbs(y) for y in ys])
    out = np.asarray(mm.mont_mul_batch(a, b))
    r_inv = pow(mm.R, -1, p)
    for i in range(8):
        assert mm.limbs_to_int(out[i]) == xs[i] * ys[i] * r_inv % p


def test_issue_and_combine_batch_match_scalar():
    """issue_shares_batch / combine_shares_batch vs their scalar
    equivalents (ops/tpke.py)."""
    pub, shares = T.deal(4, 2, seed=5)
    base = pow(T.DEFAULT_GROUP.g, 12345, T.DEFAULT_GROUP.p)
    ctx = b"batch-issue-test"
    vks = pub.verification_keys
    items = [(s, base, ctx, vks[s.index - 1]) for s in shares]
    out = T.issue_shares_batch(items)
    assert [s.index for s in out] == [s.index for s in shares]
    # every batched share verifies under the scalar verifier
    assert all(T.verify_shares(pub, base, out, ctx))
    # vk=None recomputes the verification key: same validity
    out2 = T.issue_shares_batch([(shares[0], base, ctx, None)])
    assert all(T.verify_shares(pub, base, out2, ctx))
    # combines (scalar vs batch vs distinct subsets) agree
    a = T.combine_shares(out[:2], 2)
    b = T.combine_shares(out[2:4], 2)
    assert a == b  # subset independence
    got = T.combine_shares_batch([out[:2], out[1:3], out[2:]], 2)
    assert got == [a, a, a]


def test_pow_batch_grouped_device_path_with_splits_and_tails():
    """The comb kernel's full engine path — G_ROW splitting, per-size
    compile buckets, and strictly-ordered reassembly of a group whose
    tail slice lands in a different bucket — above the device
    crossover (every other suite runs backend='cpu' and would take the
    flat fallback, leaving this logic untested)."""
    eng = mm.ModEngine("tpu", group=mm.DEFAULT_GROUP)
    rnd = random.Random(11)
    p, q = mm.DEFAULT_GROUP.p, mm.DEFAULT_GROUP.q
    groups = [
        (rnd.randrange(2, p), [rnd.randrange(0, q) for _ in range(sz)])
        # 700/1200 force G_ROW=512 splits with odd tails; 3 keeps a
        # tiny group in the same dispatch plan; total 2003 >= crossover
        for sz in (700, 1200, 100, 3)
    ]
    out = eng.pow_batch_grouped(groups)
    for (base, exps), res in zip(groups, out):
        assert len(res) == len(exps)
        for i in range(0, len(exps), 97):
            assert res[i] == pow(base, exps[i], p)
        assert res[-1] == pow(base, exps[-1], p)  # tail ordering
