"""CryptoHub: cross-instance batching of the live protocol hot path.

VERDICT.md round-1 item 3: the live path must use the batched kernels.
These tests prove (a) batched verification agrees with single-shot
verification, (b) a full epoch's crypto goes through the hub in FEW
batched dispatches instead of per-message singletons, and (c) invalid
work is rejected identically through the batched path.
"""

import numpy as np
import pytest

from cleisthenes_tpu.config import Config
from cleisthenes_tpu.ops import tpke
from cleisthenes_tpu.ops.backend import BatchCrypto
from cleisthenes_tpu.ops.coin import CommonCoin
from cleisthenes_tpu.protocol.hub import CryptoHub, HubWave, _Memo


class TestVerifyShareGroups:
    """The multi-group dual-pow fold (one dispatch for TPKE + coins)."""

    @pytest.mark.parametrize("backend", ["cpu", "tpu"])
    def test_groups_agree_with_single_calls(self, backend):
        pub_a, shares_a = tpke.deal(4, 2, seed=21)
        pub_b, shares_b = tpke.deal(7, 3, seed=22)
        svc_a = tpke.Tpke(pub_a)
        ct = svc_a.encrypt(b"group-a")
        dss = [svc_a.dec_share(s, ct) for s in shares_a]
        coin = CommonCoin(pub_b)
        cid = b"epoch|0"
        css = [coin.share(s, cid) for s in shares_b]
        # corrupt one share in each group
        dss[1] = tpke.DhShare(dss[1].index, dss[1].d, dss[1].e, dss[1].z + 1)
        css[4] = tpke.DhShare(css[4].index, css[4].d + 1, css[4].e, css[4].z)

        ga = (pub_a, ct.c1, dss, svc_a.context(ct))
        pub_c, base_c, ctx_c = coin.group_params(cid)
        gb = (pub_c, base_c, css, ctx_c)
        combined = tpke.verify_share_groups(
            [(ga[0], ga[1], ga[2], ga[3]), (gb[0], gb[1], gb[2], gb[3])],
            backend=backend,
        )
        singles = [
            tpke.verify_shares(ga[0], ga[1], ga[2], ga[3], backend="cpu"),
            tpke.verify_shares(gb[0], gb[1], gb[2], gb[3], backend="cpu"),
        ]
        assert combined == singles
        assert combined[0] == [True, False, True, True]
        assert combined[1][4] is False and sum(combined[1]) == 6


class TestSharePool:
    def test_deferred_verdicts_flow(self):
        pub, shares = tpke.deal(4, 2, seed=23)
        svc = tpke.Tpke(pub)
        ct = svc.encrypt(b"pool")
        pool = tpke.SharePool(2)
        for i, s in enumerate(shares[:3]):
            assert pool.add(f"n{i}", svc.dec_share(s, ct))
        assert len(pool) == 3
        assert pool.ready() is None  # nothing verified yet
        senders, shs = pool.collect_pending()
        ok = svc.verify_dec_shares(ct, shs)
        pool.apply_verdicts(senders, ok)
        valid = pool.ready()
        assert valid is not None and len({v.index for v in valid}) >= 2
        # burned sender cannot resubmit after a bad verdict
        pool2 = tpke.SharePool(2)
        bad = tpke.DhShare(1, 2, 3, 4)
        pool2.add("evil", bad)
        s2, sh2 = pool2.collect_pending()
        pool2.apply_verdicts(s2, [False])
        assert not pool2.add("evil", svc.dec_share(shares[0], ct))

    def test_try_verified_compat(self):
        pub, shares = tpke.deal(4, 2, seed=24)
        svc = tpke.Tpke(pub)
        ct = svc.encrypt(b"compat")
        pool = tpke.SharePool(2)
        pool.add("a", svc.dec_share(shares[0], ct))
        assert pool.try_verified(lambda s: svc.verify_dec_shares(ct, s)) is None
        pool.add("b", svc.dec_share(shares[1], ct))
        valid = pool.try_verified(lambda s: svc.verify_dec_shares(ct, s))
        assert valid is not None and len(valid) == 2


class TestHubBatching:
    def test_branch_groups_agree_with_singles(self):
        crypto = BatchCrypto("cpu", 8, 2, 4)
        hub = CryptoHub(crypto)
        rng = np.random.default_rng(31)
        shards = rng.integers(0, 256, size=(3, 8, 64), dtype=np.uint8)
        trees = crypto.merkle.build_batch(shards)
        results = {}

        class Sink:  # bulk-verdict client (the hub's branch contract)
            def on_branch_verdicts(self, ctxs, oks):
                for key, ok in zip(ctxs, oks):
                    results[key] = ok

        sink = Sink()
        wave = HubWave(hub.dedup)
        for t_i, t in enumerate(trees):
            for j in range(8):
                leaf = shards[t_i, j].tobytes()
                if t_i == 1 and j == 3:
                    leaf = b"\xff" + leaf[1:]  # corrupt
                wave.add_branch(
                    sink, t.root, leaf, tuple(t.branch(j)), j, (t_i, j)
                )
        hub._run_branches(*wave.take_branches())
        for t_i, t in enumerate(trees):
            for j in range(8):
                single = crypto.merkle.verify_branch(
                    t.root,
                    shards[t_i, j].tobytes()
                    if (t_i, j) != (1, 3)
                    else b"\xff" + shards[t_i, j].tobytes()[1:],
                    t.branch(j),
                    j,
                )
                assert results[(t_i, j)] == single
        assert results[(1, 3)] is False
        assert sum(results.values()) == 23

    def test_epoch_crypto_goes_through_hub_in_few_dispatches(self):
        """A full N=8 HBBFT epoch: every branch verify, decode and
        share verify rides the hub; total batched dispatches stay far
        below the per-message count (~N^2 branch + ~2N share singles)."""
        from tests.test_honeybadger import (
            assert_identical_batches,
            make_hb_network,
            push_txs,
        )

        cfg, net, nodes = make_hb_network(8, batch_size=16)
        push_txs(nodes, 16)
        for hb in nodes.values():
            hb.start_epoch()
        net.run()
        assert_identical_batches(nodes)
        for hb in nodes.values():
            st = hb.hub.stats()
            # the work actually went through the hub...
            # >= n-f echoes/instance: at least one instance's quorum
            assert st["branch_items"] >= 8 * (8 - 2)
            assert st["share_items"] >= 8  # coins + dec shares
            assert st["decode_items"] >= 1
            # ...in batched dispatches, not one per item
            assert st["dispatches"] < st["branch_items"] + st["share_items"]
            assert st["dispatches"] <= 120, st
            # every flush that executed work logged its column width,
            # and the widths account for every item the hub ran
            assert hb.hub.wave_widths
            assert sum(hb.hub.wave_widths) == (
                st["branch_items"] + st["decode_items"] + st["share_items"]
            )


class TestMemoFifo:
    def test_fifo_evicts_oldest_insertion_only(self):
        m = _Memo(4)
        for i in range(4):
            m.put(i, i)
        m.put(4, 4)  # at cap: evicts key 0, keeps everything newer
        assert 0 not in m.map
        assert list(m.map) == [1, 2, 3, 4]
        m.put(2, 22)  # existing key: value refresh, no eviction
        assert m.map[2] == 22 and len(m.map) == 4
        m.put(5, 5)  # next eviction is the NEXT-oldest (1), not all
        assert list(m.map) == [2, 3, 4, 5]


class TestHubWaveIdDedup:
    def test_receiver_copies_collapse_to_one_slot(self):
        """In dedup mode, N clients offering the same decoded-payload
        objects (root/leaf/branch shared via the transport's payload
        memo) produce ONE unique slot; distinct content stays
        distinct even at equal values (identity, not equality)."""
        root, leaf, br = b"r" * 32, b"leaf", (b"s" * 32,)
        wave = HubWave(dedup=True)
        for client in ("a", "b", "c"):
            wave.add_branch(client, root, leaf, br, 1, ctx=client)
        # equal VALUES under different identities must not collapse
        # (bytes(bytearray(..)) forces fresh objects — same-code-object
        # literals would be constant-folded to the very same constant)
        wave.add_branch(
            "d",
            bytes(bytearray(root)),
            bytes(bytearray(leaf)),
            (bytes(bytearray(br[0])),),
            1,
            "d",
        )
        assert len(wave.b_slots) == 2
        assert len(wave.b_items) == 4
        assert [it[2] for it in wave.b_items] == [0, 0, 0, 1]
        # non-dedup mode: every item is its own slot
        wave2 = HubWave(dedup=False)
        wave2.add_branch("a", root, leaf, br, 1, "a")
        wave2.add_branch("b", root, leaf, br, 1, "b")
        assert len(wave2.b_slots) == 2


class TestHubLiveness:
    def test_poisoned_share_burn_and_recovery(self):
        """A Byzantine dec-share burns through the batched path and the
        epoch still commits (pool recovers with honest shares)."""
        from tests.test_honeybadger import (
            assert_identical_batches,
            make_hb_network,
            push_txs,
        )
        from cleisthenes_tpu.transport.message import DecSharePayload

        cfg, net, nodes = make_hb_network(4, batch_size=8, seed=3)
        bad = "node2"
        orig_post = net.post

        def tamper(sender_id, receiver_id, msg):
            p = msg.payload
            if sender_id == bad and isinstance(p, DecSharePayload):
                from cleisthenes_tpu.transport.message import Message

                msg = Message(
                    msg.sender_id,
                    msg.timestamp,
                    DecSharePayload(
                        proposer=p.proposer,
                        epoch=p.epoch,
                        index=p.index,
                        d=p.d,
                        e=p.e,
                        z=(p.z + 1),
                    ),
                    msg.signature,
                )
            return orig_post(sender_id, receiver_id, msg)

        net.post = tamper
        push_txs(nodes, 8)
        for hb in nodes.values():
            hb.start_epoch()
        net.run()
        assert_identical_batches(nodes)
