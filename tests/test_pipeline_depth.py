"""K-deep pipelined epoch frontiers (ISSUE 15, Config.pipeline_depth).

Covers the acceptance matrix:

- equivalence: the depth-1 (lockstep) arm and depth-K windows commit
  byte-identical settled ledgers on the same seed — on the channel
  transport, over real gRPC, and across PYTHONHASHSEED values — while
  depth > 1 demonstrably runs the K-deep machinery (eager dec-share
  waves nonzero, fewer hub flushes for the same epochs);
- crash/WAL-restart with >= 2 ordered-but-unsettled epochs in the
  window: every torn epoch re-enters the settler as a settle-only
  state and settles with no loss, duplicate, or consensus re-run;
- backpressure: ordering still parks at ``decrypt_lag_max`` exactly
  as at depth 1, however wide the in-flight window;
- reconfig boundary under depth 4: a joiner ceremony completes across
  the widened window (``reconfig_lead > pipeline_depth +
  decrypt_lag_max`` keeps every in-flight epoch under one roster);
- Config.validate: depth >= 1, depth <= MAX_PIPELINE_DEPTH, and the
  widened reconfig_lead bound.
"""

from __future__ import annotations

import hashlib
import os
import pathlib
import struct
import subprocess
import sys
import threading
import time

import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from cleisthenes_tpu.config import (  # noqa: E402
    MAX_PIPELINE_DEPTH,
    Config,
)
from cleisthenes_tpu.core.ledger import (  # noqa: E402
    BatchLog,
    encode_batch_body,
)
from cleisthenes_tpu.protocol.cluster import SimulatedCluster  # noqa: E402
from cleisthenes_tpu.protocol.honeybadger import (  # noqa: E402
    EPOCH_HORIZON,
    HoneyBadger,
    setup_keys,
)
from cleisthenes_tpu.transport.broadcast import (  # noqa: E402
    ChannelBroadcaster,
)
from cleisthenes_tpu.transport.channel import ChannelNetwork  # noqa: E402


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------


def _depth_cfg(depth: int, **kw) -> Config:
    """A Config at the given window depth whose reconfig_lead always
    clears the widened validation bound."""
    lag = kw.pop("decrypt_lag_max", 4)
    return Config(
        n=4,
        batch_size=16,
        seed=5,
        pipeline_depth=depth,
        decrypt_lag_max=lag,
        reconfig_lead=max(8, depth + lag + 1),
        **kw,
    )


def _ledger_digest(cluster: SimulatedCluster) -> str:
    h = hashlib.sha256()
    for nid in cluster.ids:
        for epoch, batch in enumerate(
            cluster.nodes[nid].committed_batches
        ):
            h.update(encode_batch_body(epoch, batch))
    return h.hexdigest()


def _run_depth(depth: int, txs: int = 64) -> tuple:
    cluster = SimulatedCluster(
        config=_depth_cfg(depth), seed=5, key_seed=3
    )
    for i in range(txs):
        cluster.submit(b"kd-tx-%04d" % i)
    cluster.run_epochs()
    depth_committed = cluster.assert_agreement()
    return _ledger_digest(cluster), depth_committed, cluster


def _tear_last_clog(path: str) -> None:
    """Drop the newest CLOG record from a WAL, leaving its epoch's
    COrd in place (the crash-between-order-and-settle window; same
    framing walk as tests/test_order_settle.py)."""
    data = open(path, "rb").read()
    recs = []
    off = 0
    while off + 8 <= len(data):
        (ln,) = struct.unpack_from(">I", data, off + 4)
        end = off + 8 + ln + 4
        recs.append((data[off : off + 4], data[off:end]))
        off = end
    for i in range(len(recs) - 1, -1, -1):
        if recs[i][0] == b"CLOG":
            del recs[i]
            break
    else:
        raise AssertionError(f"no CLOG record in {path}")
    with open(path, "wb") as fh:
        fh.write(b"".join(rec for _, rec in recs))


# ---------------------------------------------------------------------------
# Config.validate (satellite)
# ---------------------------------------------------------------------------


def test_pipeline_depth_validation():
    with pytest.raises(ValueError):
        Config(n=4, pipeline_depth=0)
    with pytest.raises(ValueError):
        Config(n=4, pipeline_depth=MAX_PIPELINE_DEPTH + 1)
    # the widened reconfig_lead bound: lead must clear depth + lag
    with pytest.raises(ValueError):
        Config(
            n=4, pipeline_depth=4, decrypt_lag_max=4, reconfig_lead=8
        )
    Config(n=4, pipeline_depth=4, decrypt_lag_max=4, reconfig_lead=9)
    # the window cap is pinned to the demux horizon
    assert MAX_PIPELINE_DEPTH <= EPOCH_HORIZON


# ---------------------------------------------------------------------------
# equivalence: depth-1 (lockstep arm) vs depth-K settled ledgers
# ---------------------------------------------------------------------------


def test_depth1_vs_depth4_byte_identical_settled_ledgers_channel():
    """The pinned depth-1 arm (pipeline_depth=1 — pre-K lockstep,
    byte-identical to the historical behavior) and the K-deep windows
    settle byte-identical ledgers on the same seed, while depth > 1
    demonstrably ran the widened machinery."""
    dig1, depth1, c1 = _run_depth(1)
    dig2, depth2, c2 = _run_depth(2)
    dig4, depth4, c4 = _run_depth(4)
    assert depth1 >= 3 and depth1 == depth2 == depth4
    assert dig1 == dig2 == dig4, (
        "K-deep settled ledgers diverged from the lockstep arm"
    )
    # the lockstep arm never takes the eager path...
    eager1 = sum(
        hb.metrics.eager_share_waves.value for hb in c1.nodes.values()
    )
    assert eager1 == 0
    assert c1.nodes[c1.ids[0]].hub.stats()["dec_issue_batches"] == 0
    # ...and the K-deep arms did: eager dec shares piggybacked on
    # waves, through the hub's pooled dec-share column
    for c in (c2, c4):
        eager = sum(
            hb.metrics.eager_share_waves.value
            for hb in c.nodes.values()
        )
        assert eager > 0, "depth > 1 never piggybacked a dec share"
        assert c.nodes[c.ids[0]].hub.stats()["dec_issue_batches"] > 0
    # K concurrent epochs share waves: same committed epochs, fewer
    # hub flushes (the zero-noise dispatch-amortization evidence)
    flushes = {
        d: c.nodes[c.ids[0]].hub.stats()["flushes"]
        for d, c in ((1, c1), (2, c2), (4, c4))
    }
    assert flushes[4] < flushes[2] < flushes[1]


def test_pipeline_snapshot_block_reports_eager_waves():
    """snapshot()["pipeline"] carries the always-present gauge +
    counter (the PR-9 schema rule), nonzero after a depth-4 run."""
    _dig, _depth, cluster = _run_depth(4)
    snaps = [
        hb.metrics.snapshot()["pipeline"]
        for hb in cluster.nodes.values()
    ]
    for snap in snaps:
        assert set(snap) == {"epochs_in_flight", "eager_share_waves"}
        assert snap["epochs_in_flight"] == 0  # quiesced: nothing live
    assert sum(s["eager_share_waves"] for s in snaps) > 0


@pytest.mark.faults
def test_depth1_vs_depth4_identical_ledgers_grpc():
    """Same roster, same submissions, real sockets: the depth-1 and
    depth-4 arms settle byte-identical multi-epoch ledgers."""
    from cleisthenes_tpu.transport.host import ValidatorHost

    def run(depth: int) -> list:
        n = 4
        cfg = Config(
            n=n,
            batch_size=8,
            seed=77,
            pipeline_depth=depth,
            reconfig_lead=max(8, depth + 4 + 1),
        )
        ids = [f"node{i}" for i in range(n)]
        keys = setup_keys(cfg, ids, seed=55)
        hosts = {i: ValidatorHost(cfg, i, ids, keys[i]) for i in ids}
        try:
            addrs = {i: h.listen() for i, h in hosts.items()}
            threads = [
                threading.Thread(target=h.connect, args=(addrs,))
                for h in hosts.values()
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=15)
            # two epochs' worth of work (b//n = 2 per node per epoch)
            for i in range(16):
                hosts[ids[i % n]].submit(b"grpc-kd-%02d" % i)
            for h in hosts.values():
                h.propose()
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                if all(
                    len(h.committed_batches()) >= 2
                    for h in hosts.values()
                ):
                    break
                time.sleep(0.25)
            ledgers = {
                i: [
                    encode_batch_body(e, b)
                    for e, b in enumerate(h.committed_batches()[:2])
                ]
                for i, h in hosts.items()
            }
            assert all(len(l) == 2 for l in ledgers.values())
            first = ledgers[ids[0]]
            assert all(l == first for l in ledgers.values())
            return first
        finally:
            for h in hosts.values():
                h.stop()

    assert run(1) == run(4)


# Prints one line digesting BOTH arms' settled ledger bytes plus the
# deterministic K-deep counters.  Two PYTHONHASHSEED values must
# produce identical lines — hash-order iteration anywhere in the
# pipeline drive / eager dec-share column would show up as different
# counters or ledger bytes (staticcheck DET002's dynamic twin).
_DEPTH_DRIVER = r"""
import hashlib
from cleisthenes_tpu.config import Config
from cleisthenes_tpu.core.ledger import encode_batch_body
from cleisthenes_tpu.protocol.cluster import SimulatedCluster

def run(depth):
    cluster = SimulatedCluster(
        config=Config(
            n=4, batch_size=8, seed=909, pipeline_depth=depth,
            reconfig_lead=max(8, depth + 4 + 1),
        ),
        seed=909,
        key_seed=4,
    )
    for i in range(24):
        cluster.submit(b"kd-hs-%04d" % i)
    cluster.run_epochs()
    depth_committed = cluster.assert_agreement()
    assert depth_committed >= 2
    h = hashlib.sha256()
    for nid in cluster.ids:
        for e, b in enumerate(cluster.nodes[nid].committed_batches):
            h.update(encode_batch_body(e, b))
    eager = sum(
        hb.metrics.eager_share_waves.value
        for hb in cluster.nodes.values()
    )
    hub = cluster.nodes[cluster.ids[0]].hub.stats()
    return h.hexdigest(), eager, hub

d1, e1, hub1 = run(1)
d4, e4, hub4 = run(4)
assert d1 == d4, "depth-4 settled ledger diverged from depth-1"
assert e1 == 0 and e4 > 0
print(
    "DEPTH_DIGEST=%s eager=%d dec_batches=%d dec_items=%d "
    "flushes1=%d flushes4=%d"
    % (
        d4, e4, hub4["dec_issue_batches"], hub4["dec_issue_items"],
        hub1["flushes"], hub4["flushes"],
    )
)
"""


def _run_depth_driver(hashseed: str) -> str:
    env = dict(os.environ)
    env["PYTHONHASHSEED"] = hashseed
    env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.run(
        [sys.executable, "-c", _DEPTH_DRIVER],
        cwd=REPO,
        env=env,
        capture_output=True,
        text=True,
        timeout=300,
    )
    assert proc.returncode == 0, (
        f"PYTHONHASHSEED={hashseed} depth run failed:\n"
        f"{proc.stdout}\n{proc.stderr}"
    )
    for line in proc.stdout.splitlines():
        if line.startswith("DEPTH_DIGEST="):
            return line
    raise AssertionError(f"no depth digest line:\n{proc.stdout}")


def test_depth_equivalence_across_hash_seeds():
    a = _run_depth_driver("1")
    b = _run_depth_driver("2")
    assert a == b, (
        "K-deep pipelining diverged across PYTHONHASHSEED values:\n"
        f"  {a}\n  {b}\n-> hash-order iteration is leaking into the "
        "pipeline drive or the hub's dec-share column"
    )


# ---------------------------------------------------------------------------
# crash/WAL-restart with >= 2 ordered-but-unsettled epochs (satellite)
# ---------------------------------------------------------------------------


def _build_wal_cluster(cfg, ids, keys, logdir, net):
    nodes = {}
    for nid in ids:
        nodes[nid] = HoneyBadger(
            config=cfg,
            node_id=nid,
            member_ids=ids,
            keys=keys[nid],
            out=ChannelBroadcaster(net, nid, ids),
            batch_log=BatchLog(os.path.join(logdir, nid + ".log")),
        )
        net.join(nid, nodes[nid], None)
    return nodes


def test_wal_restart_with_two_ordered_unsettled_epochs(tmp_path):
    """Every WAL torn between COrd and CLOG for the LAST TWO epochs:
    the restarted roster re-enters BOTH epochs of the window into its
    settlers (the multi-epoch re-entry the K-deep window requires),
    re-issues its own dec shares at the first idle boundary, and
    settles the same batches — no loss, no duplicate, no re-run."""
    logdir = str(tmp_path / "wals")
    os.makedirs(logdir)
    cfg = Config(
        n=4, batch_size=8, seed=11, pipeline_depth=4, reconfig_lead=9
    )
    ids = [f"node{i}" for i in range(4)]
    keys = setup_keys(cfg, ids, seed=66)

    net = ChannelNetwork(seed=11)
    nodes = _build_wal_cluster(cfg, ids, keys, logdir, net)
    for i in range(24):
        nodes[ids[i % 4]].add_transaction(b"kd-tear-%03d" % i)
    for _ in range(8):
        for hb in nodes.values():
            hb.start_epoch()
        net.run()
        if all(hb.pending_tx_count() == 0 for hb in nodes.values()):
            break
    committed = [b.tx_list() for b in nodes[ids[0]].committed_batches]
    assert len(committed) >= 3
    for hb in nodes.values():
        hb.batch_log.close()
    for nid in ids:
        path = os.path.join(logdir, nid + ".log")
        _tear_last_clog(path)
        _tear_last_clog(path)

    net2 = ChannelNetwork(seed=12)
    nodes2 = _build_wal_cluster(cfg, ids, keys, logdir, net2)
    for hb in nodes2.values():
        # BOTH torn epochs re-entered as settle-only states: the
        # ordered frontier is past them, settlement two behind
        assert hb.epoch == len(committed)
        assert hb.settled_epoch == len(committed) - 2
        for e in (len(committed) - 2, len(committed) - 1):
            es = hb._epochs[e]
            assert es.ordered and es.acs is None
            assert not es.shares_issued
    net2.run()  # idle phase drives the settlers: shares re-issue
    for hb in nodes2.values():
        assert hb.settled_epoch == len(committed)
        got = [b.tx_list() for b in hb.committed_batches]
        assert got == committed  # same batches, once, in order
        hb.batch_log.close()


# ---------------------------------------------------------------------------
# backpressure parks at the bound under a wide window (satellite)
# ---------------------------------------------------------------------------


def test_backpressure_parks_at_bound_under_depth4():
    """decrypt_lag_max=2 under a depth-4 window: however many epochs
    run RBC/BBA concurrently, the ORDERED frontier never runs more
    than 2 epochs past settlement at any quiescence point, and the
    run still drains completely."""
    cfg = _depth_cfg(4, decrypt_lag_max=2)
    cluster = SimulatedCluster(config=cfg, seed=9, key_seed=3)
    for i in range(96):
        cluster.submit(b"kd-bp-%04d" % i)

    def check_bound(_r: int) -> None:
        for hb in cluster.nodes.values():
            lag = hb.epoch - hb.settled_epoch
            assert 0 <= lag <= 2, (
                hb.node_id, hb.epoch, hb.settled_epoch
            )

    cluster.run_epochs(on_quiescence=check_bound)
    depth = cluster.assert_agreement()
    assert depth >= 4
    n0 = cluster.nodes[cluster.ids[0]]
    assert n0.epoch == n0.settled_epoch  # fully settled at the end


# ---------------------------------------------------------------------------
# reconfig boundary under depth 4 (satellite)
# ---------------------------------------------------------------------------


@pytest.mark.faults
def test_reconfig_boundary_under_depth4():
    """A joiner ceremony under a depth-4 window: the validated
    ``reconfig_lead > pipeline_depth + decrypt_lag_max`` bound keeps
    every in-flight epoch on the correct side of the activation
    boundary — the switch converges, ledgers stay byte-identical, and
    the joiner participates under the new roster."""
    cfg = Config(
        n=4,
        batch_size=8,
        seed=7,
        pipeline_depth=4,
        decrypt_lag_max=2,
        reconfig_lead=8,
    )
    c = SimulatedCluster(config=cfg, seed=7, key_seed=33)
    for i in range(12):
        c.submit(b"kd-pre-%03d" % i)
    c.run_until_drained(max_rounds=30)
    v = c.begin_reconfig(join=["node100"])
    assert v == 1
    c.run_until_drained(max_rounds=80)
    assert set(c.roster_versions().values()) == {1}
    for i in range(20):
        c.submit(b"kd-post-%03d" % i)
    c.run_until_drained(max_rounds=60)
    nids = list(c.nodes)
    depth = min(
        len(c.nodes[nid].committed_batches) for nid in nids
    )
    assert depth > 0
    for e in range(depth):
        bodies = {
            encode_batch_body(e, c.nodes[nid].committed_batches[e])
            for nid in nids
        }
        assert len(bodies) == 1, f"fork at epoch {e}"
    jn = c.nodes["node100"]
    assert jn.roster_version == 1
    assert any(
        "node100" in b.contributions and b.contributions["node100"]
        for b in jn.committed_batches
    ), "joiner never contributed a committed proposal"
