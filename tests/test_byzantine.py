"""Byzantine-coalition HBBFT tests: f fully-adversarial nodes whose
traffic is dropped, tampered, duplicated and replayed must never break
agreement or (with reliable honest channels) liveness.

These are the adversarial-scheduler + fault-injection tests SURVEY.md
§4/§5.3 calls for, at network scale.

The whole module carries the ``faults`` marker: ci.sh's fault-
regression stage replays it (plus the marked gRPC/transport fault
tests) over a fixed seed matrix, with ``FAULT_SEED`` selecting the
scheduler/coalition seed for the seed-parametrized scenarios."""

import os

import pytest

from cleisthenes_tpu.protocol.cluster import run_until_drained
from cleisthenes_tpu.utils.adversary import Coalition
from tests.test_honeybadger import (
    assert_identical_batches,
    make_hb_network,
    push_txs,
)

pytestmark = pytest.mark.faults

# the ci.sh fault gate exports one seed per stage run; a plain pytest
# run uses the default
FAULT_SEEDS = tuple(
    int(s)
    for s in os.environ.get("FAULT_SEED", "11").replace(",", " ").split()
)


def run_epochs(net, nodes, skip=(), max_rounds=40):
    """The shared propose-and-drain loop (protocol.cluster
    run_until_drained) under this module's historical name."""
    run_until_drained(net, nodes, skip=skip, max_rounds=max_rounds)


@pytest.mark.parametrize("seed", [1, 7])
def test_byzantine_node_dropping_own_traffic(seed):
    """A faulty node that loses half its messages is just a slow/faulty
    node: the other n-f must still commit identically."""
    cfg, net, nodes = make_hb_network(4, batch_size=8, seed=seed)
    bad = "node3"
    net.fault_filter = Coalition([bad], seed=seed).drop(0.5).filter
    push_txs(nodes, 12)
    run_epochs(net, nodes)
    assert_identical_batches(nodes)


@pytest.mark.parametrize("seed", [2, 9])
def test_byzantine_tampering_caught_by_macs(seed):
    """Tampered frames from the coalition fail MAC verification and
    count as rejected, never as protocol votes."""
    cfg, net, nodes = make_hb_network(4, batch_size=8, seed=seed, auth=True)
    bad = "node1"
    net.fault_filter = Coalition([bad], seed=seed).tamper(0.7).filter
    push_txs(nodes, 12)
    run_epochs(net, nodes)
    assert_identical_batches(nodes)
    rejected = sum(
        net.endpoint_stats(nid)["rejected"] for nid in net.node_ids()
    )
    assert rejected > 0  # the tampering actually happened and was caught


@pytest.mark.parametrize("seed", [3, 11])
def test_byzantine_duplication_and_replay(seed):
    """Duplicated and replayed (valid-MAC) frames must be absorbed by
    per-sender dedup: same committed batches, no double counting."""
    cfg, net, nodes = make_hb_network(4, batch_size=8, seed=seed, auth=True)
    bad = "node2"
    net.fault_filter = (
        Coalition([bad], seed=seed).duplicate(0.5, copies=3).replay(0.5).filter
    )
    push_txs(nodes, 12)
    run_epochs(net, nodes)
    depth = assert_identical_batches(nodes)
    all_txs = [
        tx
        for b in nodes["node0"].committed_batches[:depth]
        for tx in b.tx_list()
    ]
    assert len(all_txs) == len(set(all_txs))  # replay never double-commits


def test_byzantine_full_coalition_n7():
    """n=7, f=2: two colluding nodes drop+tamper+duplicate while the
    scheduler is adversarial; five honest nodes commit identically."""
    cfg, net, nodes = make_hb_network(7, batch_size=8, seed=5, auth=True)
    coalition = ["node5", "node6"]
    net.fault_filter = (
        Coalition(coalition, seed=5)
        .drop(0.3)
        .tamper(0.3)
        .duplicate(0.3)
        .replay(0.3)
        .filter
    )
    push_txs(nodes, 14)
    run_epochs(net, nodes)
    assert_identical_batches(nodes)


def test_byzantine_silent_coalition_liveness():
    """f completely silent nodes (drop everything): the protocol's
    worst-case crash pattern at full fault budget."""
    cfg, net, nodes = make_hb_network(7, batch_size=8, seed=13)
    coalition = ["node0", "node1"]  # includes the lowest-id proposer
    net.fault_filter = Coalition(coalition, seed=13).drop(1.0).filter
    push_txs(nodes, 14, prefix=b"live")
    run_epochs(net, nodes, skip=coalition)
    depth = assert_identical_batches(nodes, skip=coalition)
    assert depth >= 1


def test_byzantine_poisoned_ciphertext_excluded():
    """ADVICE.md round-1 high finding: a proposer whose RBC'd
    "ciphertext" carries c1 = P-1 (the order-2 element, outside the
    prime-order subgroup) used to make every honest node's decryption
    share fail verification forever, burning all honest senders and
    halting consensus.  With subgroup validation at deserialization the
    proposer is deterministically excluded and the epoch commits."""
    import struct

    from cleisthenes_tpu.ops.modmath import P

    cfg, net, nodes = make_hb_network(4, batch_size=8, seed=17)
    bad = "node3"
    c2 = b"\x00" * 16
    poisoned = (
        (P - 1).to_bytes(32, "big")
        + struct.pack(">I", len(c2))
        + c2
        + b"\x11" * 32
    )
    hb_bad = nodes[bad]

    def poisoned_start():
        es = hb_bad._epoch_state(hb_bad.epoch)
        if es is None or es.proposed:
            return
        es.proposed = True
        es.my_txs = []
        es.acs.input(poisoned)

    hb_bad.start_epoch = poisoned_start
    # txs go to honest nodes only: anything queued at the poisoned
    # proposer can never commit, and its non-empty queue would keep
    # auto-proposing fresh (excluded) epochs forever — a livelock of
    # the TEST setup, not the protocol
    push_txs({k: v for k, v in nodes.items() if k != bad}, 12)
    run_epochs(net, nodes, skip=(bad,))
    depth = assert_identical_batches(nodes)
    assert depth >= 1
    # the poisoned proposal contributed no transactions anywhere
    for hb in nodes.values():
        for b in hb.committed_batches:
            assert all(tx.startswith(b"tx-") for tx in b.tx_list())


def test_byzantine_invalid_dec_share_falls_back_to_verified_path():
    """A Byzantine member broadcasting junk decryption shares must not
    poison the optimistic (unverified-subset) TPKE combine: the bad tag
    flips the proposer onto the CP-verified path, the junk share burns,
    and every honest node still commits identically."""
    from cleisthenes_tpu.ops.tpke import DhShare

    cfg, net, nodes = make_hb_network(4, batch_size=8)  # FIFO scheduler
    bad = "node0"  # sorts first: its junk share lands in the subset
    hb_bad = nodes[bad]
    real_batch = hb_bad.tpke.dec_share_batch

    def junk_dec_share_batch(share, cts):
        return [
            DhShare(index=good.index, d=12345, e=good.e, z=good.z)
            for good in real_batch(share, cts)
        ]

    hb_bad.tpke.dec_share_batch = junk_dec_share_batch
    # the K-deep eager path (Config.pipeline_depth > 1) issues
    # through the hub's dec-share column instead of tpke — tamper
    # that seam identically so the junk rides either issue path
    real_take = hb_bad.hub.take_dec_issues

    def junk_take(owner):
        rows = real_take(owner)
        if owner is hb_bad:
            rows = [
                (meta, DhShare(index=s.index, d=12345, e=s.e, z=s.z))
                for meta, s in rows
            ]
        return rows

    hb_bad.hub.take_dec_issues = junk_take
    push_txs(nodes, 12)
    run_epochs(net, nodes)
    assert_identical_batches(nodes)
    # the fallback actually exercised: some honest node hit a bad tag
    fallbacks = sum(
        len(es.opt_failed)
        for nid, hb in nodes.items()
        if nid != bad
        for es in hb._epochs.values()
    )
    burned = sum(
        bad in pool._burned
        for nid, hb in nodes.items()
        if nid != bad
        for es in hb._epochs.values()
        for pool in es.dec_shares.values()
    )
    assert fallbacks + burned > 0  # junk was seen and survived


def test_byzantine_invalid_coin_share_does_not_stall_reveal():
    """Regression (round-3 review): a Byzantine member broadcasting
    invalid coin shares burns its collected slot, and the REPLACEMENT
    shares already parked in the pool must still be collected — under
    dirty-set flushing nothing else re-offers them (every share may
    already have arrived), so the verdict callback re-marks the BBA.
    Pre-fix, every node's round-0 coin stayed unrevealed forever and
    zero transactions committed."""
    from cleisthenes_tpu.ops import tpke as tpke_mod

    cfg, net, nodes = make_hb_network(4, batch_size=8)  # FIFO scheduler
    bad = "node0"  # sorts first: collected into the f+1 verify subset
    hb_bad = nodes[bad]
    real_issue = tpke_mod.issue_share
    bad_secret_value = hb_bad.keys.coin_share.value

    def junk_issue(share, base, context, group=tpke_mod.DEFAULT_GROUP):
        good = real_issue(share, base, context, group)
        if (
            context.startswith(b"coin|")
            and share.value == bad_secret_value
        ):
            return tpke_mod.DhShare(
                index=good.index, d=12345, e=good.e, z=good.z
            )
        return good

    tpke_mod.issue_share = junk_issue
    try:
        # route the patched module function through the bad node's coin
        hb_bad.coin.share = (
            lambda secret, coin_id: junk_issue(
                secret,
                __import__(
                    "cleisthenes_tpu.ops.coin", fromlist=["coin_base"]
                ).coin_base(coin_id, hb_bad.coin.group),
                b"coin|" + coin_id,
                hb_bad.coin.group,
            )
        )
        push_txs(nodes, 12)
        run_epochs(net, nodes)
    finally:
        tpke_mod.issue_share = real_issue
    assert_identical_batches(nodes)
    committed = sum(
        len(b) for b in nodes["node1"].committed_batches
    )
    assert committed == 12  # liveness: everything still commits


@pytest.mark.skipif(
    __import__("os").environ.get("RUN_SLOW") != "1",
    reason="~5 min seeded adversarial sweep (RUN_SLOW=1 to enable)",
)
def test_byzantine_seeded_sweep():
    """Randomized coalition compositions across many scheduler seeds:
    every combination of drop/tamper/duplicate/replay from a random
    f-sized coalition, under a random adversarial delivery order, must
    preserve agreement among the honest majority — the protocol
    fuzzing pass (the reference has nothing comparable; its tests are
    4 fixed unit scenarios)."""
    import random as _random

    for seed in range(24):
        rng = _random.Random(seed)
        n = rng.choice([4, 5, 7])
        f = (n - 1) // 3
        cfg, net, nodes = make_hb_network(n, batch_size=8, seed=seed)
        bad = rng.sample(sorted(nodes), f)
        coal = Coalition(bad, seed=seed)
        for stage, arg in (
            ("drop", rng.uniform(0.1, 0.6)),
            ("tamper", rng.uniform(0.0, 0.7)),
            ("duplicate", rng.uniform(0.0, 0.5)),
            ("replay", rng.uniform(0.0, 0.5)),
        ):
            if rng.random() < 0.7:
                getattr(coal, stage)(arg)
        net.fault_filter = coal.filter
        push_txs(nodes, 3 * n)
        run_epochs(net, nodes)
        honest = {k: v for k, v in nodes.items() if k not in bad}
        hist = {
            tuple(
                tuple(sorted(b.tx_list())) for b in hb.committed_batches
            )
            for hb in honest.values()
        }
        # strict whole-history equality is SOUND here because these
        # small rosters drain (run_epochs exits on the drained
        # condition, not the round cap) — for rosters that may stop at
        # the cap, the correct assertion is prefix consistency; see
        # test_byzantine_big_roster_prefix_consistency below and the
        # round-4 seed-1005 classification (tools/sweep_roster.py)
        assert len(hist) == 1, f"agreement broke at seed {seed} (bad={bad})"
        committed = sum(
            len(b)
            for b in next(iter(honest.values())).committed_batches
        )
        assert committed > 0, f"no progress at seed {seed} (bad={bad})"


@pytest.mark.parametrize("seed", [21, 31])
def test_byzantine_delayed_frames_released_much_later(seed):
    """A coalition that HOLDS its frames and releases them many filter
    calls later (Coalition.delay) must not break agreement: a delayed
    frame is just an adversarial asynchronous schedule, and per-sender
    dedup absorbs stale arrivals."""
    cfg, net, nodes = make_hb_network(4, batch_size=8, seed=seed, auth=True)
    bad = "node2"
    coal = Coalition([bad], seed=seed).delay(0.3, hold=40)
    net.fault_filter = coal.filter
    push_txs(nodes, 12)
    run_epochs(net, nodes)
    assert_identical_batches(nodes)
    assert coal.held_total > 0  # the stage actually held frames
    assert coal.released_total > 0  # ...and released some much later


@pytest.mark.parametrize("seed", FAULT_SEEDS)
def test_crash_restart_wal_catchup_under_byzantine_coalition(
    tmp_path, seed
):
    """The crash-recovery acceptance scenario: a network with one
    Byzantine member (drop 0.3 + replay 0.2) commits epochs; an HONEST
    node then fail-stops, the survivors keep committing, and a fresh
    process restarted from the victim's WAL rejoins via CATCHUP —
    converging to byte-identical committed batches for every common
    epoch, including the epochs it was down for.

    Roster arithmetic: the down phase carries TWO simultaneous faults
    (the drop-lossy Byzantine member + the crashed honest node), so it
    needs f >= 2 — at n=4/f=1 the survivors' quorum is exactly the
    three live nodes including the lossy one, and a dropped frame
    wedges the wave forever (frame drops have no retransmission; a
    quiescent epoch is absorbing).  n=7/f=2 keeps the scenario inside
    the fault budget, which is what HBBFT actually promises."""
    from cleisthenes_tpu.config import Config
    from cleisthenes_tpu.core.ledger import BatchLog, encode_batch_body
    from cleisthenes_tpu.protocol.honeybadger import HoneyBadger, setup_keys
    from cleisthenes_tpu.transport.base import HmacAuthenticator
    from cleisthenes_tpu.transport.broadcast import ChannelBroadcaster
    from cleisthenes_tpu.transport.channel import ChannelNetwork

    cfg = Config(n=7, batch_size=8)
    ids = [f"node{i}" for i in range(7)]
    keys = setup_keys(cfg, ids, seed=33)
    net = ChannelNetwork(seed=seed)
    bad = "node6"
    net.fault_filter = (
        Coalition([bad], seed=seed).drop(0.3).replay(0.2).filter
    )
    victim = "node1"

    def build(node_id, log):
        return HoneyBadger(
            config=cfg,
            node_id=node_id,
            member_ids=ids,
            keys=keys[node_id],
            out=ChannelBroadcaster(net, node_id, ids),
            batch_log=log,
        )

    nodes = {}
    for nid in ids:
        log = (
            BatchLog(str(tmp_path / f"{nid}.log")) if nid == victim else None
        )
        nodes[nid] = build(nid, log)
        net.join(nid, nodes[nid], HmacAuthenticator(nid, keys[nid].mac_keys))

    push_txs(nodes, 14, prefix=b"pre")
    run_epochs(net, nodes)
    k = assert_identical_batches(nodes)
    assert k >= 1  # the victim crashes AFTER epoch k-1 committed

    # fail-stop: in-flight frames die with the process; the WAL survives
    net.crash(victim)
    nodes[victim].batch_log.close()
    survivors = {n: h for n, h in nodes.items() if n != victim}
    push_txs(survivors, 14, prefix=b"down")
    run_epochs(net, survivors)
    down_depth = assert_identical_batches(survivors)
    assert down_depth > k  # epochs committed WHILE the victim was down

    # restart: fresh process, same identity/keys, state from the WAL
    fresh = build(victim, BatchLog(str(tmp_path / f"{victim}.log")))
    assert fresh.epoch >= k  # resumed from the log, not from epoch 0
    net.restart(
        victim, fresh, HmacAuthenticator(victim, keys[victim].mac_keys)
    )
    nodes[victim] = fresh
    fresh.request_catchup()
    net.run()
    # rejoin the live protocol for one more joint wave
    push_txs(nodes, 8, prefix=b"post")
    run_epochs(net, nodes)
    depth = assert_identical_batches(nodes)
    assert depth >= down_depth  # caught up through its whole outage
    # byte-identical committed batches (ledger-body bytes) everywhere,
    # down epochs included
    for e in range(depth):
        want = encode_batch_body(e, nodes["node0"].committed_batches[e])
        for nid in ids:
            got = encode_batch_body(e, nodes[nid].committed_batches[e])
            assert got == want, f"epoch {e}: {nid} bytes differ"
    fresh.batch_log.close()


def test_byzantine_duplicate_index_dec_share_does_not_stall():
    """Regression (round-4 review): the batched dec-share handler
    probes decryption only on the pool-size threshold CROSSING.  A
    Byzantine member replaying an HONEST node's share index makes the
    pool hit the size threshold with too few distinct Shamir indices;
    the epoch must still decrypt when real shares arrive later —
    pre-fix the crossing was consumed and no later add re-probed,
    stalling commit forever."""
    from cleisthenes_tpu.ops.tpke import DhShare

    cfg, net, nodes = make_hb_network(4, batch_size=8)  # FIFO scheduler
    bad = "node0"  # sorts first: its share lands early in every pool
    hb_bad = nodes[bad]
    real_batch = hb_bad.tpke.dec_share_batch

    def replayed_index_batch(share, cts):
        # claim another sender's index: a valid-looking duplicate that
        # contributes no distinct interpolation point
        return [
            DhShare(index=2, d=good.d, e=good.e, z=good.z)
            for good in real_batch(share, cts)
        ]

    hb_bad.tpke.dec_share_batch = replayed_index_batch
    push_txs(nodes, 12)
    run_epochs(net, nodes)
    assert_identical_batches(nodes)
    committed = sum(len(b) for b in nodes["node1"].committed_batches)
    assert committed >= 12  # liveness held despite the index replay


def test_byzantine_big_roster_prefix_consistency():
    """Big rosters under coalition faults, with a BOUNDED step budget
    and the CORRECT safety assertion: per-epoch PREFIX consistency
    among honest nodes (HBBFT agreement), not whole-history equality.
    The strict-equality sweep above is valid only because its small
    rosters provably drain; at n in {10, 13} a bounded run stops
    mid-convergence and honest laggards legitimately hold a prefix
    (the round-4 seed-1005 classification: tools/sweep_roster.py).
    """
    # sweep_common, NOT sweep_roster: the latter registers the
    # importing process as benchlock-pausable at import time (a bench
    # capture would SIGSTOP the whole pytest run)
    from tools.sweep_common import build_seed_scenario, check_prefix

    for seed in (1001, 1013):
        cfg, net, nodes, bad, honest = build_seed_scenario(seed)
        for rnd in range(2):
            for hb in nodes.values():
                hb.start_epoch()
            net.run(max_steps=150_000)
            assert check_prefix(nodes, honest), (
                f"prefix diverged at seed {seed} round {rnd}"
            )
        committed = sum(
            len(b) for b in nodes[honest[0]].committed_batches
        )
        assert committed > 0, f"no progress at seed {seed}"


@pytest.mark.parametrize("seed", [19, 29])
def test_byzantine_reordered_frames_preserve_agreement(seed):
    """Coalition.reorder: a coalition whose frames arrive permuted
    within a sliding window is just another adversarial asynchronous
    schedule — agreement and liveness must hold, and the stage must
    actually have reordered something."""
    cfg, net, nodes = make_hb_network(4, batch_size=8, seed=seed, auth=True)
    bad = "node1"
    coal = Coalition([bad], seed=seed).reorder(0.4, window=4)
    net.fault_filter = coal.filter
    push_txs(nodes, 12)
    run_epochs(net, nodes)
    assert_identical_batches(nodes)
    assert coal.held_total > 0  # frames were actually held...
    assert coal.released_total > 0  # ...and released out of order


def test_replay_capture_is_a_reservoir_over_the_whole_run():
    """Regression (capture bias): _captured used to keep only the
    FIRST 4096 frames, so replay could never resend late-run traffic.
    The seeded reservoir must hold a healthy share of late frames
    after seeing 3x its capacity."""
    coal = Coalition(["evil"], seed=7).replay(0.5)
    cap = coal._capture_cap
    total = 3 * cap
    for i in range(total):
        # non-member sender: stages don't run, capture still does
        coal.filter("honest", "peer", b"frame-%08d" % i)
    assert len(coal._captured) == cap
    late = sum(
        1
        for f in coal._captured
        if int(f.split(b"-")[1]) >= total - cap
    )
    # uniform reservoir => ~1/3 of residents come from the last third;
    # the old first-N capture held exactly zero of them
    assert late > cap // 10


def test_coalition_without_replay_captures_nothing():
    """Capture memory is paid only when a replay stage exists."""
    coal = Coalition(["evil"], seed=7).drop(0.5)
    coal.filter("honest", "peer", b"frame")
    assert coal._captured == []


def test_metrics_transport_block_surfaces_rejections_and_dedup():
    """Metrics.snapshot()["transport"]: MAC rejections (tamper) and
    dedup absorption (duplicate+replay) are reachable through the
    public metrics surface — no reaching into net._endpoints."""
    from cleisthenes_tpu.protocol.cluster import SimulatedCluster

    c = SimulatedCluster(n=4, batch_size=8, seed=5)
    bad = c.ids[3]
    c.fault_filter = (
        Coalition([bad], seed=5)
        .tamper(0.4)
        .duplicate(0.5, copies=3)
        .replay(0.4)
        .filter
    )
    for i in range(12):
        c.submit(b"tx-%04d" % i)
    c.run_until_drained()
    c.assert_agreement()
    snap = c.nodes[c.ids[0]].metrics.snapshot()["transport"]
    assert snap["delivered"] > 0
    rejected = sum(
        c.nodes[nid].metrics.snapshot()["transport"]["rejected"]
        for nid in c.ids
    )
    absorbed = sum(
        c.nodes[nid].metrics.snapshot()["transport"]["dedup_absorbed"]
        for nid in c.ids
    )
    assert rejected > 0  # tampered frames failed their MACs
    assert absorbed > 0  # duplicated/replayed votes were absorbed


def test_rejected_frames_emit_trace_instants():
    """Every MAC-rejected frame lands in the flight recorder as a
    transport/rejected instant, so adversarial runs are visible in
    tracetool reports."""
    from cleisthenes_tpu.config import Config
    from cleisthenes_tpu.protocol.cluster import SimulatedCluster

    c = SimulatedCluster(
        n=4, config=Config(n=4, batch_size=8, trace=True), seed=5
    )
    bad = c.ids[2]
    c.fault_filter = Coalition([bad], seed=5).tamper(0.6).filter
    for i in range(8):
        c.submit(b"tx-%04d" % i)
    c.run_until_drained()
    c.assert_agreement()
    rejected_events = [
        ev
        for events in c.trace_events().values()
        for ev in events
        if ev[3] == "transport" and ev[4] == "rejected"
    ]
    assert rejected_events, "no transport/rejected instants recorded"
    total_rejected = sum(
        c.net.endpoint_stats(nid)["rejected"] for nid in c.ids
    )
    assert len(rejected_events) == total_rejected


def test_byzantine_garbage_echo_batch_burns_and_commits():
    """A Byzantine MEMBER injects structurally-valid EchoBatchPayloads
    with garbage branches/shards (its own MAC, so the frames decode):
    honest nodes must park them, burn the slots on batched branch
    verification, and still commit identically from the real echoes —
    the adversarial case for the round-5 columnar ECHO path."""
    import time as _time

    from cleisthenes_tpu.transport.message import (
        EchoBatchPayload,
        Message,
    )

    cfg, net, nodes = make_hb_network(4, batch_size=8)
    ids = sorted(nodes)
    bad = "node3"
    push_txs(nodes, 12)
    for hb in nodes.values():
        hb.start_epoch()
    # first wave delivers VALs; inject the garbage batches directly
    # into every honest node's handler (sender is a roster member, so
    # the membership gate passes — exactly what a MAC'd frame yields)
    garbage = EchoBatchPayload(
        epoch=0,
        shard_index=3,
        proposers=tuple(ids),
        roots=tuple(b"\x5a" * 32 for _ in ids),
        branches=tuple((b"\x5b" * 32, b"\x5c" * 32) for _ in ids),
        shards=tuple(b"\x5d" * 16 for _ in ids),
    )
    for nid in ids:
        if nid != bad:
            nodes[nid].serve_request(
                Message(sender_id=bad, timestamp=_time.time(),
                        payload=garbage, signature=b"")
            )
    run_epochs(net, nodes)
    honest = {k: v for k, v in nodes.items() if k != bad}
    hist = {
        tuple(tuple(sorted(b.tx_list())) for b in hb.committed_batches)
        for hb in honest.values()
    }
    assert len(hist) == 1
    committed = sum(
        len(b) for b in next(iter(honest.values())).committed_batches
    )
    assert committed > 0
