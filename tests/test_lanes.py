"""Horizontal shard-out (ISSUE 20, Config.lanes): S parallel consensus
lanes over one roster with a deterministic cross-lane total-order merge.

Covers the acceptance matrix:

- merge rule unit coverage: ``lane_of`` purity/range, MergeCursor's
  epoch-major lane-minor slot order, ``seq = epoch * S + lane``, the
  out-of-range lane guard, and the wholesale ``merge_order`` oracle
  agreeing with the incremental cursor;
- the byte-equivalence baseline arm: ``lanes=1`` commits a ledger
  byte-identical to a default (pre-lane) Config on the same seed;
- the shard-out arm: ``lanes=4`` honest nodes hold byte-identical
  merged total orders, deterministic across independent runs, with
  every submitted tx settling exactly once in its partitioned lane;
- crash/WAL-restart at lanes=4: the lane-tagged record streams replay
  every lane's frontier and the restarted node keeps committing;
- LanePayload wire framing: codec round-trip under kind 21, nesting
  rejection both ways (no lane-in-lane, no bundle-in-lane), wire-range
  guard;
- mempool lane partitioning: admission routes by ``lane_of``,
  ``drain_into(lane=k)`` drains only that lane's heap, ``lane_fill``
  witnesses the partition;
- Config.validate bounds: 1 <= lanes <= MAX_LANES.
"""

from __future__ import annotations

import hashlib
import pathlib
import sys

import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from cleisthenes_tpu.config import MAX_LANES, Config  # noqa: E402
from cleisthenes_tpu.core.ledger import encode_batch_body  # noqa: E402
from cleisthenes_tpu.core.mempool import (  # noqa: E402
    OK,
    Mempool,
    tx_digest,
)
from cleisthenes_tpu.core.merge import (  # noqa: E402
    MergeCursor,
    lane_of,
    merge_order,
)
from cleisthenes_tpu.protocol.cluster import SimulatedCluster  # noqa: E402
from cleisthenes_tpu.transport.message import (  # noqa: E402
    BbaPayload,
    BbaType,
    BundlePayload,
    LanePayload,
    Message,
    RbcPayload,
    RbcType,
    decode_message,
    encode_message,
)


# ---------------------------------------------------------------------------
# merge rule units
# ---------------------------------------------------------------------------


def test_lane_of_purity_and_range():
    digests = [hashlib.sha256(b"t%d" % i).digest() for i in range(256)]
    for lanes in (2, 4, 8):
        got = [lane_of(7, d, lanes) for d in digests]
        # pure: identical on recomputation
        assert got == [lane_of(7, d, lanes) for d in digests]
        # range: every lane index valid, every lane actually hit at
        # this sample size (256 digests over <= 8 lanes)
        assert set(got) <= set(range(lanes))
        assert set(got) == set(range(lanes))
    # the seed re-keys the partition (operators can rebalance)
    four = [lane_of(7, d, 4) for d in digests]
    assert four != [lane_of(8, d, 4) for d in digests]
    # unseeded == seed 0 (the documented fallback), still deterministic
    assert [lane_of(None, d, 4) for d in digests] == [
        lane_of(0, d, 4) for d in digests
    ]
    # lanes <= 1 short-circuits to lane 0
    assert all(lane_of(7, d, 1) == 0 for d in digests[:8])


def test_merge_cursor_epoch_major_lane_minor():
    S = 3
    cur = MergeCursor(S)
    # settle out of wall-clock order: lane 2 races ahead, lane 0 lags
    cur.push(2, 0, "L2E0")
    cur.push(1, 0, "L1E0")
    cur.push(2, 1, "L2E1")
    assert cur.drain() == []  # slot (0,0) missing: nothing emittable
    assert cur.frontier == 0
    cur.push(0, 0, "L0E0")
    rows = cur.drain()
    # emits through the first hole only: epoch 0 complete, epoch 1
    # blocked on lane 0
    assert rows == [
        (0, 0, 0, "L0E0"),
        (1, 1, 0, "L1E0"),
        (2, 2, 0, "L2E0"),
    ]
    assert all(seq == epoch * S + lane for seq, lane, epoch, _ in rows)
    assert cur.frontier == 3
    cur.push(0, 1, "L0E1")
    cur.push(1, 1, "L1E1")
    assert [r[3] for r in cur.drain()] == ["L0E1", "L1E1", "L2E1"]
    assert cur.merged == [
        "L0E0", "L1E0", "L2E0", "L0E1", "L1E1", "L2E1",
    ]


def test_merge_cursor_rejects_out_of_range_lane():
    cur = MergeCursor(2)
    with pytest.raises(ValueError):
        cur.push(2, 0, "x")
    with pytest.raises(ValueError):
        cur.push(-1, 0, "x")
    with pytest.raises(ValueError):
        MergeCursor(0)


def test_merge_order_oracle_matches_cursor():
    # ragged settled prefixes: the wholesale oracle and the
    # incremental cursor must agree on the emittable prefix
    settled = [
        ["a0", "a1", "a2"],
        ["b0"],
        ["c0", "c1"],
    ]
    got = merge_order(settled)
    # epoch 0 complete; epoch 1 blocked at lane 1 after emitting a1
    assert got == ["a0", "b0", "c0", "a1"]
    cur = MergeCursor(3)
    for lane, batches in enumerate(settled):
        for epoch, batch in enumerate(batches):
            cur.push(lane, epoch, batch)
            cur.drain()
    assert cur.merged == got


# ---------------------------------------------------------------------------
# cluster equivalence: lanes=1 baseline, lanes=4 shard-out
# ---------------------------------------------------------------------------


def _merged_digest(cluster: SimulatedCluster, nid: str) -> str:
    h = hashlib.sha256()
    for seq, batch in enumerate(cluster.nodes[nid].merged_batches):
        h.update(encode_batch_body(seq, batch))
    return h.hexdigest()


def _run_cluster(lanes: int, txs: int = 48, seed: int = 9, **kw):
    cfg = Config(n=4, batch_size=8, seed=seed, lanes=lanes)
    cluster = SimulatedCluster(config=cfg, seed=seed, key_seed=3, **kw)
    for i in range(txs):
        cluster.submit(b"ln-tx-%04d" % i)
    cluster.run_until_drained(max_rounds=200)
    return cluster


def test_lanes1_byte_identical_to_default_build():
    """The byte-equivalence baseline arm: lanes=1 must be
    indistinguishable from a Config that never mentions lanes."""
    base = SimulatedCluster(
        config=Config(n=4, batch_size=8, seed=9), seed=9, key_seed=3
    )
    armed = _run_cluster(lanes=1)
    for i in range(48):
        base.submit(b"ln-tx-%04d" % i)
    base.run_until_drained(max_rounds=200)
    base.assert_agreement()
    armed.assert_agreement()
    for nid in base.ids:
        assert _merged_digest(base, nid) == _merged_digest(armed, nid)
    # no lane machinery was ever built: self.lanes is [self]
    hb = armed.nodes[armed.ids[0]]
    assert hb.lanes == [hb]
    assert hb.merged_batches == hb.committed_batches


def test_lanes4_merged_orders_agree_and_settle_exactly_once():
    cluster = _run_cluster(lanes=4)
    depth = cluster.assert_agreement()
    assert depth > 0
    digests = {_merged_digest(cluster, nid) for nid in cluster.ids}
    assert len(digests) == 1, "honest merged orders diverged"
    # every submitted tx settled exactly once, in the lane the
    # production partitioner routed it to
    hb = cluster.nodes[cluster.ids[0]]
    assert len(hb.lanes) == 4
    seed = hb.config.seed
    seen = {}
    for lane_idx, lane in enumerate(hb.lanes):
        for batch in lane.committed_batches:
            for tx in (
                t for v in batch.contributions.values() for t in v
            ):
                assert tx not in seen, "tx settled twice"
                seen[tx] = lane_idx
                assert lane_of(seed, tx_digest(tx), 4) == lane_idx
    assert len(seen) == 48
    # every lane actually ordered something (the partition spread txs)
    assert all(lane.epoch > 0 for lane in hb.lanes)
    # the merged frontier counts ALL lanes' settled slots
    assert hb.merged_settled_frontier == sum(
        len(lane.committed_batches) for lane in hb.lanes
    )


def test_lanes4_deterministic_across_runs():
    a = _run_cluster(lanes=4)
    b = _run_cluster(lanes=4)
    a.assert_agreement()
    b.assert_agreement()
    assert _merged_digest(a, a.ids[0]) == _merged_digest(b, b.ids[0])


# ---------------------------------------------------------------------------
# crash / WAL restart at lanes=4 (lane-tagged record streams)
# ---------------------------------------------------------------------------


def test_wal_restart_recovers_all_lane_frontiers(tmp_path):
    cfg = Config(n=4, batch_size=8, seed=9, lanes=4)
    c = SimulatedCluster(
        config=cfg, seed=9, key_seed=3, wal_dir=str(tmp_path)
    )
    try:
        for i in range(48):
            c.submit(b"wl-tx-%04d" % i)
        c.run_until_drained(max_rounds=200)
        victim = c.ids[1]
        pre = c.nodes[victim]
        pre_frontiers = [len(l.committed_batches) for l in pre.lanes]
        pre_merged = _merged_digest(c, victim)
        assert sum(pre_frontiers) > 0
        # fail-stop + process restart from the lane-tagged WAL
        c.crash(victim)
        hb2 = c.restart_node(victim)
        assert len(hb2.lanes) == 4
        assert [
            len(l.committed_batches) for l in hb2.lanes
        ] == pre_frontiers
        assert _merged_digest(c, victim) == pre_merged
        # the restarted node keeps ordering across every lane
        for i in range(48, 96):
            c.submit(b"wl-tx-%04d" % i)
        c.run_until_drained(max_rounds=200)
        depth = c.assert_agreement()
        assert depth > sum(pre_frontiers)
        post = [len(l.committed_batches) for l in hb2.lanes]
        assert all(p >= q for p, q in zip(post, pre_frontiers))
        assert sum(post) > sum(pre_frontiers)
        digests = {_merged_digest(c, nid) for nid in c.ids}
        assert len(digests) == 1
    finally:
        c.stop()


# ---------------------------------------------------------------------------
# LanePayload wire framing (kind 21)
# ---------------------------------------------------------------------------


def _inner_payloads():
    return [
        RbcPayload(RbcType.READY, "p", 3, b"h" * 32),
        BbaPayload(BbaType.AUX, "n1", 2, 0, False),
    ]


def test_lane_payload_round_trip():
    for lane in (0, 1, 7, 255):
        for inner in _inner_payloads():
            msg = Message("n0", 1.5, LanePayload(lane, inner), b"sig")
            out = decode_message(encode_message(msg))
            assert out == msg
            assert out.payload.lane == lane
            assert out.payload.inner == inner
    # lane frames ride inside coalesced bundles like any payload
    bundle = BundlePayload(
        tuple(
            LanePayload(k, p)
            for k in (0, 3)
            for p in _inner_payloads()
        )
    )
    msg = Message("n0", 1.5, bundle, b"sig")
    assert decode_message(encode_message(msg)) == msg


def test_lane_payload_nesting_and_range_rejected():
    inner = RbcPayload(RbcType.READY, "p", 0, b"h")
    # no lane-in-lane, no bundle-in-lane: the lane axis is
    # outermost-but-one
    for bad in (
        LanePayload(1, LanePayload(0, inner)),
        LanePayload(1, BundlePayload((inner,))),
    ):
        with pytest.raises(ValueError):
            encode_message(Message("n0", 0.0, bad, b"s"))
    with pytest.raises(ValueError):
        encode_message(
            Message("n0", 0.0, LanePayload(256, inner), b"s")
        )


# ---------------------------------------------------------------------------
# mempool lane partitioning
# ---------------------------------------------------------------------------


class _SinkQueue:
    def __init__(self):
        self.items = []

    def push(self, tx):
        self.items.append(tx)


def test_mempool_partitions_admission_by_lane():
    pool = Mempool(capacity=64, seed=7, lanes=4)
    txs = [b"mp-%03d" % i for i in range(32)]
    for i, tx in enumerate(txs):
        assert pool.admit(tx, "c%d" % (i % 8), fee=10 + i).status == OK
    fill = pool.lane_fill()
    assert sum(fill) == 32
    by_lane = {}
    for tx in txs:
        by_lane.setdefault(lane_of(7, tx_digest(tx), 4), []).append(tx)
    assert fill == [len(by_lane.get(k, [])) for k in range(4)]
    # drain_into(lane=k) surfaces ONLY that lane's txs, highest fee
    # first; other lanes' gauges are untouched
    for k in range(4):
        q = _SinkQueue()
        moved = pool.drain_into(q, max_n=64, lane=k)
        assert moved == len(by_lane.get(k, []))
        assert set(q.items) == set(by_lane.get(k, []))
        fees = [txs.index(t) for t in q.items]
        assert fees == sorted(fees, reverse=True)
    assert pool.pending_count() == 0


# ---------------------------------------------------------------------------
# Config bounds
# ---------------------------------------------------------------------------


def test_config_validates_lane_bounds():
    Config(n=4, lanes=MAX_LANES)  # the cap itself is legal
    with pytest.raises(ValueError):
        Config(n=4, lanes=0)
    with pytest.raises(ValueError):
        Config(n=4, lanes=MAX_LANES + 1)
