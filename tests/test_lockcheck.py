"""utils/lockcheck.py: the runtime @guarded_by lock sanitizer
(ISSUE 17) — the dynamic twin of staticcheck's CONC001/CONC003 rules
over the SAME annotation registry.

The suite arms the sanitizer by flipping ``lockcheck._ENABLED``
directly (the env var is read once at import; ``is_enabled`` reads
the module global dynamically for exactly this reason) and defines
throwaway guarded classes, so no real-tree class is instrumented
behind the rest of the session's back.
"""

from __future__ import annotations

import pathlib
import sys
import threading

import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from cleisthenes_tpu.utils import lockcheck  # noqa: E402
from cleisthenes_tpu.utils.determinism import guarded_by  # noqa: E402
from cleisthenes_tpu.utils.lockcheck import (  # noqa: E402
    LockCheckError,
    new_lock,
    new_rlock,
)


@pytest.fixture
def armed(monkeypatch):
    monkeypatch.setattr(lockcheck, "_ENABLED", True)
    yield


@pytest.fixture
def disarmed(monkeypatch):
    # ci.sh stage 7 runs this suite WITH the env var set; pin the
    # state either way so both halves test what they claim
    monkeypatch.setattr(lockcheck, "_ENABLED", False)
    yield


def _make_store():
    @guarded_by("_lock", "_items", "_count")
    class Store:
        def __init__(self):
            self._lock = new_lock()
            self._items = {}
            self._count = 0

        def add(self, k, v):
            with self._lock:
                self._items[k] = v
                self._count += 1

        def bad_get(self, k):
            # the violation the armed sanitizer must catch
            return self._items.get(k)  # staticcheck: allow[CONC001] deliberate test violation

        def size(self):
            with self._lock:
                return self._count

    return Store


# ---------------------------------------------------------------------------
# disarmed (the default): zero overhead, plain primitives
# ---------------------------------------------------------------------------


def test_disarmed_factories_return_plain_primitives(disarmed):
    assert not lockcheck.is_enabled()
    lock = new_lock()
    assert isinstance(lock, type(threading.Lock()))
    rlock = new_rlock()
    assert isinstance(rlock, type(threading.RLock()))


def test_disarmed_guarded_class_is_uninstrumented(disarmed):
    Store = _make_store()
    # no wrapper layer: undisciplined access is legal (the STATIC
    # rules own enforcement when the sanitizer is off)
    s = Store()
    s.bad_get("k")
    assert not hasattr(Store, "__lockcheck_installed__")
    assert Store.__getattribute__ is object.__getattribute__


# ---------------------------------------------------------------------------
# armed: violations raise, discipline stays silent
# ---------------------------------------------------------------------------


def test_armed_violation_raises_with_names(armed):
    Store = _make_store()
    s = Store()
    with pytest.raises(LockCheckError) as ei:
        s.bad_get("k")
    err = ei.value
    assert isinstance(err, AssertionError)  # except-clause compat
    assert err.cls_name == "Store"
    assert err.attr == "_items"
    assert err.lock_attr == "_lock"
    assert err.acquirer == threading.current_thread().name
    assert err.holder is None  # nobody held it
    assert "Store._items" in str(err) and "_lock" in str(err)


def test_armed_violation_names_the_current_holder(armed):
    Store = _make_store()
    s = Store()
    captured = {}

    def contender():
        try:
            s.bad_get("k")
        except LockCheckError as e:
            captured["err"] = e

    with s._lock:
        t = threading.Thread(target=contender, name="contender-1")
        t.start()
        t.join()
    err = captured["err"]
    assert err.holder == threading.current_thread().name
    assert err.acquirer == "contender-1"


def test_armed_clean_run_is_silent(armed):
    Store = _make_store()
    s = Store()
    s.add("k", 1)
    assert s.size() == 1
    # writes from a second disciplined thread also pass
    t = threading.Thread(target=s.add, args=("j", 2))
    t.start()
    t.join()
    assert s.size() == 2


def test_armed_constructor_frames_are_exempt(armed):
    # __init__ touches guarded attrs before (and while) the lock
    # exists; the sanitizer mirrors the static rules' exemption —
    # including through comprehension frames (py<3.12 synthesizes
    # <dictcomp>/<listcomp> frames inside __init__)
    @guarded_by("_lock", "_items")
    class Warm:
        def __init__(self, keys):
            self._lock = new_lock()
            self._items = {k: 0 for k in keys}
            self._items = {k: v + 1 for k, v in self._items.items()}

    w = Warm(["a", "b"])
    with pytest.raises(LockCheckError):
        w._items


def test_armed_rlock_reentry_counts(armed):
    @guarded_by("_lock", "_n")
    class R:
        def __init__(self):
            self._lock = new_rlock()
            self._n = 0

        def outer(self):
            with self._lock:
                return self.inner()

        def inner(self):
            with self._lock:  # re-entry must not clear ownership
                self._n += 1
            # still held by outer's with: the lexical rule cannot
            # see that, the reentry-aware wrapper must
            return self._n  # staticcheck: allow[CONC001] reentry probe

    assert R().outer() == 1


def test_stacked_decorators_extend_coverage_one_wrapper(armed):
    @guarded_by("_lock", "_a")
    @guarded_by("_other", "_b")
    class X:
        def __init__(self):
            self._lock = new_lock()
            self._other = new_lock()
            self._a = 1
            self._b = 2

    x = X()
    with pytest.raises(LockCheckError) as ea:
        x._a
    assert ea.value.lock_attr == "_lock"
    with pytest.raises(LockCheckError) as eb:
        x._b
    assert eb.value.lock_attr == "_other"
    with x._lock:
        assert x._a == 1
    with x._other:
        assert x._b == 2


def test_lock_held_by_other_thread_does_not_cover_current(armed):
    Store = _make_store()
    s = Store()
    done = threading.Event()
    release = threading.Event()

    def holder():
        with s._lock:
            done.set()
            release.wait(timeout=5)

    t = threading.Thread(target=holder)
    t.start()
    done.wait(timeout=5)
    try:
        with pytest.raises(LockCheckError):
            s.bad_get("k")  # held, but by the OTHER thread
    finally:
        release.set()
        t.join()
