"""SHA-256 + Merkle forest tests: correctness vs hashlib, branch
verification incl. tamper cases (the validateMessage matrix from
reference rbc/rbc_internal_test.go:5-31, docs/RBC-EN.md:35-38)."""

import hashlib

import numpy as np
import pytest

from cleisthenes_tpu.ops.merkle import CpuMerkle, XlaMerkle, make_merkle

rng = np.random.default_rng(7)


class TestSha256Xla:
    @pytest.mark.parametrize("length", [0, 1, 31, 32, 55, 56, 63, 64, 65, 127, 200, 1000])
    def test_matches_hashlib(self, length):
        import jax.numpy as jnp

        from cleisthenes_tpu.ops.sha256_xla import sha256_batch

        msgs = rng.integers(0, 256, (5, length)).astype(np.uint8)
        got = np.asarray(sha256_batch(jnp.asarray(msgs)))
        for i in range(5):
            want = hashlib.sha256(msgs[i].tobytes()).digest()
            assert got[i].tobytes() == want, f"len={length} row={i}"

    def test_known_vector(self):
        import jax.numpy as jnp

        from cleisthenes_tpu.ops.sha256_xla import sha256_batch

        msg = np.frombuffer(b"abc", dtype=np.uint8)[None]
        got = np.asarray(sha256_batch(jnp.asarray(msg)))[0].tobytes()
        assert got.hex() == (
            "ba7816bf8f01cfea414140de5dae2223b00361a396177a9cb410ff61f20015ad"
        )


@pytest.mark.parametrize("backend", ["cpu", "tpu"])
class TestMerkle:
    @pytest.mark.parametrize("n", [1, 2, 3, 4, 7, 16])
    def test_build_and_verify_all_branches(self, backend, n):
        m = make_merkle(backend)
        shards = rng.integers(0, 256, (n, 64)).astype(np.uint8)
        tree = m.build(shards)
        for j in range(n):
            assert m.verify_branch(
                tree.root, shards[j].tobytes(), tree.branch(j), j
            ), f"branch {j} of {n}"

    def test_tampered_leaf_rejected(self, backend, n=7):
        m = make_merkle(backend)
        shards = rng.integers(0, 256, (n, 64)).astype(np.uint8)
        tree = m.build(shards)
        bad = bytearray(shards[3].tobytes())
        bad[0] ^= 1
        assert not m.verify_branch(tree.root, bytes(bad), tree.branch(3), 3)

    def test_wrong_index_rejected(self, backend, n=8):
        m = make_merkle(backend)
        shards = rng.integers(0, 256, (n, 32)).astype(np.uint8)
        tree = m.build(shards)
        assert not m.verify_branch(
            tree.root, shards[3].tobytes(), tree.branch(3), 4
        )

    def test_tampered_branch_rejected(self, backend, n=4):
        m = make_merkle(backend)
        shards = rng.integers(0, 256, (n, 32)).astype(np.uint8)
        tree = m.build(shards)
        branch = tree.branch(0)
        branch[1] = b"\x00" * 32
        assert not m.verify_branch(tree.root, shards[0].tobytes(), branch, 0)

    def test_batch_build_matches_single(self, backend):
        m = make_merkle(backend)
        shards = rng.integers(0, 256, (5, 7, 48)).astype(np.uint8)
        trees = m.build_batch(shards)
        for i, t in enumerate(trees):
            assert t.root == m.build(shards[i]).root

    def test_batch_verify(self, backend):
        """The ECHO hot path: many (root, leaf, branch, index) checks in
        one dispatch, including some invalid ones."""
        m = make_merkle(backend)
        n = 8
        shards = rng.integers(0, 256, (n, 64)).astype(np.uint8)
        tree = m.build(shards)
        roots = np.stack([np.frombuffer(tree.root, dtype=np.uint8)] * n)
        leaves = shards.copy()
        branches = np.stack(
            [
                np.stack([np.frombuffer(s, dtype=np.uint8) for s in tree.branch(j)])
                for j in range(n)
            ]
        )
        indices = np.arange(n)
        leaves[2] ^= 0xFF  # corrupt one
        ok = m.verify_batch(roots, leaves, branches, indices)
        want = np.ones(n, dtype=bool)
        want[2] = False
        assert np.array_equal(ok, want)


def test_backends_identical_roots():
    shards = rng.integers(0, 256, (7, 128)).astype(np.uint8)
    assert CpuMerkle().build(shards).root == XlaMerkle().build(shards).root


def test_branch_index_out_of_range():
    m = CpuMerkle()
    tree = m.build(rng.integers(0, 256, (4, 16)).astype(np.uint8))
    with pytest.raises(IndexError):
        tree.branch(4)
