"""cProfile of one lockstep N=128 epoch — where does bba_s go?

The chip A/B (AB_COIN_BLOCKS_r05) put the N=128 epoch at ~3.1-3.5 s
with bba_s ~2.4-3.2 s; the north star wants the whole epoch under
1 s.  This attributes the gap: device wait (XLA dispatch/transfer
frames) vs host-side marshalling (item assembly, limb packing, CP
hashing, nonce draws) — so the next optimization targets the real
cost, not the assumed one.

Usage:  python tools/profile_spmd.py [n] [batch] [backend]
"""

from __future__ import annotations

import cProfile
import io
import os
import pstats
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tools import benchlock  # noqa: E402


def main() -> int:
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 128
    batch = int(sys.argv[2]) if len(sys.argv) > 2 else 10_000
    backend = sys.argv[3] if len(sys.argv) > 3 else "tpu"
    with benchlock.hold("profile_spmd"):
        import numpy as np

        from cleisthenes_tpu.protocol.spmd import LockstepCluster

        cluster = LockstepCluster(
            n=n, batch_size=batch, crypto_backend=backend, key_seed=77
        )
        rng = np.random.default_rng(13)
        for _ in range((batch // n) * n * 3):
            tx = rng.integers(0, 256, size=64, dtype=np.uint8).tobytes()
            cluster.submit(tx)
        cluster.run_epoch()  # warm-up / compile
        prof = cProfile.Profile()
        prof.enable()
        s = cluster.run_epoch()
        prof.disable()
        print(f"stats: {s}", file=sys.stderr)
        out = io.StringIO()
        st = pstats.Stats(prof, stream=out)
        st.sort_stats("cumulative").print_stats(45)
        st.sort_stats("tottime").print_stats(35)
        print(out.getvalue())
    return 0


if __name__ == "__main__":
    sys.exit(main())
