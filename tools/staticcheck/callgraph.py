"""Pass 3 of the whole-program analyzer: the call graph and the
interprocedural rule catalog.

Passes 1-2 (program.py / registry_rules.py) check cross-module
REGISTRY contracts; everything concurrency- and entropy-shaped was
still judged one function at a time.  This pass builds a def->call
graph over every scanned file and runs three rules across it:

- CONC003  caller-holds discipline: a call site of a ``*_locked``
           function must lexically hold the callee class's
           ``@guarded_by`` lock — unless the caller is itself a
           ``*_locked`` method of the same class (then ITS call sites
           are checked, walking the contract transitively) or a
           constructor.  Replaces CONC001's single-file approximation
           of the caller side.
- CONC004  blocking-call reachability: a blocking call (time.sleep,
           socket/select waits, os.fsync, subprocess) transitively
           reachable from a dispatcher handler callback (handle_*/
           on_*/serve_*, incl. on_idle and serve_wave) stalls every
           instance behind the dispatch thread.  Makes CONC002
           transitive; depth-0 sites CONC002 already reports are not
           re-reported.
- DET007   interprocedural entropy taint: a value produced by a
           non-``utils.determinism`` randomness/wall-clock source —
           directly or through any chain of returning functions —
           must not be stored into protocol-plane instance state or
           passed into a protocol-plane function.  Subsumes DET001's
           recall gap (a plane file laundering entropy through a
           helper module).

Call resolution (documented soundness gaps and all):

1. ``self.m()``     -> the enclosing class's own ``m`` when defined
                       there (same file).
2. ``mod.f()``      -> through import aliases (FileContext.resolve)
                       and a dotted-module-suffix -> scanned-file map
                       (relative imports resolve by longest unique
                       suffix).
3. ``bare()``       -> a from-imported function (via 2) or any
                       scanned def of that name.
4. ``obj.m()``      -> when ``obj`` is a local or ``self`` attribute
                       assigned ``ClassName(...)`` in the scanned
                       tree, the method of that class.
5. fallback         -> name match across every scanned def, EXCLUDING
                       builtin-collection method names (append/get/
                       pop/items/...) and dunders.  CONC004 follows
                       every candidate (recall); DET007 propagates
                       only through UNIQUE matches (precision).

Known gaps: inheritance is not walked (a method resolved on a base
class only lands via name match); values returned through containers
lose taint; callables passed as arguments create no edge.  The
runtime twin — cleisthenes_tpu/utils/lockcheck.py, sharing the same
``@guarded_by`` registry — watches what the graph cannot prove.
"""

from __future__ import annotations

import ast
import dataclasses
import pathlib
from typing import (
    Dict,
    FrozenSet,
    Iterator,
    List,
    Optional,
    Set,
    Tuple,
)

from tools.staticcheck.core import (
    FileContext,
    Finding,
    parse_pragmas,
    rule,
)
from tools.staticcheck.rules import (
    _BLOCKING_METHOD_NAMES,
    _DET001_EXACT,
    _DET001_MODULES,
    _guarded_decls,
    _is_handler_name,
)

# Names that are overwhelmingly builtin-collection methods: a name
# match on these would wire every list.append in the tree to every
# class's append method.  Excluded from fallback resolution (gap:
# a genuinely project-defined method with one of these names only
# resolves through typing or self).
_COLLECTION_METHODS = frozenset(
    (
        "append",
        "add",
        "extend",
        "pop",
        "get",
        "items",
        "keys",
        "values",
        "clear",
        "update",
        "insert",
        "remove",
        "discard",
        "put",
        "setdefault",
        "popleft",
        "appendleft",
        "sort",
        "index",
        "count",
        "copy",
        "join",
        "split",
        "strip",
        "encode",
        "decode",
        "read",
        "write",
        "close",
        "format",
        "flush",
        "release",
        "acquire",
        "set",
        "wait",
        "start",
    )
)

# dotted blocking calls; CONC002's vocabulary plus the durability /
# process-spawn calls only reachability analysis can police
_BLOCKING_EXACT = frozenset(("time.sleep", "select.select", "os.fsync"))
_BLOCKING_MODULE_PREFIXES = ("socket.", "subprocess.")
_CONC002_EXACT = frozenset(("time.sleep", "select.select"))

_CONSTRUCTOR_EXEMPT = frozenset(("__init__", "__del__"))


def _dotted_expr(node: ast.AST) -> Optional[str]:
    """Render a Name/Attribute chain ("self._lock", "x.fh"); None for
    anything dynamic (subscripts, calls)."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = _dotted_expr(node.value)
        if base is not None:
            return f"{base}.{node.attr}"
    return None


@dataclasses.dataclass
class CallSite:
    """One call expression inside a function body."""

    name: str  # bare callable name (attr or id)
    recv: Optional[str]  # rendered receiver ("self", "self.wal", "x")
    dotted: Optional[str]  # import-alias resolution of the callee
    line: int
    col: int
    held: FrozenSet[str]  # "with <expr>:" exprs lexically held here
    node: ast.Call


@dataclasses.dataclass
class BlockingSite:
    what: str  # human name of the blocking call
    line: int
    col: int
    conc002_vocab: bool  # CONC002 would report this at depth 0


@dataclasses.dataclass
class FuncNode:
    """One function/method definition: a call-graph node."""

    relpath: str
    qual: str  # "Class.method" / "func" / "outer.inner"
    name: str  # last component
    cls: Optional[str]  # enclosing class name, if a method
    line: int
    fn: ast.AST
    calls: List[CallSite]
    blocking: List[BlockingSite]
    local_types: Dict[str, str]  # local var -> class name (x = C())
    in_plane: bool
    in_transport: bool

    @property
    def key(self) -> Tuple[str, str]:
        return (self.relpath, self.qual)


@dataclasses.dataclass
class CallGraph:
    """Every node plus the side tables resolution needs."""

    nodes: Dict[Tuple[str, str], FuncNode]
    by_name: Dict[str, List[Tuple[str, str]]]  # bare name -> keys
    classes: Dict[str, List[Tuple[str, ast.ClassDef]]]  # name -> defs
    guarded: Dict[Tuple[str, str], Dict[str, str]]  # (file, cls) decl
    methods: Dict[Tuple[str, str], Dict[str, Tuple[str, str]]]
    attr_types: Dict[Tuple[str, str], Dict[str, str]]  # self.X = C()
    module_files: Dict[str, List[str]]  # dotted-suffix -> relpaths

    def resolve_module(self, dotted_mod: str) -> Optional[str]:
        hits = self.module_files.get(dotted_mod)
        if hits is not None and len(hits) == 1:
            return hits[0]
        return None

    def class_of(self, name: str) -> Optional[Tuple[str, ast.ClassDef]]:
        hits = self.classes.get(name)
        if hits is not None and len(hits) == 1:
            return hits[0]
        return None


def _class_name_of_ctor(call: ast.Call) -> Optional[str]:
    fn = call.func
    name = None
    if isinstance(fn, ast.Name):
        name = fn.id
    elif isinstance(fn, ast.Attribute):
        name = fn.attr
    if name and name[:1].isupper():
        return name
    return None


class _FileExtractor(ast.NodeVisitor):
    """One pass over a file: nodes, class decls, attribute typing."""

    def __init__(self, ctx: FileContext, graph: "CallGraph") -> None:
        self.ctx = ctx
        self.graph = graph
        self._cls_stack: List[str] = []
        self._qual_stack: List[str] = []

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self.graph.classes.setdefault(node.name, []).append(
            (self.ctx.relpath, node)
        )
        decls = _guarded_decls(node)
        if decls:
            self.graph.guarded[(self.ctx.relpath, node.name)] = decls
        self._cls_stack.append(node.name)
        self._qual_stack.append(node.name)
        self.generic_visit(node)
        self._qual_stack.pop()
        self._cls_stack.pop()

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._function(node)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._function(node)

    def _function(self, node: ast.AST) -> None:
        qual = ".".join(self._qual_stack + [node.name])
        cls = self._cls_stack[-1] if self._cls_stack else None
        fnode = FuncNode(
            relpath=self.ctx.relpath,
            qual=qual,
            name=node.name,
            cls=cls,
            line=node.lineno,
            fn=node,
            calls=[],
            blocking=[],
            local_types={},
            in_plane=self.ctx.in_plane,
            in_transport=self.ctx.in_transport,
        )
        self.graph.nodes[fnode.key] = fnode
        self.graph.by_name.setdefault(node.name, []).append(fnode.key)
        if cls is not None and len(self._qual_stack) == 1:
            self.graph.methods.setdefault(
                (self.ctx.relpath, cls), {}
            )[node.name] = fnode.key
        _BodyWalker(self.ctx, self.graph, fnode).run()
        # nested defs become their own nodes (with an implicit edge
        # from the parent, added by _BodyWalker)
        self._qual_stack.append(node.name)
        for child in ast.iter_child_nodes(node):
            if isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                self._function(child)
            elif isinstance(child, ast.ClassDef):
                self.visit_ClassDef(child)
        self._qual_stack.pop()


class _BodyWalker:
    """Walks ONE function body (not nested defs): records call sites
    with the lexically-held ``with`` set, blocking calls, and
    local/attribute constructor typing."""

    def __init__(
        self, ctx: FileContext, graph: CallGraph, fnode: FuncNode
    ) -> None:
        self.ctx = ctx
        self.graph = graph
        self.fnode = fnode
        self.held: List[str] = []

    def run(self) -> None:
        for stmt in self.fnode.fn.body:
            self._visit(stmt)

    def _note_ctor_types(self, node: ast.Assign) -> None:
        if not isinstance(node.value, ast.Call):
            return
        cls_name = _class_name_of_ctor(node.value)
        if cls_name is None or self.graph.class_of(cls_name) is None:
            return
        for tgt in node.targets:
            if isinstance(tgt, ast.Name):
                self.fnode.local_types[tgt.id] = cls_name
            elif (
                isinstance(tgt, ast.Attribute)
                and isinstance(tgt.value, ast.Name)
                and tgt.value.id == "self"
                and self.fnode.cls is not None
            ):
                self.graph.attr_types.setdefault(
                    (self.fnode.relpath, self.fnode.cls), {}
                )[tgt.attr] = cls_name

    def _record_call(self, node: ast.Call) -> None:
        fn = node.func
        if isinstance(fn, ast.Name):
            name, recv = fn.id, None
        elif isinstance(fn, ast.Attribute):
            name, recv = fn.attr, _dotted_expr(fn.value)
        else:
            return
        dotted = self.ctx.resolve(fn)
        self.fnode.calls.append(
            CallSite(
                name=name,
                recv=recv,
                dotted=dotted,
                line=node.lineno,
                col=node.col_offset,
                held=frozenset(self.held),
                node=node,
            )
        )
        if dotted is not None and (
            dotted in _BLOCKING_EXACT
            or dotted.startswith(_BLOCKING_MODULE_PREFIXES)
        ):
            self.fnode.blocking.append(
                BlockingSite(
                    what=dotted,
                    line=node.lineno,
                    col=node.col_offset,
                    conc002_vocab=(
                        dotted in _CONC002_EXACT
                        or dotted.startswith("socket.")
                    ),
                )
            )
        elif dotted is None and name in _BLOCKING_METHOD_NAMES:
            self.fnode.blocking.append(
                BlockingSite(
                    what=f".{name}()",
                    line=node.lineno,
                    col=node.col_offset,
                    conc002_vocab=True,
                )
            )

    def _visit(self, node: ast.AST) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # nested def: implicit edge parent -> child (the parent
            # at least defines it; most are called synchronously)
            self.fnode.calls.append(
                CallSite(
                    name=node.name,
                    recv=None,
                    dotted=None,
                    line=node.lineno,
                    col=node.col_offset,
                    held=frozenset(self.held),
                    node=ast.Call(
                        func=ast.Name(id=node.name, ctx=ast.Load()),
                        args=[],
                        keywords=[],
                    ),
                )
            )
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            acquired = []
            for item in node.items:
                expr = _dotted_expr(item.context_expr)
                if expr is not None:
                    acquired.append(expr)
                    self.held.append(expr)
            for child in node.body:
                self._visit(child)
            for _ in acquired:
                self.held.pop()
            return
        if isinstance(node, ast.Assign):
            self._note_ctor_types(node)
        if isinstance(node, ast.Call):
            self._record_call(node)
        for child in ast.iter_child_nodes(node):
            self._visit(child)


def _module_dotted(relpath: str) -> List[str]:
    """Every dotted suffix a relative/absolute import could spell for
    this file: a/b/c.py -> [a.b.c, b.c, c]."""
    parts = list(pathlib.PurePosixPath(relpath).with_suffix("").parts)
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return [".".join(parts[i:]) for i in range(len(parts))]


def build_callgraph(ctx_map: Dict[str, FileContext]) -> CallGraph:
    graph = CallGraph(
        nodes={},
        by_name={},
        classes={},
        guarded={},
        methods={},
        attr_types={},
        module_files={},
    )
    for relpath in sorted(ctx_map):
        for suffix in _module_dotted(relpath):
            if suffix:
                graph.module_files.setdefault(suffix, []).append(
                    relpath
                )
    for relpath in sorted(ctx_map):
        _FileExtractor(ctx_map[relpath], graph).visit(
            ctx_map[relpath].tree
        )
    return graph


# memoized per context-set: three rules (and the audit re-run) share
# one graph build
_GRAPH_CACHE: List[Tuple[Tuple[Tuple[str, int], ...], CallGraph]] = []


def _graph_for(ctx_map: Dict[str, FileContext]) -> CallGraph:
    key = tuple(
        sorted((rp, id(ctx)) for rp, ctx in ctx_map.items())
    )
    for cached_key, cached in _GRAPH_CACHE:
        if cached_key == key:
            return cached
    graph = build_callgraph(ctx_map)
    del _GRAPH_CACHE[:]
    _GRAPH_CACHE.append((key, graph))
    return graph


# ---------------------------------------------------------------------------
# edge resolution
# ---------------------------------------------------------------------------


def _resolve_dotted(
    graph: CallGraph, dotted: str
) -> List[Tuple[str, str]]:
    """mod.func / pkg.mod.Class.method through the module-suffix map."""
    parts = dotted.split(".")
    for i in range(len(parts) - 1, 0, -1):
        relpath = graph.resolve_module(".".join(parts[:i]))
        if relpath is None:
            continue
        rest = parts[i:]
        if len(rest) == 1:
            key = (relpath, rest[0])
            if key in graph.nodes:
                return [key]
            # from mod import Class; Class.method would be rest==2
        elif len(rest) == 2:
            key = (relpath, ".".join(rest))
            if key in graph.nodes:
                return [key]
        return []
    return []


def resolve_call(
    graph: CallGraph, caller: FuncNode, site: CallSite
) -> Tuple[List[Tuple[str, str]], bool]:
    """(target node keys, exact) for one call site.  ``exact`` is True
    for self-method / typed-receiver / import-resolved targets; False
    for the name-match fallback (every scanned def of that name)."""
    # 1. self.m() inside a class that defines m
    if site.recv == "self" and caller.cls is not None:
        m = graph.methods.get((caller.relpath, caller.cls), {})
        key = m.get(site.name)
        if key is not None:
            return [key], True
    # 2. import-alias dotted resolution
    if site.dotted is not None:
        keys = _resolve_dotted(graph, site.dotted)
        if keys:
            return keys, True
    # 3. typed receiver: self.X = C(...) or x = C(...)
    if site.recv is not None and site.recv != "self":
        cls_name = None
        if "." not in site.recv:
            cls_name = caller.local_types.get(site.recv)
        elif site.recv.startswith("self.") and caller.cls is not None:
            attr = site.recv.split(".", 1)[1]
            if "." not in attr:
                cls_name = graph.attr_types.get(
                    (caller.relpath, caller.cls), {}
                ).get(attr)
        if cls_name is not None:
            hit = graph.class_of(cls_name)
            if hit is not None:
                key = graph.methods.get(
                    (hit[0], cls_name), {}
                ).get(site.name)
                if key is not None:
                    return [key], True
    # 4. bare name -> a local def in the same file
    if site.recv is None:
        for cand in graph.by_name.get(site.name, ()):
            if cand[0] == caller.relpath:
                return [cand], True
    # 5. name-match fallback
    if (
        site.name in _COLLECTION_METHODS
        or site.name.startswith("__")
    ):
        return [], False
    return list(graph.by_name.get(site.name, ())), False


# ---------------------------------------------------------------------------
# CONC003: caller-holds discipline for *_locked functions
# ---------------------------------------------------------------------------


def _required_locks(
    graph: CallGraph, callee: FuncNode
) -> List[str]:
    """Locks a ``*_locked`` method's caller must hold: the locks
    guarding the ``@guarded_by`` attrs it touches, else (if it only
    delegates) every distinct declared lock of its class."""
    if callee.cls is None:
        return []
    decls = graph.guarded.get((callee.relpath, callee.cls))
    if not decls:
        return []
    touched: Set[str] = set()
    for n in ast.walk(callee.fn):
        if (
            isinstance(n, ast.Attribute)
            and isinstance(n.value, ast.Name)
            and n.value.id == "self"
            and n.attr in decls
        ):
            touched.add(decls[n.attr])
    if touched:
        return sorted(touched)
    return sorted(set(decls.values()))


@rule
class Conc003CallerHoldsLock:
    id = "CONC003"
    doc = (
        "every call site of a *_locked function must lexically hold "
        "the callee class's @guarded_by lock (with <recv>.<lock>:), "
        "unless the caller is itself a *_locked method of that class "
        "(checked transitively at ITS call sites) or a constructor"
    )

    def check_program(
        self, index, ctx_map: Dict[str, FileContext]
    ) -> Iterator[Finding]:
        graph = _graph_for(ctx_map)
        for key in sorted(graph.nodes):
            caller = graph.nodes[key]
            if caller.name in _CONSTRUCTOR_EXEMPT:
                continue
            for site in caller.calls:
                if not site.name.endswith("_locked"):
                    continue
                yield from self._check_site(
                    graph, ctx_map, caller, site
                )

    def _check_site(
        self,
        graph: CallGraph,
        ctx_map: Dict[str, FileContext],
        caller: FuncNode,
        site: CallSite,
    ) -> Iterator[Finding]:
        targets, _exact = resolve_call(graph, caller, site)
        callees = [
            graph.nodes[k]
            for k in targets
            if graph.nodes[k].cls is not None
        ]
        if not callees:
            return
        callee = callees[0]
        # transitivity: a *_locked method calling a sibling *_locked
        # method of the SAME class defers to its own callers
        if (
            caller.name.endswith("_locked")
            and site.recv == "self"
            and caller.cls == callee.cls
            and caller.relpath == callee.relpath
        ):
            return
        required = _required_locks(graph, callee)
        if not required:
            return
        recv_base = site.recv if site.recv is not None else "self"
        missing = [
            lock
            for lock in required
            if f"{recv_base}.{lock}" not in site.held
        ]
        if not missing:
            return
        ctx = ctx_map.get(caller.relpath)
        snippet = ctx.source_line(site.line) if ctx else ""
        yield Finding(
            rule=self.id,
            path=caller.relpath,
            line=site.line,
            col=site.col,
            message=(
                f"{caller.qual}() calls {callee.cls}."
                f"{site.name}() without holding "
                f"`with {recv_base}.{missing[0]}:`; the *_locked "
                "contract is caller-holds-lock (declared via "
                f"@guarded_by on {callee.cls})"
            ),
            snippet=snippet,
            related=(
                (
                    callee.relpath,
                    callee.line,
                    f"callee {callee.qual}() defined here "
                    f"(requires {', '.join(required)})",
                ),
            ),
        )


# ---------------------------------------------------------------------------
# CONC004: blocking calls reachable from dispatcher callbacks
# ---------------------------------------------------------------------------


@rule
class Conc004BlockingReachability:
    id = "CONC004"
    doc = (
        "no blocking call (time.sleep, socket/select waits, os.fsync, "
        "subprocess) transitively reachable from a dispatcher handler "
        "callback (handle_*/on_*/serve_*, incl. on_idle/serve_wave); "
        "a blocked dispatch thread stalls every instance behind it"
    )

    def check_program(
        self, index, ctx_map: Dict[str, FileContext]
    ) -> Iterator[Finding]:
        graph = _graph_for(ctx_map)
        entries = [
            key
            for key in sorted(graph.nodes)
            if _is_handler_name(graph.nodes[key].name)
            and (
                graph.nodes[key].in_plane
                or graph.nodes[key].in_transport
            )
        ]
        if not entries:
            return
        # BFS over the call graph from every entry at once; parent
        # pointers reconstruct the shortest call chain per node
        dist: Dict[Tuple[str, str], int] = {}
        parent: Dict[
            Tuple[str, str], Optional[Tuple[Tuple[str, str], CallSite]]
        ] = {}
        work: List[Tuple[str, str]] = []
        for e in entries:
            dist[e] = 0
            parent[e] = None
            work.append(e)
        qi = 0
        while qi < len(work):
            key = work[qi]
            qi += 1
            node = graph.nodes[key]
            for site in node.calls:
                targets, _exact = resolve_call(graph, node, site)
                for tkey in targets:
                    if tkey in dist:
                        continue
                    dist[tkey] = dist[key] + 1
                    parent[tkey] = (key, site)
                    work.append(tkey)
        seen_sites: Set[Tuple[str, int]] = set()
        for key in sorted(dist, key=lambda k: (dist[k], k)):
            node = graph.nodes[key]
            if not (node.in_plane or node.in_transport):
                continue
            for b in node.blocking:
                if dist[key] == 0 and b.conc002_vocab:
                    continue  # CONC002's depth-0 report
                site_id = (node.relpath, b.line)
                if site_id in seen_sites:
                    continue
                seen_sites.add(site_id)
                chain = self._chain(graph, parent, key)
                entry = graph.nodes[chain[0][0]] if chain else node
                ctx = ctx_map.get(node.relpath)
                snippet = ctx.source_line(b.line) if ctx else ""
                related = []
                for hop_key, hop_site in chain:
                    hop = graph.nodes[hop_key]
                    related.append(
                        (
                            hop.relpath,
                            hop_site.line,
                            f"{hop.qual}() calls "
                            f"{hop_site.name}() here",
                        )
                    )
                related.append(
                    (
                        node.relpath,
                        node.line,
                        f"{node.qual}() contains the blocking call",
                    )
                )
                yield Finding(
                    rule=self.id,
                    path=node.relpath,
                    line=b.line,
                    col=b.col,
                    message=(
                        f"blocking {b.what} is reachable from "
                        f"dispatcher callback {entry.qual}() "
                        f"({dist[key]} call(s) deep) and stalls the "
                        "dispatch thread; move it off the handler "
                        "path or defer it past the dispatch turn"
                    ),
                    snippet=snippet,
                    related=tuple(related),
                )

    @staticmethod
    def _chain(
        graph: CallGraph,
        parent: Dict,
        key: Tuple[str, str],
    ) -> List[Tuple[Tuple[str, str], CallSite]]:
        """Call-site hops entry -> ... -> key, in call order."""
        hops: List[Tuple[Tuple[str, str], CallSite]] = []
        cur = key
        while True:
            p = parent.get(cur)
            if p is None:
                break
            hops.append(p)
            cur = p[0]
        hops.reverse()
        return hops


# ---------------------------------------------------------------------------
# DET007: interprocedural entropy taint into the determinism plane
# ---------------------------------------------------------------------------


def _entropy_call_dotted(dotted: Optional[str]) -> bool:
    if dotted is None:
        return False
    return (
        dotted in _DET001_EXACT
        or dotted.split(".")[0] in _DET001_MODULES
    )


class _TaintScan:
    """Per-function local-taint walk shared by the summary fixpoint
    and the finding pass.  ``summaries`` maps node key ->
    returns_entropy; ``provenance`` (finding pass only) records where
    each tainted name's entropy came from."""

    def __init__(
        self,
        graph: CallGraph,
        ctx: FileContext,
        fnode: FuncNode,
        summaries: Dict[Tuple[str, str], bool],
        sanctioned_lines: FrozenSet[int],
    ) -> None:
        self.graph = graph
        self.ctx = ctx
        self.fnode = fnode
        self.summaries = summaries
        self.sanctioned = sanctioned_lines
        self.tainted: Set[str] = set()
        self.provenance: Dict[str, Tuple[str, int, str]] = {}
        self.returns_entropy = False
        self.sinks: List[Tuple[ast.AST, str, Tuple[str, int, str]]] = []

    def _call_is_entropy(
        self, call: ast.Call
    ) -> Optional[Tuple[str, int, str]]:
        """(path, line, what) of the entropy origin, or None."""
        if call.lineno in self.sanctioned:
            return None
        dotted = self.ctx.resolve(call.func)
        if _entropy_call_dotted(dotted):
            return (
                self.fnode.relpath,
                call.lineno,
                f"entropy source {dotted}() called here",
            )
        # a call to an entropy-returning function (exact or UNIQUE
        # name match: ambiguity must not spread taint)
        fn = call.func
        if isinstance(fn, ast.Name):
            name, recv = fn.id, None
        elif isinstance(fn, ast.Attribute):
            name, recv = fn.attr, _dotted_expr(fn.value)
        else:
            return None
        site = CallSite(
            name=name,
            recv=recv,
            dotted=dotted,
            line=call.lineno,
            col=call.col_offset,
            held=frozenset(),
            node=call,
        )
        targets, exact = resolve_call(self.graph, self.fnode, site)
        if not exact and len(targets) != 1:
            return None
        for tkey in targets[:1]:
            if self.summaries.get(tkey):
                tnode = self.graph.nodes[tkey]
                return (
                    tnode.relpath,
                    tnode.line,
                    f"{tnode.qual}() returns an entropy-derived "
                    "value (defined here)",
                )
        return None

    def _expr_taint(
        self, expr: ast.AST
    ) -> Optional[Tuple[str, int, str]]:
        for n in ast.walk(expr):
            if isinstance(n, ast.Call):
                origin = self._call_is_entropy(n)
                if origin is not None:
                    return origin
            elif isinstance(n, ast.Name) and n.id in self.tainted:
                return self.provenance.get(
                    n.id,
                    (self.fnode.relpath, getattr(n, "lineno", 0),
                     f"tainted local {n.id!r}"),
                )
        return None

    def _assign(
        self, targets: List[ast.AST], value: ast.AST
    ) -> None:
        origin = self._expr_taint(value)
        for t in targets:
            if isinstance(t, ast.Name):
                if origin is not None:
                    self.tainted.add(t.id)
                    self.provenance[t.id] = origin
                else:
                    self.tainted.discard(t.id)
                    self.provenance.pop(t.id, None)
            elif (
                origin is not None
                and isinstance(t, ast.Attribute)
                and isinstance(t.value, ast.Name)
                and t.value.id == "self"
            ):
                self.sinks.append((t, t.attr, origin))

    def _check_call_args(self, call: ast.Call) -> None:
        fn = call.func
        if isinstance(fn, ast.Name):
            name, recv = fn.id, None
        elif isinstance(fn, ast.Attribute):
            name, recv = fn.attr, _dotted_expr(fn.value)
        else:
            return
        args = list(call.args) + [kw.value for kw in call.keywords]
        tainted_origin = None
        for a in args:
            tainted_origin = self._expr_taint(a)
            if tainted_origin is not None:
                break
        if tainted_origin is None:
            return
        site = CallSite(
            name=name,
            recv=recv,
            dotted=self.ctx.resolve(fn),
            line=call.lineno,
            col=call.col_offset,
            held=frozenset(),
            node=call,
        )
        targets, exact = resolve_call(self.graph, self.fnode, site)
        if not exact and len(targets) != 1:
            return
        for tkey in targets[:1]:
            tnode = self.graph.nodes[tkey]
            if tnode.in_plane:
                self.sinks.append(
                    (call, f"{tnode.qual}()", tainted_origin)
                )

    def run(self) -> None:
        def visit(node: ast.AST) -> None:
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                return  # nested defs scanned as their own nodes
            if isinstance(node, ast.Assign):
                for child in ast.walk(node.value):
                    if isinstance(child, ast.Call):
                        self._check_call_args(child)
                self._assign(node.targets, node.value)
                return
            if isinstance(node, ast.AnnAssign) and node.value is not None:
                self._assign([node.target], node.value)
                return
            if isinstance(node, ast.AugAssign):
                origin = self._expr_taint(node.value)
                if (
                    origin is not None
                    and isinstance(node.target, ast.Attribute)
                    and isinstance(node.target.value, ast.Name)
                    and node.target.value.id == "self"
                ):
                    self.sinks.append(
                        (node.target, node.target.attr, origin)
                    )
                return
            if isinstance(node, ast.Return) and node.value is not None:
                if self._expr_taint(node.value) is not None:
                    self.returns_entropy = True
            if isinstance(node, ast.Call):
                self._check_call_args(node)
            for child in ast.iter_child_nodes(node):
                visit(child)

        for stmt in self.fnode.fn.body:
            visit(stmt)


_DETERMINISM_MODULE_SUFFIX = "utils/determinism.py"


@rule
class Det007EntropyTaintFlow:
    id = "DET007"
    doc = (
        "no value derived from a non-utils.determinism randomness or "
        "wall-clock source (directly or through any chain of "
        "returning functions) may be stored into determinism-plane "
        "instance state or passed into a determinism-plane function"
    )

    def check_program(
        self, index, ctx_map: Dict[str, FileContext]
    ) -> Iterator[Finding]:
        graph = _graph_for(ctx_map)
        sanctioned = self._sanctioned_lines(ctx_map)
        summaries = self._summaries(graph, ctx_map, sanctioned)
        for key in sorted(graph.nodes):
            fnode = graph.nodes[key]
            if not fnode.in_plane:
                continue
            ctx = ctx_map.get(fnode.relpath)
            if ctx is None:
                continue
            scan = _TaintScan(
                graph,
                ctx,
                fnode,
                summaries,
                sanctioned.get(fnode.relpath, frozenset()),
            )
            scan.run()
            for node, what, origin in scan.sinks:
                is_attr = isinstance(node, ast.Attribute)
                if is_attr:
                    msg = (
                        f"{fnode.qual}() stores an entropy-derived "
                        f"value into self.{what}; determinism-plane "
                        "state must come from seeded inputs (route "
                        "sanctioned entropy through "
                        "utils.determinism)"
                    )
                else:
                    msg = (
                        f"{fnode.qual}() passes an entropy-derived "
                        f"value into determinism-plane {what}; "
                        "seed it via utils.determinism instead"
                    )
                yield Finding(
                    rule=self.id,
                    path=fnode.relpath,
                    line=node.lineno,
                    col=node.col_offset,
                    message=msg,
                    snippet=ctx.source_line(node.lineno),
                    related=(origin,),
                )

    @staticmethod
    def _sanctioned_lines(
        ctx_map: Dict[str, FileContext]
    ) -> Dict[str, FrozenSet[int]]:
        """Lines whose entropy is pragma-sanctioned (a justified
        allow[DET001] or allow[DET007], line or file scope) do not
        seed taint: the pragma already owns the exception."""
        out: Dict[str, FrozenSet[int]] = {}
        for relpath, ctx in ctx_map.items():
            p = parse_pragmas(ctx)
            if p.file_allows & {"DET001", "DET007"}:
                out[relpath] = frozenset(
                    range(1, len(ctx.lines) + 1)
                )
                continue
            lines = {
                ln
                for ln, rules_ in p.line_allows.items()
                if rules_ & {"DET001", "DET007"}
            }
            if lines:
                out[relpath] = frozenset(lines)
        return out

    @staticmethod
    def _summaries(
        graph: CallGraph,
        ctx_map: Dict[str, FileContext],
        sanctioned: Dict[str, FrozenSet[int]],
    ) -> Dict[Tuple[str, str], bool]:
        """returns-entropy per node, to fixpoint.  utils.determinism
        defs are forced non-entropy: that module IS the sanctioned
        doorway (seeded rngs derived from os entropy at the
        operator's explicit request)."""
        summaries: Dict[Tuple[str, str], bool] = {
            key: False for key in graph.nodes
        }
        for _round in range(12):
            changed = False
            for key in sorted(graph.nodes):
                if summaries[key]:
                    continue
                fnode = graph.nodes[key]
                if fnode.relpath.endswith(
                    _DETERMINISM_MODULE_SUFFIX
                ):
                    continue
                ctx = ctx_map.get(fnode.relpath)
                if ctx is None:
                    continue
                scan = _TaintScan(
                    graph,
                    ctx,
                    fnode,
                    summaries,
                    sanctioned.get(fnode.relpath, frozenset()),
                )
                scan.run()
                if scan.returns_entropy:
                    summaries[key] = True
                    changed = True
            if not changed:
                break
        return summaries


__all__ = [
    "CallGraph",
    "CallSite",
    "Conc003CallerHoldsLock",
    "Conc004BlockingReachability",
    "Det007EntropyTaintFlow",
    "FuncNode",
    "build_callgraph",
    "resolve_call",
]
