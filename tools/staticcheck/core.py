"""staticcheck engine: findings, pragmas, baseline, rule registry.

Design (mirrors go vet / staticcheck-style gates, stdlib-only):

- A *rule* is a class with an ``id``, a ``doc`` line and either a
  per-file ``check(ctx)`` generator or a whole-program
  ``check_program(index, ctx_map)`` generator yielding Findings; it
  registers itself via the ``@rule`` decorator
  (tools/staticcheck/rules.py and tools/staticcheck/registry_rules.py
  hold the catalog; tools/staticcheck/program.py builds the
  cross-module index the program rules run over).
- *Pragmas* suppress findings at the source: a trailing
  ``staticcheck: allow[<RULE>] <why>`` comment suppresses that RULE
  on that line; ``staticcheck: allow-file[<RULE>] <why>`` (its own
  line) suppresses the rule for the whole file.  A pragma WITHOUT a
  justification is itself a finding (PRAGMA001) and suppresses
  nothing — every sanctioned exception must say why.  Audit mode
  (``--audit-pragmas``) additionally re-runs all rules UNSUPPRESSED
  and reports every pragma that no longer suppresses anything
  (PRAGMA002) plus any growth of the pragma population past the
  budget recorded in the baseline file (PRAGMA003).
- The *baseline* (tools/staticcheck/baseline.json) grandfathers known
  findings so the gate can land before the tree is fully clean.  Keys
  are (rule, path, source-line-text) — stable across unrelated line
  drift.  The merged tree's baseline is EMPTY: every finding is fixed
  or pragma'd.  The same file carries ``pragma_budget``, the audit
  cap on the tree's pragma count.
- Scoping is path-derived: FileContext computes ``in_plane`` (any of
  protocol/, core/, ops/ in the path — the determinism plane) and
  ``in_transport``; each rule reads the flags it cares about.  The
  fixture corpus under tests/staticcheck_fixtures/ reuses exactly this
  mechanism by nesting fixtures in protocol/ / transport/ dirs; tree
  walks skip that corpus (it is test DATA, scanned only when targeted
  directly).
"""

from __future__ import annotations

import ast
import dataclasses
import json
import pathlib
import re
from typing import Dict, Iterable, Iterator, List, Optional, Tuple

from tools.lintcommon import REPO_ROOT, rel_posix, walk_python_files

BASELINE_PATH = pathlib.Path(__file__).resolve().parent / "baseline.json"

# directories (path segments) that define the analysis scopes
PLANE_DIRS = frozenset(("protocol", "core", "ops"))
TRANSPORT_DIRS = frozenset(("transport",))

_PRAGMA_RE = re.compile(
    r"#\s*staticcheck:\s*(allow|allow-file)\[([A-Za-z0-9_,\s]+)\]\s*(.*)$"
)


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at a source location."""

    rule: str
    path: str  # repo-relative posix path
    line: int  # 1-based
    col: int  # 0-based (ast convention)
    message: str
    snippet: str = ""  # stripped source line: the baseline key part
    # interprocedural findings carry their evidence chain: (path,
    # line, note) triples rendered as SARIF relatedLocations, so a
    # CONC003/CONC004 hit is debuggable from the report alone.  NOT
    # part of the baseline key (chains drift with unrelated edits).
    related: Tuple[Tuple[str, int, str], ...] = ()

    @property
    def key(self) -> str:
        return f"{self.rule}|{self.path}|{self.snippet}"

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"

    def to_json(self) -> Dict[str, object]:
        return dataclasses.asdict(self)


class FileContext:
    """Everything a rule needs about one file: source, AST, scope
    flags, and import-alias resolution."""

    def __init__(
        self, path: pathlib.Path, root: pathlib.Path = REPO_ROOT
    ) -> None:
        self.path = path
        self.relpath = rel_posix(path, root)
        self.text = path.read_text(encoding="utf-8")
        self.lines = self.text.splitlines()
        self.tree = ast.parse(self.text, filename=self.relpath)
        ppath = pathlib.PurePosixPath(self.relpath)
        parts = frozenset(ppath.parts)
        self.in_transport = bool(parts & TRANSPORT_DIRS)
        # WAN emulation modules live under transport/ but are part of
        # the determinism plane: every delay/loss/straggler draw must
        # come through utils.determinism (byte-identical replay for a
        # fixed seed), so DET rules gate transport files whose stem is
        # ``wan`` or ``wan_*`` exactly like protocol/core/ops code
        wan_stem = ppath.stem == "wan" or ppath.stem.startswith("wan_")
        self.in_plane = bool(parts & PLANE_DIRS) or (
            self.in_transport and wan_stem
        )
        self._aliases = _import_aliases(self.tree)

    def resolve(self, node: ast.AST) -> Optional[str]:
        """Dotted name a Name/Attribute refers to, through import
        aliases: ``_secrets.token_bytes`` -> ``secrets.token_bytes``,
        ``monotonic`` (from time import monotonic) ->
        ``time.monotonic``.  None for anything unresolvable."""
        if isinstance(node, ast.Name):
            return self._aliases.get(node.id)
        if isinstance(node, ast.Attribute):
            base = self.resolve(node.value)
            if base is None and isinstance(node.value, ast.Name):
                base = self._aliases.get(node.value.id)
            if base is not None:
                return f"{base}.{node.attr}"
        return None

    def source_line(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def finding(
        self, rule_id: str, node: ast.AST, message: str
    ) -> Finding:
        line = getattr(node, "lineno", 1)
        return Finding(
            rule=rule_id,
            path=self.relpath,
            line=line,
            col=getattr(node, "col_offset", 0),
            message=message,
            snippet=self.source_line(line),
        )


def _import_aliases(tree: ast.AST) -> Dict[str, str]:
    """local name -> dotted origin, for imports anywhere in the file
    (function-local imports are the codebase's lazy-import idiom)."""
    out: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                out[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0]
                )
        elif isinstance(node, ast.ImportFrom) and node.module:
            for a in node.names:
                out[a.asname or a.name] = f"{node.module}.{a.name}"
    return out


# ---------------------------------------------------------------------------
# rule registry
# ---------------------------------------------------------------------------

_RULES: Dict[str, object] = {}


def rule(cls):
    """Class decorator: instantiate + register a rule by its ``id``."""
    inst = cls()
    if inst.id in _RULES:
        raise ValueError(f"duplicate rule id {inst.id!r}")
    _RULES[inst.id] = inst
    return cls


def registered_rules() -> Dict[str, object]:
    return dict(_RULES)


# ---------------------------------------------------------------------------
# pragmas
# ---------------------------------------------------------------------------


class Pragmas:
    """Per-file suppression state parsed from source comments."""

    def __init__(
        self,
        line_allows: Dict[int, frozenset],
        file_allows: frozenset,
        bad: List[Finding],
        entries: Optional[List[Tuple[int, str, frozenset]]] = None,
    ) -> None:
        self.line_allows = line_allows
        self.file_allows = file_allows
        self.bad = bad  # PRAGMA001 findings (missing justification)
        # every well-formed pragma as (line, kind, rules): the audit
        # mode's raw material (PRAGMA002/PRAGMA003)
        self.entries = entries if entries is not None else []

    def suppresses(self, f: Finding) -> bool:
        if f.rule in self.file_allows:
            return True
        return f.rule in self.line_allows.get(f.line, frozenset())


def parse_pragmas(ctx: FileContext) -> Pragmas:
    line_allows: Dict[int, frozenset] = {}
    file_allows: set = set()
    bad: List[Finding] = []
    entries: List[Tuple[int, str, frozenset]] = []
    for i, line in enumerate(ctx.lines, 1):
        m = _PRAGMA_RE.search(line)
        if m is None:
            continue
        kind, rules_s, justification = m.groups()
        rules = frozenset(
            r.strip() for r in rules_s.split(",") if r.strip()
        )
        if not justification.strip():
            bad.append(
                Finding(
                    rule="PRAGMA001",
                    path=ctx.relpath,
                    line=i,
                    col=line.index("#"),
                    message=(
                        f"pragma allow[{rules_s}] has no justification; "
                        "it suppresses nothing"
                    ),
                    snippet=line.strip(),
                )
            )
            continue
        entries.append((i, kind, rules))
        if kind == "allow-file":
            file_allows |= rules
        else:
            line_allows[i] = line_allows.get(i, frozenset()) | rules
    return Pragmas(line_allows, frozenset(file_allows), bad, entries)


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------


def load_baseline(path: pathlib.Path = BASELINE_PATH) -> Dict[str, int]:
    """key -> grandfathered count; empty when absent."""
    if not path.exists():
        return {}
    data = json.loads(path.read_text(encoding="utf-8"))
    return {str(k): int(v) for k, v in data.get("findings", {}).items()}


def load_pragma_budget(
    path: pathlib.Path = BASELINE_PATH,
) -> Optional[int]:
    """The audit cap on the tree's pragma count; None = no cap
    recorded (audit then only checks staleness)."""
    if not path.exists():
        return None
    data = json.loads(path.read_text(encoding="utf-8"))
    budget = data.get("pragma_budget")
    return int(budget) if budget is not None else None


def write_baseline(
    findings: Iterable[Finding], path: pathlib.Path = BASELINE_PATH
) -> None:
    counts: Dict[str, int] = {}
    for f in findings:
        counts[f.key] = counts.get(f.key, 0) + 1
    path.write_text(
        json.dumps(
            {"version": 1, "findings": dict(sorted(counts.items()))},
            indent=2,
        )
        + "\n",
        encoding="utf-8",
    )


def split_baselined(
    findings: List[Finding], baseline: Dict[str, int]
) -> Tuple[List[Finding], List[Finding]]:
    """(fresh, grandfathered): each baseline entry absorbs at most its
    recorded count, so NEW copies of an old finding still gate."""
    budget = dict(baseline)
    fresh: List[Finding] = []
    old: List[Finding] = []
    for f in findings:
        if budget.get(f.key, 0) > 0:
            budget[f.key] -= 1
            old.append(f)
        else:
            fresh.append(f)
    return fresh, old


# ---------------------------------------------------------------------------
# runner
# ---------------------------------------------------------------------------


FIXTURE_DIR_NAME = "staticcheck_fixtures"


def _load_contexts(
    paths: Iterable[pathlib.Path], root: pathlib.Path
) -> Tuple[List[FileContext], List[Finding], int]:
    """(parsed contexts, PARSE findings, files seen).  Tree walks skip
    the fixture corpus — it is test DATA full of deliberate findings —
    unless a target points inside it."""
    ctxs: List[FileContext] = []
    parse_findings: List[Finding] = []
    n_files = 0
    seen: set = set()
    for target in paths:
        include_fixtures = FIXTURE_DIR_NAME in target.parts
        for py in walk_python_files(target):
            if (
                not include_fixtures
                and FIXTURE_DIR_NAME in py.parts
            ):
                continue
            key = str(py.resolve())
            if key in seen:
                continue
            seen.add(key)
            n_files += 1
            try:
                ctxs.append(FileContext(py, root))
            except SyntaxError as e:
                # the format gate owns syntax; surface it here too so
                # a staticcheck run never crashes on a broken file
                parse_findings.append(
                    Finding(
                        rule="PARSE",
                        path=rel_posix(py, root),
                        line=e.lineno or 1,
                        col=e.offset or 0,
                        message=f"does not parse: {e.msg}",
                    )
                )
    return ctxs, parse_findings, n_files


def _run_rules(
    ctxs: List[FileContext],
    root: pathlib.Path,
    rule_ids: Optional[Iterable[str]] = None,
) -> List[Finding]:
    """Every raw (UNsuppressed, non-pragma) finding: per-file rules
    over each context plus registry rules over the two-pass index."""
    from tools.staticcheck.program import build_index

    wanted = set(rule_ids) if rule_ids is not None else None
    out: List[Finding] = []
    for ctx in ctxs:
        for rid, r in _RULES.items():
            if wanted is not None and rid not in wanted:
                continue
            check = getattr(r, "check", None)
            if check is not None:
                out.extend(check(ctx))
    ctx_map = {ctx.relpath: ctx for ctx in ctxs}
    index = build_index(ctxs, root)
    for rid, r in _RULES.items():
        if wanted is not None and rid not in wanted:
            continue
        check_program = getattr(r, "check_program", None)
        if check_program is not None:
            out.extend(check_program(index, ctx_map))
    return out


def _suppress(
    findings: List[Finding], pragmas_by_path: Dict[str, Pragmas]
) -> List[Finding]:
    out: List[Finding] = []
    for f in findings:
        p = pragmas_by_path.get(f.path)
        if p is not None and p.suppresses(f):
            continue
        out.append(f)
    return out


def audit_pragmas(
    raw: List[Finding],
    pragmas_by_path: Dict[str, Pragmas],
    ctx_map: Dict[str, FileContext],
    budget: Optional[int],
) -> List[Finding]:
    """PRAGMA002 for every pragma that suppresses nothing in the raw
    (unsuppressed) findings; PRAGMA003 for every pragma past the
    population budget, counted in (path, line) order — a
    deterministic anchor for the overflow, not an attribution of
    which pragma was added last (the message carries the count and
    the budget; the fix is to shed any pragma or bump the budget in
    review)."""
    by_file_rules: Dict[str, set] = {}
    by_line_rules: Dict[Tuple[str, int], set] = {}
    for f in raw:
        by_file_rules.setdefault(f.path, set()).add(f.rule)
        by_line_rules.setdefault((f.path, f.line), set()).add(f.rule)
    out: List[Finding] = []
    all_entries: List[Tuple[str, int, str, frozenset]] = []
    for path in sorted(pragmas_by_path):
        for line, kind, rules in pragmas_by_path[path].entries:
            all_entries.append((path, line, kind, rules))
    for path, line, kind, rules in all_entries:
        if kind == "allow-file":
            live = by_file_rules.get(path, set())
        else:
            live = by_line_rules.get((path, line), set())
        stale = sorted(rules - live)
        if stale:
            ctx = ctx_map.get(path)
            out.append(
                Finding(
                    rule="PRAGMA002",
                    path=path,
                    line=line,
                    col=0,
                    message=(
                        f"stale pragma: {kind}[{','.join(stale)}] "
                        "suppresses nothing here any more; delete it "
                        "(or fix the rule scope it expected)"
                    ),
                    snippet=ctx.source_line(line) if ctx else "",
                )
            )
    if budget is not None and len(all_entries) > budget:
        for path, line, kind, rules in all_entries[budget:]:
            ctx = ctx_map.get(path)
            out.append(
                Finding(
                    rule="PRAGMA003",
                    path=path,
                    line=line,
                    col=0,
                    message=(
                        f"pragma population {len(all_entries)} "
                        f"exceeds the audited budget {budget} "
                        "(tools/staticcheck/baseline.json "
                        "pragma_budget); fix the finding instead, or "
                        "raise the budget deliberately in review"
                    ),
                    snippet=ctx.source_line(line) if ctx else "",
                )
            )
    return out


def check_file(
    path: pathlib.Path,
    root: pathlib.Path = REPO_ROOT,
    rule_ids: Optional[Iterable[str]] = None,
) -> List[Finding]:
    """All (pragma-filtered) findings for one file, line-ordered.
    Registry rules see a single-file index, so self-contained fixture
    registries gate here too."""
    findings, _n = check_paths([path], root, rule_ids)
    findings.sort(key=lambda f: (f.line, f.col, f.rule))
    return findings


def check_paths(
    paths: Iterable[pathlib.Path],
    root: pathlib.Path = REPO_ROOT,
    rule_ids: Optional[Iterable[str]] = None,
    audit: bool = False,
    pragma_budget: Optional[int] = None,
) -> Tuple[List[Finding], int]:
    """(findings, files_scanned) across every .py under ``paths``.

    Pass 1 parses every file and builds the cross-module registry
    index; pass 2 runs the per-file and whole-program rules, then
    applies pragma suppression.  ``audit=True`` additionally reports
    stale pragmas (PRAGMA002) and budget overruns (PRAGMA003)."""
    ctxs, parse_findings, n_files = _load_contexts(paths, root)
    pragmas_by_path = {
        ctx.relpath: parse_pragmas(ctx) for ctx in ctxs
    }
    raw = _run_rules(ctxs, root, rule_ids)
    findings: List[Finding] = list(parse_findings)
    for p in pragmas_by_path.values():
        findings.extend(p.bad)
    findings.extend(_suppress(raw, pragmas_by_path))
    if audit:
        ctx_map = {ctx.relpath: ctx for ctx in ctxs}
        # staleness is judged against EVERY rule's raw findings even
        # when --rules narrowed the report — otherwise a subset run
        # declares every other rule's pragmas stale
        raw_all = (
            raw if rule_ids is None else _run_rules(ctxs, root, None)
        )
        findings.extend(
            audit_pragmas(
                raw_all, pragmas_by_path, ctx_map, pragma_budget
            )
        )
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings, n_files


def _finding_iter(findings: List[Finding]) -> Iterator[str]:
    for f in findings:
        yield f.render()


__all__ = [
    "BASELINE_PATH",
    "FIXTURE_DIR_NAME",
    "FileContext",
    "Finding",
    "Pragmas",
    "audit_pragmas",
    "check_file",
    "check_paths",
    "load_baseline",
    "load_pragma_budget",
    "parse_pragmas",
    "registered_rules",
    "rule",
    "split_baselined",
    "write_baseline",
]
