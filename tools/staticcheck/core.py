"""staticcheck engine: findings, pragmas, baseline, rule registry.

Design (mirrors go vet / staticcheck-style gates, stdlib-only):

- A *rule* is a class with an ``id``, a ``doc`` line and a
  ``check(ctx)`` generator yielding Findings; it registers itself via
  the ``@rule`` decorator (tools/staticcheck/rules.py holds the
  catalog).
- *Pragmas* suppress findings at the source: a trailing
  ``# staticcheck: allow[RULE] justification`` suppresses that RULE on
  that line; ``# staticcheck: allow-file[RULE] justification`` (its
  own line) suppresses the rule for the whole file.  A pragma WITHOUT
  a justification is itself a finding (PRAGMA001) and suppresses
  nothing — every sanctioned exception must say why.
- The *baseline* (tools/staticcheck/baseline.json) grandfathers known
  findings so the gate can land before the tree is fully clean.  Keys
  are (rule, path, source-line-text) — stable across unrelated line
  drift.  The merged tree's baseline is EMPTY: every finding is fixed
  or pragma'd.
- Scoping is path-derived: FileContext computes ``in_plane`` (any of
  protocol/, core/, ops/ in the path — the determinism plane) and
  ``in_transport``; each rule reads the flags it cares about.  The
  fixture corpus under tests/staticcheck_fixtures/ reuses exactly this
  mechanism by nesting fixtures in protocol/ / transport/ dirs.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import pathlib
import re
from typing import Dict, Iterable, Iterator, List, Optional, Tuple

from tools.lintcommon import REPO_ROOT, rel_posix, walk_python_files

BASELINE_PATH = pathlib.Path(__file__).resolve().parent / "baseline.json"

# directories (path segments) that define the analysis scopes
PLANE_DIRS = frozenset(("protocol", "core", "ops"))
TRANSPORT_DIRS = frozenset(("transport",))

_PRAGMA_RE = re.compile(
    r"#\s*staticcheck:\s*(allow|allow-file)\[([A-Za-z0-9_,\s]+)\]\s*(.*)$"
)


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at a source location."""

    rule: str
    path: str  # repo-relative posix path
    line: int  # 1-based
    col: int  # 0-based (ast convention)
    message: str
    snippet: str = ""  # stripped source line: the baseline key part

    @property
    def key(self) -> str:
        return f"{self.rule}|{self.path}|{self.snippet}"

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"

    def to_json(self) -> Dict[str, object]:
        return dataclasses.asdict(self)


class FileContext:
    """Everything a rule needs about one file: source, AST, scope
    flags, and import-alias resolution."""

    def __init__(
        self, path: pathlib.Path, root: pathlib.Path = REPO_ROOT
    ) -> None:
        self.path = path
        self.relpath = rel_posix(path, root)
        self.text = path.read_text(encoding="utf-8")
        self.lines = self.text.splitlines()
        self.tree = ast.parse(self.text, filename=self.relpath)
        parts = frozenset(pathlib.PurePosixPath(self.relpath).parts)
        self.in_plane = bool(parts & PLANE_DIRS)
        self.in_transport = bool(parts & TRANSPORT_DIRS)
        self._aliases = _import_aliases(self.tree)

    def resolve(self, node: ast.AST) -> Optional[str]:
        """Dotted name a Name/Attribute refers to, through import
        aliases: ``_secrets.token_bytes`` -> ``secrets.token_bytes``,
        ``monotonic`` (from time import monotonic) ->
        ``time.monotonic``.  None for anything unresolvable."""
        if isinstance(node, ast.Name):
            return self._aliases.get(node.id)
        if isinstance(node, ast.Attribute):
            base = self.resolve(node.value)
            if base is None and isinstance(node.value, ast.Name):
                base = self._aliases.get(node.value.id)
            if base is not None:
                return f"{base}.{node.attr}"
        return None

    def source_line(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def finding(
        self, rule_id: str, node: ast.AST, message: str
    ) -> Finding:
        line = getattr(node, "lineno", 1)
        return Finding(
            rule=rule_id,
            path=self.relpath,
            line=line,
            col=getattr(node, "col_offset", 0),
            message=message,
            snippet=self.source_line(line),
        )


def _import_aliases(tree: ast.AST) -> Dict[str, str]:
    """local name -> dotted origin, for imports anywhere in the file
    (function-local imports are the codebase's lazy-import idiom)."""
    out: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                out[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0]
                )
        elif isinstance(node, ast.ImportFrom) and node.module:
            for a in node.names:
                out[a.asname or a.name] = f"{node.module}.{a.name}"
    return out


# ---------------------------------------------------------------------------
# rule registry
# ---------------------------------------------------------------------------

_RULES: Dict[str, object] = {}


def rule(cls):
    """Class decorator: instantiate + register a rule by its ``id``."""
    inst = cls()
    if inst.id in _RULES:
        raise ValueError(f"duplicate rule id {inst.id!r}")
    _RULES[inst.id] = inst
    return cls


def registered_rules() -> Dict[str, object]:
    return dict(_RULES)


# ---------------------------------------------------------------------------
# pragmas
# ---------------------------------------------------------------------------


class Pragmas:
    """Per-file suppression state parsed from source comments."""

    def __init__(
        self,
        line_allows: Dict[int, frozenset],
        file_allows: frozenset,
        bad: List[Finding],
    ) -> None:
        self.line_allows = line_allows
        self.file_allows = file_allows
        self.bad = bad  # PRAGMA001 findings (missing justification)

    def suppresses(self, f: Finding) -> bool:
        if f.rule in self.file_allows:
            return True
        return f.rule in self.line_allows.get(f.line, frozenset())


def parse_pragmas(ctx: FileContext) -> Pragmas:
    line_allows: Dict[int, frozenset] = {}
    file_allows: set = set()
    bad: List[Finding] = []
    for i, line in enumerate(ctx.lines, 1):
        m = _PRAGMA_RE.search(line)
        if m is None:
            continue
        kind, rules_s, justification = m.groups()
        rules = frozenset(
            r.strip() for r in rules_s.split(",") if r.strip()
        )
        if not justification.strip():
            bad.append(
                Finding(
                    rule="PRAGMA001",
                    path=ctx.relpath,
                    line=i,
                    col=line.index("#"),
                    message=(
                        f"pragma allow[{rules_s}] has no justification; "
                        "it suppresses nothing"
                    ),
                    snippet=line.strip(),
                )
            )
            continue
        if kind == "allow-file":
            file_allows |= rules
        else:
            line_allows[i] = line_allows.get(i, frozenset()) | rules
    return Pragmas(line_allows, frozenset(file_allows), bad)


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------


def load_baseline(path: pathlib.Path = BASELINE_PATH) -> Dict[str, int]:
    """key -> grandfathered count; empty when absent."""
    if not path.exists():
        return {}
    data = json.loads(path.read_text(encoding="utf-8"))
    return {str(k): int(v) for k, v in data.get("findings", {}).items()}


def write_baseline(
    findings: Iterable[Finding], path: pathlib.Path = BASELINE_PATH
) -> None:
    counts: Dict[str, int] = {}
    for f in findings:
        counts[f.key] = counts.get(f.key, 0) + 1
    path.write_text(
        json.dumps(
            {"version": 1, "findings": dict(sorted(counts.items()))},
            indent=2,
        )
        + "\n",
        encoding="utf-8",
    )


def split_baselined(
    findings: List[Finding], baseline: Dict[str, int]
) -> Tuple[List[Finding], List[Finding]]:
    """(fresh, grandfathered): each baseline entry absorbs at most its
    recorded count, so NEW copies of an old finding still gate."""
    budget = dict(baseline)
    fresh: List[Finding] = []
    old: List[Finding] = []
    for f in findings:
        if budget.get(f.key, 0) > 0:
            budget[f.key] -= 1
            old.append(f)
        else:
            fresh.append(f)
    return fresh, old


# ---------------------------------------------------------------------------
# runner
# ---------------------------------------------------------------------------


def check_file(
    path: pathlib.Path,
    root: pathlib.Path = REPO_ROOT,
    rule_ids: Optional[Iterable[str]] = None,
) -> List[Finding]:
    """All (pragma-filtered) findings for one file, line-ordered."""
    try:
        ctx = FileContext(path, root)
    except SyntaxError as e:
        # the format gate owns syntax; surface it here too so a
        # standalone staticcheck run never crashes on a broken file
        return [
            Finding(
                rule="PARSE",
                path=rel_posix(path, root),
                line=e.lineno or 1,
                col=e.offset or 0,
                message=f"does not parse: {e.msg}",
            )
        ]
    pragmas = parse_pragmas(ctx)
    wanted = set(rule_ids) if rule_ids is not None else None
    out: List[Finding] = list(pragmas.bad)
    for rid, r in _RULES.items():
        if wanted is not None and rid not in wanted:
            continue
        for f in r.check(ctx):
            if not pragmas.suppresses(f):
                out.append(f)
    out.sort(key=lambda f: (f.line, f.col, f.rule))
    return out


def check_paths(
    paths: Iterable[pathlib.Path],
    root: pathlib.Path = REPO_ROOT,
    rule_ids: Optional[Iterable[str]] = None,
) -> Tuple[List[Finding], int]:
    """(findings, files_scanned) across every .py under ``paths``."""
    findings: List[Finding] = []
    n_files = 0
    for target in paths:
        for py in walk_python_files(target):
            n_files += 1
            findings.extend(check_file(py, root, rule_ids))
    return findings, n_files


def _finding_iter(findings: List[Finding]) -> Iterator[str]:
    for f in findings:
        yield f.render()


__all__ = [
    "BASELINE_PATH",
    "FileContext",
    "Finding",
    "Pragmas",
    "check_file",
    "check_paths",
    "load_baseline",
    "parse_pragmas",
    "registered_rules",
    "rule",
    "split_baselined",
    "write_baseline",
]
