"""Pass 2 of the whole-program analyzer: the registry rule catalog.

These rules run over the cross-module ProgramIndex
(tools/staticcheck/program.py) rather than one file's AST, encoding
the contracts PRs 7-13 enforced by reviewer convention:

- WIRE001   payload-kind / pb-extension-tag registry integrity
- SCHEMA001 Metrics counters vs snapshot schema vs golden exposition
- ARM001    Config arm flags vs wave entry points vs perfgate
            fingerprint keys vs equivalence-test pins
- VERIFY001 (per-file) network-origin frames must pass verify_wire*
            before any handler dispatch

Deterministic, statically-checkable protocol state is the precondition
for a replayable finality argument (PAPERS.md arxiv 2512.09409) and
for batching crypto behind service seams (arxiv 2502.03247): each rule
turns one of those reviewed-by-hand contracts into a machine gate.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from tools.staticcheck.core import FileContext, Finding, rule
from tools.staticcheck.program import (
    PB_RESERVED_TAGS,
    ProgramIndex,
    gated_closure,
    is_wave_entry_name,
)


def _program_finding(
    rule_id: str, relpath: str, line: int, message: str, ctx_map
) -> Finding:
    snippet = ""
    ctx = ctx_map.get(relpath)
    if ctx is not None:
        snippet = ctx.source_line(line)
    return Finding(
        rule=rule_id,
        path=relpath,
        line=line,
        col=0,
        message=message,
        snippet=snippet,
    )


# ---------------------------------------------------------------------------
# WIRE001: the payload-kind / pb-tag registry
# ---------------------------------------------------------------------------
#
# transport/message.py's ``_KIND_*`` discriminants and
# transport/pb_adapter.py's ``_PB_TAG_*`` extension slots were
# extended by hand four times (PRs 1/8/12); each extension had to
# re-establish, in review, that the number was fresh, that encode and
# parse both learned the kind, and that the pb adapter either carries
# it or deliberately does not (batch/bundle kinds are capabilities
# beyond the reference's oneof and stay native-only, with a pragma
# saying so).  This rule is that checklist, mechanized.

@rule
class Wire001Registry:
    id = "WIRE001"
    doc = (
        "payload kinds (_KIND_*) must carry unique numbers and "
        "encode+parse coverage, and a pb-adapter slot or a justified "
        "pragma; pb extension tags (_PB_TAG_*) must be unique, "
        "referenced, and off the reserved proto3 envelope numbers"
    )

    def check_program(
        self, index: ProgramIndex, ctx_map
    ) -> Iterator[Finding]:
        pb_by_stem: Dict[str, List] = {}
        for p in index.pb_modules:
            for stem in p.import_stems:
                pb_by_stem.setdefault(stem, []).append(p)
        for w in index.wire_modules:
            seen_value: Dict[int, str] = {}
            paired = pb_by_stem.get(w.stem, [])
            pb_kind_refs: Set[str] = set()
            for p in paired:
                pb_kind_refs |= p.kind_refs
            for name in sorted(w.kinds):
                value, line = w.kinds[name]
                other = seen_value.get(value)
                if other is not None:
                    yield _program_finding(
                        self.id, w.relpath, line,
                        f"{name} reuses payload kind number {value} "
                        f"(already taken by {other}); every oneof "
                        "discriminant must be unique",
                        ctx_map,
                    )
                else:
                    seen_value[value] = name
                if name not in w.encode_covered:
                    yield _program_finding(
                        self.id, w.relpath, line,
                        f"{name} has no encode branch (never returned "
                        "by a payload encoder); an unencodable kind "
                        "is registry dead weight or a missed case",
                        ctx_map,
                    )
                if name not in w.parse_covered:
                    yield _program_finding(
                        self.id, w.relpath, line,
                        f"{name} has no parse branch (never compared "
                        "against an incoming kind); frames of this "
                        "kind would be rejected as unknown",
                        ctx_map,
                    )
                if paired and name not in pb_kind_refs:
                    yield _program_finding(
                        self.id, w.relpath, line,
                        f"{name} has no pb-adapter slot; give it an "
                        "extension tag or pragma why the capability "
                        "stays native-only",
                        ctx_map,
                    )
        for p in index.pb_modules:
            seen_tag: Dict[int, str] = {}
            for name in sorted(p.tags):
                value, line = p.tags[name]
                other = seen_tag.get(value)
                if other is not None:
                    yield _program_finding(
                        self.id, p.relpath, line,
                        f"{name} reuses pb extension tag {value} "
                        f"(already taken by {other}); a stock decoder "
                        "cannot tell the two fields apart",
                        ctx_map,
                    )
                else:
                    seen_tag[value] = name
                if value in PB_RESERVED_TAGS:
                    yield _program_finding(
                        self.id, p.relpath, line,
                        f"{name}={value} collides with the reference "
                        "envelope's reserved tags 1-4 (signature, "
                        "timestamp, rbc, bba)",
                        ctx_map,
                    )
                if name not in p.tag_refs:
                    yield _program_finding(
                        self.id, p.relpath, line,
                        f"{name} is declared but never used by the "
                        "adapter's encode/decode paths (orphaned tag)",
                        ctx_map,
                    )


# ---------------------------------------------------------------------------
# SCHEMA001: the metrics snapshot / exposition schema
# ---------------------------------------------------------------------------
#
# The "zeroed-key snapshot schema rule" was restated in three PR
# descriptions (9/10/13): every counter the code increments must
# appear in Metrics.snapshot() (always present, zeroed without a
# provider) and its family must exist in the golden /metrics
# exposition — otherwise dashboards silently lose a signal, or the
# golden scrape test pins families the code no longer emits.

@rule
class Schema001MetricsContract:
    id = "SCHEMA001"
    doc = (
        "every Metrics counter must be incremented somewhere and read "
        "into the snapshot schema; every exposition family must exist "
        "in the golden scrape, and vice versa — no silent drift"
    )

    def check_program(
        self, index: ProgramIndex, ctx_map
    ) -> Iterator[Finding]:
        for m in index.metrics_modules:
            for attr in sorted(m.counters):
                line = m.counters[attr]
                # never-incremented is a claim about the CONSUMERS,
                # who live in other files: a lone-real-file scan has
                # no standing to convict (lint the tree)
                if (
                    not index.partial_scan
                    and index.counter_incs.get(attr, 0) == 0
                ):
                    yield _program_finding(
                        self.id, m.relpath, line,
                        f"counter {m.cls_name}.{attr} is declared but "
                        "never incremented anywhere in the scanned "
                        "tree (dead metric, or its call sites were "
                        "lost in a refactor)",
                        ctx_map,
                    )
                if attr not in m.snapshot_reads:
                    yield _program_finding(
                        self.id, m.relpath, line,
                        f"counter {m.cls_name}.{attr} never reaches "
                        "snapshot() (read self.X.value into the "
                        "schema, zeroed-key, so scrapers see it)",
                        ctx_map,
                    )
        if index.golden_families is None:
            return
        emitted: Set[str] = set()
        for e in index.expo_modules:
            emitted |= e.family_candidates
            for fam in sorted(e.families):
                if fam not in index.golden_families:
                    yield _program_finding(
                        self.id, e.relpath, e.families[fam],
                        f"exposition family {fam!r} is missing from "
                        "the golden exposition; regenerate "
                        "tests/golden/metrics_exposition.txt",
                        ctx_map,
                    )
        if index.expo_modules:
            anchor = index.expo_modules[0]
            for fam in sorted(index.golden_families - emitted):
                yield _program_finding(
                    self.id, anchor.relpath, 1,
                    f"golden exposition family {fam!r} is no longer "
                    "emitted by any scanned exposition; regenerate "
                    "the golden or restore the family",
                    ctx_map,
                )


# ---------------------------------------------------------------------------
# ARM001: arm-flag / wave-entry-point parity
# ---------------------------------------------------------------------------
#
# Every columnar seam (PRs 7/9/10/13) keeps its scalar arm live behind
# a Config flag for byte-equivalence, and perfgate fingerprints must
# key on the flag so a mode flip never gates against the other mode's
# trend.  ``ARM_FLAGS`` in config.py is the declared registry (the
# @guarded_by of the both-arms discipline); this rule cross-checks it
# against the Config fields, the fingerprint keys, the equivalence
# tests' explicit pins, and the wave entry points' reachability from
# flag-reading modules.

@rule
class Arm001WaveArmParity:
    id = "ARM001"
    doc = (
        "every ARM_FLAGS entry must be a bool or int Config field, "
        "read by the package, pinned explicitly in tests (>= 2 "
        "distinct values for int arms), and a perfgate fingerprint "
        "key; every *_wave entry point must be reachable from an "
        "arm-flag-reading module (the scalar-arm gate)"
    )

    def check_program(
        self, index: ProgramIndex, ctx_map
    ) -> Iterator[Finding]:
        if not index.config_modules:
            return
        for c in index.config_modules:
            for flag in c.arm_flags:
                is_int_arm = flag in c.int_fields
                if flag not in c.bool_fields and not is_int_arm:
                    yield _program_finding(
                        self.id, c.relpath, c.arm_flags_line,
                        f"ARM_FLAGS entry {flag!r} is not a bool or "
                        "int Config field (stale registry entry)",
                        ctx_map,
                    )
                    continue
                line = (
                    c.int_fields[flag]
                    if is_int_arm
                    else c.bool_fields[flag]
                )
                # never-read convicts the consumers; a lone-real-file
                # scan has none in view (same rule as SCHEMA001)
                if (
                    not index.partial_scan
                    and flag not in index.attr_reads
                    and flag not in index.kw_names
                ):
                    yield _program_finding(
                        self.id, c.relpath, line,
                        f"arm flag {flag!r} is never read anywhere "
                        "in the scanned tree (dead arm; the scalar "
                        "twin cannot be reachable)",
                        ctx_map,
                    )
                if (
                    index.fingerprint_keys is not None
                    and flag not in index.fingerprint_keys
                ):
                    yield _program_finding(
                        self.id, c.relpath, line,
                        f"arm flag {flag!r} is not a perfgate "
                        "fingerprint key; a mode flip would gate "
                        "against the other mode's trend records",
                        ctx_map,
                    )
                if index.test_flag_pins is None:
                    continue
                if is_int_arm:
                    # an int arm (Config.lanes) needs the baseline
                    # value AND a fast-path value pinned, or the
                    # byte-equivalence comparison never runs
                    if len(index.int_flag_pin_values(flag)) < 2:
                        yield _program_finding(
                            self.id, c.relpath, line,
                            f"int arm flag {flag!r} pins fewer than "
                            "2 distinct values in tests; both the "
                            "byte-equivalence baseline and the fast "
                            "arm need explicit coverage",
                            ctx_map,
                        )
                elif not index.flag_pinned_in_tests(flag):
                    yield _program_finding(
                        self.id, c.relpath, line,
                        f"arm flag {flag!r} is never pinned "
                        "(flag=True/False) in tests; the scalar "
                        "byte-equivalence arm has no coverage",
                        ctx_map,
                    )
        if index.partial_scan:
            return  # the gating modules live in other files
        gated = gated_closure(index)
        for name, relpath, line in index.wave_defs:
            parts = relpath.split("/")
            if "protocol" not in parts and "transport" not in parts:
                continue
            if relpath not in gated:
                yield _program_finding(
                    self.id, relpath, line,
                    f"wave entry point {name}() is not reachable "
                    "from any arm-flag-reading module; a wave seam "
                    "without a Config-flag gate has no live scalar "
                    "twin to byte-compare against",
                    ctx_map,
                )


# ---------------------------------------------------------------------------
# VERIFY001: network-origin frames verify before dispatch (per-file)
# ---------------------------------------------------------------------------
#
# Every inbound path does decode -> verify_wire* -> handler dispatch;
# the MAC check is the only thing standing between a Byzantine peer's
# bytes and the protocol state machines.  This light intraprocedural
# taint walk flags any function in transport/ that decodes a wire
# frame (decode_frame / decode_frame_shared / decode_message /
# decode_pb_message) and lets a value derived from it reach a handler
# sink (serve_request / serve_wave / handle_message) without an
# intervening verify_wire* call over it.  Sanctioned unverified paths
# (none today) would carry allow[VERIFY001] pragmas with
# justifications.

_VERIFY001_SOURCES = frozenset(
    (
        "decode_frame",
        "decode_frame_shared",
        "decode_message",
        "decode_pb_message",
    )
)
_VERIFY001_SINKS = frozenset(
    ("serve_request", "serve_wave", "handle_message")
)


def _call_name(node: ast.Call) -> Optional[str]:
    if isinstance(node.func, ast.Name):
        return node.func.id
    if isinstance(node.func, ast.Attribute):
        return node.func.attr
    return None


def _names_of(node: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _target_names(target: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for n in ast.walk(target):
        if isinstance(n, ast.Name):
            out.add(n.id)
    return out


@rule
class Verify001FrameTaint:
    id = "VERIFY001"
    doc = (
        "in transport/ code, a decoded wire frame must pass "
        "verify_wire* before reaching a handler dispatch "
        "(serve_request/serve_wave/handle_message) in the same "
        "function"
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if not ctx.in_transport:
            return
        for fn in ast.walk(ctx.tree):
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield from self._walk_function(ctx, fn)

    def _walk_function(
        self, ctx: FileContext, fn: ast.AST
    ) -> Iterator[Finding]:
        tainted: Set[str] = set()
        findings: List[Finding] = []

        def contains_source(node: ast.AST) -> bool:
            for n in ast.walk(node):
                if isinstance(n, ast.Call):
                    name = _call_name(n)
                    if name in _VERIFY001_SOURCES:
                        return True
            return False

        def is_tainted(node: ast.AST) -> bool:
            return bool(_names_of(node) & tainted)

        def handle_call(node: ast.Call) -> None:
            name = _call_name(node)
            if name is None:
                return
            if name.startswith("verify"):
                # verification sanitizes every name it was handed
                for arg in list(node.args) + [
                    kw.value for kw in node.keywords
                ]:
                    tainted.difference_update(_names_of(arg))
                return
            if name == "append":
                # L.append(tainted) taints the collection
                val_tainted = any(
                    is_tainted(a) for a in node.args
                )
                if val_tainted and isinstance(
                    node.func, ast.Attribute
                ):
                    tainted.update(_names_of(node.func.value))
                return
            if name in _VERIFY001_SINKS:
                for arg in list(node.args) + [
                    kw.value for kw in node.keywords
                ]:
                    if is_tainted(arg):
                        findings.append(
                            ctx.finding(
                                self.id,
                                node,
                                f"{name}() dispatches a frame "
                                "decoded in this function with no "
                                "verify_wire* between decode and "
                                "dispatch; Byzantine bytes reach "
                                "the protocol plane unauthenticated",
                            )
                        )
                        break

        def assign(targets: List[ast.AST], value: ast.AST) -> None:
            make_tainted = contains_source(value) or is_tainted(value)
            for t in targets:
                names = _target_names(t)
                if make_tainted:
                    tainted.update(names)
                else:
                    tainted.difference_update(names)

        def visit(node: ast.AST) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return  # nested functions analyzed on their own
            if isinstance(node, ast.Assign):
                # calls inside the value run first (decode itself)
                for child in ast.walk(node.value):
                    if isinstance(child, ast.Call):
                        handle_call(child)
                assign(node.targets, node.value)
                return
            if isinstance(node, ast.AnnAssign) and node.value is not None:
                assign([node.target], node.value)
                return
            if isinstance(node, (ast.For, ast.AsyncFor)):
                assign([node.target], node.iter)
                for child in node.body + node.orelse:
                    visit(child)
                return
            if isinstance(node, ast.Call):
                handle_call(node)
                for child in ast.iter_child_nodes(node):
                    visit(child)
                return
            for child in ast.iter_child_nodes(node):
                visit(child)

        for stmt in fn.body:
            visit(stmt)
        yield from findings


__all__ = [
    "Arm001WaveArmParity",
    "Schema001MetricsContract",
    "Verify001FrameTaint",
    "Wire001Registry",
]
