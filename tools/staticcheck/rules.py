"""The rule catalog: this codebase's real hazard classes.

Every rule documents WHAT it flags, WHERE (scope flags), and WHY the
hazard can fork a replay or a ledger.  Adding a rule = subclass with
``id``/``doc``/``check(ctx)`` + the ``@rule`` decorator + a fixture
pair under tests/staticcheck_fixtures/ (see docs/ARCHITECTURE.md).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from tools.staticcheck.core import FileContext, Finding, rule

# ---------------------------------------------------------------------------
# DET001: wall clocks & unseeded randomness in the determinism plane
# ---------------------------------------------------------------------------

# Calls whose RESULT depends on when/where the process runs.  Any of
# these reachable from protocol/core/ops state can diverge two replays
# of the same seeded schedule.
_DET001_EXACT = frozenset(
    (
        "time.time",
        "time.time_ns",
        "time.monotonic",
        "time.monotonic_ns",
        "time.perf_counter",
        "time.perf_counter_ns",
        "os.urandom",
        "uuid.uuid4",
        "random.SystemRandom",
        "random.random",
        "random.randint",
        "random.randrange",
        "random.randbytes",
        "random.getrandbits",
        "random.choice",
        "random.choices",
        "random.shuffle",
        "random.sample",
        "random.uniform",
    )
)
# every attribute of these modules is OS entropy by definition
_DET001_MODULES = frozenset(("secrets",))


@rule
class Det001WallClockAndEntropy:
    id = "DET001"
    doc = (
        "no wall clock (time.time/monotonic/perf_counter) or unseeded "
        "randomness (random module fns, SystemRandom, secrets, "
        "os.urandom) in the determinism plane (protocol/, core/, ops/)"
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if not ctx.in_plane:
            return
        call_of: Dict[int, ast.Call] = {}
        for n in ast.walk(ctx.tree):
            if isinstance(n, ast.Call):
                call_of[id(n.func)] = n
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.Attribute, ast.Name)):
                continue
            # only flag loads (uses), not the import statements
            if not isinstance(getattr(node, "ctx", None), ast.Load):
                continue
            dotted = ctx.resolve(node)
            if dotted is None:
                continue
            mod = dotted.split(".")[0]
            if dotted in _DET001_EXACT or mod in _DET001_MODULES:
                # a bare module Name ("time") is not itself a use; the
                # full dotted Attribute node is what gets reported
                if isinstance(node, ast.Name) and dotted == mod:
                    continue
                yield ctx.finding(
                    self.id,
                    node,
                    f"{dotted} is nondeterministic; the determinism "
                    "plane must derive all state from seeded inputs "
                    "(route sanctioned entropy through "
                    "utils.determinism or pragma with justification)",
                )
            elif dotted == "random.Random":
                # seeded Random(x) is fine; zero-arg Random() seeds
                # from the OS
                call = call_of.get(id(node))
                if call is not None and not (call.args or call.keywords):
                    yield ctx.finding(
                        self.id,
                        node,
                        "random.Random() without a seed draws OS "
                        "entropy; pass an explicit seed",
                    )


# ---------------------------------------------------------------------------
# DET002: hash-order iteration over sets in the determinism plane
# ---------------------------------------------------------------------------
#
# CPython set/frozenset iteration order for str/bytes elements depends
# on PYTHONHASHSEED; two honest nodes iterating "the same" set can walk
# it in different orders and serialize different bytes.  (dicts are
# insertion-ordered since 3.7, so dict iteration is deterministic
# whenever insertions are — sets are the hazard.)  The rule flags
# iteration sinks (for/comprehension iterables, list()/tuple()/
# max()/min() args) whose expression is statically known to be a set:
# a set()/frozenset() call, a set literal/comprehension, or a local /
# self attribute assigned or annotated as one.  Wrap the boundary in
# sorted() — or restructure to an insertion-ordered dict — to fix.

_SET_ANNOTATIONS = frozenset(("set", "frozenset", "Set", "FrozenSet"))
_ORDER_SINK_CALLS = frozenset(("list", "tuple", "max", "min"))


def _is_set_expr(
    node: ast.AST, local_sets: Set[str], attr_sets: Set[str]
) -> bool:
    if isinstance(node, (ast.Set, ast.SetComp)):
        return True
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
        if node.func.id in ("set", "frozenset"):
            return True
    if isinstance(node, ast.Name):
        return node.id in local_sets
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr in attr_sets
    return False


def _annotation_is_set(ann: ast.AST) -> bool:
    # matches set / Set[...] / typing.Set[...] / frozenset
    if isinstance(ann, ast.Subscript):
        ann = ann.value
    if isinstance(ann, ast.Attribute):
        return ann.attr in _SET_ANNOTATIONS
    return isinstance(ann, ast.Name) and ann.id in _SET_ANNOTATIONS


def _collect_set_names(
    root: ast.AST,
) -> Tuple[Set[str], Set[str]]:
    """(local names, self attributes) assigned/annotated as sets
    anywhere in ``root`` — one flat namespace per file is precise
    enough for this tree's naming discipline."""
    local_sets: Set[str] = set()
    attr_sets: Set[str] = set()

    def note_target(target: ast.AST, is_set: bool) -> None:
        if isinstance(target, ast.Name):
            (local_sets.add if is_set else local_sets.discard)(target.id)
        elif (
            isinstance(target, ast.Attribute)
            and isinstance(target.value, ast.Name)
            and target.value.id == "self"
        ):
            (attr_sets.add if is_set else attr_sets.discard)(target.attr)

    for node in ast.walk(root):
        if isinstance(node, ast.Assign):
            is_set = _is_set_expr(node.value, local_sets, attr_sets)
            for t in node.targets:
                note_target(t, is_set)
        elif isinstance(node, ast.AnnAssign):
            note_target(node.target, _annotation_is_set(node.annotation))
    return local_sets, attr_sets


@rule
class Det002SetIterationOrder:
    id = "DET002"
    doc = (
        "no iteration over unordered set/frozenset in the determinism "
        "plane where order can reach wire or ledger bytes; wrap the "
        "boundary in sorted()"
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if not ctx.in_plane:
            return
        local_sets, attr_sets = _collect_set_names(ctx.tree)

        def flag(expr: ast.AST, what: str) -> Optional[Finding]:
            if _is_set_expr(expr, local_sets, attr_sets):
                return ctx.finding(
                    self.id,
                    expr,
                    f"{what} iterates a set in hash order "
                    "(PYTHONHASHSEED-dependent); wrap in sorted() or "
                    "use an insertion-ordered dict",
                )
            return None

        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.For, ast.AsyncFor)):
                f = flag(node.iter, "for loop")
                if f:
                    yield f
            elif isinstance(
                node,
                (ast.ListComp, ast.SetComp, ast.GeneratorExp, ast.DictComp),
            ):
                for gen in node.generators:
                    f = flag(gen.iter, "comprehension")
                    if f:
                        yield f
            elif (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id in _ORDER_SINK_CALLS
                and len(node.args) == 1
            ):
                f = flag(node.args[0], f"{node.func.id}()")
                if f:
                    yield f


# ---------------------------------------------------------------------------
# DET003: crypto verify/decode must route through the hub's columnar seam
# ---------------------------------------------------------------------------
#
# The wave-columnar refactor (ISSUE 7) moved every protocol-plane
# batch-crypto execution behind CryptoHub: clients stage work and
# drain it into a HubWave's typed columns; ONE dispatch per work kind
# runs per flush.  A direct BatchCrypto verify/decode call from
# protocol/ code outside hub.py silently erodes that seam back to
# scalar per-instance dispatch — the exact regression the refactor
# removed (hub_dispatches_cluster 24-37/epoch -> O(work kinds)).
# The rule flags calls to the verify/decode surfaces of the crypto
# layer (merkle verify_branch/verify_batch, RS decode_batch/
# decode_recheck_batch, threshold-share verify_* — as methods or as
# from-imported ops functions) anywhere under protocol/ except
# hub.py itself.  Legitimate inline checks (RBC's single VAL-branch
# precheck; the lockstep spmd.py plane, which IS its own columnar
# batch layer and never touches the hub) carry allow[DET003] pragmas
# with justifications.

_DET003_CALLS = frozenset(
    (
        "verify_branch",
        "verify_batch",
        "decode_batch",
        "decode_recheck_batch",
        "verify_shares",
        "verify_share_groups",
        "verify_and_combine_share_groups",
        "verify_dec_shares",
    )
)
_DET003_EXEMPT_FILES = frozenset(("hub.py",))


@rule
class Det003HubColumnarSeam:
    id = "DET003"
    doc = (
        "no direct BatchCrypto verify/decode calls from protocol/ "
        "outside hub.py; stage the work and drain it through the "
        "CryptoHub wave (drain_pending) so it batches columnar"
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        parts = ctx.relpath.split("/")
        if "protocol" not in parts or parts[-1] in _DET003_EXEMPT_FILES:
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            name = None
            if isinstance(func, ast.Attribute):
                if func.attr in _DET003_CALLS:
                    name = func.attr
            elif isinstance(func, ast.Name):
                # from-imported ops function (ctx.resolve maps the
                # local name through import aliases)
                dotted = ctx.resolve(func)
                if (
                    dotted
                    and ".ops." in f".{dotted}"
                    and dotted.rsplit(".", 1)[-1] in _DET003_CALLS
                ):
                    name = dotted
            if name is not None:
                yield ctx.finding(
                    self.id,
                    node,
                    f"direct crypto dispatch {name}() bypasses the "
                    "hub's columnar seam; stage the work and offer it "
                    "via drain_pending(wave) instead",
                )


# ---------------------------------------------------------------------------
# DET004: protocol ingest must cross the wave-router seam per WAVE
# ---------------------------------------------------------------------------
#
# The wave-routed ingest refactor (ISSUE 10) moved the inbound handler
# boundary to wave granularity: transports hand a delivery wave's
# verified frames to the handler in ONE serve_wave call, and the
# WaveRouter makes one batch dispatch per message kind — replacing the
# per-payload HoneyBadger.handle_message -> ACS -> RBC/BBA chain that
# owned the transport stage share after PR 9.  A per-frame
# ``handler.serve_request(...)`` / ``x.handle_message(...)`` call from
# transport/ code silently erodes that seam back to one Python call
# chain per payload — the exact regression the router removed.  The
# sanctioned sites (the scalar byte-equivalence comparison arm behind
# Config.wave_routing=False, local self-delivery short-circuits, and
# the non-wave-handler fallbacks) carry allow[DET004] pragmas with
# justifications.

_DET004_CALLS = frozenset(("serve_request", "handle_message"))


@rule
class Det004WaveIngestSeam:
    id = "DET004"
    doc = (
        "no per-frame handler dispatch (serve_request/handle_message) "
        "from transport/ outside the wave-router seam; buffer the "
        "wave and hand it over in one serve_wave call"
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        parts = ctx.relpath.split("/")
        if "transport" not in parts:
            return
        for node in ast.walk(ctx.tree):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _DET004_CALLS
            ):
                yield ctx.finding(
                    self.id,
                    node,
                    f"per-frame {node.func.attr}() dispatch bypasses "
                    "the wave-router seam; buffer the wave and hand "
                    "it to the handler in one serve_wave call",
                )


# ---------------------------------------------------------------------------
# DET005: epoch-scoped code must resolve the roster through the
# roster-version accessor
# ---------------------------------------------------------------------------
#
# Dynamic membership (ISSUE 12) made the roster a VERSIONED value:
# every epoch resolves n/f/keys/membership through
# ``roster_for(epoch)`` / the epoch state's ``view``.  A direct read
# of the construction-time constants (``self.config.n``,
# ``self.config.f``, ``self.members``, ``self._member_set``,
# ``self.keys``) from code that handles a PARTICULAR epoch silently
# re-pins the roster to whatever was active at construction — correct
# right up until the first RECONFIG crosses, then a fork/liveness
# bug that only a roster-change schedule can catch.  The rule flags
# those reads inside any function that takes an epoch parameter, in
# the protocol files whose objects OUTLIVE epochs; per-epoch
# instances (ACS/RBC/BBA and their banks — constructed WITH a
# version's config) are exempt, as is the reshare plane itself.

_DET005_EXEMPT_FILES = frozenset(
    (
        "acs.py",  # per-epoch: constructed with the epoch's view
        "rbc.py",
        "bba.py",
        "echobank.py",
        "votebank.py",
        "hub.py",  # roster-agnostic batch executor (geometry rides
        # with each request)
        "spmd.py",  # lockstep executor: fixed-roster by definition
        "byzantine.py",  # adversary plane: lies are the point
        "reconfig.py",  # the accessor's own implementation layer
    )
)
_DET005_CONFIG_FIELDS = frozenset(("n", "f", "decryption_threshold"))
_DET005_SELF_ATTRS = frozenset(("members", "_member_set", "keys"))
# Lane shard-out (ISSUE 20) made the epoch frontier a PER-LANE value:
# code handed a lane index must resolve frontiers through the
# lane-indexed accessor (self.lanes[lane].epoch / the merged_*
# accessors), never the bare primary-lane attributes — a bare read is
# correct at lanes=1 and silently pins lane 0's frontier the moment a
# second lane exists.
_DET005_LANE_FRONTIER_ATTRS = frozenset(
    ("epoch", "settled_epoch", "committed_batches")
)


@rule
class Det005RosterVersionAccessor:
    id = "DET005"
    doc = (
        "epoch-scoped protocol code (functions taking an epoch "
        "parameter) must resolve n/f/keys/membership via "
        "roster_for(epoch) / the epoch state's view, not the "
        "construction-time self.config.n / self.members / self.keys; "
        "lane-scoped code (functions taking a lane parameter) must "
        "resolve frontiers via the lane-indexed accessor "
        "(self.lanes[lane] / merged_*), not the bare primary-lane "
        "self.epoch / self.settled_epoch / self.committed_batches"
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        parts = ctx.relpath.split("/")
        if "protocol" not in parts or parts[-1] in _DET005_EXEMPT_FILES:
            return
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            args = fn.args
            names = [
                a.arg
                for a in (
                    args.posonlyargs + args.args + args.kwonlyargs
                )
            ]
            if any("lane" in a for a in names):
                yield from self._check_lane_scoped(ctx, fn)
            if not any("epoch" in a for a in names):
                continue
            for node in ast.walk(fn):
                if not isinstance(node, ast.Attribute):
                    continue
                inner = node.value
                # self.config.n / self.config.f / ...
                if (
                    node.attr in _DET005_CONFIG_FIELDS
                    and isinstance(inner, ast.Attribute)
                    and inner.attr == "config"
                    and _self_attr(inner) == "config"
                ):
                    yield ctx.finding(
                        self.id,
                        node,
                        f"epoch-scoped {fn.name}() reads "
                        f"self.config.{node.attr}; resolve the "
                        "epoch's roster via roster_for(epoch)/"
                        "es.view instead",
                    )
                # self.members / self._member_set / self.keys
                elif _self_attr(node) in _DET005_SELF_ATTRS:
                    yield ctx.finding(
                        self.id,
                        node,
                        f"epoch-scoped {fn.name}() reads "
                        f"self.{node.attr} (the ACTIVE roster); "
                        "resolve the epoch's roster via "
                        "roster_for(epoch)/es.view instead",
                    )

    def _check_lane_scoped(
        self, ctx: FileContext, fn: ast.AST
    ) -> Iterator[Finding]:
        """Lane-scoped code reading the bare primary-lane frontier
        (Load contexts only: lane objects still initialize their own
        ``self.epoch``).  Constructors are exempt: an object built
        WITH a lane id IS that lane, and its __init__ legitimately
        wires/replays its own frontier — the hazard is cross-lane
        aggregation code handed a lane INDEX."""
        if getattr(fn, "name", "") == "__init__":
            return
        for node in ast.walk(fn):
            if (
                isinstance(node, ast.Attribute)
                and isinstance(node.ctx, ast.Load)
                and node.attr in _DET005_LANE_FRONTIER_ATTRS
                and _self_attr(node) is not None
            ):
                yield ctx.finding(
                    self.id,
                    node,
                    f"lane-scoped {fn.name}() reads "
                    f"self.{node.attr} (the PRIMARY lane's "
                    "frontier); resolve through the lane-indexed "
                    "accessor self.lanes[lane] / the merged_* "
                    "frontier accessors instead",
                )


# ---------------------------------------------------------------------------
# DET006: egress must cross the wave signer per WAVE
# ---------------------------------------------------------------------------
#
# The egress columnarization (ISSUE 13) moved the outbound signer
# boundary to wave granularity: a coalescer flush hands its whole wave
# of folded bundles to ONE ``Authenticator.sign_wire_wave`` call,
# which encodes each distinct payload body once (shared-prefix
# FrameEncodeMemo) and runs the wave's HMACs as one batched pass.  A
# per-frame ``sign_wire_many(...)`` / ``encode_message(...)`` call
# from protocol/ code or a transport send path silently erodes that
# seam back to one envelope encode + sign pass per post — the exact
# redundancy the wave signer removed.  The sanctioned sites (the
# scalar byte-equivalence comparison arm behind
# Config.egress_columnar=False and pre-pool boot traffic) carry
# allow[DET006] pragmas with justifications; transport/message.py is
# the codec itself and transport/base.py is the authenticator layer
# whose job IS the per-frame encode+sign primitives (the hub.py of
# this seam), so both are exempt.

_DET006_CALLS = frozenset(
    ("sign_wire_many", "encode_message", "sign_wire")
)
_DET006_EXEMPT_FILES = frozenset(("message.py", "base.py"))


@rule
class Det006EgressWaveSeam:
    id = "DET006"
    doc = (
        "no per-frame envelope encode+sign (sign_wire_many/"
        "encode_message) from protocol/ or transport send paths "
        "outside the wave signer; buffer the egress wave and sign it "
        "in one sign_wire_wave call"
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        parts = ctx.relpath.split("/")
        if (
            "transport" not in parts and "protocol" not in parts
        ) or parts[-1] in _DET006_EXEMPT_FILES:
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            name = None
            if isinstance(func, ast.Attribute):
                if func.attr in _DET006_CALLS:
                    name = func.attr
            elif isinstance(func, ast.Name):
                # from-imported codec function (ctx.resolve maps the
                # local name through import aliases)
                dotted = ctx.resolve(func)
                if (
                    dotted
                    and dotted.rsplit(".", 1)[-1] in _DET006_CALLS
                ):
                    name = func.id
            if name is not None:
                yield ctx.finding(
                    self.id,
                    node,
                    f"per-frame {name}() encode+sign bypasses the "
                    "wave signer seam; buffer the egress wave and "
                    "sign it in one sign_wire_wave call",
                )


# ---------------------------------------------------------------------------
# CONC001: lock discipline for @guarded_by-annotated attributes
# ---------------------------------------------------------------------------
#
# utils.determinism.guarded_by("_lock", "_attr", ...) declares which
# instance attributes a class's lock protects.  The rule statically
# requires every self._attr access OUTSIDE __init__ to sit lexically
# inside ``with self._lock:``.  Methods named ``*_locked`` are exempt
# by convention: their docstring contract is "caller holds the lock"
# (the annotation documents the boundary; the analyzer enforces it).

_CONC001_EXEMPT = frozenset(("__init__", "__del__"))


def _guarded_decls(cls: ast.ClassDef) -> Dict[str, str]:
    """attr -> lock from guarded_by decorators (string literals only:
    the declaration is meant to be statically readable)."""
    out: Dict[str, str] = {}
    for dec in cls.decorator_list:
        if not isinstance(dec, ast.Call):
            continue
        fn = dec.func
        name = fn.attr if isinstance(fn, ast.Attribute) else getattr(
            fn, "id", None
        )
        if name != "guarded_by":
            continue
        strs = [
            a.value
            for a in dec.args
            if isinstance(a, ast.Constant) and isinstance(a.value, str)
        ]
        if len(strs) >= 2:
            lock, attrs = strs[0], strs[1:]
            for a in attrs:
                out[a] = lock
    return out


def _self_attr(node: ast.AST) -> Optional[str]:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


@rule
class Conc001LockDiscipline:
    id = "CONC001"
    doc = (
        "attributes declared via @guarded_by('_lock', ...) may only be "
        "touched inside a matching `with self._lock:` block "
        "(methods named *_locked are caller-holds-lock by contract)"
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for cls in ast.walk(ctx.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            guarded = _guarded_decls(cls)
            if not guarded:
                continue
            for meth in cls.body:
                if not isinstance(
                    meth, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    continue
                if meth.name in _CONC001_EXEMPT or meth.name.endswith(
                    "_locked"
                ):
                    continue
                yield from self._check_method(ctx, cls, meth, guarded)

    def _check_method(
        self,
        ctx: FileContext,
        cls: ast.ClassDef,
        meth: ast.AST,
        guarded: Dict[str, str],
    ) -> Iterator[Finding]:
        held: List[str] = []
        findings: List[Finding] = []

        def visit(node: ast.AST) -> None:
            if isinstance(node, (ast.With, ast.AsyncWith)):
                acquired = []
                for item in node.items:
                    attr = _self_attr(item.context_expr)
                    if attr is not None:
                        acquired.append(attr)
                        held.append(attr)
                # the context expressions themselves are lock reads
                for child in node.body:
                    visit(child)
                for _ in acquired:
                    held.pop()
                return
            attr = _self_attr(node)
            if attr is not None and attr in guarded:
                lock = guarded[attr]
                if lock not in held:
                    findings.append(
                        ctx.finding(
                            self.id,
                            node,
                            f"{cls.name}.{meth.name} touches "
                            f"self.{attr} outside `with self.{lock}:` "
                            f"(declared guarded_by {lock!r})",
                        )
                    )
                return  # don't descend: self.X.y is one access
            for child in ast.iter_child_nodes(node):
                visit(child)

        for stmt in meth.body:
            visit(stmt)
        yield from findings


# ---------------------------------------------------------------------------
# CONC002: no blocking calls inside transport handler callbacks
# ---------------------------------------------------------------------------
#
# Handler callbacks (serve_request / handle_* / on_*) run on a
# transport's dispatch thread or inside the deterministic scheduler's
# turn; a time.sleep or raw socket wait there stalls every instance
# behind it (and, in the seeded scheduler, silently changes which
# interleavings are reachable).

_BLOCKING_METHOD_NAMES = frozenset(
    ("accept", "recv", "recvfrom", "recv_into", "sendall")
)
_HANDLER_PREFIXES = ("handle", "_handle", "on_", "_on_", "serve_")


def _is_handler_name(name: str) -> bool:
    return name == "serve_request" or name.startswith(_HANDLER_PREFIXES)


@rule
class Conc002BlockingInHandlers:
    id = "CONC002"
    doc = (
        "no blocking calls (time.sleep, socket accept/recv/sendall, "
        "select) inside transport/protocol handler callbacks"
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if not (ctx.in_transport or ctx.in_plane):
            return
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if not _is_handler_name(fn.name):
                continue
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                dotted = ctx.resolve(node.func)
                if dotted in ("time.sleep", "select.select") or (
                    dotted is not None
                    and dotted.startswith("socket.")
                ):
                    yield ctx.finding(
                        self.id,
                        node,
                        f"handler {fn.name} calls blocking {dotted}",
                    )
                elif (
                    dotted is None
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _BLOCKING_METHOD_NAMES
                ):
                    yield ctx.finding(
                        self.id,
                        node,
                        f"handler {fn.name} calls blocking "
                        f".{node.func.attr}()",
                    )


# ---------------------------------------------------------------------------
# ERR001: swallowed exceptions in protocol/transport code
# ---------------------------------------------------------------------------


@rule
class Err001SwallowedExceptions:
    id = "ERR001"
    doc = (
        "no bare `except:`; no `except Exception:` whose body only "
        "passes/continues (a silent swallow hides Byzantine-input "
        "bugs and liveness stalls)"
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if not (ctx.in_plane or ctx.in_transport):
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if node.type is None:
                yield ctx.finding(
                    self.id,
                    node,
                    "bare `except:` catches SystemExit/KeyboardInterrupt "
                    "too; name the exception",
                )
                continue
            name = (
                node.type.id
                if isinstance(node.type, ast.Name)
                else getattr(node.type, "attr", None)
            )
            if name in ("Exception", "BaseException") and all(
                isinstance(s, (ast.Pass, ast.Continue)) for s in node.body
            ):
                yield ctx.finding(
                    self.id,
                    node,
                    f"blanket `except {name}:` swallows the error "
                    "(body is only pass/continue); handle, log, or "
                    "narrow it",
                )


__all__ = [
    "Det001WallClockAndEntropy",
    "Det002SetIterationOrder",
    "Det003HubColumnarSeam",
    "Det005RosterVersionAccessor",
    "Conc001LockDiscipline",
    "Conc002BlockingInHandlers",
    "Err001SwallowedExceptions",
]
