"""staticcheck: stdlib-only AST static analysis for the determinism
plane (tools/staticcheck).

The value proposition of this stack is that seeded runs replay exactly
— ci.sh's race-analog tier depends on it, and CATCHUP/WAL recovery
depends on committed bytes being identical across nodes.  Nothing
enforced that invariant until this package: it is the lint-shaped gate
that keeps wall clocks, unseeded randomness, hash-order iteration,
lock-discipline violations and swallowed exceptions out of the code
paths where they can fork a ledger.

Layout:
  core.py   -- Finding/FileContext, pragma parsing, rule registry,
               baseline round-trip, the runner
  rules.py  -- the rule catalog (DET001/DET002/CONC001/CONC002/ERR001)
  __main__  -- CLI: ``python -m tools.staticcheck cleisthenes_tpu``

See docs/ARCHITECTURE.md "Determinism plane & static analysis" for
the plane definition, the rule catalog, and the pragma policy.
"""

from tools.staticcheck.core import (
    BASELINE_PATH,
    Finding,
    check_paths,
    load_baseline,
    registered_rules,
    split_baselined,
    write_baseline,
)
import tools.staticcheck.rules  # noqa: F401  (registers the catalog)

__all__ = [
    "BASELINE_PATH",
    "Finding",
    "check_paths",
    "load_baseline",
    "registered_rules",
    "split_baselined",
    "write_baseline",
]
