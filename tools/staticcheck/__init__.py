"""staticcheck: stdlib-only AST static analysis for the determinism
plane (tools/staticcheck).

The value proposition of this stack is that seeded runs replay exactly
— ci.sh's race-analog tier depends on it, and CATCHUP/WAL recovery
depends on committed bytes being identical across nodes.  Nothing
enforced that invariant until this package: it is the lint-shaped gate
that keeps wall clocks, unseeded randomness, hash-order iteration,
lock-discipline violations and swallowed exceptions out of the code
paths where they can fork a ledger.

Since ISSUE 14 the analyzer is a whole-program tool: pass 1 builds a
cross-module symbol/registry index (payload kinds and pb extension
tags, Metrics counters vs snapshot schema vs golden exposition,
Config arm flags vs wave entry points vs perfgate fingerprint keys),
pass 2 runs the per-file rules plus the registry rules
(WIRE001/SCHEMA001/ARM001/VERIFY001) over it, and an audit mode
machine-checks the pragma population (staleness + count budget).
ISSUE 17 adds pass 3: a def->call graph over every scanned file and
the interprocedural rules CONC003 (caller-holds lock discipline for
*_locked functions), CONC004 (blocking calls transitively reachable
from dispatcher callbacks) and DET007 (entropy taint flowing into
determinism-plane state) — with the runtime lock sanitizer
cleisthenes_tpu/utils/lockcheck.py as the dynamic twin over the same
``@guarded_by`` registry.

Layout:
  core.py           -- Finding/FileContext, pragma parsing + audit,
                       rule registry, baseline round-trip, the
                       multi-pass runner
  rules.py          -- the per-file catalog (DET001-DET006, CONC001/
                       CONC002, ERR001)
  program.py        -- pass 1: the cross-module registry index
  registry_rules.py -- pass 2: WIRE001/SCHEMA001/ARM001 (+ VERIFY001)
  callgraph.py      -- pass 3: the call graph + CONC003/CONC004/
                       DET007 (interprocedural rules)
  __main__          -- CLI: ``python -m tools.staticcheck
                       cleisthenes_tpu tools tests --audit-pragmas``

See docs/STATICCHECK.md for the full rule catalog, the pragma grammar
and the audit mode; docs/ARCHITECTURE.md "Determinism plane & static
analysis" for the plane definition.
"""

from tools.staticcheck.core import (
    BASELINE_PATH,
    Finding,
    check_paths,
    load_baseline,
    load_pragma_budget,
    registered_rules,
    split_baselined,
    write_baseline,
)
import tools.staticcheck.rules  # noqa: F401  (registers the catalog)
import tools.staticcheck.registry_rules  # noqa: F401  (registry rules)
import tools.staticcheck.callgraph  # noqa: F401  (pass-3 call-graph rules)

__all__ = [
    "BASELINE_PATH",
    "Finding",
    "check_paths",
    "load_baseline",
    "load_pragma_budget",
    "registered_rules",
    "split_baselined",
    "write_baseline",
]
