"""Pass 1 of the whole-program analyzer: the cross-module registry
index.

The per-file rules (tools/staticcheck/rules.py) see one AST at a time;
the invariants that actually broke ground in PRs 7-13 are CROSS-MODULE
contracts: payload kinds must carry encode+parse+pb coverage
(transport/message.py vs transport/pb_adapter.py), Metrics counters
must appear in the snapshot schema and the golden /metrics exposition,
Config arm flags must be perfgate fingerprint keys with a pinned
scalar arm in the equivalence tests, and every ``*_wave`` entry point
must sit behind an arm-flag gate.  This module builds the one index
those registry rules (tools/staticcheck/registry_rules.py) run over.

Role detection is STRUCTURAL, not path-hardcoded, so the fixture
corpus can stand up miniature registries:

- wire module    -- module-level ``_KIND_*`` int assignments
- pb adapter     -- module-level ``_PB_TAG_*`` int assignments;
                    paired to the wire module whose stem it imports
- metrics module -- a class with ``self.X = Counter()`` attributes
                    AND a ``snapshot`` method
- exposition     -- ``.family("name", ...)`` literal calls
- config module  -- ``class Config`` plus a module-level ``ARM_FLAGS``
                    declaration (the arm registry, analogous to
                    ``@guarded_by`` for CONC001)
- perfgate       -- a dict literal carrying a ``"fingerprint"`` key

Out-of-scan context is AUGMENTED from the repo root exactly when the
scanned registry is the real one (its path is not under a
``staticcheck_fixtures`` directory): the perfgate fingerprint keys
from ``tools/perfgate.py``, the arm-flag pins from ``tests/``, and the
golden exposition families from
``tests/golden/metrics_exposition.txt``.  A fixture tree provides its
own minis under its own root and gets the same treatment.
"""

from __future__ import annotations

import ast
import dataclasses
import pathlib
import re
from typing import Dict, List, Optional, Set, Tuple

from tools.lintcommon import rel_posix
from tools.staticcheck.core import FIXTURE_DIR_NAME

_KIND_RE = re.compile(r"^_KIND_[A-Z0-9_]+$")
_PB_TAG_RE = re.compile(r"^_PB_TAG_[A-Z0-9_]+$")
# proto3 envelope fields (signature=1, timestamp=2) + the reference
# oneof (rbc=3, bba=4): an extension tag landing on these corrupts
# stock-decoder interop
PB_RESERVED_TAGS = frozenset((1, 2, 3, 4))

_WAVE_SUFFIX = "_wave"
_BOOL_FLAG_PIN_RE = r"\b{flag}\s*=\s*(?:True|False)\b"
# int-valued arms (Config.lanes): the pin is a literal integer, and
# the rule wants the DISTINCT values (baseline=1 vs shard-out>1)
_INT_FLAG_PIN_RE = r"\b{flag}\s*=\s*(\d+)"


def is_fixture_path(relpath: str) -> bool:
    return FIXTURE_DIR_NAME in relpath.split("/")


@dataclasses.dataclass
class WireModule:
    """One payload-kind registry (transport/message.py shaped)."""

    relpath: str
    stem: str
    kinds: Dict[str, Tuple[int, int]]  # name -> (value, line)
    encode_covered: Set[str]  # _KIND_ names appearing in a return
    parse_covered: Set[str]  # _KIND_ names appearing in a comparison


@dataclasses.dataclass
class PbModule:
    """One pb extension-tag registry (transport/pb_adapter.py shaped)."""

    relpath: str
    tags: Dict[str, Tuple[int, int]]  # name -> (value, line)
    tag_refs: Set[str]  # _PB_TAG_ names loaded (used) anywhere
    kind_refs: Set[str]  # _KIND_ names loaded anywhere
    import_stems: Set[str]  # last components of from-import modules


@dataclasses.dataclass
class MetricsModule:
    """One metrics registry: Counter attrs + the snapshot schema."""

    relpath: str
    cls_name: str
    counters: Dict[str, int]  # attr -> declaration line
    snapshot_reads: Set[str]  # attrs read as self.X.value in snapshot


@dataclasses.dataclass
class ExpoModule:
    """One Prometheus exposition: .family("name", ...) literal calls.

    ``families`` is the PRECISE set (literal first args — the anchor
    for "missing from golden" findings); ``family_candidates`` adds
    every string that is the first element of a tuple literal, because
    the exposition drives family loops off tuple tables — an
    over-approximation that is only used to witness that a golden
    family is still emitted (recall side), never to accuse."""

    relpath: str
    families: Dict[str, int]  # family name -> first call line
    family_candidates: Set[str]


@dataclasses.dataclass
class ConfigModule:
    """One arm-flag registry: Config bool/int fields + ARM_FLAGS."""

    relpath: str
    bool_fields: Dict[str, int]  # field -> line
    int_fields: Dict[str, int]  # field -> line (int-valued arms)
    arm_flags: List[str]
    arm_flags_line: int


@dataclasses.dataclass
class ProgramIndex:
    """Everything pass 2's registry rules read."""

    wire_modules: List[WireModule]
    pb_modules: List[PbModule]
    metrics_modules: List[MetricsModule]
    expo_modules: List[ExpoModule]
    config_modules: List[ConfigModule]
    counter_incs: Dict[str, int]  # counter attr -> inc() sites seen
    attr_reads: Set[str]  # every Attribute attr loaded anywhere
    kw_names: Set[str]  # every keyword-argument name used anywhere
    defs: Dict[str, Set[str]]  # function/class name -> defining files
    refs: Dict[str, Set[str]]  # relpath -> names referenced there
    flag_reader_files: Set[str]  # files reading any declared arm flag
    wave_defs: List[Tuple[str, str, int]]  # (name, relpath, line)
    fingerprint_keys: Optional[Set[str]]  # None: no perfgate in sight
    golden_families: Optional[Set[str]]  # None: no golden in sight
    test_flag_pins: Optional[str]  # concatenated tests text, or None
    # True when the scan is a lone real (non-fixture) file: the
    # consumer universe is NOT in view, so absence-based accusations
    # ("never incremented", "never read", wave-unreachable) must not
    # convict — lint the tree for those.  Self-contained fixture
    # files keep the full rule set.
    partial_scan: bool = False

    def flag_pinned_in_tests(self, flag: str) -> bool:
        if self.test_flag_pins is None:
            return False
        return (
            re.search(
                _BOOL_FLAG_PIN_RE.format(flag=re.escape(flag)),
                self.test_flag_pins,
            )
            is not None
        )

    def int_flag_pin_values(self, flag: str) -> Set[int]:
        """Distinct integer literals tests pin the flag to.  An
        int-valued arm (Config.lanes) needs >= 2 of them: the
        byte-equivalence baseline value AND a shard-out value, or the
        fast arm has no equivalence coverage."""
        if self.test_flag_pins is None:
            return set()
        return {
            int(m)
            for m in re.findall(
                _INT_FLAG_PIN_RE.format(flag=re.escape(flag)),
                self.test_flag_pins,
            )
        }


def is_wave_entry_name(name: str) -> bool:
    return name.endswith(_WAVE_SUFFIX) and len(name) > len(_WAVE_SUFFIX)


# ---------------------------------------------------------------------------
# per-file extraction
# ---------------------------------------------------------------------------


def _module_int_consts(tree: ast.AST, pattern) -> Dict[str, Tuple[int, int]]:
    out: Dict[str, Tuple[int, int]] = {}
    for node in ast.iter_child_nodes(tree):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        tgt = node.targets[0]
        if not (isinstance(tgt, ast.Name) and pattern.match(tgt.id)):
            continue
        if isinstance(node.value, ast.Constant) and isinstance(
            node.value.value, int
        ):
            out[tgt.id] = (node.value.value, node.lineno)
    return out


def _names_in(node: ast.AST, pattern) -> Set[str]:
    return {
        n.id
        for n in ast.walk(node)
        if isinstance(n, ast.Name) and pattern.match(n.id)
    }


def _extract_wire(ctx) -> Optional[WireModule]:
    kinds = _module_int_consts(ctx.tree, _KIND_RE)
    if not kinds:
        return None
    encode_covered: Set[str] = set()
    parse_covered: Set[str] = set()
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Return) and node.value is not None:
            encode_covered |= _names_in(node.value, _KIND_RE)
        elif isinstance(node, ast.Compare):
            parse_covered |= _names_in(node, _KIND_RE)
    return WireModule(
        relpath=ctx.relpath,
        stem=pathlib.PurePosixPath(ctx.relpath).stem,
        kinds=kinds,
        encode_covered=encode_covered,
        parse_covered=parse_covered,
    )


def _extract_pb(ctx) -> Optional[PbModule]:
    tags = _module_int_consts(ctx.tree, _PB_TAG_RE)
    if not tags:
        return None
    tag_refs: Set[str] = set()
    kind_refs: Set[str] = set()
    import_stems: Set[str] = set()
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
            if _PB_TAG_RE.match(node.id):
                tag_refs.add(node.id)
            elif _KIND_RE.match(node.id):
                kind_refs.add(node.id)
        elif isinstance(node, ast.ImportFrom) and node.module:
            import_stems.add(node.module.rsplit(".", 1)[-1])
    return PbModule(
        relpath=ctx.relpath,
        tags=tags,
        tag_refs=tag_refs,
        kind_refs=kind_refs,
        import_stems=import_stems,
    )


def _self_attr_of(node: ast.AST) -> Optional[str]:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _extract_metrics(ctx) -> List[MetricsModule]:
    out: List[MetricsModule] = []
    for cls in ast.walk(ctx.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        counters: Dict[str, int] = {}
        snapshot_fn = None
        for meth in cls.body:
            if not isinstance(meth, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if meth.name == "snapshot":
                snapshot_fn = meth
            for node in ast.walk(meth):
                if (
                    isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)
                    and isinstance(node.value.func, ast.Name)
                    and node.value.func.id == "Counter"
                ):
                    for tgt in node.targets:
                        attr = _self_attr_of(tgt)
                        if attr is not None:
                            counters[attr] = node.lineno
        if not counters or snapshot_fn is None:
            continue
        reads: Set[str] = set()
        for node in ast.walk(snapshot_fn):
            # self.<attr>.value
            if isinstance(node, ast.Attribute) and node.attr == "value":
                inner = _self_attr_of(node.value)
                if inner is not None:
                    reads.add(inner)
        out.append(
            MetricsModule(
                relpath=ctx.relpath,
                cls_name=cls.name,
                counters=counters,
                snapshot_reads=reads,
            )
        )
    return out


def _extract_expo(ctx) -> Optional[ExpoModule]:
    families: Dict[str, int] = {}
    candidates: Set[str] = set()
    for node in ast.walk(ctx.tree):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "family"
            and node.args
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)
        ):
            families.setdefault(node.args[0].value, node.lineno)
        elif isinstance(node, ast.Tuple) and node.elts:
            first = node.elts[0]
            if isinstance(first, ast.Constant) and isinstance(
                first.value, str
            ):
                candidates.add(first.value)
    if not families:
        return None
    return ExpoModule(
        relpath=ctx.relpath,
        families=families,
        family_candidates=candidates | set(families),
    )


def _bool_annotation(ann: Optional[ast.AST]) -> bool:
    return isinstance(ann, ast.Name) and ann.id == "bool"


def _int_annotation(ann: Optional[ast.AST]) -> bool:
    return isinstance(ann, ast.Name) and ann.id == "int"


def _extract_config(ctx) -> Optional[ConfigModule]:
    cls = None
    for node in ast.iter_child_nodes(ctx.tree):
        if isinstance(node, ast.ClassDef) and node.name == "Config":
            cls = node
            break
    if cls is None:
        return None
    arm_flags: Optional[List[str]] = None
    arm_line = 0
    for node in ast.iter_child_nodes(ctx.tree):
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and node.targets[0].id == "ARM_FLAGS"
            and isinstance(node.value, (ast.Tuple, ast.List))
        ):
            arm_flags = [
                e.value
                for e in node.value.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, str)
            ]
            arm_line = node.lineno
    if arm_flags is None:
        return None
    bool_fields: Dict[str, int] = {}
    int_fields: Dict[str, int] = {}
    for node in cls.body:
        if not (
            isinstance(node, ast.AnnAssign)
            and isinstance(node.target, ast.Name)
        ):
            continue
        if _bool_annotation(node.annotation):
            bool_fields[node.target.id] = node.lineno
        elif _int_annotation(node.annotation):
            int_fields[node.target.id] = node.lineno
    return ConfigModule(
        relpath=ctx.relpath,
        bool_fields=bool_fields,
        int_fields=int_fields,
        arm_flags=arm_flags,
        arm_flags_line=arm_line,
    )


def _fingerprint_keys_from_tree(tree: ast.AST) -> Optional[Set[str]]:
    """Union of literal keys across every dict that appears as the
    value of a ``"fingerprint"`` key (perfgate emits more than one
    record kind; the mini-bench fingerprint carries the arm flags)."""
    keys: Set[str] = set()
    saw = False
    for node in ast.walk(tree):
        if not isinstance(node, ast.Dict):
            continue
        for k, v in zip(node.keys, node.values):
            if (
                isinstance(k, ast.Constant)
                and k.value == "fingerprint"
                and isinstance(v, ast.Dict)
            ):
                saw = True
                keys |= {
                    kk.value
                    for kk in v.keys
                    if isinstance(kk, ast.Constant)
                    and isinstance(kk.value, str)
                }
    return keys if saw else None


def parse_golden_families(text: str) -> Set[str]:
    """Family names from ``# TYPE <prefix>_<family> <kind>`` headers,
    with the one-segment metric prefix stripped (the exposition's
    ``family()`` names are prefix-free)."""
    out: Set[str] = set()
    for line in text.splitlines():
        if not line.startswith("# TYPE "):
            continue
        parts = line.split()
        if len(parts) >= 3 and "_" in parts[2]:
            out.add(parts[2].split("_", 1)[1])
    return out


# ---------------------------------------------------------------------------
# the index builder
# ---------------------------------------------------------------------------


def build_index(ctxs, root: pathlib.Path) -> ProgramIndex:
    wire_modules: List[WireModule] = []
    pb_modules: List[PbModule] = []
    metrics_modules: List[MetricsModule] = []
    expo_modules: List[ExpoModule] = []
    config_modules: List[ConfigModule] = []
    counter_incs: Dict[str, int] = {}
    attr_reads: Set[str] = set()
    kw_names: Set[str] = set()
    defs: Dict[str, Set[str]] = {}
    refs: Dict[str, Set[str]] = {}
    wave_defs: List[Tuple[str, str, int]] = []
    # (relpath, keys) per file carrying a "fingerprint" dict: the
    # REAL registry (a file named perfgate.py) wins over
    # fingerprint-shaped dict literals in tests/helpers, so a key
    # dropped from the real fingerprint cannot be masked by a test
    # fixture that still spells it
    fingerprints_by_file: List[Tuple[str, Set[str]]] = []

    for ctx in ctxs:
        w = _extract_wire(ctx)
        if w is not None:
            wire_modules.append(w)
        p = _extract_pb(ctx)
        if p is not None:
            pb_modules.append(p)
        metrics_modules.extend(_extract_metrics(ctx))
        e = _extract_expo(ctx)
        if e is not None:
            expo_modules.append(e)
        c = _extract_config(ctx)
        if c is not None:
            config_modules.append(c)
        fp = _fingerprint_keys_from_tree(ctx.tree)
        if fp is not None:
            fingerprints_by_file.append((ctx.relpath, fp))

        file_refs: Set[str] = set()
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Attribute):
                attr_reads.add(node.attr)
                file_refs.add(node.attr)
            elif isinstance(node, ast.Name) and isinstance(
                node.ctx, ast.Load
            ):
                file_refs.add(node.id)
            elif isinstance(node, ast.Constant) and isinstance(
                node.value, str
            ):
                # getattr(handler, "serve_wave", None)-style dynamic
                # references count as uses
                if node.value.isidentifier():
                    file_refs.add(node.value)
            elif isinstance(node, ast.keyword) and node.arg:
                kw_names.add(node.arg)
                file_refs.add(node.arg)
            elif isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            ):
                defs.setdefault(node.name, set()).add(ctx.relpath)
                if isinstance(
                    node, (ast.FunctionDef, ast.AsyncFunctionDef)
                ) and is_wave_entry_name(node.name):
                    wave_defs.append(
                        (node.name, ctx.relpath, node.lineno)
                    )
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "inc"
                and isinstance(node.func.value, ast.Attribute)
            ):
                attr = node.func.value.attr
                counter_incs[attr] = counter_incs.get(attr, 0) + 1
        refs[ctx.relpath] = file_refs

    # -- root augmentation (real registries only; fixture trees carry
    # their own minis under their own root) ----------------------------
    has_real_config = any(
        not is_fixture_path(c.relpath) for c in config_modules
    )
    has_real_expo = any(
        not is_fixture_path(e.relpath) for e in expo_modules
    )
    scanned = {ctx.relpath for ctx in ctxs}

    # perfgate.py-named registries beat incidental fingerprint-shaped
    # literals (e.g. perfgate's own tests building mini records)
    real_fps = [
        keys
        for relpath, keys in fingerprints_by_file
        if pathlib.PurePosixPath(relpath).name == "perfgate.py"
    ]
    pool = real_fps if real_fps else [k for _, k in fingerprints_by_file]
    fingerprint_keys: Optional[Set[str]] = None
    for keys in pool:
        fingerprint_keys = (fingerprint_keys or set()) | keys

    if fingerprint_keys is None and has_real_config:
        pg = root / "tools" / "perfgate.py"
        if pg.exists() and "tools/perfgate.py" not in scanned:
            try:
                fingerprint_keys = _fingerprint_keys_from_tree(
                    ast.parse(pg.read_text(encoding="utf-8"))
                )
            except (OSError, SyntaxError):
                fingerprint_keys = None

    test_flag_pins: Optional[str] = None
    if has_real_config:
        chunks: List[str] = []
        tests_dir = root / "tests"
        if tests_dir.is_dir():
            for py in sorted(tests_dir.glob("test_*.py")):
                if rel_posix(py, root) in scanned:
                    continue  # already parsed as a context
                try:
                    chunks.append(py.read_text(encoding="utf-8"))
                except OSError:
                    continue
        # scanned tests (a fixture tree's tests/ live under its root)
        for ctx in ctxs:
            if ctx.relpath.startswith("tests/"):
                chunks.append(ctx.text)
        if chunks:
            test_flag_pins = "\n".join(chunks)

    golden_families: Optional[Set[str]] = None
    if has_real_expo:
        golden = root / "tests" / "golden" / "metrics_exposition.txt"
        if golden.exists():
            try:
                golden_families = parse_golden_families(
                    golden.read_text(encoding="utf-8")
                )
            except OSError:
                golden_families = None

    # files that read any declared arm flag (attribute read or keyword
    # pass-through): the gate seeds for the wave-reachability closure
    all_flags: Set[str] = set()
    for c in config_modules:
        all_flags |= set(c.arm_flags)
    # (the declarations themselves are AnnAssign targets and string
    # constants, never Attribute reads, so the config module only
    # lands here if it genuinely READS a flag)
    flag_reader_files: Set[str] = set()
    for ctx in ctxs:
        for node in ast.walk(ctx.tree):
            if (
                isinstance(node, ast.Attribute)
                and node.attr in all_flags
            ) or (
                isinstance(node, ast.keyword) and node.arg in all_flags
            ):
                flag_reader_files.add(ctx.relpath)
                break

    return ProgramIndex(
        wire_modules=wire_modules,
        pb_modules=pb_modules,
        metrics_modules=metrics_modules,
        expo_modules=expo_modules,
        config_modules=config_modules,
        counter_incs=counter_incs,
        attr_reads=attr_reads,
        kw_names=kw_names,
        defs=defs,
        refs=refs,
        flag_reader_files=flag_reader_files,
        wave_defs=wave_defs,
        fingerprint_keys=fingerprint_keys,
        golden_families=golden_families,
        test_flag_pins=test_flag_pins,
        partial_scan=(
            len(ctxs) == 1 and not is_fixture_path(ctxs[0].relpath)
        ),
    )


def gated_closure(index: ProgramIndex) -> Set[str]:
    """Files reachable from arm-flag readers over the references-a-
    name-defined-there relation: a gated module that calls into a
    module hands its arm selection down, so wave entry points defined
    anywhere in the closure sit behind a Config-flag gate."""
    gated = set(index.flag_reader_files)
    work = list(gated)
    while work:
        src = work.pop()
        for name in index.refs.get(src, ()):
            for target in index.defs.get(name, ()):
                if target not in gated:
                    gated.add(target)
                    work.append(target)
    return gated


__all__ = [
    "PB_RESERVED_TAGS",
    "ConfigModule",
    "ExpoModule",
    "MetricsModule",
    "PbModule",
    "ProgramIndex",
    "WireModule",
    "build_index",
    "gated_closure",
    "is_fixture_path",
    "is_wave_entry_name",
    "parse_golden_families",
]
