"""CLI: the ci.sh staticcheck gate stage.

Usage:
    python -m tools.staticcheck cleisthenes_tpu            # gate mode
    python -m tools.staticcheck cleisthenes_tpu tools tests \
        --audit-pragmas                                    # ci stage 2
    python -m tools.staticcheck cleisthenes_tpu --format json
    python -m tools.staticcheck cleisthenes_tpu --format sarif
    python -m tools.staticcheck pkg --write-baseline       # grandfather
    python -m tools.staticcheck pkg --no-baseline          # raw view

Exit 0 iff no unbaselined findings.  Gate mode prints one line per
fresh finding plus a one-line JSON summary (machine-greppable in CI
logs) and the human summary via the shared reporter.  ``--format
sarif`` emits SARIF 2.1.0 so editors and CI annotate findings in
place; ``--audit-pragmas`` re-runs every rule unsuppressed and fails
on stale pragmas (PRAGMA002) or pragma-population growth past the
budget in the baseline file (PRAGMA003).
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[2]))

from tools.lintcommon import REPO_ROOT, report  # noqa: E402
from tools.staticcheck import (  # noqa: E402
    BASELINE_PATH,
    check_paths,
    load_baseline,
    load_pragma_budget,
    registered_rules,
    split_baselined,
    write_baseline,
)

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = (
    "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/master/"
    "Schemata/sarif-schema-2.1.0.json"
)


def to_sarif(findings, baselined) -> dict:
    """SARIF 2.1.0 document: one run, one result per fresh finding
    (grandfathered findings ride along with 'baseline' suppressions so
    annotators can hide them)."""
    rule_ids = sorted(
        {f.rule for f in findings}
        | {f.rule for f in baselined}
        | set(registered_rules())
    )
    rules_meta = []
    catalog = registered_rules()
    for rid in rule_ids:
        desc = getattr(catalog.get(rid), "doc", "") or rid
        rules_meta.append(
            {
                "id": rid,
                "shortDescription": {"text": desc},
            }
        )

    def result(f, suppressed: bool) -> dict:
        out = {
            "ruleId": f.rule,
            "level": "error",
            "message": {"text": f.message},
            "locations": [
                {
                    "physicalLocation": {
                        "artifactLocation": {
                            "uri": f.path,
                            "uriBaseId": "SRCROOT",
                        },
                        "region": {
                            "startLine": f.line,
                            "startColumn": f.col + 1,
                        },
                    }
                }
            ],
        }
        if f.related:
            # pass-3 findings (CONC003/CONC004/DET007) carry their
            # evidence chain — each hop of the call path or taint flow
            # becomes one relatedLocation, so the report alone shows
            # WHY the sink is reachable
            out["relatedLocations"] = [
                {
                    "physicalLocation": {
                        "artifactLocation": {
                            "uri": rpath,
                            "uriBaseId": "SRCROOT",
                        },
                        "region": {"startLine": rline},
                    },
                    "message": {"text": rnote},
                }
                for rpath, rline, rnote in f.related
            ]
        if suppressed:
            out["suppressions"] = [
                {"kind": "external", "justification": "baselined"}
            ]
        return out

    return {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [
            {
                "tool": {
                    "driver": {
                        "name": "cleisthenes-staticcheck",
                        "informationUri": (
                            "docs/STATICCHECK.md"
                        ),
                        "rules": rules_meta,
                    }
                },
                "originalUriBaseIds": {
                    "SRCROOT": {"uri": REPO_ROOT.as_uri() + "/"}
                },
                "results": [result(f, False) for f in findings]
                + [result(f, True) for f in baselined],
            }
        ],
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="tools.staticcheck")
    ap.add_argument(
        "paths",
        nargs="*",
        default=["cleisthenes_tpu"],
        help="files/dirs to scan (repo-relative; default: the package)",
    )
    ap.add_argument(
        "--json",
        action="store_true",
        help="emit full findings as JSON (alias for --format json)",
    )
    ap.add_argument(
        "--format",
        choices=("text", "json", "sarif"),
        default="text",
        help="output format (sarif: editor/CI-annotatable 2.1.0)",
    )
    ap.add_argument(
        "--baseline",
        type=pathlib.Path,
        default=BASELINE_PATH,
        help="baseline file (default: tools/staticcheck/baseline.json)",
    )
    ap.add_argument(
        "--no-baseline",
        action="store_true",
        help="ignore the baseline (show every finding)",
    )
    ap.add_argument(
        "--write-baseline",
        action="store_true",
        help="grandfather the current findings and exit 0",
    )
    ap.add_argument(
        "--audit-pragmas",
        action="store_true",
        help=(
            "re-run all rules unsuppressed; fail on stale pragmas "
            "(PRAGMA002) and pragma counts past the budget (PRAGMA003)"
        ),
    )
    ap.add_argument(
        "--rules",
        help="comma-separated rule ids to run (default: all)",
    )
    ap.add_argument(
        "--root",
        type=pathlib.Path,
        default=None,
        help=(
            "analysis root for relative paths and registry "
            "augmentation (default: the repo root; point it at a "
            "fixture tree to analyze its miniature registries)"
        ),
    )
    args = ap.parse_args(argv)

    root = (
        (args.root if args.root.is_absolute() else REPO_ROOT / args.root)
        if args.root is not None
        else REPO_ROOT
    )
    targets = [
        p if p.is_absolute() else root / p
        for p in (pathlib.Path(s) for s in args.paths)
    ]
    rule_ids = args.rules.split(",") if args.rules else None
    findings, n_files = check_paths(
        targets,
        root,
        rule_ids,
        audit=args.audit_pragmas,
        pragma_budget=load_pragma_budget(args.baseline),
    )

    if args.write_baseline:
        write_baseline(findings, args.baseline)
        print(
            f"staticcheck: baselined {len(findings)} finding(s) "
            f"-> {args.baseline}"
        )
        return 0

    baseline = {} if args.no_baseline else load_baseline(args.baseline)
    fresh, old = split_baselined(findings, baseline)

    fmt = "json" if args.json else args.format
    summary = {
        "files": n_files,
        "findings": len(fresh),
        "baselined": len(old),
        "audit": bool(args.audit_pragmas),
        "rules": sorted(registered_rules()),
    }
    if fmt == "json":
        print(
            json.dumps(
                {
                    "summary": summary,
                    "findings": [f.to_json() for f in fresh],
                    "baselined": [f.to_json() for f in old],
                },
                indent=2,
            )
        )
        return 1 if fresh else 0
    if fmt == "sarif":
        print(json.dumps(to_sarif(fresh, old), indent=2))
        return 1 if fresh else 0
    return report(
        "staticcheck",
        n_files,
        [f.render() for f in fresh],
        extra=[json.dumps(summary, sort_keys=True)],
    )


if __name__ == "__main__":
    sys.exit(main())
