"""CLI: the ci.sh staticcheck gate stage.

Usage:
    python -m tools.staticcheck cleisthenes_tpu            # gate mode
    python -m tools.staticcheck cleisthenes_tpu --json     # full JSON
    python -m tools.staticcheck pkg --write-baseline       # grandfather
    python -m tools.staticcheck pkg --no-baseline          # raw view

Exit 0 iff no unbaselined findings.  Gate mode prints one line per
fresh finding plus a one-line JSON summary (machine-greppable in CI
logs) and the human summary via the shared reporter.
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[2]))

from tools.lintcommon import REPO_ROOT, report  # noqa: E402
from tools.staticcheck import (  # noqa: E402
    BASELINE_PATH,
    check_paths,
    load_baseline,
    registered_rules,
    split_baselined,
    write_baseline,
)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="tools.staticcheck")
    ap.add_argument(
        "paths",
        nargs="*",
        default=["cleisthenes_tpu"],
        help="files/dirs to scan (repo-relative; default: the package)",
    )
    ap.add_argument(
        "--json", action="store_true", help="emit full findings as JSON"
    )
    ap.add_argument(
        "--baseline",
        type=pathlib.Path,
        default=BASELINE_PATH,
        help="baseline file (default: tools/staticcheck/baseline.json)",
    )
    ap.add_argument(
        "--no-baseline",
        action="store_true",
        help="ignore the baseline (show every finding)",
    )
    ap.add_argument(
        "--write-baseline",
        action="store_true",
        help="grandfather the current findings and exit 0",
    )
    ap.add_argument(
        "--rules",
        help="comma-separated rule ids to run (default: all)",
    )
    args = ap.parse_args(argv)

    targets = [
        p if p.is_absolute() else REPO_ROOT / p
        for p in (pathlib.Path(s) for s in args.paths)
    ]
    rule_ids = args.rules.split(",") if args.rules else None
    findings, n_files = check_paths(targets, REPO_ROOT, rule_ids)

    if args.write_baseline:
        write_baseline(findings, args.baseline)
        print(
            f"staticcheck: baselined {len(findings)} finding(s) "
            f"-> {args.baseline}"
        )
        return 0

    baseline = {} if args.no_baseline else load_baseline(args.baseline)
    fresh, old = split_baselined(findings, baseline)

    summary = {
        "files": n_files,
        "findings": len(fresh),
        "baselined": len(old),
        "rules": sorted(registered_rules()),
    }
    if args.json:
        print(
            json.dumps(
                {
                    "summary": summary,
                    "findings": [f.to_json() for f in fresh],
                    "baselined": [f.to_json() for f in old],
                },
                indent=2,
            )
        )
        return 1 if fresh else 0
    return report(
        "staticcheck",
        n_files,
        [f.render() for f in fresh],
        extra=[json.dumps(summary, sort_keys=True)],
    )


if __name__ == "__main__":
    sys.exit(main())
