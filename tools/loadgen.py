"""loadgen: the million-client ingress load harness.

Every number this repo publishes so far starts at the validator
(epoch open -> commit); none starts where a user does.  This tool
closes that gap: a seeded **open-loop** generator drives a simulated
client population (10^5-10^6 distinct client ids, Pareto-bursty
arrivals, Pareto-skewed fees) through the production ingress path —
the in-proc twin of the client gRPC surface (transport/ingress.py:
identical encoded frames, identical IngressPlane/mempool admission
code) over the deterministic channel cluster — and reports the two
client-visible latencies the two-frontier commit split creates:

    submit -> ordered   (the tx's epoch crossed the ORDERED frontier)
    submit -> settled   (the epoch settled: plaintext durable, acked
                         to subscribers)

measured per tx under K-deep pipelined windows (``--depths 1,4``
runs one arm per depth over the IDENTICAL arrival schedule).

Open-loop means arrivals never wait for the service: each tick
submits whatever the schedule says arrived, whether or not the
cluster kept up — so backpressure (RETRY_AFTER) and priority
eviction are reachable outcomes, not scheduling artifacts.

Every arm is audited before any latency is reported:

- **zero lost acks**: every submission produced exactly one ack, and
  every OK-acked tx either settled exactly ONCE or is accounted by
  the eviction counter — nothing vanished in between (the mempool's
  no-silent-drops promise, end to end).
- **settled superset of ordered**: the settled frontier caught the
  ordered frontier at drain, so no ordered epoch was left undecrypted.
- **cross-node agreement**: every node settled the byte-identical
  batch sequence (SimulatedCluster.assert_agreement).
- **cross-arm determinism**: the settled tx content digests at every
  depth are identical — pipelining moves WHEN work settles, never
  WHAT settles.

CI rides the same path: ``--smoke`` shrinks the population to a
seconds-scale run with the same invariants (the ci.sh ingress stage);
``bench.py --sections ingress_load`` embeds ``run_arm`` for the
headline numbers.

    python -m tools.loadgen --clients 100000 --txs 100000 --depths 1,4
    python -m tools.loadgen --smoke
"""

from __future__ import annotations

import argparse
import hashlib
import json
import pathlib
import random
import statistics
import sys
import time
from typing import Dict, List, Optional, Tuple

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

# full-run defaults: the acceptance shape (1e5 distinct clients).
# Smoke shrinks everything by ~100x but keeps every invariant.
DEFAULT_CLIENTS = 100_000
DEFAULT_TXS = 100_000
DEFAULT_N = 4
DEFAULT_BATCH = 1024
DEFAULT_SEED = 7
DEFAULT_DEPTHS = (1, 4)
DEFAULT_TICKS = 64
# Pareto shape for inter-arrival gaps (alpha <= 2 means bursty: heavy
# tail of long gaps between arrival clumps) and for the fee skew (a
# few clients pay a lot, most pay little — the shape that makes
# fee-priority draining mean something)
ARRIVAL_ALPHA = 1.5
FEE_ALPHA = 1.2

SMOKE_CLIENTS = 2_000
SMOKE_TXS = 1_200
SMOKE_BATCH = 64
SMOKE_TICKS = 12


def build_schedule(
    *, clients: int, txs: int, ticks: int, seed: int
) -> List[List[Tuple[str, int, int, bytes]]]:
    """The arrival schedule all arms share: per tick, a list of
    (client_id, nonce, fee, tx).  Seeded and arm-independent — depth
    must never change what arrives, only how it drains.

    Client ids cycle through the whole population (txs >= clients
    means every simulated client really submits); arrival times are
    cumulative Pareto gaps normalized onto [0, ticks); fees are
    Pareto-skewed ints in [1, 10^6]."""
    rng = random.Random(seed)
    gaps = [rng.paretovariate(ARRIVAL_ALPHA) for _ in range(txs)]
    t, arrivals = 0.0, []
    for g in gaps:
        t += g
        arrivals.append(t)
    scale = ticks / arrivals[-1] if arrivals else 1.0
    schedule: List[List[Tuple[str, int, int, bytes]]] = [
        [] for _ in range(ticks)
    ]
    for i, at in enumerate(arrivals):
        tick = min(ticks - 1, int(at * scale))
        client = f"c{i % clients:07d}"
        fee = min(1_000_000, int(rng.paretovariate(FEE_ALPHA)))
        tx = b"load|%07d|%s" % (i, client.encode())
        schedule[tick].append((client, i, fee, tx))
    return schedule


def _pctl(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    return sorted_vals[
        max(0, min(len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1)))))
    ]


def run_arm(
    schedule,
    *,
    depth: int,
    n: int = DEFAULT_N,
    batch: int = DEFAULT_BATCH,
    seed: int = DEFAULT_SEED,
    lanes: int = 1,
    max_drain_rounds: int = 400,
    wan_profile: Optional[str] = None,
    progress=None,
) -> Dict:
    """One measured arm: drive the shared schedule through per-node
    ingress twins at pipeline depth ``depth``, drain to quiescence,
    audit the invariants, and report both latency distributions.

    Raises AssertionError on any invariant breach — a loadgen number
    from a run that lost a tx is not a number."""
    from cleisthenes_tpu.config import Config
    from cleisthenes_tpu.protocol.cluster import SimulatedCluster

    txs_total = sum(len(tick) for tick in schedule)
    cfg = Config(
        n=n,
        batch_size=batch,
        seed=seed,
        crypto_backend="cpu",
        # lanes > 1 shards the schedule across S consensus lanes: the
        # mempool's admit() routes each tx by seeded digest hash, so
        # loadgen exercises the production partitioner, not its own
        lanes=lanes,
        epoch_pipelining=depth > 1,
        pipeline_depth=depth,
        # keep validation headroom: reconfig_lead must exceed
        # depth + decrypt_lag_max, and loadgen never reconfigures
        reconfig_lead=16,
        # capacity sized to the whole backlog: this harness measures
        # latency under load, not admission-control behavior (the
        # backpressure tests own that) — every arrival must admit so
        # the arms settle identical content
        mempool_capacity=max(4 * batch, txs_total),
        mempool_client_cap=64,
        mempool_seen_cap=max(1 << 16, 2 * txs_total),
    )
    # wan_profile composes the PR-16 link-delay plane under the load:
    # client-visible latency with geo-realistic delivery schedules
    cluster = SimulatedCluster(
        config=cfg, seed=seed, auto_propose=False, wan_profile=wan_profile
    )
    ids = cluster.ids
    ingress = {nid: cluster.ingress(nid) for nid in ids}
    node0 = cluster.nodes[ids[0]]

    submit_ts: Dict[bytes, float] = {}
    status_counts: Dict[str, int] = {}
    acks = 0
    ok_txs: List[bytes] = []
    t_ordered: Dict[int, float] = {}
    t_settled: Dict[int, float] = {}
    seen_ordered = seen_settled = 0

    def record_frontiers() -> None:
        # MERGED frontiers (== epoch/settled_epoch at lanes=1): slot
        # timestamps and the exactly-once audit span every lane
        nonlocal seen_ordered, seen_settled
        now = time.perf_counter()
        while seen_ordered < node0.merged_ordered_frontier:
            t_ordered[seen_ordered] = now
            seen_ordered += 1
        while seen_settled < node0.merged_settled_frontier:
            t_settled[seen_settled] = now
            seen_settled += 1

    def one_round() -> None:
        # step (one delivery wave at a time) instead of run-to-
        # quiescence, observing the frontiers between waves: the
        # ordered frontier visibly leads the settled frontier inside
        # a round, which is exactly the two-latency split this
        # harness exists to measure
        for hb in cluster.nodes.values():
            hb.start_epoch()
        net = cluster.net
        while True:
            if net.step():
                record_frontiers()
                continue
            # the manual-driving contract (ChannelNetwork.step): a
            # drained queue needs the idle phase (deferred crypto +
            # bundle flushes) and another pass if it produced traffic
            net.idle_phase()
            record_frontiers()
            if not net._pending and not net._wan_holding:
                break

    t_start = time.perf_counter()
    for tick, batch_arrivals in enumerate(schedule):
        for client, nonce, fee, tx in batch_arrivals:
            # deterministic client -> admitting-node placement
            ack = ingress[ids[nonce % n]].submit(client, nonce, fee, tx)
            acks += 1
            name = ack.status.name if hasattr(ack.status, "name") else str(
                ack.status
            )
            status_counts[name] = status_counts.get(name, 0) + 1
            if name == "OK":
                submit_ts[tx] = time.perf_counter()
                ok_txs.append(tx)
        one_round()
        if progress is not None:
            progress(tick + 1, len(schedule))
    # drain: open-loop arrivals are done; run until every frontier
    # catches up and nothing is pending anywhere
    rounds = 0
    while rounds < max_drain_rounds and (
        cluster.pending() > 0
        or node0.merged_settled_frontier < node0.merged_ordered_frontier
    ):
        one_round()
        rounds += 1
    t_end = time.perf_counter()

    # -- audits (the numbers are only as good as these) ----------------
    assert acks == txs_total, f"lost acks: {acks} != {txs_total}"
    settle_epoch: Dict[bytes, int] = {}
    dup_settles = 0
    # merged total order: a tx that settled in two different lanes
    # would surface as a duplicate here — the cross-lane
    # exactly-once audit (== the single-lane one at lanes=1)
    for e, b in enumerate(node0.merged_batches):
        for tx in b.tx_list():
            if tx in settle_epoch:
                dup_settles += 1
            settle_epoch[tx] = e
    assert dup_settles == 0, f"{dup_settles} txs settled more than once"
    evicted = sum(
        hb.mempool.evicted for hb in cluster.nodes.values()
    )
    lost = [tx for tx in ok_txs if tx not in settle_epoch]
    assert len(lost) == evicted, (
        f"{len(lost)} OK-acked txs unsettled but only {evicted} evictions"
    )
    assert node0.merged_settled_frontier == node0.merged_ordered_frontier, (
        f"merged settled frontier {node0.merged_settled_frontier} trails "
        f"ordered {node0.merged_ordered_frontier} after drain"
    )
    cluster.assert_agreement()
    lane_fill = node0.mempool.lane_fill()
    ledger = hashlib.sha256()
    for tx in sorted(settle_epoch):
        ledger.update(tx)
    ingress_block = node0.metrics.snapshot()["ingress"]
    cluster.stop()

    lat_ordered = sorted(
        t_ordered[settle_epoch[tx]] - ts
        for tx, ts in submit_ts.items()
        if tx in settle_epoch
    )
    lat_settled = sorted(
        t_settled[settle_epoch[tx]] - ts
        for tx, ts in submit_ts.items()
        if tx in settle_epoch
    )
    wall = t_end - t_start
    return {
        "depth": depth,
        "lanes": lanes,
        "lane_fill": lane_fill,
        "lane_skew": max(lane_fill) - min(lane_fill),
        "wan_profile": wan_profile,
        "clients": len({c for tick in schedule for (c, _, _, _) in tick}),
        "txs": txs_total,
        "settled": len(settle_epoch),
        "evicted": evicted,
        "statuses": dict(sorted(status_counts.items())),
        "epochs": node0.merged_settled_frontier,
        "drain_rounds": rounds,
        "wall_s": round(wall, 3),
        "tx_per_s": round(len(settle_epoch) / wall, 1) if wall else 0.0,
        "submit_to_ordered_ms": {
            "p50": round(_pctl(lat_ordered, 0.50) * 1e3, 3),
            "p99": round(_pctl(lat_ordered, 0.99) * 1e3, 3),
        },
        "submit_to_settled_ms": {
            "p50": round(_pctl(lat_settled, 0.50) * 1e3, 3),
            "p99": round(_pctl(lat_settled, 0.99) * 1e3, 3),
        },
        "ledger_digest": ledger.hexdigest(),
        "node_metrics_ingress": ingress_block,
    }


def run(
    *,
    clients: int,
    txs: int,
    depths,
    n: int = DEFAULT_N,
    batch: int = DEFAULT_BATCH,
    ticks: int = DEFAULT_TICKS,
    seed: int = DEFAULT_SEED,
    lanes: int = 1,
    quiet: bool = False,
) -> Dict:
    """All arms over one shared schedule + the cross-arm audit."""
    schedule = build_schedule(
        clients=clients, txs=txs, ticks=ticks, seed=seed
    )
    arms = []
    for depth in depths:
        if not quiet:
            print(f"[loadgen] arm depth={depth}: {txs} txs, "
                  f"{clients} clients, {ticks} ticks, "
                  f"{lanes} lane(s)", flush=True)
        arms.append(
            run_arm(
                schedule, depth=depth, n=n, batch=batch, seed=seed,
                lanes=lanes,
            )
        )
        if not quiet:
            a = arms[-1]
            print(
                f"[loadgen]   settled {a['settled']}/{a['txs']} in "
                f"{a['wall_s']}s ({a['tx_per_s']} tx/s), "
                f"ordered p50 {a['submit_to_ordered_ms']['p50']}ms "
                f"p99 {a['submit_to_ordered_ms']['p99']}ms, "
                f"settled p50 {a['submit_to_settled_ms']['p50']}ms "
                f"p99 {a['submit_to_settled_ms']['p99']}ms"
                + (f", lane skew {a['lane_skew']}" if lanes > 1 else ""),
                flush=True,
            )
    digests = {a["ledger_digest"] for a in arms}
    assert len(digests) == 1, (
        f"settled ledgers diverge across depth arms: "
        f"{[(a['depth'], a['ledger_digest'][:16]) for a in arms]}"
    )
    return {
        "kind": "ingress_load",
        "seed": seed,
        "clients": clients,
        "txs": txs,
        "ticks": ticks,
        "n": n,
        "batch": batch,
        "arms": arms,
        "ledger_digest": arms[0]["ledger_digest"],
    }


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--clients", type=int, default=DEFAULT_CLIENTS)
    ap.add_argument("--txs", type=int, default=DEFAULT_TXS)
    ap.add_argument("--n", type=int, default=DEFAULT_N)
    ap.add_argument("--batch", type=int, default=DEFAULT_BATCH)
    ap.add_argument("--ticks", type=int, default=DEFAULT_TICKS)
    ap.add_argument("--seed", type=int, default=DEFAULT_SEED)
    ap.add_argument(
        "--lanes", type=int, default=1,
        help="consensus lanes (Config.lanes); submits shard across "
        "lanes through the production hash partitioner",
    )
    ap.add_argument(
        "--depths", default=",".join(str(d) for d in DEFAULT_DEPTHS),
        help="comma-separated pipeline depths, one arm each",
    )
    ap.add_argument(
        "--smoke", action="store_true",
        help="seconds-scale run with the full invariant audit "
        "(the ci.sh ingress stage)",
    )
    ap.add_argument("--json", help="write the result document here")
    args = ap.parse_args(argv)

    if args.smoke:
        args.clients = min(args.clients, SMOKE_CLIENTS)
        args.txs = min(args.txs, SMOKE_TXS)
        args.batch = min(args.batch, SMOKE_BATCH)
        args.ticks = min(args.ticks, SMOKE_TICKS)
    depths = [int(d) for d in str(args.depths).split(",") if d]

    result = run(
        clients=args.clients,
        txs=args.txs,
        depths=depths,
        n=args.n,
        batch=args.batch,
        ticks=args.ticks,
        seed=args.seed,
        lanes=args.lanes,
    )
    if args.json:
        with open(args.json, "w", encoding="utf-8") as fh:
            json.dump(result, fh, indent=2, sort_keys=True)
        print(f"[loadgen] wrote {args.json}")
    print(
        f"[loadgen] PASS: {len(result['arms'])} arms, "
        f"ledger {result['ledger_digest'][:16]}..., zero lost acks"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
