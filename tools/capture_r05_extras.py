"""Round-5 supplementary chip capture: the sections added AFTER the
main bench launched — GROUP384 flagship, host-overlap pipelining,
the 768-bit limb family — written to TPU_EXTRAS_r05.json with
per-section persistence (windows die mid-run).

Usage:  python tools/capture_r05_extras.py [sections...]
        (default: all of g384 pipelined modexp)
"""

from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import bench  # noqa: E402
from tools import benchlock  # noqa: E402

OUT = os.path.join(REPO, "TPU_EXTRAS_r05.json")


def main() -> int:
    wanted = set(sys.argv[1:]) or {"g384", "pipelined", "modexp"}
    with benchlock.hold("capture_r05_extras"):
        return _run(wanted)


def _run(wanted) -> int:
    import jax

    dev = jax.devices()[0]
    if dev.platform not in ("tpu", "axon"):
        print(f"not a TPU: {dev}; aborting", file=sys.stderr)
        return 1
    out = {}
    if os.path.exists(OUT):
        with open(OUT) as f:
            out = json.load(f)
    out.update(
        {
            "platform": dev.platform,
            "device": getattr(dev, "device_kind", ""),
            "start_utc": time.strftime(
                "%Y-%m-%dT%H:%M:%SZ", time.gmtime()
            ),
            "host_load": os.getloadavg(),
        }
    )

    def _write():
        tmp = OUT + ".tmp"
        with open(tmp, "w") as f:
            json.dump(out, f)
        os.replace(tmp, OUT)

    def stamp(name, fn):
        t0 = time.perf_counter()
        try:
            out[name] = fn()
        except Exception as exc:  # record, don't lose the window
            out[name] = {"error": repr(exc)[:300]}
        out[name + "_wall_s"] = round(time.perf_counter() - t0, 1)
        _write()
        print(f"[extras] {name} done @ {time.strftime('%H:%M:%S')}",
              file=sys.stderr, flush=True)

    if "g384" in wanted:
        from cleisthenes_tpu.ops.modmath import GROUP384

        def g384():
            tpu = bench.measure_spmd(
                "tpu", 128, 10_000, 2, group=GROUP384
            )
            cpu = bench.measure_spmd(
                bench.cpu_reference_backend(),
                128,
                10_000,
                1,
                group=GROUP384,
            )
            return {
                "n": 128, "f": 42, "batch": 10_000, "group_bits": 384,
                "tpu": tpu,
                "cpu": cpu,
                "vs_cpu": bench._vs(
                    cpu["epoch_p50_ms"], tpu["epoch_p50_ms"]
                ),
            }

        stamp("protocol_spmd_n128_g384", g384)
    if "pipelined" in wanted:
        # the crypto_n512_pipelined software-pipeline section was
        # retired by the two-frontier split (ISSUE 8); the chip
        # capture now records the real ordered-vs-settled overlap
        def pipelined():
            return {
                "tpu": bench.order_overlap_section("tpu"),
                "cpu": bench.order_overlap_section(
                    bench.cpu_reference_backend()
                ),
            }

        stamp("order_overlap", pipelined)
    if "modexp" in wanted:
        stamp("modexp_wide", bench.measure_modexp_wide)
    out["end_utc"] = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    _write()
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
