"""On-chip A/B: doubling coin-round blocks vs serial block=1.

Round-4 verdict weak #3: the doubling schedule halves sequential
dispatches but precomputes rounds speculatively, and round 3 measured
that flat speculation LOSES on a high-RTT relay.  This driver settles
it with data: alternate epochs between the two schedules on the SAME
cluster state (interleaved, so both arms sample the same relay
weather), record per-epoch wall, rounds, wave/dispatch counts, and a
tiny needle dispatch before every epoch so relay drift is visible in
the artifact.

Writes AB_COIN_BLOCKS_r05.json atomically after every epoch.

Usage:  python tools/ab_coin_blocks.py [n] [epochs_per_arm] [arm ...]
        arms: doubling (default schedule), serial (block=1 always),
        aggressive4 (first block covers rounds 0..3 — E[15/16] of the
        roster decides inside one wave, trading issue mass for two
        fewer sequential relay round-trips)
        default arms: doubling serial
"""

from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tools import benchlock  # noqa: E402

OUT = os.path.join(REPO, "AB_COIN_BLOCKS_r05.json")


def _write(doc: dict) -> None:
    tmp = OUT + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, OUT)


def _needle_ms() -> float:
    """One tiny device dispatch: the relay-health probe."""
    import jax
    import jax.numpy as jnp

    t0 = time.perf_counter()
    jax.block_until_ready(jnp.ones((8, 8)) @ jnp.ones((8, 8)))
    return round((time.perf_counter() - t0) * 1000.0, 1)


# arm name -> (coin_block_doubling, coin_block_initial)
ARMS = {
    "doubling": (True, 1),
    "serial": (False, 1),
    "aggressive4": (True, 4),
}


def main() -> int:
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 128
    per_arm = int(sys.argv[2]) if len(sys.argv) > 2 else 4
    arms = sys.argv[3:] or ["doubling", "serial"]
    for a in arms:
        if a not in ARMS:
            print(f"unknown arm {a!r}; known: {sorted(ARMS)}",
                  file=sys.stderr)
            return 1
    with benchlock.hold("ab_coin_blocks"):
        return _run(n, per_arm, arms)


def _run(n: int, per_arm: int, arms) -> int:
    import jax
    import numpy as np

    from cleisthenes_tpu.protocol.spmd import LockstepCluster

    dev = jax.devices()[0]
    out = {
        "platform": dev.platform,
        "device": getattr(dev, "device_kind", ""),
        "n": n,
        "batch": 10_000 if n >= 128 else 1024,
        "start_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "loadavg": os.getloadavg(),
        "epochs": [],
    }
    out["arms"] = arms
    batch = out["batch"]
    cluster = LockstepCluster(
        n=n, batch_size=batch, crypto_backend="tpu", key_seed=77
    )
    rng = np.random.default_rng(13)
    total_epochs = len(arms) * per_arm + len(arms)  # + warm-ups
    for _ in range((batch // n) * n * (total_epochs + 1)):
        tx = rng.integers(0, 256, size=64, dtype=np.uint8).tobytes()
        cluster.submit(tx)
    for arm in arms:  # one warm-up per arm: compile its shapes
        cluster.coin_block_doubling, cluster.coin_block_initial = ARMS[arm]
        cluster.run_epoch()
    out["warmup_done_utc"] = time.strftime(
        "%Y-%m-%dT%H:%M:%SZ", time.gmtime()
    )
    _write(out)
    for i in range(len(arms) * per_arm):
        arm = arms[i % len(arms)]  # interleave: same relay weather
        cluster.coin_block_doubling, cluster.coin_block_initial = ARMS[arm]
        needle = _needle_ms()
        s = cluster.run_epoch()
        out["epochs"].append(
            {
                "schedule": arm,
                "needle_ms": needle,
                "epoch_s": round(s["epoch_s"], 3),
                "bba_s": round(s["bba_s"], 3),
                "bba_rounds": s["bba_rounds"],
                "coin_waves": s["coin_waves"],
                "coin_issues": s["coin_issues"],
            }
        )
        _write(out)
        print(f"[ab] {out['epochs'][-1]}", file=sys.stderr, flush=True)
    for arm in arms:
        es = [e for e in out["epochs"] if e["schedule"] == arm]
        walls = sorted(e["epoch_s"] for e in es)
        out[arm] = {
            "epoch_p50_s": walls[len(walls) // 2],
            "epoch_min_s": walls[0],
            "mean_waves": sum(e["coin_waves"] for e in es) / len(es),
            "mean_issues": sum(e["coin_issues"] for e in es) / len(es),
        }
    out["end_utc"] = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    _write(out)
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
