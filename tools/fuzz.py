"""Deterministic schedule fuzzer for the semantic Byzantine plane.

Samples composite fault schedules — semantic node behaviors
(protocol.byzantine) x wire-level faults (utils.adversary.Coalition) x
crash/partition/heal timelines — runs them over a seeded
``SimulatedCluster``, and checks SAFETY INVARIANTS at every quiescence
point:

  agreement      every honest node's committed-batch prefix is
                 byte-identical (ledger-body bytes, the exact bytes a
                 WAL persists and CATCHUP serves)
  no_foreign_tx  no honest node ever commits a transaction nobody
                 submitted (sound here because the sampled adversaries
                 never inject well-formed ciphertexts of new txs —
                 a planted foreign tx is exactly how the self-test
                 plants a violation)
  liveness       every honest-submitted tx commits on every honest
                 node within the schedule's round budget

On a violation the fuzzer GREEDILY SHRINKS the schedule — dropping
timeline events, wire stages and behaviors, then halving txs/rounds —
re-running after each candidate edit and keeping it only if the
violation survives.  The minimal schedule is written as a replayable
repro file (seed + schedule JSON + violation) plus, when tracing is
requested, a PR-3 flight-recorder artifact of the failing run.

Everything is a pure function of the schedule dict: same schedule,
same run, same verdict — which is what makes the repro files useful.

Usage:
  python -m tools.fuzz --seeds 0:20              # CI smoke sweep
  python -m tools.fuzz --seed 7 --show           # print one schedule
  python -m tools.fuzz --repro r.json            # replay a repro file
  python -m tools.fuzz --seeds 0:200 --out /tmp  # deep sweep + repros
"""

from __future__ import annotations

import argparse
import copy
import json
import random
import sys
from typing import Dict, List, Optional, Sequence

from cleisthenes_tpu.config import Config
from cleisthenes_tpu.core.ledger import encode_batch_body
from cleisthenes_tpu.protocol.byzantine import (
    BEHAVIOR_KINDS,
    CompositeBehavior,
    make_behavior,
)
from cleisthenes_tpu.protocol.cluster import (
    SimulatedCluster,
    run_until_drained,
)
from cleisthenes_tpu.utils.adversary import Coalition

SCHEDULE_VERSION = 1

# wire stages the sampler may enable, with their sampled-argument
# ranges (kept mild: the budget is f Byzantine nodes, not a dead net)
_WIRE_STAGES = (
    ("drop", {"fraction": (0.05, 0.4)}),
    ("tamper", {"fraction": (0.1, 0.7)}),
    ("duplicate", {"fraction": (0.1, 0.5)}),
    ("replay", {"fraction": (0.1, 0.5)}),
    ("delay", {"fraction": (0.05, 0.3)}),
    ("reorder", {"fraction": (0.1, 0.5)}),
)

# kinds the sampler may mount: every library behavior EXCEPT the tx
# injector — injecting txs is legal HBBFT behavior that deliberately
# trips no_foreign_tx, so it exists only for planted-violation
# schedules (shrinker self-tests), never sampled sweeps
_SEMANTIC_KINDS = tuple(
    sorted(k for k in BEHAVIOR_KINDS if k != "tx_injector")
)


class Violation(Exception):
    """A safety/liveness invariant failed; carries the report dict."""

    def __init__(self, invariant: str, detail: str, rnd: int) -> None:
        super().__init__(f"{invariant}: {detail} (round {rnd})")
        self.report = {
            "invariant": invariant,
            "detail": detail,
            "round": rnd,
        }


# ---------------------------------------------------------------------------
# schedule sampling
# ---------------------------------------------------------------------------


def sample_schedule(
    seed: int,
    n: int = 4,
    rounds: int = 12,
    reconfig: bool = False,
    pipeline_depth: Optional[int] = None,
    wan: bool = False,
    wan_profile: Optional[str] = None,
    ingress: bool = False,
    reduced: bool = False,
    lanes: bool = False,
) -> dict:
    """One composite fault schedule, a pure function of ``seed``.

    All faults — semantic behaviors, wire stages, crash/partition
    timeline — are confined to ONE f-sized coalition, so the honest
    majority keeps its HBBFT guarantees and the liveness invariant is
    legitimately enforceable.

    ``reconfig=True`` (the dynamic-membership band) additionally
    schedules one roster-change event — a joiner, sometimes composed
    with the retirement of a COALITION member — so crash/partition/
    semantic schedules run ACROSS a reshare ceremony and an
    activation boundary, and the safety invariants span the roster
    change.

    ``pipeline_depth`` pins the K-deep protocol-plane window (the
    ci.sh depth band); None draws it from the seed (LAST, so the
    depth key extends the historical schedule stream instead of
    reshuffling it), spanning lockstep and pipelined windows.

    ``wan=True`` (the WAN band, ISSUE 16) mounts a seeded link-delay
    profile on the channel scheduler — drawn from the seed AFTER
    every other key (the same append-LAST rule as depth, so the WAN
    band's schedules extend the historical stream), or pinned with
    ``wan_profile``.

    ``ingress=True`` (the client-ingress band, ISSUE 18) routes every
    submitted tx through the in-proc twin of the client gRPC surface
    (SimulatedCluster.ingress -> IngressPlane -> fee-priority
    mempool) instead of add_transaction, with the admission schedule
    — pool capacity, per-client cap, client population, duplicate
    resubmit mix — drawn from the seed LAST of all (after the WAN
    key, the same append-LAST rule), so every older band's seed
    stream stays bit-identical.

    ``reduced=True`` (the reduced-quorum band, ISSUE 19) samples the
    attested 2f+1 trust model instead: the roster is drawn from the
    n >= 2f+1 shapes {3, 5, 7} at FULL fault budget f = (n-1)//2 —
    rosters the baseline 3f+1 arithmetic cannot carry — with
    ``Config.attested_log`` + ``Config.reduced_quorum`` mounted.  The
    coalition is restricted to wire-level + crash/partition faults
    plus the Equivocator, because that is the model's contract: the
    reduced quorum's intersection argument assumes equivocation is
    EXCLUDED (the attested log converts it to detectable omission),
    not that arbitrary semantic lies are tolerated past n/3.  This
    band is a NEW seed stream (n and f are drawn differently by
    construction); every reduced=False band's stream is untouched.

    ``lanes=True`` (the lane shard-out band, ISSUE 20) draws a lane
    count S from {2, 3, 4} — LAST of all keys, after the ingress
    draw, so every older band's seed stream stays bit-identical —
    and mounts Config.lanes=S: S independent HBBFT lanes over the
    one roster, tx-hash-partitioned admission, and the deterministic
    cross-lane total-order merge.  Gates the merge-determinism and
    cross-lane settle-exactly-once invariants.  Incompatible with
    ``reconfig`` (dynamic membership is a lanes=1 feature; Config
    enforcement aside, the WAL lane framing has no reconfig
    records)."""
    rng = random.Random(seed)
    if reduced:
        n = rng.choice((3, 5, 7))
        f = (n - 1) // 2
    else:
        f = (n - 1) // 3
    ids = [f"node{i:03d}" for i in range(n)]
    bad = sorted(rng.sample(ids, f)) if f else []

    behaviors: List[dict] = []
    if reduced:
        # the only semantic behavior the band mounts is the attack
        # the attested log exists to kill; its lies must degrade to
        # omission (detected + excluded), never fork honest ledgers
        for node in bad:
            if rng.random() < 0.5:
                behaviors.append(
                    {
                        "kind": "equivocator",
                        "node": node,
                        "seed": rng.randrange(1 << 16),
                    }
                )
    else:
        for node in bad:
            for kind in rng.sample(_SEMANTIC_KINDS, rng.randrange(0, 3)):
                behaviors.append(
                    {
                        "kind": kind,
                        "node": node,
                        "seed": rng.randrange(1 << 16),
                    }
                )

    wire: List[dict] = []
    for stage, argspec in _WIRE_STAGES:
        if rng.random() < 0.35:
            args = {
                name: round(rng.uniform(lo, hi), 3)
                for name, (lo, hi) in argspec.items()
            }
            wire.append({"stage": stage, "args": args})

    timeline: List[dict] = []
    if bad and rng.random() < 0.5:
        victim = rng.choice(bad)
        at = rng.randrange(1, max(2, rounds // 2))
        timeline.append({"round": at, "op": "crash", "node": victim})
        if rng.random() < 0.6:
            timeline.append(
                {
                    "round": rng.randrange(at + 1, at + 4),
                    "op": "recover",
                    "node": victim,
                }
            )
    if bad and rng.random() < 0.4:
        b = rng.choice(bad)
        peer = rng.choice([i for i in ids if i != b])
        at = rng.randrange(0, max(1, rounds // 2))
        timeline.append(
            {"round": at, "op": "partition", "node": b, "peer": peer}
        )
        timeline.append(
            {
                "round": rng.randrange(at + 1, at + 4),
                "op": "heal",
                "node": b,
                "peer": peer,
            }
        )
    if reconfig:
        honest_now = [i for i in ids if i not in bad]
        ev = {
            "round": rng.randrange(1, 4),
            "op": "reconfig",
            "node": honest_now[0],  # submit via a surviving honest node
            "join": [f"nodeJ{seed % 100:02d}"],
            "retire": (
                [rng.choice(bad)] if bad and rng.random() < 0.5 else []
            ),
        }
        timeline.append(ev)
    timeline.sort(key=lambda ev: (ev["round"], ev["op"], ev["node"]))
    if pipeline_depth is None:
        # K-deep pipelined frontiers (ISSUE 15): the cross-frontier
        # invariants must hold over every window width, so depth is
        # part of the sampled schedule space
        pipeline_depth = rng.choice((1, 2, 4))
    if wan and wan_profile is None:
        # WAN link-delay plane (ISSUE 16): drawn LAST — the newest
        # appended key, after depth — so non-WAN replays of historical
        # seeds are untouched and WAN-band schedules share every other
        # draw with their non-WAN twins
        from cleisthenes_tpu.transport.wan import wan_profile_names

        wan_profile = rng.choice(wan_profile_names())
    ingress_cfg: Optional[dict] = None
    if ingress:
        # client-ingress admission schedule (ISSUE 18): drawn LAST —
        # the newest appended key, after the WAN draw — so non-ingress
        # replays of historical seeds are untouched and an ingress
        # schedule shares every other draw with its non-ingress twin.
        # capacity below the per-admitter share of txs (txs spread
        # round-robin over the honest nodes) forces priority eviction
        # / RETRY_AFTER on some seeds; client_cap 2 trips per-client
        # backpressure; the dup fraction exercises the ingress-side
        # seen-ring dedup
        ingress_cfg = {
            "capacity": rng.choice((2, 3, 6, 16)),
            "client_cap": rng.choice((2, 4, 64)),
            "clients": rng.choice((3, 5, 8)),
            "dup_fraction": round(rng.uniform(0.0, 0.4), 3),
            "client_seed": rng.randrange(1 << 16),
        }
    lanes_n: Optional[int] = None
    if lanes:
        if reconfig:
            raise ValueError(
                "the lane band cannot compose with reconfig "
                "(Config.lanes > 1 rejects dynamic membership)"
            )
        # lane shard-out (ISSUE 20): drawn LAST — the newest appended
        # key, after the ingress draw — so non-lane replays of
        # historical seeds are untouched and a lane schedule shares
        # every other draw with its single-lane twin
        lanes_n = rng.choice((2, 3, 4))

    out = {
        "version": SCHEDULE_VERSION,
        "seed": seed,
        "pipeline_depth": pipeline_depth,
        "n": n,
        "f": f,
        "batch_size": 8,
        "key_seed": 33,
        "rounds": rounds,
        "txs": 3 * n,
        "bad": bad,
        "behaviors": behaviors,
        "wire": wire,
        "timeline": timeline,
        "check_liveness": True,
    }
    if wan_profile is not None:
        out["wan_profile"] = wan_profile
    if ingress_cfg is not None:
        out["ingress"] = ingress_cfg
    if lanes_n is not None:
        out["lanes"] = lanes_n
    if reduced:
        # one key implies both flags: Config enforces that the
        # reduced quorum never mounts without the attested log
        out["reduced"] = True
    return out


# ---------------------------------------------------------------------------
# schedule execution
# ---------------------------------------------------------------------------


def _build_cluster(schedule: dict, trace: bool) -> SimulatedCluster:
    by_node: Dict[str, list] = {}
    for spec in schedule["behaviors"]:
        b = make_behavior(
            spec["kind"], seed=spec.get("seed", 0), **spec.get("args", {})
        )
        by_node.setdefault(spec["node"], []).append(b)
    behaviors = {
        nid: (bs[0] if len(bs) == 1 else CompositeBehavior(bs))
        for nid, bs in by_node.items()
    }
    depth = int(schedule.get("pipeline_depth", 1))
    # the lead must clear depth + the DEFAULT lag the cluster runs
    # under (read off the dataclass, never a re-stated literal)
    lag = Config.__dataclass_fields__["decrypt_lag_max"].default
    # client-ingress band (ISSUE 18): the schedule mounts the
    # fee-priority mempool at its sampled capacity; absent on
    # historical schedules (capacity 0 keeps the direct
    # add_transaction path)
    ing = schedule.get("ingress")
    # reduced-quorum band (ISSUE 19): the schedule key mounts the
    # attested sender log AND the n-f quorum arithmetic together
    # (Config rejects the latter without the former); Config
    # re-derives f = (n-1)//2 to match the schedule's coalition size
    red = bool(schedule.get("reduced"))
    cfg = Config(
        n=schedule["n"],
        batch_size=schedule["batch_size"],
        seed=schedule["seed"],
        trace=trace,
        attested_log=red,
        reduced_quorum=red,
        # schedules may pin the routing arm: wave_routing drains a
        # whole wave before any handler runs, so the scalar arm's
        # finer per-message interleavings are a schedule space of
        # their own — a band stays pinned to it (the key round-trips
        # through repro files like every other schedule field)
        wave_routing=schedule.get("wave_routing", True),
        # K-deep window (ISSUE 15): depth rides the schedule; the
        # reconfig lead stretches with it where the default would
        # violate Config's lead > depth + decrypt_lag_max bound
        pipeline_depth=depth,
        reconfig_lead=max(8, depth + lag + 1),
        mempool_capacity=(0 if ing is None else int(ing["capacity"])),
        mempool_client_cap=(
            64 if ing is None else int(ing["client_cap"])
        ),
        # lane shard-out band (ISSUE 20): absent on historical
        # schedules (lanes=1 keeps the single-lane build bit-for-bit)
        lanes=int(schedule.get("lanes", 1)),
    )
    cluster = SimulatedCluster(
        n=schedule["n"],
        config=cfg,
        seed=schedule["seed"],
        key_seed=schedule["key_seed"],
        behaviors=behaviors,
        # WAN band (ISSUE 16): the schedule key mounts the seeded
        # link-delay profile; absent on historical schedules
        wan_profile=schedule.get("wan_profile"),
    )
    if schedule["wire"]:
        coal = Coalition(schedule["bad"], seed=schedule["seed"])
        for spec in schedule["wire"]:
            getattr(coal, spec["stage"])(**spec["args"])
        cluster.fault_filter = coal.filter
    return cluster


def _apply_event(cluster, ev: dict) -> None:
    op = ev["op"]
    net = cluster.net
    if op == "crash":
        net.crash(ev["node"])
    elif op == "recover":
        net.recover(ev["node"])
    elif op == "partition":
        net.partition(ev["node"], ev["peer"])
    elif op == "heal":
        net.heal(ev["node"], ev["peer"])
    elif op == "reconfig":
        # dynamic membership: joiners wire in, the RECONFIG tx is
        # submitted via the named (honest, surviving) node, and the
        # in-band reshare ceremony runs composed with whatever other
        # faults the schedule mounts
        cluster.begin_reconfig(
            join=ev.get("join", ()),
            retire=ev.get("retire", ()),
            submit_via=ev["node"],
        )
    else:
        raise ValueError(f"unknown timeline op {op!r}")


def _check_safety(cluster, honest: List[str], submitted: set, rnd: int):
    """Raise Violation on any safety breach at this quiescence point.

    ``honest`` is the STATIC honest list; joiners added mid-run by a
    reconfig event are honest by construction and fold in here, so
    the agreement/no-foreign-tx/roster invariants span the roster
    change (a joiner still bootstrapping contributes depth 0 and
    tightens nothing until it adopts)."""
    from cleisthenes_tpu.core.ledger import decode_ordered_body
    from cleisthenes_tpu.protocol.reconfig import is_protocol_tx

    nodes = cluster.nodes
    depth = min(len(nodes[h].committed_batches) for h in honest)
    for e in range(depth):
        bodies = {
            encode_batch_body(e, nodes[h].committed_batches[e])
            for h in honest
        }
        if len(bodies) != 1:
            raise Violation(
                "agreement",
                f"honest ledgers fork at epoch {e}",
                rnd,
            )
    for h in honest:
        # merged total order (== committed_batches at lanes=1): the
        # foreign-tx sweep must cover EVERY lane's settled work, and
        # a tx that settled in two lanes is a cross-lane
        # exactly-once breach (ISSUE 20)
        seen_txs: set = set()
        for e, batch in enumerate(nodes[h].merged_batches):
            for tx in batch.tx_list():
                if tx not in submitted and not is_protocol_tx(tx):
                    # reconfig-machinery txs (RECONFIG + dealings)
                    # are node-originated, never client-submitted
                    raise Violation(
                        "no_foreign_tx",
                        f"{h} committed unsubmitted tx {tx!r} "
                        f"in epoch {e}",
                        rnd,
                    )
                if tx in seen_txs:
                    raise Violation(
                        "lane_exactly_once",
                        f"{h} settled tx {tx!r} in two merged "
                        f"slots (second at {e})",
                        rnd,
                    )
                seen_txs.add(tx)
    # -- merge determinism (ISSUE 20, Config.lanes > 1) ---------------
    # every honest node's merged total order is byte-identical at the
    # common merged frontier: the merge is a pure function of the
    # committed lane streams, so a divergence here is a fork even
    # when each per-lane ledger agrees
    mdepth = min(nodes[h].merged_settled_frontier for h in honest)
    for e in range(mdepth):
        bodies = {
            encode_batch_body(e, nodes[h].merged_batches[e])
            for h in honest
        }
        if len(bodies) != 1:
            raise Violation(
                "merge_determinism",
                f"honest MERGED orders fork at slot {e}",
                rnd,
            )
    # -- roster agreement (dynamic membership) ------------------------
    # every honest node that installed a roster version agrees on its
    # activation epoch and key-material digest (the committed ceremony
    # is one log; divergent keys would be a consensus fork in disguise)
    versions: Dict[int, tuple] = {}
    for h in honest:
        for rv in nodes[h].rosters:
            if not rv.key_material_digest:
                # synthetic genesis record (a joiner's base version
                # carries no ceremony material), never comparable to
                # the real installed version of the same number
                continue
            got = (rv.activation_epoch, rv.member_ids,
                   rv.key_material_digest)
            want = versions.setdefault(rv.version, got)
            if got != want:
                raise Violation(
                    "roster_agreement",
                    f"{h} roster v{rv.version} diverges "
                    f"(activation/members/keys)",
                    rnd,
                )
    # -- two-frontier invariants (ISSUE 8, Config.order_then_settle) --
    # checked PER LANE (nodes[h].lanes is [self] at lanes=1): each
    # lane runs its own ordered/settled frontier pair
    lag_max = cluster.config.decrypt_lag_max
    for h, hb in (
        (h, lane_hb) for h in honest for lane_hb in nodes[h].lanes
    ):
        settled = len(hb.committed_batches)
        # backpressure bound: a coalition delaying settlement (share
        # forgery) may park ordering AT the bound, never push it past
        if hb.epoch - settled > lag_max:
            raise Violation(
                "decrypt_lag_bound",
                f"{h} ordered frontier {hb.epoch} ran "
                f"{hb.epoch - settled} epochs ahead of settlement "
                f"(bound {lag_max})",
                rnd,
            )
        # the settled prefix is a prefix OF the ordered log: every
        # settled epoch that was locally ordered commits exactly the
        # proposals its COrd record agreed on (epochs adopted via
        # plaintext catch-up alone legitimately carry no COrd)
        for e in range(settled):
            body = hb.ordered_record(e)
            if body is None:
                continue
            oepoch, output = decode_ordered_body(body)
            if oepoch != e:
                raise Violation(
                    "ordered_prefix",
                    f"{h} COrd body for epoch {e} claims epoch "
                    f"{oepoch}",
                    rnd,
                )
            extra = set(
                hb.committed_batches[e].contributions
            ) - set(output)
            if extra:
                raise Violation(
                    "ordered_prefix",
                    f"{h} settled epoch {e} with proposers "
                    f"{sorted(extra)} absent from its ordered record",
                    rnd,
                )
    # honest nodes' ordered logs are byte-identical wherever two of
    # them ordered the same epoch (the ACS output is one agreed value;
    # COrd bodies are its canonical encoding) — checked per lane
    # (every honest node runs the same Config.lanes; min() guards a
    # mid-bootstrap joiner's view)
    n_lanes = min(len(nodes[h].lanes) for h in honest)
    for k in range(n_lanes):
        ordered_depth = max(nodes[h].lanes[k].epoch for h in honest)
        for e in range(ordered_depth):
            bodies = {
                body
                for h in honest
                if (body := nodes[h].lanes[k].ordered_record(e))
                is not None
            }
            if len(bodies) > 1:
                raise Violation(
                    "ordered_agreement",
                    f"honest ORDERED logs fork at lane {k} "
                    f"epoch {e}",
                    rnd,
                )


def _ingress_submit(
    cluster,
    honest: List[str],
    schedule: dict,
    submitted: set,
    ok_acked: Dict[bytes, str],
) -> None:
    """Drive the schedule's client band through the in-proc ingress
    twins (ISSUE 18): every tx submits as an encoded client frame via
    SimulatedCluster.ingress() — the production admission path — with
    client identity, fee bid and duplicate resubmits drawn from the
    schedule's ``client_seed``.  Fills ``submitted`` (every tx, for
    no_foreign_tx) and ``ok_acked`` (tx -> admitting node, for the
    settle-exactly-once audit).  Raises Violation on an
    admission-contract breach at submit time: an unknown ack status,
    or a resubmit of an OK-acked tx that does not ack DUPLICATE."""
    from cleisthenes_tpu.transport.message import IngressStatus

    ing = schedule["ingress"]
    irng = random.Random(ing["client_seed"])
    clients = [f"fzclient{c:02d}" for c in range(ing["clients"])]
    gates = {h: cluster.ingress(h) for h in honest}
    for i in range(schedule["txs"]):
        tx = b"fuzz-%06d" % i
        h = honest[i % len(honest)]
        client = irng.choice(clients)
        fee = irng.randrange(1, 1_000)
        # the dup decision draws BEFORE the ack is known, so the rng
        # stream's shape never depends on mempool admission outcomes
        want_dup = irng.random() < ing["dup_fraction"]
        ack = gates[h].submit(client, i, fee, tx)
        submitted.add(tx)
        status = IngressStatus(ack.status)
        if status is IngressStatus.OK:
            ok_acked[tx] = h
        elif status is not IngressStatus.RETRY_AFTER:
            # fresh unique well-formed txs may only ack OK (admitted)
            # or RETRY_AFTER (per-client/global pressure); DUPLICATE
            # or REJECTED here is an admission-contract breach
            raise Violation(
                "ingress_ack",
                f"fresh tx {tx!r} acked {status.name} on {h}",
                0,
            )
        if want_dup and status is IngressStatus.OK:
            dup = gates[h].submit(client, i, fee, tx)
            if IngressStatus(dup.status) is not IngressStatus.DUPLICATE:
                raise Violation(
                    "ingress_dedup",
                    f"resubmit of OK-acked tx {tx!r} acked "
                    f"{IngressStatus(dup.status).name}, want DUPLICATE",
                    0,
                )


def _ingress_audit(
    cluster,
    honest: List[str],
    ok_acked: Dict[bytes, str],
    rounds_used: int,
) -> Optional[dict]:
    """The band's terminal invariant (ISSUE 18): every acked-and-
    unevicted tx settles EXACTLY once.  Concretely, on the reference
    honest ledger (agreement already holds, so any honest node is
    every honest node): no tx settles twice (the settle-time dedup
    layer), and the OK-acked txs missing from the ledger are exactly
    accounted by the honest mempools' eviction counters — an OK ack
    is a promise: settle, or evict VISIBLY.  A tx stranded pending
    (liveness hole) is unsettled-but-unevicted and fails the same
    equation, so the standard liveness tail is subsumed.  Finally a
    subscribe(0) replay on the reference node must stream the settled
    epochs gap- and duplicate-free."""
    nodes = cluster.nodes
    ref = nodes[honest[0]]
    counts: Dict[bytes, int] = {}
    for batch in ref.committed_batches:
        for tx in batch.tx_list():
            counts[tx] = counts.get(tx, 0) + 1
    for tx, c in counts.items():
        if c > 1:
            return {
                "invariant": "ingress_exact_once",
                "detail": f"tx {tx!r} settled {c} times",
                "round": rounds_used,
            }
    lost = sorted(tx for tx in ok_acked if tx not in counts)
    evicted = sum(
        nodes[h].mempool.stats()["evicted"]
        for h in honest
        if nodes[h].mempool is not None
    )
    if len(lost) != evicted:
        return {
            "invariant": "ingress_exact_once",
            "detail": (
                f"{len(lost)} OK-acked txs unsettled vs {evicted} "
                f"visible evictions"
            ),
            "round": rounds_used,
        }
    gate = cluster.ingress(honest[0])
    feed = gate.subscribe(0)
    got: List[int] = []
    while True:
        batch = gate.next_batch(feed, timeout=0.05)
        if batch is None:
            break
        got.append(batch.epoch)
    feed.close()
    if got != list(range(len(ref.committed_batches))):
        return {
            "invariant": "ingress_replay",
            "detail": (
                f"subscribe(0) streamed epochs {got}, want "
                f"0..{len(ref.committed_batches) - 1} contiguous"
            ),
            "round": rounds_used,
        }
    return None


def _reduced_audit(
    cluster, bad: List[str], rounds_used: int
) -> Optional[dict]:
    """The reduced-quorum band's terminal invariants (ISSUE 19).

    1. No false accusations: counter-fork evidence only ever
       accumulates against coalition members — an honest sender's
       vault never refuses, so an accusation of one would mean forged
       evidence (or an honest equivocation, either being a bug).
    2. Detection: every coalition equivocator whose vault actually
       refused a forked slot is in the evidence directory — its
       self-incriminating refused=1 frames reached at least one
       honest receiver and were recorded (the coalition's wire
       faults can drop SOME frames, but a lie the protocol plane
       kept retrying cannot stay invisible for a whole run).
    3. Exactly-once settle: on the reference honest ledger no tx
       settles twice — the n-f quorum arithmetic must not weaken the
       dedup/commit rule at n = 2f+1.
    """
    dirc = cluster.attest_dir
    false_accused = sorted(dirc.accused - set(bad))
    if false_accused:
        return {
            "invariant": "attest_no_false_accusation",
            "detail": f"honest nodes accused of forks: {false_accused}",
            "round": rounds_used,
        }
    undetected = sorted(
        nid
        for nid in bad
        if getattr(cluster.auths.get(nid), "vault", None) is not None
        and cluster.auths[nid].vault.refusals > 0
        and nid not in dirc.accused
    )
    if undetected:
        return {
            "invariant": "attest_fork_detection",
            "detail": (
                f"equivocators forked attested slots undetected: "
                f"{undetected}"
            ),
            "round": rounds_used,
        }
    ref = next(
        cluster.nodes[nid]
        for nid in sorted(cluster.nodes)
        if nid not in bad
    )
    counts: Dict[bytes, int] = {}
    for batch in ref.committed_batches:
        for tx in batch.tx_list():
            counts[tx] = counts.get(tx, 0) + 1
    dups = sorted(tx for tx, c in counts.items() if c > 1)
    if dups:
        return {
            "invariant": "reduced_exact_once",
            "detail": f"txs settled more than once: {dups[:4]}",
            "round": rounds_used,
        }
    return None


def run_schedule(
    schedule: dict, trace_path: Optional[str] = None
) -> Optional[dict]:
    """Execute one schedule; returns the violation report dict, or
    None if every invariant held.  With ``trace_path`` the run records
    a flight-recorder artifact (written whether or not it fails)."""
    cluster = _build_cluster(schedule, trace=trace_path is not None)
    bad = set(schedule["bad"])
    honest = [nid for nid in cluster.ids if nid not in bad]
    ing = schedule.get("ingress")
    submitted: set = set()
    ok_acked: Dict[bytes, str] = {}
    if ing is None:
        for i in range(schedule["txs"]):
            tx = b"fuzz-%06d" % i
            cluster.nodes[honest[i % len(honest)]].add_transaction(tx)
            submitted.add(tx)

    by_round: Dict[int, List[dict]] = {}
    for ev in schedule["timeline"]:
        by_round.setdefault(ev["round"], []).append(ev)

    def before_round(r: int) -> None:
        for ev in by_round.get(r, ()):
            _apply_event(cluster, ev)

    def on_quiescence(r: int) -> None:
        # recomputed per round: a reconfig event adds joiners (honest
        # by construction) to the cluster mid-run
        cur = [nid for nid in sorted(cluster.nodes) if nid not in bad]
        _check_safety(cluster, cur, submitted, r)

    violation: Optional[dict] = None
    rounds_used = schedule["rounds"]
    try:
        if ing is not None:
            # client-ingress band: submission IS part of the schedule
            # under test (ack-contract violations shrink like any
            # other), so it runs inside the violation scope
            _ingress_submit(cluster, honest, schedule, submitted,
                            ok_acked)
        rounds_used = run_until_drained(
            cluster.net,
            cluster.nodes,
            skip=bad,
            max_rounds=schedule["rounds"],
            before_round=before_round,
            on_quiescence=on_quiescence,
        )
    except Violation as v:
        violation = v.report
    if violation is None and ing is not None:
        # the band's terminal check replaces the standard liveness
        # tail: settle-exactly-once subsumes it (a stranded pending tx
        # is unsettled-but-unevicted and fails the accounting)
        final = [
            nid
            for nid in sorted(cluster.nodes)
            if nid not in bad and not cluster.nodes[nid]._retired_self
        ]
        violation = _ingress_audit(cluster, final, ok_acked,
                                   rounds_used)
    elif violation is None and schedule.get("check_liveness", True):
        # liveness spans the roster change: every honest node that is
        # (still) a member at the end — original members AND joiners —
        # must hold every submitted tx.  A retired honest node stops
        # at its activation boundary by design, so it is exempt from
        # the tail (the sampler only retires coalition members, but
        # the rule is stated generally for hand-written schedules).
        final = [
            nid
            for nid in sorted(cluster.nodes)
            if nid not in bad and not cluster.nodes[nid]._retired_self
        ]
        for h in final:
            committed = {
                tx
                for b in cluster.nodes[h].merged_batches
                for tx in b.tx_list()
            }
            missing = submitted - committed
            if missing or cluster.nodes[h].pending_tx_count():
                violation = {
                    "invariant": "liveness",
                    "detail": (
                        f"{h} missing {len(missing)} submitted txs "
                        f"after {rounds_used} rounds"
                    ),
                    "round": rounds_used,
                }
                break
    if violation is None and schedule.get("reduced"):
        # the band's extra terminal invariants: fork evidence only
        # against the coalition, every actual equivocation detected,
        # settle-exactly-once at n = 2f+1
        violation = _reduced_audit(
            cluster, schedule["bad"], rounds_used
        )
    if trace_path is not None:
        cluster.write_trace(trace_path)
    return violation


# ---------------------------------------------------------------------------
# shrinking + repro files
# ---------------------------------------------------------------------------


def shrink(schedule: dict, violation: Optional[dict] = None):
    """Greedily minimize a failing schedule: drop timeline events,
    wire stages and behaviors one at a time (keeping any removal that
    still fails), then halve txs and rounds.  Returns
    ``(minimal_schedule, violation)``.

    A candidate is kept only if it violates the SAME invariant as the
    original failure — otherwise e.g. halving the round budget under a
    mounted delay fault could manufacture an unrelated 'liveness'
    artifact and the shrinker would happily minimize that instead of
    the real bug.  Deterministic — the candidate order is fixed — and
    terminates because every accepted edit strictly shrinks the
    schedule.  Pass the already-observed ``violation`` to skip the
    redundant confirming run."""
    base_v = violation if violation is not None else run_schedule(schedule)
    if base_v is None:
        raise ValueError("shrink() needs a failing schedule")
    want = base_v["invariant"]

    def still_fails(cand: dict) -> Optional[dict]:
        v = run_schedule(cand)
        return v if v is not None and v["invariant"] == want else None

    cur = copy.deepcopy(schedule)
    cur_v = base_v
    changed = True
    while changed:
        changed = False
        for key in ("timeline", "wire", "behaviors"):
            i = 0
            while i < len(cur[key]):
                cand = copy.deepcopy(cur)
                del cand[key][i]
                v = still_fails(cand)
                if v is not None:
                    cur, cur_v = cand, v
                    changed = True
                else:
                    i += 1
        for field, floor in (("txs", 1), ("rounds", 2)):
            while cur[field] > floor:
                cand = copy.deepcopy(cur)
                cand[field] = max(floor, cur[field] // 2)
                v = still_fails(cand)
                if v is None:
                    break
                cur, cur_v = cand, v
                changed = True
    return cur, cur_v


def write_repro(
    path: str, schedule: dict, violation: dict
) -> None:
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(
            {"schedule": schedule, "violation": violation},
            fh,
            indent=2,
            sort_keys=True,
        )
        fh.write("\n")


def load_repro(path: str) -> dict:
    with open(path, "r", encoding="utf-8") as fh:
        return json.load(fh)


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def _parse_seeds(spec: str) -> List[int]:
    """"0:20" -> [0..19]; "3,7,11" -> [3, 7, 11]; "5" -> [5]."""
    if ":" in spec:
        lo, hi = spec.split(":", 1)
        return list(range(int(lo), int(hi)))
    return [int(s) for s in spec.replace(",", " ").split()]


def fuzz_seeds(
    seeds: Sequence[int],
    n: int = 4,
    rounds: int = 12,
    out_dir: Optional[str] = None,
    trace: bool = True,
    reconfig: bool = False,
    pipeline_depth: Optional[int] = None,
    wan: bool = False,
    wan_profile: Optional[str] = None,
    ingress: bool = False,
    reduced: bool = False,
    lanes: bool = False,
) -> int:
    """Run a schedule per seed; on the first violation, shrink it and
    emit a repro file plus (by default) a flight-recorder trace
    artifact of the minimal failing run.  Returns a process exit code
    (0 = every invariant held on every seed)."""
    import pathlib

    for seed in seeds:
        schedule = sample_schedule(
            seed,
            n=n,
            rounds=rounds,
            reconfig=reconfig,
            pipeline_depth=pipeline_depth,
            wan=wan,
            wan_profile=wan_profile,
            ingress=ingress,
            reduced=reduced,
            lanes=lanes,
        )
        violation = run_schedule(schedule)
        if violation is None:
            print(f"seed {seed:6d}: ok")
            continue
        print(f"seed {seed:6d}: VIOLATION {violation['invariant']}")
        minimal, final = shrink(schedule, violation)
        out = pathlib.Path(out_dir or ".")
        out.mkdir(parents=True, exist_ok=True)
        repro_path = out / f"fuzz_repro_seed{seed}.json"
        write_repro(str(repro_path), minimal, final)
        print(f"  minimal repro -> {repro_path}")
        if trace:
            trace_path = out / f"fuzz_repro_seed{seed}.trace.json"
            run_schedule(minimal, trace_path=str(trace_path))
            print(f"  flight-recorder artifact -> {trace_path}")
        return 1
    return 0


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="tools.fuzz", description=__doc__.splitlines()[0]
    )
    ap.add_argument("--seeds", help="seed range lo:hi or list a,b,c")
    ap.add_argument("--seed", type=int, help="single seed")
    ap.add_argument("--n", type=int, default=4, help="cluster size")
    ap.add_argument("--rounds", type=int, default=12)
    ap.add_argument(
        "--reconfig",
        action="store_true",
        help="dynamic-membership band: compose a join/retire "
        "reconfig event into every sampled schedule",
    )
    ap.add_argument(
        "--pipeline-depth",
        type=int,
        default=None,
        help="pin the K-deep protocol-plane window "
        "(Config.pipeline_depth) in every sampled schedule; "
        "default draws depth from the seed",
    )
    ap.add_argument(
        "--wan",
        action="store_true",
        help="WAN band: mount a seeded link-delay profile "
        "(transport.wan.PROFILES) drawn from each seed, appended "
        "LAST so historical seed streams extend",
    )
    ap.add_argument(
        "--wan-profile",
        default=None,
        help="pin one named WAN profile instead of drawing it from "
        "the seed (implies --wan)",
    )
    ap.add_argument(
        "--ingress",
        action="store_true",
        help="client-ingress band (ISSUE 18): submit every tx "
        "through the in-proc ingress twin + fee-priority mempool "
        "with a seeded client/fee/dup schedule, appended LAST so "
        "historical seed streams extend; gates the "
        "settle-exactly-once invariant",
    )
    ap.add_argument(
        "--reduced-quorum",
        action="store_true",
        help="reduced-quorum band (ISSUE 19): attested sender log + "
        "n-f quorum arithmetic on 2f+1-shaped rosters drawn from "
        "{3,5,7} at f=(n-1)//2, coalition restricted to wire/crash "
        "faults + the Equivocator; gates the fork-evidence, "
        "no-false-accusation and settle-exactly-once invariants",
    )
    ap.add_argument(
        "--lanes",
        action="store_true",
        help="lane shard-out band (ISSUE 20): draw Config.lanes "
        "from {2,3,4} per seed, appended LAST so historical seed "
        "streams extend; gates the merge-determinism and "
        "cross-lane settle-exactly-once invariants",
    )
    ap.add_argument(
        "--show", action="store_true", help="print the schedule, no run"
    )
    ap.add_argument("--repro", help="replay a repro file")
    ap.add_argument("--out", help="directory for repro artifacts")
    ap.add_argument(
        "--no-trace",
        action="store_true",
        help="skip the flight-recorder artifact for failing runs",
    )
    args = ap.parse_args(argv)

    if args.repro:
        rep = load_repro(args.repro)
        violation = run_schedule(rep["schedule"])
        want = rep.get("violation")
        print(f"replayed: {violation}")
        if violation is None:
            print("repro no longer triggers a violation")
            return 1
        if want and violation["invariant"] != want["invariant"]:
            print(f"violation changed (recorded: {want})")
            return 1
        return 0

    if args.seed is not None:
        seeds: List[int] = [args.seed]
    elif args.seeds:
        seeds = _parse_seeds(args.seeds)
    else:
        ap.error("need --seed, --seeds or --repro")
        return 2

    wan = args.wan or args.wan_profile is not None
    if args.show:  # print the sampled schedule(s), run nothing
        for seed in seeds:
            schedule = sample_schedule(
                seed, n=args.n, rounds=args.rounds,
                reconfig=args.reconfig,
                pipeline_depth=args.pipeline_depth,
                wan=wan,
                wan_profile=args.wan_profile,
                ingress=args.ingress,
                reduced=args.reduced_quorum,
                lanes=args.lanes,
            )
            json.dump(schedule, sys.stdout, indent=2, sort_keys=True)
            print()
        return 0
    return fuzz_seeds(
        seeds,
        n=args.n,
        rounds=args.rounds,
        out_dir=args.out,
        trace=not args.no_trace,
        reconfig=args.reconfig,
        pipeline_depth=args.pipeline_depth,
        wan=wan,
        wan_profile=args.wan_profile,
        ingress=args.ingress,
        reduced=args.reduced_quorum,
        lanes=args.lanes,
    )


if __name__ == "__main__":
    sys.exit(main())
