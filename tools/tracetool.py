"""tracetool: merge, validate and explain flight-recorder artifacts.

The recorder half lives in `cleisthenes_tpu/utils/trace.py` (per-node
bounded rings, merged into one Chrome-trace-event JSON by
`to_chrome`); this tool is the analysis half:

- ``--validate``  schema gate: every event carries a known category,
  a name, timestamps, and a per-track ``seq`` that increases strictly
  monotonically (sequence numbers are the determinism-plane ordering
  truth; timestamps are observability-only).  The ci.sh observability
  stage pipes a freshly captured seeded-cluster artifact through this.
- ``--report`` (default)  per-epoch critical-path attribution: the
  wall time from the earliest ``epoch/open`` to the latest
  ``epoch/commit`` is tiled by the merged event timeline — each gap is
  attributed to the stage (category) of the event that TERMINATES it,
  which in the serialized in-proc cluster is literally "what the run
  was computing toward next".  Prints per-epoch stage shares, the
  longest chain segments, and a summary table (hub dispatch counts by
  class, wave sizes, p50/p95 span durations).
- ``--capture OUT``  runs a seeded N-node SimulatedCluster with
  tracing on and writes the merged artifact — the self-contained
  source of CI fixtures and quick local looks.

Open artifacts interactively at https://ui.perfetto.dev ("Open trace
file"); one track per node, spans nested by category.  Schema details:
docs/TRACING.md.
"""

from __future__ import annotations

import argparse
import bisect
import json
import operator
import pathlib
import sys
from typing import Dict, List, Optional, Tuple

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from cleisthenes_tpu.utils.trace import CATEGORIES  # noqa: E402

_ALLOWED_PH = frozenset(("M", "X", "i"))


# ---------------------------------------------------------------------------
# loading & validation
# ---------------------------------------------------------------------------


def load(path: str) -> dict:
    with open(path, "r", encoding="utf-8") as fh:
        return json.load(fh)


def track_names(doc: dict) -> Dict[int, str]:
    """tid -> node name from the thread_name metadata events."""
    out: Dict[int, str] = {}
    for ev in doc.get("traceEvents", ()):
        if ev.get("ph") == "M" and ev.get("name") == "thread_name":
            out[ev.get("tid", 0)] = str(ev.get("args", {}).get("name", ""))
    return out


def validate(doc: dict) -> List[str]:
    """Schema + per-track monotone-sequence check; [] means valid."""
    errors: List[str] = []
    events = doc.get("traceEvents")
    if not isinstance(events, list):
        return ["artifact has no traceEvents list"]
    if not events:
        return ["traceEvents is empty"]
    last_seq: Dict[int, int] = {}
    names = track_names(doc)
    for i, ev in enumerate(events):
        where = f"traceEvents[{i}]"
        if not isinstance(ev, dict):
            errors.append(f"{where}: not an object")
            continue
        ph = ev.get("ph")
        if ph not in _ALLOWED_PH:
            errors.append(f"{where}: unknown ph {ph!r}")
            continue
        if ph == "M":
            continue
        cat = ev.get("cat")
        if cat not in CATEGORIES:
            errors.append(f"{where}: unknown category {cat!r}")
        if not isinstance(ev.get("name"), str) or not ev["name"]:
            errors.append(f"{where}: missing event name")
        ts = ev.get("ts")
        if not isinstance(ts, (int, float)) or ts < 0:
            errors.append(f"{where}: bad ts {ts!r}")
        if ph == "X":
            dur = ev.get("dur")
            if not isinstance(dur, (int, float)) or dur < 0:
                errors.append(f"{where}: X event with bad dur {dur!r}")
        args = ev.get("args")
        if not isinstance(args, dict):
            errors.append(f"{where}: missing args")
            continue
        seq = args.get("seq")
        tid = ev.get("tid")
        if not isinstance(seq, int) or seq < 1:
            errors.append(f"{where}: bad args.seq {seq!r}")
            continue
        if tid in last_seq and seq <= last_seq[tid]:
            node = names.get(tid, tid)
            errors.append(
                f"{where}: seq {seq} not after {last_seq[tid]} on "
                f"track {node!r} (per-node sequence must be "
                "strictly increasing)"
            )
        last_seq[tid] = seq
    return errors


# ---------------------------------------------------------------------------
# per-epoch critical-path attribution
# ---------------------------------------------------------------------------


def _analysis_events(doc: dict) -> List[dict]:
    return [
        ev
        for ev in doc.get("traceEvents", ())
        if ev.get("ph") in ("X", "i")
    ]


def _point(ev: dict) -> float:
    """The instant an event 'happened': span END for X events (when
    the work finished), ts for instants."""
    return float(ev["ts"]) + float(ev.get("dur", 0.0))


def epoch_windows(doc: dict) -> Dict[Tuple[int, int], Tuple[float, float]]:
    """(lane, epoch) -> (us of earliest open, us of latest close),
    for every epoch with both markers.  Lane-sharded artifacts
    (Config.lanes > 1) tag epoch events with a ``lane`` arg; lanes
    reuse epoch numbers, so the key must carry the lane or the
    windows of S concurrent epoch-k runs would merge into one bogus
    span.  Single-lane artifacts carry no ``lane`` arg and key as
    lane 0 — the historical window set, unchanged.

    The closing marker is the latest ``epoch/ordered`` instant when
    the artifact carries one for that epoch (the two-frontier commit
    split, Config.order_then_settle: the protocol-plane epoch ENDS at
    the ciphertext-ordered commit; decryption trails on the settle
    track, visible as the ``settle/decrypt_lag`` spans outside these
    windows), falling back to the latest ``epoch/commit`` on coupled
    artifacts."""
    opens: Dict[Tuple[int, int], float] = {}
    commits: Dict[Tuple[int, int], float] = {}
    ordereds: Dict[Tuple[int, int], float] = {}
    for ev in _analysis_events(doc):
        if ev.get("cat") != "epoch":
            continue
        args = ev.get("args", {})
        epoch = args.get("epoch")
        if not isinstance(epoch, int):
            continue
        key = (int(args.get("lane", 0)), epoch)
        ts = float(ev["ts"])
        if ev["name"] == "open":
            if key not in opens or ts < opens[key]:
                opens[key] = ts
        elif ev["name"] == "commit":
            if key not in commits or ts > commits[key]:
                commits[key] = ts
        elif ev["name"] == "ordered":
            if key not in ordereds or ts > ordereds[key]:
                ordereds[key] = ts
    closes = {**commits, **ordereds}  # ordered wins where present
    return {
        k: (opens[k], closes[k])
        for k in sorted(opens)
        if k in closes and closes[k] > opens[k]
    }


def sorted_points(doc: dict) -> List[Tuple[float, str, str, int]]:
    """All event completion points (point_us, cat, name, tid), sorted
    once — epoch windows slice into this via bisect, so analyzing E
    (possibly overlapping, under pipelining) epochs costs one sort,
    not E re-sorts of the whole artifact."""
    return sorted(
        (
            (_point(ev), ev["cat"], ev["name"], ev.get("tid", 0))
            for ev in _analysis_events(doc)
        ),
        key=operator.itemgetter(0),
    )


def attribute_epoch(
    doc: dict,
    t_open: float,
    t_commit: float,
    points: Optional[List[Tuple[float, str, str, int]]] = None,
) -> Tuple[Dict[str, float], List[Tuple[float, str, str, int]]]:
    """Tile [t_open, t_commit] by the merged timeline.

    Returns (shares, chain): ``shares`` maps category -> attributed
    microseconds (summing to exactly the window — every gap ends at
    some recorded event, and the closing commit is itself an event);
    ``chain`` is the gap list (gap_us, cat, name, tid) in time order —
    its largest entries are the epoch's critical-path segments.

    ``points`` is the precomputed ``sorted_points(doc)`` list; pass it
    when analyzing many windows of one artifact.
    """
    if points is None:
        points = sorted_points(doc)
    key = operator.itemgetter(0)
    lo = bisect.bisect_right(points, t_open, key=key)
    hi = bisect.bisect_right(points, t_commit, key=key)
    shares: Dict[str, float] = {}
    chain: List[Tuple[float, str, str, int]] = []
    prev = t_open
    for point, cat, name, tid in points[lo:hi]:
        gap = point - prev
        if gap > 0:
            shares[cat] = shares.get(cat, 0.0) + gap
            chain.append((gap, cat, name, tid))
        prev = point
    # anything after the last recorded point (can only happen in a
    # degenerate artifact where commit was dropped by ring overflow)
    tail = t_commit - prev
    if tail > 0:
        shares["epoch"] = shares.get("epoch", 0.0) + tail
        chain.append((tail, "epoch", "(untraced tail)", 0))
    return shares, chain


def stage_shares(doc: dict) -> Dict[str, float]:
    """Whole-run per-stage fractions of total epoch wall time — the
    bench.py --trace breakdown (fractions sum to ~1.0)."""
    windows = epoch_windows(doc)
    points = sorted_points(doc)
    totals: Dict[str, float] = {}
    wall = 0.0
    for t_open, t_commit in windows.values():
        shares, _chain = attribute_epoch(doc, t_open, t_commit, points)
        for cat, us in shares.items():
            totals[cat] = totals.get(cat, 0.0) + us
        wall += t_commit - t_open
    if wall <= 0:
        return {}
    return {
        cat: round(us / wall, 4) for cat, us in sorted(totals.items())
    }


# ---------------------------------------------------------------------------
# summary tables
# ---------------------------------------------------------------------------


def _percentile(values: List[float], p: float) -> Optional[float]:
    if not values:
        return None
    vs = sorted(values)
    idx = min(len(vs) - 1, int(round((p / 100.0) * (len(vs) - 1))))
    return vs[idx]


def summarize(doc: dict) -> dict:
    """Counts + distributions: hub dispatch classes, wave sizes,
    span-duration percentiles, event counts by category."""
    by_cat: Dict[str, int] = {}
    span_durs: Dict[Tuple[str, str], List[float]] = {}
    wave_sizes: List[float] = []
    hub = {"flushes": 0, "dispatches": 0, "branches": 0, "decodes": 0,
           "shares": 0}
    # delivery-plane columnarization (ISSUE 9): frame_decode spans
    # carry memo_hit, mac_verify_batch spans carry batch_width — the
    # counters a critical-path capture needs to attribute the
    # delivery-plane delta
    delivery = {
        "frame_decodes": 0,
        "decode_memo_hits": 0,
        "mac_verify_batches": 0,
        # wave-routed ingest (ISSUE 10): one router/route span per
        # delivery wave; args carry the wave's payload count and the
        # batch handler dispatches it collapsed to
        "router_waves": 0,
        "router_payloads": 0,
        "router_dispatches": 0,
        # egress columnarization (ISSUE 13): one transport/frame_encode
        # span per egress wave (args: bundle count + encode-memo hits)
        # and one coin/share_batch span per native coin-issue dispatch
        # (args: items + distinct owners) — the send-side twins
        "frame_encode_waves": 0,
        "frame_encode_bundles": 0,
        "encode_memo_hits": 0,
        "coin_share_batches": 0,
        "coin_share_items": 0,
    }
    batch_widths: List[float] = []
    # lane shard-out (ISSUE 20): epoch events on lane-sharded
    # artifacts carry a ``lane`` arg; merge/emit instants mark the
    # total-order slots the cross-lane merge released
    lane_ordered: Dict[int, int] = {}
    merge_emits = 0
    for ev in _analysis_events(doc):
        cat = ev["cat"]
        by_cat[cat] = by_cat.get(cat, 0) + 1
        if ev["ph"] == "X":
            span_durs.setdefault((cat, ev["name"]), []).append(
                float(ev.get("dur", 0.0))
            )
        args = ev.get("args", {})
        if cat == "epoch" and ev["name"] in ("ordered", "commit"):
            lane = int(args.get("lane", 0))
            lane_ordered[lane] = lane_ordered.get(lane, 0) + 1
        elif cat == "merge" and ev["name"] == "emit":
            merge_emits += 1
        if cat == "hub" and ev["name"] == "flush":
            hub["flushes"] += 1
            for k in ("dispatches", "branches", "decodes", "shares"):
                hub[k] += int(args.get(k, 0))
        elif cat == "transport" and ev["name"] in ("wave", "queue_depth"):
            msgs = args.get("msgs")
            if isinstance(msgs, (int, float)):
                wave_sizes.append(float(msgs))
        elif cat == "transport" and ev["name"] == "frame_decode":
            # one span covers one prepare-wave's decode attempts for
            # one receiver; args carry the counts
            delivery["frame_decodes"] += int(args.get("frames", 1))
            delivery["decode_memo_hits"] += int(args.get("memo_hits", 0))
        elif cat == "transport" and ev["name"] == "mac_verify_batch":
            delivery["mac_verify_batches"] += 1
            width = args.get("batch_width")
            if isinstance(width, (int, float)):
                batch_widths.append(float(width))
        elif cat == "transport" and ev["name"] == "frame_encode":
            # NOTE: "bundles" (folded envelopes per wave) is a
            # different unit than the metrics counter frames_encoded
            # (payload BODIES actually encoded) — named apart so a
            # trace report is never cross-read as that counter
            delivery["frame_encode_waves"] += 1
            delivery["frame_encode_bundles"] += int(args.get("frames", 1))
            delivery["encode_memo_hits"] += int(args.get("memo_hits", 0))
        elif cat == "coin" and ev["name"] == "share_batch":
            delivery["coin_share_batches"] += 1
            delivery["coin_share_items"] += int(args.get("n", 0))
        elif cat == "router" and ev["name"] == "route":
            delivery["router_waves"] += 1
            delivery["router_payloads"] += int(args.get("payloads", 0))
            delivery["router_dispatches"] += int(
                args.get("dispatches", 0)
            )
    spans = {
        f"{cat}/{name}": {
            "n": len(durs),
            "p50_us": round(_percentile(durs, 50), 1),
            "p95_us": round(_percentile(durs, 95), 1),
        }
        for (cat, name), durs in sorted(span_durs.items())
    }
    delivery["mac_batch_width_p50"] = _percentile(batch_widths, 50)
    delivery["mac_batch_width_p95"] = _percentile(batch_widths, 95)
    return {
        "events_by_category": dict(sorted(by_cat.items())),
        "hub": hub,
        "delivery": delivery,
        "lanes": {
            "count": (max(lane_ordered) + 1) if lane_ordered else 1,
            "ordered_by_lane": dict(sorted(lane_ordered.items())),
            "merge_emits": merge_emits,
        },
        "wave_size_p50": _percentile(wave_sizes, 50),
        "wave_size_p95": _percentile(wave_sizes, 95),
        "spans": spans,
    }


def report(doc: dict, top: int = 5) -> str:
    """The human-readable critical-path report."""
    names = track_names(doc)
    lines: List[str] = []
    windows = epoch_windows(doc)
    points = sorted_points(doc)
    if not windows:
        lines.append("no complete epochs (open+commit) in the artifact")
    for (lane, epoch), (t_open, t_commit) in windows.items():
        wall = t_commit - t_open
        shares, chain = attribute_epoch(doc, t_open, t_commit, points)
        covered = sum(shares.values())
        label = f"epoch {epoch}" if lane == 0 else f"epoch {epoch} lane {lane}"
        lines.append(
            f"{label}: wall {wall / 1000.0:.3f} ms, "
            f"{100.0 * covered / wall:.1f}% attributed"
        )
        for cat, us in sorted(
            shares.items(), key=lambda kv: -kv[1]
        ):
            lines.append(
                f"  {cat:<10} {us / 1000.0:>10.3f} ms "
                f"({100.0 * us / wall:5.1f}%)"
            )
        lines.append("  critical-path segments (longest first):")
        for gap, cat, name, tid in sorted(chain, key=lambda c: -c[0])[
            :top
        ]:
            lines.append(
                f"    {gap / 1000.0:>9.3f} ms -> {cat}/{name} "
                f"@ {names.get(tid, tid)}"
            )
    s = summarize(doc)
    lines.append("summary:")
    lines.append(f"  events by category: {s['events_by_category']}")
    lines.append(f"  hub: {s['hub']}")
    lines.append(f"  delivery: {s['delivery']}")
    if s["lanes"]["count"] > 1:
        lines.append(f"  lanes: {s['lanes']}")
    lines.append(
        f"  wave size p50/p95: {s['wave_size_p50']}/{s['wave_size_p95']}"
    )
    for span, st in s["spans"].items():
        lines.append(
            f"  span {span:<22} n={st['n']:<5} "
            f"p50={st['p50_us']}us p95={st['p95_us']}us"
        )
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# capture: a seeded traced cluster in one command (the CI fixture)
# ---------------------------------------------------------------------------


def capture(
    out_path: str,
    n: int = 4,
    seed: int = 7,
    txs: int = 24,
    batch: int = 8,
) -> dict:
    """Run a seeded N-node SimulatedCluster with tracing on, write the
    merged artifact, and return the loaded document."""
    from cleisthenes_tpu.config import Config
    from cleisthenes_tpu.protocol.cluster import SimulatedCluster

    cluster = SimulatedCluster(
        config=Config(n=n, batch_size=batch, seed=seed, trace=True),
        seed=seed,
        key_seed=1,
    )
    for i in range(txs):
        cluster.submit(b"trace-tx-%04d" % i)
    cluster.run_epochs()
    cluster.assert_agreement()
    cluster.write_trace(out_path)
    return load(out_path)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="tools.tracetool")
    ap.add_argument(
        "artifact",
        nargs="?",
        help="merged Chrome-trace JSON (from SimulatedCluster."
        "write_trace, demo.py --trace, or --capture)",
    )
    ap.add_argument(
        "--validate",
        action="store_true",
        help="schema + per-track monotone-seq gate (exit 1 on errors)",
    )
    ap.add_argument(
        "--report",
        action="store_true",
        help="critical-path + summary report (the default action)",
    )
    ap.add_argument(
        "--json",
        action="store_true",
        help="emit stage shares + summary as one JSON object",
    )
    ap.add_argument(
        "--capture",
        metavar="OUT",
        help="run a seeded traced cluster and write the artifact here",
    )
    ap.add_argument("--n", type=int, default=4)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--txs", type=int, default=24)
    ap.add_argument("--batch", type=int, default=8)
    args = ap.parse_args(argv)

    if args.capture:
        doc = capture(
            args.capture,
            n=args.n,
            seed=args.seed,
            txs=args.txs,
            batch=args.batch,
        )
        n_events = sum(1 for _ in _analysis_events(doc))
        print(
            f"tracetool: captured {n_events} events from a seeded "
            f"{args.n}-node cluster -> {args.capture}"
        )
        return 0
    if not args.artifact:
        ap.error("need an artifact path (or --capture OUT)")
    doc = load(args.artifact)
    if args.validate:
        errors = validate(doc)
        for e in errors:
            print(e)
        n_events = sum(1 for _ in _analysis_events(doc))
        print(
            f"tracetool: {n_events} events, {len(errors)} schema "
            f"problem(s)"
        )
        return 1 if errors else 0
    if args.json:
        print(
            json.dumps(
                {
                    "stage_shares": stage_shares(doc),
                    "summary": summarize(doc),
                }
            )
        )
        return 0
    print(report(doc))
    return 0


if __name__ == "__main__":
    sys.exit(main())
