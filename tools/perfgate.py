"""perfgate: the perf-regression observatory's CI gate.

BENCH_*.json files are ad-hoc snapshots: one number per round, no
trend, nothing watching the trajectory between rounds.  This tool
closes that gap with a durable append-only trend file
(``BENCH_TREND.jsonl``, one JSON record per measured run keyed by a
config fingerprint) and a gate that compares a fresh seeded mini-bench
against the trailing trend with noise bands:

- **epoch p50** regresses when the fresh median exceeds
  ``max(trend_median * (1 + rel_tol), trend_median + abs_tol_ms)`` —
  the relative band absorbs CI-host noise, the absolute floor keeps
  tiny mini-bench epochs from turning microseconds of jitter into
  failures.
- **hub dispatches** (the cost model of this stack, and DETERMINISTIC
  for a seeded run) regress when the fresh count exceeds the trend
  maximum by more than ``dispatch_tol`` — a wave-batching regression
  fails here with zero noise before it ever shows up in wall time.
- **stage shares** (where the epoch's wall time goes, from the PR-3
  critical-path attribution) regress when any stage's share grows by
  more than ``share_tol`` absolute — a latency leak that hides inside
  an unchanged total still moves its stage's share.  Shares are a
  wall-clock attribution, so two noise absorbers apply: a fresh run
  whose own epoch p50 is inflated past the trend is not share-gated
  at all (its stall is host noise, attributed to whichever stage the
  scheduler parked on), and a share-only failure is re-measured with
  each stage's minimum share across samples — a real leak reproduces
  on every sample, a stall does not.

Workflow (the ci.sh stage):

    python -m tools.perfgate --trend BENCH_TREND.jsonl

First run seeds the trend (pass); later runs gate against the trailing
``--window`` records with a matching fingerprint and append on pass,
so the band tracks legitimate drift.  After an INTENTIONAL perf change
(more dispatches by design, a new stage), refresh with ``--reset``.
``--record FILE`` gates a pre-measured record instead of running the
mini-bench — the test hook proving the gate actually fails on an
inflated epoch p50.

``bench.py`` appends every full benchmark run's sections through
``append_bench_trend`` so the headline numbers build the same history.
"""

from __future__ import annotations

import argparse
import json
import os
import pathlib
import statistics
import sys
import time
from typing import Dict, List, Optional, Tuple

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent
DEFAULT_TREND = REPO_ROOT / "BENCH_TREND.jsonl"

# mini-bench shape: small enough for a CI stage (~seconds), big enough
# that epoch p50 moves when the protocol path regresses
MINI_N = 4
MINI_BATCH = 64
MINI_EPOCHS = 3
MINI_SEED = 1999

DEFAULT_WINDOW = 20
# ingress mini-load shape (ISSUE 18): small enough for a CI stage,
# big enough that submit->ordered p50 moves when the admission path
# or the drain seam regresses
INGRESS_CLIENTS = 400
INGRESS_TXS = 400
INGRESS_TICKS = 6
INGRESS_BATCH = 64
DEFAULT_REL_TOL = 1.0  # fresh p50 may double before failing (CI noise)
DEFAULT_ABS_TOL_MS = 50.0
DEFAULT_SHARE_TOL = 0.25
DEFAULT_DISPATCH_TOL = 1.25


# ---------------------------------------------------------------------------
# trend file
# ---------------------------------------------------------------------------


def fingerprint_key(record: Dict) -> str:
    """Stable comparison key: records gate only against runs of the
    identical configuration."""
    return json.dumps(record.get("fingerprint", {}), sort_keys=True)


def load_trend(path: str) -> List[Dict]:
    """Every parseable record, file order (oldest first).  A corrupt
    line (torn write) is skipped, never fatal — the trend is an aid,
    not a ledger."""
    out: List[Dict] = []
    try:
        with open(path, "r", encoding="utf-8") as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if isinstance(rec, dict):
                    out.append(rec)
    except OSError:
        return []
    return out


def append_record(path: str, record: Dict) -> None:
    with open(path, "a", encoding="utf-8") as fh:
        fh.write(json.dumps(record, sort_keys=True) + "\n")


def append_bench_trend(result: Dict, path: str = str(DEFAULT_TREND)) -> int:
    """Fold one bench.py artifact into the trend: a record per
    protocol section per backend that produced an epoch p50.  Returns
    the number of records appended; never raises (bench output must
    not become hostage to trend bookkeeping)."""
    appended = 0
    try:
        stamp = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
        platform = result.get("platform")
        for section, body in result.items():
            if not isinstance(body, dict):
                continue
            for backend in ("tpu", "cpu"):
                side = body.get(backend)
                if not isinstance(side, dict):
                    continue
                p50 = side.get("epoch_p50_ms")
                if p50 is None:
                    continue
                record = {
                    "kind": "bench_section",
                    "ts": stamp,
                    "fingerprint": {
                        "kind": "bench_section",
                        "section": section,
                        "backend": backend,
                        "platform": platform,
                        "n": body.get("n"),
                        "batch": body.get("batch"),
                    },
                    "epoch_p50_ms": p50,
                    # two-frontier split (ISSUE 8): ordered-frontier
                    # p50, settled p50 and the trailing-lag p95 ride
                    # every protocol section that measures them
                    "ordered_epoch_p50_ms": side.get(
                        "ordered_epoch_p50_ms"
                    ),
                    "settled_epoch_p50_ms": side.get(
                        "settled_epoch_p50_ms"
                    ),
                    "decrypt_lag_p95_ms": side.get("decrypt_lag_p95_ms"),
                    "epoch_times_ms": side.get("epoch_times_ms"),
                    "tx_per_sec": side.get("tx_per_sec"),
                    "stage_shares": side.get("stage_shares"),
                    "hub_dispatches": side.get("hub_dispatches_cluster"),
                    # columnar-wave counters (ISSUE 7): present on
                    # protocol sections since the wave-batched hub
                    "dispatches_per_epoch": side.get(
                        "dispatches_per_epoch"
                    ),
                    "wave_width_p50": side.get("wave_width_p50"),
                    "wave_width_p95": side.get("wave_width_p95"),
                    # delivery-plane counters (ISSUE 9)
                    "frames_decoded_per_epoch": side.get(
                        "frames_decoded_per_epoch"
                    ),
                    "mac_verifies_per_epoch": side.get(
                        "mac_verifies_per_epoch"
                    ),
                    "decode_memo_hit_rate": side.get(
                        "decode_memo_hit_rate"
                    ),
                    # wave-routed ingest (ISSUE 10)
                    "handler_dispatches_per_epoch": side.get(
                        "handler_dispatches_per_epoch"
                    ),
                    # egress columnarization (ISSUE 13)
                    "frames_encoded_per_epoch": side.get(
                        "frames_encoded_per_epoch"
                    ),
                    "mac_signs_per_epoch": side.get(
                        "mac_signs_per_epoch"
                    ),
                    "encode_memo_hit_rate": side.get(
                        "encode_memo_hit_rate"
                    ),
                    "coin_dispatches_per_epoch": side.get(
                        "coin_dispatches_per_epoch"
                    ),
                }
                append_record(path, record)
                appended += 1
        # lane shard-out cadence (ISSUE 20): one record per lane-count
        # arm of the bench lane_scaling section — the virtual-time
        # throughput and dispatch-flatness trend across S
        lanes = result.get("lane_scaling")
        if isinstance(lanes, dict):
            for arm, body in lanes.get("arms", {}).items():
                if not isinstance(body, dict):
                    continue
                append_record(path, {
                    "kind": "bench_lane_scaling",
                    "ts": stamp,
                    "fingerprint": {
                        "kind": "bench_lane_scaling",
                        "arm": arm,
                        "lanes": body.get("lanes"),
                        "n": body.get("n"),
                        "batch": body.get("batch"),
                        "platform": platform,
                    },
                    "tx_per_virtual_sec": body.get("tx_per_virtual_sec"),
                    "wall_tx_per_sec": body.get("wall_tx_per_sec"),
                    "virtual_ms_per_slot": body.get("virtual_ms_per_slot"),
                    "merged_slots": body.get("merged_slots"),
                    "hub_dispatches_per_ordered_epoch": body.get(
                        "hub_dispatches_per_ordered_epoch"
                    ),
                })
                appended += 1
    except OSError:
        pass
    return appended


# ---------------------------------------------------------------------------
# the seeded mini-bench
# ---------------------------------------------------------------------------


def run_sample(
    n: int = MINI_N,
    batch: int = MINI_BATCH,
    epochs: int = MINI_EPOCHS,
    seed: int = MINI_SEED,
) -> Dict:
    """One seeded traced mini-bench over the in-proc cluster: epoch
    walls, stage shares, wave sizes, hub dispatch count."""
    from cleisthenes_tpu.config import Config
    from cleisthenes_tpu.protocol.cluster import SimulatedCluster
    from cleisthenes_tpu.utils.trace import to_chrome
    from tools import tracetool

    cfg = Config(
        n=n, batch_size=batch, seed=seed, trace=True,
        crypto_backend="cpu",
    )
    cluster = SimulatedCluster(
        config=cfg,
        seed=seed,
        key_seed=7,
        auto_propose=False,
    )
    ids = cluster.ids
    total = batch * (epochs + 1)  # +1: the warm-up epoch's own txs
    for i in range(total):
        cluster.submit(b"perfgate-%08d" % i, node_id=ids[i % n])
    for hb in cluster.nodes.values():  # warm-up epoch (compile, caches)
        hb.start_epoch()
    cluster.net.run()
    walls: List[float] = []
    for _ in range(epochs):
        t0 = time.perf_counter()
        for hb in cluster.nodes.values():
            hb.start_epoch()
        cluster.net.run()
        walls.append(time.perf_counter() - t0)
    cluster.assert_agreement()
    doc = to_chrome(cluster.trace_events())
    summary = tracetool.summarize(doc)
    p50 = statistics.median(walls)
    p95 = sorted(walls)[max(0, int(round(0.95 * (len(walls) - 1))))]
    # two-frontier commit split (ISSUE 8): the per-epoch latencies as
    # the node metrics saw them — propose -> ciphertext-ordered commit
    # (the protocol-plane number the gate now keys on), propose ->
    # settled plaintext, and the trailing decrypt lag's p95
    m = cluster.nodes[ids[0]].metrics
    ordered_p50 = m.ordered_latency.p50
    settled_p50 = m.epoch_latency.p50
    lag_p95 = m.settle_lag_latency.p95
    dstats = cluster.net.delivery_stats()
    probes = dstats["decode_memo_hits"] + dstats["decode_memo_misses"]
    return {
        "kind": "perfgate_mini",
        "ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "fingerprint": {
            "kind": "perfgate_mini",
            "n": n,
            "batch": batch,
            "epochs": epochs,
            "seed": seed,
            "backend": "cpu",
            # the commit mode changes what the epoch windows (and so
            # the stage shares) MEAN — runs must never gate against
            # trend records measured under the other mode
            "order_then_settle": bool(cfg.order_then_settle),
            # the delivery arm changes what the frame/MAC counters
            # MEAN (scalar: one decode+verify per frame; columnar:
            # memoized decode, one verify per wave) — same rule
            "delivery_columnar": bool(cfg.delivery_columnar),
            # the routing arm changes what handler_dispatches MEANS
            # (scalar: one per payload; wave: one per kind per wave)
            # — a mode flip must never gate against the other mode's
            # trend
            "wave_routing": bool(cfg.wave_routing),
            # the egress arm changes what the encode/sign/coin
            # counters MEAN (scalar: one sign pass per post, one coin
            # batch per node per drain; columnar: one wave pass per
            # flush, one pooled coin dispatch) — same rule
            "egress_columnar": bool(cfg.egress_columnar),
            # the remaining ARM_FLAGS (config.py): the hub's flush
            # discipline changes what hub_dispatches MEANS and epoch
            # pipelining changes what the epoch windows overlap —
            # every declared arm flag keys the fingerprint
            # (staticcheck ARM001 cross-checks the set)
            "hub_wave_flush": bool(cfg.hub_wave_flush),
            "epoch_pipelining": bool(cfg.epoch_pipelining),
            # K-deep pipelined frontiers (ISSUE 15): the depth
            # changes how many epochs share each wave — and with
            # them what every per-epoch dispatch counter MEANS — so
            # runs gate only against same-depth trend records
            "pipeline_depth": int(cfg.pipeline_depth),
            # the trust-model arms (ISSUE 19): the attested sender
            # log adds a per-frame stamp+verify to every MAC, and the
            # reduced-quorum mode changes the quorum arithmetic the
            # epochs wait on (f=(n-1)//2 instead of f=(n-1)//3) —
            # both change what the epoch windows and sign/verify
            # counters MEAN, so runs gate only against same-mode
            # trend records
            "attested_log": bool(cfg.attested_log),
            "reduced_quorum": bool(cfg.reduced_quorum),
            # lane shard-out (ISSUE 20): S lanes share each wave's
            # dispatches, so every per-epoch counter and latency
            # window MEANS something different at a different S —
            # runs gate only against same-lane-count trend records
            # (the int-valued arm key; staticcheck ARM001 checks it)
            "lanes": int(cfg.lanes),
            # the ingress mini-load's shape changes what the
            # submit->ordered p50 and the eviction count MEAN —
            # reshaping it re-keys the trend (run --reset after an
            # intentional change)
            "ingress": {
                "clients": INGRESS_CLIENTS,
                "txs": INGRESS_TXS,
                "ticks": INGRESS_TICKS,
                "batch": INGRESS_BATCH,
            },
        },
        "epoch_p50_ms": round(p50 * 1000.0, 3),
        "epoch_p95_ms": round(p95 * 1000.0, 3),
        "ordered_epoch_p50_ms": (
            round(ordered_p50 * 1000.0, 3)
            if ordered_p50 is not None
            else None
        ),
        "settled_epoch_p50_ms": (
            round(settled_p50 * 1000.0, 3)
            if settled_p50 is not None
            else None
        ),
        "decrypt_lag_p95_ms": (
            round(lag_p95 * 1000.0, 3) if lag_p95 is not None else None
        ),
        "epoch_times_ms": [round(w * 1000.0, 1) for w in walls],
        "stage_shares": tracetool.stage_shares(doc),
        "wave_size_p50": summary["wave_size_p50"],
        "wave_size_p95": summary["wave_size_p95"],
        "hub_dispatches": int(
            cluster.nodes[ids[0]].hub.stats()["dispatches"]
        ),
        # delivery-plane counters (ISSUE 9) — deterministic for the
        # seeded schedule, gated like hub_dispatches: a delivery-
        # columnarization regression (memo stops hitting, waves stop
        # batching) fails here with zero noise
        "frames_decoded": int(dstats["frames_decoded"]),
        "mac_verifies": int(dstats["mac_verifies"]),
        "decode_memo_hit_rate": (
            round(dstats["decode_memo_hits"] / probes, 4)
            if probes
            else 0.0
        ),
        # wave-routed ingest (ISSUE 10): batch handler invocations
        # crossing the router seam, cluster-wide — deterministic for
        # the seeded schedule, gated like hub_dispatches (a routing
        # regression — columns stop forming, the router falls back to
        # per-payload dispatch — fails here with zero noise)
        "handler_dispatches": int(
            sum(
                hb.metrics.handler_dispatches.value
                for hb in cluster.nodes.values()
            )
        ),
        # egress columnarization (ISSUE 13): outbound encode+sign
        # passes and native coin-issue dispatches — deterministic for
        # the seeded schedule, gated like the delivery counters (an
        # egress regression — the memo stops sharing, waves stop
        # folding, the coin pool stops batching — fails with zero
        # noise)
        "frames_encoded": int(dstats["frames_encoded"]),
        "mac_signs": int(dstats["mac_signs"]),
        "encode_memo_hit_rate": (
            round(
                dstats["encode_memo_hits"]
                / (dstats["encode_memo_hits"] + dstats["encode_memo_misses"]),
                4,
            )
            if (dstats["encode_memo_hits"] + dstats["encode_memo_misses"])
            else 0.0
        ),
        "coin_dispatches": int(
            cluster.nodes[ids[0]].hub.stats()["coin_issue_batches"]
        ),
        # ingress plane (ISSUE 18): a seeded mini load through the
        # production admission path (tools/loadgen.py arm — in-proc
        # twin of the client gRPC surface + fee-priority mempool).
        # submit_to_ordered_p50_ms is the client-visible protocol-
        # plane latency (wall clock: gated with the same noise band
        # as the epoch p50); mempool_evictions is DETERMINISTIC for
        # the seeded schedule and must stay zero — the mini load is
        # sized to fit the pool, so any eviction is an admission-
        # policy regression, not pressure
        **_ingress_sample(seed),
    }


def _ingress_sample(seed: int) -> Dict:
    """The ingress mini-load: one seconds-scale loadgen arm over the
    shared production path (shape below is part of the fingerprint —
    changing it re-keys the trend, see --reset)."""
    from tools import loadgen

    sched = loadgen.build_schedule(
        clients=INGRESS_CLIENTS, txs=INGRESS_TXS, ticks=INGRESS_TICKS,
        seed=seed,
    )
    arm = loadgen.run_arm(
        sched, depth=2, n=MINI_N, batch=INGRESS_BATCH, seed=seed
    )
    return {
        "submit_to_ordered_p50_ms": arm["submit_to_ordered_ms"]["p50"],
        "submit_to_settled_p50_ms": arm["submit_to_settled_ms"]["p50"],
        "mempool_evictions": int(arm["evicted"]),
    }


# ---------------------------------------------------------------------------
# the gate
# ---------------------------------------------------------------------------


def compare(
    fresh: Dict,
    trend: List[Dict],
    rel_tol: float = DEFAULT_REL_TOL,
    abs_tol_ms: float = DEFAULT_ABS_TOL_MS,
    share_tol: float = DEFAULT_SHARE_TOL,
    dispatch_tol: float = DEFAULT_DISPATCH_TOL,
) -> Tuple[bool, List[str]]:
    """(ok, reasons): gate ``fresh`` against same-fingerprint ``trend``
    records (the caller already windowed and filtered them)."""
    reasons: List[str] = []
    # the gate keys on the ORDERED-frontier epoch p50 when the fresh
    # record and the trend both carry it (two-frontier commit split:
    # the protocol-plane latency an application's ordering sees);
    # records from before the split — or coupled-arm runs — fall back
    # to the classic settled/loop epoch p50
    key = "epoch_p50_ms"
    if isinstance(
        fresh.get("ordered_epoch_p50_ms"), (int, float)
    ) and any(
        isinstance(r.get("ordered_epoch_p50_ms"), (int, float))
        for r in trend
    ):
        key = "ordered_epoch_p50_ms"
    p50s = [
        r[key] for r in trend if isinstance(r.get(key), (int, float))
    ]
    if p50s:
        med = statistics.median(p50s)
        limit = max(med * (1.0 + rel_tol), med + abs_tol_ms)
        fresh_p50 = fresh.get(key)
        if not isinstance(fresh_p50, (int, float)):
            reasons.append(f"fresh record carries no {key}")
        elif fresh_p50 > limit:
            reasons.append(
                f"{key} regression: {fresh_p50:.3f} ms > "
                f"noise-band limit {limit:.3f} ms "
                f"(trend median {med:.3f} ms over {len(p50s)} runs)"
            )
    # client-visible ingress latency (ISSUE 18): submit->ordered p50
    # through the production admission path, same noise band as the
    # epoch p50 above (wall-clock: the relative band absorbs CI-host
    # noise, the absolute floor keeps mini-load jitter honest)
    ing_p50s = [
        r["submit_to_ordered_p50_ms"]
        for r in trend
        if isinstance(r.get("submit_to_ordered_p50_ms"), (int, float))
    ]
    fresh_ing = fresh.get("submit_to_ordered_p50_ms")
    if ing_p50s and isinstance(fresh_ing, (int, float)):
        med = statistics.median(ing_p50s)
        limit = max(med * (1.0 + rel_tol), med + abs_tol_ms)
        if fresh_ing > limit:
            reasons.append(
                f"submit_to_ordered_p50_ms regression: "
                f"{fresh_ing:.3f} ms > noise-band limit {limit:.3f} ms "
                f"(trend median {med:.3f} ms over {len(ing_p50s)} runs)"
            )
    # deterministic-counter gates: hub dispatches (PR 7) and the
    # delivery-plane frame/MAC counters (ISSUE 9) share one rule —
    # the seeded schedule makes them exact, so exceeding the trend
    # maximum by more than dispatch_tol is a structural regression
    for counter, what in (
        ("hub_dispatches", "hub dispatch"),
        ("frames_decoded", "frame-decode"),
        ("mac_verifies", "MAC-verify"),
        ("handler_dispatches", "handler-dispatch"),
        ("frames_encoded", "frame-encode"),
        ("mac_signs", "MAC-sign"),
        ("coin_dispatches", "coin-dispatch"),
        # the seeded ingress mini-load fits its pool by construction,
        # so the eviction count is deterministic (zero on a healthy
        # run): any fresh eviction is an admission-policy regression
        ("mempool_evictions", "mempool-eviction"),
    ):
        history = [
            r[counter] for r in trend if isinstance(r.get(counter), int)
        ]
        fresh_v = fresh.get(counter)
        if history and isinstance(fresh_v, int):
            cap = max(history) * dispatch_tol
            if fresh_v > cap:
                reasons.append(
                    f"{what} regression: {fresh_v} > "
                    f"{cap:.0f} (trend max {max(history)} * "
                    f"{dispatch_tol}); the seeded run is deterministic "
                    "— this is a batching change, not noise "
                    "(--reset if intentional)"
                )
    trend_shares = [
        r["stage_shares"]
        for r in trend
        if isinstance(r.get("stage_shares"), dict) and r["stage_shares"]
    ]
    fresh_shares = fresh.get("stage_shares")
    # stage shares are only comparable between runs of similar wall:
    # on a loaded host the scheduler's stall lands on whichever stage
    # it happened to park in, inflating that stage's share while
    # saying nothing about the code.  Host noise inflates the GATE
    # KEY's p50 too (the stall sits inside the ordered window), so
    # skip the share gate only when the same p50 the band above
    # gated on is itself inflated past the trend — a settle-track
    # leak that keeps the ordered p50 flat stays share-gated.
    fresh_key_p50 = fresh.get(key)
    if (
        p50s
        and isinstance(fresh_key_p50, (int, float))
        and fresh_key_p50 > statistics.median(p50s) * 1.25
    ):
        fresh_shares = None
    if trend_shares and isinstance(fresh_shares, dict):
        stages = {s for shares in trend_shares for s in shares}
        for stage in sorted(stages | set(fresh_shares)):
            med_share = statistics.median(
                [float(s.get(stage, 0.0)) for s in trend_shares]
            )
            got = float(fresh_shares.get(stage, 0.0))
            if got - med_share > share_tol:
                reasons.append(
                    f"stage-share regression: {stage} owns "
                    f"{got:.2%} of epoch wall vs trend median "
                    f"{med_share:.2%} (+>{share_tol:.0%})"
                )
    return (not reasons), reasons


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(prog="tools.perfgate")
    ap.add_argument(
        "--trend", default=str(DEFAULT_TREND),
        help=f"trend JSONL path (default {DEFAULT_TREND.name})",
    )
    ap.add_argument(
        "--record", metavar="JSON",
        help="gate this pre-measured record file instead of running "
        "the mini-bench (never appended)",
    )
    ap.add_argument("--window", type=int, default=DEFAULT_WINDOW)
    ap.add_argument("--rel-tol", type=float, default=DEFAULT_REL_TOL)
    ap.add_argument("--abs-tol-ms", type=float, default=DEFAULT_ABS_TOL_MS)
    ap.add_argument("--share-tol", type=float, default=DEFAULT_SHARE_TOL)
    ap.add_argument(
        "--dispatch-tol", type=float, default=DEFAULT_DISPATCH_TOL
    )
    ap.add_argument(
        "--no-append", action="store_true",
        help="gate only; do not extend the trend on pass",
    )
    ap.add_argument(
        "--reset", action="store_true",
        help="drop same-fingerprint history first (after an "
        "INTENTIONAL perf change) and reseed from this run",
    )
    ap.add_argument("--n", type=int, default=MINI_N)
    ap.add_argument("--batch", type=int, default=MINI_BATCH)
    ap.add_argument("--epochs", type=int, default=MINI_EPOCHS)
    ap.add_argument("--seed", type=int, default=MINI_SEED)
    args = ap.parse_args(argv)

    if args.record:
        with open(args.record, "r", encoding="utf-8") as fh:
            fresh = json.load(fh)
    else:
        fresh = run_sample(
            n=args.n, batch=args.batch, epochs=args.epochs, seed=args.seed
        )
    key = fingerprint_key(fresh)
    trend_all = load_trend(args.trend)
    if args.reset:
        kept = [r for r in trend_all if fingerprint_key(r) != key]
        tmp = args.trend + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            for r in kept:
                fh.write(json.dumps(r, sort_keys=True) + "\n")
        os.replace(tmp, args.trend)
        trend_all = kept
    matching = [r for r in trend_all if fingerprint_key(r) == key]
    matching = matching[-args.window:]

    if not matching:
        if args.record:
            print(
                "perfgate: no trend history for this fingerprint and "
                "--record given; nothing to gate against"
            )
            return 0
        append_record(args.trend, fresh)
        print(
            f"perfgate: seeded trend {args.trend} "
            f"(epoch p50 {fresh['epoch_p50_ms']} ms, "
            f"{fresh.get('hub_dispatches')} hub dispatches) — PASS"
        )
        return 0

    ok, reasons = compare(
        fresh,
        matching,
        rel_tol=args.rel_tol,
        abs_tol_ms=args.abs_tol_ms,
        share_tol=args.share_tol,
        dispatch_tol=args.dispatch_tol,
    )
    if not ok and not args.record and all(
        "stage-share" in r for r in reasons
    ):
        # a scheduler stall lands on whichever stage the host parked
        # the process in, inflating that stage's share for ONE sample;
        # a real latency leak reproduces on every sample.  Re-measure
        # and keep each stage's minimum share across samples before
        # declaring a regression.
        shares_min = {
            s: float(v)
            for s, v in (fresh.get("stage_shares") or {}).items()
        }
        for _ in range(2):
            resample = run_sample(
                n=args.n,
                batch=args.batch,
                epochs=args.epochs,
                seed=args.seed,
            )
            re_shares = resample.get("stage_shares") or {}
            shares_min = {
                s: min(v, float(re_shares.get(s, 0.0)))
                for s, v in shares_min.items()
            }
            ok, reasons = compare(
                dict(fresh, stage_shares=shares_min),
                matching,
                rel_tol=args.rel_tol,
                abs_tol_ms=args.abs_tol_ms,
                share_tol=args.share_tol,
                dispatch_tol=args.dispatch_tol,
            )
            if ok:
                break
    med = statistics.median(
        [
            r["epoch_p50_ms"]
            for r in matching
            if isinstance(r.get("epoch_p50_ms"), (int, float))
        ]
        or [0.0]
    )
    if ok:
        if not args.record and not args.no_append:
            append_record(args.trend, fresh)
        print(
            f"perfgate: PASS — epoch p50 "
            f"{fresh.get('epoch_p50_ms')} ms within band of trend "
            f"median {med:.3f} ms ({len(matching)} run(s))"
        )
        return 0
    print("perfgate: FAIL")
    for r in reasons:
        print(f"  - {r}")
    return 1


if __name__ == "__main__":
    sys.exit(main())
