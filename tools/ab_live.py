"""Quick live-path A/B: measure protocol_n64 before/after a change.

Runs bench.measure_protocol on the cpu backend under the benchlock
(pausing the background sweep so the one core is ours) and prints the
section dict.  Used to attribute each columnar-delivery-plane stage's
win honestly (16.6 s r4 baseline; target <= 5 s, r4 verdict item 3).

Usage:  python tools/ab_live.py [n] [batch] [epochs]
"""

from __future__ import annotations

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ["JAX_PLATFORMS"] = "cpu"
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import bench  # noqa: E402
from tools import benchlock  # noqa: E402


def main() -> int:
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 64
    batch = int(sys.argv[2]) if len(sys.argv) > 2 else 1024
    epochs = int(sys.argv[3]) if len(sys.argv) > 3 else 2
    with benchlock.hold("ab_live"):
        out = bench.measure_protocol("cpu", n, batch, epochs)
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
