"""Side-effect-free pieces shared by the sweep driver and the suite.

tools/sweep_roster.py registers itself as a benchlock-pausable job at
import time (it is an hours-long background process); the in-suite
big-roster test must NOT inherit that registration — importing THIS
module is safe anywhere (advisor finding: the test suite was being
registered for SIGSTOPs).
"""

from __future__ import annotations

import random


def check_prefix(nodes, honest) -> bool:
    """Per-epoch PREFIX consistency among honest nodes — the real
    HBBFT agreement property for runs that may stop at a round cap
    (strict whole-history equality over-claims: honest laggards may
    hold a prefix mid-convergence).  Prints the earliest divergence."""
    hists = {
        k: [tuple(sorted(b.tx_list())) for b in nodes[k].committed_batches]
        for k in honest
    }
    ok = True
    for i in range(len(honest)):
        for j in range(i + 1, len(honest)):
            a, b = hists[honest[i]], hists[honest[j]]
            m = min(len(a), len(b))
            if a[:m] != b[:m]:
                ok = False
                for e in range(m):
                    if a[e] != b[e]:
                        sa, sb = set(a[e]), set(b[e])
                        print(
                            f"PREFIX DIVERGES {honest[i]} vs {honest[j]}"
                            f" at epoch {e}:\n"
                            f"  only in {honest[i]}: {sorted(sa - sb)[:4]}\n"
                            f"  only in {honest[j]}: {sorted(sb - sa)[:4]}",
                            flush=True,
                        )
                        break
    return ok


def build_seed_scenario(seed: int):
    """The big-roster adversarial scenario for ``seed`` — ONE
    definition, used by both tools/sweep_roster.py (the classifier)
    and tests/test_byzantine.py (the bounded suite check), so the two
    can never drift apart.  Returns (cfg, net, nodes, bad, honest)."""
    from tests.test_byzantine import make_hb_network, push_txs
    from cleisthenes_tpu.utils.adversary import Coalition

    rng = random.Random(seed)
    n = rng.choice([10, 13])
    f = (n - 1) // 3
    cfg, net, nodes = make_hb_network(n, batch_size=16, seed=seed)
    bad = rng.sample(sorted(nodes), f)
    coal = Coalition(bad, seed=seed)
    for stage, arg in (
        ("drop", rng.uniform(0.1, 0.6)),
        ("tamper", rng.uniform(0.0, 0.7)),
        ("duplicate", rng.uniform(0.0, 0.5)),
        ("replay", rng.uniform(0.0, 0.5)),
    ):
        if rng.random() < 0.7:
            getattr(coal, stage)(arg)
    net.fault_filter = coal.filter
    push_txs(nodes, 3 * n)
    honest = sorted(k for k in nodes if k not in bad)
    return cfg, net, nodes, bad, honest
