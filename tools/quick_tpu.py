"""Short-window TPU capture: the headline sections only.

The relay's healthy windows can be shorter than a full bench.py run;
this grabs the round-5 priority measurements (lockstep N=128 epoch —
the north-star scale; lockstep N=512 — the decisive-vs-cpu scale;
the crypto-plane metric; the wide-limb families) in ~6-10 minutes and
writes TPU_QUICK_r05.json atomically.  The full-artifact capture
(tools/bench_watcher.py -> BENCH_live_r05.json) remains the recorded
bench; this is the evidence fallback for a dying window.

Usage:  python tools/quick_tpu.py       (normal env, relay attached)
"""

from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import bench  # noqa: E402
from tools import benchlock  # noqa: E402


def main() -> int:
    with benchlock.hold("quick_tpu"):
        return _main_locked()


def _main_locked() -> int:
    import jax

    dev = jax.devices()[0]
    if dev.platform not in ("tpu", "axon"):
        print(f"not a TPU: {dev}; aborting", file=sys.stderr)
        return 1
    out = {
        "platform": dev.platform,
        "device": getattr(dev, "device_kind", ""),
        "start_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }

    def stamp(name, fn):
        t0 = time.perf_counter()
        try:
            out[name] = fn()
        except Exception as exc:  # record, don't lose the window
            out[name] = {"error": repr(exc)[:300]}
        out[name + "_wall_s"] = round(time.perf_counter() - t0, 1)
        print(f"[quick] {name} done @ {time.strftime('%H:%M:%S')}",
              file=sys.stderr, flush=True)
        _write(out)  # persist after EVERY section: windows die mid-run

    def _write(doc):
        tmp = os.path.join(REPO, "TPU_QUICK_r05.json.tmp")
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, os.path.join(REPO, "TPU_QUICK_r05.json"))

    stamp(
        "protocol_spmd_n128_tpu",
        lambda: bench.measure_spmd("tpu", 128, 10_000, 3),
    )
    stamp(
        "protocol_spmd_n512_tpu",
        lambda: bench.measure_spmd("tpu", 512, 4096, 2),
    )
    stamp(
        "epoch_crypto_p50_ms_tpu",
        lambda: round(bench.measure_crypto("tpu") * 1000.0, 3),
    )
    stamp("modexp_wide", bench.measure_modexp_wide)
    out["end_utc"] = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    _write(out)
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
