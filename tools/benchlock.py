"""Measurement mutual exclusion + host-load provenance.

Round-4 post-mortem (VERDICT weak #2): the armed bench_watcher's 5-min
jax-import probes ran concurrently with the driver's end-of-round
capture on this ONE-core box and inflated every CPU section ~2x
(protocol_n16 994 ms vs the builder's committed 462 ms).  The artifacts
could not prove the contamination because provenance recorded only
relay drift, not host contention.  This module fixes both halves:

1. MUTUAL EXCLUSION — one flock'd lockfile shared by every measuring
   driver (bench.py, tools/bench_watcher.py, tools/quick_tpu.py).
   While a holder measures, no other driver probes or measures.
2. PAUSABLE LOW-PRIORITY JOBS — hours-long background work
   (tools/sweep_roster.py) registers its pid; acquiring the lock
   SIGSTOPs registered jobs for the duration and SIGCONTs them on
   release, so a TPU window can be seized without the sweep
   contaminating the timing (and without losing the sweep's progress).
   A detached guardian subprocess resumes the jobs even if the holder
   is SIGKILLed mid-capture.
3. LOAD PROVENANCE — load_snapshot() records os.getloadavg() and the
   competing-python-process count so the next contaminated artifact is
   self-incriminating instead of silently wrong.

Reentrancy: a holder exports CLEISTHENES_BENCH_LOCK=<pid> so child
processes it spawns (bench.py --child, watcher -> bench.py) see the
lock as already held and no-op instead of deadlocking on the flock.
"""

from __future__ import annotations

import contextlib
import fcntl
import os
import signal
import subprocess
import sys
import time

LOCK_PATH = "/tmp/cleisthenes_bench.lock"
PAUSE_DIR = "/tmp/cleisthenes_pausable"
_ENV_KEY = "CLEISTHENES_BENCH_LOCK"


def _alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
        return True
    except (ProcessLookupError, PermissionError):
        return False


def _pausable_pids() -> list[int]:
    if not os.path.isdir(PAUSE_DIR):
        return []
    pids = []
    for name in os.listdir(PAUSE_DIR):
        try:
            pid = int(name)
        except ValueError:
            continue
        if _alive(pid):
            pids.append(pid)
        else:  # stale registration from a dead job
            with contextlib.suppress(OSError):
                os.unlink(os.path.join(PAUSE_DIR, name))
    return pids


def _lock_is_held() -> bool:
    """True when some live holder currently flocks LOCK_PATH."""
    try:
        fd = os.open(LOCK_PATH, os.O_CREAT | os.O_RDWR, 0o666)
    except OSError:
        return False
    try:
        try:
            fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except BlockingIOError:
            return True
        fcntl.flock(fd, fcntl.LOCK_UN)
        return False
    finally:
        os.close(fd)


def register_pausable() -> None:
    """Called by hours-long background jobs (the adversarial sweep):
    lock holders SIGSTOP me while they measure, SIGCONT me after.

    If a capture is ALREADY in flight when we register, stop ourselves
    now: the holder snapshotted the pause set at acquire time and
    cannot see us, but release re-scans the registry and CONTs every
    registered job, so we wake exactly when the capture ends."""
    os.makedirs(PAUSE_DIR, exist_ok=True)
    path = os.path.join(PAUSE_DIR, str(os.getpid()))
    with open(path, "w") as f:
        f.write(sys.argv[0] if sys.argv else "?")
    import atexit

    def _cleanup() -> None:
        with contextlib.suppress(OSError):
            os.unlink(path)

    atexit.register(_cleanup)
    while _lock_is_held():  # loop: a spurious wake re-checks
        os.kill(os.getpid(), signal.SIGSTOP)


def _spawn_guardian(paused: list[int]) -> "subprocess.Popen | None":
    """Detached watchdog: if the lock holder dies without releasing
    (SIGKILL by the driver's timeout is realistic), SIGCONT the paused
    jobs so a frozen sweep never outlives the capture that froze it.

    The resume condition is the FLOCK becoming free, not holder-pid
    liveness: a successor holder that acquired within the poll window
    keeps the lock busy, so the guardian never CONTs jobs the
    successor just paused, and pid reuse cannot fool it."""
    if not paused:
        return None
    code = (
        "import os,sys,time,fcntl,signal\n"
        "lock=sys.argv[1]; pids=[int(p) for p in sys.argv[2:]]\n"
        "while True:\n"
        "    time.sleep(5)\n"
        "    try:\n"
        "        fd=os.open(lock,os.O_CREAT|os.O_RDWR,0o666)\n"
        "    except OSError:\n"
        "        continue\n"
        "    try:\n"
        "        try: fcntl.flock(fd,fcntl.LOCK_EX|fcntl.LOCK_NB)\n"
        "        except BlockingIOError:\n"
        "            continue\n"
        "        for p in pids:\n"
        "            try: os.kill(p,signal.SIGCONT)\n"
        "            except OSError: pass\n"
        "        break\n"
        "    finally:\n"
        "        os.close(fd)\n"
    )
    try:
        return subprocess.Popen(
            [sys.executable, "-c", code, LOCK_PATH]
            + [str(p) for p in paused],
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
            start_new_session=True,
        )
    except OSError:
        return None


@contextlib.contextmanager
def hold(name: str, block: bool = True, timeout_s: float = 7200.0):
    """Exclusive measurement lock.  Yields True when held (or already
    held by an ancestor — reentrant via env), False when block=False
    and the lock is busy.  Pauses registered low-priority jobs."""
    if os.environ.get(_ENV_KEY):  # ancestor holds it: reentrant no-op
        yield True
        return
    fd = os.open(LOCK_PATH, os.O_CREAT | os.O_RDWR, 0o666)
    try:
        if block:
            deadline = time.time() + timeout_s
            while True:
                try:
                    fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
                    break
                except BlockingIOError:
                    if time.time() >= deadline:
                        raise TimeoutError(
                            f"bench lock busy for {timeout_s}s "
                            f"(holder: {_read_holder()})"
                        )
                    time.sleep(2)
        else:
            try:
                fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
            except BlockingIOError:
                # the outer finally closes fd — closing here too made
                # every busy non-blocking probe die with EBADF on
                # exit, killing the armed relay watcher the first
                # time a capture held the lock (round-5 regression)
                yield False
                return
        os.ftruncate(fd, 0)
        os.write(fd, f"{os.getpid()} {name} {time.time():.0f}".encode())
        os.environ[_ENV_KEY] = str(os.getpid())
        paused = _pausable_pids()
        guardian = _spawn_guardian(paused)
        for pid in paused:
            with contextlib.suppress(OSError):
                os.kill(pid, signal.SIGSTOP)
        try:
            yield True
        finally:
            # re-scan: jobs that registered DURING the capture stopped
            # themselves (register_pausable) and wait on this CONT
            for pid in set(paused) | set(_pausable_pids()):
                with contextlib.suppress(OSError):
                    os.kill(pid, signal.SIGCONT)
            if guardian is not None:
                with contextlib.suppress(OSError):
                    guardian.kill()
            os.environ.pop(_ENV_KEY, None)
            fcntl.flock(fd, fcntl.LOCK_UN)
    finally:
        os.close(fd)


def _read_holder() -> str:
    try:
        with open(LOCK_PATH) as f:
            return f.read().strip() or "?"
    except OSError:
        return "?"


def load_snapshot() -> dict:
    """Host-contention evidence for artifact provenance."""
    snap: dict = {"loadavg": [round(x, 2) for x in os.getloadavg()]}
    me = os.getpid()
    competing = []
    try:
        for entry in os.listdir("/proc"):
            if not entry.isdigit() or int(entry) == me:
                continue
            try:
                with open(f"/proc/{entry}/cmdline", "rb") as f:
                    cmd = f.read().replace(b"\x00", b" ").decode(
                        "utf-8", "replace").strip()
                with open(f"/proc/{entry}/stat") as f:
                    state = f.read().split(")")[-1].split()[0]
            except OSError:
                continue
            # running/runnable python processes are the contamination
            # vector on a one-core box; stopped (T) ones are paused
            if "python" in cmd and state in ("R", "D"):
                competing.append(cmd[:80])
    except OSError:
        pass
    snap["competing_python_procs"] = len(competing)
    if competing:
        snap["competing_cmdlines"] = competing[:6]
    snap["paused_jobs"] = len(_pausable_pids())
    return snap
