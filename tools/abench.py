"""abench: same-box interleaved A/B protocol bench — HEAD vs a git ref.

WAVE_EVIDENCE.md (and the r05 round notes) document the failure mode
this tool exists for: the recorded 12.2 s protocol_n64 baseline does
NOT reproduce on another box (HEAD itself measured 18.6-34 s there),
so comparing a fresh BENCH_*.json against a band recorded elsewhere
is unusable.  What DOES hold up is a paired comparison: run the two
code versions alternately on the SAME box inside ONE harness lifetime
(A B A B ...), so drift, thermal state and background load hit both
arms symmetrically, and report per-pair deltas instead of absolute
numbers.

    python -m tools.abench BASE_REF [--n 16] [--batch 256]
           [--epochs 3] [--pairs 4] [--seed 99]
    python bench.py --ab BASE_REF        # same thing

Mechanics: ``git worktree add --detach`` materializes BASE_REF under
``.abench/`` inside the repo, each sample runs in a fresh subprocess
with its cwd at the matching tree (two code versions cannot share one
interpreter), and the probe script uses only APIs stable since PR 1
(Config, SimulatedCluster, the manual propose-and-drain loop) so any
recent ref can serve as the base arm.  Every subprocess pins
JAX_PLATFORMS=cpu: A/B runs measure code, not relay weather.

Output: one JSON line — per-arm samples, per-pair head/base ratios,
and their medians.  ``epoch_p50_ratio_median < 1`` means HEAD is
faster.  ``ordered_epoch_p50_ms`` rides along when the arm's code
exposes it (the ISSUE-8 two-frontier split; older refs report null).
"""

from __future__ import annotations

import argparse
import json
import os
import pathlib
import statistics
import subprocess
import sys
from typing import Dict, List, Optional, Sequence

REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent
WORKTREE_DIR = REPO_ROOT / ".abench"

# The probe every arm runs: manual propose-and-drain epochs over the
# in-proc cluster, ONE JSON line on stdout.  Only touches APIs that
# exist on every ref this harness will realistically compare, and
# degrades gracefully (nulls) where a ref lacks the newer metrics.
_PROBE = r"""
import json, os, statistics, sys, time
import numpy as np
from cleisthenes_tpu.config import Config
from cleisthenes_tpu.protocol.cluster import SimulatedCluster

n, batch, epochs, seed = (
    int(sys.argv[1]), int(sys.argv[2]), int(sys.argv[3]), int(sys.argv[4])
)
# per-arm Config overrides (ABENCH_CONFIG_OVERRIDES, a JSON object of
# Config kwargs): the ISSUE-15 depth A/B pits pipeline_depth=K
# against depth 1 on the SAME code — only pass overrides to arms
# whose tree knows the fields
overrides = json.loads(os.environ.get("ABENCH_CONFIG_OVERRIDES", "{}"))
# an arm may override the roster size itself (the ISSUE-19 trust-model
# A/B pits a reduced-quorum n=2f+1 roster against the baseline 3f+1
# roster at EQUAL f): an "n" in the overrides replaces the argv n for
# that arm instead of colliding with it in the Config call
n = int(overrides.pop("n", n))
# pseudo-override "wan_profile" mounts the ISSUE-16 link model on the
# cluster (it is a SimulatedCluster kwarg, not a Config field): the
# ISSUE-20 lane A/B pairs tx-per-VIRTUAL-second across S, since wall
# throughput in the serialized one-process scheduler pays every
# lane's crypto sequentially and cannot show the shard-out win
wan = overrides.pop("wan_profile", None)
# a lanes override shards the arm into S sibling lanes (ISSUE 20);
# the submitted tx mass scales by S so every lane runs SATURATED
# epochs — the throughput-benchmark shape — and the per-settled-tx
# cost fields stay directly comparable across unequal masses
S = int(overrides.get("lanes", 1))
# the production shape: work pre-submitted, auto-propose on, ONE
# net.run chains every epoch back to back — the shape where cross-
# epoch pipelining (old or two-frontier) is actually reachable.
cluster = SimulatedCluster(
    config=Config(
        n=n, batch_size=batch, crypto_backend="cpu", seed=seed,
        **overrides
    ),
    key_seed=77,
    auto_propose=True,
    **({"wan_profile": wan} if wan else {}),
)
ids = cluster.ids
rng = np.random.default_rng(13)
for i in range(batch * S):  # warm-up epoch (compile, caches), its own txs
    cluster.nodes[ids[i % n]].add_transaction(
        rng.integers(0, 256, size=64, dtype=np.uint8).tobytes()
    )
for hb in cluster.nodes.values():  # explicit kick: add_transaction
    hb.start_epoch()               # never opens an epoch by itself
cluster.net.run()
assert len(cluster.nodes[ids[0]].committed_batches) >= 1
for i in range(batch * epochs * S):
    cluster.nodes[ids[i % n]].add_transaction(
        rng.integers(0, 256, size=64, dtype=np.uint8).tobytes()
    )
n0 = cluster.nodes[ids[0]]


def merged_log(node):
    # the ISSUE-20 merged total order when the tree has lanes; the
    # plain settled log (identical at lanes=1) on older refs
    log = getattr(node, "merged_batches", None)
    return log if log is not None else node.committed_batches


def virtual_ms():
    w = getattr(cluster.net, "wan", None)
    return int(w.stats()["virtual_time_ms"]) if w is not None else None


before = len(merged_log(n0))
v_before = virtual_ms()
t0 = time.perf_counter()
for hb in cluster.nodes.values():  # kick; auto-propose chains on
    hb.start_epoch()
cluster.net.run()
elapsed = time.perf_counter() - t0
cluster.assert_agreement()
window = merged_log(n0)[before:]
done = len(window)
settled_tx = sum(
    sum(len(v) for v in b.contributions.values()) for b in window
)
v_window = (
    virtual_ms() - v_before if v_before is not None else None
)
m = n0.metrics
epoch_p50 = m.epoch_latency.p50
ordered = getattr(m, "ordered_latency", None)
ordered_p50 = ordered.p50 if ordered is not None else None
lag = getattr(m, "settle_lag_latency", None)
lag_p95 = lag.p95 if lag is not None else None
print(json.dumps({
    # per-epoch cadence over the chained run (wall / epochs): the
    # throughput number a paired ratio compares (merged slots when
    # the tree shards into lanes)
    "epoch_wall_ms": round(elapsed * 1000.0 / max(1, done), 3),
    "elapsed_ms": round(elapsed * 1000.0, 3),
    "epochs": done,
    "settled_tx": settled_tx,
    # wall microseconds per settled tx (per-unit cost: comparable
    # across arms even when lane count scales the submitted mass)
    "tx_wall_us": (
        round(elapsed * 1e6 / settled_tx, 3) if settled_tx else None
    ),
    # virtual (link-model) microseconds per settled tx — only when a
    # wan_profile override mounted the clock; the ISSUE-20 headline
    "tx_virtual_us": (
        round(v_window * 1000.0 / settled_tx, 3)
        if v_window and settled_tx
        else None
    ),
    # per-epoch propose -> commit p50 from the node metrics (the
    # latency number; on two-frontier code this is the SETTLED p50)
    "epoch_p50_ms": (
        round(epoch_p50 * 1000.0, 3) if epoch_p50 is not None else None
    ),
    "ordered_epoch_p50_ms": (
        round(ordered_p50 * 1000.0, 3) if ordered_p50 is not None else None
    ),
    "decrypt_lag_p95_ms": (
        round(lag_p95 * 1000.0, 3) if lag_p95 is not None else None
    ),
    # wave-routed ingest (ISSUE 10): cluster-wide batch handler
    # invocations, deterministic for the seeded schedule (null on
    # refs that predate the router)
    "handler_dispatches": (
        sum(
            hb.metrics.handler_dispatches.value
            for hb in cluster.nodes.values()
        )
        if hasattr(m, "handler_dispatches")
        else None
    ),
}))
"""


def _git(args: Sequence[str], cwd: pathlib.Path = REPO_ROOT) -> str:
    return subprocess.run(
        ["git", *args], cwd=str(cwd), check=True,
        capture_output=True, text=True,
    ).stdout.strip()


def materialize_ref(ref: str) -> pathlib.Path:
    """A detached worktree of ``ref`` under .abench/ (reused when the
    resolved commit already sits there)."""
    sha = _git(["rev-parse", "--verify", f"{ref}^{{commit}}"])
    tree = WORKTREE_DIR / sha[:12]
    if tree.exists():
        return tree
    WORKTREE_DIR.mkdir(exist_ok=True)
    _git(["worktree", "add", "--detach", str(tree), sha])
    return tree


def remove_worktree(tree: pathlib.Path) -> None:
    try:
        _git(["worktree", "remove", "--force", str(tree)])
    except subprocess.CalledProcessError:
        pass  # leave it for `git worktree prune`; never sink a report


def run_sample(
    tree: pathlib.Path,
    n: int,
    batch: int,
    epochs: int,
    seed: int,
    overrides: Optional[Dict] = None,
) -> Dict:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PYTHONPATH", None)  # each arm imports from its own tree
    if overrides:
        env["ABENCH_CONFIG_OVERRIDES"] = json.dumps(overrides)
    else:
        env.pop("ABENCH_CONFIG_OVERRIDES", None)
    proc = subprocess.run(
        [sys.executable, "-c", _PROBE,
         str(n), str(batch), str(epochs), str(seed)],
        cwd=str(tree),
        env=env,
        capture_output=True,
        text=True,
        timeout=1800,
    )
    if proc.returncode != 0:
        raise RuntimeError(
            f"sample in {tree} failed (rc {proc.returncode}): "
            f"{proc.stderr.strip()[-500:]}"
        )
    return json.loads(proc.stdout.strip().splitlines()[-1])


def _ratio(a: Optional[float], b: Optional[float]) -> Optional[float]:
    if (
        isinstance(a, (int, float))
        and isinstance(b, (int, float))
        and b > 0
    ):
        return round(a / b, 4)
    return None


def run_ab(
    base_ref: str,
    n: int = 16,
    batch: int = 256,
    epochs: int = 3,
    pairs: int = 4,
    seed: int = 99,
    keep_worktree: bool = False,
    progress=print,
    head_overrides: Optional[Dict] = None,
    base_overrides: Optional[Dict] = None,
) -> Dict:
    """The paired A/B: HEAD and BASE_REF sampled alternately, one
    warm-up pair discarded, ratios computed per pair.

    ``base_ref="self"`` runs BOTH arms from the working tree — the
    same-code configuration A/B (the ISSUE-15 depth comparison:
    ``--head-overrides '{"pipeline_depth":4,...}'`` vs
    ``--base-overrides '{"pipeline_depth":1}'``); per-arm Config
    kwargs ride ABENCH_CONFIG_OVERRIDES into the probe."""
    self_ab = base_ref == "self"
    base_tree = REPO_ROOT if self_ab else materialize_ref(base_ref)
    head: List[Dict] = []
    base: List[Dict] = []
    try:
        # warm-up pair (imports, JIT, page cache) — never reported
        progress(f"[abench] warm-up pair (base={base_ref})")
        run_sample(REPO_ROOT, n, batch, epochs, seed,
                   overrides=head_overrides)
        run_sample(base_tree, n, batch, epochs, seed,
                   overrides=base_overrides)
        for i in range(pairs):
            progress(f"[abench] pair {i + 1}/{pairs} head")
            head.append(
                run_sample(REPO_ROOT, n, batch, epochs, seed,
                           overrides=head_overrides)
            )
            progress(f"[abench] pair {i + 1}/{pairs} base")
            base.append(
                run_sample(base_tree, n, batch, epochs, seed,
                           overrides=base_overrides)
            )
    finally:
        if not self_ab and not keep_worktree:
            remove_worktree(base_tree)
    wall_ratios = [
        _ratio(h.get("epoch_wall_ms"), b.get("epoch_wall_ms"))
        for h, b in zip(head, base)
    ]
    p50_ratios = [
        _ratio(h.get("epoch_p50_ms"), b.get("epoch_p50_ms"))
        for h, b in zip(head, base)
    ]
    # HEAD's ordered frontier vs the base arm's (settled) epoch p50 —
    # the protocol-plane latency comparison the two-frontier split is
    # gated on (null when HEAD ran with the split off)
    ordered_ratios = [
        _ratio(h.get("ordered_epoch_p50_ms"), b.get("epoch_p50_ms"))
        for h, b in zip(head, base)
    ]
    # like-for-like ordered frontier: HEAD's ordered p50 vs the BASE
    # arm's own ordered p50 (null when the base ref predates the
    # two-frontier split) — the cleanest signal for PRs that target
    # the open->ordered window itself (delivery/routing work)
    ordered_vs_ordered = [
        _ratio(
            h.get("ordered_epoch_p50_ms"), b.get("ordered_epoch_p50_ms")
        )
        for h, b in zip(head, base)
    ]
    # per-settled-tx cost ratios (ISSUE 20): the probe saturates each
    # arm (its tx mass scales with the arm's lane count), so these
    # pair ratios compare cost per unit of settled work (< 1 = HEAD
    # cheaper per tx = higher throughput); the virtual one is
    # non-null only when a wan_profile override mounted the clock
    tx_wall_ratios = [
        _ratio(h.get("tx_wall_us"), b.get("tx_wall_us"))
        for h, b in zip(head, base)
    ]
    tx_virtual_ratios = [
        _ratio(h.get("tx_virtual_us"), b.get("tx_virtual_us"))
        for h, b in zip(head, base)
    ]

    def med(rs):
        valid = [r for r in rs if r is not None]
        return round(statistics.median(valid), 4) if valid else None

    # honesty about what the "head" arm actually ran: it samples the
    # WORKING TREE in place (uncommitted edits included), while the
    # base arm runs a clean worktree of base_ref — flag dirtiness so
    # a ratio from half-finished edits is never mistaken for HEAD's
    try:
        head_dirty = bool(_git(["status", "--porcelain"]).strip())
    except (subprocess.CalledProcessError, OSError):
        head_dirty = None  # not a git checkout: leave it unknown
    return {
        "metric": "abench_paired",
        "base_ref": base_ref,
        "head_dirty": head_dirty,
        "head_overrides": head_overrides or {},
        "base_overrides": base_overrides or {},
        "n": n,
        "batch": batch,
        "epochs": epochs,
        "seed": seed,
        "pairs": pairs,
        "head_samples": head,
        "base_samples": base,
        "pair_epoch_wall_ratios": wall_ratios,
        "pair_epoch_p50_ratios": p50_ratios,
        "pair_ordered_p50_ratios": ordered_ratios,
        "pair_ordered_vs_ordered_ratios": ordered_vs_ordered,
        "pair_tx_wall_ratios": tx_wall_ratios,
        "pair_tx_virtual_ratios": tx_virtual_ratios,
        # < 1.0 = HEAD faster, same box, same moment
        "epoch_wall_ratio_median": med(wall_ratios),
        "epoch_p50_ratio_median": med(p50_ratios),
        "ordered_p50_ratio_median": med(ordered_ratios),
        "ordered_vs_ordered_ratio_median": med(ordered_vs_ordered),
        "tx_wall_ratio_median": med(tx_wall_ratios),
        "tx_virtual_ratio_median": med(tx_virtual_ratios),
    }


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="tools.abench", description=__doc__.splitlines()[0]
    )
    ap.add_argument(
        "base_ref",
        help="git ref for the base arm, or 'self' to run both arms "
        "from the working tree (configuration A/B via overrides)",
    )
    ap.add_argument(
        "--head-overrides", default=None, metavar="JSON",
        help="Config kwargs (JSON object) for the head arm, e.g. "
        '\'{"pipeline_depth": 4, "reconfig_lead": 12}\'',
    )
    ap.add_argument(
        "--base-overrides", default=None, metavar="JSON",
        help="Config kwargs (JSON object) for the base arm",
    )
    ap.add_argument("--n", type=int, default=16)
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--pairs", type=int, default=4)
    ap.add_argument("--seed", type=int, default=99)
    ap.add_argument(
        "--keep-worktree", action="store_true",
        help="leave .abench/<sha> in place for re-runs",
    )
    ap.add_argument(
        "--no-trend", action="store_true",
        help="do not append the paired report to BENCH_TREND.jsonl",
    )
    ap.add_argument(
        "--trend", default=str(REPO_ROOT / "BENCH_TREND.jsonl"),
        help="trend JSONL path the report appends to",
    )
    args = ap.parse_args(argv)
    report = run_ab(
        args.base_ref,
        n=args.n,
        batch=args.batch,
        epochs=args.epochs,
        pairs=args.pairs,
        seed=args.seed,
        keep_worktree=args.keep_worktree,
        progress=lambda msg: print(msg, file=sys.stderr, flush=True),
        head_overrides=(
            json.loads(args.head_overrides)
            if args.head_overrides
            else None
        ),
        base_overrides=(
            json.loads(args.base_overrides)
            if args.base_overrides
            else None
        ),
    )
    if not args.no_trend:
        # paired A/B reports join the durable trend: the same-box
        # ratio history is the number cross-round comparisons can
        # actually trust (the r05 cross-box lesson)
        from tools.perfgate import append_record

        record = dict(report)
        record["kind"] = "abench_paired"
        record["ts"] = _utc_stamp()
        record["fingerprint"] = {
            "kind": "abench_paired",
            "base_ref": args.base_ref,
            "n": args.n,
            "batch": args.batch,
            "epochs": args.epochs,
            "seed": args.seed,
            # configuration A/B (base_ref 'self'): the overrides ARE
            # the identity of the comparison
            "head_overrides": report["head_overrides"],
            "base_overrides": report["base_overrides"],
        }
        try:
            append_record(args.trend, record)
        except OSError:
            pass  # a report must never sink on trend bookkeeping
    print(json.dumps(report))
    return 0


def _utc_stamp() -> str:
    import time

    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())


if __name__ == "__main__":
    sys.exit(main())
