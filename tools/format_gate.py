"""Format gate: the deterministic style invariants of this tree,
enforced with the stdlib (the image bakes no third-party formatter —
the reference pipeline's goimports gate, translated; VERDICT round-3
item 10).

Checked per file: parses as Python (ast), LF line endings, trailing
newline at EOF, no tabs in code, no trailing whitespace, lines <= 99
columns.  Exit 1 with a file:line listing on any violation.

Usage:  python tools/format_gate.py
"""

from __future__ import annotations

import ast
import pathlib
import sys

MAX_COLS = 99

ROOT = pathlib.Path(__file__).parent.parent
TARGETS = (
    sorted(ROOT.joinpath("cleisthenes_tpu").rglob("*.py"))
    + sorted(ROOT.joinpath("tests").rglob("*.py"))
    + sorted(ROOT.joinpath("tools").glob("*.py"))
    + [ROOT / "bench.py", ROOT / "__graft_entry__.py", ROOT / "demo.py"]
)


def check(path: pathlib.Path) -> list[str]:
    if not path.exists():
        return []
    raw = path.read_bytes()
    rel = path.relative_to(ROOT)
    problems = []
    if b"\r" in raw:
        problems.append(f"{rel}: CR line endings")
    if raw and not raw.endswith(b"\n"):
        problems.append(f"{rel}: no newline at EOF")
    try:
        text = raw.decode("utf-8")
    except UnicodeDecodeError as e:
        problems.append(f"{rel}: not valid UTF-8 at byte {e.start}")
        return problems
    try:
        ast.parse(text, filename=str(rel))
    except SyntaxError as e:
        problems.append(f"{rel}:{e.lineno}: syntax error: {e.msg}")
        return problems
    for i, line in enumerate(text.splitlines(), 1):
        if "\t" in line:
            problems.append(f"{rel}:{i}: tab character")
        if line != line.rstrip():
            problems.append(f"{rel}:{i}: trailing whitespace")
        if len(line) > MAX_COLS:
            problems.append(f"{rel}:{i}: {len(line)} cols > {MAX_COLS}")
    return problems


def main() -> int:
    problems: list[str] = []
    for path in TARGETS:
        problems.extend(check(path))
    for p in problems:
        print(p)
    print(
        f"format gate: {len(TARGETS)} files, "
        f"{len(problems)} problem(s)"
    )
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
