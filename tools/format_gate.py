"""Format gate: the deterministic style invariants of this tree,
enforced with the stdlib (the image bakes no third-party formatter —
the reference pipeline's goimports gate, translated; VERDICT round-3
item 10).

Checked per file: parses as Python (ast), LF line endings, trailing
newline at EOF, no tabs in code, no trailing whitespace, lines <= 99
columns.  Exit 1 with a file:line listing on any violation.

File walking and reporting are shared with tools/staticcheck via
tools/lintcommon, so the two gates always scan the same tree.

Usage:  python tools/format_gate.py
"""

from __future__ import annotations

import ast
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from tools.lintcommon import (  # noqa: E402
    REPO_ROOT,
    gate_targets,
    rel_posix,
    report,
)

MAX_COLS = 99


def check(path: pathlib.Path) -> list:
    raw = path.read_bytes()
    rel = rel_posix(path)
    problems = []
    if b"\r" in raw:
        problems.append(f"{rel}: CR line endings")
    if raw and not raw.endswith(b"\n"):
        problems.append(f"{rel}: no newline at EOF")
    try:
        text = raw.decode("utf-8")
    except UnicodeDecodeError as e:
        problems.append(f"{rel}: not valid UTF-8 at byte {e.start}")
        return problems
    try:
        ast.parse(text, filename=rel)
    except SyntaxError as e:
        problems.append(f"{rel}:{e.lineno}: syntax error: {e.msg}")
        return problems
    for i, line in enumerate(text.splitlines(), 1):
        if "\t" in line:
            problems.append(f"{rel}:{i}: tab character")
        if line != line.rstrip():
            problems.append(f"{rel}:{i}: trailing whitespace")
        if len(line) > MAX_COLS:
            problems.append(f"{rel}:{i}: {len(line)} cols > {MAX_COLS}")
    return problems


def main() -> int:
    targets = gate_targets(REPO_ROOT)
    problems: list = []
    for path in targets:
        problems.extend(check(path))
    return report("format gate", len(targets), problems)


if __name__ == "__main__":
    sys.exit(main())
