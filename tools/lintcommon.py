"""Shared file-walking + reporting helpers for the stdlib lint gates.

Both gate tools — tools/format_gate.py (style invariants) and
tools/staticcheck (the determinism-plane AST analyzer) — walk the same
tree and report the same way: one ``path:line: message`` line per
problem plus a one-line summary, exit 1 on any problem.  This module
is that shared substrate, so the two gates can never drift apart on
WHAT they scan or HOW they report.
"""

from __future__ import annotations

import pathlib
from typing import Iterable, List, Sequence

REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent


def walk_python_files(target: pathlib.Path) -> List[pathlib.Path]:
    """Every .py file under ``target`` (or the file itself), sorted
    for deterministic gate output; silently empty for missing paths
    (optional entry scripts)."""
    if not target.exists():
        return []
    if target.is_file():
        return [target] if target.suffix == ".py" else []
    return sorted(
        p for p in target.rglob("*.py") if "__pycache__" not in p.parts
    )


def gate_targets(root: pathlib.Path = REPO_ROOT) -> List[pathlib.Path]:
    """The full file set both repo gates check: the package, the test
    suite, the tools themselves, and the entry scripts."""
    out: List[pathlib.Path] = []
    for rel in ("cleisthenes_tpu", "tests", "tools"):
        out.extend(walk_python_files(root / rel))
    for rel in ("bench.py", "__graft_entry__.py", "demo.py"):
        out.extend(walk_python_files(root / rel))
    return out


def rel_posix(path: pathlib.Path, root: pathlib.Path = REPO_ROOT) -> str:
    """Repo-relative posix path — the canonical spelling in findings,
    baselines and reports (stable across platforms)."""
    try:
        return path.resolve().relative_to(root).as_posix()
    except ValueError:
        return path.as_posix()


def report(
    name: str,
    n_files: int,
    problems: Sequence[str],
    extra: Iterable[str] = (),
) -> int:
    """Print problems + the gate summary line; return the exit code."""
    for p in problems:
        print(p)
    for line in extra:
        print(line)
    print(f"{name}: {n_files} files, {len(problems)} problem(s)")
    return 1 if problems else 0


__all__ = [
    "REPO_ROOT",
    "walk_python_files",
    "gate_targets",
    "rel_posix",
    "report",
]
