"""Relay watcher: re-capture BENCH_live_r05.json when the TPU returns.

The axon relay dies and revives unpredictably (TPU_EVIDENCE_r03.md);
this loop probes it on a long interval and, on a healthy window, runs
the full bench and ATOMICALLY replaces the live artifact — only when
the run really executed on the TPU (platform 'tpu' or 'axon'), so a relay
that dies mid-run can never overwrite good evidence with a fallback
(that exact accident cost one capture this round; the artifact now
moves via os.replace from a tempfile, never a shell truncation).

Usage:  nohup python tools/bench_watcher.py >/tmp/bench_watcher.log 2>&1 &
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
from tools import benchlock  # noqa: E402

ARTIFACT = os.path.join(REPO, "BENCH_live_r05.json")
PROBE_INTERVAL_S = 300
PROBE_TIMEOUT_S = 45
BENCH_TIMEOUT_S = 3600

_PROBE = (
    "import jax, jax.numpy as jnp\n"
    "assert jax.devices()[0].platform in ('tpu', 'axon')\n"
    "import numpy as np\n"
    "x = np.asarray(jnp.ones((8, 8)) @ jnp.ones((8, 8)))\n"
    "print('PROBE_OK', float(x.sum()))\n"
)


def probe() -> bool:
    try:
        r = subprocess.run(
            [sys.executable, "-c", _PROBE],
            capture_output=True,
            text=True,
            timeout=PROBE_TIMEOUT_S,
            cwd=REPO,
        )
    except subprocess.TimeoutExpired:
        return False
    return r.returncode == 0 and "PROBE_OK" in r.stdout


def capture() -> "str | None":
    """Returns the captured platform string, or None on failure."""
    tmp = ARTIFACT + ".tmp"
    try:
        with open(tmp, "w") as out:
            # own session: a timeout must kill the whole process GROUP
            # (bench.py + its --child grandchild), not just bench.py —
            # an orphaned child would burn the core invisibly after
            # the lock releases
            proc = subprocess.Popen(
                [sys.executable, "bench.py"],
                stdout=out,
                stderr=subprocess.DEVNULL,
                cwd=REPO,
                start_new_session=True,
            )
            try:
                rc = proc.wait(timeout=BENCH_TIMEOUT_S)
            except subprocess.TimeoutExpired:
                try:
                    os.killpg(proc.pid, signal.SIGKILL)
                except OSError:
                    pass
                proc.wait()
                return None
        if rc != 0:
            return None
        with open(tmp) as f:
            doc = json.loads(f.readline())
        if doc.get("platform") not in ("tpu", "axon"):
            return None  # fallback run: never clobber TPU evidence
        os.replace(tmp, ARTIFACT)
        return str(doc.get("platform"))
    except (json.JSONDecodeError, OSError):
        return None
    finally:
        # every non-replace exit (timeout, bad rc, fallback, crash,
        # KeyboardInterrupt) must clean the tempfile up
        if os.path.exists(tmp):
            try:
                os.unlink(tmp)
            except OSError:
                pass


def main() -> None:
    while True:
        # the lock covers the PROBE too: round 4 proved that even the
        # 5-min jax-import probes contaminate a concurrent capture on
        # this one-core box.  Busy lock -> skip the whole cycle.
        with benchlock.hold("bench_watcher", block=False) as held:
            if not held:
                time.sleep(PROBE_INTERVAL_S)
                continue
            if probe():
                print(time.strftime("%H:%M:%S"), "relay healthy; capturing",
                      flush=True)
                platform = capture()
                if platform is not None:
                    print(time.strftime("%H:%M:%S"),
                          f"captured platform={platform} artifact; exiting",
                          flush=True)
                    return
                print(time.strftime("%H:%M:%S"),
                      "capture did not yield a TPU-side artifact", flush=True)
        time.sleep(PROBE_INTERVAL_S)


if __name__ == "__main__":
    main()
