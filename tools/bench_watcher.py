"""Relay watcher: re-capture BENCH_live_r04.json when the TPU returns.

The axon relay dies and revives unpredictably (TPU_EVIDENCE_r03.md);
this loop probes it on a long interval and, on a healthy window, runs
the full bench and ATOMICALLY replaces the live artifact — only when
the run really executed on the TPU (platform == "tpu"), so a relay
that dies mid-run can never overwrite good evidence with a fallback
(that exact accident cost one capture this round; the artifact now
moves via os.replace from a tempfile, never a shell truncation).

Usage:  nohup python tools/bench_watcher.py >/tmp/bench_watcher.log 2>&1 &
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ARTIFACT = os.path.join(REPO, "BENCH_live_r04.json")
PROBE_INTERVAL_S = 300
PROBE_TIMEOUT_S = 45
BENCH_TIMEOUT_S = 3600

_PROBE = (
    "import jax, jax.numpy as jnp\n"
    "assert jax.devices()[0].platform in ('tpu', 'axon')\n"
    "import numpy as np\n"
    "x = np.asarray(jnp.ones((8, 8)) @ jnp.ones((8, 8)))\n"
    "print('PROBE_OK', float(x.sum()))\n"
)


def probe() -> bool:
    try:
        r = subprocess.run(
            [sys.executable, "-c", _PROBE],
            capture_output=True,
            text=True,
            timeout=PROBE_TIMEOUT_S,
            cwd=REPO,
        )
    except subprocess.TimeoutExpired:
        return False
    return r.returncode == 0 and "PROBE_OK" in r.stdout


def capture() -> bool:
    tmp = ARTIFACT + ".tmp"
    try:
        with open(tmp, "w") as out:
            r = subprocess.run(
                [sys.executable, "bench.py"],
                stdout=out,
                stderr=subprocess.DEVNULL,
                timeout=BENCH_TIMEOUT_S,
                cwd=REPO,
            )
        if r.returncode != 0:
            return False
        with open(tmp) as f:
            doc = json.loads(f.readline())
        if doc.get("platform") != "tpu":
            return False  # fallback run: never clobber TPU evidence
        os.replace(tmp, ARTIFACT)
        return True
    except (subprocess.TimeoutExpired, json.JSONDecodeError, OSError):
        return False
    finally:
        # every non-replace exit (timeout, bad rc, fallback, crash,
        # KeyboardInterrupt) must clean the tempfile up
        if os.path.exists(tmp):
            try:
                os.unlink(tmp)
            except OSError:
                pass


def main() -> None:
    while True:
        if probe():
            print(time.strftime("%H:%M:%S"), "relay healthy; capturing",
                  flush=True)
            if capture():
                print(time.strftime("%H:%M:%S"),
                      "captured platform=tpu artifact; exiting", flush=True)
                return
            print(time.strftime("%H:%M:%S"),
                  "capture did not yield a tpu artifact", flush=True)
        time.sleep(PROBE_INTERVAL_S)


if __name__ == "__main__":
    main()
