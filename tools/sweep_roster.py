"""Seeded adversarial sweep with the CORRECT safety assertion.

The in-suite sweep (tests/test_byzantine.py::test_byzantine_seeded_sweep)
asserts STRICT equality of honest nodes' whole committed histories.
That is stronger than HBBFT's agreement property: when a bounded run
stops at its round cap (heavy Byzantine drop rates at larger rosters),
honest laggards may legitimately hold a PREFIX of the leaders'
history — agreement requires prefix consistency, not equal length.
This driver checks the real property, per round, and reports the
earliest divergence with the differing transactions if one exists.

Round-4 context: a 20-seed extension to rosters n in {10, 13} found
seed 1005 (n=13, f=4, ~3 h of schedule on one core) failing the
STRICT assertion; this tool exists to classify such failures —
harness artifact (length skew at the cap) vs a genuine safety break.

Usage:  python tools/sweep_roster.py SEED [SEED...]
        python tools/sweep_roster.py 1000-1019   # inclusive range
Env:    SWEEP_MAX_ROUNDS (default 40)
Exit:   0 = all seeds prefix-consistent; 2 = divergence (printed).
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tools import benchlock  # noqa: E402
from tools.sweep_common import build_seed_scenario, check_prefix  # noqa: E402,F401

# hours-long low-priority job: a bench capture seizing a TPU window
# SIGSTOPs us for its duration instead of sharing the one core
benchlock.register_pausable()

MAX_ROUNDS = int(os.environ.get("SWEEP_MAX_ROUNDS", "40"))


def run_seed(seed: int) -> bool:
    cfg, net, nodes, bad, honest = build_seed_scenario(seed)
    n, f = cfg.n, cfg.f
    t0 = time.time()
    for rnd in range(MAX_ROUNDS):
        for hb in nodes.values():
            hb.start_epoch()
        net.run()
        if not check_prefix(nodes, honest):
            print(f"seed {seed}: SAFETY VIOLATION at round {rnd}", flush=True)
            return False
        print(
            f"  round {rnd}: prefix ok, honest epoch counts "
            f"{sorted({len(nodes[k].committed_batches) for k in honest})}"
            f" ({time.time()-t0:.0f}s)",
            flush=True,
        )
        # EXACT run_epochs(skip=()) drain condition — ALL nodes,
        # Byzantine included — so this driver visits every round
        # boundary the in-suite sweep visits, including the final one
        # its strict assert reads
        if all(hb.pending_tx_count() == 0 for hb in nodes.values()):
            break
    counts = {k: len(nodes[k].committed_batches) for k in honest}
    committed = sum(
        len(b) for b in nodes[honest[0]].committed_batches
    )
    print(
        f"seed {seed} n={n} f={f}: prefix-consistent; per-node epoch "
        f"counts {sorted(set(counts.values()))}, {committed} txs at "
        f"{honest[0]}, {time.time()-t0:.0f}s",
        flush=True,
    )
    return True


def main() -> int:
    seeds: list = []
    for arg in sys.argv[1:]:
        if "-" in arg:
            lo, hi = arg.split("-")
            seeds.extend(range(int(lo), int(hi) + 1))
        else:
            seeds.append(int(arg))
    ok = True
    for seed in seeds:
        ok = run_seed(seed) and ok
    print("ALL PREFIX-CONSISTENT" if ok else "VIOLATIONS FOUND", flush=True)
    return 0 if ok else 2


if __name__ == "__main__":
    sys.exit(main())
