"""Profile the live message-passing path (bench protocol_n64 config).

The round-4 A/B left the N=64/B=1024 epoch at 16.6 s with a DIFFUSE
profile (~25 functions x 0.3-1.7 s); the round-5 target is <= 5 s via
a wave-drained columnar delivery plane.  This driver reproduces the
bench section under cProfile so each candidate change is aimed at the
CURRENT top lines, not round-4 memory.

Usage:  JAX_PLATFORMS=cpu python tools/profile_live.py [N] [BATCH]
"""

from __future__ import annotations

import cProfile
import io
import os
import pstats
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# pin the platform BEFORE anything imports jax: the image's
# sitecustomize registers the axon PJRT plugin at interpreter boot,
# and with the relay down the env var alone leaves init hanging on it
os.environ["JAX_PLATFORMS"] = "cpu"
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

import bench  # noqa: E402
from tools import benchlock  # noqa: E402


def main() -> int:
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 64
    batch = int(sys.argv[2]) if len(sys.argv) > 2 else 1024
    with benchlock.hold("profile_live"):
        cfg, net, nodes, _cluster = bench.build_network(
            "cpu", n=n, batch=batch
        )
        rng = np.random.default_rng(13)
        node_ids = sorted(nodes)
        for i in range(batch * 2):
            tx = rng.integers(
                0, 256, size=bench.TX_BYTES, dtype=np.uint8
            ).tobytes()
            nodes[node_ids[i % n]].add_transaction(tx)
        # warm-up epoch
        for hb in nodes.values():
            hb.start_epoch()
        net.run()
        # measured epoch under the profiler
        prof = cProfile.Profile()
        t0 = time.perf_counter()
        prof.enable()
        for hb in nodes.values():
            hb.start_epoch()
        net.run()
        prof.disable()
        wall = time.perf_counter() - t0
    print(f"epoch wall: {wall:.2f} s  (n={n}, batch={batch})")
    for sort in ("tottime", "cumulative"):
        buf = io.StringIO()
        ps = pstats.Stats(prof, stream=buf)
        ps.sort_stats(sort).print_stats(30)
        print(f"==== top 30 by {sort} ====")
        # strip the long header boilerplate
        lines = buf.getvalue().splitlines()
        start = next(
            (i for i, ln in enumerate(lines) if "ncalls" in ln), 0
        )
        print("\n".join(lines[start:start + 32]))
    return 0


if __name__ == "__main__":
    sys.exit(main())
