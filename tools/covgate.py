"""Coverage gate: stdlib line coverage via sys.monitoring (PEP 669).

The image bakes neither coverage.py nor pytest-cov; Python 3.12's
monitoring API gives the same line-event stream at near-zero
steady-state cost — every (code, line) location DISABLEs itself after
its first hit, so the instrumented suite runs within noise of the
uninstrumented one (a settrace tracer would be ~5-20x).

Used as a pytest plugin:  pytest -p tools.covgate ...
Environment:  COVGATE_MIN  — minimum percent of executable lines of
``cleisthenes_tpu`` that must execute (default 0 = report only).

Executable lines come from the compiled code objects' co_lines()
tables (docstrings and blank lines are naturally excluded), summed
over every module in the package; covered lines come from the
monitoring stream.  The gate fails the pytest session (exit status 1)
when coverage lands under the threshold.
"""

from __future__ import annotations

import os
import pathlib
import sys

_PKG_DIR = str(
    pathlib.Path(__file__).parent.parent.joinpath("cleisthenes_tpu")
)
_TOOL = sys.monitoring.COVERAGE_ID
_covered: dict = {}  # filename -> set of line numbers


def _on_line(code, line):
    fn = code.co_filename
    if fn.startswith(_PKG_DIR):
        _covered.setdefault(fn, set()).add(line)
    # first hit recorded (or file out of scope): never fire here again
    return sys.monitoring.DISABLE


def _executable_lines() -> dict:
    out: dict = {}
    for path in pathlib.Path(_PKG_DIR).rglob("*.py"):
        try:
            top = compile(path.read_text(), str(path), "exec")
        except SyntaxError:
            continue  # the format gate owns syntax
        lines: set = set()
        stack = [top]
        while stack:
            code = stack.pop()
            lines.update(
                ln for _s, _e, ln in code.co_lines() if ln is not None
            )
            stack.extend(
                c for c in code.co_consts if hasattr(c, "co_lines")
            )
        out[str(path)] = lines
    return out


# Registration happens at plugin-import time, NOT pytest_sessionstart:
# pytest imports -p plugins before conftest.py, so module-level lines
# executed during conftest/plugin-triggered imports are counted too.
# Registering in sessionstart deflated coverage by whatever the
# conftest import graph touched first (advisor r4 finding).
_armed = False


def _arm() -> None:
    global _armed
    if _armed:
        return
    try:
        sys.monitoring.use_tool_id(_TOOL, "covgate")
    except ValueError:
        # COVERAGE_ID held by another tool (e.g. coverage.py's sysmon
        # core): stay unarmed and leave THEIR registration alone —
        # sessionfinish must not free an id we never acquired
        return
    _armed = True
    sys.monitoring.register_callback(
        _TOOL, sys.monitoring.events.LINE, _on_line
    )
    sys.monitoring.set_events(_TOOL, sys.monitoring.events.LINE)


_arm()


def pytest_sessionstart(session):
    _arm()  # idempotent; covers exotic plugin-manager import orders


def pytest_sessionfinish(session, exitstatus):
    global _armed
    if not _armed:
        # COVERAGE_ID was held by another tool for the whole session:
        # nothing was measured, so gating on the empty _covered dict
        # would fail the suite with a misleading 0% — report the
        # conflict and skip the gate instead
        print(
            "covgate: DISARMED (sys.monitoring COVERAGE_ID held by "
            "another tool); coverage not measured, gate skipped"
        )
        return
    sys.monitoring.set_events(_TOOL, 0)
    sys.monitoring.free_tool_id(_TOOL)
    _armed = False
    want = _executable_lines()
    total = sum(len(v) for v in want.values())
    hit = sum(
        len(v & want.get(fn, set())) for fn, v in _covered.items()
    )
    pct = 100.0 * hit / total if total else 0.0
    minimum = float(os.environ.get("COVGATE_MIN", "0"))
    print(
        f"\ncovgate: {hit}/{total} executable lines of "
        f"cleisthenes_tpu executed = {pct:.1f}% "
        f"(threshold {minimum:.0f}%)"
    )
    if pct < minimum:
        print("covgate: FAIL — coverage under threshold")
        session.exitstatus = 1
