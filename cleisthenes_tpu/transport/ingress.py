"""Client ingress plane: the validator's door for untrusted clients.

Everything before this module fed transactions in-process
(``demo.py`` called ``host.submit``), so no throughput claim had the
one number that arbitrates them all: client-visible submit->ordered
and submit->settled latency.  This module is the missing surface:

- **Submit**: a client sends an ``IngressSubmitPayload`` frame
  (transport.message.encode_client_frame) and gets exactly one
  ``IngressAckPayload`` back — the mempool's admission verdict
  (core/mempool.py: dedup / per-client + global backpressure /
  priority eviction) plus the admitting node's two commit frontiers,
  so the client can bound when its tx can first appear in a batch.

- **Subscribe**: a client sends an ``IngressSubscribePayload`` and
  receives the settled batch stream from ``from_epoch`` on — replay
  from the node's committed history (the same state the BatchLog
  restores at startup: one log, not two) followed by a live tail fed
  from the settlement fan-out (HoneyBadger.add_commit_listener).
  Batch bodies are the canonical ledger encoding
  (core.ledger.encode_batch_body) — the exact bytes CATCHUP serves,
  so subscribers and rejoining validators read one format.

Two mounts share ALL of this logic through ``IngressPlane``:

- ``IngressGrpcServer`` exposes it as gRPC service
  ``cleisthenes.IngressService`` (raw-bytes stream methods, the same
  generic-handler idiom as transport/grpc_net.py) on
  ``Config.ingress_port``, built and started by ``ValidatorHost``.
- ``InProcIngressClient`` is the SimulatedCluster-side twin: it
  round-trips the identical encoded frames through the identical
  plane entry points, so channel-transport tests (and the fuzz
  band's client schedules) exercise the production code path with
  no sockets.

Client frames carry no envelope MAC (clients hold no roster keys);
the mempool's admission control is the abuse guard, and ingress
frames can never reach the validator-to-validator dispatch path —
``decode_client_frame`` rejects every protocol-plane payload kind.
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Iterator, List, Optional, Tuple

from cleisthenes_tpu.core.ledger import encode_batch_body
from cleisthenes_tpu.core.mempool import (
    DUPLICATE,
    OK,
    REJECTED,
    RETRY_AFTER,
)
from cleisthenes_tpu.transport.message import (
    IngressAckPayload,
    IngressBatchPayload,
    IngressStatus,
    IngressSubmitPayload,
    IngressSubscribePayload,
    decode_client_frame,
    encode_client_frame,
)
from cleisthenes_tpu.utils.determinism import guarded_by
from cleisthenes_tpu.utils.lockcheck import new_lock

# mempool verdict -> wire status (core stays transport-free, so the
# mapping lives here at the boundary)
_STATUS = {
    OK: IngressStatus.OK,
    DUPLICATE: IngressStatus.DUPLICATE,
    REJECTED: IngressStatus.REJECTED,
    RETRY_AFTER: IngressStatus.RETRY_AFTER,
}

# a subscriber this many undelivered batches behind is dropped (slow
# consumer): the feed queue must not buffer an unbounded history
FEED_CAPACITY = 4096


class SubscriptionFeed:
    """One subscriber's batch stream: a bounded queue of encoded
    IngressBatchPayload frames, fed replay-then-live in strict epoch
    order by the owning plane.  ``next_frame`` is the consumer side
    (gRPC response generator, or the in-proc twin's iterator)."""

    def __init__(self) -> None:
        self._q: "queue.Queue" = queue.Queue(maxsize=FEED_CAPACITY)
        self._closed = threading.Event()
        # set when the plane dropped us for falling behind
        self.lagged = False

    def _push(self, frame: bytes) -> bool:
        """Plane side.  False means the consumer is too far behind
        and the feed was closed (the ingress contract prefers a
        visible drop over unbounded buffering)."""
        if self._closed.is_set():
            return False
        try:
            self._q.put_nowait(frame)
            return True
        except queue.Full:
            self.lagged = True
            self.close()
            return False

    def next_frame(self, timeout: float = 0.25) -> Optional[bytes]:
        """One encoded IngressBatchPayload, or None on timeout/close."""
        if self._closed.is_set() and self._q.empty():
            return None
        try:
            return self._q.get(timeout=timeout)
        except queue.Empty:
            return None

    @property
    def closed(self) -> bool:
        return self._closed.is_set() and self._q.empty()

    def close(self) -> None:
        self._closed.set()


@guarded_by("_lock", "_feeds")
class IngressPlane:
    """One node's transport-agnostic ingress core.  Thread-safe:
    submit_frame runs on gRPC worker threads (the mempool admits
    under its own lock), the settlement fan-out runs on the protocol
    thread, and subscribe can come from either."""

    def __init__(self, node, on_admitted: Optional[Callable[[], None]] = None):
        if node.mempool is None:
            raise RuntimeError(
                "ingress needs a mounted mempool "
                "(Config.mempool_capacity > 0)"
            )
        self._node = node
        # optional post-admission kick (ValidatorHost wires a propose
        # nudge so an idle node starts an epoch for fresh client work;
        # the in-proc cluster's run loop does its own driving)
        self._on_admitted = on_admitted
        self._lock = new_lock()
        self._feeds: List[SubscriptionFeed] = []
        node.set_subscriber_provider(self.subscriber_count)
        node.add_commit_listener(self._on_settled)

    # -- submit --------------------------------------------------------

    def submit_frame(self, data: bytes) -> bytes:
        """One client submit frame in, exactly one ack frame out —
        the no-silent-drops contract.  A malformed frame raises to
        the transport (which hangs up), never into the protocol."""
        payload = decode_client_frame(data)
        if not isinstance(payload, IngressSubmitPayload):
            raise ValueError(
                f"expected a submit frame, got {type(payload).__name__}"
            )
        tr = self._node.trace
        t0 = 0.0 if tr is None else tr.now()
        verdict = self._node.submit_ingress(
            payload.client_id, payload.fee, payload.tx
        )
        status = _STATUS[verdict.status]
        if tr is not None:
            tr.complete("ingress", "submit", t0, status=verdict.status)
        if status == IngressStatus.OK and self._on_admitted is not None:
            self._on_admitted()
        # frontiers in the ack are MERGED total-order frontiers: at
        # lanes=1 they equal (epoch, settled_epoch) byte-for-byte; at
        # lanes>1 they span every lane, so a client's exactly-once
        # audit window is one number regardless of which lane its tx
        # hashed into
        ack = IngressAckPayload(
            client_id=payload.client_id,
            nonce=payload.nonce,
            status=int(status),
            ordered_epoch=self._node.merged_ordered_frontier,
            settled_epoch=self._node.merged_settled_frontier,
            retry_after_ms=verdict.retry_after_ms,
        )
        return encode_client_frame(ack)

    # -- subscribe -----------------------------------------------------

    def subscribe(self, from_epoch: int) -> SubscriptionFeed:
        """Open one committed-batch feed: settled epochs in
        [from_epoch, settled-frontier) replay immediately from the
        committed history, later ones arrive live from the settlement
        fan-out.  Registration and replay happen under one lock
        acquisition against _on_settled, so the epoch sequence a
        subscriber sees has no gap and no duplicate at the
        replay/live seam."""
        feed = SubscriptionFeed()
        with self._lock:
            # merged total order (== committed_batches at lanes=1):
            # subscribers see ONE slot sequence across all lanes, the
            # same stream the live fan-out (add_commit_listener) emits
            batches = self._node.merged_batches
            for epoch in range(max(0, from_epoch), len(batches)):
                feed._push(
                    encode_client_frame(
                        IngressBatchPayload(
                            epoch, encode_batch_body(epoch, batches[epoch])
                        )
                    )
                )
            self._feeds.append(feed)
        return feed

    def _on_settled(self, epoch: int, batch) -> None:
        """Settlement fan-out (protocol thread, via
        HoneyBadger.add_commit_listener): encode once, feed every
        live subscriber, drop the ones that fell behind."""
        with self._lock:
            if not self._feeds:
                return
            frame = encode_client_frame(
                IngressBatchPayload(epoch, encode_batch_body(epoch, batch))
            )
            live = [f for f in self._feeds if f._push(frame)]
            self._feeds = live
        tr = self._node.trace
        if tr is not None:
            tr.instant("ingress", "stream", epoch=epoch, subs=len(live))

    def subscriber_count(self) -> int:
        with self._lock:
            return len(self._feeds)

    def close_feed(self, feed: SubscriptionFeed) -> None:
        feed.close()
        with self._lock:
            if feed in self._feeds:
                self._feeds.remove(feed)

    def close(self) -> None:
        with self._lock:
            feeds, self._feeds = self._feeds, []
        for f in feeds:
            f.close()


class InProcIngressClient:
    """The SimulatedCluster-side twin of the gRPC client: identical
    encoded frames through the identical IngressPlane entry points,
    minus the sockets — so channel-transport tests and the fuzz
    band's client schedules exercise the production path."""

    def __init__(self, plane: IngressPlane):
        self._plane = plane

    def submit(
        self, client_id: str, nonce: int, fee: int, tx: bytes
    ) -> IngressAckPayload:
        frame = encode_client_frame(
            IngressSubmitPayload(client_id, nonce, fee, tx)
        )
        ack = decode_client_frame(self._plane.submit_frame(frame))
        assert isinstance(ack, IngressAckPayload)
        return ack

    def subscribe(self, from_epoch: int = 0) -> SubscriptionFeed:
        return self._plane.subscribe(from_epoch)

    def next_batch(
        self, feed: SubscriptionFeed, timeout: float = 0.25
    ) -> Optional[IngressBatchPayload]:
        frame = feed.next_frame(timeout=timeout)
        if frame is None:
            return None
        payload = decode_client_frame(frame)
        assert isinstance(payload, IngressBatchPayload)
        return payload


# ---------------------------------------------------------------------------
# gRPC mount
# ---------------------------------------------------------------------------

INGRESS_SERVICE = "cleisthenes.IngressService"
SUBMIT_METHOD = "Submit"
SUBSCRIBE_METHOD = "Subscribe"


def _identity(b: bytes) -> bytes:
    return b


class IngressGrpcServer:
    """The client-facing gRPC mount of one node's IngressPlane: raw-
    bytes stream methods via the generic-handler idiom (the
    grpc_net.GrpcServer pattern), bound on Config.ingress_port.

    ``Submit`` is bidi: each request frame yields exactly one ack
    frame, so a pipelining client matches acks by nonce.
    ``Subscribe`` takes one IngressSubscribePayload frame and streams
    IngressBatchPayload frames until the client hangs up."""

    def __init__(self, plane: IngressPlane, addr: str) -> None:
        import grpc  # deferred like grpc_net: core never needs it

        self._grpc = grpc
        self._plane = plane
        self.addr = addr
        self.port: Optional[int] = None
        self._server: Optional["grpc.Server"] = None

    def _submit_behavior(self, request_iterator, context):
        for data in request_iterator:
            try:
                yield self._plane.submit_frame(data)
            except ValueError:
                # malformed client frame: hang up, never crash the node
                context.cancel()
                return

    def _subscribe_behavior(self, request_iterator, context):
        try:
            first = next(iter(request_iterator))
            payload = decode_client_frame(first)
        except (StopIteration, ValueError):
            context.cancel()
            return
        if not isinstance(payload, IngressSubscribePayload):
            context.cancel()
            return
        feed = self._plane.subscribe(payload.from_epoch)
        try:
            while context.is_active():
                frame = feed.next_frame(timeout=0.25)
                if frame is not None:
                    yield frame
                elif feed.closed:
                    return
        finally:
            self._plane.close_feed(feed)

    def listen(self, max_workers: int = 16) -> None:
        grpc = self._grpc
        handler = grpc.method_handlers_generic_handler(
            INGRESS_SERVICE,
            {
                SUBMIT_METHOD: grpc.stream_stream_rpc_method_handler(
                    self._submit_behavior,
                    request_deserializer=_identity,
                    response_serializer=_identity,
                ),
                SUBSCRIBE_METHOD: grpc.stream_stream_rpc_method_handler(
                    self._subscribe_behavior,
                    request_deserializer=_identity,
                    response_serializer=_identity,
                ),
            },
        )
        from concurrent import futures as _futures

        self._server = grpc.server(
            _futures.ThreadPoolExecutor(max_workers=max_workers)
        )
        self._server.add_generic_rpc_handlers((handler,))
        self.port = self._server.add_insecure_port(self.addr)
        if self.port == 0:
            raise RuntimeError(f"could not bind ingress {self.addr}")
        self._server.start()

    def stop(self, grace: float = 0.5) -> None:
        self._plane.close()
        if self._server is not None:
            self._server.stop(grace)


class IngressGrpcClient:
    """A client's handle on one node's ingress service (demo.py and
    the gRPC round-trip tests; loadgen uses the in-proc twin)."""

    def __init__(self, addr: str) -> None:
        import grpc

        self._channel = grpc.insecure_channel(addr)
        self._submit = self._channel.stream_stream(
            f"/{INGRESS_SERVICE}/{SUBMIT_METHOD}",
            request_serializer=_identity,
            response_deserializer=_identity,
        )
        self._subscribe = self._channel.stream_stream(
            f"/{INGRESS_SERVICE}/{SUBSCRIBE_METHOD}",
            request_serializer=_identity,
            response_deserializer=_identity,
        )

    def submit(
        self, client_id: str, nonce: int, fee: int, tx: bytes,
        timeout: float = 10.0,
    ) -> IngressAckPayload:
        acks = self.submit_many(
            [(client_id, nonce, fee, tx)], timeout=timeout
        )
        return acks[0]

    def submit_many(
        self,
        submits: List[Tuple[str, int, int, bytes]],
        timeout: float = 30.0,
    ) -> List[IngressAckPayload]:
        """Pipeline many submits on one stream; one ack per submit,
        in order."""
        frames = [
            encode_client_frame(IngressSubmitPayload(c, n, f, t))
            for (c, n, f, t) in submits
        ]
        acks: List[IngressAckPayload] = []
        for resp in self._submit(iter(frames), timeout=timeout):
            ack = decode_client_frame(resp)
            assert isinstance(ack, IngressAckPayload)
            acks.append(ack)
            if len(acks) == len(frames):
                break
        return acks

    def subscribe(
        self, from_epoch: int = 0, timeout: float = 3600.0
    ) -> Iterator[IngressBatchPayload]:
        """Yields settled batches from ``from_epoch`` until the caller
        abandons the iterator (closing the channel tears it down)."""
        frame = encode_client_frame(IngressSubscribePayload(from_epoch))
        for resp in self._subscribe(iter([frame]), timeout=timeout):
            payload = decode_client_frame(resp)
            assert isinstance(payload, IngressBatchPayload)
            yield payload

    def close(self) -> None:
        self._channel.close()


__all__ = [
    "FEED_CAPACITY",
    "INGRESS_SERVICE",
    "IngressGrpcClient",
    "IngressGrpcServer",
    "IngressPlane",
    "InProcIngressClient",
    "SubscriptionFeed",
]
