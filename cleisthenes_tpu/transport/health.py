"""Self-healing dial layer: backoff policy + per-peer health states.

The reference redials never (a lost stream stays lost until process
restart, conn.go:104-128), and the first TPU-build redial loop spun at
a fixed interval — the other failure mode: a roster of N validators
hammering a dead peer in lockstep, then reconnect-storming it the
moment it returns.  This module is the middle path, shared by boot
dials and mid-run redials (transport/host.py):

- ``Backoff``: capped exponential delays with seeded jitter.  Jitter
  de-synchronizes the roster's retries; seeding it (Config.seed) keeps
  fault tests replayable.
- ``PeerHealthTracker``: a per-peer UP / DEGRADED / DOWN state machine
  with reconnect counters and the recent delay schedule, surfaced
  through utils.metrics.Metrics.snapshot() as the transport-health
  block — the observability that proves the redial layer is backing
  off rather than spinning.

State machine (per peer):

    UP --stream lost--> DEGRADED --DOWN_AFTER consecutive
    failed dials--> DOWN; any successful dial --> UP (and, when the
    peer was not UP, reconnects += 1).
"""

from __future__ import annotations

import random
import time
from typing import Dict, List, Optional

from cleisthenes_tpu.utils.determinism import guarded_by
from cleisthenes_tpu.utils.lockcheck import new_lock

# canonical UP/DEGRADED/DOWN vocabulary lives in utils/watchdog.py;
# dial health and SLO verdicts must stay comparable (host peer states
# feed SloWatchdog._lagging_peers and the /healthz fold)
from cleisthenes_tpu.utils.watchdog import DEGRADED, DOWN, UP

# consecutive failed dials before a DEGRADED peer is declared DOWN
# (it keeps being redialed — DOWN is a reporting state, not a stop)
DOWN_AFTER = 5

# recent dial delays kept per peer (enough to show the backoff curve)
_DELAY_KEEP = 16


class Backoff:
    """Capped exponential backoff with jitter and a stability-gated
    reset.

    ``next_delay()`` returns base * factor^k jittered +/-25% so
    independent retriers spread out, then capped at ``max_s`` —
    ``max_s`` is a HARD bound (operators tune it to bound reconnect
    latency), so the jitter never overshoots it.  Deterministic for a
    seeded ``rng`` (fault tests), OS-random otherwise.

    Re-arming: a bare ``reset()`` re-arms unconditionally, but the
    dial layer must NOT call it on every successful dial — a WAN link
    that flaps (dial lands, stream dies seconds later, repeat) would
    then be re-probed from ``base_s`` forever, hammering the remote at
    base cadence with the cap never reached (the ISSUE 16 regression).
    Instead the owner reports ``note_connected()`` / ``note_lost()``
    and the schedule re-arms only when the connection stayed up for at
    least ``stability_s`` (default: ``max_s`` — a link must survive
    one full max-backoff period to count as healed); a shorter-lived
    success CONTINUES the capped seeded-jitter schedule.
    """

    def __init__(
        self,
        base_s: float,
        max_s: float,
        rng: Optional[random.Random] = None,
        factor: float = 2.0,
        stability_s: Optional[float] = None,
    ) -> None:
        if base_s <= 0 or max_s < base_s:
            raise ValueError(f"backoff needs 0 < base <= max, "
                             f"got base={base_s} max={max_s}")
        self.base_s = base_s
        self.max_s = max_s
        self.factor = factor
        self.stability_s = max_s if stability_s is None else stability_s
        self._rng = rng if rng is not None else random.Random()
        self._cur = base_s
        self._connected_at: Optional[float] = None

    def next_delay(self) -> float:
        d = self._cur
        self._cur = min(self._cur * self.factor, self.max_s)
        return min(d * (0.75 + 0.5 * self._rng.random()), self.max_s)

    def reset(self) -> None:
        self._cur = self.base_s

    def note_connected(self, now: Optional[float] = None) -> None:
        """The dial landed.  Starts the stability clock; does NOT
        re-arm the schedule (see class docstring)."""
        self._connected_at = (
            time.monotonic() if now is None else now
        )

    def note_lost(self, now: Optional[float] = None) -> None:
        """The stream died.  Re-arms the schedule only if the
        connection survived ``stability_s`` — a flap continues the
        capped schedule instead of restarting it."""
        if now is None:
            now = time.monotonic()
        if (
            self._connected_at is not None
            and now - self._connected_at >= self.stability_s
        ):
            self.reset()
        self._connected_at = None


def backoff_rng(seed: Optional[int], node_id: str, peer_id: str) -> random.Random:
    """Jitter source for one (node, peer) dial lane: derived from the
    config seed when set — every retry schedule replays — and from OS
    entropy in production (Config.seed docs)."""
    if seed is None:
        return random.Random()
    return random.Random(f"{seed}|{node_id}|{peer_id}|dial")


class _PeerHealth:
    __slots__ = (
        "state",
        "ever_up",
        "reconnects",
        "dial_attempts",
        "dial_failures",
        "consecutive_failures",
        "recent_delays",
        "since",
    )

    def __init__(self) -> None:
        self.state = DEGRADED  # not connected until the first dial lands
        self.ever_up = False
        self.reconnects = 0  # successful re-establishments after a loss
        self.dial_attempts = 0
        self.dial_failures = 0
        self.consecutive_failures = 0
        self.recent_delays: List[float] = []
        self.since = time.monotonic()

    def _enter(self, state: str) -> None:
        if state != self.state:
            self.state = state
            self.since = time.monotonic()


@guarded_by("_lock", "_peers", "_retired")
class PeerHealthTracker:
    """Thread-safe per-peer health registry for one validator host.

    Writers are the dial paths (connect loop, redial threads, stream
    loss callbacks) and the retirement path (dynamic membership);
    readers are Metrics.snapshot() and tests.
    """

    def __init__(self, peer_ids=()) -> None:
        self._peers: Dict[str, _PeerHealth] = {
            p: _PeerHealth() for p in peer_ids
        }
        # peers removed from the roster (RECONFIG retirement): their
        # health rows are dropped from snapshots and every later dial
        # event for them is ignored — without the flag, a racing
        # redial thread's dial_failed() would silently resurrect the
        # row and the backoff loop would hammer a host that is GONE,
        # forever (the redial-storm the retirement satellite kills)
        self._retired: set = set()
        self._lock = new_lock()

    def _peer_locked(self, peer_id: str) -> _PeerHealth:
        """Lookup-or-create; caller holds ``_lock`` (CONC001 naming
        contract)."""
        ph = self._peers.get(peer_id)
        if ph is None:
            ph = self._peers[peer_id] = _PeerHealth()
        return ph

    def retire(self, peer_id: str) -> None:
        """Peer left the roster: drop its health state and ignore
        every later dial event for it.  Idempotent."""
        with self._lock:
            self._retired.add(peer_id)
            self._peers.pop(peer_id, None)

    def readmit(self, peer_id: str) -> None:
        """Un-retire: a later RECONFIG re-admitted the id.  The peer
        starts from a fresh (DEGRADED-until-dialed) health row, like
        any new joiner."""
        with self._lock:
            self._retired.discard(peer_id)

    def is_retired(self, peer_id: str) -> bool:
        """Dial loops poll this to cancel their backoff (a retired
        peer must stop generating redial storms)."""
        with self._lock:
            return peer_id in self._retired

    def dial_scheduled(self, peer_id: str, delay_s: float) -> None:
        """A redial was scheduled ``delay_s`` in the future: record the
        backoff curve (the anti-spinning evidence)."""
        with self._lock:
            if peer_id in self._retired:
                return
            ph = self._peer_locked(peer_id)
            ph.recent_delays.append(delay_s)
            del ph.recent_delays[:-_DELAY_KEEP]

    def dial_started(self, peer_id: str) -> None:
        with self._lock:
            if peer_id in self._retired:
                return
            self._peer_locked(peer_id).dial_attempts += 1

    def dial_failed(self, peer_id: str) -> None:
        with self._lock:
            if peer_id in self._retired:
                return
            ph = self._peer_locked(peer_id)
            ph.dial_failures += 1
            ph.consecutive_failures += 1
            ph._enter(
                DOWN
                if ph.consecutive_failures >= DOWN_AFTER
                else DEGRADED
            )

    def connected(self, peer_id: str) -> None:
        with self._lock:
            if peer_id in self._retired:
                return
            ph = self._peer_locked(peer_id)
            if ph.ever_up and ph.state != UP:
                # re-establishment, not the boot-time first connect
                ph.reconnects += 1
            ph.ever_up = True
            ph.consecutive_failures = 0
            ph._enter(UP)

    def stream_lost(self, peer_id: str) -> None:
        with self._lock:
            if peer_id in self._retired:
                return
            ph = self._peer_locked(peer_id)
            ph._enter(DEGRADED)

    def state(self, peer_id: str) -> str:
        with self._lock:
            if peer_id in self._retired:
                return DOWN  # reported, never re-created
            return self._peer_locked(peer_id).state

    def snapshot(self) -> Dict[str, Dict[str, object]]:
        """Per-peer health block for Metrics.snapshot()."""
        now = time.monotonic()
        with self._lock:
            return {
                peer: {
                    "state": ph.state,
                    "reconnects": ph.reconnects,
                    "dial_attempts": ph.dial_attempts,
                    "dial_failures": ph.dial_failures,
                    "consecutive_failures": ph.consecutive_failures,
                    "recent_delays_s": list(ph.recent_delays),
                    "state_age_s": round(now - ph.since, 3),
                }
                for peer, ph in self._peers.items()
            }


__all__ = [
    "UP",
    "DEGRADED",
    "DOWN",
    "DOWN_AFTER",
    "Backoff",
    "backoff_rng",
    "PeerHealthTracker",
]
