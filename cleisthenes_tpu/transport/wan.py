"""Seeded WAN emulation plane: per-link delay models on a virtual clock.

Every bench and fuzz band before ISSUE 16 ran on a same-box
zero-latency ``ChannelNetwork``, so the robustness machinery (stall
watchdogs, CATCHUP, K-deep pipelining) had never been exercised in the
regime HBBFT was designed for: asynchronous WANs with heterogeneous
links.  This module prices every frame's admission into a *virtual*
delivery deadline; the channel scheduler holds the frame invisible
until its seeded virtual clock passes that deadline (see
``ChannelNetwork._wan_release``).  Virtual time never touches wall
time — a ``wan_global`` schedule with 300 ms RTTs still runs at CPU
speed — and every draw routes through ``utils.determinism.wan_rng``
named streams, so a fixed (seed, profile) pair replays byte-identical
ledgers across processes and PYTHONHASHSEED values.

Model, per ordered (sender, receiver) pair (``LinkModel``):

- base RTT drawn once per link from the profile's intra-/inter-region
  range (regions assigned round-robin in registration order);
- per-frame jitter as a seeded fraction of the one-way delay;
- loss as *reliable-transport retransmission delay*: each seeded
  "lost" transmission adds one exponentially-backed-off RTO to the
  deadline.  Frames are never silently dropped — the channel transport
  has no retransmit layer, so a true drop would model a broken TCP
  stack, not a lossy WAN, and would wedge liveness for reasons the
  protocol under test cannot fix;
- a bandwidth cap that serializes frames sharing a link (per-link
  ``busy_until`` in virtual time);
- heavy-tailed straggler episodes: a seeded minority of nodes suffers
  Pareto-distributed slow episodes that multiply the delay of every
  frame they send or receive while active.

The profile matrix (``PROFILES``) is the named scenario vocabulary for
``SimulatedCluster(wan_profile=)``, ``tools/fuzz.py --wan`` and
bench.py's WAN section; docs/FAULTS.md documents what each profile is
meant to catch.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple, Union

from cleisthenes_tpu.utils.determinism import guarded_by, wan_rng
from cleisthenes_tpu.utils.lockcheck import new_lock

# an episode's Pareto tail is capped so one draw cannot freeze a link
# for the whole schedule (virtual seconds)
_EPISODE_DUR_CAP_S = 120.0
# retransmission attempts are capped: past this the emulated link is
# effectively down for the frame and the accumulated RTOs already
# dominate the deadline
_MAX_RETRANSMITS = 8


@dataclasses.dataclass(frozen=True)
class WanProfile:
    """One named WAN scenario: every knob the link models read."""

    name: str
    regions: Tuple[str, ...]
    intra_rtt_ms: Tuple[float, float]  # base RTT range within a region
    inter_rtt_ms: Tuple[float, float]  # base RTT range across regions
    jitter_frac: float  # per-frame one-way jitter, fraction of base
    loss_p: float  # per-transmission loss probability
    bandwidth_bps: Optional[float]  # link serialization rate, bytes/s
    straggler_fraction: float  # fraction of nodes with episodes
    straggler_gap_s: float  # mean virtual gap between episodes
    straggler_dur_s: float  # episode duration scale (Pareto)
    straggler_alpha: float  # Pareto shape; smaller = heavier tail
    straggler_mult: Tuple[float, float]  # delay multiplier range
    delivery_quantum_ms: float  # co-deadline coalescing window
    stall_floor_s: float  # epoch-stall watchdog budget floor


PROFILES: Dict[str, WanProfile] = {
    # same-rack control: sub-ms RTT, no loss, no stragglers — the
    # regression anchor proving the WAN plane at its floor matches
    # the zero-latency scheduler's protocol outcomes
    "lan": WanProfile(
        name="lan",
        regions=("rack",),
        intra_rtt_ms=(0.2, 0.6),
        inter_rtt_ms=(0.2, 0.6),
        jitter_frac=0.05,
        loss_p=0.0,
        bandwidth_bps=1.25e9,
        straggler_fraction=0.0,
        straggler_gap_s=1.0,
        straggler_dur_s=0.1,
        straggler_alpha=2.0,
        straggler_mult=(1.0, 1.0),
        delivery_quantum_ms=0.1,
        stall_floor_s=2.0,
    ),
    # three continents, clean links: the canonical geo-replication
    # deployment — exercises RTT heterogeneity (intra vs inter gap)
    # and the partition/heal scenarios between region blocks
    "wan_3region": WanProfile(
        name="wan_3region",
        regions=("us-east", "eu-west", "ap-south"),
        intra_rtt_ms=(1.0, 3.0),
        inter_rtt_ms=(30.0, 120.0),
        jitter_frac=0.10,
        loss_p=0.002,
        bandwidth_bps=1.25e7,
        straggler_fraction=0.0,
        straggler_gap_s=10.0,
        straggler_dur_s=1.0,
        straggler_alpha=1.5,
        straggler_mult=(1.0, 1.0),
        delivery_quantum_ms=5.0,
        stall_floor_s=8.0,
    ),
    # five regions, long tails, thin pipes, mild stragglers: the
    # worst realistic envelope — bandwidth serialization starts to
    # matter for batched frames
    "wan_global": WanProfile(
        name="wan_global",
        regions=("us-east", "us-west", "eu-west", "ap-south", "ap-east"),
        intra_rtt_ms=(2.0, 5.0),
        inter_rtt_ms=(80.0, 320.0),
        jitter_frac=0.20,
        loss_p=0.01,
        bandwidth_bps=2.5e6,
        straggler_fraction=0.2,
        straggler_gap_s=20.0,
        straggler_dur_s=2.0,
        straggler_alpha=1.5,
        straggler_mult=(2.0, 8.0),
        delivery_quantum_ms=10.0,
        stall_floor_s=20.0,
    ),
    # moderate RTTs, but a seeded minority of nodes hits heavy-tailed
    # slow episodes (alpha 1.1: infinite-variance durations) with
    # 10-100x delay multipliers — the watchdog-calibration scenario:
    # epoch-stall must not flip DOWN while the honest majority makes
    # progress, and a straggling-but-alive peer must read DEGRADED
    "straggler_tail": WanProfile(
        name="straggler_tail",
        regions=("us-east", "eu-west"),
        intra_rtt_ms=(1.0, 3.0),
        inter_rtt_ms=(20.0, 60.0),
        jitter_frac=0.10,
        loss_p=0.001,
        bandwidth_bps=1.25e7,
        straggler_fraction=0.3,
        straggler_gap_s=5.0,
        straggler_dur_s=1.0,
        straggler_alpha=1.1,
        straggler_mult=(10.0, 100.0),
        delivery_quantum_ms=5.0,
        stall_floor_s=30.0,
    ),
    # 5% per-transmission loss on thin links: retransmission delay
    # dominates — exercises the RBC echo/ready paths and CATCHUP under
    # pervasive delay variance rather than topology
    "lossy": WanProfile(
        name="lossy",
        regions=("us-east", "eu-west"),
        intra_rtt_ms=(1.0, 3.0),
        inter_rtt_ms=(10.0, 40.0),
        jitter_frac=0.15,
        loss_p=0.05,
        bandwidth_bps=5e6,
        straggler_fraction=0.0,
        straggler_gap_s=10.0,
        straggler_dur_s=1.0,
        straggler_alpha=1.5,
        straggler_mult=(1.0, 1.0),
        delivery_quantum_ms=2.0,
        stall_floor_s=10.0,
    ),
}


def wan_profile_names() -> Tuple[str, ...]:
    """Sorted profile names — the seed-draw vocabulary for fuzz."""
    return tuple(sorted(PROFILES))


class _Straggler:
    """One node's heavy-tailed slow-episode process in virtual time.

    Episodes are generated lazily as the clock advances: Pareto
    durations (capped), uniform delay multipliers, exponential gaps.
    The whole trajectory is a pure function of the node's named rng
    stream, independent of how often it is sampled.
    """

    __slots__ = ("rng", "profile", "episode_until", "mult", "next_start", "episodes")

    def __init__(self, rng, profile: WanProfile) -> None:
        self.rng = rng
        self.profile = profile
        self.episode_until = 0.0
        self.mult = 1.0
        self.next_start = rng.expovariate(1.0 / profile.straggler_gap_s)
        self.episodes = 0

    def multiplier(self, now: float) -> float:
        p = self.profile
        while self.next_start <= now:
            dur = min(
                p.straggler_dur_s * self.rng.paretovariate(p.straggler_alpha),
                _EPISODE_DUR_CAP_S,
            )
            self.episode_until = self.next_start + dur
            self.mult = self.rng.uniform(*p.straggler_mult)
            self.episodes += 1
            self.next_start = self.episode_until + self.rng.expovariate(
                1.0 / p.straggler_gap_s
            )
        return self.mult if now < self.episode_until else 1.0

    def active(self, now: float) -> bool:
        self.multiplier(now)  # advance the process to ``now``
        return now < self.episode_until


class LinkModel:
    """Delay state for one ordered (sender, receiver) pair."""

    __slots__ = ("rng", "rtt_s", "busy_until")

    def __init__(self, profile: WanProfile, same_region: bool, rng) -> None:
        lo, hi = (
            profile.intra_rtt_ms if same_region else profile.inter_rtt_ms
        )
        self.rng = rng
        self.rtt_s = rng.uniform(lo, hi) / 1e3
        self.busy_until = 0.0  # bandwidth serialization horizon


@guarded_by("_lock", "_links", "_regions", "_stragglers")
class WanEmulator:
    """The virtual clock + the lazy per-link / per-node model maps.

    Owned by ``ChannelNetwork``; the scheduler calls ``admit`` at
    enqueue time and ``advance`` when the visible queue drains.  All
    state is keyed by name (node id, ordered pair), never by
    construction order, so observability reads cannot perturb replay.

    The lazy model maps are guarded: ``admit`` runs on the scheduler
    thread while ``stats``/``link_info`` serve the metrics scrape
    thread, and an unguarded lazy fill racing a scrape iteration is a
    dict-mutation error at best and a silently forked model at worst
    (the ISSUE-17 interprocedural sweep surfaced exactly this).  The
    virtual clock and the two delay counters stay unguarded: they are
    scalar monotone values read opportunistically by observers.
    """

    def __init__(
        self,
        profile: Union[str, WanProfile],
        seed: Optional[int],
    ) -> None:
        if isinstance(profile, str):
            try:
                profile = PROFILES[profile]
            except KeyError:
                raise ValueError(
                    f"unknown WAN profile {profile!r}; "
                    f"known: {', '.join(wan_profile_names())}"
                ) from None
        self.profile = profile
        self._seed = seed
        self.now = 0.0  # the virtual clock (seconds)
        self._lock = new_lock()
        self._links: Dict[Tuple[str, str], LinkModel] = {}
        self._regions: Dict[str, str] = {}
        self._stragglers: Dict[str, Optional[_Straggler]] = {}
        self.frames_delayed = 0
        self.retransmits = 0

    # -- topology ------------------------------------------------------

    def _register_locked(self, node_id: str) -> None:
        if node_id not in self._regions:
            regions = self.profile.regions
            self._regions[node_id] = regions[len(self._regions) % len(regions)]

    def register(self, node_id: str) -> None:
        """Assign ``node_id`` a region, round-robin in registration
        order (ChannelNetwork.join order — sorted ids for every
        driver in the tree, so the mapping is schedule-stable)."""
        with self._lock:
            self._register_locked(node_id)

    def _region_of_locked(self, node_id: str) -> str:
        self._register_locked(node_id)
        return self._regions[node_id]

    def region_of(self, node_id: str) -> str:
        with self._lock:
            return self._region_of_locked(node_id)

    def _link_locked(self, sender: str, receiver: str) -> LinkModel:
        key = (sender, receiver)
        link = self._links.get(key)
        if link is None:
            same = self._region_of_locked(
                sender
            ) == self._region_of_locked(receiver)
            link = LinkModel(
                self.profile,
                same,
                wan_rng(self._seed, "link", sender, receiver),
            )
            self._links[key] = link
        return link

    def _straggler_locked(self, node_id: str) -> Optional[_Straggler]:
        if node_id not in self._stragglers:
            p = self.profile
            rng = wan_rng(self._seed, "straggler", node_id)
            picked = (
                p.straggler_fraction > 0.0
                and rng.random() < p.straggler_fraction
            )
            self._stragglers[node_id] = _Straggler(rng, p) if picked else None
        return self._stragglers[node_id]

    # -- the pricing model ---------------------------------------------

    def admit(self, sender: str, receiver: str, nbytes: int) -> float:
        """Price one frame: the virtual time at which it becomes
        visible to the delivery scheduler."""
        p = self.profile
        now = self.now
        with self._lock:
            link = self._link_locked(sender, receiver)
            owd = (link.rtt_s / 2.0) * (
                1.0 + p.jitter_frac * link.rng.random()
            )
            if p.loss_p > 0.0:
                # reliable-transport retransmission: every seeded loss
                # adds one RTO, doubling (TCP-ish) up to the cap
                rto = max(2.0 * link.rtt_s, 0.01)
                lost = 0
                while (
                    lost < _MAX_RETRANSMITS
                    and link.rng.random() < p.loss_p
                ):
                    owd += rto
                    rto *= 2.0
                    lost += 1
                self.retransmits += lost
            start = now
            if p.bandwidth_bps:
                # frames sharing a link serialize behind its horizon
                start = max(now, link.busy_until) + nbytes / p.bandwidth_bps
                link.busy_until = start
            mult = 1.0
            s = self._straggler_locked(sender)
            if s is not None:
                mult = s.multiplier(now)
            r = self._straggler_locked(receiver)
            if r is not None:
                mult = max(mult, r.multiplier(now))
        ready = start + owd * mult
        if ready > now:
            self.frames_delayed += 1
        return ready

    def advance(self, t: float) -> None:
        """Move the virtual clock forward (never backward)."""
        if t > self.now:
            self.now = t

    # -- observability -------------------------------------------------

    def link_info(self, sender: str, receiver: str) -> Dict[str, object]:
        """One link's model state for ``ChannelNetwork.link_states``:
        base rtt_ms, the profile loss probability, and whether either
        endpoint is inside a straggler episode right now."""
        with self._lock:
            link = self._link_locked(sender, receiver)
            straggling = False
            for node in (sender, receiver):
                s = self._straggler_locked(node)
                if s is not None and s.active(self.now):
                    straggling = True
                    break
        return {
            "rtt_ms": link.rtt_s * 1e3,
            "loss": self.profile.loss_p,
            "straggling": straggling,
        }

    def stall_floor_s(self) -> float:
        """The epoch-stall watchdog budget floor this profile needs:
        a cold-start p50 measured on a LAN must not flip DOWN when the
        deployment's links are priced like this profile's."""
        return self.profile.stall_floor_s

    def stats(self) -> Dict[str, object]:
        """The ``Metrics.snapshot()["wan"]`` provider payload."""
        with self._lock:
            episodes = sum(
                s.episodes
                for s in self._stragglers.values()
                if s is not None
            )
        return {
            "enabled": 1,
            "profile": self.profile.name,
            "frames_delayed": self.frames_delayed,
            "retransmits": self.retransmits,
            "straggler_episodes": episodes,
            "virtual_time_ms": int(self.now * 1e3),
        }


__all__ = [
    "LinkModel",
    "PROFILES",
    "WanEmulator",
    "WanProfile",
    "wan_profile_names",
]
