"""The Connection / Broadcaster / Handler seam + message authentication.

Mirrors reference conn.go: ``Handler.ServeRequest(msg)`` (conn.go:27-29),
the ``Connection`` interface ``{Send, Ip, Id, Close, Start, Handle}``
(conn.go:31-38), ``Broadcaster`` (conn.go:182-184) and
``ConnectionPool.{GetAll, Broadcast, Add, Remove}`` (conn.go:186-216).
Two deliberate upgrades over the reference:

- ``ConnectionPool`` is lock-guarded — the reference's pool map is the
  one shared structure it forgot to lock (SURVEY.md §5.2 "known gap",
  conn.go:186-216).
- ``verify`` is real: the reference's envelope has a ``signature``
  field but its check is a TODO returning true (conn.go:134-137);
  here an ``Authenticator`` seam MACs the envelope
  (HMAC-SHA256 over transport.message.signing_bytes).
"""

from __future__ import annotations

import abc
import hashlib
import hmac
import threading
from typing import Callable, Dict, List, Optional, Protocol, runtime_checkable

from cleisthenes_tpu.transport.message import Message, signing_bytes


@runtime_checkable
class Handler(Protocol):
    """Reference conn.go:27-29."""

    def serve_request(self, msg: Message) -> None: ...


@runtime_checkable
class Connection(Protocol):
    """Reference conn.go:31-38.  ``send`` is fire-and-forget with
    optional delivery callbacks (conn.go:66-77)."""

    def send(
        self,
        msg: Message,
        on_success: Optional[Callable[[Message], None]] = None,
        on_err: Optional[Callable[[Exception], None]] = None,
    ) -> None: ...

    def id(self) -> str: ...

    def close(self) -> None: ...

    def start(self) -> None: ...

    def handle(self, handler: Handler) -> None: ...


class Broadcaster(Protocol):
    """Reference conn.go:182-184 — the only transport dependency the
    protocol layer has (rbc/rbc.go:35, bba/bba.go:60)."""

    def broadcast(self, msg: Message) -> None: ...

    def send_to(self, conn_id: str, msg: Message) -> None: ...


# ---------------------------------------------------------------------------
# Authentication (the implemented version of conn.go:134-137's TODO)
# ---------------------------------------------------------------------------


class Authenticator(abc.ABC):
    """Signs and verifies envelope MACs."""

    @abc.abstractmethod
    def sign(self, msg: Message) -> Message:
        """Return a copy of ``msg`` with the signature field filled."""

    @abc.abstractmethod
    def verify(self, msg: Message) -> bool: ...


class NullAuthenticator(Authenticator):
    """Reference-faithful stand-in: accept everything
    (conn.go:134-137 behavior, for benchmarks isolating crypto cost)."""

    def sign(self, msg: Message) -> Message:
        return msg

    def verify(self, msg: Message) -> bool:
        return True


class HmacAuthenticator(Authenticator):
    """HMAC-SHA256 over the envelope with per-sender derived keys.

    Key for sender i is HKDF-style ``H(master || sender_id)`` so a MAC
    authenticates the claimed ``sender_id``, preventing one roster
    member from impersonating another (the property the reference's
    empty ``verify`` was meant to provide).  The master secret is part
    of the trusted-dealer setup alongside the TPKE/coin keys.
    """

    def __init__(self, master_secret: bytes, self_id: str):
        self._master = master_secret
        self._self_id = self_id

    def _key_for(self, sender_id: str) -> bytes:
        return hashlib.sha256(
            b"mac|" + self._master + b"|" + sender_id.encode("utf-8")
        ).digest()

    def sign(self, msg: Message) -> Message:
        if msg.sender_id != self._self_id:
            # a mismatch would produce messages every receiver silently
            # rejects (MAC keyed by self_id, verified by sender_id)
            raise ValueError(
                f"cannot sign as {msg.sender_id!r}: this authenticator "
                f"holds the key for {self._self_id!r}"
            )
        mac = hmac.new(
            self._key_for(self._self_id), signing_bytes(msg), hashlib.sha256
        ).digest()
        return Message(
            sender_id=msg.sender_id,
            timestamp=msg.timestamp,
            payload=msg.payload,
            signature=mac,
        )

    def verify(self, msg: Message) -> bool:
        want = hmac.new(
            self._key_for(msg.sender_id), signing_bytes(msg), hashlib.sha256
        ).digest()
        return hmac.compare_digest(want, msg.signature)


# ---------------------------------------------------------------------------
# ConnectionPool
# ---------------------------------------------------------------------------


class ConnectionPool:
    """id -> Connection map with broadcast (reference conn.go:186-216),
    lock-guarded (fixing the reference's unguarded map)."""

    def __init__(self) -> None:
        self._conns: Dict[str, Connection] = {}
        self._lock = threading.RLock()

    def add(self, conn: Connection) -> None:
        with self._lock:
            self._conns[conn.id()] = conn

    def remove(self, conn_id: str) -> None:
        """Reference conn.go:214-216."""
        with self._lock:
            self._conns.pop(conn_id, None)

    def get(self, conn_id: str) -> Optional[Connection]:
        with self._lock:
            return self._conns.get(conn_id)

    def get_all(self) -> List[Connection]:
        """Reference conn.go:196-202 (GetAll)."""
        with self._lock:
            return list(self._conns.values())

    def broadcast(self, msg: Message) -> None:
        """Fire-and-forget send to every pooled peer
        (reference conn.go:204-208)."""
        for conn in self.get_all():
            conn.send(msg)

    def send_to(self, conn_id: str, msg: Message) -> None:
        conn = self.get(conn_id)
        if conn is not None:
            conn.send(msg)

    def __len__(self) -> int:
        with self._lock:
            return len(self._conns)


__all__ = [
    "Handler",
    "Connection",
    "Broadcaster",
    "Authenticator",
    "NullAuthenticator",
    "HmacAuthenticator",
    "ConnectionPool",
]
