"""The Connection / Broadcaster / Handler seam + message authentication.

Mirrors reference conn.go: ``Handler.ServeRequest(msg)`` (conn.go:27-29),
the ``Connection`` interface ``{Send, Ip, Id, Close, Start, Handle}``
(conn.go:31-38), ``Broadcaster`` (conn.go:182-184) and
``ConnectionPool.{GetAll, Broadcast, Add, Remove}`` (conn.go:186-216).
Two deliberate upgrades over the reference:

- ``ConnectionPool`` is lock-guarded — the reference's pool map is the
  one shared structure it forgot to lock (SURVEY.md §5.2 "known gap",
  conn.go:186-216).
- ``verify`` is real: the reference's envelope has a ``signature``
  field but its check is a TODO returning true (conn.go:134-137);
  here an ``Authenticator`` seam MACs the envelope
  (HMAC-SHA256 over transport.message.signing_bytes).
"""

from __future__ import annotations

import abc
import hashlib
import hmac
from typing import Callable, Dict, List, Optional, Protocol, runtime_checkable

from cleisthenes_tpu.transport.message import (
    Message,
    attach_signature,
    signing_bytes,
)
from cleisthenes_tpu.utils.determinism import guarded_by
from cleisthenes_tpu.utils.lockcheck import new_rlock


@runtime_checkable
class Handler(Protocol):
    """Reference conn.go:27-29."""

    def serve_request(self, msg: Message) -> None: ...


@runtime_checkable
class Connection(Protocol):
    """Reference conn.go:31-38.  ``send`` is fire-and-forget with
    optional delivery callbacks (conn.go:66-77)."""

    def send(
        self,
        msg: Message,
        on_success: Optional[Callable[[Message], None]] = None,
        on_err: Optional[Callable[[Exception], None]] = None,
    ) -> None: ...

    def id(self) -> str: ...

    def close(self) -> None: ...

    def start(self) -> None: ...

    def handle(self, handler: Handler) -> None: ...


class Broadcaster(Protocol):
    """Reference conn.go:182-184 — the only transport dependency the
    protocol layer has (rbc/rbc.go:35, bba/bba.go:60)."""

    def broadcast(self, msg: Message) -> None: ...

    def send_to(self, conn_id: str, msg: Message) -> None: ...


def wire_idle_hooks(handler):
    """The transport-manages-idle handshake, in one place.

    Returns ``(flush_outbound, on_idle)`` — the handler's optional
    transport hooks (None when absent) — and, IFF the handler exposes
    ``on_idle``, notifies it via ``transport_manages_idle()`` that this
    transport COMMITS to calling ``on_idle`` at every quiescence point.
    The promise is load-bearing: a notified handler defers batched
    crypto and outbound bundling to those callbacks, so a transport
    must only call this if it will deliver them (ChannelNetwork.run's
    idle phase; SerialDispatcher's empty-mailbox check).
    """
    flush_outbound = getattr(handler, "flush_outbound", None)
    on_idle = getattr(handler, "on_idle", None)
    notify = getattr(handler, "transport_manages_idle", None)
    if on_idle is not None and callable(notify):
        notify()
    return flush_outbound, on_idle


# ---------------------------------------------------------------------------
# Authentication (the implemented version of conn.go:134-137's TODO)
# ---------------------------------------------------------------------------


def _hmac_sha256_fn(key: bytes) -> Callable[[bytes], bytes]:
    """Precomputed HMAC-SHA256 for one pair key (RFC 2104).

    ``hmac.new`` re-runs the key schedule — two full SHA-256 block
    compressions over the padded key — on EVERY call; at N=64 that is
    ~280k schedules per epoch (one per signed + one per verified
    frame) for a roster of 63 fixed keys.  Here the inner/outer pad
    contexts initialize once per pair key and each MAC is two context
    copies + updates.  Byte-for-byte identical output to
    ``hmac.new(key, msg, hashlib.sha256).digest()`` (asserted by
    tests/test_transport.py); comparisons still go through
    ``hmac.compare_digest``.
    """
    if len(key) > 64:  # SHA-256 block size
        key = hashlib.sha256(key).digest()
    key = key.ljust(64, b"\x00")
    inner = hashlib.sha256(bytes(b ^ 0x36 for b in key))
    outer = hashlib.sha256(bytes(b ^ 0x5C for b in key))

    def mac(msg: bytes, _inner=inner, _outer=outer) -> bytes:
        h = _inner.copy()
        h.update(msg)
        o = _outer.copy()
        o.update(h.digest())
        return o.digest()

    return mac


class Authenticator(abc.ABC):
    """Signs and verifies envelope MACs.

    ``sign`` takes the intended receiver because MAC keys are scoped to
    the (sender, receiver) pair — a broadcast is N individually-MACed
    frames, not one frame fanned out.
    """

    @abc.abstractmethod
    def sign(self, msg: Message, receiver_id: Optional[str] = None) -> Message:
        """Return a copy of ``msg`` with the signature field filled."""

    @abc.abstractmethod
    def verify(self, msg: Message) -> bool: ...

    def verify_wire(self, msg: Message, signing_prefix: bytes) -> bool:
        """Verify using the frame's own signing-bytes prefix (from
        transport.message.decode_frame) — MAC backends override to
        skip the payload re-encode that ``verify`` must do."""
        return self.verify(msg)

    def verify_wire_many(self, msgs, signing_prefixes) -> "List[bool]":
        """Verdicts for one inbound wave's frames in ONE call
        (Config.delivery_columnar): the transports buffer frames per
        message wave and verify them together, so per-frame python
        dispatch amortizes across the batch.  Default: loop
        verify_wire.  MAC backends override to hoist the per-sender
        key-schedule lookup out of the loop (PR 7's _hmac_sha256_fn
        contexts are per-pair constants — one dict probe per DISTINCT
        sender per wave instead of one per frame)."""
        return [
            self.verify_wire(m, p) for m, p in zip(msgs, signing_prefixes)
        ]

    def sign_wire_many(self, msg: Message, receiver_ids) -> "Dict[str, bytes]":
        """receiver_id -> complete wire frame, for broadcasts.

        Default: sign+encode per receiver.  Pairwise-MAC backends
        override to encode the envelope once and append per-receiver
        MACs (the broadcast hot path is N frames that differ only in
        the 32-byte signature).
        """
        from cleisthenes_tpu.transport.message import encode_message

        return {
            rid: encode_message(self.sign(msg, rid))
            for rid in receiver_ids
        }

    def sign_wire_wave(self, items, memo=None) -> "List[Dict[str, bytes]]":
        """One EGRESS wave's frames in ONE call (Config.egress_columnar)
        — the send-side twin of ``verify_wire_many``.

        ``items`` is ``[(msg, receiver_ids)]``: everything one
        coalescer flush ships (one folded bundle per receiver, or one
        shared bundle for a pure broadcast wave).  Returns one
        ``{receiver_id: wire frame}`` dict per item, byte-identical to
        looping ``sign_wire_many`` (tests/test_egress_equivalence.py
        asserts it).  ``memo`` is the caller's FrameEncodeMemo
        (transport.message): a wave's per-receiver bundles mostly
        re-encode SHARED payload objects, so the memo collapses those
        to one encode + joins.  Default: loop sign_wire_many; MAC
        backends override to run the whole wave's HMACs as one batched
        pass over the PR-7 precomputed key schedules."""
        return [
            self.sign_wire_many(m, rids)
            for m, rids in items
        ]


def sign_wave_counted(auth: "Authenticator", items, memo):
    """One egress wave through ``auth.sign_wire_wave`` with the
    counter attribution both transports share: ``(frames_list,
    memo_hits, memo_misses, payload_bodies_encoded)``.

    ``payload_bodies_encoded`` (the ``frames_encoded`` counter's
    unit) is the FrameEncodeMemo's miss delta when the signer
    consulted the memo (Hmac/Null always probe at least once per
    item); a backend whose wave path ignores the memo (the ABC's
    per-item default) falls back to the scalar arm's unit — payload
    bodies per entry — WITHOUT inventing memo misses for probes that
    never happened, so the memo stat surfaces stay truthful and the
    perfgate-gated counter never silently reads zero."""
    from cleisthenes_tpu.transport.message import payload_body_count

    h0 = memo.hits if memo is not None else 0
    m0 = memo.misses if memo is not None else 0
    frames_list = auth.sign_wire_wave(items, memo)
    hits = (memo.hits - h0) if memo is not None else 0
    misses = (memo.misses - m0) if memo is not None else 0
    if hits or misses:
        return frames_list, hits, misses, misses
    bodies = sum(payload_body_count(m.payload) for m, _rids in items)
    return frames_list, 0, 0, bodies


class NullAuthenticator(Authenticator):
    """Reference-faithful stand-in: accept everything
    (conn.go:134-137 behavior, for benchmarks isolating crypto cost)."""

    def sign(self, msg: Message, receiver_id: Optional[str] = None) -> Message:
        return msg

    def verify(self, msg: Message) -> bool:
        return True

    def sign_wire_many(self, msg: Message, receiver_ids) -> "Dict[str, bytes]":
        """No MAC, so every receiver's frame is the same bytes object:
        one encode per broadcast."""
        from cleisthenes_tpu.transport.message import encode_message

        wire = encode_message(msg)
        return {rid: wire for rid in receiver_ids}

    def verify_wire_many(self, msgs, signing_prefixes) -> "List[bool]":
        return [True] * len(msgs)

    def sign_wire_wave(self, items, memo=None) -> "List[Dict[str, bytes]]":
        """No MAC: each item's frame is its signing bytes + an empty
        signature, encoded once per distinct payload via the memo."""
        from cleisthenes_tpu.transport.message import (
            attach_signature,
            signing_bytes_shared,
        )

        out: "List[Dict[str, bytes]]" = []
        for msg, rids in items:
            sb = (
                signing_bytes_shared(msg, memo)
                if memo is not None
                else signing_bytes(msg)
            )
            wire = attach_signature(sb, msg.signature)
            out.append({rid: wire for rid in rids})
        return out


class HmacAuthenticator(Authenticator):
    """HMAC-SHA256 over the envelope with per-ordered-pair keys.

    Node i holds ONLY the pair keys ``k_{i,j}`` for pairs it belongs
    to: it signs a message to j with ``k_{i,j}`` and verifies an
    inbound claim "from j" with ``k_{j,i}`` (= ``k_{i,j}``, unordered).
    Because a third roster member c never holds ``k_{i,j}``, c cannot
    forge envelopes between honest i and j — which is the quorum-
    intersection property RBC/BBA/ACS need from the reference's empty
    ``verify`` TODO (conn.go:134-137).  What a Byzantine j CAN still do
    is lie to each peer separately (equivocate) — the protocol's
    Byzantine tolerance, not the MAC layer, covers that.

    The dealer derives pair keys from a master secret it never
    distributes (``protocol.honeybadger.setup_keys``); each node
    receives just its own key map.  ``derive`` reproduces the dealer's
    schedule for tests that hold the master themselves.
    """

    def __init__(self, self_id: str, peer_keys: "Dict[str, bytes]"):
        self._self_id = self_id
        self._peer_keys = dict(peer_keys)
        # per-peer precomputed HMAC key schedules (the roster changes
        # only at reconfig boundaries; see _hmac_sha256_fn)
        self._macs: "Dict[str, Callable[[bytes], bytes]]" = {
            peer: _hmac_sha256_fn(key)
            for peer, key in self._peer_keys.items()
        }
        # MAC rotation (protocol.reconfig): the SECONDARY verify map.
        # A surviving pair's next-version key is STAGED here at
        # reconfig discovery (verification accepts either key, signing
        # stays on the old one), PROMOTED to primary at the activation
        # boundary (the old key drops into this map so in-flight
        # frames still verify), and the leftover alternate is dropped
        # at retirement teardown — after which a stale pre-rotation
        # key no longer authenticates anything.
        self._alt_keys: "Dict[str, bytes]" = {}
        self._alt_macs: "Dict[str, Callable[[bytes], bytes]]" = {}

    def set_peer_key(self, peer_id: str, key: bytes) -> None:
        """Install (or rotate) one pair key — the dynamic-membership
        seam: a RECONFIG ceremony derives fresh pair keys for joiner
        pairs and installs them here the moment the roster change is
        discovered, so a joiner's CATCHUP traffic authenticates before
        its activation epoch.  Single-assignment per peer per call;
        in-flight frames MAC'd under a replaced key are rejected, the
        same fate as any stale-roster frame."""
        self._peer_keys[peer_id] = key
        self._macs[peer_id] = _hmac_sha256_fn(key)

    def stage_peer_key(self, peer_id: str, key: bytes) -> None:
        """Stage a SURVIVING pair's next-version key for verification
        only (MAC rotation step 1, at reconfig discovery): inbound
        frames verify under the current OR the staged key, outbound
        frames keep signing under the current one.  Nodes cross the
        activation boundary at different instants, so a hard swap
        would reject every in-flight frame straddling it; staging at
        discovery — the earliest log position all survivors share —
        makes the handover seamless in both directions."""
        if key == self._peer_keys.get(peer_id):
            return  # same-key "rotation" (e.g. replay): nothing staged
        self._alt_keys[peer_id] = key
        self._alt_macs[peer_id] = _hmac_sha256_fn(key)

    def promote_staged_key(self, peer_id: str) -> None:
        """Switch signing to the staged key (MAC rotation step 2, at
        the activation boundary): the staged key becomes primary and
        the OLD key drops into the secondary verify map, so frames
        MAC'd just before the boundary still verify until teardown."""
        key = self._alt_keys.get(peer_id)
        if key is None:
            return
        old_key = self._peer_keys.get(peer_id)
        old_fn = self._macs.get(peer_id)
        self._peer_keys[peer_id] = key
        self._macs[peer_id] = self._alt_macs[peer_id]
        if old_key is not None:
            self._alt_keys[peer_id] = old_key
            self._alt_macs[peer_id] = old_fn
        else:
            del self._alt_keys[peer_id]
            del self._alt_macs[peer_id]

    def drop_alt_key(self, peer_id: str) -> None:
        """Forget the secondary key (MAC rotation step 3, at
        retirement teardown): from here a frame MAC'd under the
        pre-rotation key is rejected — the stale-key regression the
        rotation exists to create."""
        self._alt_keys.pop(peer_id, None)
        self._alt_macs.pop(peer_id, None)

    def drop_peer(self, peer_id: str) -> None:
        """Retire one pair key: frames to/from the peer no longer
        sign or verify (the MAC-layer half of peer retirement —
        transport.health tears down the dial half)."""
        self._peer_keys.pop(peer_id, None)
        self._macs.pop(peer_id, None)
        self._alt_keys.pop(peer_id, None)
        self._alt_macs.pop(peer_id, None)

    @staticmethod
    def pair_key(master_secret: bytes, a: str, b: str) -> bytes:
        """The dealer's derivation: unordered-pair key
        ``H("macpair" || master || min(a,b) || max(a,b))``."""
        lo, hi = sorted((a.encode("utf-8"), b.encode("utf-8")))
        return hashlib.sha256(
            b"macpair|" + master_secret + b"|" + lo + b"|" + hi
        ).digest()

    @classmethod
    def key_map(
        cls, master_secret: bytes, self_id: str, roster_ids
    ) -> "Dict[str, bytes]":
        """The dealer's key schedule for one node: every pair key
        ``self_id`` belongs to (the single source both ``derive`` and
        ``protocol.honeybadger.setup_keys`` use)."""
        return {
            peer: cls.pair_key(master_secret, self_id, peer)
            for peer in roster_ids
        }

    @classmethod
    def derive(
        cls, master_secret: bytes, self_id: str, roster_ids
    ) -> "HmacAuthenticator":
        """Build node ``self_id``'s authenticator from the dealer's
        master (test/dealer-side convenience)."""
        return cls(self_id, cls.key_map(master_secret, self_id, roster_ids))

    def _key_with(self, peer_id: str) -> Optional[bytes]:
        return self._peer_keys.get(peer_id)

    def sign(self, msg: Message, receiver_id: Optional[str] = None) -> Message:
        if msg.sender_id != self._self_id:
            # a mismatch would produce messages every receiver silently
            # rejects (pair key involves self_id, verified by sender_id)
            raise ValueError(
                f"cannot sign as {msg.sender_id!r}: this authenticator "
                f"holds the keys of {self._self_id!r}"
            )
        if receiver_id is None:
            raise ValueError(
                "pairwise MAC needs the receiver id at sign time"
            )
        mac_fn = self._macs.get(receiver_id)
        if mac_fn is None:
            raise ValueError(f"no pair key with {receiver_id!r}")
        return Message(
            sender_id=msg.sender_id,
            timestamp=msg.timestamp,
            payload=msg.payload,
            signature=mac_fn(signing_bytes(msg)),
        )

    def verify(self, msg: Message) -> bool:
        mac_fn = self._macs.get(msg.sender_id)
        if mac_fn is None:  # not a roster member we share a key with
            return False
        sb = signing_bytes(msg)
        if hmac.compare_digest(mac_fn(sb), msg.signature):
            return True
        alt_fn = self._alt_macs.get(msg.sender_id)
        return alt_fn is not None and hmac.compare_digest(
            alt_fn(sb), msg.signature
        )

    def verify_wire(self, msg: Message, signing_prefix: bytes) -> bool:
        """MAC the frame's signing prefix directly.

        The security argument: the MAC binds the RECEIVED bytes, and
        only the two pair-key holders can produce a valid MAC over any
        byte string, so acceptance here implies the claimed sender
        authenticated exactly these bytes.  This is strictly
        byte-binding — stronger than re-encode-verify for attackers
        without the key.  Where it can differ from ``verify``: a frame
        whose payload was encoded NON-canonically (e.g. an int field
        with a leading zero byte) yet MAC'd by the key holder itself
        would pass here and fail re-encode-verify — but our encoder is
        canonical, so honest peers never emit such frames, and a
        Byzantine key holder gains nothing it couldn't send anyway
        (no component deduplicates or compares raw frame bytes)."""
        mac_fn = self._macs.get(msg.sender_id)
        if mac_fn is None:
            return False
        if hmac.compare_digest(mac_fn(signing_prefix), msg.signature):
            return True
        alt_fn = self._alt_macs.get(msg.sender_id)
        return alt_fn is not None and hmac.compare_digest(
            alt_fn(signing_prefix), msg.signature
        )

    def verify_wire_many(self, msgs, signing_prefixes) -> "List[bool]":
        """Wave verify fast path: the per-sender MAC context resolves
        once per run of same-sender frames (an inbound wave is mostly
        runs — each peer's bundle fan-in arrives together), and each
        verdict is two SHA-256 context copies + a compare_digest."""
        macs = self._macs
        alt_macs = self._alt_macs
        out: List[bool] = []
        last_sender: Optional[str] = None
        mac_fn = None
        alt_fn = None
        for msg, prefix in zip(msgs, signing_prefixes):
            sender = msg.sender_id
            if sender != last_sender:
                mac_fn = macs.get(sender)
                alt_fn = alt_macs.get(sender) if alt_macs else None
                last_sender = sender
            if mac_fn is None:
                out.append(False)
                continue
            out.append(
                hmac.compare_digest(mac_fn(prefix), msg.signature)
                or (
                    alt_fn is not None
                    and hmac.compare_digest(alt_fn(prefix), msg.signature)
                )
            )
        return out

    def sign_wire_many(self, msg: Message, receiver_ids) -> "Dict[str, bytes]":
        """Broadcast fast path: one payload encode, one MAC per peer."""
        if msg.sender_id != self._self_id:
            raise ValueError(
                f"cannot sign as {msg.sender_id!r}: this authenticator "
                f"holds the keys of {self._self_id!r}"
            )
        sb = signing_bytes(msg)
        macs = self._macs
        out: Dict[str, bytes] = {}
        for rid in receiver_ids:
            mac_fn = macs.get(rid)
            if mac_fn is None:
                raise ValueError(f"no pair key with {rid!r}")
            out[rid] = attach_signature(sb, mac_fn(sb))
        return out

    def sign_wire_wave(self, items, memo=None) -> "List[Dict[str, bytes]]":
        """Egress wave fast path (Config.egress_columnar): the whole
        flush's envelope bodies encode once per distinct payload
        OBJECT through the caller's FrameEncodeMemo — a mixed wave's
        per-receiver bundles share their broadcast run's sub-payloads,
        so N receiver bundles cost one encode each plus joins — and
        every frame's HMAC runs in one batched pass over the
        precomputed per-pair key schedules (two SHA-256 context copies
        per MAC, one dict probe per receiver).  Output byte-identical
        to looping ``sign_wire_many`` over the items."""
        from cleisthenes_tpu.transport.message import signing_bytes_shared

        macs = self._macs
        self_id = self._self_id
        out: "List[Dict[str, bytes]]" = []
        for msg, rids in items:
            if msg.sender_id != self_id:
                raise ValueError(
                    f"cannot sign as {msg.sender_id!r}: this "
                    f"authenticator holds the keys of {self_id!r}"
                )
            sb = (
                signing_bytes_shared(msg, memo)
                if memo is not None
                else signing_bytes(msg)
            )
            frames: Dict[str, bytes] = {}
            for rid in rids:
                mac_fn = macs.get(rid)
                if mac_fn is None:
                    raise ValueError(f"no pair key with {rid!r}")
                frames[rid] = attach_signature(sb, mac_fn(sb))
            out.append(frames)
        return out


# ---------------------------------------------------------------------------
# ConnectionPool
# ---------------------------------------------------------------------------


@guarded_by("_lock", "_conns")
class ConnectionPool:
    """id -> Connection map with broadcast (reference conn.go:186-216),
    lock-guarded (fixing the reference's unguarded map)."""

    def __init__(self) -> None:
        self._conns: Dict[str, Connection] = {}
        self._lock = new_rlock()

    def add(self, conn: Connection) -> None:
        with self._lock:
            self._conns[conn.id()] = conn

    def remove(self, conn_id: str) -> None:
        """Reference conn.go:214-216."""
        with self._lock:
            self._conns.pop(conn_id, None)

    def get(self, conn_id: str) -> Optional[Connection]:
        with self._lock:
            return self._conns.get(conn_id)

    def get_all(self) -> List[Connection]:
        """Reference conn.go:196-202 (GetAll)."""
        with self._lock:
            return list(self._conns.values())

    def broadcast(self, msg: Message) -> None:
        """Fire-and-forget send to every pooled peer
        (reference conn.go:204-208)."""
        for conn in self.get_all():
            conn.send(msg)

    def send_to(self, conn_id: str, msg: Message) -> None:
        conn = self.get(conn_id)
        if conn is not None:
            conn.send(msg)

    def __len__(self) -> int:
        with self._lock:
            return len(self._conns)


__all__ = [
    "Handler",
    "Connection",
    "Broadcaster",
    "Authenticator",
    "NullAuthenticator",
    "HmacAuthenticator",
    "ConnectionPool",
    "sign_wave_counted",
]
